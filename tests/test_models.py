"""Model + sharding tests (the reference has no models of its own; these
cover the benchmark/flagship models and the driver entry contract)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P


def test_resnet50_forward_shape():
    from horovod_tpu.models import ResNet50

    model = ResNet50(num_classes=10, dtype=jnp.float32)
    x = jnp.zeros((2, 64, 64, 3))
    variables = model.init(jax.random.PRNGKey(0), x, train=True)
    logits, mutated = model.apply(variables, x, train=True,
                                  mutable=["batch_stats"])
    assert logits.shape == (2, 10)
    assert logits.dtype == jnp.float32
    assert "batch_stats" in mutated


def test_conv0_space_to_depth_is_numerically_identical():
    """The s2d stem is a pure reindexing of the 7x7/2 conv: same kernel
    parameter, same output, for any input — and the checkpoint layout
    ({"conv_init": {"kernel"}}, shape (7,7,3,width)) is unchanged."""
    from horovod_tpu.models.resnet import _SpaceToDepthStem
    from jax import lax

    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(2, 32, 32, 3), jnp.float32)
    stem = _SpaceToDepthStem(features=16, dtype=jnp.float32)
    variables = stem.init(jax.random.PRNGKey(1), x)
    k = variables["params"]["kernel"]
    assert k.shape == (7, 7, 3, 16)

    got = stem.apply(variables, x)
    want = lax.conv_general_dilated(
        x, k, window_strides=(2, 2), padding=((3, 3), (3, 3)),
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    assert got.shape == want.shape == (2, 16, 16, 16)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_resnet_conv0_s2d_checkpoint_layout_matches_standard_stem():
    from horovod_tpu.models import ResNet50

    x = jnp.zeros((1, 64, 64, 3))
    std = ResNet50(num_classes=10, dtype=jnp.float32).init(
        jax.random.PRNGKey(0), x, train=True)
    s2d = ResNet50(num_classes=10, dtype=jnp.float32,
                   conv0_space_to_depth=True).init(
        jax.random.PRNGKey(0), x, train=True)
    assert (std["params"]["conv_init"]["kernel"].shape
            == s2d["params"]["conv_init"]["kernel"].shape)
    # a standard-stem checkpoint loads into an s2d model verbatim
    std_tree = jax.tree.structure(std)
    s2d_tree = jax.tree.structure(s2d)
    assert std_tree == s2d_tree


def test_resnet_eval_mode():
    from horovod_tpu.models import ResNet50

    model = ResNet50(num_classes=10, dtype=jnp.float32)
    x = jnp.zeros((2, 64, 64, 3))
    variables = model.init(jax.random.PRNGKey(0), x, train=True)
    logits = model.apply(variables, x, train=False)
    assert logits.shape == (2, 10)


def test_gpt_forward():
    from horovod_tpu.models import GPT, GPTConfig

    cfg = GPTConfig(vocab_size=64, n_layers=2, d_model=32, n_heads=2,
                    d_ff=64, dtype=jnp.float32)
    model = GPT(cfg)
    tokens = jnp.asarray(np.random.RandomState(0).randint(0, 64, (2, 16)))
    params = model.init(jax.random.PRNGKey(0), tokens)
    logits = model.apply(params, tokens)
    assert logits.shape == (2, 16, 64)


def test_gpt_causality():
    # changing a future token must not affect earlier logits
    from horovod_tpu.models import GPT, GPTConfig

    cfg = GPTConfig(vocab_size=64, n_layers=1, d_model=32, n_heads=2,
                    d_ff=64, dtype=jnp.float32)
    model = GPT(cfg)
    rng = np.random.RandomState(1)
    t1 = rng.randint(0, 64, (1, 8))
    t2 = t1.copy()
    t2[0, -1] = (t2[0, -1] + 1) % 64
    params = model.init(jax.random.PRNGKey(0), jnp.asarray(t1))
    l1 = model.apply(params, jnp.asarray(t1))
    l2 = model.apply(params, jnp.asarray(t2))
    np.testing.assert_allclose(np.asarray(l1[0, :-1]),
                               np.asarray(l2[0, :-1]), atol=1e-5)
    assert not np.allclose(np.asarray(l1[0, -1]), np.asarray(l2[0, -1]))


def test_param_partition_spec():
    from horovod_tpu.models import GPT, GPTConfig
    from horovod_tpu.models.transformer import param_partition_spec

    cfg = GPTConfig(vocab_size=64, n_layers=1, d_model=32, n_heads=2,
                    d_ff=64, dtype=jnp.float32)
    model = GPT(cfg)
    tokens = jnp.zeros((1, 8), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens)["params"]
    specs = param_partition_spec(params)
    flat = jax.tree_util.tree_flatten_with_path(
        specs, is_leaf=lambda x: isinstance(x, P))[0]
    by_name = {"/".join(str(getattr(k, "key", k)) for k in path): spec
               for path, spec in flat}
    assert by_name["embedding"] == P("tp", None)
    assert any(s == P(None, "tp", None) for n, s in by_name.items()
               if n.endswith("q/kernel"))
    assert any(s == P("tp", None, None) for n, s in by_name.items()
               if n.endswith("o/kernel"))
    assert any(s == P(None, "tp") for n, s in by_name.items()
               if n.endswith("up/kernel"))
    assert any(s == P("tp", None) for n, s in by_name.items()
               if n.endswith("down/kernel"))
    assert any(s == P() for n, s in by_name.items() if "ln" in n)


def test_graft_entry_single_chip():
    import __graft_entry__ as ge

    fwd, (params, tokens) = ge.entry()
    logits = jax.jit(fwd)(params, tokens)
    assert logits.shape[:2] == tokens.shape
    assert bool(jnp.all(jnp.isfinite(logits)))


def test_graft_dryrun_multichip_8():
    import __graft_entry__ as ge

    ge.dryrun_multichip(8)


def test_mesh_factors():
    import __graft_entry__ as ge

    for n in (1, 2, 4, 8, 16, 64, 256):
        dp, sp, tp = ge._mesh_factors(n)
        assert dp * sp * tp == n


def test_gpt_flash_attention_matches_einsum_path():
    """use_flash must be a pure performance switch: identical logits and
    gradients (the pallas kernel runs in interpret mode on the CPU
    mesh)."""
    import dataclasses

    from horovod_tpu.models import GPT, GPTConfig

    cfg = GPTConfig(vocab_size=64, n_layers=2, d_model=32, n_heads=2,
                    d_ff=64, dtype=jnp.float32)
    tokens = jnp.asarray(np.random.RandomState(1).randint(0, 64, (2, 16)))
    model = GPT(cfg)
    params = model.init(jax.random.PRNGKey(0), tokens)
    model_f = GPT(dataclasses.replace(cfg, use_flash=True))

    def loss(m, p):
        return (m.apply(p, tokens).astype(jnp.float32) ** 2).mean()

    l0, g0 = jax.value_and_grad(lambda p: loss(model, p))(params)
    l1, g1 = jax.value_and_grad(lambda p: loss(model_f, p))(params)
    np.testing.assert_allclose(np.asarray(l1), np.asarray(l0),
                               rtol=2e-5, atol=2e-6)
    for a, b in zip(jax.tree.leaves(g1), jax.tree.leaves(g0)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-5)


class TestTpuBatchNorm:
    """TpuBatchNorm must be a pure performance rewrite of nn.BatchNorm:
    same formula (fast variance), same batch_stats layout, same numerics
    in fp32, same loss trajectory in bf16 (see models/normalization.py)."""

    def _pair(self, use_running_average=False):
        import flax.linen as nn

        from horovod_tpu.models.normalization import TpuBatchNorm

        kw = dict(use_running_average=use_running_average, momentum=0.9,
                  epsilon=1e-5, dtype=jnp.float32,
                  param_dtype=jnp.float32)
        return TpuBatchNorm(**kw), nn.BatchNorm(**kw)

    def test_forward_and_stats_match_flax_fp32(self):
        tpu_bn, flax_bn = self._pair()
        x = jnp.asarray(np.random.RandomState(0).randn(4, 5, 5, 7) * 3 + 1,
                        jnp.float32)
        v_t = tpu_bn.init(jax.random.PRNGKey(0), x)
        v_f = flax_bn.init(jax.random.PRNGKey(0), x)
        y_t, m_t = tpu_bn.apply(v_t, x, mutable=["batch_stats"])
        y_f, m_f = flax_bn.apply(v_f, x, mutable=["batch_stats"])
        np.testing.assert_allclose(np.asarray(y_t), np.asarray(y_f),
                                   rtol=1e-5, atol=1e-5)
        for a, b in zip(jax.tree.leaves(m_t), jax.tree.leaves(m_f)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-5, atol=1e-6)

    def test_grads_match_flax_fp32(self):
        tpu_bn, flax_bn = self._pair()
        x = jnp.asarray(np.random.RandomState(1).randn(8, 3, 3, 4),
                        jnp.float32)
        v = flax_bn.init(jax.random.PRNGKey(0), x)

        def loss(mod, params, x):
            y, _ = mod.apply({"params": params,
                              "batch_stats": v["batch_stats"]}, x,
                             mutable=["batch_stats"])
            return (y ** 2).mean()

        for argnum in (1, 2):
            g_t = jax.grad(lambda p, xx: loss(tpu_bn, p, xx),
                           argnums=argnum - 1)(v["params"], x)
            g_f = jax.grad(lambda p, xx: loss(flax_bn, p, xx),
                           argnums=argnum - 1)(v["params"], x)
            for a, b in zip(jax.tree.leaves(g_t), jax.tree.leaves(g_f)):
                np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                           rtol=1e-4, atol=1e-5)

    def test_eval_mode_uses_running_stats(self):
        tpu_bn, flax_bn = self._pair(use_running_average=True)
        x = jnp.asarray(np.random.RandomState(2).randn(2, 4, 4, 3),
                        jnp.float32)
        v = flax_bn.init(jax.random.PRNGKey(0), x)
        v["batch_stats"]["mean"] = jnp.asarray([0.5, -1.0, 2.0])
        v["batch_stats"]["var"] = jnp.asarray([1.5, 0.25, 4.0])
        y_t = tpu_bn.apply(v, x)
        y_f = flax_bn.apply(v, x)
        np.testing.assert_allclose(np.asarray(y_t), np.asarray(y_f),
                                   rtol=1e-5, atol=1e-5)

    def test_sync_bn_pmean_equals_full_batch(self):
        """axis_name statistics across a 2-device pmap must equal the
        full-batch statistics (the reference's sync_batch_norm parity)."""
        from horovod_tpu.models.normalization import TpuBatchNorm

        x = jnp.asarray(np.random.RandomState(3).randn(4, 3, 3, 2),
                        jnp.float32)
        full = TpuBatchNorm(use_running_average=False, momentum=0.9,
                            dtype=jnp.float32)
        v = full.init(jax.random.PRNGKey(0), x)
        y_full, _ = full.apply(v, x, mutable=["batch_stats"])

        sync = TpuBatchNorm(use_running_average=False, momentum=0.9,
                            dtype=jnp.float32, axis_name="dp")
        xs = x.reshape(2, 2, 3, 3, 2)
        y_sync, _ = jax.pmap(
            lambda xx: sync.apply(v, xx, mutable=["batch_stats"]),
            axis_name="dp", devices=jax.devices()[:2])(xs)
        np.testing.assert_allclose(np.asarray(y_sync.reshape(x.shape)),
                                   np.asarray(y_full), rtol=1e-5,
                                   atol=1e-5)

    def test_resnet_loss_trajectory_matches_flax_bn(self):
        """norm_impl='tpu' must track norm_impl='flax' step for step —
        the parity-clean-numerics gate for the MFU work (VERDICT r2 #2)."""
        import optax

        from horovod_tpu.models import ResNet50

        rng = np.random.RandomState(4)
        x = jnp.asarray(rng.randn(4, 32, 32, 3), jnp.float32)
        labels = jnp.asarray(rng.randint(0, 10, (4,)))

        def run(norm_impl):
            model = ResNet50(num_classes=10, dtype=jnp.float32,
                             norm_impl=norm_impl)
            variables = model.init(jax.random.PRNGKey(0), x, train=True)
            params, bs = variables["params"], variables["batch_stats"]
            tx = optax.sgd(0.05, momentum=0.9)
            opt = tx.init(params)
            losses = []

            @jax.jit
            def step(params, bs, opt):
                def loss_fn(p, b):
                    logits, mut = model.apply(
                        {"params": p, "batch_stats": b}, x, train=True,
                        mutable=["batch_stats"])
                    l = optax.softmax_cross_entropy_with_integer_labels(
                        logits, labels).mean()
                    return l, mut["batch_stats"]

                (l, bs2), g = jax.value_and_grad(
                    loss_fn, has_aux=True)(params, bs)
                up, opt2 = tx.update(g, opt, params)
                return optax.apply_updates(params, up), bs2, opt2, l

            for _ in range(3):
                params, bs, opt, l = step(params, bs, opt)
                losses.append(float(l))
            return losses

        np.testing.assert_allclose(run("tpu"), run("flax"), rtol=1e-4)

    def test_resnet_bf16_loss_trajectory_tracks_flax_bn(self):
        """Same trajectory check in bf16 — the production default path
        (the fp32 test would pass even if the bf16 affine application
        regressed). Loose tolerance: the two implementations round at
        different points by design."""
        import optax

        from horovod_tpu.models import ResNet50

        rng = np.random.RandomState(5)
        x = jnp.asarray(rng.randn(4, 32, 32, 3), jnp.bfloat16)
        labels = jnp.asarray(rng.randint(0, 10, (4,)))

        def run(norm_impl):
            model = ResNet50(num_classes=10, dtype=jnp.bfloat16,
                             norm_impl=norm_impl)
            variables = model.init(jax.random.PRNGKey(0), x, train=True)
            params, bs = variables["params"], variables["batch_stats"]
            # small lr: a big step overfits 4 samples to ~0 loss in one
            # update, where relative comparison is meaningless
            tx = optax.sgd(0.005, momentum=0.9)
            opt = tx.init(params)

            @jax.jit
            def step(params, bs, opt):
                def loss_fn(p, b):
                    logits, mut = model.apply(
                        {"params": p, "batch_stats": b}, x, train=True,
                        mutable=["batch_stats"])
                    l = optax.softmax_cross_entropy_with_integer_labels(
                        logits, labels).mean()
                    return l, mut["batch_stats"]

                (l, bs2), g = jax.value_and_grad(
                    loss_fn, has_aux=True)(params, bs)
                up, opt2 = tx.update(g, opt, params)
                return optax.apply_updates(params, up), bs2, opt2, l

            losses = []
            for _ in range(3):
                params, bs, opt, l = step(params, bs, opt)
                losses.append(float(l))
            return losses

        t, f = run("tpu"), run("flax")
        assert all(np.isfinite(t)) and all(np.isfinite(f))
        np.testing.assert_allclose(t, f, rtol=0.05, atol=0.02)


@pytest.mark.parametrize("use_flash", [False, True])
def test_gpt_ring_mesh_matches_plain(use_flash):
    """GPTConfig.ring_mesh swaps GSPMD attention for the explicit ring
    schedule (flash per block when use_flash) — logits and gradients
    must match the plain model."""
    import dataclasses

    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as PS

    from horovod_tpu.models import GPT, GPTConfig
    from horovod_tpu.parallel.mesh import make_parallel_mesh

    mesh = make_parallel_mesh(sp=8)
    cfg = GPTConfig(vocab_size=64, n_layers=2, d_model=32, n_heads=2,
                    d_ff=64, dtype=jnp.float32)
    tokens = jnp.asarray(np.random.RandomState(2).randint(0, 64, (2, 32)))
    model = GPT(cfg)
    params = model.init(jax.random.PRNGKey(0), tokens)
    cfg_ring = dataclasses.replace(cfg, ring_mesh=mesh,
                                   use_flash=use_flash)
    model_r = GPT(cfg_ring)
    tokens_sp = jax.device_put(tokens,
                               NamedSharding(mesh, PS(None, "sp")))

    def loss(m, p, t):
        return (m.apply(p, t).astype(jnp.float32) ** 2).mean()

    l0, g0 = jax.value_and_grad(lambda p: loss(model, p, tokens))(params)
    l1, g1 = jax.value_and_grad(
        lambda p: loss(model_r, p, tokens_sp))(params)
    np.testing.assert_allclose(np.asarray(l1), np.asarray(l0),
                               rtol=2e-5, atol=2e-6)
    for a, b in zip(jax.tree.leaves(g1), jax.tree.leaves(g0)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-3, atol=1e-5)


def test_gpt_use_flash_auto_resolves_by_sequence_length(monkeypatch):
    """use_flash="auto" (opt-in; the default stays False) picks the
    measured winner per sequence length: einsum at/below the 2048 crossover, the flash
    kernel above (at 8192 the einsum path crashes the TPU worker, so
    auto is also a safety rail). Verified by instrumenting the kernel
    entry point."""
    import dataclasses

    from horovod_tpu.models import GPT, GPTConfig
    from horovod_tpu.models import transformer as tr
    from horovod_tpu.ops import flash_attention as fa

    calls = []
    real = fa.flash_attention

    def spy(*a, **k):
        calls.append(a[0].shape)
        return real(*a, **k)

    monkeypatch.setattr(fa, "flash_attention", spy)
    # "auto" upgrades only on a real TPU backend (off-TPU the kernel
    # would run in interpret mode); fake the backend for the resolver
    # and keep the kernel itself in interpret mode via the env knob
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    monkeypatch.setenv("HVT_FLASH_INTERPRET", "1")
    # resolver sanity incl. the boundary
    assert tr._resolve_flash("auto", 2048) is False
    assert tr._resolve_flash("auto", 2049) is True
    assert tr._resolve_flash(True, 16) is True
    assert tr._resolve_flash(False, 100000) is False
    with pytest.raises(ValueError, match="auto"):
        tr._resolve_flash("einsum", 16)

    cfg = GPTConfig(vocab_size=64, n_layers=1, d_model=32, n_heads=2,
                    d_ff=64, dtype=jnp.float32, max_seq_len=4096,
                    use_flash="auto")
    tokens_short = jnp.asarray(
        np.random.RandomState(0).randint(0, 64, (1, 16)))
    model = GPT(cfg)
    params = model.init(jax.random.PRNGKey(0), tokens_short)
    model.apply(params, tokens_short)
    assert not calls, "auto must use einsum at short sequences"

    # long sequence: auto must route through the flash kernel. Shrink
    # the threshold so the CPU-interpret run stays fast.
    monkeypatch.setattr(fa, "FLASH_AUTO_THRESHOLD", 64)
    tokens_long = jnp.asarray(
        np.random.RandomState(0).randint(0, 64, (1, 128)))
    model.apply(params, tokens_long)
    assert calls, "auto must use the flash kernel at long sequences"


def test_vgg16_and_inception_forward_backward():
    """Benchmark-trio parity (reference docs/benchmarks.rst:13-14 runs
    Inception V3 + VGG-16 + ResNet): both models train a step at reduced
    resolution with finite loss/grads; the canonical param counts at
    native resolution are asserted below (VGG16-BN 138.4M incl. the
    4096-wide FCs; InceptionV3 23.8M)."""
    import optax

    from horovod_tpu.models import InceptionV3, VGG16

    # canonical param counts at native resolution: a silently altered
    # tower width would otherwise keep loss/grads finite while bench.py
    # benchmarks a different model than the reference trio
    def n_params(model, size):
        var = jax.eval_shape(
            lambda: model.init(jax.random.PRNGKey(0),
                               jnp.zeros((1, size, size, 3), jnp.float32),
                               train=True))
        return sum(int(np.prod(l.shape))
                   for l in jax.tree.leaves(var["params"]))

    assert abs(n_params(VGG16(num_classes=1000, dtype=jnp.float32), 224)
               - 138.36e6) < 0.3e6
    assert abs(n_params(InceptionV3(num_classes=1000, dtype=jnp.float32),
                        299) - 23.83e6) < 0.1e6

    rs = np.random.RandomState(0)
    for model, size in [(VGG16(num_classes=10, dtype=jnp.float32), 32),
                        (InceptionV3(num_classes=10, dtype=jnp.float32),
                         299)]:
        x = jnp.asarray(rs.randn(2, size, size, 3), jnp.float32)
        y = jnp.asarray(rs.randint(0, 10, (2,)))
        variables = model.init(jax.random.PRNGKey(0), x, train=True)
        params, bstats = variables["params"], variables["batch_stats"]

        def loss_fn(p):
            logits, _ = model.apply(
                {"params": p, "batch_stats": bstats}, x, train=True,
                mutable=["batch_stats"])
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, y).mean()

        l, g = jax.value_and_grad(loss_fn)(params)
        assert np.isfinite(float(l))
        leaves = jax.tree.leaves(g)
        assert leaves and all(np.all(np.isfinite(np.asarray(p)))
                              for p in leaves)


def test_gpt_gqa_all_attention_paths_agree():
    """n_kv_heads (GQA/MQA, LLaMA-2 lineage): einsum, flash, and
    ring-mesh paths must produce identical logits/grads for the same
    params; K/V projections shrink to n_kv_heads."""
    import dataclasses

    from horovod_tpu.models import GPT, GPTConfig

    cfg = GPTConfig(vocab_size=64, n_layers=2, d_model=32, n_heads=4,
                    n_kv_heads=2, d_ff=64, dtype=jnp.float32)
    tokens = jnp.asarray(np.random.RandomState(2).randint(0, 64, (2, 16)))
    model = GPT(cfg)
    params = model.init(jax.random.PRNGKey(0), tokens)

    # K/V kernels carry n_kv_heads
    att0 = params["params"]["block_0"]["attn"]
    assert att0["q"]["kernel"].shape == (32, 4, 8)
    assert att0["k"]["kernel"].shape == (32, 2, 8)
    assert att0["v"]["kernel"].shape == (32, 2, 8)

    def loss(m, p):
        return (m.apply(p, tokens).astype(jnp.float32) ** 2).mean()

    l0, g0 = jax.value_and_grad(lambda p: loss(model, p))(params)
    model_f = GPT(dataclasses.replace(cfg, use_flash=True))
    l1, g1 = jax.value_and_grad(lambda p: loss(model_f, p))(params)
    np.testing.assert_allclose(np.asarray(l1), np.asarray(l0),
                               rtol=2e-5, atol=2e-6)
    for a, b in zip(jax.tree.leaves(g1), jax.tree.leaves(g0)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-5)

    # MQA (n_kv_heads=1) also runs
    cfg_mqa = dataclasses.replace(cfg, n_kv_heads=1)
    m2 = GPT(cfg_mqa)
    p2 = m2.init(jax.random.PRNGKey(0), tokens)
    assert np.isfinite(float(loss(m2, p2)))

    with pytest.raises(ValueError, match="divide"):
        GPT(dataclasses.replace(cfg, n_kv_heads=3)).init(
            jax.random.PRNGKey(0), tokens)


def test_gpt_gqa_ring_mesh_matches_plain():
    """GQA composes with ring-attention sequence parallelism (K/V
    broadcast before the ring; logits match the non-ring model)."""
    import dataclasses

    from jax.sharding import Mesh

    from horovod_tpu.models import GPT, GPTConfig

    devs = np.array(jax.devices()[:4]).reshape(1, 4)
    mesh = Mesh(devs, ("dp", "sp"))
    cfg = GPTConfig(vocab_size=64, n_layers=1, d_model=32, n_heads=4,
                    n_kv_heads=2, d_ff=64, dtype=jnp.float32)
    tokens = jnp.asarray(np.random.RandomState(3).randint(0, 64, (2, 32)))
    model = GPT(cfg)
    params = model.init(jax.random.PRNGKey(0), tokens)
    base = model.apply(params, tokens)

    ring = GPT(dataclasses.replace(cfg, ring_mesh=mesh))
    out = ring.apply(params, tokens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(base),
                               rtol=2e-4, atol=2e-4)


def test_param_partition_spec_gqa_tp_fallback():
    """Round-4 review pin: with n_kv_heads < tp the K/V head axis is not
    divisible over the tp mesh axis — the spec must fall back to
    REPLICATED K/V (Megatron MQA layout) instead of emitting a sharding
    GSPMD rejects. Q keeps its tp sharding either way."""
    from horovod_tpu.models import GPT, GPTConfig
    from horovod_tpu.models.transformer import param_partition_spec

    cfg = GPTConfig(vocab_size=64, n_layers=1, d_model=32, n_heads=8,
                    n_kv_heads=2, d_ff=64, dtype=jnp.float32)
    params = GPT(cfg).init(jax.random.PRNGKey(0),
                           jnp.zeros((1, 8), jnp.int32))["params"]
    att = params["block_0"]["attn"]

    specs4 = param_partition_spec(params, tp_size=4)
    s_att4 = specs4["block_0"]["attn"]
    assert s_att4["q"]["kernel"] == P(None, "tp", None)
    assert s_att4["k"]["kernel"] == P()       # 2 kv heads % 4 -> replicate
    assert s_att4["v"]["kernel"] == P()

    specs2 = param_partition_spec(params, tp_size=2)
    s_att2 = specs2["block_0"]["attn"]
    assert s_att2["k"]["kernel"] == P(None, "tp", None)  # divisible: shard

    # no tp_size: pre-GQA behavior (assumes divisibility)
    specs = param_partition_spec(params)
    assert specs["block_0"]["attn"]["k"]["kernel"] == P(None, "tp", None)
    del att


def test_conv0_space_to_depth_odd_input_raises_clear_error():
    """Odd H/W cannot fold 2x2 pixel blocks; the stem must raise a
    ValueError naming conv0_space_to_depth, not an opaque reshape
    error from deep inside XLA."""
    from horovod_tpu.models.resnet import _SpaceToDepthStem

    stem = _SpaceToDepthStem(features=16, dtype=jnp.float32)
    x = jnp.zeros((1, 33, 32, 3), jnp.float32)
    with pytest.raises(ValueError, match="conv0_space_to_depth.*33x32"):
        stem.init(jax.random.PRNGKey(0), x)
    with pytest.raises(ValueError, match="conv0_space_to_depth"):
        stem.init(jax.random.PRNGKey(0),
                  jnp.zeros((1, 32, 31, 3), jnp.float32))
