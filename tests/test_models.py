"""Model + sharding tests (the reference has no models of its own; these
cover the benchmark/flagship models and the driver entry contract)."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P


def test_resnet50_forward_shape():
    from horovod_tpu.models import ResNet50

    model = ResNet50(num_classes=10, dtype=jnp.float32)
    x = jnp.zeros((2, 64, 64, 3))
    variables = model.init(jax.random.PRNGKey(0), x, train=True)
    logits, mutated = model.apply(variables, x, train=True,
                                  mutable=["batch_stats"])
    assert logits.shape == (2, 10)
    assert logits.dtype == jnp.float32
    assert "batch_stats" in mutated


def test_resnet_eval_mode():
    from horovod_tpu.models import ResNet50

    model = ResNet50(num_classes=10, dtype=jnp.float32)
    x = jnp.zeros((2, 64, 64, 3))
    variables = model.init(jax.random.PRNGKey(0), x, train=True)
    logits = model.apply(variables, x, train=False)
    assert logits.shape == (2, 10)


def test_gpt_forward():
    from horovod_tpu.models import GPT, GPTConfig

    cfg = GPTConfig(vocab_size=64, n_layers=2, d_model=32, n_heads=2,
                    d_ff=64, dtype=jnp.float32)
    model = GPT(cfg)
    tokens = jnp.asarray(np.random.RandomState(0).randint(0, 64, (2, 16)))
    params = model.init(jax.random.PRNGKey(0), tokens)
    logits = model.apply(params, tokens)
    assert logits.shape == (2, 16, 64)


def test_gpt_causality():
    # changing a future token must not affect earlier logits
    from horovod_tpu.models import GPT, GPTConfig

    cfg = GPTConfig(vocab_size=64, n_layers=1, d_model=32, n_heads=2,
                    d_ff=64, dtype=jnp.float32)
    model = GPT(cfg)
    rng = np.random.RandomState(1)
    t1 = rng.randint(0, 64, (1, 8))
    t2 = t1.copy()
    t2[0, -1] = (t2[0, -1] + 1) % 64
    params = model.init(jax.random.PRNGKey(0), jnp.asarray(t1))
    l1 = model.apply(params, jnp.asarray(t1))
    l2 = model.apply(params, jnp.asarray(t2))
    np.testing.assert_allclose(np.asarray(l1[0, :-1]),
                               np.asarray(l2[0, :-1]), atol=1e-5)
    assert not np.allclose(np.asarray(l1[0, -1]), np.asarray(l2[0, -1]))


def test_param_partition_spec():
    from horovod_tpu.models import GPT, GPTConfig
    from horovod_tpu.models.transformer import param_partition_spec

    cfg = GPTConfig(vocab_size=64, n_layers=1, d_model=32, n_heads=2,
                    d_ff=64, dtype=jnp.float32)
    model = GPT(cfg)
    tokens = jnp.zeros((1, 8), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens)["params"]
    specs = param_partition_spec(params)
    flat = jax.tree_util.tree_flatten_with_path(
        specs, is_leaf=lambda x: isinstance(x, P))[0]
    by_name = {"/".join(str(getattr(k, "key", k)) for k in path): spec
               for path, spec in flat}
    assert by_name["embedding"] == P("tp", None)
    assert any(s == P(None, "tp", None) for n, s in by_name.items()
               if n.endswith("q/kernel"))
    assert any(s == P("tp", None, None) for n, s in by_name.items()
               if n.endswith("o/kernel"))
    assert any(s == P(None, "tp") for n, s in by_name.items()
               if n.endswith("up/kernel"))
    assert any(s == P("tp", None) for n, s in by_name.items()
               if n.endswith("down/kernel"))
    assert any(s == P() for n, s in by_name.items() if "ln" in n)


def test_graft_entry_single_chip():
    import __graft_entry__ as ge

    fwd, (params, tokens) = ge.entry()
    logits = jax.jit(fwd)(params, tokens)
    assert logits.shape[:2] == tokens.shape
    assert bool(jnp.all(jnp.isfinite(logits)))


def test_graft_dryrun_multichip_8():
    import __graft_entry__ as ge

    ge.dryrun_multichip(8)


def test_mesh_factors():
    import __graft_entry__ as ge

    for n in (1, 2, 4, 8, 16, 64, 256):
        dp, sp, tp = ge._mesh_factors(n)
        assert dp * sp * tp == n


def test_gpt_flash_attention_matches_einsum_path():
    """use_flash must be a pure performance switch: identical logits and
    gradients (the pallas kernel runs in interpret mode on the CPU
    mesh)."""
    import dataclasses

    from horovod_tpu.models import GPT, GPTConfig

    cfg = GPTConfig(vocab_size=64, n_layers=2, d_model=32, n_heads=2,
                    d_ff=64, dtype=jnp.float32)
    tokens = jnp.asarray(np.random.RandomState(1).randint(0, 64, (2, 16)))
    model = GPT(cfg)
    params = model.init(jax.random.PRNGKey(0), tokens)
    model_f = GPT(dataclasses.replace(cfg, use_flash=True))

    def loss(m, p):
        return (m.apply(p, tokens).astype(jnp.float32) ** 2).mean()

    l0, g0 = jax.value_and_grad(lambda p: loss(model, p))(params)
    l1, g1 = jax.value_and_grad(lambda p: loss(model_f, p))(params)
    np.testing.assert_allclose(np.asarray(l1), np.asarray(l0),
                               rtol=2e-5, atol=2e-6)
    for a, b in zip(jax.tree.leaves(g1), jax.tree.leaves(g0)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-5)
