"""Checkpoint/resume tests (SURVEY.md §5.4 — orbax file layer with
broadcast-on-restore)."""

import numpy as np
import pytest

pytest.importorskip("orbax.checkpoint")

import jax.numpy as jnp  # noqa: E402

from horovod_tpu import checkpoint  # noqa: E402


def _state(seed=0):
    rs = np.random.RandomState(seed)
    return {"params": {"w": jnp.asarray(rs.randn(4, 3).astype(np.float32)),
                       "b": jnp.asarray(rs.randn(3).astype(np.float32))},
            "step": jnp.asarray(7)}


def _assert_tree_equal(a, b):
    import jax

    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y))


def test_save_restore_roundtrip(tmp_path):
    state = _state()
    checkpoint.save(str(tmp_path / "ckpt"), state)
    restored = checkpoint.restore(str(tmp_path / "ckpt"), template=state)
    _assert_tree_equal(state, restored)


def test_manager_latest_and_retention(tmp_path):
    mgr = checkpoint.CheckpointManager(str(tmp_path), max_to_keep=2,
                                       async_save=False)
    try:
        for step in (1, 2, 3):
            st = _state(step)
            assert mgr.save(step, st)
        mgr.wait()
        assert mgr.latest_step() == 3
        assert len(mgr.all_steps()) <= 2           # retention enforced
        restored = mgr.restore_latest(template=_state(0))
        _assert_tree_equal(_state(3), restored)
    finally:
        mgr.close()


def test_manager_save_interval(tmp_path):
    mgr = checkpoint.CheckpointManager(str(tmp_path), max_to_keep=5,
                                       save_interval_steps=2,
                                       async_save=False)
    try:
        assert mgr.save(0, _state(0))
        assert not mgr.save(1, _state(1))          # skipped by interval
        assert mgr.save(2, _state(2))
        assert mgr.save(3, _state(3), force=True)  # force overrides
    finally:
        mgr.close()


def test_restore_latest_empty(tmp_path):
    mgr = checkpoint.CheckpointManager(str(tmp_path), async_save=False)
    try:
        assert mgr.restore_latest() is None
    finally:
        mgr.close()


def test_async_save_then_wait(tmp_path):
    mgr = checkpoint.CheckpointManager(str(tmp_path), async_save=True)
    try:
        st = _state(42)
        mgr.save(5, st)
        mgr.wait()                                  # durable after wait
        restored = mgr.restore(5, template=st)
        _assert_tree_equal(st, restored)
    finally:
        mgr.close()
