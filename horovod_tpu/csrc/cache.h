// Response cache — steady-state fast path of the coordination protocol
// (reference horovod/common/response_cache.{h,cc}: LRU of negotiated
// responses whose *bit positions* are synchronized across ranks, so a
// repeating training step skips the full request gather; fast path at
// controller.cc:194-237).
//
// Determinism requirement (reference controller.cc:226-236): every rank
// must hold an identical cache (same entries at same positions, same
// eviction order). Guaranteed here because insertions and touches happen
// only while executing the coordinator-ordered response list, which is
// identical on all ranks.
#pragma once

#include <cstdint>
#include <list>
#include <string>
#include <unordered_map>
#include <vector>

#include "common.h"
#include "wire.h"

namespace hvt {

struct CachedParams {
  OpType op;
  ReduceKind reduce;
  DataType dtype;
  TensorShape shape;
  int32_t root_rank;
  double prescale, postscale;
  std::vector<int64_t> splits;
  // process-set membership (empty = the global set). Cached responses
  // are lane-scoped: a hit only fires when the announcing request names
  // the same member list, and the fast path requires exactly the cached
  // members (not the whole world) to have the position pending.
  std::vector<int64_t> members;

  bool Matches(const Request& r) const {
    return op == r.op && reduce == r.reduce && dtype == r.dtype &&
           shape == r.shape && root_rank == r.root_rank &&
           prescale == r.prescale && postscale == r.postscale &&
           splits == r.splits && members == r.members;
  }
};

class ResponseCache {
 public:
  explicit ResponseCache(size_t capacity = 1024) : capacity_(capacity) {}

  static constexpr int32_t kMiss = -1;
  static constexpr int32_t kInvalid = -2;

  // kMiss: not cached. position >= 0: cached with matching params.
  // kInvalid: cached under different params → must be evicted everywhere.
  int32_t Lookup(const Request& r) const {
    auto it = index_.find(r.name);
    if (it == index_.end()) return kMiss;
    return it->second.params.Matches(r) ? it->second.position : kInvalid;
  }

  const CachedParams* ParamsAt(int32_t position) const {
    auto it = by_position_.find(position);
    return it == by_position_.end() ? nullptr : &index_.at(it->second).params;
  }
  const std::string& NameAt(int32_t position) const {
    return by_position_.at(position);
  }
  int32_t PositionOf(const std::string& name) const {
    auto it = index_.find(name);
    return it == index_.end() ? kMiss : it->second.position;
  }
  // Build the execution Response for a cached position — the single
  // spelling shared by the coordinator's all-members-hit fast path and
  // by workers rebuilding a positions-form response frame
  // (kRespFlagPositions): both sides MUST produce byte-identical
  // responses from the same (identical-by-construction) cache, or the
  // steady-state bypass would diverge the gang. Returns false when the
  // position is not live.
  bool ResponseAt(int32_t position, Response* out) const {
    const CachedParams* p = ParamsAt(position);
    if (!p) return false;
    out->kind = Response::Kind::TENSOR;
    out->op = p->op;
    out->names = {NameAt(position)};
    out->dtype = p->dtype;
    out->reduce = p->reduce;
    out->root = p->root_rank;
    out->prescale = p->prescale;
    out->postscale = p->postscale;
    out->numels = {p->shape.num_elements()};
    out->shapes = {p->shape};  // local-only: see Response::shapes
    out->members = p->members;
    return true;
  }

  // Evict by position; returns the evicted name ("" if not present).
  std::string EvictPosition(int32_t position) {
    auto it = by_position_.find(position);
    if (it == by_position_.end()) return "";
    std::string name = it->second;
    Evict(name);
    return name;
  }

  // Insert after execution (same order on all ranks). Returns position.
  int32_t Insert(const std::string& name, const CachedParams& p) {
    auto it = index_.find(name);
    if (it != index_.end()) {
      it->second.params = p;
      Touch(name);
      return it->second.position;
    }
    if (index_.size() >= capacity_) EvictLRU();
    int32_t pos = next_position_++;
    index_[name] = Entry{p, pos};
    by_position_[pos] = name;
    lru_.push_back(name);
    return pos;
  }

  void Touch(const std::string& name) {
    lru_.remove(name);
    lru_.push_back(name);
  }

  void Evict(const std::string& name) {
    auto it = index_.find(name);
    if (it == index_.end()) return;
    by_position_.erase(it->second.position);
    lru_.remove(name);
    index_.erase(it);
  }

  size_t size() const { return index_.size(); }

  // Dense bitvector over live positions; positions are monotonically
  // assigned, so the bit index is the position itself (sparse but bounded
  // by total distinct tensors; fine for the control plane frame).
  int32_t max_position() const { return next_position_; }

 private:
  void EvictLRU() {
    if (lru_.empty()) return;
    Evict(lru_.front());
  }

  struct Entry {
    CachedParams params;
    int32_t position;
  };
  size_t capacity_;
  int32_t next_position_ = 0;
  std::unordered_map<std::string, Entry> index_;
  std::unordered_map<int32_t, std::string> by_position_;
  std::list<std::string> lru_;  // front = least recently used
};

}  // namespace hvt
