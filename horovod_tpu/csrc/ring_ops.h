// CPU data plane: collectives over a TCP full mesh.
//
// This is the Gloo-equivalent CPU backend (reference
// horovod/common/ops/gloo_operations.cc — ring/halving-doubling allreduce,
// allgatherv, broadcast, alltoallv), rebuilt without the gloo dependency:
//
// - allreduce: ring reduce-scatter + ring allgather (bandwidth-optimal,
//   2(N-1)/N * bytes on the wire per rank).
// - allgatherv: ring rotation, N-1 steps.
// - broadcast: star from root (N is small on the eager path; the TPU data
//   plane handles the large-N case in XLA).
// - alltoallv: pairwise exchange, rank-ordered to avoid deadlock.
//
// fp16/bf16 are accumulated in fp32 (reference half.{h,cc} + the fused
// scale kernels do the same widening).
#pragma once

#include <cstdint>
#include <vector>

#include "common.h"
#include "net.h"

namespace hvt {

class DataPlane {
 public:
  // peers: socket per rank (peers[self] unused/invalid).
  DataPlane(int rank, int size, std::vector<Sock> peers)
      : rank_(rank), size_(size), peers_(std::move(peers)) {}

  int rank() const { return rank_; }
  int size() const { return size_; }

  void Allreduce(void* buf, int64_t count, DataType dtype, ReduceKind red);
  // rows per rank along dim 0; row_bytes = bytes of one row.
  void Allgatherv(const void* in, int64_t my_rows,
                  const std::vector<int64_t>& rows, int64_t row_bytes,
                  void* out);
  void Broadcast(void* buf, int64_t bytes, int root);
  // send_rows[r] rows go to rank r; returns recv rows from each rank in
  // recv_rows; out must hold sum(recv_rows)*row_bytes.
  void Alltoallv(const void* in, const std::vector<int64_t>& send_rows,
                 int64_t row_bytes, void* out,
                 const std::vector<int64_t>& recv_rows);

 private:
  Sock& peer(int r) { return peers_[static_cast<size_t>(r)]; }
  int rank_, size_;
  std::vector<Sock> peers_;
  std::vector<uint8_t> scratch_;
};

// Elementwise accumulate: dst = dst (op) src, for count elements.
void ReduceInto(void* dst, const void* src, int64_t count, DataType dtype,
                ReduceKind red);
// dst *= factor (no-op for factor 1.0); used for pre/postscale + Average.
void ScaleBuffer(void* dst, int64_t count, DataType dtype, double factor);

}  // namespace hvt
