// CPU data plane: collectives over a TCP full mesh.
//
// This is the Gloo-equivalent CPU backend (reference
// horovod/common/ops/gloo_operations.cc — ring/halving-doubling allreduce,
// allgatherv, broadcast, alltoallv), rebuilt without the gloo dependency:
//
// - allreduce: ring reduce-scatter + ring allgather (bandwidth-optimal,
//   2(N-1)/N * bytes on the wire per rank), pipelined: each ring step
//   pumps both socket directions with nonblocking I/O + poll and reduces
//   each received chunk while later chunks are still in flight, so
//   recv(k+1) overlaps reduce(k) and send(k-1) instead of the serialized
//   send → recv → reduce of a blocking ring. HVT_RING_CHUNK_BYTES sets
//   the chunk (default 1 MB); HVT_RING_PIPELINE=0 restores the
//   blocking parity-ordered ring (A/B baseline).
// - allgatherv: ring rotation, N-1 steps, same duplex pump.
// - broadcast: star from root (N is small on the eager path; the TPU data
//   plane handles the large-N case in XLA).
// - alltoallv: pairwise exchange, rank-ordered to avoid deadlock.
//
// fp16/bf16 are accumulated in fp32 (reference half.{h,cc} + the fused
// scale kernels do the same widening).
//
// Wire compression: when a response is stamped with a non-RAW WireCodec
// (fp32 allreduce under HVT_WIRE_COMPRESSION; see csrc/codecs.h for the
// codec family), both ring phases move compressed payloads — bf16
// halves the bytes, the block-scaled int8/fp8 codecs cut ~3.94x — and
// widen back to fp32 for the reduce. Chunked pipelining survives
// because every codec's stream is self-contained at WireBlockBytes()
// granularity (in-band per-block scales), and ring chunks are aligned
// to it. Every rank ends with bit-identical buffers: after the
// reduce-scatter each rank round-trips its owned segment through the
// codec before the allgather, so owners and receivers see the same
// values; compressed allgather forwarding never recompresses.
#pragma once

#include <atomic>
#include <cstdint>
#include <functional>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

#include "common.h"
#include "events.h"
#include "net.h"
#include "transport.h"

namespace hvt {

// Per-OpType wire-telemetry slots (OpType 0..6; mirrors engine kStatsOps).
constexpr int kWireOps = 7;

// Index of `rank` within an ascending rank group (throws if absent) —
// shared by the ring phases and the topology builder (backends.cc).
inline int GroupIndexOf(const std::vector<int>& group, int rank) {
  for (size_t i = 0; i < group.size(); ++i)
    if (group[i] == rank) return static_cast<int>(i);
  throw std::runtime_error("hvt: rank not in collective group");
}

class DataPlane {
 public:
  // peers: one Transport per rank (peers[self] unused/null). The plane
  // codes strictly against the Transport seam (transport.h) — the
  // self-healing TcpLink is what the engine wires in today, and the
  // io_uring/RDMA backends ROADMAP item 5 plans replace it here.
  DataPlane(int rank, int size,
            std::vector<std::unique_ptr<Transport>> peers);

  int rank() const { return rank_; }
  int size() const { return size_; }

  // postscale is folded into the final allgather pass: each rank scales
  // the one segment it owns fully-reduced (1/N of the scalar work) and
  // the rotation distributes scaled data — no separate full-buffer sweep.
  void Allreduce(void* buf, int64_t count, DataType dtype, ReduceKind red,
                 double postscale = 1.0, WireCodec wire = WireCodec::RAW);
  // Group-parameterized ring collective over a subset of ranks (ascending
  // global ranks, must contain this rank). Disjoint groups may run
  // concurrently — the mesh is pairwise, so their traffic never crosses.
  // Building block of the hierarchical LOCAL/CROSS composition
  // (backends.h).
  void AllreduceGroup(void* buf, int64_t count, DataType dtype,
                      ReduceKind red, const std::vector<int>& group,
                      double postscale = 1.0,
                      WireCodec wire = WireCodec::RAW);
  // Ring reduce-scatter phase: after it, the rank at group index i owns
  // fully-reduced segment (i+1) % |group| of `bytes` (segments given by
  // seg_off, element size el). A non-RAW wire codec requires el == 4
  // (fp32); callers pass the codec already resolved for this link class
  // (the backends map {intra, inter} pairs onto phases).
  void RingReduceScatter(uint8_t* bytes,
                         const std::vector<int64_t>& seg_off, size_t el,
                         DataType dtype, ReduceKind red,
                         const std::vector<int>& group,
                         WireCodec wire = WireCodec::RAW);
  // Ring allgather phase rotating owned segments (inverse of the above's
  // ownership: entering, group index i holds segment (i+1) % |group|).
  // With a compressing wire codec, received segments are forwarded in
  // compressed form (no recompression at intermediate hops).
  void RingAllgatherSegs(uint8_t* bytes,
                         const std::vector<int64_t>& seg_off, size_t el,
                         const std::vector<int>& group,
                         WireCodec wire = WireCodec::RAW);
  // rows per rank along dim 0; row_bytes = bytes of one row.
  void Allgatherv(const void* in, int64_t my_rows,
                  const std::vector<int64_t>& rows, int64_t row_bytes,
                  void* out);
  // Subgroup variant: rows indexed by group POSITION; this rank must be
  // in `group` (ascending global ranks).
  void AllgathervGroup(const void* in, int64_t my_rows,
                       const std::vector<int64_t>& rows, int64_t row_bytes,
                       void* out, const std::vector<int>& group);
  void Broadcast(void* buf, int64_t bytes, int root);
  // root is a GLOBAL rank and must be in `group`.
  void BroadcastGroup(void* buf, int64_t bytes, int root,
                      const std::vector<int>& group);
  // send_rows[r] rows go to rank r; returns recv rows from each rank in
  // recv_rows; out must hold sum(recv_rows)*row_bytes.
  void Alltoallv(const void* in, const std::vector<int64_t>& send_rows,
                 int64_t row_bytes, void* out,
                 const std::vector<int64_t>& recv_rows);
  // Subgroup variant: send/recv rows indexed by group POSITION.
  void AlltoallvGroup(const void* in, const std::vector<int64_t>& send_rows,
                      int64_t row_bytes, void* out,
                      const std::vector<int64_t>& recv_rows,
                      const std::vector<int>& group);

  // Coordinated-abort fan-out: hard-close every peer link (DEAD — no
  // reconnect). The close sends a FIN/RST, so any peer blocked in a
  // data-plane recv on this rank wakes immediately with PeerLostError
  // instead of waiting out its own HVT_OP_TIMEOUT_MS deadline —
  // survivors of a gang failure converge in one deadline, not N.
  // Engine-thread only (called on the abort path after the collective
  // in flight threw).
  void Abort() {
    for (auto& s : peers_)
      if (s) s->Abort();
  }

  // ---- wire telemetry (hvt_engine_stats → metrics plane) --------------
  // The engine stamps the OpType before dispatching a response; every
  // byte this plane sends is attributed to it. The counters themselves
  // are OWNED BY THE CALLER (the engine's stats block, which outlives
  // this object) and bound here — scrape threads must be able to read
  // them while Shutdown destroys the DataPlane. Arrays of kWireOps
  // relaxed atomics.
  void BindTxCounters(std::atomic<int64_t>* tx,
                      std::atomic<int64_t>* tx_comp) {
    tx_sink_ = tx;
    txc_sink_ = tx_comp;
  }
  // Per-(codec, op) byte attribution behind
  // hvt_wire_tx_bytes_total{op,codec}: a flat
  // [kWireCodecCount * kWireOps] array, codec-major — caller-owned like
  // the per-op counters above.
  void BindCodecTxCounters(std::atomic<int64_t>* sink) {
    codec_tx_sink_ = sink;
  }
  void set_stat_op(int op) {
    Ctx().stat_op = (op >= 0 && op < kWireOps) ? op : 0;
  }

  // ---- wire-phase flight-recorder spans --------------------------------
  // The engine binds its EventRing (which outlives this object, like the
  // tx counters) and stamps the executing response's identity before
  // dispatch; the duplex pump then records WIRE_BEGIN/WIRE_END spans so
  // the timeline/analyzer can split execution into wire vs reduce time.
  // Spans cover the pipelined pump (the default path); the blocking
  // HVT_RING_PIPELINE=0 parity baseline and the shm backend are not
  // spanned. Fused units attribute their spans to the first member name.
  void BindEvents(EventRing* ring) { events_ = ring; }
  // Syscall counter for the generic duplex pump
  // (hvt_pump_syscalls_total): every poll/send/recv the fallback loop
  // issues, flushed once per Duplex. Together with the hub's
  // uring_enters sink this is the per-backend syscalls-per-op story
  // the r18 sweep reports (blocking HVT_RING_PIPELINE=0 transfers and
  // control frames are not counted). Caller-owned, like the tx sinks.
  void BindPumpCounters(std::atomic<int64_t>* pump_syscalls) {
    pump_sink_ = pump_syscalls;
  }
  void set_wire_ctx(const std::string& name, int lane) {
    PlaneCtx& cx = Ctx();
    cx.wire_name = name;
    cx.wire_lane = lane;
  }

 private:
  // Per-thread execution context: the response-scoped telemetry stamps
  // (stat_op / wire ctx) and the scratch/staging buffers. One per
  // calling thread so the engine's per-lane worker pool can pump
  // disjoint sub-rings concurrently without sharing mutable state —
  // each lane's buffers also converge to that lane's working-set size,
  // exactly like the engine's per-lane fusion buffers.
  struct PlaneCtx {
    int stat_op = 0;
    std::string wire_name;
    int wire_lane = 0;
    std::vector<uint8_t> scratch;
    std::vector<uint8_t> wire_send, wire_recv;  // compressed ping-pong
    std::vector<float> decode;  // block-codec chunk-decode staging
  };
  PlaneCtx& Ctx() {
    const std::thread::id id = std::this_thread::get_id();
    std::lock_guard<std::mutex> lk(ctx_mu_);
    auto& p = ctxs_[id];
    if (!p) p.reset(new PlaneCtx());
    return *p;  // stable: boxed, never moved by rehash
  }
  Transport& peer(int r) { return *peers_[static_cast<size_t>(r)]; }
  void CountTx(size_t n, WireCodec codec) {
    if (!tx_sink_) return;
    const int op = Ctx().stat_op;
    tx_sink_[op].fetch_add(static_cast<int64_t>(n),
                           std::memory_order_relaxed);
    if (codec != WireCodec::RAW)
      txc_sink_[op].fetch_add(static_cast<int64_t>(n),
                              std::memory_order_relaxed);
    if (codec_tx_sink_)
      codec_tx_sink_[static_cast<int>(codec) * kWireOps + op]
          .fetch_add(static_cast<int64_t>(n), std::memory_order_relaxed);
  }
  void SendCounted(Transport& s, const void* data, size_t n,
                   WireCodec codec) {
    s.Send(data, n);
    CountTx(n, codec);
  }
  // Full-duplex pump: stream send_n bytes to `out` while receiving
  // recv_n bytes from `in` (nonblocking + poll, so neither direction
  // head-of-line blocks the other); on_chunk(byte_off, byte_len) fires
  // as each chunk_bytes-sized piece of the receive completes, letting
  // the reduce overlap the remaining transfer. `out` and `in` may be
  // the same socket (2-member rings).
  void Duplex(Transport& out, const uint8_t* send_buf, size_t send_n,
              Transport& in, uint8_t* recv_buf, size_t recv_n,
              size_t chunk_bytes, WireCodec codec,
              const std::function<void(size_t, size_t)>& on_chunk);

  int rank_, size_;
  std::vector<std::unique_ptr<Transport>> peers_;
  bool pipeline_ = true;        // HVT_RING_PIPELINE
  int64_t chunk_bytes_ = 1 << 20;  // HVT_RING_CHUNK_BYTES
  std::atomic<int64_t>* tx_sink_ = nullptr;   // [kWireOps], caller-owned
  std::atomic<int64_t>* txc_sink_ = nullptr;  // [kWireOps], caller-owned
  // [kWireCodecCount * kWireOps] codec-major, caller-owned
  std::atomic<int64_t>* codec_tx_sink_ = nullptr;
  std::atomic<int64_t>* pump_sink_ = nullptr;  // caller-owned scalar
  EventRing* events_ = nullptr;               // caller-owned (engine)
  std::mutex ctx_mu_;
  std::unordered_map<std::thread::id, std::unique_ptr<PlaneCtx>> ctxs_;
};

// Elementwise accumulate: dst = dst (op) src, for count elements.
void ReduceInto(void* dst, const void* src, int64_t count, DataType dtype,
                ReduceKind red);
// dst *= factor (no-op for factor 1.0); used for pre/postscale + Average.
// Integer dtypes round to nearest (half away from zero) rather than
// truncating toward zero.
void ScaleBuffer(void* dst, int64_t count, DataType dtype, double factor);

// (the bf16 wire helpers that used to live here are now the BF16 entry
// of the codec registry — csrc/codecs.{h,cc})

}  // namespace hvt
