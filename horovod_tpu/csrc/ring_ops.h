// CPU data plane: collectives over a TCP full mesh.
//
// This is the Gloo-equivalent CPU backend (reference
// horovod/common/ops/gloo_operations.cc — ring/halving-doubling allreduce,
// allgatherv, broadcast, alltoallv), rebuilt without the gloo dependency:
//
// - allreduce: ring reduce-scatter + ring allgather (bandwidth-optimal,
//   2(N-1)/N * bytes on the wire per rank).
// - allgatherv: ring rotation, N-1 steps.
// - broadcast: star from root (N is small on the eager path; the TPU data
//   plane handles the large-N case in XLA).
// - alltoallv: pairwise exchange, rank-ordered to avoid deadlock.
//
// fp16/bf16 are accumulated in fp32 (reference half.{h,cc} + the fused
// scale kernels do the same widening).
#pragma once

#include <cstdint>
#include <vector>

#include "common.h"
#include "net.h"

namespace hvt {

// Index of `rank` within an ascending rank group (throws if absent) —
// shared by the ring phases and the topology builder (backends.cc).
inline int GroupIndexOf(const std::vector<int>& group, int rank) {
  for (size_t i = 0; i < group.size(); ++i)
    if (group[i] == rank) return static_cast<int>(i);
  throw std::runtime_error("hvt: rank not in collective group");
}

class DataPlane {
 public:
  // peers: socket per rank (peers[self] unused/invalid).
  DataPlane(int rank, int size, std::vector<Sock> peers)
      : rank_(rank), size_(size), peers_(std::move(peers)) {}

  int rank() const { return rank_; }
  int size() const { return size_; }

  void Allreduce(void* buf, int64_t count, DataType dtype, ReduceKind red);
  // Group-parameterized ring collective over a subset of ranks (ascending
  // global ranks, must contain this rank). Disjoint groups may run
  // concurrently — the mesh is pairwise, so their traffic never crosses.
  // Building block of the hierarchical LOCAL/CROSS composition
  // (backends.h).
  void AllreduceGroup(void* buf, int64_t count, DataType dtype,
                      ReduceKind red, const std::vector<int>& group);
  // Ring reduce-scatter phase: after it, the rank at group index i owns
  // fully-reduced segment (i+1) % |group| of `bytes` (segments given by
  // seg_off, element size el).
  void RingReduceScatter(uint8_t* bytes,
                         const std::vector<int64_t>& seg_off, size_t el,
                         DataType dtype, ReduceKind red,
                         const std::vector<int>& group);
  // Ring allgather phase rotating owned segments (inverse of the above's
  // ownership: entering, group index i holds segment (i+1) % |group|).
  void RingAllgatherSegs(uint8_t* bytes,
                         const std::vector<int64_t>& seg_off, size_t el,
                         const std::vector<int>& group);
  // rows per rank along dim 0; row_bytes = bytes of one row.
  void Allgatherv(const void* in, int64_t my_rows,
                  const std::vector<int64_t>& rows, int64_t row_bytes,
                  void* out);
  // Subgroup variant: rows indexed by group POSITION; this rank must be
  // in `group` (ascending global ranks).
  void AllgathervGroup(const void* in, int64_t my_rows,
                       const std::vector<int64_t>& rows, int64_t row_bytes,
                       void* out, const std::vector<int>& group);
  void Broadcast(void* buf, int64_t bytes, int root);
  // root is a GLOBAL rank and must be in `group`.
  void BroadcastGroup(void* buf, int64_t bytes, int root,
                      const std::vector<int>& group);
  // send_rows[r] rows go to rank r; returns recv rows from each rank in
  // recv_rows; out must hold sum(recv_rows)*row_bytes.
  void Alltoallv(const void* in, const std::vector<int64_t>& send_rows,
                 int64_t row_bytes, void* out,
                 const std::vector<int64_t>& recv_rows);
  // Subgroup variant: send/recv rows indexed by group POSITION.
  void AlltoallvGroup(const void* in, const std::vector<int64_t>& send_rows,
                      int64_t row_bytes, void* out,
                      const std::vector<int64_t>& recv_rows,
                      const std::vector<int>& group);

 private:
  Sock& peer(int r) { return peers_[static_cast<size_t>(r)]; }
  int rank_, size_;
  std::vector<Sock> peers_;
  std::vector<uint8_t> scratch_;
};

// Elementwise accumulate: dst = dst (op) src, for count elements.
void ReduceInto(void* dst, const void* src, int64_t count, DataType dtype,
                ReduceKind red);
// dst *= factor (no-op for factor 1.0); used for pre/postscale + Average.
void ScaleBuffer(void* dst, int64_t count, DataType dtype, double factor);

}  // namespace hvt
