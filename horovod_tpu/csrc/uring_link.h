// IoUringLink — the io_uring data-plane backend behind the Transport
// seam (ROADMAP item 5; selected by HVT_LINK_BACKEND={tcp,io_uring,auto}).
//
// What it changes and what it keeps:
//
// - The SESSION layer is inherited, not reimplemented: IoUringLink IS a
//   TcpLink, so per-direction stream sequence numbers, the bounded
//   replay ring, transparent reconnect by rendezvous role (re-dial /
//   re-accept / parked-dial adoption), owner-token claims,
//   Abort-as-shutdown-without-close, and every chaos hook behave
//   bit-identically under both backends. Only the duplex PUMP — how
//   bytes move while a ring step is in flight — is replaced.
//
// - The pump override (Transport::PumpDuplex) batches a full-duplex
//   ring step into ONE io_uring_enter per wait: the send direction is
//   a single IORING_OP_SEND submitted straight from the fusion/chunk
//   scratch (no staging copy), the receive direction is either a
//   direct IORING_OP_RECV into the caller's buffer (large transfers)
//   or a multishot recv (IORING_RECV_MULTISHOT) draining into a
//   registered provided-buffer ring (IORING_REGISTER_PBUF_RING), so
//   many arriving chunks complete against one standing SQE. The old
//   poll+sendmsg+recv-per-chunk loop remains as the fallback and the
//   failure path: the pump is best-effort and returns partial progress
//   whenever the link needs the session machinery (replay pending,
//   reconnect, chaos cut), letting the battle-tested generic loop and
//   its heal/escalation semantics finish the transfer.
//
// - One ring per executing thread (engine thread + each HVT_LANE_WORKERS
//   lane worker, mirroring DataPlane's per-thread PlaneCtx): rings are
//   thread_local and lazily created, so disjoint serving lanes pump
//   disjoint link sets with no shared ring state and no locks.
//
// - Completion wait is spin-then-block: after submitting, the pump
//   polls the CQ from user space with cheap non-blocking
//   io_uring_enter(GETEVENTS) flushes for up to HVT_URING_SPIN_US
//   before arming a timed blocking wait (IORING_ENTER_EXT_ARG). On a
//   same-host gang the completion usually lands inside the spin
//   window, which removes the sleep/wake scheduler hop that dominates
//   the small-payload p50 (see docs/performance.md §transport-backends).
//
// Everything is raw syscalls (io_uring_setup/enter/register + mmap):
// the build does not depend on liburing, and constants newer than the
// toolchain's <linux/io_uring.h> are shimmed locally in uring_link.cc.
// Kernel support is probed once (UringSupported): ring setup +
// IORING_REGISTER_PROBE for SEND/RECV/ASYNC_CANCEL, and the provided
// buffer ring is verified by actually registering one. Callers (engine
// backend selection, tests, ci.sh) treat a failed probe as "use tcp".
#pragma once

#include "transport.h"

namespace hvt {

// One-time cached kernel-capability probe: true when a ring can be set
// up and every opcode the pump submits is supported. auto-selection,
// `hvt_uring_supported`, and the test/CI skips all key off this.
bool UringSupported();

// Resolved HVT_LINK_BACKEND: 0 = tcp, 1 = io_uring. The default is
// `auto` — io_uring wherever the kernel probe passes, with graceful
// fallback to tcp (and tcp for unknown values), so the fast path is on
// by default and a locked-down kernel/seccomp profile degrades to the
// seed behavior instead of failing.
int ResolveLinkBackend();
constexpr int kLinkBackendTcp = 0;
constexpr int kLinkBackendUring = 1;

// HVT_URING_DEPTH (default 64): SQ entries per per-thread ring. Bounds
// the SQE batch a single enter can submit; the pump needs at most a
// handful per step, so this only matters for many links per thread.
int64_t UringDepth();
// HVT_URING_SPIN_US (default 40): completion-wait spin window before
// the pump arms a blocking timed wait. 0 = always block immediately
// (lowest CPU, re-adds the wakeup hop to small-payload latency).
int64_t UringSpinUs();
// HVT_URING_MULTISHOT_MAX (default 262144): receive transfers at or
// under this many bytes use multishot recv through the registered
// provided-buffer pool (one standing SQE, bytes copied out of ring
// buffers); larger transfers use direct single-shot recv into the
// caller's buffer (zero-copy, one SQE per completion).
int64_t UringMultishotMax();

class IoUringLink : public TcpLink {
 public:
  using TcpLink::TcpLink;  // same roles/session state as the TCP link
  ~IoUringLink() override;

  // The batched pump (see the file comment). Best-effort: advances
  // `sent`/`rcvd`, fires `on_progress` after each receive completion
  // so chunk reduces overlap the in-flight transfer, and returns early
  // (having canceled and reaped every in-flight SQE — nothing may
  // reference the caller's buffers after return) whenever the session
  // layer must take over. Throws OpTimeoutError on a no-progress
  // deadline exactly like the generic loop.
  void PumpDuplex(Transport& in, const uint8_t* send_buf, size_t send_n,
                  uint8_t* recv_buf, size_t recv_n, size_t chunk_bytes,
                  size_t& sent, size_t& rcvd,
                  const std::function<void()>& on_progress) override;

  // Multishot recv can overshoot the current transfer (the peer runs
  // ahead into the next ring step); the overrun bytes — already
  // rx_-counted when reaped, so the replay handshake stays exact —
  // wait in a spill buffer that every receive path consumes first.
  size_t RecvSome(void* p, size_t n) override;
  void Recv(void* p, size_t n, int64_t timeout_ms = -1) override;
  // While spill bytes are pending the link reports fd() < 0 so the
  // generic Duplex loop drives RecvSome directly (its heal path)
  // instead of parking in poll() on a socket that owes nothing.
  int fd() const override {
    return spill_off_ < spill_.size() ? -1 : TcpLink::fd();
  }

 private:
  size_t TakeSpill(void* p, size_t n);
  friend struct UringPump;
  std::vector<uint8_t> spill_;
  size_t spill_off_ = 0;
};

}  // namespace hvt
