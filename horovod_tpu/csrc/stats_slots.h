// hvt_engine_stats slot manifest — THE single source of truth for the
// stats-slot ABI shared by csrc/c_api.cc (producer), engine/native.py
// (ctypes decoder), and common/basics.py poll_engine_stats (metrics
// bridge). The contract is APPEND-ONLY:
//
//   * never renumber, reorder, reuse, or delete a slot — older .so /
//     newer Python (and vice versa) must keep agreeing on every index
//     that both sides know about;
//   * to add telemetry, append new slots at the end, bump
//     HVT_STATS_SLOT_COUNT, extend the layout constants in
//     engine/native.py, and read the new fields in poll_engine_stats
//     (docs/development.md walks through it).
//
// The cross-language lint (horovod_tpu/tools/hvt_lint.py, `ci.sh
// --lint`) machine-checks all of this: indices contiguous and unique,
// names matching the Python layout exactly, the count matching the
// C++ formula (static_assert in c_api.cc), and every slot group read
// by the metrics bridge. Names use the Python-layout spelling:
// scalar slots are bare names, per-op arrays are `group[op]`, and the
// two engine histograms are `hist.bucket[i]` / `hist.sum_ns` /
// `hist.count`.
#pragma once

#define HVT_STATS_SLOT_COUNT 161

// X-macro: HVT_STATS_SLOT(index, "name")
#define HVT_STATS_SLOTS(X)                  \
  X(0, "cycles")                            \
  X(1, "tensors_submitted")                 \
  X(2, "tensors_coordinated")               \
  X(3, "cache_hits")                        \
  X(4, "cache_misses")                      \
  X(5, "fusion_bytes")                      \
  X(6, "responses_fused")                   \
  X(7, "stall_events")                      \
  X(8, "exec_ns[allreduce]")                \
  X(9, "exec_ns[allgather]")                \
  X(10, "exec_ns[broadcast]")               \
  X(11, "exec_ns[alltoall]")                \
  X(12, "exec_ns[reducescatter]")           \
  X(13, "exec_ns[join]")                    \
  X(14, "exec_ns[barrier]")                 \
  X(15, "exec_count[allreduce]")            \
  X(16, "exec_count[allgather]")            \
  X(17, "exec_count[broadcast]")            \
  X(18, "exec_count[alltoall]")             \
  X(19, "exec_count[reducescatter]")        \
  X(20, "exec_count[join]")                 \
  X(21, "exec_count[barrier]")              \
  X(22, "wire_tx_bytes[allreduce]")         \
  X(23, "wire_tx_bytes[allgather]")         \
  X(24, "wire_tx_bytes[broadcast]")         \
  X(25, "wire_tx_bytes[alltoall]")          \
  X(26, "wire_tx_bytes[reducescatter]")     \
  X(27, "wire_tx_bytes[join]")              \
  X(28, "wire_tx_bytes[barrier]")           \
  X(29, "wire_tx_comp_bytes[allreduce]")    \
  X(30, "wire_tx_comp_bytes[allgather]")    \
  X(31, "wire_tx_comp_bytes[broadcast]")    \
  X(32, "wire_tx_comp_bytes[alltoall]")     \
  X(33, "wire_tx_comp_bytes[reducescatter]") \
  X(34, "wire_tx_comp_bytes[join]")         \
  X(35, "wire_tx_comp_bytes[barrier]")      \
  X(36, "cycle_hist.bucket[0]")             \
  X(37, "cycle_hist.bucket[1]")             \
  X(38, "cycle_hist.bucket[2]")             \
  X(39, "cycle_hist.bucket[3]")             \
  X(40, "cycle_hist.bucket[4]")             \
  X(41, "cycle_hist.bucket[5]")             \
  X(42, "cycle_hist.bucket[6]")             \
  X(43, "cycle_hist.bucket[7]")             \
  X(44, "cycle_hist.bucket[8]")             \
  X(45, "cycle_hist.bucket[9]")             \
  X(46, "cycle_hist.bucket[10]")            \
  X(47, "cycle_hist.bucket[11]")            \
  X(48, "cycle_hist.bucket[12]")            \
  X(49, "cycle_hist.bucket[13]")            \
  X(50, "cycle_hist.bucket[14]")            \
  X(51, "cycle_hist.sum_ns")                \
  X(52, "cycle_hist.count")                 \
  X(53, "wakeup_hist.bucket[0]")            \
  X(54, "wakeup_hist.bucket[1]")            \
  X(55, "wakeup_hist.bucket[2]")            \
  X(56, "wakeup_hist.bucket[3]")            \
  X(57, "wakeup_hist.bucket[4]")            \
  X(58, "wakeup_hist.bucket[5]")            \
  X(59, "wakeup_hist.bucket[6]")            \
  X(60, "wakeup_hist.bucket[7]")            \
  X(61, "wakeup_hist.bucket[8]")            \
  X(62, "wakeup_hist.bucket[9]")            \
  X(63, "wakeup_hist.bucket[10]")           \
  X(64, "wakeup_hist.bucket[11]")           \
  X(65, "wakeup_hist.bucket[12]")           \
  X(66, "wakeup_hist.bucket[13]")           \
  X(67, "wakeup_hist.bucket[14]")           \
  X(68, "wakeup_hist.sum_ns")               \
  X(69, "wakeup_hist.count")                \
  X(70, "aborts[timeout]")                  \
  X(71, "aborts[peer_lost]")                \
  X(72, "aborts[remote_abort]")             \
  X(73, "aborts[heartbeat]")                \
  X(74, "aborts[internal]")                 \
  X(75, "lanes_active")                     \
  X(76, "lane_depth[0]")                    \
  X(77, "lane_depth[1]")                    \
  X(78, "lane_depth[2]")                    \
  X(79, "lane_depth[3]")                    \
  X(80, "lane_depth[4]")                    \
  X(81, "lane_depth[5]")                    \
  X(82, "lane_depth[6]")                    \
  X(83, "lane_depth[7]")                    \
  X(84, "lane_exec_ns[0]")                  \
  X(85, "lane_exec_ns[1]")                  \
  X(86, "lane_exec_ns[2]")                  \
  X(87, "lane_exec_ns[3]")                  \
  X(88, "lane_exec_ns[4]")                  \
  X(89, "lane_exec_ns[5]")                  \
  X(90, "lane_exec_ns[6]")                  \
  X(91, "lane_exec_ns[7]")                  \
  X(92, "lane_exec_count[0]")               \
  X(93, "lane_exec_count[1]")               \
  X(94, "lane_exec_count[2]")               \
  X(95, "lane_exec_count[3]")               \
  X(96, "lane_exec_count[4]")               \
  X(97, "lane_exec_count[5]")               \
  X(98, "lane_exec_count[6]")               \
  X(99, "lane_exec_count[7]")               \
  X(100, "ctrl_tx_bytes")                   \
  X(101, "ctrl_rx_bytes")                   \
  X(102, "ctrl_peers")                      \
  X(103, "ctrl_bypass_cycles")              \
  X(104, "codec_tx_bytes[none][allreduce]") \
  X(105, "codec_tx_bytes[none][allgather]") \
  X(106, "codec_tx_bytes[none][broadcast]") \
  X(107, "codec_tx_bytes[none][alltoall]") \
  X(108, "codec_tx_bytes[none][reducescatter]") \
  X(109, "codec_tx_bytes[none][join]")     \
  X(110, "codec_tx_bytes[none][barrier]")  \
  X(111, "codec_tx_bytes[bf16][allreduce]") \
  X(112, "codec_tx_bytes[bf16][allgather]") \
  X(113, "codec_tx_bytes[bf16][broadcast]") \
  X(114, "codec_tx_bytes[bf16][alltoall]") \
  X(115, "codec_tx_bytes[bf16][reducescatter]") \
  X(116, "codec_tx_bytes[bf16][join]")     \
  X(117, "codec_tx_bytes[bf16][barrier]")  \
  X(118, "codec_tx_bytes[int8][allreduce]") \
  X(119, "codec_tx_bytes[int8][allgather]") \
  X(120, "codec_tx_bytes[int8][broadcast]") \
  X(121, "codec_tx_bytes[int8][alltoall]") \
  X(122, "codec_tx_bytes[int8][reducescatter]") \
  X(123, "codec_tx_bytes[int8][join]")     \
  X(124, "codec_tx_bytes[int8][barrier]")  \
  X(125, "codec_tx_bytes[fp8][allreduce]") \
  X(126, "codec_tx_bytes[fp8][allgather]") \
  X(127, "codec_tx_bytes[fp8][broadcast]") \
  X(128, "codec_tx_bytes[fp8][alltoall]")  \
  X(129, "codec_tx_bytes[fp8][reducescatter]") \
  X(130, "codec_tx_bytes[fp8][join]")      \
  X(131, "codec_tx_bytes[fp8][barrier]")   \
  X(132, "ef_residual_bytes")              \
  X(133, "ef_residuals_dropped")           \
  X(134, "link_reconnects[ctrl]")          \
  X(135, "link_reconnects[data]")          \
  X(136, "frames_replayed")                \
  X(137, "replay_bytes")                   \
  X(138, "lane_pool_tasks")                \
  X(139, "lane_workers")                   \
  X(140, "lane_hol_ns[0]")                 \
  X(141, "lane_hol_ns[1]")                 \
  X(142, "lane_hol_ns[2]")                 \
  X(143, "lane_hol_ns[3]")                 \
  X(144, "lane_hol_ns[4]")                 \
  X(145, "lane_hol_ns[5]")                 \
  X(146, "lane_hol_ns[6]")                 \
  X(147, "lane_hol_ns[7]")                 \
  X(148, "lane_hol_count[0]")              \
  X(149, "lane_hol_count[1]")              \
  X(150, "lane_hol_count[2]")              \
  X(151, "lane_hol_count[3]")              \
  X(152, "lane_hol_count[4]")              \
  X(153, "lane_hol_count[5]")              \
  X(154, "lane_hol_count[6]")              \
  X(155, "lane_hol_count[7]")             \
  X(156, "link_backend")                  \
  X(157, "pump_syscalls")                 \
  X(158, "uring_sqes")                    \
  X(159, "uring_enters")                  \
  X(160, "uring_cqes")
