#include "codecs.h"

#include <algorithm>
#include <cfloat>
#include <cmath>
#include <cstring>
#include <string>

namespace hvt {

int WireCodecFromName(const char* name) {
  if (name == nullptr) return static_cast<int>(WireCodec::RAW);
  std::string s(name);
  if (s.empty() || s == "raw") return static_cast<int>(WireCodec::RAW);
#define HVT_CODEC_FROM_NAME(id, nm) \
  if (s == nm) return id;
  HVT_WIRE_CODECS(HVT_CODEC_FROM_NAME)
#undef HVT_CODEC_FROM_NAME
  return -1;
}

// ---- bf16 (migrated from ring_ops.cc, PR 3) --------------------------------

namespace {

class Bf16Codec final : public Codec {
 public:
  WireCodec id() const override { return WireCodec::BF16; }
  size_t CompressedSize(int64_t n) const override {
    return static_cast<size_t>(n) * 2;
  }
  size_t WireBlockBytes() const override { return 2; }
  int64_t BlockElems() const override { return 1; }
  // memcpy, not a reinterpret_cast walk: the codec stream sits at an
  // arbitrary byte offset inside a frame buffer (codec id byte, frame
  // headers), so 2-byte-aligned access is not guaranteed — a punned
  // uint16_t* load/store is UB there (fuzzer-found under UBSan).
  void Compress(uint8_t* dst, const float* src, int64_t n) const override {
    const float* __restrict s = src;
    for (int64_t i = 0; i < n; ++i) {
      uint16_t v = FloatToBf16(s[i]);
      memcpy(dst + 2 * i, &v, 2);
    }
  }
  void Decompress(float* dst, const uint8_t* src,
                  int64_t n) const override {
    float* __restrict d = dst;
    for (int64_t i = 0; i < n; ++i) {
      uint16_t v;
      memcpy(&v, src + 2 * i, 2);
      d[i] = Bf16ToFloat(v);
    }
  }
  void Roundtrip(float* dst, int64_t n) const override {
    float* __restrict d = dst;
    for (int64_t i = 0; i < n; ++i) d[i] = Bf16ToFloat(FloatToBf16(d[i]));
  }
};

// ---- block-scaled int8 -----------------------------------------------------
//
// Wire block = fp32 absmax-derived scale, then kCodecBlockElems int8
// codes: value = code * scale, code = rint(value / scale) in
// [-127, 127] (symmetric; -128 unused so the grid is sign-balanced and
// roundtrip is idempotent). A zero/absent block (all zeros) encodes
// scale 0. Non-finite inputs saturate to ±127 codes via the fp32
// clamp. 256 elems cost 4 + 256 wire bytes → 1024/260 ≈ 3.94x on the
// fp32 payload.

inline float BlockAbsMax(const float* s, int64_t m) {
  float amax = 0.f;
  for (int64_t i = 0; i < m; ++i) amax = std::max(amax, std::fabs(s[i]));
  return amax;
}

// Shared block framing for the scaled codecs (int8/fp8): the wire
// layout, tail-block rule, scale derivation, and the stack-buffer
// Roundtrip are written ONCE here; an Impl supplies only its code
// ceiling (the scale divisor) and the scalar encode/decode of
// value/scale. CRTP, not virtual hooks — the per-element calls sit in
// the hot loops.
template <class Impl>
class BlockCodec : public Codec {
 public:
  size_t CompressedSize(int64_t n) const override {
    int64_t full = n / kCodecBlockElems;
    int64_t rem = n % kCodecBlockElems;
    return static_cast<size_t>(full) * (4 + kCodecBlockElems) +
           (rem ? static_cast<size_t>(4 + rem) : 0);
  }
  size_t WireBlockBytes() const override { return 4 + kCodecBlockElems; }
  int64_t BlockElems() const override { return kCodecBlockElems; }
  void Compress(uint8_t* dst, const float* src, int64_t n) const override {
    for (int64_t base = 0; base < n; base += kCodecBlockElems) {
      const int64_t m = std::min(kCodecBlockElems, n - base);
      const float* __restrict s = src + base;
      float amax = BlockAbsMax(s, m);
      // an Inf element would make the scale Inf and every finite
      // neighbor decode as 0·inf = NaN; clamping the absmax keeps the
      // scale finite so non-finite inputs saturate to the code ceiling
      // (≈FLT_MAX/2 after decode) while their 255 block-mates stay ~0.
      // The /2 headroom keeps ceiling·(amax/ceiling) clear of overflow
      // when the scale division rounds up
      if (!std::isfinite(amax)) amax = FLT_MAX * 0.5f;
      float scale = amax > 0.f ? amax / Impl::kMaxCode : 0.f;
      memcpy(dst, &scale, 4);
      uint8_t* __restrict q = dst + 4;
      if (scale > 0.f) {
        const float inv = 1.f / scale;
        for (int64_t i = 0; i < m; ++i) q[i] = Impl::Encode(s[i] * inv);
      } else {
        memset(q, 0, static_cast<size_t>(m));
      }
      dst += 4 + m;
    }
  }
  void Decompress(float* dst, const uint8_t* src,
                  int64_t n) const override {
    for (int64_t base = 0; base < n; base += kCodecBlockElems) {
      const int64_t m = std::min(kCodecBlockElems, n - base);
      float scale;
      memcpy(&scale, src, 4);
      const uint8_t* __restrict q = src + 4;
      float* __restrict d = dst + base;
      for (int64_t i = 0; i < m; ++i) d[i] = Impl::Decode(q[i]) * scale;
      src += 4 + m;
    }
  }
  void Roundtrip(float* dst, int64_t n) const override {
    // compress+decompress through a stack block so the owner's values
    // are BY CONSTRUCTION what peers decode — no separately-maintained
    // quantization math to drift
    uint8_t wire[4 + kCodecBlockElems];
    for (int64_t base = 0; base < n; base += kCodecBlockElems) {
      const int64_t m = std::min(kCodecBlockElems, n - base);
      Compress(wire, dst + base, m);
      Decompress(dst + base, wire, m);
    }
  }
};

class Int8BlockCodec final : public BlockCodec<Int8BlockCodec> {
 public:
  static constexpr float kMaxCode = 127.f;
  WireCodec id() const override { return WireCodec::INT8_BLOCK; }
  static uint8_t Encode(float v) {
    v = std::max(-127.f, std::min(127.f, v));  // NaN lands on the rail
    return static_cast<uint8_t>(
        static_cast<int8_t>(std::lrintf(v)));
  }
  static float Decode(uint8_t b) {
    return static_cast<float>(static_cast<int8_t>(b));
  }
};

// ---- block-scaled fp8 (e4m3) -----------------------------------------------
//
// Same block layout as int8; codes are OCP e4m3 bytes (1-4-3, bias 7,
// max 448, no inf, 0x7f = NaN) of value / scale with
// scale = absmax / 448. Wider dynamic range inside a block than int8
// (~2^-9 .. 448 relative to the scale) at 3 mantissa bits — the trade
// gradient tensors with heavy-tailed blocks prefer.

inline float E4m3ToFloat(uint8_t b) {
  const float sign = (b & 0x80) ? -1.f : 1.f;
  const int exp = (b >> 3) & 0xF;
  const int man = b & 7;
  if (exp == 0xF && man == 7)  // NaN code; never emitted by Compress
    return sign * 448.f;
  float val;
  if (exp == 0)
    val = std::ldexp(static_cast<float>(man), -9);  // subnormal: m/8 · 2^-6
  else
    val = std::ldexp(1.0f + static_cast<float>(man) / 8.0f, exp - 7);
  return sign * val;
}

inline uint8_t FloatToE4m3(float v) {
  uint32_t bits;
  memcpy(&bits, &v, 4);
  const uint8_t sign = static_cast<uint8_t>((bits >> 24) & 0x80);
  float a = std::fabs(v);
  if (std::isnan(a)) return static_cast<uint8_t>(sign | 0x7E);  // sat, no NaN
  if (a >= 448.f) return static_cast<uint8_t>(sign | 0x7E);     // 448
  if (a < std::ldexp(1.0f, -10)) return sign;  // below half min subnormal
  int e;
  std::frexp(a, &e);
  e -= 1;  // a = g · 2^e, g ∈ [1, 2)
  if (e < -6) e = -6;  // subnormal range encodes with exp field 0
  const float step = std::ldexp(1.0f, e - 3);
  float q = std::nearbyint(a / step);  // round-to-nearest-even mantissa
  if (q >= 16.f) {
    q *= 0.5f;
    e += 1;
  }
  if (e > 8 || (e == 8 && q > 14.f))
    return static_cast<uint8_t>(sign | 0x7E);  // rounded past 448 → sat
  const int iq = static_cast<int>(q);
  if (iq < 8)  // subnormal (e == -6): exp field 0, mantissa iq
    return static_cast<uint8_t>(sign | iq);
  return static_cast<uint8_t>(sign | (((e + 7) << 3) | (iq - 8)));
}

class Fp8BlockCodec final : public BlockCodec<Fp8BlockCodec> {
 public:
  static constexpr float kMaxCode = 448.f;
  WireCodec id() const override { return WireCodec::FP8_BLOCK; }
  static uint8_t Encode(float v) { return FloatToE4m3(v); }
  static float Decode(uint8_t b) { return E4m3ToFloat(b); }
};

}  // namespace

const Codec* CodecFor(WireCodec id) {
  static const Bf16Codec bf16;
  static const Int8BlockCodec int8;
  static const Fp8BlockCodec fp8;
  switch (id) {
    case WireCodec::BF16:
      return &bf16;
    case WireCodec::INT8_BLOCK:
      return &int8;
    case WireCodec::FP8_BLOCK:
      return &fp8;
    default:
      return nullptr;  // RAW and unknown ids move raw bytes
  }
}

}  // namespace hvt
