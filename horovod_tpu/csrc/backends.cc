#include "backends.h"

#include <fcntl.h>
#include <sched.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <cstdio>
#include <cstring>
#include <map>
#include <stdexcept>

#include "logging.h"

namespace hvt {

Topology Topology::Build(int rank, const std::vector<std::string>& hosts) {
  Topology t;
  t.host_of_rank = hosts;
  // hosts in first-appearance order; ranks ascend within a host because we
  // scan by rank
  std::map<std::string, std::vector<int>> by_host;
  std::vector<std::string> order;
  for (int r = 0; r < static_cast<int>(hosts.size()); ++r) {
    auto& v = by_host[hosts[r]];
    if (v.empty()) order.push_back(hosts[r]);
    v.push_back(r);
  }
  t.n_hosts = static_cast<int>(order.size());
  const auto& mine = by_host[hosts[rank]];
  t.local_group = mine;
  t.my_local = GroupIndexOf(mine, rank);
  size_t local_size = mine.size();
  for (auto& h : order)
    t.homogeneous = t.homogeneous && by_host[h].size() == local_size;
  if (t.homogeneous) {
    for (auto& h : order)
      t.cross_group.push_back(by_host[h][t.my_local]);
    std::sort(t.cross_group.begin(), t.cross_group.end());
  }
  return t;
}

void CollectiveBackend::Allgatherv(const void*, int64_t,
                                   const std::vector<int64_t>&, int64_t,
                                   void*) {
  throw std::runtime_error(std::string("hvt backend '") + Name() +
                           "' does not implement allgather");
}

void CollectiveBackend::Broadcast(void*, int64_t, int) {
  throw std::runtime_error(std::string("hvt backend '") + Name() +
                           "' does not implement broadcast");
}

void CollectiveBackend::Alltoallv(const void*, const std::vector<int64_t>&,
                                  int64_t, void*,
                                  const std::vector<int64_t>&) {
  throw std::runtime_error(std::string("hvt backend '") + Name() +
                           "' does not implement alltoall");
}

void CollectiveBackend::AlltoallvMatrix(
    const void* in, const std::vector<int64_t>& rows_flat, int m,
    int64_t row_bytes, void* out, int my_pos) {
  std::vector<int64_t> send_rows(m, 0), recv_rows(m, 0);
  for (int d = 0; d < m; ++d)
    send_rows[d] = rows_flat[static_cast<size_t>(my_pos) * m + d];
  for (int s = 0; s < m; ++s)
    recv_rows[s] = rows_flat[static_cast<size_t>(s) * m + my_pos];
  Alltoallv(in, send_rows, row_bytes, out, recv_rows);
}

void CollectiveBackend::AllreduceGroup(void*, int64_t, DataType,
                                       ReduceKind,
                                       const std::vector<int>&, double,
                                       WirePair) {
  throw std::runtime_error(std::string("hvt backend '") + Name() +
                           "' does not implement subset allreduce");
}

void CollectiveBackend::AllgathervGroup(const void*, int64_t,
                                        const std::vector<int64_t>&,
                                        int64_t, void*,
                                        const std::vector<int>&) {
  throw std::runtime_error(std::string("hvt backend '") + Name() +
                           "' does not implement subset allgather");
}

void CollectiveBackend::BroadcastGroup(void*, int64_t, int,
                                       const std::vector<int>&) {
  throw std::runtime_error(std::string("hvt backend '") + Name() +
                           "' does not implement subset broadcast");
}

void CollectiveBackend::AlltoallvMatrixGroup(const void*,
                                             const std::vector<int64_t>&,
                                             int, int64_t, void*, int,
                                             const std::vector<int>&) {
  throw std::runtime_error(std::string("hvt backend '") + Name() +
                           "' does not implement subset alltoall");
}

void CollectiveBackend::ReduceScatter(void* buf, int64_t count,
                                      DataType dtype, ReduceKind red,
                                      int my_pos, int m,
                                      const std::vector<int>& group,
                                      bool full_world) {
  // default lowering: full allreduce; the caller slices chunk my_pos
  (void)my_pos;
  (void)m;
  if (full_world)
    Allreduce(buf, count, dtype, red, 1.0, WirePair{});
  else
    AllreduceGroup(buf, count, dtype, red, group, 1.0, WirePair{});
}

void RingBackend::Allreduce(void* buf, int64_t count, DataType dtype,
                            ReduceKind red, double postscale,
                            WirePair wire) {
  dp_->Allreduce(buf, count, dtype, red, postscale,
                 ResolveLinkCodec(topo_, wire, {}));
}

void RingBackend::Allgatherv(const void* in, int64_t my_rows,
                             const std::vector<int64_t>& rows,
                             int64_t row_bytes, void* out) {
  dp_->Allgatherv(in, my_rows, rows, row_bytes, out);
}

void RingBackend::Broadcast(void* buf, int64_t bytes, int root) {
  dp_->Broadcast(buf, bytes, root);
}

void RingBackend::Alltoallv(const void* in,
                            const std::vector<int64_t>& send_rows,
                            int64_t row_bytes, void* out,
                            const std::vector<int64_t>& recv_rows) {
  dp_->Alltoallv(in, send_rows, row_bytes, out, recv_rows);
}

void RingBackend::AllreduceGroup(void* buf, int64_t count, DataType dtype,
                                 ReduceKind red,
                                 const std::vector<int>& group,
                                 double postscale, WirePair wire) {
  dp_->AllreduceGroup(buf, count, dtype, red, group, postscale,
                      ResolveLinkCodec(topo_, wire, group));
}

void RingBackend::AllgathervGroup(const void* in, int64_t my_rows,
                                  const std::vector<int64_t>& rows,
                                  int64_t row_bytes, void* out,
                                  const std::vector<int>& group) {
  dp_->AllgathervGroup(in, my_rows, rows, row_bytes, out, group);
}

void RingBackend::BroadcastGroup(void* buf, int64_t bytes, int root,
                                 const std::vector<int>& group) {
  dp_->BroadcastGroup(buf, bytes, root, group);
}

void RingBackend::AlltoallvMatrixGroup(const void* in,
                                       const std::vector<int64_t>& rows_flat,
                                       int m, int64_t row_bytes, void* out,
                                       int my_pos,
                                       const std::vector<int>& group) {
  std::vector<int64_t> send_rows(m, 0), recv_rows(m, 0);
  for (int d = 0; d < m; ++d)
    send_rows[d] = rows_flat[static_cast<size_t>(my_pos) * m + d];
  for (int s = 0; s < m; ++s)
    recv_rows[s] = rows_flat[static_cast<size_t>(s) * m + my_pos];
  dp_->AlltoallvGroup(in, send_rows, row_bytes, out, recv_rows, group);
}

// ---------------------------------------------------------------- shm

namespace {
// One progress word per rank, one cache line each. A rank publishes
// (response_seq << 3 | phase) into ITS OWN word; barrier waiters compare
// co-members' words against that value. Values are strictly monotonic
// per writer (the engine's response sequence is a single global stream),
// so a non-member rank that skipped a response and ran ahead can never
// corrupt an in-flight group's barrier — its word only ever proves MORE
// progress, and nobody waits on non-members.
struct ShmProgress {
  std::atomic<uint64_t> v;
  uint8_t pad[56];
};
static_assert(sizeof(ShmProgress) == 64, "one cache line per rank");
}  // namespace

ShmLocalBackend::ShmLocalBackend(DataPlane* dp, int rank, int size,
                                 int shm_key, int64_t capacity,
                                 bool enabled)
    : rank_(rank), size_(size), capacity_(capacity) {
  // deterministic across ranks (env + topology), so every rank takes the
  // same branch here and the data-plane syncs below stay in lockstep
  if (!enabled || size < 2) return;
  char name[64];
  snprintf(name, sizeof(name), "/hvt_shm_%d", shm_key);
  hdr_bytes_ = sizeof(ShmProgress) * static_cast<size_t>(size_);
  map_bytes_ = hdr_bytes_ + static_cast<size_t>(capacity_) * (size_ + 1);
  world_group_.resize(size_);
  for (int i = 0; i < size_; ++i) world_group_[i] = i;
  try {
    int fd = -1;
    uint8_t sync = 0;
    if (rank_ == 0) {
      shm_unlink(name);  // stale segment from a crashed earlier job
      fd = shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
      if (fd >= 0 && ftruncate(fd, static_cast<off_t>(map_bytes_)) != 0) {
        close(fd);
        fd = -1;
      }
      dp->Broadcast(&sync, 1, 0);  // segment exists before peers open
    } else {
      dp->Broadcast(&sync, 1, 0);
      fd = shm_open(name, O_RDWR, 0600);
    }
    void* p = MAP_FAILED;
    if (fd >= 0) {
      p = mmap(nullptr, map_bytes_, PROT_READ | PROT_WRITE, MAP_SHARED,
               fd, 0);
      close(fd);
    }
    // consensus: the backend is on only if EVERY rank mapped — a split
    // decision would deadlock (some ranks in the shm barrier, others in
    // the ring). Runs on all ranks unconditionally.
    int32_t ok = p != MAP_FAILED ? 1 : 0;
    dp->Allreduce(&ok, 1, DataType::INT32, ReduceKind::MIN);
    if (rank_ == 0) shm_unlink(name);  // everyone open or given up
    if (p != MAP_FAILED && !ok) {
      munmap(p, map_bytes_);
      p = MAP_FAILED;
    }
    if (p == MAP_FAILED) return;
    base_ = static_cast<uint8_t*>(p);
    enabled_ = true;
    HVT_LOG(DEBUG, rank_) << "shm local data plane up (" << size_
                          << " ranks, " << (capacity_ >> 20)
                          << " MB slots)";
  } catch (const std::exception&) {
    // data-plane sync failed — leave disabled; the ring still works
  }
}

ShmLocalBackend::~ShmLocalBackend() {
  if (base_) munmap(base_, map_bytes_);
}

uint8_t* ShmLocalBackend::result() const { return base_ + hdr_bytes_; }

uint8_t* ShmLocalBackend::slot(int r) const {
  return base_ + hdr_bytes_ + static_cast<size_t>(capacity_) * (1 + r);
}

void ShmLocalBackend::BeginResponse(uint64_t seq) {
  seq_ = seq;
  phase_ = 0;
}

void ShmLocalBackend::Barrier(const std::vector<int>& group) {
  const uint64_t val = (seq_ << 3) | static_cast<uint64_t>(++phase_);
  auto* words = reinterpret_cast<ShmProgress*>(base_);
  words[rank_].v.store(val, std::memory_order_release);
  for (int g : group) {
    if (g == rank_) continue;
    // brief spin for the common in-step case, then sleep-wait with
    // exponential backoff: ranks skewed by compute must not burn a core
    // the computing rank needs (TCP recv would have slept in the
    // kernel). On an oversubscribed host (CI: 2 ranks, 1 core) a FIXED
    // short nap still wakes the waiter hundreds of times per phase,
    // stealing quanta and cache from the worker mid-memcpy — backoff to
    // 2 ms caps the steal at harmless while keeping in-step latency low.
    int spins = 0;
    long nap_ns = 20'000;  // 20 µs, doubling to 2 ms
    while (words[g].v.load(std::memory_order_acquire) < val) {
      if (++spins < 512) {
        sched_yield();
      } else {
        struct timespec nap = {0, nap_ns};
        nanosleep(&nap, nullptr);
        if (nap_ns < 2'000'000) nap_ns *= 2;
      }
    }
  }
}

void ShmLocalBackend::LogSubsetOnce(const std::vector<int>& group) {
  if (!subset_logged_) {
    subset_logged_ = true;
    HVT_LOG(DEBUG, rank_) << "shm subset collective engaged ("
                          << group.size() << " members)";
  }
}

bool ShmLocalBackend::Enabled(const Response& resp,
                              int64_t total_elems) const {
  if (!enabled_ || resp.kind != Response::Kind::TENSOR) return false;
  // subsets are served too (per-group barrier cells, direct slot reads);
  // members must be valid ranks of this single-host world
  const int m = resp.members.empty() ? size_
                                     : static_cast<int>(resp.members.size());
  if (!resp.members.empty()) {
    if (m < 2) return false;
    for (auto r : resp.members)
      if (r < 0 || r >= size_) return false;
  }
  const int64_t el = static_cast<int64_t>(DataTypeSize(resp.dtype));
  if (resp.op == OpType::ALLGATHER) {
    // every participant's contribution must fit its slot (uneven rows;
    // rows_flat indexed by group position)
    if (resp.rows_flat.size() < static_cast<size_t>(m) ||
        resp.trailing <= 0)
      return false;
    int64_t mx = 0;
    for (int r = 0; r < m; ++r)
      mx = std::max(mx, resp.rows_flat[r]);
    return mx * resp.trailing * el <= capacity_;
  }
  if (resp.op == OpType::ALLTOALL) {
    // every sender's full send buffer must fit its slot (m x m
    // position-major row matrix)
    if (resp.rows_flat.size() <
            static_cast<size_t>(m) * static_cast<size_t>(m) ||
        resp.trailing <= 0)
      return false;
    int64_t mx = 0;
    for (int s = 0; s < m; ++s) {
      int64_t tot = 0;
      for (int d = 0; d < m; ++d)
        tot += resp.rows_flat[static_cast<size_t>(s) * m + d];
      mx = std::max(mx, tot);
    }
    return mx * resp.trailing * el <= capacity_;
  }
  if (total_elems <= 0 || total_elems * el > capacity_) return false;
  if (resp.op == OpType::ALLREDUCE || resp.op == OpType::REDUCESCATTER)
    // reducescatter runs natively (chunk reduce straight from slots)
    return resp.reduce != ReduceKind::ADASUM;
  return resp.op == OpType::BROADCAST;
}

void ShmLocalBackend::Allreduce(void* buf, int64_t count, DataType dtype,
                                ReduceKind red, double postscale,
                                WirePair wire) {
  (void)wire;  // no wire bytes to compress on a shm plane
  if (!used_logged_) {
    used_logged_ = true;
    HVT_LOG(DEBUG, rank_) << "shm allreduce engaged (" << count
                          << " elems)";
  }
  const size_t el = DataTypeSize(dtype);
  const size_t bytes = static_cast<size_t>(count) * el;
  memcpy(slot(rank_), buf, bytes);
  Barrier(world_group_);  // all contributions visible
  // parallel reduce-scatter in memory: rank i combines chunk i of every
  // slot into the shared result area
  int64_t lo = count * rank_ / size_;
  int64_t hi = count * (rank_ + 1) / size_;
  if (hi > lo) {
    uint8_t* dst = result() + lo * el;
    memcpy(dst, slot(0) + lo * el, static_cast<size_t>(hi - lo) * el);
    for (int r = 1; r < size_; ++r)
      ReduceInto(dst, slot(r) + lo * el, hi - lo, dtype, red);
    // postscale folds into the chunked reduce: each rank scales only
    // its chunk of the shared result before publishing it
    if (postscale != 1.0) ScaleBuffer(dst, hi - lo, dtype, postscale);
  }
  Barrier(world_group_);  // result complete
  memcpy(buf, result(), bytes);
  Barrier(world_group_);  // everyone has read; slots/result reusable next op
}

void ShmLocalBackend::Allgatherv(const void* in, int64_t my_rows,
                                 const std::vector<int64_t>& rows,
                                 int64_t row_bytes, void* out) {
  if (!gather_logged_) {
    gather_logged_ = true;
    HVT_LOG(DEBUG, rank_) << "shm allgather engaged";
  }
  memcpy(slot(rank_), in, static_cast<size_t>(my_rows * row_bytes));
  Barrier(world_group_);  // all contributions visible
  auto* dst = static_cast<uint8_t*>(out);
  size_t off = 0;
  for (int r = 0; r < size_; ++r) {
    size_t nb = static_cast<size_t>(rows[r] * row_bytes);
    memcpy(dst + off, slot(r), nb);
    off += nb;
  }
  Barrier(world_group_);  // reads done; slots reusable by the next op
}

void ShmLocalBackend::AlltoallvMatrix(const void* in,
                                      const std::vector<int64_t>& rows_flat,
                                      int m, int64_t row_bytes, void* out,
                                      int my_pos) {
  (void)my_pos;  // full world only: position == rank
  if (!a2a_logged_) {
    a2a_logged_ = true;
    HVT_LOG(DEBUG, rank_) << "shm alltoall engaged";
  }
  A2aFromSlots(in, rows_flat, m, row_bytes, out, rank_, world_group_);
}

void ShmLocalBackend::Broadcast(void* buf, int64_t bytes, int root) {
  if (!bcast_logged_) {
    bcast_logged_ = true;
    HVT_LOG(DEBUG, rank_) << "shm broadcast engaged (" << bytes
                          << " bytes)";
  }
  // write-once-read-many: root publishes into the shared result area.
  // Result writes are always preceded by a barrier that confirmed the
  // previous op's readers are done (this op's trailing barrier plays
  // that role for the next one).
  if (rank_ == root) memcpy(result(), buf, static_cast<size_t>(bytes));
  Barrier(world_group_);
  if (rank_ != root) memcpy(buf, result(), static_cast<size_t>(bytes));
  Barrier(world_group_);
}

// ---- subset ops: per-group barrier cell (lowest member), direct peer
// slot reads, NO shared result area — disjoint groups run concurrently.

void ShmLocalBackend::AllreduceGroup(void* buf, int64_t count,
                                     DataType dtype, ReduceKind red,
                                     const std::vector<int>& group,
                                     double postscale, WirePair wire) {
  (void)wire;
  LogSubsetOnce(group);
  const size_t el = DataTypeSize(dtype);
  const size_t bytes = static_cast<size_t>(count) * el;
  memcpy(slot(rank_), buf, bytes);
  Barrier(group);  // all member contributions visible
  // every member reduces in the SAME slot order → bitwise-identical
  // results across the group
  memcpy(buf, slot(group[0]), bytes);
  for (size_t i = 1; i < group.size(); ++i)
    ReduceInto(buf, slot(group[i]), count, dtype, red);
  if (postscale != 1.0) ScaleBuffer(buf, count, dtype, postscale);
  Barrier(group);  // reads done; slots reusable
}

void ShmLocalBackend::BroadcastGroup(void* buf, int64_t bytes, int root,
                                     const std::vector<int>& group) {
  LogSubsetOnce(group);
  if (rank_ == root)
    memcpy(slot(rank_), buf, static_cast<size_t>(bytes));
  Barrier(group);
  if (rank_ != root)
    memcpy(buf, slot(root), static_cast<size_t>(bytes));
  Barrier(group);
}

void ShmLocalBackend::AllgathervGroup(const void* in, int64_t my_rows,
                                      const std::vector<int64_t>& rows,
                                      int64_t row_bytes, void* out,
                                      const std::vector<int>& group) {
  LogSubsetOnce(group);
  memcpy(slot(rank_), in, static_cast<size_t>(my_rows * row_bytes));
  Barrier(group);
  auto* dst = static_cast<uint8_t*>(out);
  size_t off = 0;
  for (size_t i = 0; i < group.size(); ++i) {
    size_t nb = static_cast<size_t>(rows[i] * row_bytes);
    memcpy(dst + off, slot(group[i]), nb);
    off += nb;
  }
  Barrier(group);
}

void ShmLocalBackend::AlltoallvMatrixGroup(
    const void* in, const std::vector<int64_t>& rows_flat, int m,
    int64_t row_bytes, void* out, int my_pos,
    const std::vector<int>& group) {
  LogSubsetOnce(group);
  A2aFromSlots(in, rows_flat, m, row_bytes, out, my_pos, group);
}

void ShmLocalBackend::A2aFromSlots(const void* in,
                                   const std::vector<int64_t>& rows_flat,
                                   int m, int64_t row_bytes, void* out,
                                   int my_pos,
                                   const std::vector<int>& group) {
  int64_t my_send = 0;
  for (int d = 0; d < m; ++d)
    my_send += rows_flat[static_cast<size_t>(my_pos) * m + d];
  memcpy(slot(rank_), in, static_cast<size_t>(my_send * row_bytes));
  Barrier(group);  // all send buffers visible
  auto* dst = static_cast<uint8_t*>(out);
  size_t off = 0;
  for (int s = 0; s < m; ++s) {
    // sender s's slot holds destinations in position order; my segment
    // starts after everything addressed to positions < mine
    int64_t pre = 0;
    for (int d = 0; d < my_pos; ++d)
      pre += rows_flat[static_cast<size_t>(s) * m + d];
    size_t nb = static_cast<size_t>(
        rows_flat[static_cast<size_t>(s) * m + my_pos] * row_bytes);
    memcpy(dst + off, slot(group[s]) + pre * row_bytes, nb);
    off += nb;
  }
  Barrier(group);  // reads done; slots reusable
}

void ShmLocalBackend::ReduceScatter(void* buf, int64_t count,
                                    DataType dtype, ReduceKind red,
                                    int my_pos, int m,
                                    const std::vector<int>& group,
                                    bool full_world) {
  // native chunk reduce: each participant combines ONLY its own chunk
  // straight from the member slots — reads count bytes/rank where the
  // allreduce lowering reads ~2x and writes the full result
  (void)full_world;  // group always lists every participant
  if (!rs_logged_) {
    rs_logged_ = true;
    HVT_LOG(DEBUG, rank_) << "shm reducescatter engaged (native chunk "
                          << "reduce, " << m << " participants)";
  }
  const size_t el = DataTypeSize(dtype);
  memcpy(slot(rank_), buf, static_cast<size_t>(count) * el);
  Barrier(group);  // all contributions visible
  const int64_t lo = count * my_pos / m;
  const int64_t hi = count * (my_pos + 1) / m;
  if (hi > lo) {
    uint8_t* dst = static_cast<uint8_t*>(buf) + lo * el;
    memcpy(dst, slot(group[0]) + lo * el,
           static_cast<size_t>(hi - lo) * el);
    for (int i = 1; i < m; ++i)
      ReduceInto(dst, slot(group[i]) + lo * el, hi - lo, dtype, red);
  }
  Barrier(group);  // reads done; slots reusable
}

bool HierarchicalBackend::Enabled(const Response& resp,
                                  int64_t total_elems) const {
  // reducescatter reaches this backend through the default
  // CollectiveBackend::ReduceScatter lowering (full allreduce; only the
  // shm backend overrides it with a native chunk reduce), so the
  // hierarchical decomposition serves it identically
  return enabled_ &&
         (resp.op == OpType::ALLREDUCE ||
          resp.op == OpType::REDUCESCATTER) &&
         resp.kind == Response::Kind::TENSOR && resp.members.empty() &&
         resp.reduce != ReduceKind::ADASUM && total_elems > 0;
}

void HierarchicalBackend::Allreduce(void* buf, int64_t count, DataType dtype,
                                    ReduceKind red, double postscale,
                                    WirePair wire) {
  // reference NCCLHierarchicalAllreduce decomposition
  // (nccl_operations.cc:188-350): local reduce-scatter, parallel
  // cross-host allreduce (one slice per local rank), local allgather.
  const int l = static_cast<int>(topo_.local_group.size());
  const size_t el = DataTypeSize(dtype);
  auto* bytes = static_cast<uint8_t*>(buf);
  std::vector<int64_t> seg_off(l + 1);
  for (int i = 0; i <= l; ++i) seg_off[i] = count * i / l;
  dp_->RingReduceScatter(bytes, seg_off, el, dtype, red, topo_.local_group,
                         wire.intra);
  // I now own fully-reduced (locally) segment (my_local+1) % l; my cross
  // peers (same local index on every host) own the SAME segment of their
  // hosts' local sums — allreduce it across hosts, all slices in parallel.
  // postscale + the INTER codec ride the cross-host phase: the slice
  // comes back scaled (and each rank's slice already codec-truncated
  // identically on every host), so the local allgather distributes
  // finished data. Only the cross phase crosses the network, which is
  // also where compressed wire bytes pay off — the intra codec
  // (default: none, full precision) covers only the in-host phases.
  const int own = (topo_.my_local + 1) % l;
  int64_t own_n = seg_off[own + 1] - seg_off[own];
  dp_->AllreduceGroup(bytes + seg_off[own] * el, own_n, dtype, red,
                      topo_.cross_group, postscale, wire.inter);
  if (dtype == DataType::FLOAT32)
    if (const Codec* c = CodecFor(wire.intra))
      // same owner-roundtrip invariant the flat ring maintains: the
      // finished slice must read exactly as local peers will decode it
      // off the compressed allgather, or ranks would diverge bitwise
      c->Roundtrip(reinterpret_cast<float*>(bytes + seg_off[own] * el),
                   own_n);
  dp_->RingAllgatherSegs(bytes, seg_off, el, topo_.local_group,
                         wire.intra);
}

}  // namespace hvt
