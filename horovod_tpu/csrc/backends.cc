#include "backends.h"

#include <algorithm>
#include <map>
#include <stdexcept>

namespace hvt {

Topology Topology::Build(int rank, const std::vector<std::string>& hosts) {
  Topology t;
  t.host_of_rank = hosts;
  // hosts in first-appearance order; ranks ascend within a host because we
  // scan by rank
  std::map<std::string, std::vector<int>> by_host;
  std::vector<std::string> order;
  for (int r = 0; r < static_cast<int>(hosts.size()); ++r) {
    auto& v = by_host[hosts[r]];
    if (v.empty()) order.push_back(hosts[r]);
    v.push_back(r);
  }
  t.n_hosts = static_cast<int>(order.size());
  const auto& mine = by_host[hosts[rank]];
  t.local_group = mine;
  t.my_local = GroupIndexOf(mine, rank);
  size_t local_size = mine.size();
  for (auto& h : order)
    t.homogeneous = t.homogeneous && by_host[h].size() == local_size;
  if (t.homogeneous) {
    for (auto& h : order)
      t.cross_group.push_back(by_host[h][t.my_local]);
    std::sort(t.cross_group.begin(), t.cross_group.end());
  }
  return t;
}

void CollectiveBackend::Allgatherv(const void*, int64_t,
                                   const std::vector<int64_t>&, int64_t,
                                   void*) {
  throw std::runtime_error(std::string("hvt backend '") + Name() +
                           "' does not implement allgather");
}

void CollectiveBackend::Broadcast(void*, int64_t, int) {
  throw std::runtime_error(std::string("hvt backend '") + Name() +
                           "' does not implement broadcast");
}

void CollectiveBackend::Alltoallv(const void*, const std::vector<int64_t>&,
                                  int64_t, void*,
                                  const std::vector<int64_t>&) {
  throw std::runtime_error(std::string("hvt backend '") + Name() +
                           "' does not implement alltoall");
}

void RingBackend::Allreduce(void* buf, int64_t count, DataType dtype,
                            ReduceKind red) {
  dp_->Allreduce(buf, count, dtype, red);
}

void RingBackend::Allgatherv(const void* in, int64_t my_rows,
                             const std::vector<int64_t>& rows,
                             int64_t row_bytes, void* out) {
  dp_->Allgatherv(in, my_rows, rows, row_bytes, out);
}

void RingBackend::Broadcast(void* buf, int64_t bytes, int root) {
  dp_->Broadcast(buf, bytes, root);
}

void RingBackend::Alltoallv(const void* in,
                            const std::vector<int64_t>& send_rows,
                            int64_t row_bytes, void* out,
                            const std::vector<int64_t>& recv_rows) {
  dp_->Alltoallv(in, send_rows, row_bytes, out, recv_rows);
}

bool HierarchicalBackend::Enabled(const Response& resp,
                                  int64_t total_elems) const {
  return enabled_ && resp.op == OpType::ALLREDUCE &&
         resp.kind == Response::Kind::TENSOR &&
         resp.reduce != ReduceKind::ADASUM && total_elems > 0;
}

void HierarchicalBackend::Allreduce(void* buf, int64_t count, DataType dtype,
                                    ReduceKind red) {
  // reference NCCLHierarchicalAllreduce decomposition
  // (nccl_operations.cc:188-350): local reduce-scatter, parallel
  // cross-host allreduce (one slice per local rank), local allgather.
  const int l = static_cast<int>(topo_.local_group.size());
  const size_t el = DataTypeSize(dtype);
  auto* bytes = static_cast<uint8_t*>(buf);
  std::vector<int64_t> seg_off(l + 1);
  for (int i = 0; i <= l; ++i) seg_off[i] = count * i / l;
  dp_->RingReduceScatter(bytes, seg_off, el, dtype, red, topo_.local_group);
  // I now own fully-reduced (locally) segment (my_local+1) % l; my cross
  // peers (same local index on every host) own the SAME segment of their
  // hosts' local sums — allreduce it across hosts, all slices in parallel.
  const int own = (topo_.my_local + 1) % l;
  int64_t own_n = seg_off[own + 1] - seg_off[own];
  dp_->AllreduceGroup(bytes + seg_off[own] * el, own_n, dtype, red,
                      topo_.cross_group);
  dp_->RingAllgatherSegs(bytes, seg_off, el, topo_.local_group);
}

}  // namespace hvt
