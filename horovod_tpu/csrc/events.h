// Engine flight recorder — a fixed-size lock-free event ring recording
// the per-tensor lifecycle from inside the engine (ENQUEUED on the
// submitting thread; NEGOTIATE / RANK_READY / FUSED / EXEC / DONE /
// CYCLE / STALL on the engine thread), drained over the C API
// (hvt_events_drain) by the Python timeline's drainer thread
// (horovod_tpu/utils/timeline.py) into per-rank chrome-trace shards.
//
// Unlike the EngineTimeline (timeline.h), which formats JSON and writes
// a file on rank 0 only, the ring is raw, always-on, and per-rank: the
// reference's stall inspector and timeline are post-hoc / coordinator
// surfaces, while pod-scale profiling work (arXiv:1909.09756) needs
// every rank's engine-thread view merged into one clock-aligned trace.
//
// Concurrency: multi-producer (engine thread + any submitting client
// thread), single consumer (the Python drainer; a mutex serializes
// accidental concurrent drains). Producers claim a slot with a relaxed
// fetch_add on the head cursor, write the payload, then publish the
// slot's sequence with a release store. The consumer validates the
// sequence before AND after copying the payload (per-slot seqlock), so
// a producer lapping the ring mid-copy yields a counted drop, never a
// torn record. Record() is wait-free; an idle ring costs nothing.
#pragma once

#include <atomic>
#include <chrono>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <string>

#include "thread_annotations.h"

namespace hvt {

// Wire ids — part of the C ABI (EVENT_KINDS in engine/native.py).
enum class EventKind : int32_t {
  ENQUEUED = 0,         // Submit() accepted the entry (client thread)
  NEGOTIATE_BEGIN = 1,  // first rank announced (coordinator)
  NEGOTIATE_END = 2,    // all required ranks announced (coordinator)
  RANK_READY = 3,       // rank `arg` announced (coordinator)
  FUSED = 4,            // executed as part of an `arg2`-tensor fused unit
  EXEC_BEGIN = 5,       // data-plane execution started (engine thread)
  EXEC_END = 6,         // data-plane execution finished
  DONE = 7,             // handle completed; arg = StatusType
  CYCLE = 8,            // a cycle that executed `arg` responses
  STALL = 9,            // stall inspector fired; arg = seconds waiting,
                        // arg2 = missing-rank bitmask (ranks < 64)
  WAKEUP = 10,          // event-driven cycle drained `arg` submissions;
                        // arg2 = submit→drain coalescing latency (µs)
  ABORT = 11,           // engine entered the sticky broken state;
                        // arg = abort cause (kAbortCauseNames index),
                        // name = truncated reason
  CTRL_BYTES = 12,      // control-plane frame bytes this cycle (incl.
                        // the 8-byte length prefixes): arg = sent,
                        // arg2 = received, op = the recording rank's
                        // CtrlRole (engine.h; root/leader/member — the
                        // tree's leader hop attributes separately).
                        // Recorded only on cycles that carried
                        // negotiation payload or executed responses —
                        // idle heartbeat cycles accumulate into the
                        // ctrl_tx/rx_bytes stats slots instead of
                        // flooding the ring.
  WIRE_BEGIN = 13,      // TCP data-plane duplex pump span begin (one per
                        // ring step / pairwise exchange): arg2 = bytes
                        // this pump will move (tx + rx), lane = LaneSlot
  WIRE_END = 14,        // matching end; arg2 = bytes moved
  RECONNECT = 15,       // a link healed (transport.h): name = "rank R"
                        // (the peer), op = LinkPlane (0 ctrl, 1 data),
                        // arg = dial retries used, arg2 = time spent in
                        // RECONNECTING (µs) — the stall the heal cost
  REPLAY = 16,          // frames/bytes re-sent after a reconnect:
                        // name/op as RECONNECT, arg = whole control
                        // frames replayed, arg2 = bytes replayed
  RECOVERY = 17,        // elastic recovery phase marker, recorded from
                        // Python via hvt_record_event (the engine is
                        // down for most of a recovery, so phases are
                        // stamped after re-init with their measured
                        // durations): name = phase ("restore",
                        // "rendezvous", "rebuild", ...), op = -1,
                        // arg = outcome (0 ok, 1 fallback, 2 failed),
                        // arg2 = phase duration (µs)
};

// POD view of one event — mirrored field-for-field by the ctypes
// Structure EngineEvent in engine/native.py. 96 bytes, naturally
// aligned; changing the layout is an ABI break.
struct EventView {
  int64_t ts_us;   // CLOCK_REALTIME microseconds (same epoch the Python
                   // timeline stamps with, so shards merge without a
                   // per-source offset)
  int64_t arg2;
  int32_t kind;
  int32_t op;      // OpType wire id, -1 when not applicable
  int32_t arg;
  int32_t lane;    // LaneSlot of the process set the event belongs to
                   // (0 = global lane; was padding before the lane
                   // field existed, so old .so's report 0 — the same
                   // value, since they predate per-set lanes)
  char name[64];   // tensor name, NUL-terminated, truncated to fit
};
static_assert(sizeof(EventView) == 96, "EventView is part of the C ABI");

class EventRing {
 public:
  static constexpr uint64_t kCapacity = 8192;  // power of two

  void Record(EventKind kind, const std::string& name, int32_t op,
              int32_t arg, int64_t arg2, int32_t lane = 0) {
    uint64_t idx = head_.fetch_add(1, std::memory_order_relaxed);
    Slot& s = slots_[idx & (kCapacity - 1)];
    // invalidate while writing so a concurrent reader can't accept a
    // half-written payload under the OLD (lapped) sequence
    s.seq.store(0, std::memory_order_release);
    s.view.ts_us = NowEpochUs();
    s.view.arg2 = arg2;
    s.view.kind = static_cast<int32_t>(kind);
    s.view.op = op;
    s.view.arg = arg;
    s.view.lane = lane;
    size_t n = name.size() < sizeof(s.view.name) - 1
                   ? name.size()
                   : sizeof(s.view.name) - 1;
    memcpy(s.view.name, name.data(), n);
    s.view.name[n] = '\0';
    s.seq.store(idx + 1, std::memory_order_release);
  }

  // Copies up to max_n published events into out, oldest first; returns
  // the number copied. Events overwritten before they were drained are
  // skipped and counted in dropped().
  int Drain(EventView* out, int max_n) EXCLUDES(drain_mu_) {
    MutexLock lk(drain_mu_);
    int n = 0;
    while (n < max_n) {
      uint64_t want = tail_ + 1;
      Slot& s = slots_[tail_ & (kCapacity - 1)];
      uint64_t seq = s.seq.load(std::memory_order_acquire);
      if (seq < want) {
        if (seq == 0 && head_.load(std::memory_order_relaxed) > tail_ &&
            head_.load(std::memory_order_relaxed) - tail_ > kCapacity) {
          // slot is mid-overwrite by a producer a full lap ahead
          SkipToWindow();
          continue;
        }
        break;  // caught up (or the next slot is still being written)
      }
      if (seq > want) {  // lapped: this slot now holds a newer event
        SkipToWindow();
        continue;
      }
      out[n] = s.view;
      // re-check: a producer may have lapped us mid-copy
      if (s.seq.load(std::memory_order_acquire) != want) {
        SkipToWindow();
        continue;
      }
      ++tail_;
      ++n;
    }
    return n;
  }

  int64_t dropped() const {
    return dropped_.load(std::memory_order_relaxed);
  }

  static int64_t NowEpochUs() {
    return std::chrono::duration_cast<std::chrono::microseconds>(
               std::chrono::system_clock::now().time_since_epoch())
        .count();
  }

 private:
  struct Slot {
    std::atomic<uint64_t> seq{0};
    EventView view{};
  };

  // Jump the read cursor to the oldest slot that can still be intact,
  // counting everything skipped as dropped.
  void SkipToWindow() REQUIRES(drain_mu_) {
    uint64_t head = head_.load(std::memory_order_relaxed);
    uint64_t oldest = head > kCapacity ? head - kCapacity : 0;
    // one extra slot of slack: the slot at `oldest` may be the one a
    // producer is overwriting right now
    ++oldest;
    if (oldest > tail_) {
      dropped_.fetch_add(static_cast<int64_t>(oldest - tail_),
                         std::memory_order_relaxed);
      tail_ = oldest;
    }
  }

  Slot slots_[kCapacity];
  std::atomic<uint64_t> head_{0};
  uint64_t tail_ GUARDED_BY(drain_mu_) = 0;
  std::atomic<int64_t> dropped_{0};
  Mutex drain_mu_;
};

}  // namespace hvt
