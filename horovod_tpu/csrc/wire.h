// Control-plane wire format — replaces the reference's FlatBuffers schema
// (horovod/common/wire/message.fbs, message.cc) with a dependency-free
// length-prefixed binary encoding. Requests announce per-rank tensor
// readiness; Responses carry the coordinator's fused execution order
// (reference message.h: Request:50, Response:152).
#pragma once

#include <algorithm>
#include <cstdint>
#include <map>
#include <set>
#include <stdexcept>
#include <string>
#include <vector>

#include "common.h"

namespace hvt {

// --------------------------------------------------------------------------
// Frame-flag registry — every control-frame flag bit is defined ONCE,
// here. The first byte of a worker→rank-0 frame is the kCtrlFlag* set;
// the first byte of a rank-0→worker frame is the kRespFlag* set; a
// frame whose first byte has kAbortFrameFlag set is an ABORT in EITHER
// direction (it replaces any expected frame, so both readers check it
// before parsing — engine.cc IsAbortFrame). A new flag must claim an
// unused bit in its direction AND must not collide with the abort bit;
// the cross-language lint (tools/hvt_lint.py) enforces both, plus that
// no other file re-defines these constants.
// --------------------------------------------------------------------------
constexpr uint8_t kCtrlFlagShutdown = 0x01;  // rank requests shutdown
constexpr uint8_t kCtrlFlagJoin = 0x02;      // rank has joined
constexpr uint8_t kCtrlFlagBitmask = 0x04;   // steady-state bypass: the
                                             // announce is a cache-
                                             // position bitmask vote,
                                             // not per-name payloads
constexpr uint8_t kCtrlFlagAggregate = 0x08; // hierarchical control
                                             // plane: one leader frame
                                             // batching a whole host's
                                             // announcements
constexpr uint8_t kRespFlagShutdown = 0x01;  // whole gang shut down
constexpr uint8_t kRespFlagPositions = 0x02; // steady-state bypass: the
                                             // response carries cache
                                             // POSITIONS; every rank
                                             // rebuilds the responses
                                             // from its own (identical)
                                             // cache
constexpr uint8_t kAbortFrameFlag = 0x80;    // frame is an ABORT
                                             // (origin rank + reason)

struct Request {
  int32_t rank = 0;
  OpType op = OpType::ALLREDUCE;
  ReduceKind reduce = ReduceKind::SUM;
  std::string name;
  DataType dtype = DataType::FLOAT32;
  TensorShape shape;
  int32_t root_rank = 0;
  double prescale = 1.0, postscale = 1.0;
  std::vector<int64_t> splits;
  // deterministic fusion group (reference group_table.h / Request group
  // semantics); -1 → ungrouped
  int32_t group_id = -1;
  int32_t group_size = 0;
  // process set (ascending global ranks; empty → global)
  std::vector<int64_t> members;
};

struct Response {
  enum class Kind : uint8_t { TENSOR = 0, ERROR = 1, JOIN = 2, BARRIER = 3 };
  Kind kind = Kind::TENSOR;
  OpType op = OpType::ALLREDUCE;
  std::vector<std::string> names;   // >1 → fused unit
  std::string error;
  // Execution params, carried so ranks without a local entry (joined
  // ranks) can build zero stand-ins (reference JoinOp,
  // collective_operations.h:259):
  DataType dtype = DataType::FLOAT32;
  ReduceKind reduce = ReduceKind::SUM;
  int32_t root = 0;                 // bcast root / last-joined rank (JOIN)
  double prescale = 1.0, postscale = 1.0;
  std::vector<int64_t> numels;      // per name
  // allgatherv: rows per (name, rank), flattened names-major;
  // alltoallv: full size x size split matrix, sender-major.
  std::vector<int64_t> rows_flat;
  // elements per row (product of trailing dims), set by the coordinator
  // for allgather/alltoall so joined ranks — which have no local entry to
  // read a shape from — still use the same transfer sizes as their peers.
  int64_t trailing = 1;
  // fusion-group id the member(s) came from; workers use it to skip the
  // response cache for grouped tensors (groups renegotiate as a unit)
  int32_t group_id = -1;
  // process set the collective runs over (empty → global); non-member
  // ranks skip the response entirely
  std::vector<int64_t> members;
  // per-link-class wire codecs for the data-plane transfer (WireCodec
  // wire ids), stamped by rank 0 so all participants compress/
  // decompress identically; 0 = raw bytes. Intra-host links (the
  // hierarchical backend's local phases, single-host rings) take
  // wire_intra; anything crossing hosts takes wire_inter — the EQuARX
  // "quantize only the DCN hops" split when the pair differs.
  uint8_t wire_intra = 0;
  uint8_t wire_inter = 0;
  // NOT on the wire: full per-name dims, populated by the coordinator's
  // BuildResponse / cache fast path for ITS OWN local execution.
  // Rank 0's response-cache copies must hold the true shapes — its
  // HitToArrival fold replays them as Requests, where a flattened
  // stand-in would fail BuildResponse's shape consistency check and
  // error out an innocent lane. Workers decode responses without this
  // field and fall back to flattened stand-ins, which is safe: only
  // the coordinator ever folds cache hits.
  std::vector<TensorShape> shapes;
};

class Writer {
 public:
  std::vector<uint8_t> buf;
  void u8(uint8_t v) { buf.push_back(v); }
  void i32(int32_t v) { append(&v, 4); }
  void i64(int64_t v) { append(&v, 8); }
  void f64(double v) { append(&v, 8); }
  void str(const std::string& s) {
    i32(static_cast<int32_t>(s.size()));
    append(s.data(), s.size());
  }
  void i64vec(const std::vector<int64_t>& v) {
    i32(static_cast<int32_t>(v.size()));
    for (auto x : v) i64(x);
  }

 private:
  void append(const void* p, size_t n) {
    auto* b = static_cast<const uint8_t*>(p);
    buf.insert(buf.end(), b, b + n);
  }
};

// Bounds-checked decoder. Control frames cross trust boundaries (a
// corrupt or truncated peer frame must land on the engine's
// containment-abort path, never on an out-of-bounds read), so every
// read validates against the remaining buffer and throws — the engine
// thread maps the exception to EnterBroken like any other protocol
// failure. NOTE: Reader holds a REFERENCE; never construct one from a
// temporary (`Reader rd(sock.RecvFrame())` dangles).
struct TruncatedFrameError : std::runtime_error {
  TruncatedFrameError()
      : std::runtime_error("hvt: truncated/corrupt control frame") {}
};

class Reader {
 public:
  explicit Reader(const std::vector<uint8_t>& b) : buf_(b) {}
  uint8_t u8() { need(1); return buf_[pos_++]; }
  int32_t i32() { int32_t v; copy(&v, 4); return v; }
  int64_t i64() { int64_t v; copy(&v, 8); return v; }
  double f64() { double v; copy(&v, 8); return v; }
  std::string str() {
    size_t n = count(1);
    std::string s(reinterpret_cast<const char*>(buf_.data() + pos_), n);
    pos_ += n;
    return s;
  }
  std::vector<int64_t> i64vec() {
    size_t n = count(8);
    std::vector<int64_t> v(n);
    for (auto& x : v) x = i64();
    return v;
  }
  // Element count for a list whose entries occupy at least
  // min_elem_bytes each — rejects negative and buffer-overrunning
  // counts BEFORE any allocation sized from wire data.
  size_t count(size_t min_elem_bytes) {
    int32_t n = i32();
    if (n < 0 ||
        static_cast<size_t>(n) > remaining() / (min_elem_bytes ? min_elem_bytes : 1))
      throw TruncatedFrameError();
    return static_cast<size_t>(n);
  }
  size_t remaining() const { return buf_.size() - pos_; }
  bool done() const { return pos_ >= buf_.size(); }

 private:
  void need(size_t n) const {
    if (remaining() < n) throw TruncatedFrameError();
  }
  void copy(void* p, size_t n) {
    need(n);
    memcpy(p, buf_.data() + pos_, n);
    pos_ += n;
  }
  const std::vector<uint8_t>& buf_;
  size_t pos_ = 0;
};

// Grammar-derived minimum encoded sizes (every variable-length field
// empty): the sum of the fixed-width writer calls in the matching
// Encode* body, counting 4 bytes for each length-prefixed str/i64vec.
// The proto pass in tools/hvt_lint.py re-derives these totals from the
// encoder bodies and fails lint when a field is added to an encoder
// without updating the paired Reader::count() bound below.
constexpr size_t kMinEncodedRequestBytes = 51;
constexpr size_t kMinEncodedResponseBytes = 58;

inline void EncodeRequest(Writer& w, const Request& r) {
  w.i32(r.rank);
  w.u8(static_cast<uint8_t>(r.op));
  w.u8(static_cast<uint8_t>(r.reduce));
  w.str(r.name);
  w.u8(static_cast<uint8_t>(r.dtype));
  w.i64vec(r.shape.dims);
  w.i32(r.root_rank);
  w.f64(r.prescale);
  w.f64(r.postscale);
  w.i64vec(r.splits);
  w.i32(r.group_id);
  w.i32(r.group_size);
  w.i64vec(r.members);
}

inline Request DecodeRequest(Reader& rd) {
  Request r;
  r.rank = rd.i32();
  r.op = static_cast<OpType>(rd.u8());
  r.reduce = static_cast<ReduceKind>(rd.u8());
  r.name = rd.str();
  r.dtype = static_cast<DataType>(rd.u8());
  r.shape.dims = rd.i64vec();
  r.root_rank = rd.i32();
  r.prescale = rd.f64();
  r.postscale = rd.f64();
  r.splits = rd.i64vec();
  r.group_id = rd.i32();
  r.group_size = rd.i32();
  r.members = rd.i64vec();
  return r;
}

inline void EncodeRequestList(Writer& w, const std::vector<Request>& rs) {
  w.i32(static_cast<int32_t>(rs.size()));
  for (auto& r : rs) EncodeRequest(w, r);
}

inline std::vector<Request> DecodeRequestList(Reader& rd) {
  // per-element bound = the exact empty-field encoded size of one
  // Request — rejects corrupt lengths before the allocation
  size_t n = rd.count(kMinEncodedRequestBytes);
  std::vector<Request> rs(n);
  for (auto& r : rs) r = DecodeRequest(rd);
  return rs;
}

inline void EncodeResponse(Writer& w, const Response& r) {
  w.u8(static_cast<uint8_t>(r.kind));
  w.u8(static_cast<uint8_t>(r.op));
  w.i32(static_cast<int32_t>(r.names.size()));
  for (auto& n : r.names) w.str(n);
  w.str(r.error);
  w.u8(static_cast<uint8_t>(r.dtype));
  w.u8(static_cast<uint8_t>(r.reduce));
  w.i32(r.root);
  w.f64(r.prescale);
  w.f64(r.postscale);
  w.i64vec(r.numels);
  w.i64vec(r.rows_flat);
  w.i64(r.trailing);
  w.i32(r.group_id);
  w.i64vec(r.members);
  w.u8(r.wire_intra);
  w.u8(r.wire_inter);
}

inline Response DecodeResponse(Reader& rd) {
  Response r;
  r.kind = static_cast<Response::Kind>(rd.u8());
  r.op = static_cast<OpType>(rd.u8());
  // each name is a length-prefixed str (>= 4 bytes); routing the count
  // through the bound rejects a negative/huge names count before the
  // resize can allocate from wire data
  size_t n = rd.count(4);
  r.names.resize(n);
  for (auto& s : r.names) s = rd.str();
  r.error = rd.str();
  r.dtype = static_cast<DataType>(rd.u8());
  r.reduce = static_cast<ReduceKind>(rd.u8());
  r.root = rd.i32();
  r.prescale = rd.f64();
  r.postscale = rd.f64();
  r.numels = rd.i64vec();
  r.rows_flat = rd.i64vec();
  r.trailing = rd.i64();
  r.group_id = rd.i32();
  r.members = rd.i64vec();
  r.wire_intra = rd.u8();
  r.wire_inter = rd.u8();
  return r;
}

inline void EncodeResponseList(Writer& w, const std::vector<Response>& rs) {
  w.i32(static_cast<int32_t>(rs.size()));
  for (auto& r : rs) EncodeResponse(w, r);
}

inline std::vector<Response> DecodeResponseList(Reader& rd) {
  // per-element bound pinned independently of DecodeRequestList: the
  // exact empty-field encoded size of one Response
  size_t n = rd.count(kMinEncodedResponseBytes);
  std::vector<Response> rs(n);
  for (auto& r : rs) r = DecodeResponse(rd);
  return rs;
}

// --------------------------------------------------------------------------
// per-rank announcement + the hierarchical / bypass codecs
// --------------------------------------------------------------------------
// One rank's per-cycle control-plane announcement, decoded from any of
// the three wire forms (plain, bitmask vote, leader aggregate). The
// coordinator consumes ONLY this struct, so star and tree mode share
// the negotiation core verbatim — which is what makes the two modes
// bit-identical by construction.
struct Announce {
  int32_t rank = 0;
  uint8_t flags = 0;                 // kCtrlFlagShutdown / kCtrlFlagJoin
  std::vector<int64_t> hits;         // cache positions announced as hits
  std::vector<int64_t> invalids;     // positions needing gang eviction
  std::vector<Request> reqs;         // cache misses (full requests)
};

// Hard cap on the bitmask vote width: cache positions are monotonic
// (never reused), so a pathologically churny job could grow the mask
// unboundedly — past this bound the announce falls back to the plain
// position-list form.
constexpr int64_t kCtrlBitmaskMaxPos = 1 << 20;

// Encode one rank's announce. The steady-state bypass form — a fixed
// width cache-position bitmask instead of per-name payloads — engages
// when the cycle is PURE cache hits (no misses, no invalidations, no
// join/shutdown flags): the dominant shape of a settled training or
// serving loop, where control bytes then stop scaling with tensor-name
// length entirely.
inline void EncodeAnnounceFrame(Writer& w, const Announce& a,
                                bool allow_bitmask) {
  int64_t max_pos = -1;
  for (auto p : a.hits) max_pos = p > max_pos ? p : max_pos;
  // the mask must actually be SMALLER than the plain position list:
  // positions are monotonic (never reused), so a long-lived job hitting
  // a few high-position tensors would otherwise pay a max_pos/8-byte
  // mask where the plain form costs 8 bytes per hit
  int64_t mask_bytes = max_pos / 8 + 1;
  bool bitmask = allow_bitmask && a.flags == 0 && !a.hits.empty() &&
                 a.invalids.empty() && a.reqs.empty() &&
                 max_pos < kCtrlBitmaskMaxPos &&
                 mask_bytes <=
                     static_cast<int64_t>(a.hits.size()) * 8 + 8;
  if (bitmask) {
    w.u8(kCtrlFlagBitmask);
    int32_t nbytes = static_cast<int32_t>(mask_bytes);
    w.i32(nbytes);
    size_t base = w.buf.size();
    w.buf.resize(base + static_cast<size_t>(nbytes), 0);
    for (auto p : a.hits)
      w.buf[base + static_cast<size_t>(p / 8)] |=
          static_cast<uint8_t>(1u << (p % 8));
    return;
  }
  w.u8(a.flags);
  w.i64vec(a.hits);
  w.i64vec(a.invalids);
  EncodeRequestList(w, a.reqs);
}

// Decode a plain or bitmask announce frame into the rank's Announce.
inline Announce DecodeAnnounceFrame(Reader& rd, int32_t rank) {
  Announce a;
  a.rank = rank;
  uint8_t first = rd.u8();
  if (first & kCtrlFlagBitmask) {
    a.flags = 0;  // bitmask form implies no join/shutdown this cycle
    size_t nbytes = rd.count(1);
    for (size_t i = 0; i < nbytes; ++i) {
      uint8_t byte = rd.u8();
      while (byte) {
        int bit = __builtin_ctz(byte);
        a.hits.push_back(static_cast<int64_t>(i) * 8 + bit);
        byte = static_cast<uint8_t>(byte & (byte - 1));
      }
    }
    return a;
  }
  a.flags = first;
  a.hits = rd.i64vec();
  a.invalids = rd.i64vec();
  a.reqs = DecodeRequestList(rd);
  return a;
}

// Leader aggregate (tree mode): one cross-host frame batching every
// announcement of the leader's subtree. Redundancy across co-located
// ranks is collapsed — a steady training step announces each tensor
// once per HOST instead of once per RANK:
//   * identical hit sets merge into one (ranks, positions) group;
//   * byte-identical requests (ignoring the announcing rank) merge
//     into one (request, ranks) group;
//   * invalidations are a deduplicated union (eviction broadcasts are
//     rank-agnostic);
//   * per-rank flags ride a full roster, because shutdown/join state
//     must track every covered rank every cycle (a roster gap would
//     freeze the rank's last flags at the coordinator).
inline void EncodeAggregateFrame(Writer& w,
                                 const std::vector<Announce>& anns) {
  w.u8(kCtrlFlagAggregate);
  w.i32(static_cast<int32_t>(anns.size()));
  for (auto& a : anns) {
    w.i32(a.rank);
    w.u8(a.flags);
  }
  // hit groups: identical (sorted) hit sets share one entry
  std::map<std::vector<int64_t>, std::vector<int64_t>> hit_groups;
  for (auto& a : anns) {
    if (a.hits.empty()) continue;
    std::vector<int64_t> key = a.hits;
    std::sort(key.begin(), key.end());
    hit_groups[std::move(key)].push_back(a.rank);
  }
  w.i32(static_cast<int32_t>(hit_groups.size()));
  for (auto& [positions, ranks] : hit_groups) {
    w.i64vec(ranks);
    w.i64vec(positions);
  }
  // invalidations: deduplicated union
  std::set<int64_t> invalids;
  for (auto& a : anns)
    invalids.insert(a.invalids.begin(), a.invalids.end());
  w.i64vec(std::vector<int64_t>(invalids.begin(), invalids.end()));
  // request groups: byte-identical requests (rank zeroed) share one
  // encoded body + the announcing-rank list
  std::map<std::vector<uint8_t>,
           std::pair<const Request*, std::vector<int64_t>>> req_groups;
  for (auto& a : anns)
    for (auto& q : a.reqs) {
      Writer kw;
      Request norm = q;
      norm.rank = -1;
      EncodeRequest(kw, norm);
      auto& group = req_groups[std::move(kw.buf)];
      if (group.first == nullptr) group.first = &q;
      group.second.push_back(a.rank);
    }
  w.i32(static_cast<int32_t>(req_groups.size()));
  for (auto& kv : req_groups) {
    EncodeRequest(w, *kv.second.first);
    w.i64vec(kv.second.second);
  }
}

// Expand an aggregate frame back into per-rank announcements (the
// Reader must be positioned AFTER the kCtrlFlagAggregate byte).
inline std::vector<Announce> DecodeAggregateFrame(Reader& rd) {
  size_t n = rd.count(5);  // roster entries are 5 bytes each
  std::vector<Announce> anns(n);
  std::map<int64_t, size_t> by_rank;
  for (size_t i = 0; i < n; ++i) {
    anns[i].rank = rd.i32();
    anns[i].flags = rd.u8();
    // a duplicated roster rank is a corrupt frame — route it onto the
    // containment path rather than applying one rank's flags twice
    if (!by_rank.emplace(anns[i].rank, i).second) throw TruncatedFrameError();
  }
  auto at = [&](int64_t r) -> Announce* {
    auto it = by_rank.find(r);
    return it == by_rank.end() ? nullptr : &anns[it->second];
  };
  size_t n_hits = rd.count(8);  // each group: two non-empty i64vecs
  for (size_t g = 0; g < n_hits; ++g) {
    auto ranks = rd.i64vec();
    auto positions = rd.i64vec();
    for (auto r : ranks)
      if (Announce* a = at(r))
        a->hits.insert(a->hits.end(), positions.begin(), positions.end());
  }
  auto invalids = rd.i64vec();
  if (!anns.empty())
    anns[0].invalids = std::move(invalids);  // rank-agnostic broadcast
  // each group: one full Request body + its announcing-rank i64vec
  size_t n_reqs = rd.count(kMinEncodedRequestBytes + 4);
  for (size_t g = 0; g < n_reqs; ++g) {
    Request proto = DecodeRequest(rd);
    auto ranks = rd.i64vec();
    for (auto r : ranks)
      if (Announce* a = at(r)) {
        Request q = proto;
        q.rank = static_cast<int32_t>(r);
        a->reqs.push_back(std::move(q));
      }
  }
  return anns;
}

}  // namespace hvt
