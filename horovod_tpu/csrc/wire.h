// Control-plane wire format — replaces the reference's FlatBuffers schema
// (horovod/common/wire/message.fbs, message.cc) with a dependency-free
// length-prefixed binary encoding. Requests announce per-rank tensor
// readiness; Responses carry the coordinator's fused execution order
// (reference message.h: Request:50, Response:152).
#pragma once

#include <cstdint>
#include <string>
#include <vector>

#include "common.h"

namespace hvt {

// --------------------------------------------------------------------------
// Frame-flag registry — every control-frame flag bit is defined ONCE,
// here. The first byte of a worker→rank-0 frame is the kCtrlFlag* set;
// the first byte of a rank-0→worker frame is the kRespFlag* set; a
// frame whose first byte has kAbortFrameFlag set is an ABORT in EITHER
// direction (it replaces any expected frame, so both readers check it
// before parsing — engine.cc IsAbortFrame). A new flag must claim an
// unused bit in its direction AND must not collide with the abort bit;
// the cross-language lint (tools/hvt_lint.py) enforces both, plus that
// no other file re-defines these constants.
// --------------------------------------------------------------------------
constexpr uint8_t kCtrlFlagShutdown = 0x01;  // rank requests shutdown
constexpr uint8_t kCtrlFlagJoin = 0x02;      // rank has joined
constexpr uint8_t kRespFlagShutdown = 0x01;  // whole gang shut down
constexpr uint8_t kAbortFrameFlag = 0x80;    // frame is an ABORT
                                             // (origin rank + reason)

struct Request {
  int32_t rank = 0;
  OpType op = OpType::ALLREDUCE;
  ReduceKind reduce = ReduceKind::SUM;
  std::string name;
  DataType dtype = DataType::FLOAT32;
  TensorShape shape;
  int32_t root_rank = 0;
  double prescale = 1.0, postscale = 1.0;
  std::vector<int64_t> splits;
  // deterministic fusion group (reference group_table.h / Request group
  // semantics); -1 → ungrouped
  int32_t group_id = -1;
  int32_t group_size = 0;
  // process set (ascending global ranks; empty → global)
  std::vector<int64_t> members;
};

struct Response {
  enum class Kind : uint8_t { TENSOR = 0, ERROR = 1, JOIN = 2, BARRIER = 3 };
  Kind kind = Kind::TENSOR;
  OpType op = OpType::ALLREDUCE;
  std::vector<std::string> names;   // >1 → fused unit
  std::string error;
  // Execution params, carried so ranks without a local entry (joined
  // ranks) can build zero stand-ins (reference JoinOp,
  // collective_operations.h:259):
  DataType dtype = DataType::FLOAT32;
  ReduceKind reduce = ReduceKind::SUM;
  int32_t root = 0;                 // bcast root / last-joined rank (JOIN)
  double prescale = 1.0, postscale = 1.0;
  std::vector<int64_t> numels;      // per name
  // allgatherv: rows per (name, rank), flattened names-major;
  // alltoallv: full size x size split matrix, sender-major.
  std::vector<int64_t> rows_flat;
  // elements per row (product of trailing dims), set by the coordinator
  // for allgather/alltoall so joined ranks — which have no local entry to
  // read a shape from — still use the same transfer sizes as their peers.
  int64_t trailing = 1;
  // fusion-group id the member(s) came from; workers use it to skip the
  // response cache for grouped tensors (groups renegotiate as a unit)
  int32_t group_id = -1;
  // process set the collective runs over (empty → global); non-member
  // ranks skip the response entirely
  std::vector<int64_t> members;
  // wire codec for the data-plane transfer (WireCodec wire id), stamped
  // by rank 0 so all participants compress/decompress identically;
  // 0 = raw bytes
  uint8_t wire = 0;
  // NOT on the wire: full per-name dims, populated by the coordinator's
  // BuildResponse / cache fast path for ITS OWN local execution.
  // Rank 0's response-cache copies must hold the true shapes — its
  // HitToArrival fold replays them as Requests, where a flattened
  // stand-in would fail BuildResponse's shape consistency check and
  // error out an innocent lane. Workers decode responses without this
  // field and fall back to flattened stand-ins, which is safe: only
  // the coordinator ever folds cache hits.
  std::vector<TensorShape> shapes;
};

class Writer {
 public:
  std::vector<uint8_t> buf;
  void u8(uint8_t v) { buf.push_back(v); }
  void i32(int32_t v) { append(&v, 4); }
  void i64(int64_t v) { append(&v, 8); }
  void f64(double v) { append(&v, 8); }
  void str(const std::string& s) {
    i32(static_cast<int32_t>(s.size()));
    append(s.data(), s.size());
  }
  void i64vec(const std::vector<int64_t>& v) {
    i32(static_cast<int32_t>(v.size()));
    for (auto x : v) i64(x);
  }

 private:
  void append(const void* p, size_t n) {
    auto* b = static_cast<const uint8_t*>(p);
    buf.insert(buf.end(), b, b + n);
  }
};

class Reader {
 public:
  explicit Reader(const std::vector<uint8_t>& b) : buf_(b) {}
  uint8_t u8() { return buf_[pos_++]; }
  int32_t i32() { int32_t v; copy(&v, 4); return v; }
  int64_t i64() { int64_t v; copy(&v, 8); return v; }
  double f64() { double v; copy(&v, 8); return v; }
  std::string str() {
    int32_t n = i32();
    std::string s(reinterpret_cast<const char*>(buf_.data() + pos_), n);
    pos_ += n;
    return s;
  }
  std::vector<int64_t> i64vec() {
    int32_t n = i32();
    std::vector<int64_t> v(n);
    for (auto& x : v) x = i64();
    return v;
  }
  bool done() const { return pos_ >= buf_.size(); }

 private:
  void copy(void* p, size_t n) {
    memcpy(p, buf_.data() + pos_, n);
    pos_ += n;
  }
  const std::vector<uint8_t>& buf_;
  size_t pos_ = 0;
};

inline void EncodeRequest(Writer& w, const Request& r) {
  w.i32(r.rank);
  w.u8(static_cast<uint8_t>(r.op));
  w.u8(static_cast<uint8_t>(r.reduce));
  w.str(r.name);
  w.u8(static_cast<uint8_t>(r.dtype));
  w.i64vec(r.shape.dims);
  w.i32(r.root_rank);
  w.f64(r.prescale);
  w.f64(r.postscale);
  w.i64vec(r.splits);
  w.i32(r.group_id);
  w.i32(r.group_size);
  w.i64vec(r.members);
}

inline Request DecodeRequest(Reader& rd) {
  Request r;
  r.rank = rd.i32();
  r.op = static_cast<OpType>(rd.u8());
  r.reduce = static_cast<ReduceKind>(rd.u8());
  r.name = rd.str();
  r.dtype = static_cast<DataType>(rd.u8());
  r.shape.dims = rd.i64vec();
  r.root_rank = rd.i32();
  r.prescale = rd.f64();
  r.postscale = rd.f64();
  r.splits = rd.i64vec();
  r.group_id = rd.i32();
  r.group_size = rd.i32();
  r.members = rd.i64vec();
  return r;
}

inline void EncodeRequestList(Writer& w, const std::vector<Request>& rs) {
  w.i32(static_cast<int32_t>(rs.size()));
  for (auto& r : rs) EncodeRequest(w, r);
}

inline std::vector<Request> DecodeRequestList(Reader& rd) {
  int32_t n = rd.i32();
  std::vector<Request> rs(n);
  for (auto& r : rs) r = DecodeRequest(rd);
  return rs;
}

inline void EncodeResponse(Writer& w, const Response& r) {
  w.u8(static_cast<uint8_t>(r.kind));
  w.u8(static_cast<uint8_t>(r.op));
  w.i32(static_cast<int32_t>(r.names.size()));
  for (auto& n : r.names) w.str(n);
  w.str(r.error);
  w.u8(static_cast<uint8_t>(r.dtype));
  w.u8(static_cast<uint8_t>(r.reduce));
  w.i32(r.root);
  w.f64(r.prescale);
  w.f64(r.postscale);
  w.i64vec(r.numels);
  w.i64vec(r.rows_flat);
  w.i64(r.trailing);
  w.i32(r.group_id);
  w.i64vec(r.members);
  w.u8(r.wire);
}

inline Response DecodeResponse(Reader& rd) {
  Response r;
  r.kind = static_cast<Response::Kind>(rd.u8());
  r.op = static_cast<OpType>(rd.u8());
  int32_t n = rd.i32();
  r.names.resize(n);
  for (auto& s : r.names) s = rd.str();
  r.error = rd.str();
  r.dtype = static_cast<DataType>(rd.u8());
  r.reduce = static_cast<ReduceKind>(rd.u8());
  r.root = rd.i32();
  r.prescale = rd.f64();
  r.postscale = rd.f64();
  r.numels = rd.i64vec();
  r.rows_flat = rd.i64vec();
  r.trailing = rd.i64();
  r.group_id = rd.i32();
  r.members = rd.i64vec();
  r.wire = rd.u8();
  return r;
}

inline void EncodeResponseList(Writer& w, const std::vector<Response>& rs) {
  w.i32(static_cast<int32_t>(rs.size()));
  for (auto& r : rs) EncodeResponse(w, r);
}

inline std::vector<Response> DecodeResponseList(Reader& rd) {
  int32_t n = rd.i32();
  std::vector<Response> rs(n);
  for (auto& r : rs) r = DecodeResponse(rd);
  return rs;
}

}  // namespace hvt
