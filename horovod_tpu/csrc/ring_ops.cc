#include "ring_ops.h"

#include <algorithm>
#include <cmath>
#include <cstring>
#include <stdexcept>

namespace hvt {

// ---- fp16 / bf16 widening helpers -----------------------------------------

static inline float HalfToFloat(uint16_t h) {
  uint32_t sign = (h & 0x8000u) << 16;
  uint32_t exp = (h >> 10) & 0x1f;
  uint32_t man = h & 0x3ffu;
  uint32_t f;
  if (exp == 0) {
    if (man == 0) {
      f = sign;
    } else {  // subnormal
      exp = 127 - 15 + 1;
      while ((man & 0x400u) == 0) {
        man <<= 1;
        exp--;
      }
      man &= 0x3ffu;
      f = sign | (exp << 23) | (man << 13);
    }
  } else if (exp == 0x1f) {
    f = sign | 0x7f800000u | (man << 13);
  } else {
    f = sign | ((exp + 127 - 15) << 23) | (man << 13);
  }
  float out;
  memcpy(&out, &f, 4);
  return out;
}

static inline uint16_t FloatToHalf(float v) {
  uint32_t f;
  memcpy(&f, &v, 4);
  uint32_t sign = (f >> 16) & 0x8000u;
  int32_t exp = static_cast<int32_t>((f >> 23) & 0xff) - 127 + 15;
  uint32_t man = f & 0x7fffffu;
  if (exp >= 0x1f) return static_cast<uint16_t>(sign | 0x7c00u);  // inf
  if (exp <= 0) {
    if (exp < -10) return static_cast<uint16_t>(sign);
    man |= 0x800000u;
    uint32_t shift = static_cast<uint32_t>(14 - exp);
    return static_cast<uint16_t>(sign | (man >> shift));
  }
  return static_cast<uint16_t>(sign | (exp << 10) | (man >> 13));
}

static inline float Bf16ToFloat(uint16_t h) {
  uint32_t f = static_cast<uint32_t>(h) << 16;
  float out;
  memcpy(&out, &f, 4);
  return out;
}

static inline uint16_t FloatToBf16(float v) {
  uint32_t f;
  memcpy(&f, &v, 4);
  // round-to-nearest-even
  uint32_t rounding = 0x7fffu + ((f >> 16) & 1);
  return static_cast<uint16_t>((f + rounding) >> 16);
}

// ---- elementwise reductions ------------------------------------------------

template <typename T>
static void ReduceTyped(T* dst, const T* src, int64_t n, ReduceKind red) {
  switch (red) {
    case ReduceKind::SUM:
    case ReduceKind::AVERAGE:  // averaged via postscale after the ring
    case ReduceKind::ADASUM:   // engine lowers adasum to scalar+sum phases
      for (int64_t i = 0; i < n; ++i) dst[i] = dst[i] + src[i];
      break;
    case ReduceKind::MIN:
      for (int64_t i = 0; i < n; ++i) dst[i] = std::min(dst[i], src[i]);
      break;
    case ReduceKind::MAX:
      for (int64_t i = 0; i < n; ++i) dst[i] = std::max(dst[i], src[i]);
      break;
    case ReduceKind::PRODUCT:
      for (int64_t i = 0; i < n; ++i) dst[i] = dst[i] * src[i];
      break;
  }
}

template <typename T, float (*ToF)(T), T (*FromF)(float)>
static void ReduceHalfTyped(T* dst, const T* src, int64_t n,
                            ReduceKind red) {
  for (int64_t i = 0; i < n; ++i) {
    float a = ToF(dst[i]), b = ToF(src[i]), r;
    switch (red) {
      case ReduceKind::MIN:
        r = std::min(a, b);
        break;
      case ReduceKind::MAX:
        r = std::max(a, b);
        break;
      case ReduceKind::PRODUCT:
        r = a * b;
        break;
      default:
        r = a + b;
        break;
    }
    dst[i] = FromF(r);
  }
}

void ReduceInto(void* dst, const void* src, int64_t count, DataType dtype,
                ReduceKind red) {
  switch (dtype) {
    case DataType::FLOAT32:
      ReduceTyped(static_cast<float*>(dst), static_cast<const float*>(src),
                  count, red);
      break;
    case DataType::FLOAT64:
      ReduceTyped(static_cast<double*>(dst),
                  static_cast<const double*>(src), count, red);
      break;
    case DataType::INT32:
      ReduceTyped(static_cast<int32_t*>(dst),
                  static_cast<const int32_t*>(src), count, red);
      break;
    case DataType::INT64:
      ReduceTyped(static_cast<int64_t*>(dst),
                  static_cast<const int64_t*>(src), count, red);
      break;
    case DataType::UINT8:
      ReduceTyped(static_cast<uint8_t*>(dst),
                  static_cast<const uint8_t*>(src), count, red);
      break;
    case DataType::INT8:
      ReduceTyped(static_cast<int8_t*>(dst),
                  static_cast<const int8_t*>(src), count, red);
      break;
    case DataType::BOOL: {
      auto* d = static_cast<uint8_t*>(dst);
      auto* s = static_cast<const uint8_t*>(src);
      // bool sum == logical or; product/min == and; max == or
      for (int64_t i = 0; i < count; ++i) {
        bool a = d[i], b = s[i];
        bool r = (red == ReduceKind::MIN || red == ReduceKind::PRODUCT)
                     ? (a && b)
                     : (a || b);
        d[i] = r ? 1 : 0;
      }
      break;
    }
    case DataType::FLOAT16:
      ReduceHalfTyped<uint16_t, HalfToFloat, FloatToHalf>(
          static_cast<uint16_t*>(dst), static_cast<const uint16_t*>(src),
          count, red);
      break;
    case DataType::BFLOAT16:
      ReduceHalfTyped<uint16_t, Bf16ToFloat, FloatToBf16>(
          static_cast<uint16_t*>(dst), static_cast<const uint16_t*>(src),
          count, red);
      break;
  }
}

void ScaleBuffer(void* dst, int64_t count, DataType dtype, double factor) {
  if (factor == 1.0) return;
  switch (dtype) {
    case DataType::FLOAT32: {
      auto* d = static_cast<float*>(dst);
      for (int64_t i = 0; i < count; ++i) d[i] *= static_cast<float>(factor);
      break;
    }
    case DataType::FLOAT64: {
      auto* d = static_cast<double*>(dst);
      for (int64_t i = 0; i < count; ++i) d[i] *= factor;
      break;
    }
    case DataType::FLOAT16: {
      auto* d = static_cast<uint16_t*>(dst);
      for (int64_t i = 0; i < count; ++i)
        d[i] = FloatToHalf(HalfToFloat(d[i]) * static_cast<float>(factor));
      break;
    }
    case DataType::BFLOAT16: {
      auto* d = static_cast<uint16_t*>(dst);
      for (int64_t i = 0; i < count; ++i)
        d[i] = FloatToBf16(Bf16ToFloat(d[i]) * static_cast<float>(factor));
      break;
    }
    case DataType::INT32: {
      auto* d = static_cast<int32_t*>(dst);
      for (int64_t i = 0; i < count; ++i)
        d[i] = static_cast<int32_t>(d[i] * factor);
      break;
    }
    case DataType::INT64: {
      auto* d = static_cast<int64_t*>(dst);
      for (int64_t i = 0; i < count; ++i)
        d[i] = static_cast<int64_t>(d[i] * factor);
      break;
    }
    default:
      throw std::runtime_error("hvt: scale unsupported for dtype");
  }
}

// ---- collectives -----------------------------------------------------------

void DataPlane::RingReduceScatter(uint8_t* bytes,
                                  const std::vector<int64_t>& seg_off,
                                  size_t el, DataType dtype, ReduceKind red,
                                  const std::vector<int>& group) {
  const int l = static_cast<int>(group.size());
  if (l == 1) return;
  const int idx = GroupIndexOf(group, rank_);
  const int next = group[(idx + 1) % l];
  const int prev = group[(idx + l - 1) % l];
  int64_t max_seg = 0;
  for (int i = 0; i < l; ++i)
    max_seg = std::max(max_seg, seg_off[i + 1] - seg_off[i]);
  scratch_.resize(static_cast<size_t>(max_seg) * el);

  // after l-1 steps, group index i owns fully-reduced segment (i+1) % l
  for (int step = 0; step < l - 1; ++step) {
    int send_seg = (idx - step + l) % l;
    int recv_seg = (idx - step - 1 + l) % l;
    int64_t send_n = seg_off[send_seg + 1] - seg_off[send_seg];
    int64_t recv_n = seg_off[recv_seg + 1] - seg_off[recv_seg];
    // full-duplex: send to next, recv from prev (index parity ordering
    // avoids head-of-line deadlock on blocking sockets for small frames)
    if (idx % 2 == 0) {
      peer(next).SendAll(bytes + seg_off[send_seg] * el,
                         static_cast<size_t>(send_n) * el);
      peer(prev).RecvAll(scratch_.data(), static_cast<size_t>(recv_n) * el);
    } else {
      peer(prev).RecvAll(scratch_.data(), static_cast<size_t>(recv_n) * el);
      peer(next).SendAll(bytes + seg_off[send_seg] * el,
                         static_cast<size_t>(send_n) * el);
    }
    ReduceInto(bytes + seg_off[recv_seg] * el, scratch_.data(), recv_n,
               dtype, red);
  }
}

void DataPlane::RingAllgatherSegs(uint8_t* bytes,
                                  const std::vector<int64_t>& seg_off,
                                  size_t el,
                                  const std::vector<int>& group) {
  const int l = static_cast<int>(group.size());
  if (l == 1) return;
  const int idx = GroupIndexOf(group, rank_);
  const int next = group[(idx + 1) % l];
  const int prev = group[(idx + l - 1) % l];
  for (int step = 0; step < l - 1; ++step) {
    int send_seg = (idx + 1 - step + l) % l;
    int recv_seg = (idx - step + l) % l;
    int64_t send_n = seg_off[send_seg + 1] - seg_off[send_seg];
    int64_t recv_n = seg_off[recv_seg + 1] - seg_off[recv_seg];
    if (idx % 2 == 0) {
      peer(next).SendAll(bytes + seg_off[send_seg] * el,
                         static_cast<size_t>(send_n) * el);
      peer(prev).RecvAll(bytes + seg_off[recv_seg] * el,
                         static_cast<size_t>(recv_n) * el);
    } else {
      peer(prev).RecvAll(bytes + seg_off[recv_seg] * el,
                         static_cast<size_t>(recv_n) * el);
      peer(next).SendAll(bytes + seg_off[send_seg] * el,
                         static_cast<size_t>(send_n) * el);
    }
  }
}

void DataPlane::AllreduceGroup(void* buf, int64_t count, DataType dtype,
                               ReduceKind red,
                               const std::vector<int>& group) {
  if (group.size() == 1 || count == 0) return;
  const size_t el = DataTypeSize(dtype);
  auto* bytes = static_cast<uint8_t*>(buf);
  const int l = static_cast<int>(group.size());
  // segment boundaries (element granularity)
  std::vector<int64_t> seg_off(l + 1);
  for (int i = 0; i <= l; ++i) seg_off[i] = count * i / l;
  RingReduceScatter(bytes, seg_off, el, dtype, red, group);
  RingAllgatherSegs(bytes, seg_off, el, group);
}

void DataPlane::Allreduce(void* buf, int64_t count, DataType dtype,
                          ReduceKind red) {
  if (size_ == 1 || count == 0) return;
  std::vector<int> all(size_);
  for (int i = 0; i < size_; ++i) all[i] = i;
  AllreduceGroup(buf, count, dtype, red, all);
}

void DataPlane::AllgathervGroup(const void* in, int64_t my_rows,
                                const std::vector<int64_t>& rows,
                                int64_t row_bytes, void* out,
                                const std::vector<int>& group) {
  const int m = static_cast<int>(group.size());
  const int idx = GroupIndexOf(group, rank_);
  auto* dst = static_cast<uint8_t*>(out);
  std::vector<int64_t> offs(m + 1, 0);
  for (int i = 0; i < m; ++i) offs[i + 1] = offs[i] + rows[i];
  // place own rows
  memcpy(dst + offs[idx] * row_bytes, in,
         static_cast<size_t>(my_rows) * row_bytes);
  if (m == 1) return;
  const int next = group[(idx + 1) % m];
  const int prev = group[(idx + m - 1) % m];
  // ring rotation: at step s, send the block originally from position
  // (idx - s) % m, receive the block from (idx - s - 1) % m
  for (int step = 0; step < m - 1; ++step) {
    int send_blk = (idx - step + m) % m;
    int recv_blk = (idx - step - 1 + m) % m;
    size_t send_bytes = static_cast<size_t>(rows[send_blk]) * row_bytes;
    size_t recv_bytes = static_cast<size_t>(rows[recv_blk]) * row_bytes;
    if (idx % 2 == 0) {
      peer(next).SendAll(dst + offs[send_blk] * row_bytes, send_bytes);
      peer(prev).RecvAll(dst + offs[recv_blk] * row_bytes, recv_bytes);
    } else {
      peer(prev).RecvAll(dst + offs[recv_blk] * row_bytes, recv_bytes);
      peer(next).SendAll(dst + offs[send_blk] * row_bytes, send_bytes);
    }
  }
}

void DataPlane::Allgatherv(const void* in, int64_t my_rows,
                           const std::vector<int64_t>& rows,
                           int64_t row_bytes, void* out) {
  std::vector<int> all(size_);
  for (int i = 0; i < size_; ++i) all[i] = i;
  AllgathervGroup(in, my_rows, rows, row_bytes, out, all);
}

void DataPlane::BroadcastGroup(void* buf, int64_t bytes, int root,
                               const std::vector<int>& group) {
  if (group.size() == 1 || bytes == 0) return;
  if (rank_ == root) {
    for (int r : group) {
      if (r == root) continue;
      peer(r).SendAll(buf, static_cast<size_t>(bytes));
    }
  } else {
    peer(root).RecvAll(buf, static_cast<size_t>(bytes));
  }
}

void DataPlane::Broadcast(void* buf, int64_t bytes, int root) {
  if (size_ == 1) return;
  std::vector<int> all(size_);
  for (int i = 0; i < size_; ++i) all[i] = i;
  BroadcastGroup(buf, bytes, root, all);
}

void DataPlane::AlltoallvGroup(const void* in,
                               const std::vector<int64_t>& send_rows,
                               int64_t row_bytes, void* out,
                               const std::vector<int64_t>& recv_rows,
                               const std::vector<int>& group) {
  const int m = static_cast<int>(group.size());
  const int idx = GroupIndexOf(group, rank_);
  auto* src = static_cast<const uint8_t*>(in);
  auto* dst = static_cast<uint8_t*>(out);
  std::vector<int64_t> soff(m + 1, 0), roff(m + 1, 0);
  for (int i = 0; i < m; ++i) {
    soff[i + 1] = soff[i] + send_rows[i];
    roff[i + 1] = roff[i] + recv_rows[i];
  }
  // self block
  memcpy(dst + roff[idx] * row_bytes, src + soff[idx] * row_bytes,
         static_cast<size_t>(send_rows[idx]) * row_bytes);
  // pairwise exchange, lower group position sends first
  for (int opos = 0; opos < m; ++opos) {
    if (opos == idx) continue;
    int other = group[opos];
    size_t sb = static_cast<size_t>(send_rows[opos]) * row_bytes;
    size_t rb = static_cast<size_t>(recv_rows[opos]) * row_bytes;
    if (idx < opos) {
      if (sb) peer(other).SendAll(src + soff[opos] * row_bytes, sb);
      if (rb) peer(other).RecvAll(dst + roff[opos] * row_bytes, rb);
    } else {
      if (rb) peer(other).RecvAll(dst + roff[opos] * row_bytes, rb);
      if (sb) peer(other).SendAll(src + soff[opos] * row_bytes, sb);
    }
  }
}

void DataPlane::Alltoallv(const void* in,
                          const std::vector<int64_t>& send_rows,
                          int64_t row_bytes, void* out,
                          const std::vector<int64_t>& recv_rows) {
  std::vector<int> all(size_);
  for (int i = 0; i < size_; ++i) all[i] = i;
  AlltoallvGroup(in, send_rows, row_bytes, out, recv_rows, all);
}

}  // namespace hvt
