#include "ring_ops.h"

#include <poll.h>

#include <algorithm>
#include <cmath>
#include <cstring>
#include <stdexcept>

namespace hvt {

// ---- fp16 / bf16 widening helpers -----------------------------------------

static inline float HalfToFloat(uint16_t h) {
  uint32_t sign = (h & 0x8000u) << 16;
  uint32_t exp = (h >> 10) & 0x1f;
  uint32_t man = h & 0x3ffu;
  uint32_t f;
  if (exp == 0) {
    if (man == 0) {
      f = sign;
    } else {  // subnormal
      exp = 127 - 15 + 1;
      while ((man & 0x400u) == 0) {
        man <<= 1;
        exp--;
      }
      man &= 0x3ffu;
      f = sign | (exp << 23) | (man << 13);
    }
  } else if (exp == 0x1f) {
    f = sign | 0x7f800000u | (man << 13);
  } else {
    f = sign | ((exp + 127 - 15) << 23) | (man << 13);
  }
  float out;
  memcpy(&out, &f, 4);
  return out;
}

static inline uint16_t FloatToHalf(float v) {
  uint32_t f;
  memcpy(&f, &v, 4);
  uint32_t sign = (f >> 16) & 0x8000u;
  int32_t exp = static_cast<int32_t>((f >> 23) & 0xff) - 127 + 15;
  uint32_t man = f & 0x7fffffu;
  if (exp >= 0x1f) return static_cast<uint16_t>(sign | 0x7c00u);  // inf
  if (exp <= 0) {
    if (exp < -10) return static_cast<uint16_t>(sign);
    man |= 0x800000u;
    uint32_t shift = static_cast<uint32_t>(14 - exp);
    return static_cast<uint16_t>(sign | (man >> shift));
  }
  return static_cast<uint16_t>(sign | (exp << 10) | (man >> 13));
}

// (bf16 scalar conversions live in codecs.h — shared with the wire
// codec registry, which migrated the PR 3 bf16 helpers.)

// dst (fp32) op= widen(src bf16) — the compressed-wire reduce step,
// fused so the widened chunk never needs its own scratch pass.
static void ReduceFromBf16(float* dst, const uint16_t* src, int64_t n,
                           ReduceKind red) {
  float* __restrict d = dst;
  const uint16_t* __restrict s = src;
  switch (red) {
    case ReduceKind::MIN:
      for (int64_t i = 0; i < n; ++i) d[i] = std::min(d[i], Bf16ToFloat(s[i]));
      break;
    case ReduceKind::MAX:
      for (int64_t i = 0; i < n; ++i) d[i] = std::max(d[i], Bf16ToFloat(s[i]));
      break;
    case ReduceKind::PRODUCT:
      for (int64_t i = 0; i < n; ++i) d[i] *= Bf16ToFloat(s[i]);
      break;
    default:  // SUM / AVERAGE / ADASUM phases
      for (int64_t i = 0; i < n; ++i) d[i] += Bf16ToFloat(s[i]);
      break;
  }
}

// dst (fp32) op= decode(src wire bytes) for any registry codec. bf16
// keeps its fused widen-reduce; block codecs decode into a staging
// vector (chunk-sized) then reduce — the staging pass is noise next to
// the 4x fewer socket bytes they exist to buy.
static void ReduceFromWire(const Codec& c, float* dst, const uint8_t* src,
                           int64_t n, ReduceKind red,
                           std::vector<float>& staging) {
  if (c.id() == WireCodec::BF16) {
    ReduceFromBf16(dst, reinterpret_cast<const uint16_t*>(src), n, red);
    return;
  }
  if (static_cast<int64_t>(staging.size()) < n)
    staging.resize(static_cast<size_t>(n));
  c.Decompress(staging.data(), src, n);
  ReduceInto(dst, staging.data(), n, DataType::FLOAT32, red);
}

// ---- elementwise reductions ------------------------------------------------

template <typename T>
static void ReduceTyped(T* dst, const T* src, int64_t n, ReduceKind red) {
  // restrict-qualified contiguous loops with the switch hoisted out —
  // each case body is a straight-line loop the compiler can vectorize
  T* __restrict d = dst;
  const T* __restrict s = src;
  switch (red) {
    case ReduceKind::SUM:
    case ReduceKind::AVERAGE:  // averaged via postscale after the ring
    case ReduceKind::ADASUM:   // engine lowers adasum to scalar+sum phases
      for (int64_t i = 0; i < n; ++i) d[i] = d[i] + s[i];
      break;
    case ReduceKind::MIN:
      for (int64_t i = 0; i < n; ++i) d[i] = std::min(d[i], s[i]);
      break;
    case ReduceKind::MAX:
      for (int64_t i = 0; i < n; ++i) d[i] = std::max(d[i], s[i]);
      break;
    case ReduceKind::PRODUCT:
      for (int64_t i = 0; i < n; ++i) d[i] = d[i] * s[i];
      break;
  }
}

// fp16/bf16: widen a block to fp32, reduce, narrow — block staging (vs
// per-scalar through float) keeps the convert and combine loops
// independently vectorizable and the working set in L1.
template <typename T, float (*ToF)(T), T (*FromF)(float)>
static void ReduceHalfTyped(T* dst, const T* src, int64_t n,
                            ReduceKind red) {
  constexpr int64_t kBlk = 128;
  float a[kBlk], b[kBlk];
  T* __restrict dd = dst;
  const T* __restrict ss = src;
  for (int64_t base = 0; base < n; base += kBlk) {
    const int64_t m = std::min(kBlk, n - base);
    for (int64_t i = 0; i < m; ++i) a[i] = ToF(dd[base + i]);
    for (int64_t i = 0; i < m; ++i) b[i] = ToF(ss[base + i]);
    switch (red) {
      case ReduceKind::MIN:
        for (int64_t i = 0; i < m; ++i) a[i] = std::min(a[i], b[i]);
        break;
      case ReduceKind::MAX:
        for (int64_t i = 0; i < m; ++i) a[i] = std::max(a[i], b[i]);
        break;
      case ReduceKind::PRODUCT:
        for (int64_t i = 0; i < m; ++i) a[i] *= b[i];
        break;
      default:
        for (int64_t i = 0; i < m; ++i) a[i] += b[i];
        break;
    }
    for (int64_t i = 0; i < m; ++i) dd[base + i] = FromF(a[i]);
  }
}

void ReduceInto(void* dst, const void* src, int64_t count, DataType dtype,
                ReduceKind red) {
  switch (dtype) {
    case DataType::FLOAT32:
      ReduceTyped(static_cast<float*>(dst), static_cast<const float*>(src),
                  count, red);
      break;
    case DataType::FLOAT64:
      ReduceTyped(static_cast<double*>(dst),
                  static_cast<const double*>(src), count, red);
      break;
    case DataType::INT32:
      ReduceTyped(static_cast<int32_t*>(dst),
                  static_cast<const int32_t*>(src), count, red);
      break;
    case DataType::INT64:
      ReduceTyped(static_cast<int64_t*>(dst),
                  static_cast<const int64_t*>(src), count, red);
      break;
    case DataType::UINT8:
      ReduceTyped(static_cast<uint8_t*>(dst),
                  static_cast<const uint8_t*>(src), count, red);
      break;
    case DataType::INT8:
      ReduceTyped(static_cast<int8_t*>(dst),
                  static_cast<const int8_t*>(src), count, red);
      break;
    case DataType::BOOL: {
      auto* __restrict d = static_cast<uint8_t*>(dst);
      auto* __restrict s = static_cast<const uint8_t*>(src);
      // bool sum == logical or; product/min == and; max == or
      if (red == ReduceKind::MIN || red == ReduceKind::PRODUCT) {
        for (int64_t i = 0; i < count; ++i) d[i] = (d[i] && s[i]) ? 1 : 0;
      } else {
        for (int64_t i = 0; i < count; ++i) d[i] = (d[i] || s[i]) ? 1 : 0;
      }
      break;
    }
    case DataType::FLOAT16:
      ReduceHalfTyped<uint16_t, HalfToFloat, FloatToHalf>(
          static_cast<uint16_t*>(dst), static_cast<const uint16_t*>(src),
          count, red);
      break;
    case DataType::BFLOAT16:
      ReduceHalfTyped<uint16_t, Bf16ToFloat, FloatToBf16>(
          static_cast<uint16_t*>(dst), static_cast<const uint16_t*>(src),
          count, red);
      break;
  }
}

void ScaleBuffer(void* dst, int64_t count, DataType dtype, double factor) {
  if (factor == 1.0) return;
  switch (dtype) {
    case DataType::FLOAT32: {
      auto* __restrict d = static_cast<float*>(dst);
      const float f = static_cast<float>(factor);
      for (int64_t i = 0; i < count; ++i) d[i] *= f;
      break;
    }
    case DataType::FLOAT64: {
      auto* __restrict d = static_cast<double*>(dst);
      for (int64_t i = 0; i < count; ++i) d[i] *= factor;
      break;
    }
    case DataType::FLOAT16: {
      auto* __restrict d = static_cast<uint16_t*>(dst);
      constexpr int64_t kBlk = 128;
      float a[kBlk];
      const float f = static_cast<float>(factor);
      for (int64_t base = 0; base < count; base += kBlk) {
        const int64_t m = std::min(kBlk, count - base);
        for (int64_t i = 0; i < m; ++i) a[i] = HalfToFloat(d[base + i]);
        for (int64_t i = 0; i < m; ++i) a[i] *= f;
        for (int64_t i = 0; i < m; ++i) d[base + i] = FloatToHalf(a[i]);
      }
      break;
    }
    case DataType::BFLOAT16: {
      auto* __restrict d = static_cast<uint16_t*>(dst);
      constexpr int64_t kBlk = 128;
      float a[kBlk];
      const float f = static_cast<float>(factor);
      for (int64_t base = 0; base < count; base += kBlk) {
        const int64_t m = std::min(kBlk, count - base);
        for (int64_t i = 0; i < m; ++i) a[i] = Bf16ToFloat(d[base + i]);
        for (int64_t i = 0; i < m; ++i) a[i] *= f;
        for (int64_t i = 0; i < m; ++i) d[base + i] = FloatToBf16(a[i]);
      }
      break;
    }
    case DataType::INT32: {
      // round, don't truncate: an integral allreduce averaged over N or
      // prescaled by a non-integral factor must not bias toward zero
      auto* __restrict d = static_cast<int32_t*>(dst);
      for (int64_t i = 0; i < count; ++i)
        d[i] = static_cast<int32_t>(std::llround(d[i] * factor));
      break;
    }
    case DataType::INT64: {
      auto* __restrict d = static_cast<int64_t*>(dst);
      for (int64_t i = 0; i < count; ++i)
        d[i] = static_cast<int64_t>(std::llround(d[i] * factor));
      break;
    }
    default:
      throw std::runtime_error("hvt: scale unsupported for dtype");
  }
}

// ---- transport pump --------------------------------------------------------

DataPlane::DataPlane(int rank, int size,
                     std::vector<std::unique_ptr<Transport>> peers)
    : rank_(rank), size_(size), peers_(std::move(peers)) {
  pipeline_ = EnvInt("HVT_RING_PIPELINE", 1) != 0;
  // 1 MB default: measured sweet spot on loopback gangs — small enough
  // to overlap reduce with transfer, large enough that poll/reduce
  // interleaving overhead stays negligible (see docs/performance.md)
  chunk_bytes_ = EnvInt("HVT_RING_CHUNK_BYTES", 1 << 20);
  if (chunk_bytes_ < 64) chunk_bytes_ = 64;
}

void DataPlane::Duplex(Transport& out, const uint8_t* send_buf,
                       size_t send_n, Transport& in, uint8_t* recv_buf,
                       size_t recv_n, size_t chunk_bytes, WireCodec codec,
                       const std::function<void(size_t, size_t)>& on_chunk) {
  size_t sent = 0, rcvd = 0, notified = 0;
  auto flush_chunks = [&] {
    while ((rcvd - notified >= chunk_bytes) ||
           (rcvd == recv_n && notified < recv_n)) {
      size_t len = std::min(chunk_bytes, recv_n - notified);
      if (on_chunk) on_chunk(notified, len);
      notified += len;
    }
  };
  // progress deadline (HVT_OP_TIMEOUT_MS): re-armed whenever bytes move
  // in either direction, so a genuinely slow transfer keeps going but a
  // wedged/dead peer trips OpTimeoutError within one deadline instead
  // of parking the engine thread in poll forever
  const int64_t timeout_ms = OpTimeoutMs();
  int64_t deadline = timeout_ms > 0 ? NowMs() + timeout_ms : -1;
  // wire-phase span: one per pump (= per ring step), so the timeline and
  // hvt_analyze can attribute execution time to the wire vs the reduce.
  // A pump that throws leaves the span unclosed — an aborted transfer is
  // exactly what an open WIRE span in a trace means.
  PlaneCtx& cx = Ctx();
  const int64_t wire_bytes = static_cast<int64_t>(send_n + recv_n);
  if (events_ && wire_bytes > 0)
    events_->Record(EventKind::WIRE_BEGIN, cx.wire_name, cx.stat_op, 0,
                    wire_bytes, cx.wire_lane);
  // Batched fast path (transport.h Transport::PumpDuplex — a no-op on
  // TcpLink, the one-enter-per-step ring pump on IoUringLink): moves
  // as much of the transfer as the backend can handle, firing the
  // chunk callback as receive completions land. Best-effort by
  // contract — whatever remains (including every session-layer event:
  // replay, heal, chaos cut, escalation) is finished by the generic
  // poll+Some() loop below, which is also the whole pump under the
  // tcp backend.
  out.PumpDuplex(in, send_buf, send_n, recv_buf, recv_n, chunk_bytes,
                 sent, rcvd, [&] { flush_chunks(); });
  // the pump ran its own progress deadline; re-arm ours fresh
  if (deadline >= 0) deadline = NowMs() + timeout_ms;
  // generic-loop syscall tally (poll + each nonblocking send/recv),
  // flushed into the caller-owned sink at the end — the tcp side of
  // the syscalls-per-op comparison (the io_uring side counts enters
  // in the hub's uring sinks instead)
  int64_t pump_syscalls = 0;
  while (sent < send_n || rcvd < recv_n) {
    // a link mid-reconnect reports fd < 0: drive its Some() op directly
    // (the call heals the link or escalates) instead of parking an
    // incomplete direction outside the poll set. A heal can take whole
    // seconds, so the progress deadline re-arms — the transfer itself
    // made none, but the link just proved the peer alive.
    if (sent < send_n && out.fd() < 0) {
      sent += out.SendSome(send_buf + sent, send_n - sent);
      ++pump_syscalls;
      if (deadline >= 0) deadline = NowMs() + timeout_ms;
    }
    if (rcvd < recv_n && in.fd() < 0) {
      rcvd += in.RecvSome(recv_buf + rcvd,
                          std::min(recv_n - rcvd, 2 * chunk_bytes));
      ++pump_syscalls;
      if (deadline >= 0) deadline = NowMs() + timeout_ms;
    }
    struct pollfd fds[2];
    // a COMPLETED direction is masked with fd = -1 (poll ignores
    // negative fds) — events = 0 would not suppress POLLERR/POLLHUP,
    // which nothing here consumes once the direction is done, and an
    // unconsumed error event would spin the loop
    fds[0].fd = sent < send_n ? out.fd() : -1;
    fds[0].events = POLLOUT;
    fds[0].revents = 0;
    fds[1].fd = rcvd < recv_n ? in.fd() : -1;
    fds[1].events = POLLIN;
    fds[1].revents = 0;
    int wait_ms = -1;
    if (deadline >= 0) {
      int64_t left = deadline - NowMs();
      if (left <= 0)
        throw OpTimeoutError(
            "hvt: data-plane transfer made no progress for " +
            std::to_string(timeout_ms) + " ms (HVT_OP_TIMEOUT_MS)");
      wait_ms = left > 1000 ? 1000 : static_cast<int>(left);
    }
    if (wait_ms < 0 || wait_ms > 200) wait_ms = 200;
    int prc = ::poll(fds, 2, wait_ms);
    ++pump_syscalls;
    if (prc < 0) {
      if (errno == EINTR) continue;
      throw PeerLostError("hvt: poll failed on data socket");
    }
    if (prc == 0) {
      // idle poll round: let the links service the engine's OTHER
      // broken connections (transport.h Transport::Idle) — a stalled
      // pump may be stalled exactly because a peer is waiting on a
      // reconnect only this thread can drive. One sweep covers the
      // whole hub (it excludes only the sweeping link, which the
      // pump's own fd<0 recovery handles).
      in.Idle();
      continue;
    }
    size_t before = sent + rcvd;
    int64_t gen_before = in.Generation() + out.Generation();
    // service BOTH socket directions before doing any reduce work: the
    // peer must never sit idle behind our compute. The recv is capped
    // per iteration so a fast sender cannot monopolize the loop either.
    if (rcvd < recv_n &&
        (fds[1].revents & (POLLIN | POLLERR | POLLHUP))) {
      size_t want = std::min(recv_n - rcvd, 2 * chunk_bytes);
      rcvd += in.RecvSome(recv_buf + rcvd, want);
      ++pump_syscalls;
    }
    if (sent < send_n &&
        (fds[0].revents & (POLLOUT | POLLERR | POLLHUP))) {
      sent += out.SendSome(send_buf + sent, send_n - sent);
      ++pump_syscalls;
    }
    // progress re-arms the deadline — and so does a heal that happened
    // INSIDE a Some() call (generation bump): the reconnect may have
    // consumed most of the budget, but it just proved the peer alive
    if (deadline >= 0 &&
        (sent + rcvd > before ||
         in.Generation() + out.Generation() != gen_before))
      deadline = NowMs() + timeout_ms;
    // reduce completed chunks last, overlapping the in-flight transfer
    // (the kernel keeps streaming into/out of the socket buffers while
    // this runs)
    flush_chunks();
  }
  flush_chunks();
  if (pump_sink_ && pump_syscalls)
    pump_sink_->fetch_add(pump_syscalls, std::memory_order_relaxed);
  if (events_ && wire_bytes > 0)
    events_->Record(EventKind::WIRE_END, cx.wire_name, cx.stat_op, 0,
                    wire_bytes, cx.wire_lane);
  CountTx(send_n, codec);
}

// ---- collectives -----------------------------------------------------------

void DataPlane::RingReduceScatter(uint8_t* bytes,
                                  const std::vector<int64_t>& seg_off,
                                  size_t el, DataType dtype, ReduceKind red,
                                  const std::vector<int>& group,
                                  WireCodec wire) {
  const int l = static_cast<int>(group.size());
  if (l == 1) return;
  const int idx = GroupIndexOf(group, rank_);
  const int next = group[(idx + 1) % l];
  const int prev = group[(idx + l - 1) % l];
  // codecs operate on fp32 payloads only; anything else moves raw
  const Codec* cdc = el == 4 ? CodecFor(wire) : nullptr;
  const WireCodec wid = cdc ? wire : WireCodec::RAW;
  auto wbytes = [&](int64_t n) {
    return cdc ? cdc->CompressedSize(n) : static_cast<size_t>(n) * el;
  };
  PlaneCtx& cx = Ctx();
  int64_t max_seg = 0;
  for (int i = 0; i < l; ++i)
    max_seg = std::max(max_seg, seg_off[i + 1] - seg_off[i]);
  cx.scratch.resize(wbytes(max_seg));
  if (cdc) cx.wire_send.resize(wbytes(max_seg));
  // chunk alignment: raw streams align to the element, codec streams to
  // the self-contained wire block (in-band scales) — either way a
  // completed chunk decodes and reduces in place
  const size_t align = cdc ? cdc->WireBlockBytes() : el;
  const size_t chunk = std::max<size_t>(
      align, (static_cast<size_t>(chunk_bytes_) / align) * align);

  // after l-1 steps, group index i owns fully-reduced segment (i+1) % l
  for (int step = 0; step < l - 1; ++step) {
    int send_seg = (idx - step + l) % l;
    int recv_seg = (idx - step - 1 + l) % l;
    int64_t send_n = seg_off[send_seg + 1] - seg_off[send_seg];
    int64_t recv_n = seg_off[recv_seg + 1] - seg_off[recv_seg];
    const size_t send_w = wbytes(send_n), recv_w = wbytes(recv_n);
    const uint8_t* sp = bytes + seg_off[send_seg] * el;
    if (cdc) {
      cdc->Compress(cx.wire_send.data(),
                    reinterpret_cast<const float*>(sp), send_n);
      sp = cx.wire_send.data();
    }
    uint8_t* dst_seg = bytes + seg_off[recv_seg] * el;
    auto reduce_chunk = [&](size_t off, size_t len) {
      if (cdc) {
        // off is block-aligned (chunk is a block multiple); the final
        // chunk may end mid-block only at the stream's end, where the
        // remaining element count closes the partial tail block
        int64_t e0 = CodecElemsBefore(*cdc, off);
        int64_t e1 = off + len >= recv_w
                         ? recv_n
                         : CodecElemsBefore(*cdc, off + len);
        ReduceFromWire(*cdc, reinterpret_cast<float*>(dst_seg) + e0,
                       cx.scratch.data() + off, e1 - e0, red, cx.decode);
      } else {
        ReduceInto(dst_seg + off, cx.scratch.data() + off,
                   static_cast<int64_t>(len / el), dtype, red);
      }
    };
    if (pipeline_) {
      Duplex(peer(next), sp, send_w, peer(prev), cx.scratch.data(),
             recv_w, chunk, wid, reduce_chunk);
    } else {
      // blocking baseline: full-duplex via index-parity ordering (avoids
      // head-of-line deadlock for frames below the socket buffer size)
      if (idx % 2 == 0) {
        SendCounted(peer(next), sp, send_w, wid);
        peer(prev).Recv(cx.scratch.data(), recv_w);
      } else {
        peer(prev).Recv(cx.scratch.data(), recv_w);
        SendCounted(peer(next), sp, send_w, wid);
      }
      if (recv_n > 0) reduce_chunk(0, recv_w);
    }
  }
}

void DataPlane::RingAllgatherSegs(uint8_t* bytes,
                                  const std::vector<int64_t>& seg_off,
                                  size_t el,
                                  const std::vector<int>& group,
                                  WireCodec wire) {
  const int l = static_cast<int>(group.size());
  if (l == 1) return;
  const int idx = GroupIndexOf(group, rank_);
  const int next = group[(idx + 1) % l];
  const int prev = group[(idx + l - 1) % l];
  const Codec* cdc = el == 4 ? CodecFor(wire) : nullptr;
  const WireCodec wid = cdc ? wire : WireCodec::RAW;
  auto wbytes = [&](int64_t n) {
    return cdc ? cdc->CompressedSize(n) : static_cast<size_t>(n) * el;
  };
  const size_t align = cdc ? cdc->WireBlockBytes() : el;
  const size_t chunk = std::max<size_t>(
      align, (static_cast<size_t>(chunk_bytes_) / align) * align);
  PlaneCtx& cx = Ctx();
  if (cdc) {
    int64_t max_seg = 0;
    for (int i = 0; i < l; ++i)
      max_seg = std::max(max_seg, seg_off[i + 1] - seg_off[i]);
    cx.wire_send.resize(wbytes(max_seg));
    cx.wire_recv.resize(wbytes(max_seg));
  }
  for (int step = 0; step < l - 1; ++step) {
    int send_seg = (idx + 1 - step + l) % l;
    int recv_seg = (idx - step + l) % l;
    int64_t send_n = seg_off[send_seg + 1] - seg_off[send_seg];
    int64_t recv_n = seg_off[recv_seg + 1] - seg_off[recv_seg];
    if (cdc) {
      // step 0 compresses the owned segment; later steps forward the
      // compressed form received last step (no recompression, and the
      // values stay identical at every hop)
      const size_t send_w = wbytes(send_n), recv_w = wbytes(recv_n);
      if (step == 0)
        cdc->Compress(
            cx.wire_send.data(),
            reinterpret_cast<const float*>(bytes + seg_off[send_seg] * el),
            send_n);
      float* dst = reinterpret_cast<float*>(bytes + seg_off[recv_seg] * el);
      auto widen_chunk = [&](size_t off, size_t len) {
        int64_t e0 = CodecElemsBefore(*cdc, off);
        int64_t e1 = off + len >= recv_w
                         ? recv_n
                         : CodecElemsBefore(*cdc, off + len);
        cdc->Decompress(dst + e0, cx.wire_recv.data() + off, e1 - e0);
      };
      if (pipeline_) {
        Duplex(peer(next), cx.wire_send.data(), send_w, peer(prev),
               cx.wire_recv.data(), recv_w, chunk, wid, widen_chunk);
      } else {
        if (idx % 2 == 0) {
          SendCounted(peer(next), cx.wire_send.data(), send_w, wid);
          peer(prev).Recv(cx.wire_recv.data(), recv_w);
        } else {
          peer(prev).Recv(cx.wire_recv.data(), recv_w);
          SendCounted(peer(next), cx.wire_send.data(), send_w, wid);
        }
        if (recv_n > 0) widen_chunk(0, recv_w);
      }
      std::swap(cx.wire_send, cx.wire_recv);
      continue;
    }
    if (pipeline_) {
      Duplex(peer(next), bytes + seg_off[send_seg] * el,
             static_cast<size_t>(send_n) * el, peer(prev),
             bytes + seg_off[recv_seg] * el,
             static_cast<size_t>(recv_n) * el, chunk, WireCodec::RAW,
             nullptr);
    } else if (idx % 2 == 0) {
      SendCounted(peer(next), bytes + seg_off[send_seg] * el,
                  static_cast<size_t>(send_n) * el, WireCodec::RAW);
      peer(prev).Recv(bytes + seg_off[recv_seg] * el,
                         static_cast<size_t>(recv_n) * el);
    } else {
      peer(prev).Recv(bytes + seg_off[recv_seg] * el,
                         static_cast<size_t>(recv_n) * el);
      SendCounted(peer(next), bytes + seg_off[send_seg] * el,
                  static_cast<size_t>(send_n) * el, WireCodec::RAW);
    }
  }
}

void DataPlane::AllreduceGroup(void* buf, int64_t count, DataType dtype,
                               ReduceKind red,
                               const std::vector<int>& group,
                               double postscale, WireCodec wire) {
  if (group.size() == 1 || count == 0) {
    if (postscale != 1.0) ScaleBuffer(buf, count, dtype, postscale);
    return;
  }
  const size_t el = DataTypeSize(dtype);
  auto* bytes = static_cast<uint8_t*>(buf);
  const int l = static_cast<int>(group.size());
  const Codec* cdc =
      dtype == DataType::FLOAT32 ? CodecFor(wire) : nullptr;
  const WireCodec wid = cdc ? wire : WireCodec::RAW;
  // segment boundaries (element granularity)
  std::vector<int64_t> seg_off(l + 1);
  for (int i = 0; i <= l; ++i) seg_off[i] = count * i / l;
  RingReduceScatter(bytes, seg_off, el, dtype, red, group, wid);
  // postscale folds into the allgather: each rank scales only the one
  // segment it owns fully-reduced, and the rotation distributes scaled
  // data — 1/l of the scalar work and no separate full-buffer sweep
  const int idx = GroupIndexOf(group, rank_);
  const int own = (idx + 1) % l;
  const int64_t own_n = seg_off[own + 1] - seg_off[own];
  if (postscale != 1.0)
    ScaleBuffer(bytes + seg_off[own] * el, own_n, dtype, postscale);
  if (cdc)
    // truncate the owned segment exactly as peers will decompress it, so
    // every rank's final buffer is bit-identical
    cdc->Roundtrip(reinterpret_cast<float*>(bytes + seg_off[own] * el),
                   own_n);
  RingAllgatherSegs(bytes, seg_off, el, group, wid);
}

void DataPlane::Allreduce(void* buf, int64_t count, DataType dtype,
                          ReduceKind red, double postscale, WireCodec wire) {
  if (size_ == 1 || count == 0) {
    if (postscale != 1.0) ScaleBuffer(buf, count, dtype, postscale);
    return;
  }
  std::vector<int> all(size_);
  for (int i = 0; i < size_; ++i) all[i] = i;
  AllreduceGroup(buf, count, dtype, red, all, postscale, wire);
}

void DataPlane::AllgathervGroup(const void* in, int64_t my_rows,
                                const std::vector<int64_t>& rows,
                                int64_t row_bytes, void* out,
                                const std::vector<int>& group) {
  const int m = static_cast<int>(group.size());
  const int idx = GroupIndexOf(group, rank_);
  auto* dst = static_cast<uint8_t*>(out);
  std::vector<int64_t> offs(m + 1, 0);
  for (int i = 0; i < m; ++i) offs[i + 1] = offs[i] + rows[i];
  // place own rows
  memcpy(dst + offs[idx] * row_bytes, in,
         static_cast<size_t>(my_rows) * row_bytes);
  if (m == 1) return;
  const int next = group[(idx + 1) % m];
  const int prev = group[(idx + m - 1) % m];
  const size_t chunk = static_cast<size_t>(chunk_bytes_);
  // ring rotation: at step s, send the block originally from position
  // (idx - s) % m, receive the block from (idx - s - 1) % m
  for (int step = 0; step < m - 1; ++step) {
    int send_blk = (idx - step + m) % m;
    int recv_blk = (idx - step - 1 + m) % m;
    size_t send_bytes = static_cast<size_t>(rows[send_blk]) * row_bytes;
    size_t recv_bytes = static_cast<size_t>(rows[recv_blk]) * row_bytes;
    if (pipeline_) {
      Duplex(peer(next), dst + offs[send_blk] * row_bytes, send_bytes,
             peer(prev), dst + offs[recv_blk] * row_bytes, recv_bytes,
             chunk, WireCodec::RAW, nullptr);
    } else if (idx % 2 == 0) {
      SendCounted(peer(next), dst + offs[send_blk] * row_bytes, send_bytes,
                  WireCodec::RAW);
      peer(prev).Recv(dst + offs[recv_blk] * row_bytes, recv_bytes);
    } else {
      peer(prev).Recv(dst + offs[recv_blk] * row_bytes, recv_bytes);
      SendCounted(peer(next), dst + offs[send_blk] * row_bytes, send_bytes,
                  WireCodec::RAW);
    }
  }
}

void DataPlane::Allgatherv(const void* in, int64_t my_rows,
                           const std::vector<int64_t>& rows,
                           int64_t row_bytes, void* out) {
  std::vector<int> all(size_);
  for (int i = 0; i < size_; ++i) all[i] = i;
  AllgathervGroup(in, my_rows, rows, row_bytes, out, all);
}

void DataPlane::BroadcastGroup(void* buf, int64_t bytes, int root,
                               const std::vector<int>& group) {
  if (group.size() == 1 || bytes == 0) return;
  if (rank_ == root) {
    for (int r : group) {
      if (r == root) continue;
      SendCounted(peer(r), buf, static_cast<size_t>(bytes),
                  WireCodec::RAW);
    }
  } else {
    peer(root).Recv(buf, static_cast<size_t>(bytes));
  }
}

void DataPlane::Broadcast(void* buf, int64_t bytes, int root) {
  if (size_ == 1) return;
  std::vector<int> all(size_);
  for (int i = 0; i < size_; ++i) all[i] = i;
  BroadcastGroup(buf, bytes, root, all);
}

void DataPlane::AlltoallvGroup(const void* in,
                               const std::vector<int64_t>& send_rows,
                               int64_t row_bytes, void* out,
                               const std::vector<int64_t>& recv_rows,
                               const std::vector<int>& group) {
  const int m = static_cast<int>(group.size());
  const int idx = GroupIndexOf(group, rank_);
  auto* src = static_cast<const uint8_t*>(in);
  auto* dst = static_cast<uint8_t*>(out);
  std::vector<int64_t> soff(m + 1, 0), roff(m + 1, 0);
  for (int i = 0; i < m; ++i) {
    soff[i + 1] = soff[i] + send_rows[i];
    roff[i + 1] = roff[i] + recv_rows[i];
  }
  // self block
  memcpy(dst + roff[idx] * row_bytes, src + soff[idx] * row_bytes,
         static_cast<size_t>(send_rows[idx]) * row_bytes);
  // pairwise exchange; the duplex pump moves both directions at once
  // (the legacy path orders by group position to avoid deadlock)
  for (int opos = 0; opos < m; ++opos) {
    if (opos == idx) continue;
    int other = group[opos];
    size_t sb = static_cast<size_t>(send_rows[opos]) * row_bytes;
    size_t rb = static_cast<size_t>(recv_rows[opos]) * row_bytes;
    if (pipeline_) {
      if (sb || rb)
        Duplex(peer(other), src + soff[opos] * row_bytes, sb, peer(other),
               dst + roff[opos] * row_bytes, rb,
               static_cast<size_t>(chunk_bytes_), WireCodec::RAW,
               nullptr);
    } else if (idx < opos) {
      if (sb) SendCounted(peer(other), src + soff[opos] * row_bytes, sb,
                          WireCodec::RAW);
      if (rb) peer(other).Recv(dst + roff[opos] * row_bytes, rb);
    } else {
      if (rb) peer(other).Recv(dst + roff[opos] * row_bytes, rb);
      if (sb) SendCounted(peer(other), src + soff[opos] * row_bytes, sb,
                          WireCodec::RAW);
    }
  }
}

void DataPlane::Alltoallv(const void* in,
                          const std::vector<int64_t>& send_rows,
                          int64_t row_bytes, void* out,
                          const std::vector<int64_t>& recv_rows) {
  std::vector<int> all(size_);
  for (int i = 0; i < size_; ++i) all[i] = i;
  AlltoallvGroup(in, send_rows, row_bytes, out, recv_rows, all);
}

}  // namespace hvt
