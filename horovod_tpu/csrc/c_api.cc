// extern "C" surface for the ctypes bridge (horovod_tpu/engine/native.py) —
// the counterpart of the reference's C API (horovod/common/operations.cc:
// 708-896 horovod_init/rank/size + per-framework enqueue entry points).
#include <algorithm>
#include <chrono>
#include <cstring>

#include "engine.h"
#include "stats_slots.h"
#include "uring_link.h"

using hvt::DataType;
using hvt::Engine;
using hvt::EntryPtr;
using hvt::OpType;
using hvt::ReduceKind;
using hvt::TensorTableEntry;

extern "C" {

// forward decl: the thread-local error buffer lives with the wait
// surface below; init failures land there too so hvt_error_message
// can explain a refused rendezvous (previously the status reason was
// silently dropped and callers saw an empty message)
static void set_last_error(const std::string& reason);

int hvt_init(int rank, int size, const char* master_addr, int master_port,
             int cycle_ms) {
  auto s = Engine::Get().Init(rank, size, master_addr ? master_addr : "",
                              master_port, cycle_ms);
  if (!s.ok()) {
    set_last_error(s.reason);
    return -1;
  }
  return 0;
}

void hvt_shutdown() { Engine::Get().Shutdown(); }

int hvt_initialized() { return Engine::Get().initialized() ? 1 : 0; }
int hvt_rank() { return Engine::Get().rank(); }
int hvt_size() { return Engine::Get().size(); }
int hvt_local_rank() { return Engine::Get().local_rank(); }
int hvt_local_size() { return Engine::Get().local_size(); }

// Returns handle >= 0, or -1 when the engine is not initialized.
int hvt_submit(const char* name, int op, int reduce, int dtype, int ndims,
               const long long* dims, const void* data, long long nbytes,
               int root_rank, double prescale, double postscale,
               int nsplits, const long long* splits, int group_id,
               int group_size, int n_members, const long long* members) {
  auto e = std::make_shared<TensorTableEntry>();
  e->name = name ? name : "";
  e->op = static_cast<OpType>(op);
  e->reduce = static_cast<ReduceKind>(reduce);
  e->dtype = static_cast<DataType>(dtype);
  for (int i = 0; i < ndims; ++i) e->shape.dims.push_back(dims[i]);
  e->root_rank = root_rank;
  e->prescale = prescale;
  e->postscale = postscale;
  if (data && nbytes > 0) {
    e->input.resize(static_cast<size_t>(nbytes));
    memcpy(e->input.data(), data, static_cast<size_t>(nbytes));
  }
  for (int i = 0; i < nsplits; ++i) e->splits.push_back(splits[i]);
  e->group_id = group_id;
  e->group_size = group_size;
  for (int i = 0; i < n_members; ++i) e->members.push_back(members[i]);
  return Engine::Get().Submit(std::move(e));
}

int hvt_poll(int handle) { return Engine::Get().Poll(handle) ? 1 : 0; }

// Blocks. Returns 0 on success; <0 on collective error (message readable
// via hvt_error_message into caller buffer).
static thread_local std::string g_last_error;
static thread_local hvt::HandleState g_last_state;

static void set_last_error(const std::string& reason) {
  g_last_error = reason;
}

int hvt_wait(int handle) {
  g_last_state = Engine::Get().Wait(handle);
  if (!g_last_state.status.ok()) {
    g_last_error = g_last_state.status.reason;
    return -static_cast<int>(g_last_state.status.type);
  }
  return 0;
}

// Deadline-bounded hvt_wait: 0 done-ok, <0 done-error (same codes as
// hvt_wait), 1 when the handle is still pending after timeout_ms (no
// result loaded — the collective keeps running; wait again or release).
int hvt_wait_timeout(int handle, long long timeout_ms) {
  hvt::HandleState st;
  if (!Engine::Get().WaitFor(handle, static_cast<int64_t>(timeout_ms),
                             st))
    return 1;
  g_last_state = std::move(st);
  if (!g_last_state.status.ok()) {
    g_last_error = g_last_state.status.reason;
    return -static_cast<int>(g_last_state.status.type);
  }
  return 0;
}

long long hvt_result_bytes(int handle) {
  (void)handle;
  return static_cast<long long>(g_last_state.output.size());
}

void hvt_result_read(int handle, void* dst, long long nbytes) {
  (void)handle;
  memcpy(dst, g_last_state.output.data(),
         static_cast<size_t>(nbytes) < g_last_state.output.size()
             ? static_cast<size_t>(nbytes)
             : g_last_state.output.size());
}

int hvt_result_recv_splits(int handle, long long* dst, int max_n) {
  (void)handle;
  int n = static_cast<int>(g_last_state.recv_splits.size());
  for (int i = 0; i < n && i < max_n; ++i)
    dst[i] = g_last_state.recv_splits[i];
  return n;
}

int hvt_join_result(int handle) {
  (void)handle;
  return g_last_state.join_result;
}

void hvt_release(int handle) { Engine::Get().Release(handle); }

int hvt_error_message(char* dst, int max_n) {
  int n = static_cast<int>(g_last_error.size());
  if (max_n > 0) {
    int k = n < max_n - 1 ? n : max_n - 1;
    memcpy(dst, g_last_error.data(), static_cast<size_t>(k));
    dst[k] = '\0';
  }
  return n;
}

// ---- autotune internals, exported for unit tests (the reference tests
// ---- GaussianProcessRegressor / BayesianOptimization the same way)

// Fit a GP on n d-dim points (row-major X) and predict nq query points.
int hvt_gp_fit_predict(const double* X, const double* y, int n, int d,
                       const double* Xq, int nq, double* mean_out,
                       double* var_out) {
  std::vector<std::vector<double>> xs(n, std::vector<double>(d));
  for (int i = 0; i < n; ++i)
    for (int j = 0; j < d; ++j) xs[i][j] = X[i * d + j];
  std::vector<double> ys(y, y + n);
  hvt::GaussianProcess gp;
  if (!gp.Fit(xs, ys)) return -1;
  for (int q = 0; q < nq; ++q) {
    std::vector<double> xq(Xq + q * d, Xq + (q + 1) * d);
    gp.Predict(xq, &mean_out[q], &var_out[q]);
  }
  return 0;
}

// Given observed samples, return the optimizer's next suggestion in
// [0,1]^d. Deterministic for a fixed sample set.
int hvt_bo_suggest(const double* X, const double* y, int n, int d,
                   double* out) {
  hvt::BayesianOptimizer bo(d);
  for (int i = 0; i < n; ++i) {
    std::vector<double> x(X + i * d, X + (i + 1) * d);
    bo.AddSample(x, y[i]);
  }
  auto s = bo.Suggest();
  for (int j = 0; j < d; ++j) out[j] = s[j];
  return 0;
}

// Data-plane collectives executed so far (one fused unit = one) — lets
// tests assert fusion/grouping behavior.
long long hvt_data_ops() {
  return static_cast<long long>(Engine::Get().data_ops());
}

// Current engine tuning state: [fusion_threshold, cycle_ms, samples,
// active]. For integration tests and introspection.
void hvt_autotune_state(long long* out4) {
  auto& e = Engine::Get();
  out4[0] = e.fusion_threshold();
  out4[1] = e.current_cycle_ms();
  out4[2] = e.autotune().samples();
  out4[3] = e.autotune().active() ? 1 : 0;
}

// Frame-synchronized tuned flags: bit0 = response cache enabled, bit1 =
// flat-ring preference. Identical across ranks at any frame boundary —
// tests allgather this to pin the broadcast.
int hvt_engine_flags() {
  auto& e = Engine::Get();
  return (e.cache_enabled() ? 1 : 0) | (e.prefer_flat() ? 2 : 0);
}

// Live engine stats block for the telemetry bridge
// (horovod_tpu/metrics; polled by common/basics.py:poll_engine_stats).
// The authoritative slot-by-slot manifest is csrc/stats_slots.h
// (append-only ABI, machine-checked by tools/hvt_lint.py); the summary
// below is a convenience copy. Fixed layout, in slots:
//   0 cycles                 4 cache_misses
//   1 tensors_submitted      5 fusion_bytes
//   2 tensors_coordinated    6 responses_fused (coordinator-side)
//   3 cache_hits             7 stall_events
//   8..14  exec_ns    per OpType (ALLREDUCE..BARRIER)
//   15..21 exec_count per OpType
//   22..28 wire_tx_bytes per OpType (TCP data-plane bytes sent)
//   29..35 wire_tx_compressed_bytes per OpType (subset sent compressed)
//   36..50 cycle-duration histogram buckets (≤ 1 µs · 4^i, last = +Inf)
//   51     cycle-duration sum (ns)        52 cycle-duration count
//   53..67 wakeup-latency histogram buckets (same bounds)
//   68     wakeup-latency sum (ns)        69 wakeup-latency count
//   70..74 aborts by cause (timeout, peer_lost, remote_abort,
//          heartbeat, internal) — hvt_engine_aborts_total{cause}
//   75     lanes_active (distinct process-set lanes seen since init)
//   76..83 lane_depth per lane bucket (gauge; bucket 0 = global lane)
//   84..91 lane_exec_ns per lane bucket
//   92..99 lane_exec_count per lane bucket
//   100    ctrl_tx_bytes (control-plane frame bytes sent, incl. prefixes)
//   101    ctrl_rx_bytes (control-plane frame bytes received)
//   102    ctrl_peers (direct control-plane peers this rank serves —
//          star rank 0: world-1; tree rank 0: one per host with a
//          leader, i.e. the host count, minus one when rank 0 has a
//          host to itself)
//   103    ctrl_bypass_cycles (cycles served by the steady-state
//          positions-form bypass instead of full response payloads)
//   104..131 codec_tx_bytes[codec][op]: TCP data-plane bytes sent per
//          (wire codec, OpType), codec-major (codecs.h registry order:
//          none/bf16/int8/fp8) — hvt_wire_tx_bytes_total{op,codec}
//   132    ef_residual_bytes (resident error-feedback residual bytes)
//   133    ef_residuals_dropped (residual buffers HVT_EF_MAX_BYTES
//          evicted or refused)
//   134..135 link_reconnects per LinkPlane (ctrl, data): transparent
//          self-healing reconnects — hvt_link_reconnects_total{plane}
//   136    frames_replayed (whole control frames re-sent after heals)
//   137    replay_bytes (replay-ring bytes re-sent after heals)
//   138    lane_pool_tasks (responses executed on a lane-pool worker)
//   139    lane_workers (configured HVT_LANE_WORKERS; 0 = pool off)
//   140..147 lane_hol_ns per lane bucket (submit → engine-queue
//          pickup head-of-line wait — hvt_lane_hol_seconds_total)
//   148..155 lane_hol_count per lane bucket
//   156    link_backend (info gauge: resolved HVT_LINK_BACKEND —
//          0 = tcp, 1 = io_uring — hvt_link_backend)
//   157    pump_syscalls (generic duplex-pump poll/send/recv syscalls)
//   158    uring_sqes (io_uring SQEs submitted by the batched pump)
//   159    uring_enters (io_uring_enter syscalls, incl. spin flushes)
//   160    uring_cqes (io_uring completions reaped)
// Returns the number of slots the engine knows about; fills at most
// max_n. Callers sizing the buffer off the return value stay compatible
// with a newer .so that appends fields.
constexpr int kStatsScalars = 8;  // the slot-0..7 scalar block
// scalar slots APPENDED after the structured groups (native.py
// STATS_TAIL_SCALARS — the append-only escape hatch for new plain
// counters)
constexpr int kStatsTailScalars = 4;
// error-feedback scalars appended after the per-codec byte block
constexpr int kStatsEfScalars = 2;
// self-healing link telemetry appended after the EF scalars: one
// reconnect counter per LinkPlane, then the replay scalars
constexpr int kStatsLinkPlanes = 2;
constexpr int kStatsRecoveryScalars = 2;
// per-lane execution pool scalars appended after the recovery block:
// lane_pool_tasks (counter) + lane_workers (gauge)
constexpr int kStatsLanePoolScalars = 2;
// per-lane head-of-line telemetry appended after the pool scalars:
// lane_hol_ns + lane_hol_count, kLaneSlots each (the in-rank
// response-ready → exec-start wait the lane pool removes)
constexpr int kStatsLaneHolGroups = 2;
// transport-backend scalars appended after the lane-hol block:
// link_backend info gauge + the per-backend pump syscall/SQE counters
// (slots 156-160)
constexpr int kStatsUringScalars = 5;
static_assert(kStatsLinkPlanes == hvt::kLinkPlanes,
              "transport.h kLinkPlanes drifted from the stats layout");
constexpr int kStatsHist = hvt::kLatBuckets + 1 + 2;  // buckets+sum+count
constexpr int kStatsSlotCount = kStatsScalars + 4 * hvt::kStatsOps +
                                2 * kStatsHist + hvt::kAbortCauses +
                                1 + 3 * hvt::kLaneSlots +
                                kStatsTailScalars +
                                hvt::kWireCodecCount * hvt::kStatsOps +
                                kStatsEfScalars + kStatsLinkPlanes +
                                kStatsRecoveryScalars +
                                kStatsLanePoolScalars +
                                kStatsLaneHolGroups * hvt::kLaneSlots +
                                kStatsUringScalars;
static_assert(kStatsSlotCount == HVT_STATS_SLOT_COUNT,
              "hvt_engine_stats layout drifted from stats_slots.h — the "
              "slot ABI is append-only: add new slots to the end of the "
              "manifest and bump HVT_STATS_SLOT_COUNT (see "
              "docs/development.md)");

int hvt_engine_stats(long long* out, int max_n) {
  auto& eng = Engine::Get();
  const auto& s = eng.stats();
  long long v[kStatsSlotCount] = {
      s.cycles.load(std::memory_order_relaxed),
      s.tensors_submitted.load(std::memory_order_relaxed),
      s.tensors_coordinated.load(std::memory_order_relaxed),
      s.cache_hits.load(std::memory_order_relaxed),
      s.cache_misses.load(std::memory_order_relaxed),
      s.fusion_bytes.load(std::memory_order_relaxed),
      s.responses_fused.load(std::memory_order_relaxed),
      s.stall_events.load(std::memory_order_relaxed),
  };
  for (int i = 0; i < hvt::kStatsOps; ++i) {
    v[kStatsScalars + i] = s.exec_ns[i].load(std::memory_order_relaxed);
    v[kStatsScalars + hvt::kStatsOps + i] =
        s.exec_count[i].load(std::memory_order_relaxed);
    v[kStatsScalars + 2 * hvt::kStatsOps + i] = eng.wire_tx_bytes(i);
    v[kStatsScalars + 3 * hvt::kStatsOps + i] =
        eng.wire_tx_comp_bytes(i);
  }
  int base = kStatsScalars + 4 * hvt::kStatsOps;
  for (const hvt::LatencyHist* h : {&s.cycle_hist, &s.wakeup_hist}) {
    for (int i = 0; i <= hvt::kLatBuckets; ++i)
      v[base++] = h->buckets[i].load(std::memory_order_relaxed);
    v[base++] = h->sum_ns.load(std::memory_order_relaxed);
    v[base++] = h->count.load(std::memory_order_relaxed);
  }
  for (int i = 0; i < hvt::kAbortCauses; ++i)
    v[base++] = s.aborts[i].load(std::memory_order_relaxed);
  v[base++] = s.lanes_active.load(std::memory_order_relaxed);
  for (int i = 0; i < hvt::kLaneSlots; ++i)
    v[base++] = s.lane_depth[i].load(std::memory_order_relaxed);
  for (int i = 0; i < hvt::kLaneSlots; ++i)
    v[base++] = s.lane_exec_ns[i].load(std::memory_order_relaxed);
  for (int i = 0; i < hvt::kLaneSlots; ++i)
    v[base++] = s.lane_exec_count[i].load(std::memory_order_relaxed);
  v[base++] = s.ctrl_tx_bytes.load(std::memory_order_relaxed);
  v[base++] = s.ctrl_rx_bytes.load(std::memory_order_relaxed);
  v[base++] = s.ctrl_peers.load(std::memory_order_relaxed);
  v[base++] = s.ctrl_bypass_cycles.load(std::memory_order_relaxed);
  for (int i = 0; i < hvt::kWireCodecCount * hvt::kStatsOps; ++i)
    v[base++] = s.codec_tx_bytes[i].load(std::memory_order_relaxed);
  v[base++] = s.ef_residual_bytes.load(std::memory_order_relaxed);
  v[base++] = s.ef_residuals_dropped.load(std::memory_order_relaxed);
  for (int i = 0; i < hvt::kLinkPlanes; ++i)
    v[base++] = s.link_reconnects[i].load(std::memory_order_relaxed);
  v[base++] = s.frames_replayed.load(std::memory_order_relaxed);
  v[base++] = s.replay_bytes.load(std::memory_order_relaxed);
  v[base++] = s.lane_pool_tasks.load(std::memory_order_relaxed);
  v[base++] = s.lane_workers.load(std::memory_order_relaxed);
  for (int i = 0; i < hvt::kLaneSlots; ++i)
    v[base++] = s.lane_hol_ns[i].load(std::memory_order_relaxed);
  for (int i = 0; i < hvt::kLaneSlots; ++i)
    v[base++] = s.lane_hol_count[i].load(std::memory_order_relaxed);
  v[base++] = s.link_backend.load(std::memory_order_relaxed);
  v[base++] = s.pump_syscalls.load(std::memory_order_relaxed);
  v[base++] = s.uring_sqes.load(std::memory_order_relaxed);
  v[base++] = s.uring_enters.load(std::memory_order_relaxed);
  v[base++] = s.uring_cqes.load(std::memory_order_relaxed);
  for (int i = 0; i < kStatsSlotCount && i < max_n; ++i) out[i] = v[i];
  return kStatsSlotCount;
}

// Current wire-codec pair of this rank's engine, packed as
// intra | inter << 8 (WireCodec wire ids, codecs.h registry), with bit
// 16 set while HVT_WIRE_COMPRESSION=auto is active. Rank 0's values
// govern the gang via per-response {intra, inter} stamps; under auto
// the packed ids reflect rank 0's latest tuner picks.
int hvt_wire_compression() { return Engine::Get().wire_mode(); }

// Roundtrip `count` fp32 elements in place through wire codec id
// `codec` (decode(encode(x)) — exactly what segment owners and the
// error-feedback pass apply). Unit-test surface for the block-scaled
// codecs: chunk/block-boundary numerics and EF math without spinning
// up a gang. Returns 0; -1 for raw/unknown ids (nothing to do).
int hvt_codec_roundtrip(void* data, long long count, int codec) {
  const hvt::Codec* c =
      hvt::CodecFor(static_cast<hvt::WireCodec>(codec));
  if (c == nullptr) return -1;
  c->Roundtrip(static_cast<float*>(data), static_cast<int64_t>(count));
  return 0;
}

// Wire bytes codec id `codec` spends on `count` fp32 elements (raw:
// 4 * count) — pins the exact-byte-counter math the codec sweep and
// the data-plane tests assert against.
long long hvt_codec_wire_bytes(long long count, int codec) {
  const hvt::Codec* c =
      hvt::CodecFor(static_cast<hvt::WireCodec>(codec));
  if (c == nullptr) return 4 * count;
  return static_cast<long long>(c->CompressedSize(count));
}

// Sticky broken state (coordinated abort landed): returns 1 and fills
// dst with "<cause>: <reason>" (NUL-terminated, truncated to max_n)
// when broken, 0 when healthy. Submits fail fast while broken; recover
// with hvt_shutdown + a fresh hvt_init.
int hvt_engine_broken(char* dst, int max_n) {
  auto& eng = Engine::Get();
  if (!eng.broken()) {
    if (dst && max_n > 0) dst[0] = '\0';
    return 0;
  }
  std::string s = eng.BrokenInfo();
  if (dst && max_n > 0) {
    int k = static_cast<int>(s.size()) < max_n - 1
                ? static_cast<int>(s.size())
                : max_n - 1;
    memcpy(dst, s.data(), static_cast<size_t>(k));
    dst[k] = '\0';
  }
  return 1;
}

// Direct ScaleBuffer entry point for unit tests (pins the integer
// round-vs-truncate semantics without spinning up a gang). dtype is the
// DataType wire id. Returns 0, or -1 for an unsupported dtype.
int hvt_scale_buffer(void* data, long long count, int dtype,
                     double factor) {
  try {
    hvt::ScaleBuffer(data, static_cast<int64_t>(count),
                     static_cast<DataType>(dtype), factor);
    return 0;
  } catch (const std::exception&) {
    return -1;
  }
}

// ---- flight recorder (csrc/events.h) -------------------------------------

// Drain up to max_n engine events into buf (an array of EventView — the
// ctypes EngineEvent Structure mirrors the layout). Returns the number
// written, oldest first. Safe to call whether or not the engine is
// initialized; events survive Shutdown until drained or overwritten.
int hvt_events_drain(void* buf, int max_n) {
  if (!buf || max_n <= 0) return 0;
  return Engine::Get().events().Drain(
      static_cast<hvt::EventView*>(buf), max_n);
}

// Events overwritten before anyone drained them (ring capacity 8192).
long long hvt_events_dropped() {
  return static_cast<long long>(Engine::Get().events().dropped());
}

// Record one event into the flight-recorder ring from the host
// language. The elastic recovery path lives in Python and spans a
// Shutdown/Init cycle, so its RECOVERY phase markers cannot be stamped
// by any engine code path — this is the narrow door in. kind must be a
// known EventKind wire id (unknown ids are dropped: a drained ring must
// never carry kinds the drainer cannot name); returns 0 on record, -1
// on a rejected kind. Safe whether or not the engine is initialized
// (the ring, like the drain, outlives Shutdown).
int hvt_record_event(int kind, const char* name, int op, int arg,
                     long long arg2) {
  if (kind < 0 || kind > static_cast<int>(hvt::EventKind::RECOVERY)) {
    return -1;
  }
  Engine::Get().events().Record(
      static_cast<hvt::EventKind>(kind), name ? name : "", op, arg,
      static_cast<int64_t>(arg2));
  return 0;
}

// ---- transport backend introspection -------------------------------------

// 1 when this kernel passes the io_uring capability probe (ring setup,
// EXT_ARG timed waits, SEND/RECV/ASYNC_CANCEL opcodes) — i.e. when
// HVT_LINK_BACKEND=auto resolves to io_uring. The probe result is
// cached per process; safe to call without an initialized engine.
int hvt_uring_supported() { return hvt::UringSupported() ? 1 : 0; }

// getsockopt probe for the registered link on `plane` (0 ctrl, 1 data)
// to rank `peer`: fills out3 = {TCP_NODELAY, SO_SNDBUF, SO_RCVBUF}.
// Returns 0, or -1 when no live link matches. Pins socket-option
// continuity across transparent heals — every re-dial/re-accept path
// must re-apply TCP_NODELAY + HVT_SOCK_BUF to the fresh socket.
int hvt_link_sockopt_probe(int plane, int peer, long long* out3) {
  if (!out3) return -1;
  return Engine::Get().LinkSockoptProbe(plane, peer, out3);
}

// Transport-level ping-pong micro-benchmark, isolated from the engine
// (no control plane, no negotiation — it measures exactly the layer
// HVT_LINK_BACKEND swaps): role 0 listens on `port`, role 1 dials
// `host:port`; both sides run `iters` timed full-duplex steps of
// `payload` bytes each direction over ONE link. backend 0 = TcpLink
// driven by the generic poll/send/recv loop (the engine Duplex
// fallback, replicated step-for-step), 1 = IoUringLink::PumpDuplex
// with the same fallback tail. Fills out[0..3] = {p50_ns, mean_ns,
// syscalls, steps}; syscalls covers the measured steps only —
// poll/send/recv for the generic loop plus io_uring_enter for the
// ring. Returns 0, or -1 on setup/transfer failure. Benchmark-only
// surface: benchmarks/engine_scaling.py --uring drives it pairwise
// for the committed r18_uring_sweep.json speedup claims.
int hvt_transport_bench(int role, const char* host, int port,
                        long long payload, int iters, int backend,
                        long long* out) {
  if (!out || iters <= 0 || payload <= 0) return -1;
  try {
    hvt::Listener lis;
    hvt::Sock s;
    if (role == 0) {
      lis.Listen(port);
      s = lis.Accept(30);
    } else {
      s = hvt::Sock::Connect(host ? host : "127.0.0.1", port, 30);
    }
    if (!s.valid()) return -1;
    hvt::ReconnectHub hub;
    std::atomic<int64_t> sqes{0}, enters{0}, cqes{0};
    hub.uring_sqes = &sqes;
    hub.uring_enters = &enters;
    hub.uring_cqes = &cqes;
    std::unique_ptr<hvt::TcpLink> link;
    if (backend == hvt::kLinkBackendUring)
      link.reset(new hvt::IoUringLink(std::move(s),
                                      hvt::LinkPlane::DATA, 1 - role,
                                      &hub));
    else
      link.reset(new hvt::TcpLink(std::move(s), hvt::LinkPlane::DATA,
                                  1 - role, &hub));
    const size_t n = static_cast<size_t>(payload);
    std::vector<uint8_t> sbuf(n, static_cast<uint8_t>(role + 1));
    std::vector<uint8_t> rbuf(n);
    long long syscalls = 0;
    std::vector<long long> ns;
    ns.reserve(static_cast<size_t>(iters));
    const int warm = iters / 10 + 8;
    for (int it = 0; it < warm + iters; ++it) {
      if (it == warm) {
        syscalls = 0;
        enters.store(0);
      }
      const auto t0 = std::chrono::steady_clock::now();
      size_t sent = 0, rcvd = 0;
      link->PumpDuplex(*link, sbuf.data(), n, rbuf.data(), n, n, sent,
                       rcvd, nullptr);
      while (sent < n || rcvd < n) {  // the engine Duplex fallback
        struct pollfd pd {link->fd(), 0, 0};
        if (sent < n) pd.events |= POLLOUT;
        if (rcvd < n) pd.events |= POLLIN;
        if (pd.fd >= 0) {
          ::poll(&pd, 1, 1000);
          ++syscalls;
        } else {  // banked multishot spill: drain it directly
          pd.revents = POLLIN;
        }
        if ((pd.revents & POLLOUT) && sent < n) {
          sent += link->SendSome(sbuf.data() + sent, n - sent);
          ++syscalls;
        }
        if ((pd.revents & (POLLIN | POLLHUP | POLLERR)) && rcvd < n) {
          rcvd += link->RecvSome(rbuf.data() + rcvd, n - rcvd);
          ++syscalls;
        }
      }
      const auto t1 = std::chrono::steady_clock::now();
      if (it >= warm)
        ns.push_back(std::chrono::duration_cast<std::chrono::nanoseconds>(
                         t1 - t0)
                         .count());
    }
    std::sort(ns.begin(), ns.end());
    long long sum = 0;
    for (long long v : ns) sum += v;
    out[0] = ns[ns.size() / 2];
    out[1] = sum / static_cast<long long>(ns.size());
    out[2] = syscalls + enters.load();
    out[3] = iters;
    return 0;
  } catch (...) {
    return -1;
  }
}

// ---- wire-grammar decode probe -------------------------------------------

// Feeds raw bytes into one decoder family and classifies the outcome —
// the C-side half of the deterministic frame fuzzer
// (tools/hvt_fuzz.py). The control probes check the abort bit first,
// exactly like the engine readers' IsAbortFrame guard, and the codec
// probe enforces the transfer-size agreement the data plane pins
// before any decompress. Families:
//   0 announce frame     (DecodeAnnounceFrame)
//   1 leader aggregate   (dispatch flag byte + DecodeAggregateFrame)
//   2 response frame     (Engine::DecodeResponseFrame frame grammar)
//   3 session HELLO      (TcpLink::ReadHello grammar)
//   4 session ACK        (TcpLink reconnect-ack grammar)
//   5 codec block stream (leading wire-codec id byte + blocks)
//   6 request list       (DecodeRequestList)
//   7 response list      (DecodeResponseList)
// Returns 0 = decoded clean, 1 = typed rejection (TruncatedFrameError
// or the documented magic/size agreement check), 2 = any OTHER
// exception — a containment failure the fuzzer reports as a bug —
// and -1 for a null buffer or unknown family.
int hvt_decode_probe(int family, const void* data, long long nbytes) {
  if (nbytes < 0 || (nbytes > 0 && data == nullptr)) return -1;
  const auto* p = static_cast<const uint8_t*>(data);
  std::vector<uint8_t> buf(p, p + static_cast<size_t>(nbytes));
  try {
    hvt::Reader rd(buf);
    switch (family) {
      case 0:
      case 1:
      case 2: {
        if (!buf.empty() && (buf[0] & hvt::kAbortFrameFlag) != 0) {
          // an ABORT replaces any expected control frame (engine.cc
          // ParseAbortFrame): u8 flag | i32 origin | str reason
          rd.u8();
          (void)rd.i32();
          (void)rd.str();
        } else if (family == 0) {
          (void)hvt::DecodeAnnounceFrame(rd, 0);
        } else if (family == 1) {
          rd.u8();  // the kCtrlFlagAggregate dispatch byte
          (void)hvt::DecodeAggregateFrame(rd);
        } else {
          // rank-0 → worker response frame (Engine::DecodeResponseFrame
          // minus the engine-state side effects): flags | tuned cycle |
          // tuned bits | evictions | positions form or full list
          uint8_t first = rd.u8();
          (void)rd.i32();
          (void)rd.u8();
          (void)rd.i64vec();
          if (first & hvt::kRespFlagPositions) {
            (void)rd.u8();
            (void)rd.u8();
            (void)rd.i64();
            (void)rd.i64vec();
          } else {
            (void)hvt::DecodeResponseList(rd);
          }
        }
        break;
      }
      case 3: {  // HELLO: magic | rank | plane | epoch | rx
        if (rd.i32() != hvt::kLinkHelloMagic) return 1;
        (void)rd.i32();
        (void)rd.u8();
        (void)rd.i64();
        (void)rd.i64();
        break;
      }
      case 4: {  // ACK: magic | epoch | rx
        if (rd.i32() != hvt::kLinkHelloMagic) return 1;
        (void)rd.i64();
        (void)rd.i64();
        break;
      }
      case 5: {
        // The data plane never decodes a stream whose byte count
        // disagrees with CompressedSize(n) — both ends derive the
        // transfer size from the negotiated element count — so a size
        // with no matching n is the typed rejection here.
        uint8_t id = rd.u8();
        const hvt::Codec* c = hvt::CodecFor(static_cast<hvt::WireCodec>(id));
        if (c == nullptr) return 1;  // RAW / unknown id: no block grammar
        const size_t s = rd.remaining();
        const size_t wbb = c->WireBlockBytes();
        const int64_t be = c->BlockElems();
        int64_t n = static_cast<int64_t>(s / wbb) * be;
        const size_t tail = s % wbb;
        if (tail != 0) {
          int64_t rem = -1;
          for (int64_t k = 1; k < be; ++k)
            if (c->CompressedSize(k) == tail) {
              rem = k;
              break;
            }
          if (rem < 0) return 1;
          n += rem;
        }
        if (c->CompressedSize(n) != s) return 1;
        std::vector<float> out(static_cast<size_t>(n));
        c->Decompress(out.data(), buf.data() + 1, n);
        break;
      }
      case 6:
        (void)hvt::DecodeRequestList(rd);
        break;
      case 7:
        (void)hvt::DecodeResponseList(rd);
        break;
      default:
        return -1;
    }
  } catch (const hvt::TruncatedFrameError&) {
    return 1;
  } catch (const std::exception&) {
    return 2;
  }
  return 0;
}

// JSON diagnostics snapshot: engine queue depth, pending tensors with
// ages, and (on rank 0) the negotiation arrival table with per-tensor
// missing-rank sets — the machine-readable face of the stall inspector.
// Fills dst (NUL-terminated, truncated to max_n); returns the full
// length, so callers can re-size and retry like hvt_error_message.
int hvt_diagnostics(char* dst, int max_n) {
  std::string s = Engine::Get().DiagnosticsJson();
  int n = static_cast<int>(s.size());
  if (dst && max_n > 0) {
    int k = n < max_n - 1 ? n : max_n - 1;
    memcpy(dst, s.data(), static_cast<size_t>(k));
    dst[k] = '\0';
  }
  return n;
}

}  // extern "C"
