#include "engine.h"

#include "logging.h"
#include "uring_link.h"

#include <climits>
#include <csignal>
#include <chrono>
#include <cstdio>
#include <cstdlib>
#include <set>
#include <sstream>

namespace hvt {

Engine& Engine::Get() {
  static Engine* engine = new Engine();
  return *engine;
}

// --------------------------------------------------------------------------
// coordinated-abort control frames
// --------------------------------------------------------------------------
// The first byte of every control frame is a flags byte (worker→rank 0)
// or resp_flags byte (rank 0→worker); neither protocol uses bit 7, so an
// ABORT frame is any frame whose first byte has kAbortFrameFlag set:
//   u8(kAbortFrameFlag) | i32(origin rank) | str(reason)
// It can arrive in place of ANY expected frame — both readers check the
// bit before parsing — which is what lets a failing rank interrupt the
// gang mid-protocol. All flag bits live in the wire.h registry
// (kCtrlFlag* / kRespFlag* / kAbortFrameFlag) so a new flag can never
// silently collide with the abort bit.

static bool IsAbortFrame(const std::vector<uint8_t>& f) {
  return !f.empty() && (f[0] & kAbortFrameFlag) != 0;
}

static std::vector<uint8_t> BuildAbortFrame(int origin_rank,
                                            const std::string& reason) {
  Writer w;
  w.u8(kAbortFrameFlag);
  w.i32(origin_rank);
  w.str(reason);
  return std::move(w.buf);
}

static std::string ParseAbortFrame(const std::vector<uint8_t>& f) {
  Reader rd(f);
  rd.u8();
  int32_t origin = rd.i32();
  std::string reason = rd.str();
  return "abort from rank " + std::to_string(origin) + ": " + reason;
}

// HVT_FAULT_INJECT grammar (chaos harness; see docs/troubleshooting.md):
//   kill:rank=R:after_ops=N   raise(SIGKILL) before data-plane op N+1
//   drop_conn:rank=R[:after_ops=N]   mark every engine link DEAD (the
//                                    PERMANENT loss — escalates to the
//                                    coordinated abort, PR 4 semantics)
//   delay_ms:rank=R:MS        sleep MS ms before every data-plane op
// Transient faults (the self-healing links must reconnect through
// these with zero aborts):
//   flaky_conn:rank=R:count=N[:after_ops=K]   N times, cut rank R's
//       data links mid-transfer (and its upstream control link); the
//       first cut arms after op K (default 1), repeats every 2 ops
//   partition:hosts=A|B:ms=MS[:after_ops=K]   cut every link crossing
//       the A|B host boundary (comma-separated host lists; matched
//       against the rendezvous topology) and hold reconnects for MS ms
//   reset_storm:every_ops=N[:rank=R]   every N data ops, reset one of
//       the rank's data links (round-robin); all ranks unless rank=R
// Specs for other ranks (or Python-level specs like after_sec, owned by
// task_runner) are ignored here.
static void ParseFaultInject(const std::string& spec, int my_rank,
                             Engine::FaultSpec& out) {
  out = Engine::FaultSpec{};
  size_t p = spec.find(':');
  std::string kind = spec.substr(0, p);
  int64_t rank = -1, after_ops = -1, bare = -1, count = -1, every = -1;
  int64_t ms = -1;
  std::string hosts;
  bool has_after_sec = false;
  while (p != std::string::npos) {
    size_t q = spec.find(':', p + 1);
    std::string tok = spec.substr(p + 1, q == std::string::npos
                                             ? std::string::npos
                                             : q - p - 1);
    if (tok.rfind("rank=", 0) == 0)
      rank = atoll(tok.c_str() + 5);
    else if (tok.rfind("after_ops=", 0) == 0)
      after_ops = atoll(tok.c_str() + 10);
    else if (tok.rfind("count=", 0) == 0)
      count = atoll(tok.c_str() + 6);
    else if (tok.rfind("every_ops=", 0) == 0)
      every = atoll(tok.c_str() + 10);
    else if (tok.rfind("ms=", 0) == 0)
      ms = atoll(tok.c_str() + 3);
    else if (tok.rfind("hosts=", 0) == 0)
      hosts = tok.substr(6);
    else if (tok.rfind("after_sec=", 0) == 0)
      has_after_sec = true;  // Python-level trigger (task_runner)
    else if (!tok.empty() && (isdigit(tok[0]) || tok[0] == '-'))
      bare = atoll(tok.c_str());
    p = q;
  }
  if (kind == "kill" && after_ops >= 0 && rank == my_rank) {
    // after_sec-triggered kills belong to task_runner; arm here only
    // for the op-count trigger
    out.kind = Engine::FaultKind::KILL;
    out.after_ops = after_ops;
  } else if (kind == "drop_conn" && !has_after_sec && rank == my_rank) {
    out.kind = Engine::FaultKind::DROP_CONN;
    out.after_ops = after_ops >= 0 ? after_ops : 0;
  } else if (kind == "delay_ms" && rank == my_rank) {
    out.kind = Engine::FaultKind::DELAY_MS;
    out.after_ops = after_ops >= 0 ? after_ops : 0;
    out.arg = bare > 0 ? bare : 0;
  } else if (kind == "flaky_conn" && rank == my_rank) {
    out.kind = Engine::FaultKind::FLAKY_CONN;
    out.after_ops = after_ops >= 0 ? after_ops : 1;
    out.count = count > 0 ? count : 1;
  } else if (kind == "partition" && hosts.find('|') != std::string::npos) {
    // host-based, no rank=: every rank decides its side at trigger time
    out.kind = Engine::FaultKind::PARTITION;
    out.after_ops = after_ops >= 0 ? after_ops : 0;
    out.arg = ms > 0 ? ms : 0;
    size_t bar = hosts.find('|');
    out.hosts_a = hosts.substr(0, bar);
    out.hosts_b = hosts.substr(bar + 1);
  } else if (kind == "reset_storm" && every > 0 &&
             (rank < 0 || rank == my_rank)) {
    out.kind = Engine::FaultKind::RESET_STORM;
    out.every_ops = every;
    out.after_ops = -1;  // last-fired marker
  }
}

// comma-separated host-list membership (partition fault)
static bool HostInList(const std::string& csv, const std::string& host) {
  size_t p = 0;
  while (p <= csv.size()) {
    size_t q = csv.find(',', p);
    size_t end = q == std::string::npos ? csv.size() : q;
    if (csv.compare(p, end - p, host) == 0) return true;
    if (q == std::string::npos) break;
    p = q + 1;
  }
  return false;
}

// --------------------------------------------------------------------------
// init / rendezvous / mesh bring-up
// --------------------------------------------------------------------------

Status Engine::Init(int rank, int size, const std::string& master_addr,
                    int master_port, int cycle_ms) {
  if (initialized_.load()) return Status::OK();
  rank_ = rank;
  size_ = size;
  cycle_ms_ = cycle_ms > 0 ? cycle_ms : 2;
  event_driven_ = EnvInt("HVT_EVENT_DRIVEN", 1) != 0;
  // Control-plane shape: HVT_CTRL_TOPOLOGY=tree elects one leader per
  // host to aggregate its members' announcements (must agree across
  // the gang — the launcher propagates it); star is the default and
  // the parity baseline. HVT_CTRL_BYPASS=0 disables the steady-state
  // bitmask/positions encodings (full frames everywhere).
  tree_mode_ = false;
  if (const char* ct = getenv("HVT_CTRL_TOPOLOGY"); ct && *ct)
    tree_mode_ = std::string(ct) == "tree";
  ctrl_bypass_ = EnvInt("HVT_CTRL_BYPASS", 1) != 0;
  ctrl_role_ = rank_ == 0 ? CtrlRole::ROOT : CtrlRole::MEMBER;
  ctrl_children_.clear();
  // Wire-codec pair for fp32 allreduce payloads. Every rank parses the
  // env for introspection, but only rank 0's values matter: it stamps
  // the per-link-class pair into each Response, so the gang always
  // agrees even when the env differs across hosts. Forms:
  //   "<codec>"          same codec on both link classes (PR 3 compat)
  //   "<intra>,<inter>"  EQuARX split — e.g. "none,int8" keeps in-host
  //                      traffic full precision and quantizes only the
  //                      cross-host hops
  //   "auto"             intra none; inter picked per (size, link) by
  //                      the CodecTuner from live sweep samples
  //   "<intra>,auto"     fixed intra codec, tuner-picked inter —
  //                      e.g. "bf16,auto" keeps bf16 in-host while the
  //                      cross-host codec adapts
  {
    wire_intra_ = wire_inter_ = 0;
    wire_auto_ = false;
    const char* wc = getenv("HVT_WIRE_COMPRESSION");
    std::string spec = wc ? wc : "";
    auto parse_tok = [&](const std::string& tok, bool allow_auto,
                         uint8_t* out) {
      if (allow_auto && tok == "auto") {
        wire_auto_ = true;
        *out = 0;
        return;
      }
      int id = WireCodecFromName(tok.c_str());
      if (id < 0 || id >= kWireCodecCount) {
        HVT_LOG(WARNING, rank_)
            << "HVT_WIRE_COMPRESSION: unknown codec '" << tok
            << "' — moving raw bytes";
        id = 0;
      }
      *out = static_cast<uint8_t>(id);
    };
    auto comma = spec.find(',');
    if (comma == std::string::npos) {
      uint8_t id = 0;
      parse_tok(spec, /*allow_auto=*/true, &id);
      wire_intra_ = wire_auto_ ? 0 : id;  // auto quantizes inter only
      wire_inter_ = id;
    } else {
      parse_tok(spec.substr(0, comma), /*allow_auto=*/false,
                &wire_intra_);
      parse_tok(spec.substr(comma + 1), /*allow_auto=*/true,
                &wire_inter_);
    }
    wire_cur_intra_.store(wire_intra_, std::memory_order_relaxed);
    wire_cur_inter_.store(wire_inter_, std::memory_order_relaxed);
    stamped_intra_ = wire_intra_;
    stamped_inter_ = wire_inter_;
    stamp_uniform_ = true;
    codec_tuner_.Reset();
  }
  // error feedback: compensate lossy wire quantization by carrying each
  // tensor's quantization error into its next submission (cleared on
  // shutdown/re-init; bounded by HVT_EF_MAX_BYTES)
  ef_enabled_ = EnvInt("HVT_ERROR_FEEDBACK", 1) != 0;
  ef_max_bytes_ = EnvInt("HVT_EF_MAX_BYTES", 64 << 20);
  ef_bufs_.clear();
  ef_bytes_ = 0;
  ef_tick_ = 0;
  fusion_threshold_ = EnvInt("HVT_FUSION_THRESHOLD", 64 << 20);
  stall_warn_sec_ =
      static_cast<double>(EnvInt("HVT_STALL_WARN_SEC", 60));
  // liveness: idle-gang control frames double as heartbeats; this is
  // the deadline applied to them when no work is outstanding (0 → use
  // HVT_OP_TIMEOUT_MS everywhere)
  heartbeat_ms_ = EnvInt("HVT_HEARTBEAT_MS", 30000);
  if (const char* fi = getenv("HVT_FAULT_INJECT"); fi && *fi) {
    ParseFaultInject(fi, rank, fault_);
    if (fault_.kind != FaultKind::NONE) {
      HVT_LOG(WARNING, rank) << "fault injection armed: " << fi;
    }
  } else {
    fault_ = FaultSpec{};
  }
  disable_group_fusion_ = EnvInt("HVT_DISABLE_GROUP_FUSION", 0) != 0;
  cache_ = ResponseCache(
      static_cast<size_t>(EnvInt("HVT_CACHE_CAPACITY", 1024)));
  autotune_.Initialize(fusion_threshold_, cycle_ms_);
  std::vector<std::string> topo_hosts(size_, "localhost");
  // self-healing link plumbing: the hub must exist before the first
  // TcpLink wraps a socket (links register with it); its telemetry
  // sinks are stats fields, which outlive every link, so scrapes can
  // never race a teardown
  shutdown_requested_ = false;
  hub_.Reset();
  hub_.my_rank = rank_;
  hub_.reconnects = stats_.link_reconnects;
  hub_.frames_replayed = &stats_.frames_replayed;
  hub_.replay_bytes = &stats_.replay_bytes;
  hub_.uring_sqes = &stats_.uring_sqes;
  hub_.uring_enters = &stats_.uring_enters;
  hub_.uring_cqes = &stats_.uring_cqes;
  hub_.events = &events_;
  hub_.stop = &shutdown_requested_;
  // abort sniffing: sibling sweeps peek queued control frames for this
  // bit so a rank stuck reconnecting joins a gang teardown immediately
  hub_.abort_flag = kAbortFrameFlag;
  try {
    if (size_ > 1) {
      data_listener_.Close();
      data_listener_.Listen(0);
      const char* host_env = getenv("HVT_HOSTNAME");
      std::string my_host = host_env ? host_env : "127.0.0.1";
      std::string my_ep =
          my_host + ":" + std::to_string(data_listener_.port());
      // topology identity may differ from the dialable endpoint host
      // (HVT_TOPO_HOST lets tests fake a multi-host layout on loopback)
      const char* topo_env = getenv("HVT_TOPO_HOST");
      std::string my_topo = topo_env && *topo_env ? topo_env : my_host;

      // endpoint + topology exchange over the control star (the
      // rendezvous; reference analog: gloo HTTP-store scoped KV,
      // gloo_context.cc)
      std::vector<std::string> endpoints(size_);
      if (rank_ == 0) {
        // the control listener is a MEMBER and stays open for the
        // engine's lifetime: a worker link that drops re-dials the
        // master port and rank 0 re-accepts here (transport.h)
        control_listener_.Close();
        control_listener_.Listen(master_port);
        endpoints[0] = my_ep;
        topo_hosts[0] = my_topo;
        std::vector<Sock> raw(size_);
        for (int i = 0; i < size_ - 1; ++i) {
          Sock s = control_listener_.Accept();
          auto frame = s.RecvFrame();
          Reader rd(frame);
          int32_t r = rd.i32();
          endpoints[r] = rd.str();
          topo_hosts[r] = rd.str();
          raw[r] = std::move(s);
        }
        Writer w;
        for (auto& ep : endpoints) w.str(ep);
        for (auto& th : topo_hosts) w.str(th);
        for (int r = 1; r < size_; ++r) raw[r].SendFrame(w.buf);
        // wrap into self-healing links AFTER the rendezvous exchange —
        // both ends wrap at the same stream position, so the replay
        // sequence numbers agree from byte 0
        workers_.clear();
        workers_.resize(static_cast<size_t>(size_));
        for (int r = 1; r < size_; ++r)
          workers_[static_cast<size_t>(r)] = std::make_unique<TcpLink>(
              std::move(raw[static_cast<size_t>(r)]), LinkPlane::CTRL,
              r, &hub_, "", 0, &control_listener_);
      } else {
        Sock c = Sock::Connect(master_addr, master_port);
        Writer w;
        w.i32(rank_);
        w.str(my_ep);
        w.str(my_topo);
        c.SendFrame(w.buf);
        auto frame = c.RecvFrame();
        Reader rd(frame);
        for (auto& ep : endpoints) ep = rd.str();
        for (auto& th : topo_hosts) th = rd.str();
        // workers re-DIAL the master port when the link drops
        control_ = std::make_unique<TcpLink>(std::move(c),
                                             LinkPlane::CTRL, 0, &hub_,
                                             master_addr, master_port);
      }
      // full data mesh: i connects to j for i < j; acceptor learns the
      // peer's rank from a 4-byte hello. Each socket is wrapped into a
      // link with the same dial/accept role for reconnects (the data
      // listener stays open for the engine's lifetime). The DATA plane
      // is where the backend choice lands (HVT_LINK_BACKEND resolved
      // through the kernel probe): IoUringLink inherits the whole
      // TcpLink session layer, so both backends share replay/heal
      // state bit-for-bit. Control links stay TcpLink — their traffic
      // is small frames where the batched pump buys nothing.
      const bool uring =
          ResolveLinkBackend() == kLinkBackendUring;
      auto make_data_link = [&](Sock s, int peer_rank,
                                const std::string& host, int port,
                                Listener* listener) -> LinkPtr {
        if (uring)
          return std::make_unique<IoUringLink>(
              std::move(s), LinkPlane::DATA, peer_rank, &hub_, host,
              port, listener);
        return std::make_unique<TcpLink>(std::move(s), LinkPlane::DATA,
                                         peer_rank, &hub_, host, port,
                                         listener);
      };
      HVT_LOG(INFO, rank_) << "data-plane link backend: "
                           << (uring ? "io_uring" : "tcp")
                           << " (HVT_LINK_BACKEND, kernel probe "
                           << (UringSupported() ? "ok" : "failed")
                           << ")";
      std::vector<std::unique_ptr<Transport>> peers(size_);
      int to_accept = rank_;  // ranks below me dial in
      for (int j = rank_ + 1; j < size_; ++j) {
        auto pos = endpoints[j].rfind(':');
        std::string host = endpoints[j].substr(0, pos);
        int port = atoi(endpoints[j].c_str() + pos + 1);
        Sock s = Sock::Connect(host, port);
        int32_t me = rank_;
        s.SendAll(&me, 4);
        peers[static_cast<size_t>(j)] =
            make_data_link(std::move(s), j, host, port, nullptr);
      }
      for (int k = 0; k < to_accept; ++k) {
        Sock s = data_listener_.Accept();
        int32_t who = -1;
        s.RecvAll(&who, 4);
        peers[static_cast<size_t>(who)] =
            make_data_link(std::move(s), who, "", 0, &data_listener_);
      }
      data_ = std::make_unique<DataPlane>(rank_, size_, std::move(peers));

      // control-plane roles + tree links (uses the star for the port
      // exchange, so it must run while every control socket is fresh)
      if (tree_mode_) {
        SetupTreeControl(endpoints, topo_hosts);
      } else if (rank_ == 0) {
        for (int r = 1; r < size_; ++r) ctrl_children_.push_back(r);
      }
    } else {
      data_ = std::make_unique<DataPlane>(
          0, 1, std::vector<std::unique_ptr<Transport>>{});
    }
  } catch (const std::exception& e) {
    return Status::Error(std::string("hvt init failed: ") + e.what());
  }
  // ordered backend list (reference operations.cc:142-249): hierarchical
  // first when the topology supports it, flat ring as the fallback
  topo_ = Topology::Build(rank_, topo_hosts);
  bool hier_ok = topo_.homogeneous && topo_.n_hosts > 1 &&
                 topo_.local_group.size() > 1;
  bool hier_on = hier_ok && EnvInt("HVT_HIERARCHICAL_ALLREDUCE", 1) != 0;
  // shm data plane: only when every rank shares this host (autotuning
  // can grow the fusion threshold, so give the slots headroom over it)
  bool shm_on = topo_.n_hosts == 1 && size_ > 1 &&
                EnvInt("HVT_SHM_ALLREDUCE", 1) != 0;
  int64_t shm_cap =
      std::max<int64_t>(fusion_threshold_ * 2, int64_t{64} << 20);
  shm_cap = (shm_cap + 63) & ~int64_t{63};  // keep every slot 64B-aligned
  backends_.clear();
  backends_.push_back(std::make_unique<ShmLocalBackend>(
      data_.get(), rank_, size_, master_port, shm_cap, shm_on));
  backends_.push_back(std::make_unique<HierarchicalBackend>(
      data_.get(), topo_, hier_on));
  backends_.push_back(std::make_unique<RingBackend>(data_.get(), topo_));
  // must restart from the same value on every rank — an elastic re-init
  // mixes survivors with fresh workers, and the shm barrier words are
  // keyed to this sequence
  resp_seq_ = 0;
  stats_.Reset();  // fresh telemetry per (re-)init — an elastic restart
                   // starts a new scrape epoch on every rank
  // info gauge (hvt_link_backend): which backend this gang's data
  // links actually resolved to — 1-rank gangs have no data links, so
  // report the resolution the mesh WOULD use (same probe path)
  stats_.link_backend.store(static_cast<int64_t>(ResolveLinkBackend()),
                            std::memory_order_relaxed);
  // per-lane execution pool (HVT_LANE_WORKERS; 0 = off, bit-identical
  // single-thread engine)
  StartLanePool();
  // direct control-plane peers this rank serves: children (+ the parent
  // link for non-root ranks) — the fan-in number the tree exists to cap
  stats_.ctrl_peers.store(
      size_ > 1 ? static_cast<int64_t>(ctrl_children_.size()) +
                      (ctrl_role_ == CtrlRole::ROOT ? 0 : 1)
                : 0,
      std::memory_order_relaxed);
  // wire telemetry lands in the stats block, which outlives data_ —
  // scrape threads may poll hvt_engine_stats while Shutdown tears the
  // DataPlane down
  data_->BindTxCounters(stats_.wire_tx_bytes, stats_.wire_tx_comp_bytes);
  data_->BindCodecTxCounters(stats_.codec_tx_bytes);
  data_->BindPumpCounters(&stats_.pump_syscalls);
  // wire-phase spans land in the flight-recorder ring, which (like the
  // stats block) is engine-owned and outlives data_
  data_->BindEvents(&events_);
  cache_enabled_ = true;
  prefer_flat_ = false;
  tuned_cache_enabled_ = true;
  tuned_prefer_flat_ = false;
  rank_joined_.assign(size_, false);
  rank_shutdown_.assign(size_, false);
  hit_pending_.assign(size_, {});
  pending_evictions_.clear();
  announced_.clear();
  lanes_seen_.clear();
  fusion_buffers_.clear();
  shutdown_requested_ = false;
  fatal_ = false;
  broken_ = false;  // a fresh init starts healthy (elastic re-init path)
  {
    MutexLock lk(broken_mu_);
    broken_reason_.clear();
    broken_cause_ = kAbortInternal;
  }
  // only the coordinator writes the timeline file (reference
  // operations.cc:422-425); started only after a successful rendezvous
  // so an Init failure leaves no orphan writer thread / open file
  const char* tl = getenv("HVT_TIMELINE");
  if (rank_ == 0 && tl && *tl)
    timeline_.Initialize(tl,
                         EnvInt("HVT_TIMELINE_MARK_CYCLES", 0) != 0);
  initialized_ = true;
  thread_ = std::thread([this] { ThreadLoop(); });
  HVT_LOG(INFO, rank_) << "engine up: size " << size_ << ", cycle "
                       << cycle_ms_ << " ms, fusion "
                       << (fusion_threshold_ >> 20) << " MB"
                       << (tree_mode_ && size_ > 1
                               ? std::string(", ctrl tree (") +
                                     CtrlRoleName(ctrl_role_) + ", " +
                                     std::to_string(
                                         ctrl_children_.size()) +
                                     " children)"
                               : "")
                       << (autotune_.active() ? ", autotune on" : "")
                       << (hier_on
                               ? ", hierarchical allreduce ("
                                     + std::to_string(topo_.n_hosts) + "x"
                                     + std::to_string(
                                           topo_.local_group.size()) + ")"
                               : "");
  return Status::OK();
}

void Engine::Shutdown() {
  if (!initialized_.load()) return;
  shutdown_requested_ = true;
  {
    // pair with the cv wait's predicate check so the wakeup can't be
    // missed between predicate evaluation and sleep
    MutexLock lk(queue_mu_);
  }
  queue_cv_.notify_all();
  if (thread_.joinable()) thread_.join();
  StopLanePool();  // idempotent — EnterBroken may have stopped it
  workers_.clear();
  control_.reset();
  tree_parent_.reset();
  tree_child_socks_.clear();
  ctrl_children_.clear();
  backends_.clear();  // before data_: backends hold raw DataPlane*
  data_.reset();
  data_listener_.Close();
  control_listener_.Close();
  tree_listener_.Close();
  hub_.Reset();  // parked reconnect dials die with the run
  initialized_ = false;
  timeline_.Shutdown();
  // reset engine-thread state for a potential re-init (elastic restart)
  pending_.clear();
  counts_.clear();
  {
    MutexLock lk(handles_mu_);
    inflight_.clear();
  }
  cache_ = ResponseCache(1024);
  join_pending_ = false;
  join_entry_.reset();
  last_join_rank_ = -1;
  announced_.clear();
  counts_.clear();
  groups_.clear();
  stall_warned_.clear();
  lanes_seen_.clear();
  fusion_buffers_.clear();
  // error-feedback residuals are per-run state: a re-init (elastic
  // restart, possibly a different codec) must start uncompensated
  ef_bufs_.clear();
  ef_bytes_ = 0;
  ef_tick_ = 0;
  stats_.ef_residual_bytes.store(0, std::memory_order_relaxed);
}

// --------------------------------------------------------------------------
// submission / handles
// --------------------------------------------------------------------------

int32_t Engine::Submit(EntryPtr entry) {
  if (!initialized_.load()) return -1;
  stats_.tensors_submitted.fetch_add(1, std::memory_order_relaxed);
  entry->submit_sec = NowSec();
  events_.Record(EventKind::ENQUEUED, entry->name,
                 static_cast<int32_t>(entry->op), rank_,
                 static_cast<int64_t>(entry->input.size()),
                 LaneSlot(LaneId(entry->members)));
  int32_t h;
  {
    MutexLock lk(handles_mu_);
    h = next_handle_++;
    handles_[h] = HandleState{};
  }
  entry->handle = h;
  if (fatal_.load()) {
    // sticky broken state: fail fast (bounded, never a hang) until the
    // caller runs shutdown() + a fresh init()
    std::string why = BrokenInfo();
    CompleteEntry(entry,
                  Status::Aborted(why.empty()
                                      ? "hvt engine failed earlier"
                                      : "hvt engine aborted (" + why +
                                            "); shutdown() and re-init() "
                                            "to recover"));
    return h;
  }
  bool accepted = false;
  {
    // FailAll sets fatal_ and then drains this queue under the same
    // mutex, so re-checking fatal_ here closes the submit/abort race:
    // without it, an entry pushed between Submit's fast-path check and
    // FailAll's drain would never complete and its Wait would hang.
    MutexLock lk(queue_mu_);
    if (!fatal_.load()) {
      submitted_.push_back(std::move(entry));
      accepted = true;
    }
  }
  if (!accepted) {
    std::string why = BrokenInfo();
    CompleteEntry(entry,
                  Status::Aborted(why.empty()
                                      ? "hvt engine failed earlier"
                                      : "hvt engine aborted (" + why +
                                            "); shutdown() and re-init() "
                                            "to recover"));
    return h;
  }
  queue_cv_.notify_one();  // wake the engine mid-coalescing-wait
  return h;
}

bool Engine::Poll(int32_t handle) {
  MutexLock lk(handles_mu_);
  auto it = handles_.find(handle);
  return it == handles_.end() || it->second.done;
}

HandleState Engine::Wait(int32_t handle) {
  CvLock lk(handles_mu_);
  // REQUIRES on the predicate: clang's thread-safety analysis treats
  // lambda bodies as separate functions that do not inherit the
  // enclosing scope's held capabilities — and cv predicates do run
  // with the lock held.
  handles_cv_.wait(lk.native(), [&]() REQUIRES(handles_mu_) {
    auto it = handles_.find(handle);
    return it == handles_.end() || it->second.done;
  });
  auto it = handles_.find(handle);
  if (it == handles_.end()) return HandleState{};
  // MOVE the payload out rather than copying — for a 16 MB allreduce
  // this is a 16 MB memcpy off the wait path. Handles are waited at
  // most once (native.py caches, tf_ops waits once); a repeated Wait
  // still sees done/status but an empty output.
  HandleState out = std::move(it->second);
  it->second.done = out.done;
  it->second.status = out.status;
  it->second.join_result = out.join_result;
  return out;
}

bool Engine::WaitFor(int32_t handle, int64_t timeout_ms,
                     HandleState& out) {
  CvLock lk(handles_mu_);
  auto done = [&]() REQUIRES(handles_mu_) {  // see Wait's predicate note
    auto it = handles_.find(handle);
    return it == handles_.end() || it->second.done;
  };
  if (!handles_cv_.wait_for(lk.native(),
                            std::chrono::milliseconds(timeout_ms), done))
    return false;
  auto it = handles_.find(handle);
  if (it == handles_.end()) {
    out = HandleState{};
    return true;
  }
  // move semantics identical to Wait (handles are waited at most once)
  out = std::move(it->second);
  it->second.done = out.done;
  it->second.status = out.status;
  it->second.join_result = out.join_result;
  return true;
}

void Engine::Release(int32_t handle) {
  MutexLock lk(handles_mu_);
  handles_.erase(handle);
}

void Engine::CompleteEntry(const EntryPtr& e, const Status& s) {
  events_.Record(EventKind::DONE, e->name, static_cast<int32_t>(e->op),
                 static_cast<int32_t>(s.type), 0,
                 LaneSlot(LaneId(e->members)));
  {
    MutexLock lk(handles_mu_);
    for (size_t i = 0; i < inflight_.size(); ++i)
      if (inflight_[i] == e) {
        inflight_.erase(inflight_.begin() + static_cast<long>(i));
        break;
      }
    auto it = handles_.find(e->handle);
    if (it == handles_.end()) return;
    it->second.done = true;
    it->second.status = s;
    it->second.output = std::move(e->output);
    it->second.recv_splits = std::move(e->recv_splits);
  }
  // notify AFTER releasing handles_mu_: waking a waiter straight into a
  // held mutex costs an extra scheduler bounce per completion
  handles_cv_.notify_all();
}

void Engine::FailAll(const std::string& why) {
  fatal_ = true;
  // entries mid-execution when the data plane threw: their handles must
  // complete too, or Engine::Wait would hang past the abort
  std::vector<EntryPtr> inflight;
  {
    MutexLock lk(handles_mu_);
    inflight.swap(inflight_);
  }
  for (auto& e : inflight) CompleteEntry(e, Status::Aborted(why));
  for (auto& [name, e] : pending_)
    CompleteEntry(e, Status::Aborted(why));
  pending_.clear();
  if (join_entry_) {
    CompleteEntry(join_entry_, Status::Aborted(why));
    join_entry_.reset();
    join_pending_ = false;
  }
  MutexLock lk(queue_mu_);
  for (auto& e : submitted_) CompleteEntry(e, Status::Aborted(why));
  submitted_.clear();
}

int Engine::LinkSockoptProbe(int plane, int peer, long long out3[3]) {
  for (TcpLink* l : hub_.links) {
    if (static_cast<int>(l->plane()) != plane || l->peer_rank() != peer)
      continue;
    const int fd = l->fd();
    if (fd < 0) return -1;
    int nodelay = 0, sndbuf = 0, rcvbuf = 0;
    socklen_t n = sizeof(int);
    if (::getsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &nodelay, &n) != 0)
      return -1;
    n = sizeof(int);
    if (::getsockopt(fd, SOL_SOCKET, SO_SNDBUF, &sndbuf, &n) != 0)
      return -1;
    n = sizeof(int);
    if (::getsockopt(fd, SOL_SOCKET, SO_RCVBUF, &rcvbuf, &n) != 0)
      return -1;
    out3[0] = nodelay;
    out3[1] = sndbuf;
    out3[2] = rcvbuf;
    return 0;
  }
  return -1;
}

// --------------------------------------------------------------------------
// failure containment
// --------------------------------------------------------------------------

std::string Engine::BrokenInfo() {
  if (!broken_.load()) return "";
  MutexLock lk(broken_mu_);
  return std::string(AbortCauseName(broken_cause_)) + ": " +
         broken_reason_;
}

void Engine::EnterBroken(int cause, const std::string& why) {
  bool expected = false;
  if (!broken_.compare_exchange_strong(expected, true)) return;
  if (cause < 0 || cause >= kAbortCauses) cause = kAbortInternal;
  {
    MutexLock lk(broken_mu_);
    broken_cause_ = cause;
    broken_reason_ = why;
  }
  stats_.aborts[cause].fetch_add(1, std::memory_order_relaxed);
  events_.Record(EventKind::ABORT, why, -1, cause, 0);
  HVT_LOG(ERROR, rank_) << "engine aborting ("
                        << AbortCauseName(cause) << "): " << why
                        << " — completing all pending collectives with "
                        << "errors; submits fail fast until re-init";
  // Fan the ABORT out over the control topology (best effort — peers
  // may already be gone). Rank 0 tells every worker; a worker tells its
  // upstream (rank 0, and its leader in tree mode), and a leader also
  // relays down to its members — so each survivor reads the frame in
  // place of its next expected control message (tree members also poll
  // their parked star socket once per cycle) and aborts within one
  // cycle. The one slower path: a tree member already BLOCKED on a
  // wedged-but-alive leader converges at its own control deadline
  // (heartbeat/op timeout) — still bounded, one deadline not N.
  // Stop the healing machinery FIRST: reconnect attempts refuse
  // (hub_.closed) and the listeners close, so a peer's re-dial to this
  // deliberately-aborting rank is REFUSED instantly — an aborting rank
  // must look dead, not flaky, or survivors would burn their retry
  // window before converging on the PR 4 clock.
  hub_.closed.store(true);
  data_listener_.Close();
  control_listener_.Close();
  tree_listener_.Close();
  auto frame = BuildAbortFrame(rank_, why);
  auto try_send = [&](TcpLink* s) {
    if (!s || !s->valid()) return;
    try {
      s->SendFrame(frame, 1000);
    } catch (const std::exception&) {
    }
  };
  if (rank_ == 0) {
    for (int r = 1; r < size_; ++r)
      try_send(workers_[static_cast<size_t>(r)].get());
  } else {
    try_send(control_.get());
    try_send(tree_parent_.get());
  }
  for (auto& [child, sock] : tree_child_socks_) {
    (void)child;
    try_send(sock.get());
  }
  // Close the data mesh: peers blocked mid-collective on a socket to
  // this rank wake with PeerLostError immediately (FIN from Close), so
  // the abort cascades through the gang in one deadline, not N.
  if (data_) data_->Abort();
  // Quiesce the lane pool BEFORE FailAll: workers mid-collective fail
  // fast on the aborted links, and joining them here means FailAll is
  // the only writer left completing their stranded entries.
  StopLanePool();
  FailAll("hvt engine aborted (" + std::string(AbortCauseName(cause)) +
          "): " + why);
}

void Engine::CutLinksToRank(int r) {
  for (TcpLink* l : hub_.links)
    if (l->peer_rank() == r) l->InjectCutNow();
}

void Engine::MaybeInjectFault() {
  if (fault_.kind == FaultKind::NONE) return;
  int64_t ops = data_ops_.load();
  switch (fault_.kind) {
    case FaultKind::KILL:
      if (ops > fault_.after_ops) {
        HVT_LOG(WARNING, rank_) << "HVT_FAULT_INJECT: raising SIGKILL "
                                << "after " << fault_.after_ops
                                << " data ops";
        raise(SIGKILL);
      }
      break;
    case FaultKind::DROP_CONN:
      // PERMANENT loss (PR 4 semantics): links go DEAD — the next I/O
      // escalates straight into the coordinated abort, no reconnect
      if (ops > fault_.after_ops) {
        HVT_LOG(WARNING, rank_)
            << "HVT_FAULT_INJECT: dropping all engine connections";
        fault_ = FaultSpec{};  // fire once
        if (data_) data_->Abort();
        if (control_) control_->Abort();
        for (auto& s : workers_)
          if (s) s->Abort();
        if (tree_parent_) tree_parent_->Abort();
        for (auto& [child, s] : tree_child_socks_) {
          (void)child;
          s->Abort();
        }
      }
      break;
    case FaultKind::DELAY_MS:
      if (ops > fault_.after_ops && fault_.arg > 0)
        std::this_thread::sleep_for(
            std::chrono::milliseconds(fault_.arg));
      break;
    case FaultKind::FLAKY_CONN:
      // TRANSIENT: arm a mid-transfer cut on every data link (the
      // socket closes after 8 KB more tx — genuinely mid-collective)
      // and reset the upstream control link; the self-healing layer
      // reconnects + replays, and the collective completes
      // bit-identically with zero aborts.
      if (ops > fault_.after_ops && fault_.count > 0) {
        HVT_LOG(WARNING, rank_)
            << "HVT_FAULT_INJECT: flaky_conn cut (" << fault_.count
            << " left)";
        fault_.count--;
        fault_.after_ops = ops + 2;  // space successive injections
        for (TcpLink* l : hub_.links)
          if (l->plane() == LinkPlane::DATA) {
            l->InjectCutAfter(8192);
            // rx-side cut too: closing with unread kernel-buffered
            // data forces the peer through the replay ring
            l->InjectCutAfterRx(8192);
          }
        // cut the live upstream control link: control_ for star
        // workers AND tree leaders (their parent link to rank 0),
        // tree_parent_ for members. A tree MEMBER's control_ is the
        // reconnect-disabled parked side channel — cutting it would
        // just retire it, not exercise a heal.
        if (control_ && (!tree_mode_ || ctrl_role_ == CtrlRole::LEADER))
          control_->InjectCutNow();
        if (tree_parent_) tree_parent_->InjectCutNow();
      }
      break;
    case FaultKind::PARTITION:
      // TRANSIENT: cut every link crossing the A|B host boundary and
      // hold reconnects for ms=MS — heals by itself afterwards.
      if (ops > fault_.after_ops) {
        const std::string& my_host =
            topo_.host_of_rank[static_cast<size_t>(rank_)];
        int side = HostInList(fault_.hosts_a, my_host)   ? 0
                   : HostInList(fault_.hosts_b, my_host) ? 1
                                                         : -1;
        if (side >= 0) {
          const std::string& other =
              side == 0 ? fault_.hosts_b : fault_.hosts_a;
          HVT_LOG(WARNING, rank_)
              << "HVT_FAULT_INJECT: partitioning away from hosts "
              << other << " for " << fault_.arg << " ms";
          hub_.hold_until_ms = NowMs() + fault_.arg;
          for (int r = 0; r < size_; ++r)
            if (r != rank_ &&
                HostInList(other,
                           topo_.host_of_rank[static_cast<size_t>(r)]))
              CutLinksToRank(r);
        }
        fault_ = FaultSpec{};  // fire once
      }
      break;
    case FaultKind::RESET_STORM:
      // TRANSIENT: every_ops data ops, reset ONE data link
      // (round-robin) — a sustained connection-churn soak.
      if (fault_.every_ops > 0 && ops > 0 &&
          ops % fault_.every_ops == 0 && ops != fault_.after_ops) {
        fault_.after_ops = ops;  // last-fired marker
        std::vector<TcpLink*> dl;
        for (TcpLink* l : hub_.links)
          if (l->plane() == LinkPlane::DATA && l->valid())
            dl.push_back(l);
        if (!dl.empty()) {
          size_t pick = static_cast<size_t>(ops / fault_.every_ops) %
                        dl.size();
          HVT_LOG(WARNING, rank_)
              << "HVT_FAULT_INJECT: reset_storm cutting data link to "
              << "rank " << dl[pick]->peer_rank();
          dl[pick]->InjectCutNow();
        }
      }
      break;
    case FaultKind::NONE:
      break;
  }
}

int64_t Engine::ControlTimeoutMs(bool idle) const {
  // Idle-gang control frames flow every cycle regardless of user work,
  // so they double as heartbeats: bound them with the (typically much
  // shorter) HVT_HEARTBEAT_MS so a silently dead peer — SIGSTOP, kernel
  // hang, network partition — surfaces without waiting out the full op
  // deadline. With work outstanding the op deadline governs, since a
  // peer may legitimately be grinding a large data-plane transfer
  // between frames.
  if (idle && heartbeat_ms_ > 0) return heartbeat_ms_;
  return OpTimeoutMs();
}

// --------------------------------------------------------------------------
// hierarchical control plane (HVT_CTRL_TOPOLOGY=tree)
// --------------------------------------------------------------------------

// Derive the per-host leader election from the rendezvous topology and
// build the member↔leader links. The leader of a host is its lowest
// rank EXCLUDING rank 0: the root stays a pure coordinator, so its
// per-cycle fan-in is exactly the host count — even the ranks
// co-located with rank 0 reach it through their own leader. Leaders
// reuse their existing control-star socket as the parent link; only
// member→leader connections are new, with the leader ports exchanged
// over the star (the same rendezvous channel the data mesh used).
void Engine::SetupTreeControl(
    const std::vector<std::string>& endpoints,
    const std::vector<std::string>& topo_hosts) {
  std::map<std::string, std::vector<int>> by_host;
  for (int r = 0; r < size_; ++r)
    by_host[topo_hosts[static_cast<size_t>(r)]].push_back(r);
  int my_leader = -1;
  std::vector<int> my_members;
  std::vector<int> leaders;
  for (auto& [host, ranks] : by_host) {
    int leader = -1;
    for (int r : ranks)
      if (r != 0) {
        leader = r;
        break;
      }
    if (leader >= 0) leaders.push_back(leader);
    if (host == topo_hosts[static_cast<size_t>(rank_)]) {
      my_leader = leader;
      for (int r : ranks)
        if (r != 0 && r != leader) my_members.push_back(r);
    }
  }
  std::sort(leaders.begin(), leaders.end());
  if (rank_ == 0) {
    ctrl_role_ = CtrlRole::ROOT;
    ctrl_children_ = leaders;
  } else if (rank_ == my_leader) {
    ctrl_role_ = CtrlRole::LEADER;
    ctrl_children_ = my_members;
  } else {
    ctrl_role_ = CtrlRole::MEMBER;
    ctrl_children_.clear();
  }

  // leader control ports travel over the star: gather at rank 0, then
  // broadcast the full rank→port table. The leader listener is a
  // MEMBER (tree_listener_) and stays open so a dropped member link
  // can re-accept — the "leader re-accept" leg of the self-healing
  // control plane.
  bool listening = ctrl_role_ == CtrlRole::LEADER && !my_members.empty();
  tree_listener_.Close();
  if (listening) tree_listener_.Listen(0);
  std::vector<int32_t> ctrl_ports(size_, 0);
  if (rank_ == 0) {
    for (int r = 1; r < size_; ++r) {
      auto frame = workers_[static_cast<size_t>(r)]->RecvFrame();
      Reader rd(frame);  // Reader holds a reference — keep frame alive
      ctrl_ports[static_cast<size_t>(r)] = rd.i32();
    }
    Writer w;
    for (auto p : ctrl_ports) w.i32(p);
    for (int r = 1; r < size_; ++r)
      workers_[static_cast<size_t>(r)]->SendFrame(w.buf);
  } else {
    Writer w;
    w.i32(listening ? static_cast<int32_t>(tree_listener_.port()) : 0);
    control_->SendFrame(w.buf);
    auto frame = control_->RecvFrame();
    Reader rd(frame);  // see above
    for (auto& p : ctrl_ports) p = rd.i32();
  }

  if (ctrl_role_ == CtrlRole::MEMBER) {
    const std::string& ep = endpoints[static_cast<size_t>(my_leader)];
    std::string host = ep.substr(0, ep.rfind(':'));
    int lport = ctrl_ports[static_cast<size_t>(my_leader)];
    Sock raw = Sock::Connect(host, lport);
    int32_t me = rank_;
    raw.SendAll(&me, 4);
    tree_parent_ = std::make_unique<TcpLink>(
        std::move(raw), LinkPlane::CTRL, my_leader, &hub_, host, lport);
  } else if (listening) {
    for (size_t k = 0; k < my_members.size(); ++k) {
      Sock s = tree_listener_.Accept();
      int32_t who = -1;
      s.RecvAll(&who, 4);
      tree_child_socks_[who] = std::make_unique<TcpLink>(
          std::move(s), LinkPlane::CTRL, who, &hub_, "", 0,
          &tree_listener_);
    }
  }

  // Parked star links carry nothing but root-abort frames after this
  // point: a drop there must NOT spin up a reconnect against a peer
  // that will never handshake mid-cycle — the link is quietly retired
  // instead (the leader path still reaches every member).
  if (rank_ == 0) {
    std::set<int> kids(ctrl_children_.begin(), ctrl_children_.end());
    for (int r = 1; r < size_; ++r)
      if (!kids.count(r) && workers_[static_cast<size_t>(r)])
        workers_[static_cast<size_t>(r)]->SetReconnect(false);
  } else if (ctrl_role_ == CtrlRole::MEMBER && control_) {
    control_->SetReconnect(false);
  }
}

// --------------------------------------------------------------------------
// cycle loop
// --------------------------------------------------------------------------

void Engine::ThreadLoop() {
  // How long open-but-unprogressing negotiations keep the loop hot
  // before it decays to cycle_ms pacing (see below).
  const double grace_sec =
      static_cast<double>(EnvInt("HVT_SPIN_GRACE_MS", 250)) / 1e3;
  double last_progress = NowSec();
  while (true) {
    double t0 = NowSec();
    bool progressed = false;
    bool outstanding = false;
    try {
      if (!RunCycle(progressed, outstanding)) return;
    } catch (const RemoteAbortError& e) {
      EnterBroken(kAbortRemote, e.what());
      return;
    } catch (const HeartbeatLostError& e) {
      EnterBroken(kAbortHeartbeat, e.what());
      return;
    } catch (const OpTimeoutError& e) {
      EnterBroken(kAbortTimeout, e.what());
      return;
    } catch (const PeerLostError& e) {
      EnterBroken(kAbortPeerLost, e.what());
      return;
    } catch (const std::exception& e) {
      EnterBroken(kAbortInternal, std::string("hvt engine: ") + e.what());
      return;
    }
    double now = NowSec();
    stats_.cycle_hist.Observe(static_cast<int64_t>((now - t0) * 1e9));
    if (!event_driven_) {
      // legacy fixed-rate loop (HVT_EVENT_DRIVEN=0): every cycle pays
      // the full sleep even with work queued — the A/B baseline
      std::this_thread::sleep_for(std::chrono::milliseconds(cycle_ms_));
      continue;
    }
    if (progressed) last_progress = now;
    // Event-driven pacing: cycles run back-to-back while the engine is
    // progressing (draining submissions / executing responses), and —
    // within a grace window — while negotiations are still open
    // (pending_): an engine with open negotiations must keep
    // exchanging, since its peers cannot finish a cycle without its
    // frame, so one sleeping participant would pace the whole gang at
    // cycle_ms. The grace window bounds the failure mode where EVERY
    // rank has open-but-unmatchable work (e.g. crossed tensor names):
    // after HVT_SPIN_GRACE_MS without progress the loop decays to the
    // legacy cv-timeout pacing instead of spinning control frames at
    // full speed, and any real progress re-arms the window. Only a
    // fully idle engine sleeps immediately, and a Submit cuts every
    // sleep short: cycle_ms is the MAX coalescing wait, not a latency
    // floor.
    bool hot = progressed ||
               (outstanding && now - last_progress < grace_sec);
    if (hot || shutdown_requested_.load()) continue;
    CvLock lk(queue_mu_);
    queue_cv_.wait_for(lk.native(), std::chrono::milliseconds(cycle_ms_),
                       [&]() REQUIRES(queue_mu_) {  // see Wait's note
                         return !submitted_.empty() ||
                                shutdown_requested_.load();
                       });
  }
}

bool Engine::RunCycle(bool& progressed, bool& outstanding) {
  // a lane worker's failure surfaces here, at cycle granularity: the
  // rethrow reaches ThreadLoop's catch ladder with its abort class and
  // the usual EnterBroken containment runs (links aborted → remaining
  // workers fail fast → FailAll completes their entries)
  if (!lane_threads_.empty()) RethrowLanePoolError();
  stats_.cycles.fetch_add(1, std::memory_order_relaxed);
  if (timeline_.active() && timeline_.mark_cycles())
    timeline_.CycleMark();
  // 1. drain submissions
  {
    MutexLock lk(queue_mu_);
    if (!submitted_.empty()) {
      progressed = true;
      // wakeup latency: how long the oldest submission sat in the queue
      // before this cycle picked it up — the event-driven loop's
      // coalescing delay (≈ µs when signaled, ≤ cycle_ms worst case)
      double oldest = submitted_.front()->submit_sec;
      for (auto& e : submitted_)
        if (e->submit_sec > 0 && e->submit_sec < oldest)
          oldest = e->submit_sec;
      if (oldest > 0) {
        int64_t ns = static_cast<int64_t>((NowSec() - oldest) * 1e9);
        if (ns < 0) ns = 0;
        stats_.wakeup_hist.Observe(ns);
        events_.Record(EventKind::WAKEUP, "", -1,
                       static_cast<int32_t>(submitted_.size()), ns / 1000);
      }
      // per-lane head-of-line wait: how long each submission sat in
      // the client queue before the engine thread picked it up
      // (lane_hol_ns/lane_hol_count). Both ends are stamped on THIS
      // rank, so peers' submit skew and negotiation latency cannot
      // leak in: the wait grows only when this engine thread is busy —
      // which is exactly what a hot neighbor executing INLINE causes
      // and what the per-lane pool (HVT_LANE_WORKERS) removes. The
      // single-thread floor is the event-driven coalescing delay
      // (≤ cycle_ms) plus scheduler quanta.
      const double now_sec = NowSec();
      for (auto& e : submitted_) {
        if (e->op == OpType::JOIN || e->submit_sec <= 0) continue;
        int64_t ns =
            static_cast<int64_t>((now_sec - e->submit_sec) * 1e9);
        if (ns < 0) ns = 0;
        const int32_t ls = LaneSlot(LaneId(e->members));
        stats_.lane_hol_ns[ls].fetch_add(ns,
                                         std::memory_order_relaxed);
        stats_.lane_hol_count[ls].fetch_add(
            1, std::memory_order_relaxed);
      }
    }
    for (auto& e : submitted_) {
      if (e->op == OpType::JOIN) {
        if (join_pending_) {
          CompleteEntry(e, Status::InvalidArgument("join already pending"));
        } else {
          join_pending_ = true;
          join_entry_ = e;
        }
        continue;
      }
      if (pending_.count(e->name)) {
        // reference DUPLICATE_NAME_ERROR (common.h:165)
        CompleteEntry(
            e, Status::InvalidArgument(
                   "a tensor named '" + e->name +
                   "' is already pending; names must be unique per cycle"));
        continue;
      }
      if (lanes_seen_.insert(LaneId(e->members)).second)
        stats_.lanes_active.store(
            static_cast<int64_t>(lanes_seen_.size()),
            std::memory_order_relaxed);
      pending_[e->name] = e;
    }
    submitted_.clear();
  }

  // 2. build the control frame
  uint8_t flags = 0;
  if (shutdown_requested_.load()) flags |= kCtrlFlagShutdown;
  if (join_pending_) flags |= kCtrlFlagJoin;
  std::vector<int64_t> hit_positions, invalid_positions;
  std::vector<Request> misses;
  for (auto& [name, e] : pending_) {
    if (announced_.count(name)) continue;
    Request r;
    r.rank = rank_;
    r.op = e->op;
    r.reduce = e->reduce;
    r.name = name;
    r.dtype = e->dtype;
    r.shape = e->shape;
    r.root_rank = e->root_rank;
    r.prescale = e->prescale;
    r.postscale = e->postscale;
    r.splits = e->splits;
    r.group_id = e->group_id;
    r.group_size = e->group_size;
    r.members = e->members;
    // Only ungrouped ALLREDUCE is cacheable: its execution params are
    // fully participant-symmetric. allgather/alltoall rows vary per
    // call and per rank; grouped tensors renegotiate as an atomic unit.
    // Process-set allreduces ARE cacheable since the per-set-lane
    // rework: CachedParams carries the member list, the fast path
    // requires exactly the cached members to announce the position, and
    // every rank (members and non-members alike) inserts in response
    // order so positions stay identical gang-wide. This is what lets a
    // steady-state serving replica skip negotiation entirely.
    bool cacheable = cache_enabled_.load() &&
                     e->op == OpType::ALLREDUCE && e->group_id < 0;
    int32_t pos = cacheable ? cache_.Lookup(r) : ResponseCache::kMiss;
    if (pos >= 0 && !join_pending_) {
      stats_.cache_hits.fetch_add(1, std::memory_order_relaxed);
      hit_positions.push_back(pos);
    } else {
      if (cacheable)
        stats_.cache_misses.fetch_add(1, std::memory_order_relaxed);
      if (pos == ResponseCache::kInvalid) {
        // params changed → the whole job must evict this entry before the
        // name can renegotiate (reference CacheCoordinator invalid bits)
        int32_t old = cache_.PositionOf(name);
        if (old >= 0) invalid_positions.push_back(old);
      }
      misses.push_back(r);
    }
    announced_.insert(name);
  }

  Announce mine;
  mine.rank = rank_;
  mine.flags = flags;
  mine.hits = std::move(hit_positions);
  mine.invalids = std::move(invalid_positions);
  mine.reqs = std::move(misses);
  // negotiation payload carried this cycle (vs a bare keepalive frame):
  // gates the CTRL_BYTES flight-recorder event below so idle heartbeat
  // cycles don't flood the ring. Coordinating ranks also flag cycles
  // where a REMOTE announce carried payload (a straggling negotiation
  // this rank isn't part of is still control-plane cost to attribute).
  bool did_negotiate = !mine.hits.empty() || !mine.invalids.empty() ||
                       !mine.reqs.empty();
  auto payload = [](const Announce& a) {
    return !a.hits.empty() || !a.invalids.empty() || !a.reqs.empty();
  };
  // deadline-bounded control recv: heartbeat pace when idle, op
  // deadline when work is outstanding — classified per peer. A
  // transient drop heals INSIDE RecvFrame (the self-healing link
  // reconnects + replays); only an escalated loss surfaces here, and
  // its reason (retry budget, replay budget, peer dead) rides along
  // into the abort.
  auto recv_ctrl = [&](TcpLink& s, int64_t ctl_ms, bool idle,
                       const std::string& who) {
    try {
      auto frame = s.RecvFrame(ctl_ms);
      // every control frame starts with a flags byte; a zero-length
      // frame is protocol corruption and must become a containment
      // abort, not an out-of-bounds Reader access at the decode site
      if (frame.empty())
        throw PeerLostError("empty control frame from " + who);
      return frame;
    } catch (const OpTimeoutError&) {
      if (idle && heartbeat_ms_ > 0 && ctl_ms == heartbeat_ms_)
        throw HeartbeatLostError(
            "no heartbeat from " + who + " for " +
            std::to_string(heartbeat_ms_) + " ms (HVT_HEARTBEAT_MS)");
      throw OpTimeoutError("no control frame from " + who + " within " +
                           std::to_string(ctl_ms) +
                           " ms (HVT_OP_TIMEOUT_MS)");
    } catch (const PeerLostError& e) {
      throw PeerLostError("control connection to " + who + " lost (" +
                          e.what() + ")");
    }
  };

  // 3. exchange over the control topology. ctl_tx/ctl_rx count this
  // cycle's control frame bytes on THIS rank's sockets (payload + the
  // 8-byte length prefix per frame) — each byte is counted exactly once
  // gang-wide, at the rank that moved it, so tree-mode aggregates are
  // never double-counted at the members they batch.
  int64_t ctl_tx = 0, ctl_rx = 0;
  std::vector<Response> responses;
  std::vector<int64_t> evictions;
  uint8_t resp_flags = 0;
  if (size_ == 1) {
    // initializer_list elements are const, so {std::move(mine)} would
    // silently deep-copy — push_back keeps the move a move
    std::vector<Announce> anns;
    anns.push_back(std::move(mine));
    responses = Coordinate(anns);
    StampWireCodecs(responses);
    resp_flags = rank_shutdown_[0] ? kRespFlagShutdown : 0;
  } else if (ctrl_role_ == CtrlRole::ROOT) {
    // root: one frame per child — every rank in star mode, one LEADER
    // per host in tree mode (each frame covering its whole subtree).
    // Any frame may be an ABORT from a failing peer (checked first).
    std::vector<Announce> anns;
    anns.reserve(static_cast<size_t>(size_));
    anns.push_back(std::move(mine));
    bool idle = pending_.empty() && !join_pending_ && counts_.empty();
    int64_t ctl_ms = ControlTimeoutMs(idle);
    for (int child : ctrl_children_) {
      auto frame = recv_ctrl(*workers_[static_cast<size_t>(child)],
                             ctl_ms, idle,
                             "rank " + std::to_string(child));
      if (IsAbortFrame(frame))
        throw RemoteAbortError(ParseAbortFrame(frame));
      ctl_rx += static_cast<int64_t>(frame.size()) + kFramePrefixBytes;
      Reader rd(frame);
      if (frame[0] & kCtrlFlagAggregate) {
        rd.u8();
        for (auto& a : DecodeAggregateFrame(rd)) {
          did_negotiate = did_negotiate || payload(a);
          anns.push_back(std::move(a));
        }
      } else {
        Announce a = DecodeAnnounceFrame(rd, child);
        did_negotiate = did_negotiate || payload(a);
        anns.push_back(std::move(a));
      }
    }
    responses = Coordinate(anns);
    StampWireCodecs(responses);
    bool all_down = true;
    for (bool b : rank_shutdown_)
      all_down = all_down && b;
    resp_flags = all_down ? kRespFlagShutdown : 0;
    // evictions gathered by Coordinate into pending_evictions_.
    // Broadcast the (possibly autotuned) cycle time and cache/backend
    // flags — the analog of Controller::SynchronizeParameters
    // (controller.cc:39-53). The flags apply on every rank at THIS
    // frame boundary (rank 0 below, workers on receipt), so the next
    // cycle's cache lookups and this cycle's backend picks stay
    // rank-identical. Steady-state bypass: when every response this
    // cycle came off the cache fast path, broadcast the POSITIONS and
    // let each rank rebuild the responses from its own (identical)
    // cache — response bytes then stop scaling with per-name payload.
    // auto mode can stamp per-response codec pairs; a positions-form
    // frame carries exactly ONE pair, so a non-uniform cycle must ship
    // full responses instead. That happens while the tuner explores,
    // and permanently when locked per-size-bucket picks diverge within
    // one cycle (a small and a large cross-host allreduce coordinated
    // together whose buckets locked different codecs) — the known cost
    // of keeping the PR 8 one-pair frame format; fixed pairs and
    // single-pick workloads always bypass. Intra-only responses are
    // stamped raw and excluded from the uniformity check, so they
    // never veto the bypass.
    bool bypass =
        ctrl_bypass_ && coordinate_pure_fastpath_ && stamp_uniform_;
    Writer out;
    out.u8(bypass
               ? static_cast<uint8_t>(resp_flags | kRespFlagPositions)
               : resp_flags);
    out.i32(static_cast<int32_t>(cycle_ms_));
    out.u8(static_cast<uint8_t>((tuned_cache_enabled_ ? 1 : 0) |
                                (tuned_prefer_flat_ ? 2 : 0)));
    out.i64vec(pending_evictions_);
    if (bypass) {
      out.u8(stamped_intra_);
      out.u8(stamped_inter_);
      // workers re-run FuseResponses on the rebuilt list, so the
      // (possibly autotuned) fusion threshold must ride along or the
      // fused units could diverge across ranks
      out.i64(fusion_threshold_);
      out.i64vec(fastpath_positions_);
      stats_.ctrl_bypass_cycles.fetch_add(1, std::memory_order_relaxed);
    } else {
      EncodeResponseList(out, responses);
    }
    for (int child : ctrl_children_)
      workers_[static_cast<size_t>(child)]->SendFrame(out.buf);
    ctl_tx += (static_cast<int64_t>(out.buf.size()) +
               kFramePrefixBytes) *
              static_cast<int64_t>(ctrl_children_.size());
    cache_enabled_ = tuned_cache_enabled_;
    prefer_flat_ = tuned_prefer_flat_;
    evictions = std::move(pending_evictions_);
    pending_evictions_.clear();
  } else if (ctrl_role_ == CtrlRole::LEADER) {
    // leader: gather the host's member announcements, batch them (plus
    // our own) into ONE deduplicated cross-host frame, and fan the
    // root's (identical-for-everyone) response frame back down.
    bool idle = pending_.empty() && !join_pending_;
    int64_t ctl_ms = ControlTimeoutMs(idle);
    std::vector<Announce> anns;
    bool subtree_payload = did_negotiate;
    for (int child : ctrl_children_) {
      auto frame = recv_ctrl(*tree_child_socks_[child], ctl_ms, idle,
                             "member rank " + std::to_string(child));
      if (IsAbortFrame(frame))
        throw RemoteAbortError(ParseAbortFrame(frame));
      ctl_rx += static_cast<int64_t>(frame.size()) + kFramePrefixBytes;
      Reader rd(frame);
      Announce a = DecodeAnnounceFrame(rd, child);
      subtree_payload = subtree_payload || payload(a);
      anns.push_back(std::move(a));
    }
    anns.push_back(std::move(mine));
    Writer agg;
    EncodeAggregateFrame(agg, anns);
    ctl_tx += static_cast<int64_t>(agg.buf.size()) + kFramePrefixBytes;
    control_->SendFrame(agg.buf);
    // a busy subtree keeps the response wait on the op deadline even
    // when this leader itself has nothing outstanding
    bool up_idle = idle && !subtree_payload;
    auto frame = recv_ctrl(*control_, ControlTimeoutMs(up_idle), up_idle,
                           "rank 0 (coordinator)");
    if (IsAbortFrame(frame))
      throw RemoteAbortError(ParseAbortFrame(frame));
    ctl_rx += static_cast<int64_t>(frame.size()) + kFramePrefixBytes;
    for (int child : ctrl_children_)
      tree_child_socks_[child]->SendFrame(frame);
    ctl_tx += (static_cast<int64_t>(frame.size()) + kFramePrefixBytes) *
              static_cast<int64_t>(ctrl_children_.size());
    did_negotiate = subtree_payload;
    DecodeResponseFrame(frame, responses, evictions, resp_flags);
  } else {
    // member: one announce up (a bitmask vote when the cycle is pure
    // cache hits), one response frame down. The upstream peer is the
    // host leader in tree mode, rank 0 in star mode.
    TcpLink& up = tree_mode_ ? *tree_parent_ : *control_;
    const std::string peer =
        tree_mode_ ? "the host leader" : "rank 0 (coordinator)";
    // Tree members park their star socket after init; the only frame
    // rank 0 ever sends on it afterwards is an ABORT. Poll it
    // nonblocking each cycle so a root abort reaches this member even
    // when its leader is wedged (stalled, not dead — a dead leader's
    // FIN surfaces through tree_parent_ immediately). A member already
    // blocked waiting on a wedged leader converges at its own control
    // deadline instead. The parked link is reconnect-disabled (see
    // SetupTreeControl): a drop here retires the side channel quietly
    // rather than spinning a reconnect nobody will answer.
    if (tree_mode_ && control_ && control_->valid() &&
        control_->fd() >= 0) {
      struct pollfd pd {control_->fd(), POLLIN, 0};
      if (::poll(&pd, 1, 0) > 0) {
        try {
          auto f = control_->RecvFrame(1000);
          if (IsAbortFrame(f))
            throw RemoteAbortError(ParseAbortFrame(f));
        } catch (const PeerLostError&) {
          control_->Abort();  // side channel gone; leader path remains
        }
      }
    }
    Writer w;
    EncodeAnnounceFrame(w, mine, ctrl_bypass_);
    ctl_tx += static_cast<int64_t>(w.buf.size()) + kFramePrefixBytes;
    up.SendFrame(w.buf);
    bool idle = pending_.empty() && !join_pending_;
    auto frame = recv_ctrl(up, ControlTimeoutMs(idle), idle, peer);
    if (IsAbortFrame(frame))
      throw RemoteAbortError(ParseAbortFrame(frame));
    ctl_rx += static_cast<int64_t>(frame.size()) + kFramePrefixBytes;
    DecodeResponseFrame(frame, responses, evictions, resp_flags);
  }
  if (ctl_tx || ctl_rx) {
    stats_.ctrl_tx_bytes.fetch_add(ctl_tx, std::memory_order_relaxed);
    stats_.ctrl_rx_bytes.fetch_add(ctl_rx, std::memory_order_relaxed);
    // per-cycle attribution event — only for cycles that did real work
    // (see EventKind::CTRL_BYTES on why idle keepalives are excluded);
    // op carries this rank's CtrlRole so hvt_analyze can attribute the
    // tree's leader hop separately from root/member traffic
    if (did_negotiate || !responses.empty())
      events_.Record(EventKind::CTRL_BYTES, "",
                     static_cast<int32_t>(ctrl_role_),
                     static_cast<int32_t>(ctl_tx), ctl_rx);
  }

  // 4. apply evictions (cache must stay identical on every rank)
  for (int64_t pos : evictions) {
    if (pos < 0) continue;
    std::string nm = cache_.EvictPosition(static_cast<int32_t>(pos));
    // only re-announce names that are still pending (unexecuted)
    if (!nm.empty() && pending_.count(nm)) announced_.erase(nm);
  }

  // 5. execute. With the per-lane pool active (HVT_LANE_WORKERS),
  // eligible set-lane allreduces are handed to worker threads — a hot
  // tenant's data-plane time no longer head-of-line-blocks its
  // neighbors within this rank. Everything else quiesces the pool
  // first (LaneBarrier) and runs inline with single-thread semantics;
  // non-member skips run inline WITHOUT a barrier (pure cache
  // bookkeeping, but it must advance in response order).
  for (auto& resp : responses) {
    bool tensor = resp.kind == Response::Kind::TENSOR;
    bool nonmember_skip = false;
    if (!lane_threads_.empty()) {
      RethrowLanePoolError();
      if (tensor && !resp.members.empty()) {
        bool mine = false;
        std::vector<int> grp;
        for (auto mr : resp.members) {
          grp.push_back(static_cast<int>(mr));
          mine = mine || mr == rank_;
        }
        // non-member set-lane responses are pure cache bookkeeping —
        // no data plane touched — so they fall through to the inline
        // path (keeping its EXEC events and exec_ns/exec_count stats
        // identical to the pool-off build) WITHOUT quiescing the
        // pool: they must advance in response order, not serialize
        // against the workers
        nonmember_skip = !mine;
        if (mine && LanePoolEligible(resp, grp, mine)) {
          auto t = std::make_shared<LaneTask>();
          t->resp = resp;
          ++resp_seq_;
          t->seq = resp_seq_;
          data_ops_++;
          MaybeInjectFault();
          const size_t el_d = DataTypeSize(resp.dtype);
          t->entries.resize(resp.names.size());
          for (size_t i = 0; i < resp.names.size(); ++i) {
            auto it = pending_.find(resp.names[i]);
            if (it == pending_.end()) continue;
            t->entries[i] = it->second;
            pending_.erase(it);
            announced_.erase(resp.names[i]);
            // in-flight until CompleteEntry: a worker throw leaves
            // the entry for FailAll, exactly like the inline path
            MutexLock lk(handles_mu_);
            inflight_.push_back(t->entries[i]);
          }
          stats_.tensors_coordinated.fetch_add(
              static_cast<int64_t>(resp.names.size()),
              std::memory_order_relaxed);
          for (int64_t n : resp.numels) {
            cycle_bytes_ += n * static_cast<int64_t>(el_d);
            stats_.fusion_bytes.fetch_add(
                n * static_cast<int64_t>(el_d),
                std::memory_order_relaxed);
          }
          // cache inserts stay on the engine thread IN RESPONSE ORDER
          // (positions must be identical gang-wide); doing them at
          // dispatch instead of post-exec keeps one order for pooled
          // and inline responses alike
          if (CacheableResponse(resp)) {
            for (size_t i = 0; i < resp.names.size(); ++i) {
              if (!t->entries[i]) continue;
              CachedParams p{resp.op,      resp.reduce,
                             resp.dtype,   t->entries[i]->shape,
                             resp.root,    resp.prescale,
                             resp.postscale, t->entries[i]->splits,
                             resp.members};
              cache_.Insert(resp.names[i], p);
            }
          }
          DispatchLaneTask(std::move(t));
          continue;
        }
      }
      if (!nonmember_skip) LaneBarrier();
    }
    bool trace = timeline_.active() && tensor;
    if (trace)
      for (auto& n : resp.names)
        timeline_.ExecuteStart(n, OpName(resp.op));
    int32_t resp_lane = LaneSlot(LaneId(resp.members));
    if (tensor) {
      int32_t op_w = static_cast<int32_t>(resp.op);
      int64_t fused_n = static_cast<int64_t>(resp.names.size());
      for (auto& n : resp.names) {
        if (fused_n > 1)
          events_.Record(EventKind::FUSED, n, op_w, rank_, fused_n,
                         resp_lane);
        events_.Record(EventKind::EXEC_BEGIN, n, op_w, rank_, 0,
                       resp_lane);
      }
    }
    double exec_t0 = tensor ? NowSec() : 0;
    ExecuteResponse(resp, pending_);
    if (tensor) {
      int op_i = static_cast<int>(resp.op);
      int64_t exec_ns = static_cast<int64_t>((NowSec() - exec_t0) * 1e9);
      if (op_i >= 0 && op_i < kStatsOps) {
        stats_.exec_ns[op_i].fetch_add(exec_ns,
                                       std::memory_order_relaxed);
        stats_.exec_count[op_i].fetch_add(1, std::memory_order_relaxed);
      }
      // lane attribution: which process set this response served (the
      // hvt_lane_exec_* metrics behind the serving-gang dashboards).
      // Members only — a skipped response's ~0 ns entry would dilute
      // the lane's mean latency on every non-member rank
      bool mine = resp.members.empty();
      for (auto mr : resp.members) mine = mine || mr == rank_;
      if (mine) {
        int lslot = LaneSlot(LaneId(resp.members));
        stats_.lane_exec_ns[lslot].fetch_add(exec_ns,
                                             std::memory_order_relaxed);
        stats_.lane_exec_count[lslot].fetch_add(
            1, std::memory_order_relaxed);
      }
      for (auto& n : resp.names)
        events_.Record(EventKind::EXEC_END, n,
                       static_cast<int32_t>(resp.op), rank_, 0,
                       resp_lane);
      // auto-mode feedback: rank 0 credits the executed codec with this
      // response's wall time so the CodecTuner's per-(size, link) cells
      // converge on the fastest codec for live traffic. Intra-only
      // groups are skipped to mirror StampWireCodecs — no inter hop ran,
      // so their timing must not train the inter-codec cells. Members
      // only, like the lane stats above: a process set that excludes
      // rank 0 executes here in ~µs (the skip path), and that phantom
      // throughput would lock the tuner onto an arbitrary codec.
      if (rank_ == 0 && mine && wire_auto_ && WireEligible(resp)) {
        std::vector<int> wgrp;
        for (auto mr : resp.members) wgrp.push_back(static_cast<int>(mr));
        if (GroupSpansHosts(topo_, wgrp)) {
          int64_t bytes = 0;
          for (auto nn : resp.numels) bytes += nn * 4;
          codec_tuner_.Observe(bytes, /*link=*/1,
                               static_cast<WireCodec>(resp.wire_inter),
                               exec_ns);
        }
      }
    }
    if (trace)
      for (auto& n : resp.names) timeline_.ExecuteEnd(n);
  }
  if (!responses.empty()) {
    progressed = true;
    events_.Record(EventKind::CYCLE, "", -1,
                   static_cast<int32_t>(responses.size()), 0);
  }

  // feed the autotuner with this cycle's throughput (rank 0 tunes;
  // reference operations.cc:610-642 feeds the ParameterManager the same
  // way); tuned values apply next cycle
  if (rank_ == 0 && autotune_.active() &&
      autotune_.Record(cycle_bytes_)) {
    fusion_threshold_ = autotune_.fusion_threshold();
    cycle_ms_ = autotune_.cycle_ms();
    tuned_cache_enabled_ = autotune_.cache_enabled();
    tuned_prefer_flat_ = autotune_.prefer_flat();
    if (size_ == 1) {
      cache_enabled_ = tuned_cache_enabled_;
      prefer_flat_ = tuned_prefer_flat_;
    }
    HVT_LOG(DEBUG, rank_) << "autotune sample " << autotune_.samples()
                          << ": fusion " << (fusion_threshold_ >> 20)
                          << " MB, cycle " << cycle_ms_ << " ms";
  }
  cycle_bytes_ = 0;

  if (rank_ == 0) CheckStalls();
  UpdateLaneDepths();
  UpdateDiag();

  if (resp_flags & kRespFlagShutdown) {
    // coordinated shutdown: quiesce the lane pool (its in-flight
    // collectives must complete — every member executes the same
    // stream), then drain anything left as errors
    LaneBarrier();
    for (auto& [n, e] : pending_)
      CompleteEntry(e, Status::Aborted("hvt shut down"));
    pending_.clear();
    announced_.clear();
    return false;
  }
  // open negotiations keep the cycle loop hot — within the grace
  // window (see ThreadLoop)
  outstanding = !pending_.empty() || join_pending_;
  return true;
}

// Per-lane pending-depth gauges, refreshed once per cycle from the
// engine-thread-only pending table (cheap: pending_ is small between
// executions; the serving autoscaler reads these through
// hvt_engine_stats → hvt_lane_depth{lane=...}).
void Engine::UpdateLaneDepths() {
  int64_t depth[kLaneSlots] = {};
  for (auto& [name, e] : pending_) depth[LaneSlot(LaneId(e->members))]++;
  for (int i = 0; i < kLaneSlots; ++i)
    stats_.lane_depth[i].store(depth[i], std::memory_order_relaxed);
}

// --------------------------------------------------------------------------
// coordinator (rank 0)
// --------------------------------------------------------------------------

std::string Engine::NegotiationKey(const std::string& name,
                                   const std::vector<int64_t>& members) {
  // different process sets may legitimately reuse a tensor name (each
  // rank belongs to at most one of them for a given name — its local
  // pending table dedups by name), so the key carries the member list
  if (members.empty()) return name;
  std::string k = name;
  k += '\x01';
  for (auto mr : members) {
    k += std::to_string(mr);
    k += ',';
  }
  return k;
}

// Fold rank `r`'s cached-hit announcement for `pos` into the slow-path
// negotiation of the tensor cached there, as if the rank had announced a
// full Request with the cached params (a hit certifies its params matched
// the cache at announce time). This is the liveness valve for MIXED
// hit/miss states: the cache-enabled flag is applied at a frame boundary
// on every rank, but two ranks can announce the SAME tensor in frames on
// opposite sides of an autotuner flip — one as a hit, one as a miss.
// Without folding, the hit waits for all-ranks-hit and the miss waits for
// all-ranks-request, and both starve forever (observed as the
// test_autotune_engine_integration stall: rank 0 wedged 60 s on g1).
// The reference's CacheCoordinator avoids the state by synchronizing hit
// bitvectors before acting (response_cache.cc); we reconcile instead.
void Engine::HitToArrival(int r, int64_t pos, double now_sec) {
  const CachedParams* p = cache_.ParamsAt(static_cast<int32_t>(pos));
  if (!p) return;  // position already evicted; the eviction broadcast
                   // re-opened the name on rank r, which re-announces a
                   // plain miss next cycle
  const std::string& name = cache_.NameAt(static_cast<int32_t>(pos));
  Request q;
  q.rank = r;
  q.op = p->op;
  q.reduce = p->reduce;
  q.name = name;
  q.dtype = p->dtype;
  q.shape = p->shape;
  q.root_rank = p->root_rank;
  q.prescale = p->prescale;
  q.postscale = p->postscale;
  q.splits = p->splits;
  q.members = p->members;
  // the negotiation key carries the cached entry's process set, so a
  // folded hit lands in the same lane-scoped entry as plain requests
  RegisterArrival(NegotiationKey(name, p->members), r, std::move(q),
                  now_sec);
}

// Single home of the negotiation-arrival bookkeeping, shared by the
// request loop and the hit-fold path so the two can never diverge.
// Returns false when the rank was already counted for this key.
bool Engine::RegisterArrival(const std::string& key, int r, Request q,
                             double now_sec) {
  auto& tc = counts_[key];
  if (tc.seen.empty()) tc.seen.assign(size_, false);
  if (tc.seen[r]) return false;
  tc.seen[r] = true;
  if (tc.first_seen_sec == 0) tc.first_seen_sec = now_sec;
  if (timeline_.active()) {
    if (tc.count == 0) timeline_.NegotiateStart(q.name, OpName(q.op));
    timeline_.NegotiateRankReady(q.name, r);
  }
  int32_t lane = LaneSlot(LaneId(q.members));
  if (tc.count == 0)
    events_.Record(EventKind::NEGOTIATE_BEGIN, q.name,
                   static_cast<int32_t>(q.op), r, 0, lane);
  events_.Record(EventKind::RANK_READY, q.name,
                 static_cast<int32_t>(q.op), r, 0, lane);
  tc.requests.push_back(std::move(q));
  tc.count++;
  return true;
}

// The coordinator core consumes per-rank Announce structs — the SAME
// structs whether they arrived as star frames, bitmask votes, or
// tree-mode leader aggregates — so every control topology negotiates
// through identical logic and produces identical response streams.
std::vector<Response> Engine::Coordinate(
    const std::vector<Announce>& anns) {
  std::vector<Response> out;
  double now = NowSec();
  fastpath_positions_.clear();
  coordinate_pure_fastpath_ = false;

  // Iterate in RANK order regardless of arrival order: tree-mode
  // aggregates deliver announces in subtree order, and order-sensitive
  // bookkeeping (last_join_rank_ when two ranks join in one cycle, the
  // first-announcer request a negotiation entry is keyed from) must
  // match the star baseline exactly or the two topologies would
  // diverge on identical workloads.
  std::vector<const Announce*> by_rank(anns.size());
  for (size_t i = 0; i < anns.size(); ++i) by_rank[i] = &anns[i];
  std::sort(by_rank.begin(), by_rank.end(),
            [](const Announce* a, const Announce* b) {
              return a->rank < b->rank;
            });
  for (const Announce* ann_p : by_rank) {
    const Announce& ann = *ann_p;
    int r = ann.rank;
    if (r < 0 || r >= size_) continue;  // corrupt aggregate entry
    uint8_t flags = ann.flags;
    rank_shutdown_[r] = rank_shutdown_[r] || (flags & kCtrlFlagShutdown);
    bool joined = (flags & kCtrlFlagJoin) != 0;
    if (joined && !rank_joined_[r])
      last_join_rank_ = r;  // join order is observed here, cycle by cycle
    rank_joined_[r] = joined;
    const auto& hits = ann.hits;
    const auto& invalids = ann.invalids;
    const auto& reqs = ann.reqs;
    for (auto pos : hits) {
      // mixed hit/miss reconciliation, hit-after-miss direction: the
      // tensor cached at `pos` is already in slow-path negotiation
      // (some rank announced it as a miss), so fold this hit into that
      // negotiation instead of parking it on the fast path it can
      // never complete
      const CachedParams* cp = cache_.ParamsAt(static_cast<int32_t>(pos));
      if (cp && counts_.count(NegotiationKey(
                    cache_.NameAt(static_cast<int32_t>(pos)),
                    cp->members)))
        HitToArrival(r, pos, now);
      else
        hit_pending_[r].insert(pos);
    }
    for (auto pos : invalids)
      if (pos >= 0) pending_evictions_.push_back(pos);
    for (auto& q : reqs) {
      // negotiation state is keyed by (name, process set) — see
      // NegotiationKey
      std::string ck = NegotiationKey(q.name, q.members);
      if (!RegisterArrival(ck, r, q, now)) continue;
      // miss-after-hit direction: other ranks may have announced this
      // tensor as a cached hit in an earlier frame (before an autotuner
      // cache flip, or with a since-diverged param set). Fold those hits
      // into this fresh negotiation — only when the cached entry belongs
      // to the SAME lane (a different set's same-name entry resolves
      // through kInvalid eviction instead); param disagreements then
      // surface as BuildResponse errors instead of a starved protocol.
      {
        int32_t cpos = cache_.PositionOf(q.name);
        const CachedParams* cp =
            cpos >= 0 ? cache_.ParamsAt(cpos) : nullptr;
        if (cp && cp->members == q.members)
          for (int r2 = 0; r2 < size_; ++r2)
            if (hit_pending_[r2].erase(cpos)) HitToArrival(r2, cpos, now);
      }
    }
  }

  int active = 0;
  for (int r = 0; r < size_; ++r)
    if (!rank_joined_[r]) active++;

  // cross-set conflict check: the same tensor name pending under two
  // DIFFERENT process sets that share a rank means the ranks disagree on
  // the set — deliver a per-tensor ERROR instead of letting both
  // negotiations starve (disjoint sets may legitimately reuse names).
  // The ERROR fires only once every active member of every conflicting
  // entry has announced the name — earlier, a member whose submission is
  // still in its local queue would miss the broadcast (the response
  // targets pending entries) and its entry would starve instead.
  {
    auto overlap = [&](const std::vector<int64_t>& a,
                       const std::vector<int64_t>& b) {
      if (a.empty() || b.empty()) return true;  // global overlaps any set
      size_t i = 0, j = 0;
      while (i < a.size() && j < b.size()) {
        if (a[i] == b[j]) return true;
        if (a[i] < b[j]) ++i; else ++j;
      }
      return false;
    };
    bool any_sets = false;
    for (auto& [k, tc] : counts_)
      if (k.find('\x01') != std::string::npos) {
        any_sets = true;
        break;
      }
    std::map<std::string, std::vector<std::string>> by_name;
    if (any_sets)  // common no-process-set path pays nothing
      for (auto& [k, tc] : counts_)
        by_name[tc.requests[0].name].push_back(k);
    std::set<std::string> conflicted;
    struct ConflictErr {
      std::string name;
      std::vector<int64_t> members;  // union; empty → all ranks
    };
    std::vector<ConflictErr> errs;
    for (auto& [nm, keys] : by_name) {
      if (keys.size() < 2) continue;
      std::set<std::string> cand;
      for (size_t i = 0; i < keys.size(); ++i)
        for (size_t j = i + 1; j < keys.size(); ++j) {
          const Request& a = counts_[keys[i]].requests[0];
          const Request& b = counts_[keys[j]].requests[0];
          if (overlap(a.members, b.members)) {
            cand.insert(keys[i]);
            cand.insert(keys[j]);
          }
        }
      if (cand.empty()) continue;
      std::vector<bool> seen_any(size_, false);
      for (auto& k : keys) {
        auto& tc = counts_[k];
        for (int r = 0; r < size_; ++r)
          seen_any[r] = seen_any[r] || (r < static_cast<int>(
                                            tc.seen.size()) && tc.seen[r]);
      }
      bool covered = true;
      for (auto& k : cand) {
        const auto& mem = counts_[k].requests[0].members;
        if (mem.empty()) {
          for (int r = 0; r < size_; ++r)
            if (!rank_joined_[r]) covered = covered && seen_any[r];
        } else {
          for (auto mr : mem)
            if (mr >= 0 && mr < size_ && !rank_joined_[mr])
              covered = covered && seen_any[mr];
        }
      }
      if (!covered) continue;  // wait for stragglers to announce
      conflicted.insert(cand.begin(), cand.end());
      // the ERROR must reach exactly the conflicted entries' members —
      // an innocent disjoint set reusing the name keeps its entry (its
      // members are disjoint from every conflicted entry by
      // construction, so rank-level targeting is entry-level targeting)
      std::set<int64_t> uni;
      bool global = false;
      for (auto& k : cand) {
        const auto& mem = counts_[k].requests[0].members;
        if (mem.empty()) global = true;
        for (auto mr : mem) uni.insert(mr);
      }
      ConflictErr ce;
      ce.name = nm;
      if (!global)
        ce.members.assign(uni.begin(), uni.end());
      errs.push_back(std::move(ce));
    }
    // a conflicted member of a fusion group poisons the group — sibling
    // members held in groups_ must error out, not starve. Aggregate by
    // group first: a conflicted NAME appears under one key per
    // disagreeing set, but occupies only ONE group slot.
    std::map<int32_t, std::pair<int, std::set<std::string>>> gconf;
    for (auto& k : conflicted) {
      const Request& cq = counts_[k].requests[0];
      if (cq.group_id >= 0 && cq.group_size > 0) {
        auto& e = gconf[cq.group_id];
        e.first = cq.group_size;
        e.second.insert(cq.name);
      }
      counts_.erase(k);
    }
    for (auto& [gid, info] : gconf) {
      auto& gs = groups_[gid];
      gs.expected = info.first;
      if (!gs.poisoned) {
        gs.poisoned = true;
        gs.error = "a member of fusion group " + std::to_string(gid) +
                   " was submitted with conflicting process sets across "
                   "ranks (group aborted)";
      }
      for (auto& [n2, r2] : gs.held) {
        Response err;
        err.kind = Response::Kind::ERROR;
        err.names = r2.names;
        err.members = r2.members;
        err.error = gs.error;
        out.push_back(std::move(err));
        gs.released++;
      }
      gs.held.clear();
      // one slot per conflicted tensor name (errored via errs below)
      gs.released += static_cast<int>(info.second.size());
      if (gs.released >= gs.expected) groups_.erase(gid);
    }
    for (auto& ce : errs) {
      Response err;
      err.kind = Response::Kind::ERROR;
      err.names = {ce.name};
      err.members = ce.members;
      err.error = "tensor '" + ce.name + "' was submitted with "
                  "conflicting process sets across ranks";
      out.push_back(std::move(err));
    }
  }

  // JOIN: everyone joined → emit join response (workers drop their joined
  // flag after executing it; a duplicate response in the crossover cycle
  // is a harmless no-op)
  {
    bool all_joined = size_ > 0;
    for (int r = 0; r < size_; ++r)
      all_joined = all_joined && rank_joined_[r];
    if (all_joined) {
      Response j;
      j.kind = Response::Kind::JOIN;
      j.names = {"<join>"};
      // the actual last rank to join (reference Join semantics: callers
      // broadcast final state from it); several ranks joining within one
      // cycle tie-break by rank order deterministically
      j.root = last_join_rank_ >= 0 ? last_join_rank_ : size_ - 1;
      out.push_back(j);
    }
  }

  // cache fast path: positions every PARTICIPANT has pending. The
  // participant set is the cached entry's member list (the whole world
  // for the global lane) — a serving replica's steady-state traffic
  // completes here on the announcements of its own members alone,
  // without waiting on (or disturbing) any other lane.
  const size_t pre_fastpath = out.size();
  if (active == size_) {
    std::set<int64_t> candidates;
    for (auto& hp : hit_pending_)
      candidates.insert(hp.begin(), hp.end());
    std::vector<int64_t> ready;
    for (auto pos : candidates) {
      const CachedParams* p = cache_.ParamsAt(static_cast<int32_t>(pos));
      if (!p) {
        // evicted while announced: the eviction broadcast re-opened the
        // name on every announcing rank, which re-announces a miss —
        // drop the stale hit so it cannot linger forever
        for (auto& hp : hit_pending_) hp.erase(pos);
        continue;
      }
      bool all = true;
      if (p->members.empty()) {
        for (int r = 0; r < size_; ++r)
          all = all && hit_pending_[r].count(pos);
      } else {
        for (auto mr : p->members)
          all = all && mr >= 0 && mr < size_ &&
                hit_pending_[static_cast<size_t>(mr)].count(pos);
      }
      if (all) ready.push_back(pos);
    }
    for (auto pos : ready) {
      for (int r = 0; r < size_; ++r) hit_pending_[r].erase(pos);
      // single spelling shared with the worker-side positions rebuild
      // (ResponseCache::ResponseAt) — the steady-state bypass depends
      // on both sides producing byte-identical responses
      Response resp;
      if (!cache_.ResponseAt(static_cast<int32_t>(pos), &resp)) continue;
      fastpath_positions_.push_back(pos);
      out.push_back(std::move(resp));
    }
  } else {
    // Some rank joined: it will never announce its remaining tensors,
    // so the all-ranks-hit fast path above can never fire again. Fold
    // every outstanding hit into slow-path negotiation — its required
    // count excludes joined ranks — so cached tensors cannot starve
    // behind a join (reference JoinOp + CacheCoordinator interplay).
    for (int r = 0; r < size_; ++r) {
      std::set<int64_t> hp;
      hp.swap(hit_pending_[r]);
      for (auto pos : hp) HitToArrival(r, pos, now);
    }
  }

  // slow path: tensors every active participant announced (the global
  // set, or the request's process-set members). EVERY active participant
  // must be individually seen — a raw count would let announcements from
  // since-JOINED ranks (e.g. an async submit followed by join, or a
  // folded hit from the join branch above) stand in for active ranks
  // that never announced, firing a collective half its participants
  // haven't entered.
  std::vector<std::string> complete;
  for (auto& [name, tc] : counts_) {
    const auto& mem = tc.requests[0].members;
    bool all_seen = true;
    int required = 0;
    auto need = [&](int r2) {
      required++;
      all_seen = all_seen &&
                 (r2 < static_cast<int>(tc.seen.size()) && tc.seen[r2]);
    };
    if (mem.empty()) {
      for (int r2 = 0; r2 < size_; ++r2)
        if (!rank_joined_[r2]) need(r2);
    } else {
      for (auto mr : mem)
        if (mr >= 0 && mr < size_ && !rank_joined_[mr]) need(static_cast<int>(mr));
    }
    if (all_seen && required > 0) complete.push_back(name);
  }
  for (auto& name : complete) {
    auto& tc = counts_[name];
    if (timeline_.active()) timeline_.NegotiateEnd(tc.requests[0].name);
    events_.Record(EventKind::NEGOTIATE_END, tc.requests[0].name,
                   static_cast<int32_t>(tc.requests[0].op), tc.count, 0,
                   LaneSlot(LaneId(tc.requests[0].members)));
    Response resp = BuildResponse(tc.requests);
    int32_t gid = tc.requests[0].group_id;
    int32_t gsize = tc.requests[0].group_size;
    counts_.erase(name);
    if (gid < 0 || gsize <= 0 || resp.kind == Response::Kind::BARRIER) {
      out.push_back(std::move(resp));
      continue;
    }
    // group member: hold until every member of the group is globally
    // ready, then release adjacently (reference group_table semantics —
    // grouped_allreduce is all-or-nothing)
    auto& gs = groups_[gid];
    gs.expected = gsize;
    if (resp.kind == Response::Kind::ERROR && !gs.poisoned) {
      gs.poisoned = true;
      gs.error = resp.error + " (fusion group " + std::to_string(gid) +
                 " aborted)";
    }
    if (gs.poisoned) {
      // dissolve: error out held members and every later-arriving member
      // (use the held response's plain names + member targeting — the
      // map key may be the internal (name, set) negotiation key)
      for (auto& [n2, r2] : gs.held) {
        Response err;
        err.kind = Response::Kind::ERROR;
        err.names = r2.names;
        err.members = r2.members;
        err.error = gs.error;
        out.push_back(std::move(err));
        gs.released++;
      }
      gs.held.clear();
      if (resp.kind != Response::Kind::ERROR) {
        resp.kind = Response::Kind::ERROR;
        resp.error = gs.error;
      }
      out.push_back(std::move(resp));
      gs.released++;
    } else {
      resp.group_id = gid;
      gs.held.emplace(name, std::move(resp));
      if (static_cast<int>(gs.held.size()) + gs.released >= gs.expected) {
        for (auto& [n2, r2] : gs.held) {
          out.push_back(std::move(r2));
          gs.released++;
        }
        gs.held.clear();
      }
    }
    if (gs.released >= gs.expected)
      groups_.erase(gid);  // deregister on completion (operations.cc:622)
  }

  // Bypass eligibility: the cycle produced ONLY fast-path responses
  // (no errors, join, barrier, group releases, or slow-path builds) —
  // evaluated pre-fusion, since workers re-fuse the rebuilt list with
  // the same deterministic pass.
  coordinate_pure_fastpath_ =
      !fastpath_positions_.empty() && pre_fastpath == 0 &&
      out.size() == fastpath_positions_.size();
  FuseResponses(out);
  return out;
}

// Only fp32 non-Adasum TENSOR allreduces compress — the single gate
// shared by stamping, error feedback, and the auto tuner.
bool Engine::WireEligible(const Response& r) {
  return r.kind == Response::Kind::TENSOR &&
         r.op == OpType::ALLREDUCE && r.dtype == DataType::FLOAT32 &&
         r.reduce != ReduceKind::ADASUM;
}

// Stamp one uniform codec pair on every eligible response — workers
// rebuilding a positions-form frame (the broadcast carries rank 0's
// pair, so the stamp rule evaluates identically gang-wide), and the
// fixed-mode coordinator path via StampWireCodecs below.
void Engine::StampWireCodec(std::vector<Response>& responses,
                            uint8_t wire_intra, uint8_t wire_inter) {
  if (wire_intra == 0 && wire_inter == 0) return;
  for (auto& r : responses)
    if (WireEligible(r)) {
      r.wire_intra = wire_intra;
      r.wire_inter = wire_inter;
    }
}

// Coordinator-side stamping (rank 0 after Coordinate, and the size==1
// fast path). Fixed modes stamp the configured pair; auto mode asks
// the CodecTuner per response (size-bucketed, link-classed), recording
// whether the cycle ended uniform — the bypass frame can only carry
// one pair.
void Engine::StampWireCodecs(std::vector<Response>& responses) {
  stamp_uniform_ = true;
  stamped_intra_ = wire_intra_;
  stamped_inter_ = wire_inter_;
  if (!wire_auto_) {
    StampWireCodec(responses, wire_intra_, wire_inter_);
    return;
  }
  bool first = true;
  for (auto& r : responses) {
    if (!WireEligible(r)) continue;
    int64_t bytes = 0;
    for (auto n : r.numels) bytes += n * 4;
    std::vector<int> grp;
    for (auto m : r.members) grp.push_back(static_cast<int>(m));
    int link = GroupSpansHosts(topo_, grp) ? 1 : 0;
    // auto picks only the inter-host codec (EQuARX). A group confined
    // to one host has no inter hop, so the tuner must not be consulted
    // there — its exploration picks would never execute, yet they'd
    // break bypass uniformity and (via Observe) lock link-0 cells onto
    // codecs that never ran. The intra codec honors the pair spec:
    // "bf16,auto" keeps bf16 in-host; bare "auto" parses intra as raw.
    // Intra-only responses also sit OUT of the uniformity accounting:
    // their wire_inter is never resolved (ResolveLinkCodec/EffectiveWire
    // take the intra class), so the forced 0 differing from a locked
    // inter pick must not veto the steady-state bypass — a workload
    // mixing single-host process-set ops with cross-host ops would
    // otherwise never regain the positions form after the tuner locks.
    uint8_t pick = 0;
    if (link != 0) {
      pick = static_cast<uint8_t>(codec_tuner_.Pick(bytes, link));
      if (first) {
        stamped_inter_ = pick;
        first = false;
      } else if (pick != stamped_inter_) {
        stamp_uniform_ = false;
      }
      wire_cur_inter_.store(pick, std::memory_order_relaxed);
    }
    r.wire_intra = wire_intra_;
    r.wire_inter = pick;
  }
}

// Worker-side decode of a rank-0→worker response frame — the full form
// (EncodeResponseList) or the steady-state positions form
// (kRespFlagPositions), which rebuilds the coordinator's response list
// from this rank's own cache. Shared by star workers, tree members,
// and tree leaders, and applies the frame-synchronized cycle/cache/
// backend parameters as a side effect.
void Engine::DecodeResponseFrame(const std::vector<uint8_t>& frame,
                                 std::vector<Response>& responses,
                                 std::vector<int64_t>& evictions,
                                 uint8_t& resp_flags) {
  Reader rd(frame);
  uint8_t first = rd.u8();
  resp_flags = static_cast<uint8_t>(first & ~kRespFlagPositions);
  int tuned_cycle = rd.i32();
  if (tuned_cycle > 0) cycle_ms_ = tuned_cycle;
  uint8_t tuned = rd.u8();
  cache_enabled_ = (tuned & 1) != 0;
  prefer_flat_ = (tuned & 2) != 0;
  evictions = rd.i64vec();
  if (first & kRespFlagPositions) {
    uint8_t wi = rd.u8();  // PR 8's synced-codec slot, grown to the pair
    uint8_t we = rd.u8();
    // adopt the coordinator's fusion threshold before re-fusing the
    // rebuilt list — local fusion must never diverge from rank 0's
    fusion_threshold_ = rd.i64();
    responses = ResponsesFromPositions(rd.i64vec(), wi, we);
    stats_.ctrl_bypass_cycles.fetch_add(1, std::memory_order_relaxed);
    wire_cur_intra_.store(wi, std::memory_order_relaxed);
    wire_cur_inter_.store(we, std::memory_order_relaxed);
  } else {
    responses = DecodeResponseList(rd);
    // mirror rank 0's stamps into this rank's reported pair — under
    // auto the env parse says (none, none) on workers, and an operator
    // debugging a stall via a worker's /debugz must see the codecs the
    // links actually move
    for (const auto& r : responses)
      if (WireEligible(r)) {
        wire_cur_intra_.store(r.wire_intra, std::memory_order_relaxed);
        wire_cur_inter_.store(r.wire_inter, std::memory_order_relaxed);
        break;
      }
  }
}

std::vector<Response> Engine::ResponsesFromPositions(
    const std::vector<int64_t>& positions, uint8_t wire_intra,
    uint8_t wire_inter) {
  std::vector<Response> out;
  out.reserve(positions.size());
  for (auto pos : positions) {
    Response r;
    if (!cache_.ResponseAt(static_cast<int32_t>(pos), &r))
      // caches are identical on every rank by construction; a missing
      // position means the sync invariant broke — fail loudly (the
      // engine maps this to a coordinated abort) instead of silently
      // skipping a collective the rest of the gang will run
      throw std::runtime_error(
          "hvt: positions-form response names cache position " +
          std::to_string(pos) +
          " which is not present locally (response-cache divergence)");
    out.push_back(std::move(r));
  }
  FuseResponses(out);
  StampWireCodec(out, wire_intra, wire_inter);
  return out;
}

Response Engine::BuildResponse(const std::vector<Request>& reqs) {
  // cross-rank consistency checks (reference controller.cc:481-706)
  const Request& a = reqs[0];
  Response resp;
  resp.names = {a.name};
  // ERROR responses must be member-targeted from the start: an
  // untargeted error would take a DISJOINT same-name set's pending
  // entries on innocent ranks and silently corrupt their collective
  // (zero stand-ins). All requests in one negotiation entry share the
  // same member list by construction — the counts key encodes it.
  resp.members = a.members;
  auto fail = [&](const std::string& why) {
    resp.kind = Response::Kind::ERROR;
    resp.error = why;
    return resp;
  };
  for (auto& q : reqs) {
    if (q.op != a.op)
      return fail("mismatched collective op for tensor '" + a.name + "'");
    if (q.dtype != a.dtype)
      return fail("mismatched dtype for tensor '" + a.name + "'");
    if (q.reduce != a.reduce)
      return fail("mismatched reduce op for tensor '" + a.name + "'");
    if (q.root_rank != a.root_rank)
      return fail("mismatched root rank for tensor '" + a.name + "'");
    if (q.prescale != a.prescale || q.postscale != a.postscale)
      return fail("mismatched scale factors for tensor '" + a.name + "'");
    if (q.group_id != a.group_id || q.group_size != a.group_size)
      return fail("mismatched fusion group for tensor '" + a.name +
                  "' (all ranks must submit grouped collectives with "
                  "identical membership)");
    // invariant guard — the negotiation key encodes the member list, so
    // per-entry requests cannot differ unless the keying changes
    if (q.members != a.members)
      return fail("mismatched process set for tensor '" + a.name +
                  "' (every participant must pass the same set)");
    bool shape_free_dim0 =
        a.op == OpType::ALLGATHER || a.op == OpType::ALLTOALL;
    if (shape_free_dim0) {
      if (q.shape.dims.size() != a.shape.dims.size())
        return fail("mismatched rank (ndims) for tensor '" + a.name + "'");
      for (size_t d = 1; d < a.shape.dims.size(); ++d)
        if (q.shape.dims[d] != a.shape.dims[d])
          return fail("mismatched non-leading dims for tensor '" + a.name +
                      "'");
    } else if (!(q.shape == a.shape)) {
      return fail("mismatched shape for tensor '" + a.name + "' (" +
                  q.shape.DebugString() + " vs " + a.shape.DebugString() +
                  ")");
    }
  }
  resp.kind = Response::Kind::TENSOR;
  resp.op = a.op;
  resp.dtype = a.dtype;
  resp.reduce = a.reduce;
  resp.root = a.root_rank;
  resp.prescale = a.prescale;
  resp.postscale = a.postscale;
  resp.numels = {a.shape.num_elements()};
  resp.shapes = {a.shape};  // local-only: see Response::shapes
  // resp.members already assigned at the top (error targeting)

  // participant count + rank → position map (identity for the global set)
  const int m = a.members.empty() ? size_
                                  : static_cast<int>(a.members.size());
  auto pos_of = [&](int rank) -> int {
    if (a.members.empty()) return rank;
    for (size_t i = 0; i < a.members.size(); ++i)
      if (a.members[i] == rank) return static_cast<int>(i);
    return -1;
  };
  if (!a.members.empty()) {
    int64_t prev = -1;
    for (auto mr : a.members) {
      if (mr <= prev || mr >= size_)
        return fail("process set for tensor '" + a.name +
                    "' must be ascending unique ranks within the world");
      prev = mr;
    }
    for (auto& q : reqs)
      if (pos_of(q.rank) < 0)
        return fail("rank " + std::to_string(q.rank) + " submitted '" +
                    a.name + "' but is not in its process set");
  }

  if (a.op == OpType::BARRIER) resp.kind = Response::Kind::BARRIER;

  if (a.op == OpType::ALLREDUCE && a.reduce == ReduceKind::ADASUM &&
      (m & (m - 1)) != 0)
    return fail("Adasum requires a power-of-two participant count");

  if (a.op == OpType::BROADCAST && pos_of(a.root_rank) < 0)
    return fail("broadcast root " + std::to_string(a.root_rank) +
                " is not in the process set for '" + a.name + "'");

  if (a.op == OpType::ALLGATHER || a.op == OpType::ALLTOALL) {
    // trailing dims were validated equal across ranks above; carry the
    // per-row element count so joined ranks use the same transfer sizes
    resp.trailing = 1;
    for (size_t d = 1; d < a.shape.dims.size(); ++d)
      resp.trailing *= a.shape.dims[d];
  }
  if (a.op == OpType::ALLGATHER) {
    resp.rows_flat.assign(m, 0);
    for (auto& q : reqs)
      resp.rows_flat[pos_of(q.rank)] =
          q.shape.dims.empty() ? 1 : q.shape.dims[0];
  }
  if (a.op == OpType::ALLTOALL) {
    resp.rows_flat.assign(static_cast<size_t>(m) * m, 0);
    for (auto& q : reqs) {
      if (static_cast<int>(q.splits.size()) != m)
        return fail("alltoall splits length must equal the participant "
                    "count for '" + a.name + "'");
      int64_t total = 0;
      for (auto s : q.splits) total += s;
      if (!q.shape.dims.empty() && total != q.shape.dims[0])
        return fail("alltoall splits must sum to dim 0 for '" + a.name +
                    "'");
      for (int d = 0; d < m; ++d)
        resp.rows_flat[static_cast<size_t>(pos_of(q.rank)) * m + d] =
            q.splits[d];
    }
  }
  if (a.op == OpType::REDUCESCATTER) {
    int64_t rows = a.shape.dims.empty() ? 1 : a.shape.dims[0];
    if (rows % m != 0)
      return fail("reducescatter dim 0 must be divisible by the "
                  "participant count for '" + a.name + "'");
  }
  return resp;
}

void Engine::FuseResponses(std::vector<Response>& responses) {
  // merge adjacent allreduce responses with identical execution params
  // while the fused payload stays under the threshold (reference
  // controller.cc:777 FuseResponses). Members of the same fusion group
  // merge UNCONDITIONALLY (no threshold — deterministic group fusion,
  // reference controller.cc:199-223) unless HVT_DISABLE_GROUP_FUSION is
  // set; grouped responses never merge with ungrouped ones or with other
  // groups, so each group stays one atomic collective.
  std::vector<Response> fused;
  for (auto& r : responses) {
    bool params_match =
        !fused.empty() && r.kind == Response::Kind::TENSOR &&
        fused.back().kind == Response::Kind::TENSOR &&
        r.op == OpType::ALLREDUCE && fused.back().op == OpType::ALLREDUCE &&
        r.dtype == fused.back().dtype && r.reduce == fused.back().reduce &&
        r.prescale == fused.back().prescale &&
        r.postscale == fused.back().postscale &&
        r.members == fused.back().members &&
        r.reduce != ReduceKind::ADASUM;
    bool same_group = params_match && r.group_id >= 0 &&
                      fused.back().group_id == r.group_id &&
                      !disable_group_fusion_;
    bool can_fuse = params_match && (same_group || (r.group_id < 0 &&
                                                    fused.back().group_id < 0));
    if (can_fuse) {
      int64_t cur = 0, add = 0;
      for (auto n : fused.back().numels) cur += n;
      for (auto n : r.numels) add += n;
      int64_t el = static_cast<int64_t>(DataTypeSize(r.dtype));
      if (same_group || (cur + add) * el <= fusion_threshold_) {
        fused.back().names.insert(fused.back().names.end(), r.names.begin(),
                                  r.names.end());
        fused.back().numels.insert(fused.back().numels.end(),
                                   r.numels.begin(), r.numels.end());
        fused.back().shapes.insert(fused.back().shapes.end(),
                                   r.shapes.begin(), r.shapes.end());
        stats_.responses_fused.fetch_add(1, std::memory_order_relaxed);
        continue;
      }
    }
    fused.push_back(std::move(r));
  }
  responses = std::move(fused);
}

CollectiveBackend* Engine::PickBackend(const Response& resp,
                                       int64_t total_elems) {
  // autotuned flat preference: bypass the priority backends entirely
  // (flag is frame-synchronized, so every rank picks the same family)
  if (prefer_flat_.load()) return backends_.back().get();
  for (auto& b : backends_)
    if (b->Enabled(resp, total_elems)) return b.get();
  return backends_.back().get();  // ring fallback accepts everything
}

void Engine::CheckStalls() {
  double now = NowSec();
  for (auto& [name, tc] : counts_) {
    if (tc.first_seen_sec == 0 || stall_warned_[name]) continue;
    if (now - tc.first_seen_sec > stall_warn_sec_) {
      const auto& mem = tc.requests[0].members;
      auto expected = [&](int r) {
        if (mem.empty()) return true;
        for (auto mr : mem)
          if (mr == r) return true;
        return false;
      };
      std::ostringstream missing;
      int64_t missing_mask = 0;  // ranks >= 64 appear only in the
                                 // diagnostics JSON, not the event mask
      for (int r = 0; r < size_; ++r)
        if (!tc.seen[r] && !rank_joined_[r] && expected(r)) {
          missing << r << " ";
          if (r < 64) missing_mask |= int64_t{1} << r;
        }
      HVT_LOG(WARNING, rank_)
          << "tensor '" << tc.requests[0].name
          << "' was submitted by some ranks but "
          << "not by ranks [ " << missing.str() << "] for "
          << static_cast<long>(now - tc.first_seen_sec)
          << " s — possible stall (reference stall_inspector semantics)";
      stats_.stall_events.fetch_add(1, std::memory_order_relaxed);
      events_.Record(
          EventKind::STALL, tc.requests[0].name,
          static_cast<int32_t>(tc.requests[0].op),
          static_cast<int32_t>(now - tc.first_seen_sec), missing_mask,
          LaneSlot(LaneId(tc.requests[0].members)));
      stall_warned_[name] = true;
    }
  }
}

// Snapshot engine-thread state for client-thread diagnostics readers.
// Throttled to ~10 Hz: the copy is O(pending + negotiations × size)
// string work, which must not tax the 2 ms cycle loop of a large gang
// that nobody is scraping; 100 ms staleness is invisible to the 5 s
// debugz push loop and to human-driven hvt.diagnostics() polling.
void Engine::UpdateDiag() {
  double now = NowSec();
  {
    MutexLock lk(diag_mu_);
    if (diag_.valid && now - diag_.updated_sec < 0.1) return;
  }
  DiagState d;
  d.valid = true;
  d.cycles = stats_.cycles.load(std::memory_order_relaxed);
  {
    MutexLock lk(queue_mu_);
    d.queue_depth = static_cast<int>(submitted_.size());
  }
  for (auto& [name, e] : pending_)
    d.pending.push_back(DiagPending{
        name, e->submit_sec > 0 ? now - e->submit_sec : 0.0,
        LaneSlot(LaneId(e->members))});
  if (rank_ == 0) {
    for (auto& [key, tc] : counts_) {
      if (tc.requests.empty()) continue;
      DiagNegotiation n;
      n.name = tc.requests[0].name;
      n.op = tc.requests[0].op;
      n.waiting_sec = tc.first_seen_sec > 0 ? now - tc.first_seen_sec : 0;
      const auto& mem = tc.requests[0].members;
      auto expected = [&](int r) {
        if (mem.empty()) return true;
        for (auto mr : mem)
          if (mr == r) return true;
        return false;
      };
      for (int r = 0; r < size_; ++r) {
        if (!expected(r) || rank_joined_[r]) continue;
        bool seen = r < static_cast<int>(tc.seen.size()) && tc.seen[r];
        (seen ? n.arrived : n.missing).push_back(r);
      }
      d.negotiations.push_back(std::move(n));
    }
  }
  // per-link health (transport.h): a flapping link shows up here
  // (state/retries/seconds-in-state) before it ever becomes an abort
  for (TcpLink* l : hub_.links)
    d.links.push_back(DiagLink{l->peer_rank(),
                               static_cast<int>(l->plane()),
                               static_cast<int>(l->state()),
                               l->retries(), l->epoch(),
                               now - l->state_since_sec()});
  d.stall_warn_sec = stall_warn_sec_;
  d.updated_sec = now;
  MutexLock lk(diag_mu_);
  diag_ = std::move(d);
}

static void JsonAppendEscaped(std::string& out, const std::string& s) {
  for (char c : s) {
    if (c == '"' || c == '\\') {
      out += '\\';
      out += c;
    } else if (static_cast<unsigned char>(c) < 0x20) {
      char buf[8];
      snprintf(buf, sizeof(buf), "\\u%04x", c);
      out += buf;
    } else {
      out += c;
    }
  }
}

static void JsonAppendRanks(std::string& out, const std::vector<int>& v) {
  out += '[';
  for (size_t i = 0; i < v.size(); ++i) {
    if (i) out += ',';
    out += std::to_string(v[i]);
  }
  out += ']';
}

std::string Engine::DiagnosticsJson() {
  DiagState d;
  {
    MutexLock lk(diag_mu_);
    d = diag_;
  }
  bool running = initialized_.load();
  char num[64];
  std::string out = "{\"engine\":{\"running\":";
  out += running ? "true" : "false";
  out += ",\"rank\":" + std::to_string(rank_);
  out += ",\"size\":" + std::to_string(size_);
  out += ",\"cycles\":" + std::to_string(d.cycles);
  out += ",\"queue_depth\":" + std::to_string(d.queue_depth);
  snprintf(num, sizeof(num), "%.3f", d.stall_warn_sec);
  out += std::string(",\"stall_warn_sec\":") + num;
  out += ",\"events_dropped\":" + std::to_string(events_.dropped());
  // wire-codec pair (current; auto shows rank 0's latest picks) — a
  // mixed-codec gang is visible when debugging stalls via /debugz
  out += std::string(",\"wire\":{\"intra\":\"") +
         WireCodecName(static_cast<WireCodec>(
             wire_cur_intra_.load(std::memory_order_relaxed))) +
         "\",\"inter\":\"" +
         WireCodecName(static_cast<WireCodec>(
             wire_cur_inter_.load(std::memory_order_relaxed))) +
         "\",\"auto\":";
  out += wire_auto_ ? "true}" : "false}";
  out += ",\"broken\":";
  out += broken_.load() ? "true" : "false";
  if (broken_.load()) {
    MutexLock lk(broken_mu_);
    out += ",\"abort_cause\":\"";
    out += AbortCauseName(broken_cause_);
    out += "\",\"abort_reason\":\"";
    JsonAppendEscaped(out, broken_reason_);
    out += "\"";
  }
  out += "},\"pending\":[";
  for (size_t i = 0; i < d.pending.size(); ++i) {
    if (i) out += ',';
    out += "{\"tensor\":\"";
    JsonAppendEscaped(out, d.pending[i].name);
    snprintf(num, sizeof(num), "%.3f", d.pending[i].age_sec);
    out += std::string("\",\"age_sec\":") + num;
    out += ",\"lane\":" + std::to_string(d.pending[i].lane) + "}";
  }
  out += "],\"links\":[";
  for (size_t i = 0; i < d.links.size(); ++i) {
    const auto& l = d.links[i];
    if (i) out += ',';
    out += "{\"peer\":" + std::to_string(l.peer);
    out += std::string(",\"plane\":\"") +
           LinkPlaneName(static_cast<LinkPlane>(l.plane)) + "\"";
    out += std::string(",\"state\":\"") +
           LinkStateName(static_cast<LinkState>(l.state)) + "\"";
    out += ",\"retries\":" + std::to_string(l.retries);
    out += ",\"epoch\":" + std::to_string(l.epoch);
    snprintf(num, sizeof(num), "%.3f", l.in_state_sec);
    out += std::string(",\"in_state_sec\":") + num + "}";
  }
  out += "],\"negotiations\":[";
  // stalls = negotiations past the warn threshold; emitted as a separate
  // array so callers don't re-derive the policy
  std::string stalls;
  for (size_t i = 0; i < d.negotiations.size(); ++i) {
    const auto& n = d.negotiations[i];
    std::string entry = "{\"tensor\":\"";
    JsonAppendEscaped(entry, n.name);
    entry += "\",\"op\":\"";
    entry += OpName(n.op);
    snprintf(num, sizeof(num), "%.3f", n.waiting_sec);
    entry += std::string("\",\"waiting_sec\":") + num;
    entry += ",\"arrived_ranks\":";
    JsonAppendRanks(entry, n.arrived);
    entry += ",\"missing_ranks\":";
    JsonAppendRanks(entry, n.missing);
    entry += "}";
    if (i) out += ',';
    out += entry;
    if (!n.missing.empty() && n.waiting_sec > d.stall_warn_sec) {
      if (!stalls.empty()) stalls += ',';
      stalls += entry;
    }
  }
  out += "],\"stalls\":[" + stalls + "]}";
  return out;
}

// --------------------------------------------------------------------------
// error feedback + link-class resolution
// --------------------------------------------------------------------------

// Which codec will actually touch this response's payload, given the
// backend the engine picked: shm moves no wire bytes; the hierarchical
// backend's lossy phase is its cross-host allreduce (the intra phases
// are full precision under the recommended pair); a ring resolves by
// whether its members span hosts. This is the codec the error-feedback
// pass compensates — compensating a codec that never runs would
// needlessly quantize the input.
WireCodec Engine::EffectiveWire(const CollectiveBackend* be,
                                const Response& resp,
                                const std::vector<int>& grp) const {
  if (!WireEligible(resp)) return WireCodec::RAW;
  WirePair wp{static_cast<WireCodec>(resp.wire_intra),
              static_cast<WireCodec>(resp.wire_inter)};
  if (!wp.any()) return WireCodec::RAW;
  const char* n = be->Name();
  if (strcmp(n, "shm") == 0) return WireCodec::RAW;
  if (strcmp(n, "hierarchical") == 0)
    // compensate the first LOSSY hop: normally the cross-host phase,
    // but an int8,none-style pair quantizes only the local
    // reduce-scatter/allgather — falling through to wp.inter there
    // would skip EF entirely while the intra codec biases every step
    return wp.inter != WireCodec::RAW ? wp.inter : wp.intra;
  return ResolveLinkCodec(topo_, wp,
                          resp.members.empty() ? std::vector<int>{} : grp);
}

float* Engine::EfResidual(const std::string& name, uint64_t lane,
                          int64_t n) {
  const int64_t need = n * 4;
  if (need > ef_max_bytes_) {
    stats_.ef_residuals_dropped.fetch_add(1, std::memory_order_relaxed);
    return nullptr;
  }
  std::string key = name;
  key.push_back('\x1f');
  key += std::to_string(lane);
  auto it = ef_bufs_.find(key);
  if (it != ef_bufs_.end() &&
      static_cast<int64_t>(it->second.v.size()) != n) {
    // shape changed under the same name: the old residual is for a
    // different tensor — start clean
    ef_bytes_ -= static_cast<int64_t>(it->second.v.size()) * 4;
    ef_bufs_.erase(it);
    it = ef_bufs_.end();
  }
  if (it == ef_bufs_.end()) {
    // LRU-evict until the new buffer fits the budget
    while (ef_bytes_ + need > ef_max_bytes_ && !ef_bufs_.empty()) {
      auto lru = ef_bufs_.begin();
      for (auto j = ef_bufs_.begin(); j != ef_bufs_.end(); ++j)
        if (j->second.tick < lru->second.tick) lru = j;
      ef_bytes_ -= static_cast<int64_t>(lru->second.v.size()) * 4;
      ef_bufs_.erase(lru);
      stats_.ef_residuals_dropped.fetch_add(1, std::memory_order_relaxed);
    }
    if (ef_bytes_ + need > ef_max_bytes_) {
      stats_.ef_residuals_dropped.fetch_add(1, std::memory_order_relaxed);
      return nullptr;
    }
    auto& buf = ef_bufs_[key];
    buf.v.assign(static_cast<size_t>(n), 0.f);
    ef_bytes_ += need;
    it = ef_bufs_.find(key);
  }
  it->second.tick = ++ef_tick_;
  stats_.ef_residual_bytes.store(ef_bytes_, std::memory_order_relaxed);
  return it->second.v.data();
}

// --------------------------------------------------------------------------
// execution
// --------------------------------------------------------------------------

// local Adasum tree combine over gathered per-rank vectors (fp32/fp64).
// Levels with stride < start_level average instead of adasum-combining —
// the reference's GPU start_level composition (adasum.h:177-183: local
// ranks average, only cross-host levels run the scale-invariant combine).
template <typename T>
static void AdasumTree(std::vector<std::vector<T>>& vs, int start_level) {
  int n = static_cast<int>(vs.size());
  for (int stride = 1; stride < n; stride <<= 1) {
    for (int base = 0; base < n; base += stride << 1) {
      auto& a = vs[base];
      auto& b = vs[base + stride];
      if (stride < start_level) {
        for (size_t i = 0; i < a.size(); ++i)
          a[i] = static_cast<T>(0.5 * (static_cast<double>(a[i]) + b[i]));
        continue;
      }
      double dot = 0, asq = 0, bsq = 0;
      for (size_t i = 0; i < a.size(); ++i) {
        dot += static_cast<double>(a[i]) * b[i];
        asq += static_cast<double>(a[i]) * a[i];
        bsq += static_cast<double>(b[i]) * b[i];
      }
      double ca = asq > 0 ? 1.0 - dot / (2 * asq) : 1.0;
      double cb = bsq > 0 ? 1.0 - dot / (2 * bsq) : 1.0;
      for (size_t i = 0; i < a.size(); ++i)
        a[i] = static_cast<T>(ca * a[i] + cb * b[i]);
    }
  }
}

// AdasumTree pairs by GLOBAL rank adjacency, so "local ranks average
// first" is only true when each host's ranks are a contiguous run.
static bool HostContiguousRanks(const std::vector<std::string>& hosts) {
  std::set<std::string> closed;
  for (size_t i = 0; i < hosts.size(); ++i) {
    if (i == 0 || hosts[i] != hosts[i - 1]) {
      if (!closed.insert(hosts[i]).second) return false;  // host reappears
    }
  }
  return true;
}

// HVT_ADASUM_START_LEVEL: integer, or "local" for the host-local rank
// count (the reference GPU op's choice).
static int AdasumStartLevel(const Topology& topo, int rank) {
  const char* v = getenv("HVT_ADASUM_START_LEVEL");
  if (!v || !*v) return 1;
  if (std::string(v) == "local") {
    // the composition assumes host-contiguous global ranks and equal
    // local sizes; with an interleaved placement the levels would invert
    // (cross-host pairs averaging) — fall back to pure adasum instead
    if (!topo.homogeneous || !HostContiguousRanks(topo.host_of_rank)) {
      HVT_LOG(WARNING, rank)
          << "HVT_ADASUM_START_LEVEL=local needs host-contiguous ranks "
          << "and equal per-host sizes; falling back to pure adasum";
      return 1;
    }
    return static_cast<int>(topo.local_group.size());
  }
  int n = atoi(v);
  return n > 0 ? n : 1;
}

// Exactly the condition under which the member-side execution path
// inserts into the response cache — the non-member mirror below must
// never diverge from it, or cache positions would drift across ranks.
bool Engine::CacheableResponse(const Response& resp) const {
  return resp.kind == Response::Kind::TENSOR &&
         resp.op == OpType::ALLREDUCE &&
         resp.reduce != ReduceKind::ADASUM && resp.group_id < 0 &&
         cache_enabled_.load() && !join_pending_;
}

void Engine::CacheResponseAllRanks(const Response& resp) {
  if (!CacheableResponse(resp)) return;
  for (size_t i = 0; i < resp.names.size(); ++i) {
    // True dims when the response carries them (always on rank 0 —
    // its HitToArrival fold replays cached params as Requests, so a
    // stand-in would trip BuildResponse's shape check); a flattened
    // stand-in on workers, whose non-member copies are position
    // ballast only — they never announce this (name, set) pair, and a
    // different set's Lookup resolves through the members mismatch
    // (kInvalid → eviction) regardless of shape.
    TensorShape shape = i < resp.shapes.size()
                            ? resp.shapes[i]
                            : TensorShape{{resp.numels[i]}};
    CachedParams p{resp.op,
                   resp.reduce,
                   resp.dtype,
                   std::move(shape),
                   resp.root,
                   resp.prescale,
                   resp.postscale,
                   {},
                   resp.members};
    cache_.Insert(resp.names[i], p);
  }
}

void Engine::ExecuteResponse(const Response& resp,
                             std::map<std::string, EntryPtr>& pending) {
  auto take = [&](const std::string& name) -> EntryPtr {
    auto it = pending.find(name);
    if (it == pending.end()) return nullptr;
    EntryPtr e = it->second;
    pending.erase(it);
    announced_.erase(name);
    {
      // track as in-flight until CompleteEntry: if the data plane
      // throws mid-collective, FailAll must error-complete this entry
      // or its waiter would hang past the abort
      MutexLock lk(handles_mu_);
      inflight_.push_back(e);
    }
    return e;
  };

  switch (resp.kind) {
    case Response::Kind::ERROR: {
      if (!resp.members.empty()) {
        // member-targeted error (cross-set conflicts): an innocent
        // disjoint set reusing the name must keep its pending entry
        bool mine = false;
        for (auto mr : resp.members) mine = mine || mr == rank_;
        if (!mine) return;
      }
      for (auto& name : resp.names) {
        auto e = take(name);
        if (e) CompleteEntry(e, Status::PreconditionError(resp.error));
      }
      return;
    }
    case Response::Kind::BARRIER: {
      auto e = take(resp.names[0]);
      if (e) CompleteEntry(e, Status::OK());
      return;
    }
    case Response::Kind::JOIN: {
      if (join_entry_) {
        join_entry_->output.clear();
        HandleState hs;
        {
          MutexLock lk(handles_mu_);
          auto it = handles_.find(join_entry_->handle);
          if (it != handles_.end()) {
            it->second.join_result = resp.root;
            it->second.done = true;
            it->second.status = Status::OK();
          }
        }
        handles_cv_.notify_all();  // after unlock (see CompleteEntry)
        join_entry_.reset();
      }
      join_pending_ = false;
      // join + cache interact badly (reference controller.cc:87-120);
      // clearing keeps every rank's cache identical afterwards
      cache_ = ResponseCache(1024);
      if (rank_ == 0)
        for (auto& s : hit_pending_) s.clear();
      return;
    }
    case Response::Kind::TENSOR:
      break;
  }

  // Global response sequence: identical on every rank (one coordinated
  // response stream), advanced for every TENSOR response INCLUDING ones
  // this rank skips — the shm plane keys its progress-word barriers to it
  ++resp_seq_;

  // process-set participants (the whole world when members is empty);
  // non-member ranks skip the response — they are not in the sub-rings
  std::vector<int> grp;
  if (resp.members.empty()) {
    grp.resize(size_);
    for (int i = 0; i < size_; ++i) grp[i] = i;
  } else {
    bool mine = false;
    for (auto mr : resp.members) {
      grp.push_back(static_cast<int>(mr));
      mine = mine || mr == rank_;
    }
    if (!mine) {
      // cache positions are assigned in response order on EVERY rank —
      // a skipped cacheable response still claims its position here or
      // the gang-wide eviction sync would evict the wrong names
      CacheResponseAllRanks(resp);
      return;
    }
  }
  const int m = static_cast<int>(grp.size());
  const int my_pos = GroupIndexOf(grp, rank_);

  const size_t el = DataTypeSize(resp.dtype);
  data_ops_++;  // one per TENSOR response = one data-plane collective
  MaybeInjectFault();  // HVT_FAULT_INJECT chaos hook (no-op when unset)
  // attribute this response's wire bytes to its OpType (engine thread
  // is the only data-plane user, so a plain member set suffices), and
  // stamp the tensor identity the duplex pump's WIRE spans carry
  if (data_) {
    data_->set_stat_op(static_cast<int>(resp.op));
    data_->set_wire_ctx(resp.names[0], LaneSlot(LaneId(resp.members)));
  }
  stats_.tensors_coordinated.fetch_add(
      static_cast<int64_t>(resp.names.size()), std::memory_order_relaxed);
  for (int64_t n : resp.numels) {
    cycle_bytes_ += n * static_cast<int64_t>(el);
    stats_.fusion_bytes.fetch_add(n * static_cast<int64_t>(el),
                                  std::memory_order_relaxed);
  }
  switch (resp.op) {
    case OpType::ALLREDUCE: {
      if (resp.reduce == ReduceKind::ADASUM) {
        auto e = take(resp.names[0]);
        int64_t numel = resp.numels[0];
        std::vector<uint8_t> mine(numel * el, 0);
        if (e) memcpy(mine.data(), e->input.data(), mine.size());
        std::vector<uint8_t> gathered(mine.size() * m);
        std::vector<int64_t> rows(m, numel);
        data_->AllgathervGroup(mine.data(), numel, rows,
                               static_cast<int64_t>(el), gathered.data(),
                               grp);
        if (resp.dtype == DataType::FLOAT32) {
          std::vector<std::vector<float>> vs(m);
          for (int r = 0; r < m; ++r) {
            vs[r].resize(numel);
            memcpy(vs[r].data(), gathered.data() + r * mine.size(),
                   mine.size());
          }
          AdasumTree(vs, AdasumStartLevel(topo_, rank_));
          if (e) {
            e->output.resize(mine.size());
            memcpy(e->output.data(), vs[0].data(), mine.size());
          }
        } else if (resp.dtype == DataType::FLOAT64) {
          std::vector<std::vector<double>> vs(m);
          for (int r = 0; r < m; ++r) {
            vs[r].resize(numel);
            memcpy(vs[r].data(), gathered.data() + r * mine.size(),
                   mine.size());
          }
          AdasumTree(vs, AdasumStartLevel(topo_, rank_));
          if (e) {
            e->output.resize(mine.size());
            memcpy(e->output.data(), vs[0].data(), mine.size());
          }
        } else {
          if (e)
            CompleteEntry(e, Status::InvalidArgument(
                                 "Adasum supports float32/float64"));
          return;
        }
        if (e) CompleteEntry(e, Status::OK());
        return;
      }

      // fused path: pack → (prescale) → (EF) → ring → unpack, with
      // postscale folded into the backend. The body is shared with the
      // per-lane execution pool (ExecFusedAllreduce); entries are taken
      // HERE because the pending table is engine-thread state, and
      // cache inserts stay on the engine thread in response order.
      std::vector<EntryPtr> entries(resp.names.size());
      for (size_t i = 0; i < resp.names.size(); ++i)
        entries[i] = take(resp.names[i]);
      // per-lane fusion scratch: each process set's buffer converges
      // to its own working-set size instead of thrashing one shared
      // allocation across tenants
      ExecFusedAllreduce(resp, entries, resp_seq_,
                         fusion_buffers_[LaneId(resp.members)],
                         /*apply_ef=*/true);
      // every rank inserts in the same order → identical caches;
      // grouped tensors stay uncached (groups renegotiate as a
      // unit). Set-scoped responses cache too (lane-keyed fast
      // path); non-member ranks mirror the insert via
      // CacheResponseAllRanks so positions never diverge.
      if (CacheableResponse(resp)) {
        for (size_t i = 0; i < resp.names.size(); ++i) {
          if (!entries[i]) continue;
          CachedParams p{resp.op,      resp.reduce,    resp.dtype,
                         entries[i]->shape, resp.root, resp.prescale,
                         resp.postscale, entries[i]->splits,
                         resp.members};
          cache_.Insert(resp.names[i], p);
        }
      }
      return;
    }

    case OpType::ALLGATHER: {
      auto e = take(resp.names[0]);
      std::vector<int64_t> rows(resp.rows_flat.begin(),
                                resp.rows_flat.begin() + m);
      // per-row element count from the coordinator (identical on every
      // rank, including joined ranks with no local entry)
      int64_t row_bytes = resp.trailing * static_cast<int64_t>(el);
      // mirror the coordinator's row convention (BuildResponse counts a
      // 0-d entry as ONE row) or peers would read an uninitialized row
      int64_t my_rows =
          e ? (e->shape.dims.empty() ? 1 : e->shape.dims[0]) : 0;
      int64_t total_rows = 0;
      for (auto r : rows) total_rows += r;
      std::vector<uint8_t> out(static_cast<size_t>(total_rows) * row_bytes);
      const void* in = e ? static_cast<const void*>(e->input.data())
                         : static_cast<const void*>(out.data());
      {
        auto* be = PickBackend(resp, total_rows * resp.trailing);
        be->BeginResponse(resp_seq_);
        if (resp.members.empty())
          // full world: shm single-copy concat from slots
          be->Allgatherv(in, my_rows, rows, row_bytes, out.data());
        else
          be->AllgathervGroup(in, my_rows, rows, row_bytes, out.data(),
                              grp);
      }
      if (e) {
        e->output = std::move(out);
        e->recv_splits = rows;
        CompleteEntry(e, Status::OK());
      }
      return;
    }

    case OpType::BROADCAST: {
      auto e = take(resp.names[0]);
      size_t bytes = static_cast<size_t>(resp.numels[0]) * el;
      std::vector<uint8_t> buf(bytes, 0);
      if (e) memcpy(buf.data(), e->input.data(), bytes);
      {
        auto* be = PickBackend(resp, resp.numels[0]);
        be->BeginResponse(resp_seq_);
        if (resp.members.empty())
          // full world: shm write-once-read-many beats the TCP star for
          // model-sized payloads
          be->Broadcast(buf.data(), static_cast<int64_t>(bytes),
                        resp.root);
        else
          be->BroadcastGroup(buf.data(), static_cast<int64_t>(bytes),
                             resp.root, grp);
      }
      if (e) {
        e->output = std::move(buf);
        CompleteEntry(e, Status::OK());
      }
      return;
    }

    case OpType::ALLTOALL: {
      auto e = take(resp.names[0]);
      // rows_flat: sender-POSITION-major m x m matrix
      std::vector<int64_t> send_rows(m, 0), recv_rows(m, 0);
      for (int d = 0; d < m; ++d)
        send_rows[d] =
            resp.rows_flat[static_cast<size_t>(my_pos) * m + d];
      for (int s = 0; s < m; ++s)
        recv_rows[s] =
            resp.rows_flat[static_cast<size_t>(s) * m + my_pos];
      int64_t row_bytes = resp.trailing * static_cast<int64_t>(el);
      int64_t total_recv = 0;
      for (auto r : recv_rows) total_recv += r;
      std::vector<uint8_t> out(static_cast<size_t>(total_recv) * row_bytes);
      const void* in = e ? static_cast<const void*>(e->input.data())
                         : static_cast<const void*>(out.data());
      {
        auto* be = PickBackend(resp, total_recv * resp.trailing);
        be->BeginResponse(resp_seq_);
        if (resp.members.empty())
          be->AlltoallvMatrix(in, resp.rows_flat, m, row_bytes,
                              out.data(), my_pos);
        else
          be->AlltoallvMatrixGroup(in, resp.rows_flat, m, row_bytes,
                                   out.data(), my_pos, grp);
      }
      if (e) {
        e->output = std::move(out);
        e->recv_splits = recv_rows;
        CompleteEntry(e, Status::OK());
      }
      return;
    }

    case OpType::REDUCESCATTER: {
      auto e = take(resp.names[0]);
      int64_t numel = resp.numels[0];
      std::vector<uint8_t> buf(static_cast<size_t>(numel) * el, 0);
      if (e) memcpy(buf.data(), e->input.data(), buf.size());
      if (resp.prescale != 1.0)
        ScaleBuffer(buf.data(), numel, resp.dtype, resp.prescale);
      ReduceKind rk = resp.reduce == ReduceKind::AVERAGE
                          ? ReduceKind::SUM
                          : resp.reduce;
      // backend-native reduce-scatter: only this rank's chunk of buf is
      // guaranteed reduced afterwards (the slice below reads just that);
      // the default lowering is still a full allreduce
      {
        auto* be = PickBackend(resp, numel);
        be->BeginResponse(resp_seq_);
        be->ReduceScatter(buf.data(), numel, resp.dtype, rk, my_pos, m,
                          grp, resp.members.empty());
      }
      double rs_post = resp.postscale;
      if (resp.reduce == ReduceKind::AVERAGE) rs_post /= m;
      if (rs_post != 1.0)
        // only this rank's chunk is read below — scale just it
        ScaleBuffer(buf.data() + (numel * my_pos / m) * el, numel / m,
                    resp.dtype, rs_post);
      if (e) {
        int64_t rows = e->shape.dims.empty() ? 1 : e->shape.dims[0];
        int64_t row_bytes = (e->shape.num_elements() / rows) *
                            static_cast<int64_t>(el);
        int64_t chunk_rows = rows / m;
        size_t chunk_bytes = static_cast<size_t>(chunk_rows) * row_bytes;
        e->output.assign(
            buf.data() + static_cast<size_t>(my_pos) * chunk_bytes,
            buf.data() + static_cast<size_t>(my_pos + 1) * chunk_bytes);
        CompleteEntry(e, Status::OK());
      }
      return;
    }

    default:
      return;
  }
}

// --------------------------------------------------------------------------
// fused-allreduce execution body (engine thread AND lane-pool workers)
// --------------------------------------------------------------------------

void Engine::ExecFusedAllreduce(const Response& resp,
                                std::vector<EntryPtr>& entries,
                                uint64_t seq,
                                std::vector<uint8_t>& scratch,
                                bool apply_ef) {
  // participants — the caller already established this rank is one
  std::vector<int> grp;
  if (resp.members.empty()) {
    grp.resize(size_);
    for (int i = 0; i < size_; ++i) grp[i] = i;
  } else {
    for (auto mr : resp.members) grp.push_back(static_cast<int>(mr));
  }
  const int m = static_cast<int>(grp.size());
  const size_t el = DataTypeSize(resp.dtype);
  // response-scoped telemetry stamps: the DataPlane context is
  // per-thread, so the EXECUTING thread (engine or pool worker) stamps
  // its own — a worker's WIRE spans and byte counters attribute to its
  // own lane even while the engine thread executes something else
  if (data_) {
    data_->set_stat_op(static_cast<int>(resp.op));
    data_->set_wire_ctx(resp.names[0], LaneSlot(LaneId(resp.members)));
  }
  int64_t total = 0;
  for (auto n : resp.numels) total += n;
  // Single-tensor responses — the common shape for large payloads,
  // which fuse rarely — skip the fusion buffer entirely and run the
  // collective in place on the entry's own input buffer: no 2·bytes
  // pack/unpack memcpy sweep.
  uint8_t* work;
  const bool in_place = entries.size() == 1 && entries[0] != nullptr &&
                        entries[0]->input.size() ==
                            static_cast<size_t>(total) * el;
  if (in_place) {
    work = entries[0]->input.data();
  } else {
    scratch.resize(static_cast<size_t>(total) * el);
    work = scratch.data();
    int64_t off = 0;
    for (size_t i = 0; i < resp.names.size(); ++i) {
      size_t bytes = static_cast<size_t>(resp.numels[i]) * el;
      if (entries[i]) {
        memcpy(work + off, entries[i]->input.data(), bytes);
      } else {
        memset(work + off, 0, bytes);  // joined stand-in
      }
      off += bytes;
    }
  }
  if (resp.prescale != 1.0)
    ScaleBuffer(work, total, resp.dtype, resp.prescale);
  {
    // subset responses route through the backend list too (shm serves
    // them via per-group barrier cells; ring is the fallback) — the
    // reference serves every op from the selected backend
    // (operation_manager.cc). postscale (incl. the Average divide)
    // folds into the backend's final data pass, and the negotiated
    // wire-codec pair rides along for the TCP ring.
    double post = resp.postscale;
    if (resp.reduce == ReduceKind::AVERAGE) post /= m;
    WirePair wire{static_cast<WireCodec>(resp.wire_intra),
                  static_cast<WireCodec>(resp.wire_inter)};
    auto* be = PickBackend(resp, total);
    // error feedback: compensate the codec that will actually touch
    // this payload. Add each tensor's stored residual, roundtrip the
    // compensated input through the codec (idempotent on the wire's
    // own grid, so the first-hop quantization of this rank's data
    // becomes lossless — exactly so when ring-segment offsets are
    // block-aligned; unaligned segments re-grid at most one wire
    // quantum per element, uncaptured), and keep the new
    // quantization error for the next submission of the same
    // (name, lane). Per-rank local — every rank compensates only
    // its own contribution, so cross-rank bit-identity of the
    // collective is untouched. EffectiveWire picks ONE codec per
    // payload: a pair with two lossy codecs (bf16,int8
    // hierarchical) leaves the intra-phase bf16 rounding
    // uncompensated — see docs/performance.md §EF. apply_ef is false
    // on the pool path (residuals are engine-thread state; EF-active
    // responses never reach the pool — LanePoolEligible).
    const Codec* efc = (apply_ef && ef_enabled_)
                           ? CodecFor(EffectiveWire(be, resp, grp))
                           : nullptr;
    if (efc && WireEligible(resp)) {
      const uint64_t lane = LaneId(resp.members);
      int64_t eoff = 0;
      for (size_t i = 0; i < resp.names.size(); ++i) {
        const int64_t n = resp.numels[i];
        if (entries[i]) {  // joined stand-ins carry no gradient
          float* seg = reinterpret_cast<float*>(work) + eoff;
          if (float* r = EfResidual(resp.names[i], lane, n)) {
            for (int64_t j = 0; j < n; ++j) seg[j] += r[j];
            memcpy(r, seg, static_cast<size_t>(n) * 4);
            efc->Roundtrip(seg, n);
            for (int64_t j = 0; j < n; ++j) r[j] -= seg[j];
          } else {
            efc->Roundtrip(seg, n);  // over budget: quantize w/o memory
          }
        }
        eoff += n;
      }
    }
    be->BeginResponse(seq);
    if (resp.members.empty())
      be->Allreduce(work, total, resp.dtype, resp.reduce, post, wire);
    else
      be->AllreduceGroup(work, total, resp.dtype, resp.reduce, grp,
                         post, wire);
  }
  int64_t off = 0;
  for (size_t i = 0; i < resp.names.size(); ++i) {
    size_t bytes = static_cast<size_t>(resp.numels[i]) * el;
    if (entries[i]) {
      if (in_place)
        entries[i]->output = std::move(entries[i]->input);
      else
        entries[i]->output.assign(work + off, work + off + bytes);
      CompleteEntry(entries[i], Status::OK());
    }
    off += bytes;
  }
}

// --------------------------------------------------------------------------
// per-lane execution pool (HVT_LANE_WORKERS)
// --------------------------------------------------------------------------

// |a ∩ b| ≥ 2: the two member lists share at least one rank PAIR, i.e.
// at least one data socket — their collectives must serialize in
// response order (which is identical on every rank, so all ranks
// serialize them the same way). Sharing exactly ONE rank is safe: that
// rank talks to disjoint peer sets over disjoint sockets, which is
// precisely the in-rank isolation the pool exists to provide. Member
// lists are ascending (the submit path sorts them).
static bool LaneMembersConflict(const std::vector<int64_t>& a,
                                const std::vector<int64_t>& b) {
  size_t i = 0, j = 0;
  int shared = 0;
  while (i < a.size() && j < b.size()) {
    if (a[i] == b[j]) {
      if (++shared >= 2) return true;
      ++i;
      ++j;
    } else if (a[i] < b[j]) {
      ++i;
    } else {
      ++j;
    }
  }
  return false;
}

void Engine::StartLanePool() {
  lane_workers_ = 0;
  stats_.lane_workers.store(0, std::memory_order_relaxed);
  if (size_ <= 1) return;
  int n = static_cast<int>(EnvInt("HVT_LANE_WORKERS", 0));
  if (n <= 0) return;
  if (n > 16) n = 16;
  lane_workers_ = n;
  {
    MutexLock lk(pool_mu_);
    pool_stop_ = false;
    pool_error_.clear();
    pool_error_cause_ = -1;
    lane_queues_.assign(static_cast<size_t>(n), {});
    lane_active_.assign(static_cast<size_t>(n), nullptr);
    lane_worker_of_.clear();
  }
  for (int i = 0; i < n; ++i)
    lane_threads_.emplace_back([this, i] { LaneWorkerLoop(i); });
  stats_.lane_workers.store(n, std::memory_order_relaxed);
  HVT_LOG(INFO, rank_) << "per-lane execution pool: " << n
                       << " worker(s) (HVT_LANE_WORKERS)";
}

void Engine::StopLanePool() {
  if (lane_threads_.empty()) {
    lane_workers_ = 0;
    return;
  }
  {
    MutexLock lk(pool_mu_);
    pool_stop_ = true;
  }
  pool_cv_.notify_all();
  for (auto& th : lane_threads_)
    if (th.joinable()) th.join();
  lane_threads_.clear();
  {
    // tasks still queued here were error-completed by FailAll (their
    // entries sit in inflight_); on the clean path the shutdown-cycle
    // barrier drained everything first
    MutexLock lk(pool_mu_);
    lane_queues_.clear();
    lane_active_.clear();
    lane_worker_of_.clear();
    pool_stop_ = false;
  }
  lane_workers_ = 0;
  stats_.lane_workers.store(0, std::memory_order_relaxed);
}

void Engine::LaneWorkerLoop(int wi) {
  while (true) {
    std::shared_ptr<LaneTask> t;
    {
      CvLock lk(pool_mu_);
      pool_cv_.wait(lk.native(), [&]() REQUIRES(pool_mu_) {
        return pool_stop_ ||
               !lane_queues_[static_cast<size_t>(wi)].empty();
      });
      if (pool_stop_) return;
      t = lane_queues_[static_cast<size_t>(wi)].front();
      lane_queues_[static_cast<size_t>(wi)].pop_front();
      lane_active_[static_cast<size_t>(wi)] = t;
    }
    auto note = [&](int cause, const char* what) {
      MutexLock lk(pool_mu_);
      if (pool_error_.empty()) {
        pool_error_ = what;
        pool_error_cause_ = cause;
      }
    };
    try {
      RunLaneTask(*t);
    } catch (const OpTimeoutError& e) {
      note(kAbortTimeout, e.what());
    } catch (const PeerLostError& e) {
      note(kAbortPeerLost, e.what());
    } catch (const std::exception& e) {
      // the failed task's entries stay in inflight_ — the engine
      // thread rethrows this error, EnterBroken aborts the links, and
      // FailAll error-completes them (PR 4 containment unchanged)
      note(kAbortInternal, e.what());
    }
    {
      MutexLock lk(pool_mu_);
      lane_active_[static_cast<size_t>(wi)] = nullptr;
    }
    pool_done_cv_.notify_all();
  }
}

void Engine::RethrowLanePoolError() {
  std::string msg;
  int cause = -1;
  {
    MutexLock lk(pool_mu_);
    if (pool_error_.empty()) return;
    msg = "lane worker: " + pool_error_;
    cause = pool_error_cause_;
  }
  switch (cause) {
    case kAbortTimeout:
      throw OpTimeoutError(msg);
    case kAbortPeerLost:
      throw PeerLostError(msg);
    default:
      throw std::runtime_error(msg);
  }
}

void Engine::LaneBarrier() {
  if (lane_threads_.empty()) return;
  {
    CvLock lk(pool_mu_);
    pool_done_cv_.wait(lk.native(), [&]() REQUIRES(pool_mu_) {
      for (auto& q : lane_queues_)
        if (!q.empty()) return false;
      for (auto& a : lane_active_)
        if (a) return false;
      return true;
    });
  }
  RethrowLanePoolError();
}

void Engine::DispatchLaneTask(std::shared_ptr<LaneTask> t) {
  RethrowLanePoolError();
  const uint64_t lid = LaneId(t->resp.members);
  {
    CvLock lk(pool_mu_);
    // sticky anti-affinity assignment: a lane keeps its worker (FIFO
    // program order), and a first-seen lane lands on the least-busy
    // worker — a blind LaneId-hash can deterministically co-locate a
    // hot lane with an idle neighbor on one FIFO, reintroducing
    // exactly the head-of-line blocking the pool exists to remove
    int wi;
    auto wit = lane_worker_of_.find(lid);
    if (wit != lane_worker_of_.end()) {
      wi = wit->second;
    } else {
      std::vector<int> lanes_on(static_cast<size_t>(lane_workers_), 0);
      for (auto& kv : lane_worker_of_)
        lanes_on[static_cast<size_t>(kv.second)]++;
      wi = 0;
      size_t best_load = SIZE_MAX;
      int best_lanes = INT_MAX;
      for (int w = 0; w < lane_workers_; ++w) {
        size_t load = lane_queues_[static_cast<size_t>(w)].size() +
                      (lane_active_[static_cast<size_t>(w)] ? 1 : 0);
        int nl = lanes_on[static_cast<size_t>(w)];
        if (load < best_load ||
            (load == best_load && nl < best_lanes)) {
          best_load = load;
          best_lanes = nl;
          wi = w;
        }
      }
      lane_worker_of_[lid] = wi;
    }
    auto conflicted = [&]() REQUIRES(pool_mu_) {
      if (!pool_error_.empty()) return false;  // unblock; rethrown below
      for (int w = 0; w < lane_workers_; ++w) {
        if (w == wi) continue;  // same queue = FIFO program order
        auto& act = lane_active_[static_cast<size_t>(w)];
        if (act &&
            LaneMembersConflict(act->resp.members, t->resp.members))
          return true;
        for (auto& q : lane_queues_[static_cast<size_t>(w)])
          if (LaneMembersConflict(q->resp.members, t->resp.members))
            return true;
      }
      return false;
    };
    pool_done_cv_.wait(lk.native(), [&]() REQUIRES(pool_mu_) {
      return !conflicted();
    });
    lane_queues_[static_cast<size_t>(wi)].push_back(std::move(t));
  }
  pool_cv_.notify_all();
  RethrowLanePoolError();
}

bool Engine::LanePoolEligible(const Response& resp,
                              const std::vector<int>& grp, bool mine) {
  if (lane_threads_.empty() || !mine || resp.members.empty())
    return false;
  if (resp.op != OpType::ALLREDUCE ||
      resp.reduce == ReduceKind::ADASUM)
    return false;
  int64_t total = 0;
  for (auto n : resp.numels) total += n;
  auto* be = PickBackend(resp, total);
  if (!be->ConcurrentGroupsSafe()) return false;
  // rank 0's auto-mode codec tuner learns from inline executions only
  if (rank_ == 0 && wire_auto_ && WireEligible(resp)) return false;
  // EF residuals are engine-thread state: a response the error-feedback
  // pass would compensate stays inline
  if (ef_enabled_ && WireEligible(resp) &&
      CodecFor(EffectiveWire(be, resp, grp)) != nullptr)
    return false;
  return true;
}

void Engine::RunLaneTask(LaneTask& t) {
  const Response& resp = t.resp;
  const int32_t resp_lane = LaneSlot(LaneId(resp.members));
  const int32_t op_w = static_cast<int32_t>(resp.op);
  const int64_t fused_n = static_cast<int64_t>(resp.names.size());
  const bool trace = timeline_.active();  // mutex-guarded writer
  for (auto& n : resp.names) {
    if (trace) timeline_.ExecuteStart(n, OpName(resp.op));
    if (fused_n > 1)
      events_.Record(EventKind::FUSED, n, op_w, rank_, fused_n,
                     resp_lane);
    events_.Record(EventKind::EXEC_BEGIN, n, op_w, rank_, 0, resp_lane);
  }
  const double t0 = NowSec();
  ExecFusedAllreduce(resp, t.entries, t.seq, t.buf, /*apply_ef=*/false);
  const int64_t exec_ns = static_cast<int64_t>((NowSec() - t0) * 1e9);
  const int op_i = static_cast<int>(resp.op);
  if (op_i >= 0 && op_i < kStatsOps) {
    stats_.exec_ns[op_i].fetch_add(exec_ns, std::memory_order_relaxed);
    stats_.exec_count[op_i].fetch_add(1, std::memory_order_relaxed);
  }
  // pool tasks are member-only by construction, so the lane attribution
  // rule (members only) holds
  stats_.lane_exec_ns[resp_lane].fetch_add(exec_ns,
                                           std::memory_order_relaxed);
  stats_.lane_exec_count[resp_lane].fetch_add(1,
                                              std::memory_order_relaxed);
  stats_.lane_pool_tasks.fetch_add(1, std::memory_order_relaxed);
  for (auto& n : resp.names) {
    events_.Record(EventKind::EXEC_END, n, op_w, rank_, 0, resp_lane);
    if (trace) timeline_.ExecuteEnd(n);
  }
}

}  // namespace hvt
