// TCP transport — the engine's DCN fabric. Replaces the reference's
// MPI/Gloo contexts (horovod/common/mpi/mpi_context.h:96,
// horovod/common/gloo/gloo_context.cc): a control star (workers → rank 0)
// plus a lazily-connected full mesh for the data plane. Endpoint discovery
// happens over the control star at init, the analog of the Gloo HTTP-store
// rendezvous.
#pragma once

#include <arpa/inet.h>
#include <errno.h>
#include <fcntl.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <sys/socket.h>
#include <sys/uio.h>
#include <unistd.h>

#include <chrono>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <stdexcept>
#include <string>
#include <vector>

#include "thread_annotations.h"

namespace hvt {

// Thread-safety contract (checked by the engine-layer annotations
// rather than locks here): Sock and Listener are NOT internally
// synchronized. Every socket is engine-thread affine after Init — the
// rendezvous builds them on the caller's thread before the engine
// thread starts, and Shutdown closes them only after joining it. The
// only cross-thread transition is DataPlane::Abort / fault injection,
// both of which run ON the engine thread. Static env-derived settings
// (OpTimeoutMs, ConfigureSockBufs) are initialized via thread-safe
// function-local statics.

// Typed transport failures so the engine can classify its abort cause
// (hvt_engine_aborts_total{cause}) and the containment path can react
// differently to a dead peer vs a stalled one. Both inherit
// runtime_error, so legacy catch sites keep working.
// Every control/data frame travels with a u64 length prefix; byte
// accounting (hvt_ctrl_*_bytes_total, CTRL_BYTES events) includes it.
constexpr int64_t kFramePrefixBytes = 8;

struct PeerLostError : std::runtime_error {
  explicit PeerLostError(const std::string& w) : std::runtime_error(w) {}
};
struct OpTimeoutError : std::runtime_error {
  explicit OpTimeoutError(const std::string& w) : std::runtime_error(w) {}
};

// HVT_OP_TIMEOUT_MS: progress deadline for every control/data socket
// operation (default 60000; 0 disables). The deadline bounds STALL time,
// not total transfer time — it re-arms whenever bytes move — so a large
// collective on a slow link never false-positives while a wedged or
// silently-dead peer surfaces within one deadline instead of hanging
// recv forever (the pre-containment failure mode).
inline int64_t OpTimeoutMs() {
  static const int64_t ms = [] {
    const char* v = getenv("HVT_OP_TIMEOUT_MS");
    return v ? atoll(v) : int64_t{60000};
  }();
  return ms;
}

inline int64_t NowMs() {
  return std::chrono::duration_cast<std::chrono::milliseconds>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

// Block until fd is ready for `events` (POLLIN/POLLOUT) or deadline_ms
// (absolute, NowMs clock; <0 → no deadline). Throws OpTimeoutError on
// expiry, PeerLostError when poll itself fails.
inline void WaitReady(int fd, short events, int64_t deadline_ms,
                      const char* what) {
  if (fd < 0)
    throw PeerLostError(std::string("hvt: ") + what +
                        " on a closed socket");
  while (true) {
    struct pollfd p {fd, events, 0};
    int wait_ms = -1;
    if (deadline_ms >= 0) {
      int64_t left = deadline_ms - NowMs();
      if (left <= 0)
        throw OpTimeoutError(std::string("hvt: ") + what +
                             " deadline exceeded");
      wait_ms = left > 1000 ? 1000 : static_cast<int>(left);
    }
    int rc = ::poll(&p, 1, wait_ms);
    if (rc > 0) return;  // ready (POLLERR/POLLHUP surface via recv/send)
    if (rc < 0 && errno != EINTR)
      throw PeerLostError(std::string("hvt: poll failed during ") + what);
  }
}

// HVT_SOCK_BUF: explicit SO_SNDBUF/SO_RCVBUF for every data/control
// socket (bytes; 0/unset → kernel autotuning). Large rings on fat pipes
// want this well above the payload chunk size so the nonblocking duplex
// pump can keep both directions moving while the reduce runs.
inline void ConfigureSockBufs(int fd) {
  static const long buf = [] {
    const char* v = getenv("HVT_SOCK_BUF");
    return v ? atol(v) : 0L;
  }();
  if (buf > 0) {
    int b = static_cast<int>(buf);
    setsockopt(fd, SOL_SOCKET, SO_SNDBUF, &b, sizeof(b));
    setsockopt(fd, SOL_SOCKET, SO_RCVBUF, &b, sizeof(b));
  }
}

class Sock {
 public:
  Sock() = default;
  explicit Sock(int fd) : fd_(fd) {}
  Sock(const Sock&) = delete;
  Sock& operator=(const Sock&) = delete;
  Sock(Sock&& o) noexcept : fd_(o.fd_) { o.fd_ = -1; }
  Sock& operator=(Sock&& o) noexcept {
    Close();
    fd_ = o.fd_;
    o.fd_ = -1;
    return *this;
  }
  ~Sock() { Close(); }

  void Close() {
    if (fd_ >= 0) {
      ::shutdown(fd_, SHUT_RDWR);
      ::close(fd_);
      fd_ = -1;
    }
  }
  // Wake any thread blocked in (or about to issue) a syscall on this
  // fd WITHOUT releasing the fd number: close() would let a concurrent
  // accept/dial recycle it under that thread, silently redirecting its
  // I/O to an unrelated socket. The fd is reclaimed by Close() /
  // the destructor once no other thread can be driving the link.
  void ShutdownOnly() {
    if (fd_ >= 0) ::shutdown(fd_, SHUT_RDWR);
  }
  bool valid() const { return fd_ >= 0; }
  int fd() const { return fd_; }

  // Deadline-bounded blocking transfers: the progress deadline
  // (timeout_ms, default HVT_OP_TIMEOUT_MS; 0 → none) re-arms after
  // every chunk that moves, so only a stalled peer trips it. A lost
  // peer (FIN/RST) throws PeerLostError, a stall OpTimeoutError — the
  // engine maps both to a coordinated abort instead of a hang.
  void SendAll(const void* data, size_t n, int64_t timeout_ms = -1) const {
    if (timeout_ms < 0) timeout_ms = OpTimeoutMs();
    auto* p = static_cast<const uint8_t*>(data);
    int64_t deadline = timeout_ms > 0 ? NowMs() + timeout_ms : -1;
    while (n > 0) {
      WaitReady(fd_, POLLOUT, deadline, "send (HVT_OP_TIMEOUT_MS)");
      ssize_t k = ::send(fd_, p, n, MSG_DONTWAIT | MSG_NOSIGNAL);
      if (k < 0 && (errno == EAGAIN || errno == EWOULDBLOCK ||
                    errno == EINTR))
        continue;
      if (k <= 0) throw PeerLostError("hvt: send failed (peer lost)");
      p += k;
      n -= static_cast<size_t>(k);
      if (deadline >= 0) deadline = NowMs() + timeout_ms;  // progress
    }
  }
  void RecvAll(void* data, size_t n, int64_t timeout_ms = -1) const {
    if (timeout_ms < 0) timeout_ms = OpTimeoutMs();
    auto* p = static_cast<uint8_t*>(data);
    int64_t deadline = timeout_ms > 0 ? NowMs() + timeout_ms : -1;
    while (n > 0) {
      WaitReady(fd_, POLLIN, deadline, "recv (HVT_OP_TIMEOUT_MS)");
      ssize_t k = ::recv(fd_, p, n, MSG_DONTWAIT);
      if (k < 0 && (errno == EAGAIN || errno == EWOULDBLOCK ||
                    errno == EINTR))
        continue;
      if (k <= 0) throw PeerLostError("hvt: recv failed (peer lost)");
      p += k;
      n -= static_cast<size_t>(k);
      if (deadline >= 0) deadline = NowMs() + timeout_ms;  // progress
    }
  }
  // Nonblocking best-effort send/recv (MSG_DONTWAIT — the socket itself
  // stays blocking for SendAll/RecvAll users). Return bytes moved, 0 when
  // the operation would block; throw on a lost peer.
  size_t SendSome(const void* data, size_t n) const {
    ssize_t k = ::send(fd_, data, n, MSG_DONTWAIT | MSG_NOSIGNAL);
    if (k >= 0) return static_cast<size_t>(k);
    if (errno == EAGAIN || errno == EWOULDBLOCK || errno == EINTR) return 0;
    throw PeerLostError("hvt: send failed (peer lost)");
  }
  size_t RecvSome(void* data, size_t n) const {
    ssize_t k = ::recv(fd_, data, n, MSG_DONTWAIT);
    if (k > 0) return static_cast<size_t>(k);
    if (k == 0) throw PeerLostError("hvt: recv failed (peer lost)");
    if (errno == EAGAIN || errno == EWOULDBLOCK || errno == EINTR) return 0;
    throw PeerLostError("hvt: recv failed (peer lost)");
  }
  // Length-prefixed frames for control messages. A vectored send
  // coalesces the 8-byte header with the payload into one syscall/TCP
  // segment — two separate send()s cost a spare syscall per frame and,
  // without TCP_NODELAY, a Nagle stall. sendmsg (not writev) so
  // MSG_NOSIGNAL applies: a lost peer must surface as the catchable
  // "peer lost" error, not SIGPIPE.
  void SendFrame(const std::vector<uint8_t>& b,
                 int64_t timeout_ms = -1) const {
    uint64_t n = b.size();
    struct iovec iov[2];
    iov[0].iov_base = &n;
    iov[0].iov_len = 8;
    iov[1].iov_base = const_cast<uint8_t*>(b.data());
    iov[1].iov_len = b.size();
    struct msghdr msg {};
    msg.msg_iov = iov;
    msg.msg_iovlen = n ? 2 : 1;
    size_t total = 8 + b.size();
    // nonblocking first try: a full socket buffer (e.g. a stalled peer)
    // must fall through to the deadline-bounded byte-wise path, never
    // wedge inside a blocking sendmsg
    ssize_t k = ::sendmsg(fd_, &msg, MSG_DONTWAIT | MSG_NOSIGNAL);
    if (k < 0) {
      if (errno != EINTR && errno != EAGAIN && errno != EWOULDBLOCK)
        throw PeerLostError("hvt: send failed (peer lost)");
      k = 0;  // nothing moved: finish byte-wise
    }
    if (static_cast<size_t>(k) == total) return;
    // short write (socket buffer full mid-frame): finish byte-wise
    size_t done = static_cast<size_t>(k);
    if (done < 8) {
      SendAll(reinterpret_cast<const uint8_t*>(&n) + done, 8 - done,
              timeout_ms);
      done = 8;
    }
    if (done - 8 < b.size())
      SendAll(b.data() + (done - 8), b.size() - (done - 8), timeout_ms);
  }
  std::vector<uint8_t> RecvFrame(int64_t timeout_ms = -1) const {
    uint64_t n = 0;
    RecvAll(&n, 8, timeout_ms);
    std::vector<uint8_t> b(n);
    if (n) RecvAll(b.data(), n, timeout_ms);
    return b;
  }

  // Single connect attempt with a short bounded wait (nonblocking
  // connect + poll) — the reconnect engine's dial primitive. Returns an
  // invalid Sock on any failure (refused, timeout, resolve error); the
  // caller owns the retry/backoff policy, unlike Connect below which
  // retries internally for the whole rendezvous budget.
  static Sock DialOnce(const std::string& host, int port,
                       int timeout_ms = 1000) {
    addrinfo hints{}, *res = nullptr;
    hints.ai_family = AF_INET;
    hints.ai_socktype = SOCK_STREAM;
    std::string p = std::to_string(port);
    if (getaddrinfo(host.c_str(), p.c_str(), &hints, &res) != 0 || !res)
      return Sock();
    int fd = ::socket(res->ai_family, res->ai_socktype,
                      res->ai_protocol);
    if (fd < 0) {
      freeaddrinfo(res);
      return Sock();
    }
    int fl = fcntl(fd, F_GETFL, 0);
    fcntl(fd, F_SETFL, fl | O_NONBLOCK);
    int rc = ::connect(fd, res->ai_addr, res->ai_addrlen);
    freeaddrinfo(res);
    if (rc != 0) {
      if (errno != EINPROGRESS) {
        ::close(fd);
        return Sock();
      }
      struct pollfd pd {fd, POLLOUT, 0};
      if (::poll(&pd, 1, timeout_ms) <= 0) {
        ::close(fd);
        return Sock();
      }
      int err = 0;
      socklen_t len = sizeof(err);
      getsockopt(fd, SOL_SOCKET, SO_ERROR, &err, &len);
      if (err != 0) {
        ::close(fd);
        return Sock();
      }
    }
    fcntl(fd, F_SETFL, fl);  // back to blocking for the Sock contract
    int one = 1;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    ConfigureSockBufs(fd);
    return Sock(fd);
  }

  static Sock Connect(const std::string& host, int port,
                      int timeout_sec = 60) {
    // HVT_CONNECT_TIMEOUT (seconds) overrides the caller's budget —
    // slow pods need more than the default startup window
    if (const char* v = getenv("HVT_CONNECT_TIMEOUT")) {
      int t = atoi(v);
      if (t > 0) timeout_sec = t;
    }
    addrinfo hints{}, *res = nullptr;
    hints.ai_family = AF_INET;
    hints.ai_socktype = SOCK_STREAM;
    std::string p = std::to_string(port);
    if (getaddrinfo(host.c_str(), p.c_str(), &hints, &res) != 0 || !res)
      throw std::runtime_error("hvt: getaddrinfo failed for " + host);
    int fd = -1;
    // Retry loop: peers come up in arbitrary order. Exponential backoff
    // with jitter (10 ms → 1 s) instead of a fixed 100 ms spin: at pod
    // scale thousands of workers re-dialing a late rank 0 in lockstep
    // is a listen-backlog thundering herd; jitter decorrelates them.
    int64_t deadline = NowMs() + int64_t{timeout_sec} * 1000;
    unsigned seed = static_cast<unsigned>(NowMs() ^ (port << 8) ^
                                          reinterpret_cast<uintptr_t>(&fd));
    int64_t backoff_ms = 10;
    while (true) {
      fd = ::socket(res->ai_family, res->ai_socktype, res->ai_protocol);
      if (fd >= 0) {
        if (::connect(fd, res->ai_addr, res->ai_addrlen) == 0) break;
        ::close(fd);
        fd = -1;
      }
      if (NowMs() >= deadline) break;
      // ±25% jitter around the current backoff, clamped to the deadline
      int64_t jitter = backoff_ms / 4;
      int64_t sleep_ms = backoff_ms - jitter +
                         (jitter > 0
                              ? static_cast<int64_t>(rand_r(&seed)) %
                                    (2 * jitter + 1)
                              : 0);
      int64_t left = deadline - NowMs();
      if (sleep_ms > left) sleep_ms = left;
      if (sleep_ms > 0) {
        struct timespec ts {sleep_ms / 1000, (sleep_ms % 1000) * 1000000};
        nanosleep(&ts, nullptr);
      }
      backoff_ms = backoff_ms < 1000 ? backoff_ms * 2 : 1000;
    }
    freeaddrinfo(res);
    if (fd < 0)
      throw OpTimeoutError("hvt: connect to " + host + ":" + p +
                           " timed out after " +
                           std::to_string(timeout_sec) +
                           " s (HVT_CONNECT_TIMEOUT)");
    int one = 1;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    ConfigureSockBufs(fd);
    return Sock(fd);
  }

 private:
  int fd_ = -1;
};

class Listener {
 public:
  // port==0 → ephemeral; bound port readable via port().
  void Listen(int port) {
    fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
    if (fd_ < 0) throw std::runtime_error("hvt: socket() failed");
    int one = 1;
    setsockopt(fd_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_addr.s_addr = htonl(INADDR_ANY);
    addr.sin_port = htons(static_cast<uint16_t>(port));
    if (::bind(fd_, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0)
      throw std::runtime_error("hvt: bind failed on port " +
                               std::to_string(port));
    if (::listen(fd_, 128) != 0)
      throw std::runtime_error("hvt: listen failed");
    socklen_t len = sizeof(addr);
    getsockname(fd_, reinterpret_cast<sockaddr*>(&addr), &len);
    port_ = ntohs(addr.sin_port);
  }
  // Bounded single accept for the reconnect engine: returns an invalid
  // Sock when nothing dialed in within timeout_ms (never throws on
  // timeout — the caller owns the episode budget).
  Sock TryAccept(int timeout_ms) const {
    if (fd_ < 0) return Sock();
    struct pollfd pd {fd_, POLLIN, 0};
    int rc = ::poll(&pd, 1, timeout_ms);
    if (rc <= 0) return Sock();
    int c = ::accept(fd_, nullptr, nullptr);
    if (c < 0) return Sock();
    int one = 1;
    setsockopt(c, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    ConfigureSockBufs(c);
    return Sock(c);
  }
  Sock Accept(int timeout_sec = 60) const {
    // bounded like Connect (HVT_CONNECT_TIMEOUT): a peer that never
    // dials in must fail the rendezvous, not hang it
    if (const char* v = getenv("HVT_CONNECT_TIMEOUT")) {
      int t = atoi(v);
      if (t > 0) timeout_sec = t;
    }
    WaitReady(fd_, POLLIN, NowMs() + int64_t{timeout_sec} * 1000,
              "accept (HVT_CONNECT_TIMEOUT)");
    int c = ::accept(fd_, nullptr, nullptr);
    if (c < 0) throw std::runtime_error("hvt: accept failed");
    int one = 1;
    setsockopt(c, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    ConfigureSockBufs(c);
    return Sock(c);
  }
  int port() const { return port_; }
  void Close() {
    if (fd_ >= 0) {
      ::close(fd_);
      fd_ = -1;
    }
  }
  ~Listener() { Close(); }

 private:
  int fd_ = -1;
  int port_ = 0;
};

}  // namespace hvt
