// TCP transport — the engine's DCN fabric. Replaces the reference's
// MPI/Gloo contexts (horovod/common/mpi/mpi_context.h:96,
// horovod/common/gloo/gloo_context.cc): a control star (workers → rank 0)
// plus a lazily-connected full mesh for the data plane. Endpoint discovery
// happens over the control star at init, the analog of the Gloo HTTP-store
// rendezvous.
#pragma once

#include <arpa/inet.h>
#include <errno.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <sys/uio.h>
#include <unistd.h>

#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <mutex>
#include <stdexcept>
#include <string>
#include <vector>

namespace hvt {

// HVT_SOCK_BUF: explicit SO_SNDBUF/SO_RCVBUF for every data/control
// socket (bytes; 0/unset → kernel autotuning). Large rings on fat pipes
// want this well above the payload chunk size so the nonblocking duplex
// pump can keep both directions moving while the reduce runs.
inline void ConfigureSockBufs(int fd) {
  static const long buf = [] {
    const char* v = getenv("HVT_SOCK_BUF");
    return v ? atol(v) : 0L;
  }();
  if (buf > 0) {
    int b = static_cast<int>(buf);
    setsockopt(fd, SOL_SOCKET, SO_SNDBUF, &b, sizeof(b));
    setsockopt(fd, SOL_SOCKET, SO_RCVBUF, &b, sizeof(b));
  }
}

class Sock {
 public:
  Sock() = default;
  explicit Sock(int fd) : fd_(fd) {}
  Sock(const Sock&) = delete;
  Sock& operator=(const Sock&) = delete;
  Sock(Sock&& o) noexcept : fd_(o.fd_) { o.fd_ = -1; }
  Sock& operator=(Sock&& o) noexcept {
    Close();
    fd_ = o.fd_;
    o.fd_ = -1;
    return *this;
  }
  ~Sock() { Close(); }

  void Close() {
    if (fd_ >= 0) {
      ::shutdown(fd_, SHUT_RDWR);
      ::close(fd_);
      fd_ = -1;
    }
  }
  bool valid() const { return fd_ >= 0; }
  int fd() const { return fd_; }

  void SendAll(const void* data, size_t n) const {
    auto* p = static_cast<const uint8_t*>(data);
    while (n > 0) {
      ssize_t k = ::send(fd_, p, n, MSG_NOSIGNAL);
      if (k <= 0) throw std::runtime_error("hvt: send failed (peer lost)");
      p += k;
      n -= static_cast<size_t>(k);
    }
  }
  void RecvAll(void* data, size_t n) const {
    auto* p = static_cast<uint8_t*>(data);
    while (n > 0) {
      ssize_t k = ::recv(fd_, p, n, 0);
      if (k <= 0) throw std::runtime_error("hvt: recv failed (peer lost)");
      p += k;
      n -= static_cast<size_t>(k);
    }
  }
  // Nonblocking best-effort send/recv (MSG_DONTWAIT — the socket itself
  // stays blocking for SendAll/RecvAll users). Return bytes moved, 0 when
  // the operation would block; throw on a lost peer.
  size_t SendSome(const void* data, size_t n) const {
    ssize_t k = ::send(fd_, data, n, MSG_DONTWAIT | MSG_NOSIGNAL);
    if (k >= 0) return static_cast<size_t>(k);
    if (errno == EAGAIN || errno == EWOULDBLOCK || errno == EINTR) return 0;
    throw std::runtime_error("hvt: send failed (peer lost)");
  }
  size_t RecvSome(void* data, size_t n) const {
    ssize_t k = ::recv(fd_, data, n, MSG_DONTWAIT);
    if (k > 0) return static_cast<size_t>(k);
    if (k == 0) throw std::runtime_error("hvt: recv failed (peer lost)");
    if (errno == EAGAIN || errno == EWOULDBLOCK || errno == EINTR) return 0;
    throw std::runtime_error("hvt: recv failed (peer lost)");
  }
  // Length-prefixed frames for control messages. A vectored send
  // coalesces the 8-byte header with the payload into one syscall/TCP
  // segment — two separate send()s cost a spare syscall per frame and,
  // without TCP_NODELAY, a Nagle stall. sendmsg (not writev) so
  // MSG_NOSIGNAL applies: a lost peer must surface as the catchable
  // "peer lost" error, not SIGPIPE.
  void SendFrame(const std::vector<uint8_t>& b) const {
    uint64_t n = b.size();
    struct iovec iov[2];
    iov[0].iov_base = &n;
    iov[0].iov_len = 8;
    iov[1].iov_base = const_cast<uint8_t*>(b.data());
    iov[1].iov_len = b.size();
    struct msghdr msg {};
    msg.msg_iov = iov;
    msg.msg_iovlen = n ? 2 : 1;
    size_t total = 8 + b.size();
    ssize_t k = ::sendmsg(fd_, &msg, MSG_NOSIGNAL);
    if (k < 0) {
      if (errno != EINTR)
        throw std::runtime_error("hvt: send failed (peer lost)");
      k = 0;  // interrupted before any byte moved: finish byte-wise
    }
    if (static_cast<size_t>(k) == total) return;
    // short write (socket buffer full mid-frame): finish byte-wise
    size_t done = static_cast<size_t>(k);
    if (done < 8) {
      SendAll(reinterpret_cast<const uint8_t*>(&n) + done, 8 - done);
      done = 8;
    }
    if (done - 8 < b.size()) SendAll(b.data() + (done - 8), b.size() - (done - 8));
  }
  std::vector<uint8_t> RecvFrame() const {
    uint64_t n = 0;
    RecvAll(&n, 8);
    std::vector<uint8_t> b(n);
    if (n) RecvAll(b.data(), n);
    return b;
  }

  static Sock Connect(const std::string& host, int port,
                      int timeout_sec = 60) {
    addrinfo hints{}, *res = nullptr;
    hints.ai_family = AF_INET;
    hints.ai_socktype = SOCK_STREAM;
    std::string p = std::to_string(port);
    if (getaddrinfo(host.c_str(), p.c_str(), &hints, &res) != 0 || !res)
      throw std::runtime_error("hvt: getaddrinfo failed for " + host);
    int fd = -1;
    // retry loop: peers come up in arbitrary order
    for (int attempt = 0; attempt < timeout_sec * 10; ++attempt) {
      fd = ::socket(res->ai_family, res->ai_socktype, res->ai_protocol);
      if (fd < 0) continue;
      if (::connect(fd, res->ai_addr, res->ai_addrlen) == 0) break;
      ::close(fd);
      fd = -1;
      struct timespec ts {0, 100000000};  // 100 ms
      nanosleep(&ts, nullptr);
    }
    freeaddrinfo(res);
    if (fd < 0)
      throw std::runtime_error("hvt: connect to " + host + ":" + p +
                               " timed out");
    int one = 1;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    ConfigureSockBufs(fd);
    return Sock(fd);
  }

 private:
  int fd_ = -1;
};

class Listener {
 public:
  // port==0 → ephemeral; bound port readable via port().
  void Listen(int port) {
    fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
    if (fd_ < 0) throw std::runtime_error("hvt: socket() failed");
    int one = 1;
    setsockopt(fd_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_addr.s_addr = htonl(INADDR_ANY);
    addr.sin_port = htons(static_cast<uint16_t>(port));
    if (::bind(fd_, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0)
      throw std::runtime_error("hvt: bind failed on port " +
                               std::to_string(port));
    if (::listen(fd_, 128) != 0)
      throw std::runtime_error("hvt: listen failed");
    socklen_t len = sizeof(addr);
    getsockname(fd_, reinterpret_cast<sockaddr*>(&addr), &len);
    port_ = ntohs(addr.sin_port);
  }
  Sock Accept() const {
    int c = ::accept(fd_, nullptr, nullptr);
    if (c < 0) throw std::runtime_error("hvt: accept failed");
    int one = 1;
    setsockopt(c, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    ConfigureSockBufs(c);
    return Sock(c);
  }
  int port() const { return port_; }
  void Close() {
    if (fd_ >= 0) {
      ::close(fd_);
      fd_ = -1;
    }
  }
  ~Listener() { Close(); }

 private:
  int fd_ = -1;
  int port_ = 0;
};

}  // namespace hvt
