// Engine-side Chrome-trace timeline — counterpart of the reference's
// C++ Timeline (horovod/common/timeline.{h,cc}): every tensor's
// lifecycle is recorded as chrome://tracing events (NEGOTIATE_<OP> with
// per-rank ready instants, then the execute phase), produced by the
// engine thread and drained to disk by a dedicated writer thread so the
// cycle loop never blocks on file I/O (the reference uses a lock-free
// SPSC queue, timeline.h:84-86; a mutexed deque swapped wholesale by the
// writer gives the same non-blocking property at engine-cycle rates).
//
// Like the reference (operations.cc:422-425), only the coordinator
// (rank 0) writes a file; enabled via HVT_TIMELINE=<path>, optional
// cycle markers via HVT_TIMELINE_MARK_CYCLES=1.
#pragma once

#include <chrono>
#include <cstdio>
#include <deque>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>

#include "thread_annotations.h"

namespace hvt {

class EngineTimeline {
 public:
  void Initialize(const std::string& path, bool mark_cycles) {
    MutexLock lk(mu_);
    if (file_) return;
    file_ = fopen(path.c_str(), "w");
    if (!file_) return;
    fputs("[\n", file_);
    // full reset: re-entered on elastic shutdown/re-init, and the new
    // trace file must not inherit lanes or the written-something flag
    first_ = true;
    lanes_.clear();
    lane_names_.clear();
    queue_.clear();
    next_lane_ = 0;
    mark_cycles_ = mark_cycles;
    start_us_ = NowUs();
    stop_ = false;
    writer_ = std::thread([this] { WriterLoop(); });
  }

  bool active() const { return file_ != nullptr; }
  bool mark_cycles() const { return mark_cycles_; }

  void NegotiateStart(const std::string& tensor, const std::string& op) {
    Emit(tensor, "B", "NEGOTIATE_" + op);
  }
  void NegotiateRankReady(const std::string& tensor, int rank) {
    Emit(tensor, "i", "RANK_READY_" + std::to_string(rank));
  }
  void NegotiateEnd(const std::string& tensor) { Emit(tensor, "E", ""); }
  void ExecuteStart(const std::string& tensor, const std::string& op) {
    Emit(tensor, "B", op);
  }
  void ExecuteEnd(const std::string& tensor) { Emit(tensor, "E", ""); }
  void CycleMark() { Emit("CYCLE", "i", "CYCLE_START"); }

  void Shutdown() {
    {
      MutexLock lk(mu_);
      if (!file_) return;
      stop_ = true;
    }
    if (writer_.joinable()) writer_.join();
    Drain();
    fputs("\n]\n", file_);
    fclose(file_);
    file_ = nullptr;
  }

 private:
  struct Event {
    int64_t ts_us;
    int lane;
    char phase;         // B / E / i
    std::string name;
  };

  static std::string JsonEscape(const std::string& s) {
    std::string out;
    out.reserve(s.size());
    for (char c : s) {
      if (c == '"' || c == '\\') {
        out += '\\';
        out += c;
      } else if (static_cast<unsigned char>(c) < 0x20) {
        char buf[8];
        snprintf(buf, sizeof(buf), "\\u%04x", c);
        out += buf;
      } else {
        out += c;
      }
    }
    return out;
  }

  static int64_t NowUs() {
    return std::chrono::duration_cast<std::chrono::microseconds>(
               std::chrono::steady_clock::now().time_since_epoch())
        .count();
  }

  void Emit(const std::string& tensor, const char* phase,
            const std::string& name) {
    MutexLock lk(mu_);
    if (!file_) return;
    auto it = lanes_.find(tensor);
    int lane;
    if (it == lanes_.end()) {
      lane = next_lane_++;
      lanes_[tensor] = lane;
      lane_names_.push_back({lane, tensor});
    } else {
      lane = it->second;
    }
    queue_.push_back(Event{NowUs() - start_us_, lane, phase[0], name});
  }

  void WriterLoop() {
    while (true) {
      {
        MutexLock lk(mu_);
        if (stop_) return;
      }
      Drain();
      std::this_thread::sleep_for(std::chrono::milliseconds(100));
    }
  }

  void Drain() {
    std::deque<Event> local;
    std::deque<std::pair<int, std::string>> names;
    {
      MutexLock lk(mu_);
      local.swap(queue_);
      names.swap(lane_names_);
    }
    for (auto& [lane, tensor] : names) {
      fprintf(file_,
              "%s{\"name\": \"thread_name\", \"ph\": \"M\", \"pid\": 0, "
              "\"tid\": %d, \"args\": {\"name\": \"%s\"}}",
              first_ ? "" : ",\n", lane, JsonEscape(tensor).c_str());
      first_ = false;
    }
    for (auto& e : local) {
      std::string esc = e.name.empty() ? "" : JsonEscape(e.name);
      fprintf(file_,
              "%s{\"ph\": \"%c\", \"pid\": 0, \"tid\": %d, "
              "\"ts\": %lld%s%s%s%s}",
              first_ ? "" : ",\n", e.phase, e.lane,
              static_cast<long long>(e.ts_us),
              e.name.empty() ? "" : ", \"name\": \"",
              esc.c_str(),
              e.name.empty() ? "" : "\"",
              e.phase == 'i' ? ", \"s\": \"t\"" : "");
      first_ = false;
    }
    fflush(file_);
  }

  Mutex mu_;
  // file_ / first_ / mark_cycles_ / start_us_ are writer-thread (and
  // Initialize/Shutdown) state — cross-thread reads are the benign
  // active() flag check, so they stay unguarded by design.
  FILE* file_ = nullptr;
  bool mark_cycles_ = false;
  bool stop_ GUARDED_BY(mu_) = false;
  bool first_ = true;
  int64_t start_us_ = 0;
  int next_lane_ GUARDED_BY(mu_) = 0;
  std::unordered_map<std::string, int> lanes_ GUARDED_BY(mu_);
  std::deque<std::pair<int, std::string>> lane_names_ GUARDED_BY(mu_);
  std::deque<Event> queue_ GUARDED_BY(mu_);
  std::thread writer_;
};

}  // namespace hvt
