// Transport seam + self-healing TCP links.
//
// The narrow interface the data/control planes code against
// (ROADMAP item 5: the io_uring/RDMA backends plug in HERE), plus the
// one implementation this build ships: TcpLink, a session layer over
// net.h's raw Sock that makes a transient connection drop a
// RECOVERABLE event instead of a gang-wide abort.
//
// Wire-level sessions: every link counts the bytes it has ever sent
// (tx) and consumed (rx) — per-direction stream sequence numbers — and
// the sender keeps a bounded replay ring of the most recent tx bytes
// (HVT_REPLAY_BUDGET_BYTES). When a connection drops (ECONNRESET /
// FIN / EPIPE), the link transitions HEALTHY → RECONNECTING: the side
// that originally dialed re-dials, the side that accepted re-accepts
// on its listener, and a handshake exchanges (session epoch, rx
// offset) in both directions. Each sender rewinds to the peer's rx
// offset and replays the missing bytes from its ring, so the stream
// resumes EXACTLY where the receiver left off — a collective in
// flight completes bit-identically, with no renegotiation and no
// tensor loss. Only an exhausted retry budget (HVT_LINK_RETRIES /
// HVT_LINK_RETRY_WINDOW_MS), a replay gap the ring cannot cover, or a
// deliberate Abort() escalates into the PR 4 containment path
// (PeerLostError → EnterBroken), which is unchanged.
//
// Deadlines still mean what they meant: an OpTimeoutError (stalled but
// CONNECTED peer, missed idle heartbeat) is NOT retried — reconnecting
// to a wedged peer fixes nothing — so the heartbeat/timeout abort
// classes behave exactly as PR 4 pinned them.
//
// Thread-safety: a link is used by ONE thread at a time, but since the
// per-lane execution pool (engine.cc, HVT_LANE_WORKERS) that thread is
// no longer always the engine thread: disjoint serving lanes pump
// disjoint link sets concurrently. Every blocking/nonblocking transfer
// claims the link for its duration (LinkClaim — a per-link owner-token
// CAS), and a sibling sweep's ProbeAndRepair try-claims and SKIPS links
// another thread holds, so two threads can never race a socket or a
// heal. The state/epoch/retry fields read by the diagnostics snapshot
// are relaxed atomics — UpdateDiag may now copy them while a worker
// thread heals the link.
#pragma once

#include <algorithm>
#include <atomic>
#include <deque>
#include <functional>
#include <map>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <utility>
#include <vector>

#include "common.h"
#include "events.h"
#include "net.h"
#include "wire.h"

namespace hvt {

// Link planes — the {plane} label of hvt_link_reconnects_total and the
// index into EngineStats::link_reconnects. Wire ids (stats-slot ABI).
enum class LinkPlane : int { CTRL = 0, DATA = 1 };
constexpr int kLinkPlanes = 2;
inline const char* LinkPlaneName(LinkPlane p) {
  return p == LinkPlane::CTRL ? "ctrl" : "data";
}

enum class LinkState : int { HEALTHY = 0, RECONNECTING = 1, DEAD = 2 };
inline const char* LinkStateName(LinkState s) {
  switch (s) {
    case LinkState::HEALTHY: return "healthy";
    case LinkState::RECONNECTING: return "reconnecting";
    case LinkState::DEAD: return "dead";
  }
  return "?";
}

// HVT_LINK_RECONNECT (default 1): 0 restores the PR 4 behavior — any
// socket failure escalates straight to the coordinated abort.
inline bool LinkReconnectEnabled() {
  static const bool on = EnvInt("HVT_LINK_RECONNECT", 1) != 0;
  return on;
}
// HVT_LINK_RETRIES (default 10): dial attempts per reconnect episode.
// A dead peer's listener refuses instantly, so this bounds dead-peer
// detection to ~seconds while a live-but-flapping peer gets the full
// window below.
inline int64_t LinkRetries() {
  static const int64_t n = EnvInt("HVT_LINK_RETRIES", 10);
  return n;
}
// HVT_LINK_RETRY_WINDOW_MS: wall-clock budget per reconnect episode.
// Default = one op deadline capped at 10 s — so an abort that must
// happen (peer truly dead) still converges on the PR 4 clock, and a
// transparent heal always finishes before a HEALTHY neighbor's own
// progress deadline fires.
inline int64_t LinkRetryWindowMs() {
  static const int64_t ms = [] {
    int64_t v = EnvInt("HVT_LINK_RETRY_WINDOW_MS", 0);
    if (v > 0) return v;
    int64_t op = OpTimeoutMs();
    return op > 0 && op < 10000 ? op : int64_t{10000};
  }();
  return ms;
}
// HVT_REPLAY_BUDGET_BYTES (default 8 MB, 0 disables replay): per-link
// sender-side replay ring. Must cover the bytes a drop can lose —
// both sockets' kernel buffers plus in-flight — or the reconnect
// escalates with a budget-exhausted reason.
inline int64_t ReplayBudgetBytes() {
  static const int64_t b = EnvInt("HVT_REPLAY_BUDGET_BYTES", 8 << 20);
  return b < 0 ? 0 : b;
}

// --------------------------------------------------------------------------
// replay ring — a circular window over the sender's byte stream
// --------------------------------------------------------------------------
class ReplayRing {
 public:
  explicit ReplayRing(int64_t budget) : budget_(budget) {}

  // Stream offsets currently covered: [start(), end()).
  int64_t start() const { return end_ - size_; }
  int64_t end() const { return end_; }
  bool Covers(int64_t from) const {
    return from >= start() && from <= end_;
  }

  // Append n freshly-sent bytes (stream position end()..end()+n),
  // evicting the oldest bytes past the budget. The backing buffer
  // grows geometrically up to the budget (a control link whose whole
  // history is a few KB never pays the full 8 MiB — at fleet scale the
  // per-link rings would otherwise cost O(ranks) x budget per rank).
  void Append(const void* p, int64_t n) {
    if (budget_ <= 0 || n <= 0) {
      end_ += n > 0 ? n : 0;
      size_ = 0;
      return;
    }
    EnsureCap(std::min(size_ + n, budget_));
    auto* src = static_cast<const uint8_t*>(p);
    if (n >= cap_) {  // only the newest cap_ bytes survive
      src += n - cap_;
      end_ += n;
      size_ = cap_;
      head_ = 0;
      memcpy(buf_.data(), src, static_cast<size_t>(cap_));
      return;
    }
    int64_t w = (head_ + size_) % cap_;  // write cursor
    int64_t first = std::min(n, cap_ - w);
    memcpy(buf_.data() + w, src, static_cast<size_t>(first));
    if (n > first)
      memcpy(buf_.data(), src + first, static_cast<size_t>(n - first));
    end_ += n;
    size_ += n;
    if (size_ > cap_) {  // evicted the oldest
      head_ = (head_ + (size_ - cap_)) % cap_;
      size_ = cap_;
    }
  }

  // Contiguous view starting at stream offset `from` (must be covered
  // and < end()): returns (ptr, len) of at most the bytes up to the
  // ring's wraparound point — call again for the rest.
  std::pair<const uint8_t*, int64_t> Peek(int64_t from) const {
    int64_t off = from - start();          // offset into the window
    int64_t pos = (head_ + off) % cap_;    // physical position
    int64_t len = std::min(size_ - off, cap_ - pos);
    return {buf_.data() + pos, len};
  }

 private:
  // Grow the backing buffer (unwrapping the stored window) so at least
  // `want` bytes fit: powers of two from 64 KiB, capped at the budget.
  void EnsureCap(int64_t want) {
    if (want <= cap_) return;
    int64_t cap = cap_ > 0 ? cap_ : std::min<int64_t>(64 << 10, budget_);
    while (cap < want && cap < budget_) cap *= 2;
    if (cap > budget_) cap = budget_;
    if (cap == cap_) return;
    std::vector<uint8_t> nb(static_cast<size_t>(cap));
    if (size_ > 0) {
      int64_t first = std::min(size_, cap_ - head_);
      memcpy(nb.data(), buf_.data() + head_,
             static_cast<size_t>(first));
      if (size_ > first)
        memcpy(nb.data() + first, buf_.data(),
               static_cast<size_t>(size_ - first));
    }
    buf_ = std::move(nb);
    head_ = 0;
    cap_ = cap;
  }

  std::vector<uint8_t> buf_;  // allocated lazily, grown geometrically
  int64_t budget_;
  int64_t cap_ = 0;   // current backing capacity (≤ budget_)
  int64_t head_ = 0;  // physical index of stream offset start()
  int64_t size_ = 0;  // bytes stored
  int64_t end_ = 0;   // stream offset just past the newest byte
};

// --------------------------------------------------------------------------
// Transport — the seam
// --------------------------------------------------------------------------
// What a data/control plane needs from a connection, and nothing else:
// blocking deadline-bounded transfers, nonblocking best-effort moves
// for the duplex pump (fd() feeds its poll set), length-prefixed
// frames, and a hard Abort. A future io_uring/RDMA backend implements
// exactly this.
class Transport {
 public:
  virtual ~Transport() = default;
  virtual bool valid() const = 0;
  virtual int fd() const = 0;  // for poll(); may change across reconnects
  virtual void Send(const void* p, size_t n, int64_t timeout_ms = -1) = 0;
  virtual void Recv(void* p, size_t n, int64_t timeout_ms = -1) = 0;
  // Nonblocking: bytes moved, 0 = would block; throws on escalation.
  virtual size_t SendSome(const void* p, size_t n) = 0;
  virtual size_t RecvSome(void* p, size_t n) = 0;
  virtual void SendFrame(const std::vector<uint8_t>& b,
                         int64_t timeout_ms = -1) = 0;
  virtual std::vector<uint8_t> RecvFrame(int64_t timeout_ms = -1) = 0;
  // Hard close: the link goes DEAD, no reconnect — the PR 4 abort path.
  virtual void Abort() = 0;
  // Called when a caller's wait on THIS transport times out with
  // nothing ready: a hook for housekeeping only the (single) engine
  // thread can do — TcpLink services the engine's other broken links
  // here, because a peer stuck waiting for OUR dial can never be
  // helped while we block on a different, healthy connection.
  virtual void Idle() {}
  // Monotonic heal counter (TcpLink: the session epoch). A caller
  // whose nonblocking Some() call returned 0 can compare generations
  // to tell "nothing happened" from "the link spent seconds healing
  // underneath me" — the latter must re-arm progress deadlines, since
  // the heal just proved the peer alive.
  virtual int64_t Generation() const { return 0; }
  // Optional whole-transfer fast path for the full-duplex ring pump:
  // stream send_n bytes out of THIS transport while receiving recv_n
  // from `in` (which may be this same object on 2-member rings),
  // advancing `sent`/`rcvd` and firing on_progress after each receive
  // completion so chunk reduces overlap the transfer. Best-effort by
  // contract: a backend may return at ANY point with partial progress
  // — the caller's generic poll+Some() loop (ring_ops.cc Duplex) owns
  // every session-layer event (replay, heal, escalation) and finishes
  // the remainder. The base transport has no batched path; IoUringLink
  // (uring_link.h) overrides this with the one-enter-per-step pump.
  virtual void PumpDuplex(Transport& in, const uint8_t* send_buf,
                          size_t send_n, uint8_t* recv_buf,
                          size_t recv_n, size_t chunk_bytes,
                          size_t& sent, size_t& rcvd,
                          const std::function<void()>& on_progress) {
    (void)in;
    (void)send_buf;
    (void)send_n;
    (void)recv_buf;
    (void)recv_n;
    (void)chunk_bytes;
    (void)sent;
    (void)rcvd;
    (void)on_progress;
  }
};

class TcpLink;
struct ReconnectHub;
// While one link's reconnect episode waits, repair the engine's other
// broken links (defined after TcpLink; see the full comment there).
inline void ServiceSiblingLinks(ReconnectHub* hub, TcpLink* busy);

// Small monotonically-assigned per-thread id used as the link owner
// token (std::thread::id is not CAS-friendly). 0 is reserved for
// "unowned".
inline uint64_t LinkThreadToken() {
  static std::atomic<uint64_t> next{1};
  thread_local uint64_t tok = next.fetch_add(1);
  return tok;
}

// Shared reconnect state, owned by the engine (one per engine run):
// the parking lot for accepted-but-not-mine reconnect dials, the
// telemetry sinks (EngineStats fields — they outlive every link), and
// the global gates (shutdown flag, containment close, partition hold).
struct ReconnectHub {
  // telemetry sinks, bound by the engine at Init (may be null in
  // unit-test contexts): reconnects is an array of kLinkPlanes
  std::atomic<int64_t>* reconnects = nullptr;
  std::atomic<int64_t>* frames_replayed = nullptr;
  std::atomic<int64_t>* replay_bytes = nullptr;
  // io_uring backend telemetry (uring_link.cc flushes per pump; null
  // under the tcp backend or in unit-test contexts)
  std::atomic<int64_t>* uring_sqes = nullptr;
  std::atomic<int64_t>* uring_enters = nullptr;
  std::atomic<int64_t>* uring_cqes = nullptr;
  EventRing* events = nullptr;
  // engine gates
  std::atomic<bool>* stop = nullptr;    // engine shutdown_requested_
  std::atomic<bool> closed{false};      // EnterBroken: reconnects refuse
  // partition fault: heal no earlier than this. Atomic: the chaos
  // injector arms it on the engine thread while lane-pool workers read
  // it inside their own reconnect episodes.
  std::atomic<int64_t> hold_until_ms{0};
  int my_rank = 0;
  // Abort sniffing: the engine sets abort_flag to its control-frame
  // abort bit (wire.h kAbortFrameFlag); sibling sweeps then PEEK
  // queued control frames and set remote_abort_seen when one carries
  // it — so a rank stuck in a reconnect episode learns the gang is
  // already tearing down and escalates NOW instead of waiting out a
  // retry window per hop of the abort cascade (the PR 4 "~one
  // deadline" convergence clock).
  uint8_t abort_flag = 0;
  std::atomic<bool> remote_abort_seen{false};
  // a reconnect dial whose HELLO names another link parks here until
  // that link's own ReAccept adopts it (keyed (plane, peer rank))
  struct Parked {
    Sock sock;
    int64_t peer_epoch = 0;
    int64_t peer_rx = 0;
  };
  // guarded by parked_mu: two threads (engine + a lane-pool worker, or
  // two workers) can run acceptor-side reconnects concurrently, each
  // parking dials the other's link owns
  std::mutex parked_mu;
  std::map<std::pair<int, int>, Parked> parked;
  // live links — registered/unregistered only at Init/Shutdown (no
  // lane workers running), so sweeps iterate it without a lock; the
  // diagnostics snapshot and the chaos injector walk this instead of
  // widening the Transport seam
  std::vector<TcpLink*> links;

  void Reset() {
    closed.store(false);
    hold_until_ms.store(0);
    remote_abort_seen.store(false);
    std::lock_guard<std::mutex> lk(parked_mu);
    parked.clear();
    // links unregister themselves via ~TcpLink
  }
};

// --------------------------------------------------------------------------
// TcpLink — the self-healing TCP implementation
// --------------------------------------------------------------------------
constexpr int32_t kLinkHelloMagic = 0x4856524C;  // "HVRL"

class TcpLink : public Transport {
 public:
  // dial_host empty → this side ACCEPTED the original connection and
  // re-accepts on `listener` during a reconnect; otherwise this side
  // re-dials dial_host:dial_port.
  TcpLink(Sock sock, LinkPlane plane, int peer_rank, ReconnectHub* hub,
          std::string dial_host = "", int dial_port = 0,
          Listener* listener = nullptr)
      : sock_(std::move(sock)),
        plane_(plane),
        peer_(peer_rank),
        hub_(hub),
        dial_host_(std::move(dial_host)),
        dial_port_(dial_port),
        listener_(listener),
        ring_(ReplayBudgetBytes()),
        state_since_(NowSec()) {
    if (hub_) hub_->links.push_back(this);
  }
  ~TcpLink() override {
    if (hub_)
      for (size_t i = 0; i < hub_->links.size(); ++i)
        if (hub_->links[i] == this) {
          hub_->links.erase(hub_->links.begin() + static_cast<long>(i));
          break;
        }
  }
  TcpLink(const TcpLink&) = delete;
  TcpLink& operator=(const TcpLink&) = delete;

  bool valid() const override {
    return state_ != LinkState::DEAD &&
           (sock_.valid() || state_ == LinkState::RECONNECTING);
  }
  int fd() const override { return sock_.fd(); }
  LinkPlane plane() const { return plane_; }
  int peer_rank() const { return peer_; }
  LinkState state() const { return state_; }
  int64_t epoch() const { return epoch_; }
  int retries() const { return retries_; }
  double state_since_sec() const { return state_since_; }

  // Exclusive-use claim (see the thread-safety note at the top of this
  // file). Reentrant: a frame whose caller already holds the link
  // (Send → SendSome) sees its own token and holds nothing. Contention
  // is rare and short — a sibling sweep's probe (≤ ~0.65 s) on a link
  // whose owner is between pump iterations — so waiting is a yield
  // loop, not a futex.
  class Claim {
   public:
    explicit Claim(TcpLink* l) : l_(l) {
      const uint64_t me = LinkThreadToken();
      if (l_->owner_.load(std::memory_order_relaxed) == me) return;
      uint64_t expect = 0;
      while (!l_->owner_.compare_exchange_weak(
          expect, me, std::memory_order_acquire,
          std::memory_order_relaxed)) {
        expect = 0;
        std::this_thread::yield();
      }
      held_ = true;
    }
    ~Claim() {
      if (held_) l_->owner_.store(0, std::memory_order_release);
    }
    Claim(const Claim&) = delete;
    Claim& operator=(const Claim&) = delete;

   private:
    TcpLink* l_;
    bool held_ = false;
  };
  // Reconnect opt-out for parked side channels (tree members' star
  // socket): a failure throws immediately instead of healing, so the
  // owner can retire the link without a coordinator on the other end.
  void SetReconnect(bool on) { reconnect_ = on; }

  // ---- chaos hooks (HVT_FAULT_INJECT) --------------------------------
  // Close the socket after `more` additional tx bytes — a genuinely
  // mid-transfer cut (flaky_conn); the next I/O heals it.
  void InjectCutAfter(int64_t more) { cut_after_ = tx_ + more; }
  // Close after `more` additional RX bytes: unread kernel-buffered
  // data dies with the socket (RST), so the PEER must replay — the
  // deterministic way to exercise the replay ring under chaos.
  void InjectCutAfterRx(int64_t more) { cut_after_rx_ = rx_ + more; }
  // Cut right now (partition / reset_storm). Transient: state stays
  // HEALTHY, so the next I/O reconnects instead of escalating.
  void InjectCutNow() {
    cut_after_ = -1;
    sock_.Close();
  }

  void Abort() override {
    state_ = LinkState::DEAD;
    state_since_ = NowSec();
    // shutdown WITHOUT close: EnterBroken aborts the links BEFORE
    // joining the lane pool, so a worker may still be blocked in (or
    // about to issue) a syscall on this fd. shutdown wakes it with
    // FIN/RST but keeps the fd number allocated — close() here could
    // let a concurrent reconnect-accept recycle the number under the
    // worker. The fd is reclaimed when the link is destroyed
    // (engine Shutdown tears the DataPlane down after the pool joins).
    sock_.ShutdownOnly();
  }

  void Idle() override { ServiceSiblingLinks(hub_, this); }
  int64_t Generation() const override { return epoch_; }

  // Sibling servicing (called while ANOTHER link's reconnect episode
  // waits — see ServiceSiblingLinks): make remote breakage locally
  // visible by peeking for an unread FIN/RST (never consumes data),
  // then run a single dial+handshake attempt when this side holds the
  // dial role. Never blocks beyond one bounded attempt; a repaired
  // link goes straight back to HEALTHY with its replay armed.
  void ProbeAndRepair() {
    // try-claim: never touch a link another thread is actively driving
    // or already probing — the owner heals its own link in-call, and a
    // concurrent probe would race the socket mid-heal
    const uint64_t me = LinkThreadToken();
    bool held = false;
    if (owner_.load(std::memory_order_relaxed) != me) {
      uint64_t expect = 0;
      if (!owner_.compare_exchange_strong(expect, me,
                                          std::memory_order_acquire,
                                          std::memory_order_relaxed))
        return;
      held = true;
    }
    ProbeAndRepairOwned();
    if (held) owner_.store(0, std::memory_order_release);
  }

  void ProbeAndRepairOwned() {
    if (state_ == LinkState::DEAD || (hub_ && hub_->closed.load()))
      return;
    if (state_ == LinkState::HEALTHY && sock_.valid()) {
      // peek far enough to sniff a queued control frame's flags byte
      // (8-byte length prefix + 1): the engine consumes ctrl frames
      // whole, so a non-busy link's stream always sits at a frame
      // boundary and byte 8 IS the flags byte of the next frame
      uint8_t hdr[9];
      ssize_t k =
          ::recv(sock_.fd(), hdr, sizeof(hdr), MSG_PEEK | MSG_DONTWAIT);
      if (k > 0) {
        if (plane_ == LinkPlane::CTRL && hub_ && hub_->abort_flag &&
            k >= 9 && (hdr[8] & hub_->abort_flag))
          hub_->remote_abort_seen.store(true);
        return;  // live bytes pending — healthy
      }
      if (k < 0 && (errno == EAGAIN || errno == EWOULDBLOCK ||
                    errno == EINTR))
        return;           // quiet and healthy
      sock_.Close();      // FIN (k == 0) or RST — broken
    }
    if (!reconnect_ || !LinkReconnectEnabled() ||
        (hub_ && NowMs() < hub_->hold_until_ms))
      return;
    if (sock_.valid() || dial_host_.empty()) return;
    if (state_ != LinkState::RECONNECTING) {
      state_ = LinkState::RECONNECTING;
      state_since_ = NowSec();
      retries_ = 0;
    }
    // Deliberately does NOT count toward retries_: probes are free
    // attempts made while the engine waits elsewhere — the peer may
    // simply not have noticed the break yet, and burning the owning
    // episode's HVT_LINK_RETRIES budget here would turn a live peer
    // into a spurious "peer is dead" escalation. Bounds are short
    // (one probe must not starve the operation the engine actually
    // blocks on): a ready peer pairs in ms, an unaware one costs
    // ≤ ~0.65 s and is retried next idle round.
    (void)TryDialHandshake(NowMs() + 400, state_since_, 250);
  }

  // ---- blocking deadline-bounded transfers ---------------------------
  // Progress re-arms the deadline; so does a successful in-call heal
  // (visible as an epoch bump) — a reconnect that consumed most of the
  // remaining budget just proved the peer alive, and timing out right
  // after it would turn a healed link into an abort (the Duplex pump
  // re-arms for exactly the same reason).
  void Send(const void* p, size_t n, int64_t timeout_ms = -1) override {
    Claim claim(this);
    if (timeout_ms < 0) timeout_ms = OpTimeoutMs();
    int64_t deadline = timeout_ms > 0 ? NowMs() + timeout_ms : -1;
    auto* src = static_cast<const uint8_t*>(p);
    size_t done = 0;
    while (done < n) {
      PollReady(POLLOUT, deadline, "send (HVT_OP_TIMEOUT_MS)");
      int64_t e0 = epoch_;
      size_t k = SendSome(src + done, n - done);
      done += k;
      if ((k || epoch_ != e0) && deadline >= 0)
        deadline = NowMs() + timeout_ms;
    }
  }
  void Recv(void* p, size_t n, int64_t timeout_ms = -1) override {
    Claim claim(this);
    if (timeout_ms < 0) timeout_ms = OpTimeoutMs();
    int64_t deadline = timeout_ms > 0 ? NowMs() + timeout_ms : -1;
    auto* dst = static_cast<uint8_t*>(p);
    size_t done = 0;
    while (done < n) {
      PollReady(POLLIN, deadline, "recv (HVT_OP_TIMEOUT_MS)");
      int64_t e0 = epoch_;
      size_t k = RecvSome(dst + done, n - done);
      done += k;
      if ((k || epoch_ != e0) && deadline >= 0)
        deadline = NowMs() + timeout_ms;
    }
  }

  // ---- nonblocking best-effort moves (the duplex pump) ---------------
  size_t SendSome(const void* p, size_t n) override {
    Claim claim(this);
    if (!EnsureUsable("send")) return 0;
    // stream order: pending replay bytes precede any new payload
    if (replay_from_ >= 0 && !FlushReplayOnce()) return 0;
    if (replay_from_ >= 0) return 0;
    ssize_t k = ::send(sock_.fd(), p, n, MSG_DONTWAIT | MSG_NOSIGNAL);
    if (k < 0) {
      if (errno == EAGAIN || errno == EWOULDBLOCK || errno == EINTR)
        return 0;
      HandleFailure("send");
      return 0;
    }
    AccountTx(p, k);
    return static_cast<size_t>(k);
  }
  size_t RecvSome(void* p, size_t n) override {
    Claim claim(this);
    if (!EnsureUsable("recv")) return 0;
    ssize_t k = ::recv(sock_.fd(), p, n, MSG_DONTWAIT);
    if (k > 0) {
      AccountRx(k);
      return static_cast<size_t>(k);
    }
    if (k < 0 &&
        (errno == EAGAIN || errno == EWOULDBLOCK || errno == EINTR))
      return 0;
    HandleFailure("recv");
    return 0;
  }

  // ---- length-prefixed frames (control plane) ------------------------
  void SendFrame(const std::vector<uint8_t>& b,
                 int64_t timeout_ms = -1) override {
    uint64_t n = b.size();
    // one contiguous buffer (one syscall, like the old vectored
    // sendmsg) and one ring append — control frames are small
    frame_.resize(8 + b.size());
    memcpy(frame_.data(), &n, 8);
    if (n) memcpy(frame_.data() + 8, b.data(), b.size());
    Send(frame_.data(), frame_.size(), timeout_ms);
    // frame boundary bookkeeping for the frames_replayed counter
    frame_ends_.push_back(tx_);
    while (!frame_ends_.empty() && frame_ends_.front() < ring_.start())
      frame_ends_.pop_front();
  }
  std::vector<uint8_t> RecvFrame(int64_t timeout_ms = -1) override {
    uint64_t n = 0;
    Recv(&n, 8, timeout_ms);
    std::vector<uint8_t> b(n);
    if (n) Recv(b.data(), n, timeout_ms);
    return b;
  }

 protected:
  // Everything below is protected rather than private for exactly one
  // subclass: IoUringLink (uring_link.h) reuses the WHOLE session
  // layer — sockets, replay ring, stream counters, heal machinery —
  // and only replaces how bytes move while a duplex ring step is in
  // flight. Its reaped completions account through the two helpers
  // here so both backends keep bit-identical session state.

  // Stream accounting for k bytes just handed to the kernel from p:
  // replay-ring append, tx_ advance, and the armed chaos cut — the
  // exact side effects of the SendSome syscall path.
  void AccountTx(const void* p, int64_t k) {
    ring_.Append(p, k);
    tx_ += k;
    if (cut_after_ >= 0 && tx_ >= cut_after_) {
      // chaos: flaky_conn armed a mid-transfer cut; both sides see the
      // reset and heal through the replay handshake
      cut_after_ = -1;
      sock_.Close();
    }
  }
  // Stream accounting for k bytes durably delivered to the caller (or
  // its spill buffer): rx_ is what the reconnect handshake reports, so
  // it must count exactly the bytes this side will never re-request.
  void AccountRx(int64_t k) {
    rx_ += k;
    if (cut_after_rx_ >= 0 && rx_ >= cut_after_rx_) {
      cut_after_rx_ = -1;  // chaos: drop the link mid-receive
      sock_.Close();
    }
  }

  // poll for `events` on the current fd, also flushing pending replay
  // whenever the socket turns writable; throws OpTimeoutError at the
  // deadline (NOT retried — stalled-but-alive is a containment case).
  // Idle poll rounds (≤200 ms each) service the engine's OTHER broken
  // links: while this thread blocks here, it is the only actor that
  // can repair them, and a peer may be stuck waiting on exactly that
  // (e.g. rank 0 parked in a control recv while its broken data link
  // is what the peer's reconnect-accept is waiting for).
  void PollReady(short events, int64_t deadline, const char* what) {
    while (true) {
      if (!sock_.valid()) return;  // Some() path will reconnect
      short ev = events;
      if (replay_from_ >= 0) ev |= POLLOUT;
      struct pollfd p {sock_.fd(), ev, 0};
      int wait_ms = 200;
      if (deadline >= 0) {
        int64_t left = deadline - NowMs();
        if (left <= 0)
          throw OpTimeoutError(std::string("hvt: ") + what +
                               " deadline exceeded");
        if (left < wait_ms) wait_ms = static_cast<int>(left);
      }
      int rc = ::poll(&p, 1, wait_ms);
      if (rc > 0) {
        if ((p.revents & POLLOUT) && replay_from_ >= 0)
          FlushReplayOnce();
        return;
      }
      if (rc < 0 && errno != EINTR)
        throw PeerLostError(std::string("hvt: poll failed during ") +
                            what);
      if (rc == 0) ServiceSiblingLinks(hub_, this);
    }
  }

  // False → caller should return 0 (a reconnect just happened or is
  // impossible without escalation, which throws).
  bool EnsureUsable(const char* what) {
    if (state_ == LinkState::DEAD)
      throw PeerLostError("hvt: " + Describe() + " is dead");
    if (!sock_.valid()) {
      HandleFailure(what);
      return false;
    }
    return true;
  }

  std::string Describe() const {
    return std::string(LinkPlaneName(plane_)) + " link to rank " +
           std::to_string(peer_);
  }

  // A transport-level failure: heal when allowed, escalate otherwise.
  // Escalation throws PeerLostError — the engine maps it to the PR 4
  // EnterBroken path unchanged.
  void HandleFailure(const char* what) {
    sock_.Close();
    if (state_ == LinkState::DEAD || !reconnect_ ||
        !LinkReconnectEnabled() || (hub_ && hub_->closed.load()))
      throw PeerLostError("hvt: " + std::string(what) + " failed on " +
                          Describe() + " (peer lost)");
    Reconnect();
  }

  void Escalate(const std::string& why) {
    state_ = LinkState::DEAD;
    state_since_ = NowSec();
    sock_.Close();
    throw PeerLostError("hvt: " + Describe() + ": " + why);
  }

  void CheckGates() {
    if (hub_ && hub_->stop && hub_->stop->load())
      Escalate("engine shutdown requested during reconnect");
    if (hub_ && hub_->closed.load())
      Escalate("engine aborted during reconnect");
    if (hub_ && hub_->remote_abort_seen.load())
      Escalate("a peer broadcast a gang abort while this link was "
               "reconnecting — joining the coordinated teardown");
  }

  // Heal the link: re-establish the socket (dial or accept, by the
  // original role), handshake (epoch, rx offsets), arm the replay.
  // Each wait iteration also services the engine's OTHER broken links
  // (ServiceSiblingLinks) — see its comment for the deadlock it breaks.
  void Reconnect() {
    if (state_ != LinkState::RECONNECTING) {
      state_ = LinkState::RECONNECTING;
      state_since_ = NowSec();
      retries_ = 0;
    }
    const double t0 = NowSec();
    const int64_t window = LinkRetryWindowMs();
    const int64_t deadline = NowMs() + window;
    int64_t backoff = 10;
    unsigned seed = static_cast<unsigned>(NowMs() ^ (peer_ << 8) ^
                                          static_cast<int>(plane_));
    while (true) {
      CheckGates();
      if (NowMs() >= deadline)
        Escalate("reconnect budget exhausted (HVT_LINK_RETRIES=" +
                 std::to_string(LinkRetries()) +
                 ", HVT_LINK_RETRY_WINDOW_MS=" + std::to_string(window) +
                 ") — peer is unreachable");
      if (hub_ && NowMs() < hub_->hold_until_ms) {
        // partition fault: the injector holds healing for its window
        struct timespec ts {0, 20 * 1000000};
        nanosleep(&ts, nullptr);
        continue;
      }
      if (!dial_host_.empty()) {
        if (retries_ >= LinkRetries())
          Escalate("reconnect budget exhausted (HVT_LINK_RETRIES=" +
                   std::to_string(LinkRetries()) +
                   ", HVT_LINK_RETRY_WINDOW_MS=" +
                   std::to_string(window) + ") — peer is dead");
        ++retries_;
        if (TryDialHandshake(deadline, t0)) return;
        int64_t jitter = backoff / 4;
        int64_t sleep_ms =
            backoff - jitter +
            (jitter > 0 ? static_cast<int64_t>(rand_r(&seed)) %
                              (2 * jitter + 1)
                        : 0);
        struct timespec ts {sleep_ms / 1000,
                            (sleep_ms % 1000) * 1000000};
        nanosleep(&ts, nullptr);
        backoff = backoff < 500 ? backoff * 2 : 500;
      } else {
        // acceptor: adopt a parked dial for this link, or accept a new
        // one (a hello for another link parks there for its owner)
        int64_t peer_epoch = 0, peer_rx = -1;
        bool adopted = false;
        if (hub_) {
          // move the parked dial out under the lock, handshake outside
          // it (TryAck blocks up to 2 s)
          Sock s;
          {
            std::lock_guard<std::mutex> plk(hub_->parked_mu);
            auto it =
                hub_->parked.find({static_cast<int>(plane_), peer_});
            if (it != hub_->parked.end()) {
              s = std::move(it->second.sock);
              peer_epoch = it->second.peer_epoch;
              peer_rx = it->second.peer_rx;
              hub_->parked.erase(it);
              adopted = true;
            }
          }
          if (adopted && TryAck(s, peer_epoch)) sock_ = std::move(s);
        }
        if (!adopted) {
          if (!listener_)
            Escalate("no listener to re-accept on (link was "
                     "dial-less)");
          Sock s = listener_->TryAccept(200);
          if (s.valid()) {
            int64_t pe = 0, prx = 0;
            int prank = -1, pplane = -1;
            if (ReadHello(s, &prank, &pplane, &pe, &prx)) {
              if (prank == peer_ &&
                  pplane == static_cast<int>(plane_)) {
                peer_epoch = pe;
                peer_rx = prx;
                if (TryAck(s, peer_epoch)) sock_ = std::move(s);
              } else if (hub_) {
                ReconnectHub::Parked pk;
                pk.sock = std::move(s);
                pk.peer_epoch = pe;
                pk.peer_rx = prx;
                std::lock_guard<std::mutex> plk(hub_->parked_mu);
                hub_->parked[{pplane, prank}] =
                    std::move(pk);  // latest wins
              }
            }
          }
        }
        if (sock_.valid()) {
          FinishReconnect(peer_epoch, peer_rx, t0);
          return;
        }
      }
      ServiceSiblingLinks(hub_, this);
    }
  }

  // One dial + handshake attempt; on success adopts the socket, arms
  // the replay, and marks the link HEALTHY. Used by the dialer branch
  // of Reconnect and by sibling servicing.
  bool TryDialHandshake(int64_t ack_deadline_ms, double t0,
                        int dial_ms = 1000) {
    Sock s = Sock::DialOnce(dial_host_, dial_port_, dial_ms);
    if (!s.valid()) return false;
    int64_t peer_epoch = 0, peer_rx = -1;
    try {
      // HELLO: magic | rank | plane | epoch | rx — built with the
      // wire.h Writer/Reader pair, so the session handshake rides the
      // same bounds-checked containment path as every control frame
      // (a truncated ACK throws TruncatedFrameError, caught below).
      Writer w;
      w.i32(kLinkHelloMagic);
      w.i32(hub_ ? hub_->my_rank : -1);
      w.u8(static_cast<uint8_t>(plane_));
      w.i64(epoch_);
      w.i64(rx_);
      s.SendFrame(w.buf, 2000);
      auto ack = s.RecvFrame(std::min<int64_t>(
          3000, std::max<int64_t>(100, ack_deadline_ms - NowMs())));
      Reader rd(ack);
      if (rd.i32() != kLinkHelloMagic) return false;
      peer_epoch = rd.i64();
      peer_rx = rd.i64();
    } catch (const std::exception&) {
      return false;  // handshake failed: retry within the budget
    }
    sock_ = std::move(s);
    FinishReconnect(peer_epoch, peer_rx, t0);
    return true;
  }

  // Post-handshake tail shared by every heal path: validate the peer's
  // rx offset, arm the replay, count/record, go HEALTHY.
  void FinishReconnect(int64_t peer_epoch, int64_t peer_rx, double t0) {
    // arm the replay: the peer consumed peer_rx of our tx_ bytes
    if (peer_rx > tx_ || peer_rx < 0)
      Escalate("reconnect handshake is corrupt (peer claims rx=" +
               std::to_string(peer_rx) + " of tx=" +
               std::to_string(tx_) + ")");
    int64_t gap = tx_ - peer_rx;
    if (gap > 0 && !ring_.Covers(peer_rx))
      Escalate("cannot replay " + std::to_string(gap) +
               " lost bytes to rank " + std::to_string(peer_) +
               " — replay budget exhausted (HVT_REPLAY_BUDGET_BYTES=" +
               std::to_string(ReplayBudgetBytes()) + ")");
    replay_from_ = gap > 0 ? peer_rx : -1;
    int64_t frames = 0;
    for (int64_t end : frame_ends_)
      if (end > peer_rx) ++frames;
    // The heal is complete only once the peer HAS the replayed bytes:
    // this side's transfer counters may already be satisfied (the
    // bytes were handed to the kernel before the drop), so the
    // application might never touch this link again this phase — an
    // unflushed replay would strand the peer waiting forever on data
    // only we can re-send. The flush cannot deadlock: the gap is at
    // most what was in flight when the link dropped, which by
    // construction fits back into the (now empty) socket buffers
    // without the peer consuming a byte.
    {
      const int64_t flush_deadline = NowMs() + LinkRetryWindowMs();
      while (replay_from_ >= 0) {
        if (NowMs() >= flush_deadline)
          Escalate("replay flush stalled after reconnect (peer not "
                   "draining)");
        struct pollfd p {sock_.fd(), POLLOUT, 0};
        if (::poll(&p, 1, 200) <= 0) continue;
        if (!FlushReplayOnce()) return;  // dropped again mid-flush: the
                                         // nested heal flushed its own
                                         // (re-armed) replay
      }
    }
    epoch_.store(std::max(epoch_.load(), peer_epoch));
    if (dial_host_.empty()) ++epoch_;  // acceptor already bumped in ack
    state_ = LinkState::HEALTHY;
    double dur = NowSec() - t0;
    state_since_ = NowSec();
    if (hub_) {
      if (hub_->reconnects)
        hub_->reconnects[static_cast<int>(plane_)].fetch_add(
            1, std::memory_order_relaxed);
      if (gap > 0) {
        if (hub_->replay_bytes)
          hub_->replay_bytes->fetch_add(gap, std::memory_order_relaxed);
        if (hub_->frames_replayed)
          hub_->frames_replayed->fetch_add(frames,
                                           std::memory_order_relaxed);
      }
      if (hub_->events) {
        hub_->events->Record(EventKind::RECONNECT,
                             "rank " + std::to_string(peer_),
                             static_cast<int32_t>(plane_), retries_,
                             static_cast<int64_t>(dur * 1e6));
        if (gap > 0)
          hub_->events->Record(EventKind::REPLAY,
                               "rank " + std::to_string(peer_),
                               static_cast<int32_t>(plane_),
                               static_cast<int32_t>(frames), gap);
      }
    }
  }

  // Read a reconnect HELLO off a fresh acceptor-side socket.
  bool ReadHello(Sock& s, int* rank, int* plane, int64_t* ep,
                 int64_t* rx) {
    try {
      auto f = s.RecvFrame(2000);
      Reader rd(f);
      if (rd.i32() != kLinkHelloMagic) return false;
      *rank = rd.i32();
      *plane = rd.u8();
      *ep = rd.i64();
      *rx = rd.i64();
      return true;
    } catch (const std::exception&) {
      return false;
    }
  }

  bool TryAck(Sock& s, int64_t peer_epoch) {
    try {
      Writer w;
      w.i32(kLinkHelloMagic);
      w.i64(std::max(epoch_.load(), peer_epoch) + 1);
      w.i64(rx_);
      s.SendFrame(w.buf, 2000);
      return true;
    } catch (const std::exception&) {
      return false;
    }
  }

  // Push pending replay bytes nonblockingly; false when a reconnect
  // happened underneath (caller restarts its operation).
  bool FlushReplayOnce() {
    while (replay_from_ >= 0) {
      auto [ptr, len] = ring_.Peek(replay_from_);
      if (len <= 0) {
        replay_from_ = -1;
        break;
      }
      ssize_t k =
          ::send(sock_.fd(), ptr, static_cast<size_t>(len),
                 MSG_DONTWAIT | MSG_NOSIGNAL);
      if (k < 0) {
        if (errno == EAGAIN || errno == EWOULDBLOCK || errno == EINTR)
          return true;  // socket full; flush resumes on next POLLOUT
        HandleFailure("replay");
        return false;
      }
      replay_from_ += k;
      if (replay_from_ >= tx_) replay_from_ = -1;
    }
    return true;
  }

  Sock sock_;
  LinkPlane plane_;
  int peer_;
  ReconnectHub* hub_;
  std::string dial_host_;
  int dial_port_;
  Listener* listener_;
  ReplayRing ring_;
  bool reconnect_ = true;
  // owner-thread token (0 = unowned): the Claim CAS word above
  std::atomic<uint64_t> owner_{0};
  // tx_/rx_ are owner-thread counters, but the chaos injector reads
  // tx_ (InjectCutAfter) and the diagnostics snapshot may read either
  // from the engine thread while a lane worker drives the link —
  // atomics keep those cross-thread reads defined
  std::atomic<int64_t> tx_{0};  // bytes ever handed to the kernel
  std::atomic<int64_t> rx_{0};  // bytes ever consumed by the app
  int64_t replay_from_ = -1;  // pending replay cursor (<0 → none;
                              // owner-thread only, like the ring)
  // chaos cut marks: armed by the engine thread, checked by the owner
  std::atomic<int64_t> cut_after_{-1};
  std::atomic<int64_t> cut_after_rx_{-1};
  std::deque<int64_t> frame_ends_;  // SendFrame end offsets in-window
  // state/epoch/retries/state_since: written by the owning thread,
  // read by UpdateDiag from the engine thread — relaxed-consistency
  // telemetry reads, hence atomics
  std::atomic<LinkState> state_{LinkState::HEALTHY};
  std::atomic<int64_t> epoch_{0};
  std::atomic<int> retries_{0};
  std::atomic<double> state_since_;
  std::vector<uint8_t> frame_;  // SendFrame staging
};

// While one link's reconnect episode waits (dial backoff / accept
// poll), repair every OTHER link the engine could fix meanwhile. This
// breaks the cross-plane reconnect deadlock: two single-threaded
// peers can each be waiting as the ACCEPTOR of a different broken
// link (rank 0 re-accepting the control link while its peer
// re-accepts the data link) — each waiting for a dial only the other
// one's engine thread could make. Probing makes remotely-cut links
// locally visible (an unread FIN/RST), and a single dial attempt per
// wait iteration heals every link this side is the dialer of.
inline void ServiceSiblingLinks(ReconnectHub* hub, TcpLink* busy) {
  if (!hub) return;
  for (TcpLink* l : hub->links)
    if (l != busy) l->ProbeAndRepair();
}

using LinkPtr = std::unique_ptr<TcpLink>;

}  // namespace hvt
