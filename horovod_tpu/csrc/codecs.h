// Quantized wire-codec registry — the codec family behind
// HVT_WIRE_COMPRESSION (EQuARX-style block-scaled quantized allreduce,
// arXiv:2506.17615). PR 3 proved the plumbing with ad-hoc bf16 helpers
// inside ring_ops.cc; this header is their grown-up home: every codec
// the data plane can put on a TCP link lives behind ONE narrow
// interface (CompressedSize / Compress / Decompress / Roundtrip), and
// the codec ids below are the single registry the C++ engine, the
// Python name table (horovod_tpu/compression), and the
// docs/performance.md codec table must agree on — machine-checked by
// tools/hvt_lint.py's `codecs` pass.
//
// On-wire block format (int8/fp8): payloads are cut into blocks of
// kCodecBlockElems fp32 elements; each block's fp32 scale rides
// IN-BAND ahead of its quantized payload, so every WireBlockBytes()
// bytes of the stream decode independently — which is what lets the
// pipelined chunked ring (HVT_RING_CHUNK_BYTES) decode and reduce any
// block-aligned prefix of a transfer while later chunks are still in
// flight. bf16 is the degenerate case (1-elem "blocks", no scale).
#pragma once

#include <cstddef>
#include <cstdint>
#include <cstring>

namespace hvt {

// --------------------------------------------------------------------------
// codec id ↔ canonical name registry. THE single source of truth for
// wire-codec ids: the WireCodec enum, WireCodecName(), the Python name
// table (horovod_tpu/compression CODEC_IDS + engine/native.py
// WIRE_CODECS), and the docs/performance.md codec table are all kept
// in lockstep by the hvt_lint `codecs` pass. Ids are wire values
// (stamped into Responses and the stats-slot ABI): append-only, never
// renumber.
// --------------------------------------------------------------------------
#define HVT_WIRE_CODECS(X) \
  X(0, "none")             \
  X(1, "bf16")             \
  X(2, "int8")             \
  X(3, "fp8")

enum class WireCodec : uint8_t {
  RAW = 0,         // bit-exact raw bytes (default)
  BF16 = 1,        // round-to-nearest-even bf16 truncation, 2x
  INT8_BLOCK = 2,  // per-block absmax int8, ~3.94x on fp32
  FP8_BLOCK = 3,   // per-block absmax fp8 e4m3, ~3.94x on fp32
};
constexpr int kWireCodecCount = 4;

inline const char* WireCodecName(WireCodec c) {
  switch (static_cast<int>(c)) {
#define HVT_CODEC_NAME_CASE(id, name) \
  case id:                            \
    return name;
    HVT_WIRE_CODECS(HVT_CODEC_NAME_CASE)
#undef HVT_CODEC_NAME_CASE
  }
  return "?";
}

// Per-link-class codec pair, stamped by rank 0 into every eligible
// Response (EQuARX: quantize only the inter-host hops — the intra-host
// phase of the hierarchical backend, and any ring whose members share
// one host, take `intra`; anything that crosses hosts takes `inter`).
struct WirePair {
  WireCodec intra = WireCodec::RAW;
  WireCodec inter = WireCodec::RAW;
  bool any() const {
    return intra != WireCodec::RAW || inter != WireCodec::RAW;
  }
};

// Block geometry shared by the scaled codecs: 256 fp32 elements per
// block (~1 KB raw) keeps the in-band scale overhead at 4/256 bytes
// per element while the absmax stays local enough that one outlier
// cannot wash out a whole tensor's resolution.
constexpr int64_t kCodecBlockElems = 256;

// fp32 <-> bf16 scalar conversions (round-to-nearest-even truncation);
// shared with ring_ops.cc's half/bf16 reduce widening.
inline float Bf16ToFloat(uint16_t h) {
  uint32_t f = static_cast<uint32_t>(h) << 16;
  float out;
  memcpy(&out, &f, 4);
  return out;
}

inline uint16_t FloatToBf16(float v) {
  uint32_t f;
  memcpy(&f, &v, 4);
  uint32_t rounding = 0x7fffu + ((f >> 16) & 1);
  return static_cast<uint16_t>((f + rounding) >> 16);
}

// The narrow codec interface. Codecs operate on fp32 payloads only —
// the engine's stamp rule already restricts compression to fp32
// non-Adasum allreduces, every other dtype moves raw.
class Codec {
 public:
  virtual ~Codec() = default;
  virtual WireCodec id() const = 0;
  // Bytes on the wire for n fp32 elements (the transfer size every
  // participant must agree on).
  virtual size_t CompressedSize(int64_t n) const = 0;
  // Self-contained stream granularity: every WireBlockBytes() bytes
  // decode BlockElems() elements independently of the rest of the
  // stream (the scale rides in-band ahead of each block's payload).
  // Ring chunks are aligned to this so chunked decodes stay valid.
  virtual size_t WireBlockBytes() const = 0;
  virtual int64_t BlockElems() const = 0;
  virtual void Compress(uint8_t* dst, const float* src,
                        int64_t n) const = 0;
  virtual void Decompress(float* dst, const uint8_t* src,
                          int64_t n) const = 0;
  // dst[i] = decode(encode(dst[i])) in place — segment owners truncate
  // exactly as peers will decompress, preserving the PR 3 invariant
  // that every rank's final buffer is bit-identical. Also the
  // quantizer the engine's error-feedback pass runs on inputs.
  virtual void Roundtrip(float* dst, int64_t n) const = 0;
};

// Registry lookup: nullptr for RAW and unknown ids (raw bytes move
// uncompressed — the safe default for a stale peer stamping an id this
// build does not know).
const Codec* CodecFor(WireCodec id);

// Elements ahead of a block-aligned wire offset — maps a chunk's wire
// byte offset back to its fp32 element offset during pipelined decode.
inline int64_t CodecElemsBefore(const Codec& c, size_t wire_off) {
  return static_cast<int64_t>(wire_off / c.WireBlockBytes()) *
         c.BlockElems();
}

// Codec id for an env token ("none"/"raw"/""/codec names); -1 unknown.
int WireCodecFromName(const char* name);

}  // namespace hvt
