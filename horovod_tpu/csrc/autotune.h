// Autotuning of engine knobs — counterpart of the reference's
// ParameterManager (horovod/common/parameter_manager.h:42-120) +
// BayesianOptimization / GaussianProcessRegressor
// (horovod/common/optim/bayesian_optimization.cc, gaussian_process.cc).
//
// Rank 0 tunes {fusion threshold, cycle time, cache enabled, backend
// preference} by Bayesian optimization (RBF-kernel Gaussian process +
// expected-improvement acquisition) over the observed data-plane
// throughput (bytes/sec), discarding warmup samples — the same four-knob
// surface the reference ParameterManager tunes (parameter_manager.h:60-78:
// fusion threshold, cycle time, cache enabled, hierarchical
// allreduce/allgather; our backend-preference knob covers the
// hierarchical/flat split). The tuned fusion threshold applies
// coordinator-side only; cycle time and the cache/backend flags are
// broadcast to workers piggybacked on the per-cycle response frame (the
// analog of Controller::SynchronizeParameters, controller.cc:39-53) and
// applied at the same frame boundary on every rank, so cache lookups and
// backend picks never diverge.
//
// The reference maximizes EI with LBFGS over a vendored library; we use
// deterministic random-candidate search — dependency-free, and for this
// low-dimensional box (2 continuous + 2 effectively-binary axes, where
// EI is piecewise-flat and gradient search adds nothing) just as
// effective at 512 candidates.
#pragma once

#include <atomic>
#include <cstdint>
#include <string>
#include <vector>

#include "codecs.h"

namespace hvt {

// Small dense Gaussian process regressor, RBF kernel + observation noise.
// Inputs must be pre-scaled to ~[0,1]^d; y is standardized internally.
class GaussianProcess {
 public:
  explicit GaussianProcess(double length_scale = 0.25,
                           double noise = 1e-4)
      : length_scale_(length_scale), noise_(noise) {}

  // X: n rows of d columns (row-major). Returns false on a singular fit.
  bool Fit(const std::vector<std::vector<double>>& X,
           const std::vector<double>& y);
  // Predict mean and variance (of the standardized process scaled back).
  void Predict(const std::vector<double>& x, double* mean,
               double* var) const;
  bool fitted() const { return fitted_; }

 private:
  double Kernel(const std::vector<double>& a,
                const std::vector<double>& b) const;

  double length_scale_, noise_;
  bool fitted_ = false;
  std::vector<std::vector<double>> X_;
  std::vector<double> alpha_;            // K^-1 (y - mean)
  std::vector<std::vector<double>> L_;   // Cholesky factor of K
  double y_mean_ = 0.0, y_std_ = 1.0;
};

// Expected-improvement Bayesian optimizer over the unit box [0,1]^d.
class BayesianOptimizer {
 public:
  explicit BayesianOptimizer(int dims, uint64_t seed = 0x5deece66dULL)
      : dims_(dims), rng_(seed) {}

  void AddSample(const std::vector<double>& x, double y);
  // Next point to evaluate: quasi-random while under `min_fit` samples,
  // then argmax of EI over `candidates` random points.
  std::vector<double> Suggest(int candidates = 512, int min_fit = 3);
  const std::vector<double>& best_x() const { return best_x_; }
  double best_y() const { return best_y_; }
  int num_samples() const { return static_cast<int>(ys_.size()); }

 private:
  double NextUniform();
  double ExpectedImprovement(const GaussianProcess& gp,
                             const std::vector<double>& x) const;

  int dims_;
  uint64_t rng_;
  std::vector<std::vector<double>> xs_;
  std::vector<double> ys_;
  std::vector<double> best_x_;
  double best_y_ = -1e300;
};

// Tunes fusion_threshold (log2-scaled, 1 MB..256 MB), cycle_ms (1..25),
// cache_enabled (response cache on/off), and prefer_flat (bypass the
// shm/hierarchical priority backends for the flat ring).
class ParameterManager {
 public:
  ParameterManager();

  // Read env knobs (HVT_AUTOTUNE, HVT_AUTOTUNE_LOG,
  // HVT_AUTOTUNE_WARMUP_SAMPLES, HVT_AUTOTUNE_CYCLES_PER_SAMPLE,
  // HVT_AUTOTUNE_MAX_SAMPLES — reference common.h:68-73) and seed the
  // current point from the configured defaults.
  void Initialize(int64_t fusion_threshold, int cycle_ms);

  bool active() const { return active_; }

  // Record one engine cycle's executed payload bytes. Returns true when
  // the tuned parameters changed (caller re-reads the getters).
  bool Record(int64_t bytes);

  int64_t fusion_threshold() const { return fusion_threshold_; }
  int cycle_ms() const { return cycle_ms_; }
  bool cache_enabled() const { return cache_enabled_; }
  bool prefer_flat() const { return prefer_flat_; }
  int samples() const { return samples_; }
  double best_score() const { return bo_.best_y(); }

 private:
  void ApplyPoint(const std::vector<double>& x);
  std::vector<double> CurrentPoint() const;
  void Log(double score);

  // atomics: read by the introspection API from client threads while the
  // engine thread tunes
  std::atomic<bool> active_{false};
  bool done_ = false;
  int warmup_remaining_ = 3;
  int cycles_per_sample_ = 50;
  int max_samples_ = 20;
  std::string log_path_;

  BayesianOptimizer bo_{4};
  int64_t fusion_threshold_ = 64 << 20;
  int cycle_ms_ = 2;
  bool cache_enabled_ = true;
  bool prefer_flat_ = false;

  int cycle_count_ = 0;
  int64_t bytes_acc_ = 0;
  double window_start_ = 0.0;
  std::atomic<int> samples_{0};
};

// Wire-codec auto-selection (HVT_WIRE_COMPRESSION=auto). Rank 0 tries
// each candidate codec on live fp32-allreduce traffic, keyed by
// (link class, log2-size bucket), and locks the byte-throughput argmax
// per key once every candidate has enough samples — the sweep-sample
// analog of the committed benchmarks/r09_codec_sweep.json curve,
// measured in-situ instead of offline. Deterministic (fixed candidate
// rotation, no RNG) and rank-0 only: workers just follow the codec ids
// rank 0 stamps into each Response, so no cross-rank agreement problem
// exists. Engine-thread only.
class CodecTuner {
 public:
  // candidate rotation: raw baseline, bf16 (2x), int8 block (3.94x).
  // fp8 is deliberately not auto-picked — same wire bytes as int8 with
  // looser error bounds, so it can only tie (select it explicitly for
  // heavy-tailed payloads; see docs/performance.md).
  static constexpr int kNumCand = 3;
  static constexpr int kTrials = 5;   // samples per candidate per key
  static constexpr int kBuckets = 18; // log2 bytes, 1 KB .. 128 MB+

  void Reset();
  // Codec to stamp for an eligible response of `bytes` payload on link
  // class `link` (0 intra / 1 inter): the still-exploring candidate, or
  // the locked winner.
  WireCodec Pick(int64_t bytes, int link);
  // Measured execution of a response previously stamped via Pick.
  void Observe(int64_t bytes, int link, WireCodec codec, int64_t ns);
  // True once Pick(bytes, link) would return a locked winner.
  bool Locked(int64_t bytes, int link) const;

 private:
  struct Cell {
    int64_t ns[kNumCand] = {};
    int64_t bytes[kNumCand] = {};
    int n[kNumCand] = {};
    int locked = -1;  // candidate index once decided
  };
  static int Bucket(int64_t bytes);
  static int CandIndex(WireCodec c);
  Cell cells_[2][kBuckets];
};

}  // namespace hvt
