// Autotuning of engine knobs — counterpart of the reference's
// ParameterManager (horovod/common/parameter_manager.h:42-120) +
// BayesianOptimization / GaussianProcessRegressor
// (horovod/common/optim/bayesian_optimization.cc, gaussian_process.cc).
//
// Rank 0 tunes {fusion threshold, cycle time, cache enabled, backend
// preference} by Bayesian optimization (RBF-kernel Gaussian process +
// expected-improvement acquisition) over the observed data-plane
// throughput (bytes/sec), discarding warmup samples — the same four-knob
// surface the reference ParameterManager tunes (parameter_manager.h:60-78:
// fusion threshold, cycle time, cache enabled, hierarchical
// allreduce/allgather; our backend-preference knob covers the
// hierarchical/flat split). The tuned fusion threshold applies
// coordinator-side only; cycle time and the cache/backend flags are
// broadcast to workers piggybacked on the per-cycle response frame (the
// analog of Controller::SynchronizeParameters, controller.cc:39-53) and
// applied at the same frame boundary on every rank, so cache lookups and
// backend picks never diverge.
//
// The reference maximizes EI with LBFGS over a vendored library; we use
// deterministic random-candidate search — dependency-free, and for this
// low-dimensional box (2 continuous + 2 effectively-binary axes, where
// EI is piecewise-flat and gradient search adds nothing) just as
// effective at 512 candidates.
#pragma once

#include <atomic>
#include <cstdint>
#include <string>
#include <vector>

namespace hvt {

// Small dense Gaussian process regressor, RBF kernel + observation noise.
// Inputs must be pre-scaled to ~[0,1]^d; y is standardized internally.
class GaussianProcess {
 public:
  explicit GaussianProcess(double length_scale = 0.25,
                           double noise = 1e-4)
      : length_scale_(length_scale), noise_(noise) {}

  // X: n rows of d columns (row-major). Returns false on a singular fit.
  bool Fit(const std::vector<std::vector<double>>& X,
           const std::vector<double>& y);
  // Predict mean and variance (of the standardized process scaled back).
  void Predict(const std::vector<double>& x, double* mean,
               double* var) const;
  bool fitted() const { return fitted_; }

 private:
  double Kernel(const std::vector<double>& a,
                const std::vector<double>& b) const;

  double length_scale_, noise_;
  bool fitted_ = false;
  std::vector<std::vector<double>> X_;
  std::vector<double> alpha_;            // K^-1 (y - mean)
  std::vector<std::vector<double>> L_;   // Cholesky factor of K
  double y_mean_ = 0.0, y_std_ = 1.0;
};

// Expected-improvement Bayesian optimizer over the unit box [0,1]^d.
class BayesianOptimizer {
 public:
  explicit BayesianOptimizer(int dims, uint64_t seed = 0x5deece66dULL)
      : dims_(dims), rng_(seed) {}

  void AddSample(const std::vector<double>& x, double y);
  // Next point to evaluate: quasi-random while under `min_fit` samples,
  // then argmax of EI over `candidates` random points.
  std::vector<double> Suggest(int candidates = 512, int min_fit = 3);
  const std::vector<double>& best_x() const { return best_x_; }
  double best_y() const { return best_y_; }
  int num_samples() const { return static_cast<int>(ys_.size()); }

 private:
  double NextUniform();
  double ExpectedImprovement(const GaussianProcess& gp,
                             const std::vector<double>& x) const;

  int dims_;
  uint64_t rng_;
  std::vector<std::vector<double>> xs_;
  std::vector<double> ys_;
  std::vector<double> best_x_;
  double best_y_ = -1e300;
};

// Tunes fusion_threshold (log2-scaled, 1 MB..256 MB), cycle_ms (1..25),
// cache_enabled (response cache on/off), and prefer_flat (bypass the
// shm/hierarchical priority backends for the flat ring).
class ParameterManager {
 public:
  ParameterManager();

  // Read env knobs (HVT_AUTOTUNE, HVT_AUTOTUNE_LOG,
  // HVT_AUTOTUNE_WARMUP_SAMPLES, HVT_AUTOTUNE_CYCLES_PER_SAMPLE,
  // HVT_AUTOTUNE_MAX_SAMPLES — reference common.h:68-73) and seed the
  // current point from the configured defaults.
  void Initialize(int64_t fusion_threshold, int cycle_ms);

  bool active() const { return active_; }

  // Record one engine cycle's executed payload bytes. Returns true when
  // the tuned parameters changed (caller re-reads the getters).
  bool Record(int64_t bytes);

  int64_t fusion_threshold() const { return fusion_threshold_; }
  int cycle_ms() const { return cycle_ms_; }
  bool cache_enabled() const { return cache_enabled_; }
  bool prefer_flat() const { return prefer_flat_; }
  int samples() const { return samples_; }
  double best_score() const { return bo_.best_y(); }

 private:
  void ApplyPoint(const std::vector<double>& x);
  std::vector<double> CurrentPoint() const;
  void Log(double score);

  // atomics: read by the introspection API from client threads while the
  // engine thread tunes
  std::atomic<bool> active_{false};
  bool done_ = false;
  int warmup_remaining_ = 3;
  int cycles_per_sample_ = 50;
  int max_samples_ = 20;
  std::string log_path_;

  BayesianOptimizer bo_{4};
  int64_t fusion_threshold_ = 64 << 20;
  int cycle_ms_ = 2;
  bool cache_enabled_ = true;
  bool prefer_flat_ = false;

  int cycle_count_ = 0;
  int64_t bytes_acc_ = 0;
  double window_start_ = 0.0;
  std::atomic<int> samples_{0};
};

}  // namespace hvt
