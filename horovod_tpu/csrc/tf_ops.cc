// TensorFlow custom-op library — the native analog of the reference's
// tensorflow/mpi_ops.cc (HorovodAllreduceOp:374 AsyncOpKernel,
// HorovodAllgatherOp:571, HorovodBroadcastOp:642, HorovodAlltoallOp:873,
// scalar Size/Rank ops :758-856). Each op enqueues the tensor into the
// background engine and defers the TF `done` callback until the collective
// completes, so TF executor threads are never blocked on the network.
//
// Linkage: this library talks to the engine ONLY through the extern "C"
// surface (c_api.cc) and links against libhvt_core.so with an $ORIGIN
// rpath. That keeps one Engine singleton per process (the ctypes bridge
// dlopens the same path) and makes the boundary immune to whatever
// C++ ABI flags TensorFlow was compiled with.
#include <atomic>
#include <condition_variable>
#include <cstring>
#include <deque>
#include <functional>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include "tensorflow/core/framework/common_shape_fns.h"
#include "tensorflow/core/framework/op.h"
#include "tensorflow/core/framework/op_kernel.h"
#include "tensorflow/core/framework/shape_inference.h"

extern "C" {
// mirrors c_api.cc; wire ids match csrc/common.h enums
int hvt_initialized();
int hvt_rank();
int hvt_size();
int hvt_local_rank();
int hvt_local_size();
int hvt_submit(const char* name, int op, int reduce, int dtype, int ndims,
               const long long* dims, const void* data, long long nbytes,
               int root_rank, double prescale, double postscale,
               int nsplits, const long long* splits, int group_id,
               int group_size, int n_members, const long long* members);
int hvt_wait(int handle);
long long hvt_result_bytes(int handle);
void hvt_result_read(int handle, void* dst, long long nbytes);
int hvt_result_recv_splits(int handle, long long* dst, int max_n);
void hvt_release(int handle);
int hvt_error_message(char* dst, int max_n);
}

namespace hvt_tf {

using namespace tensorflow;  // NOLINT

enum WireOp { OP_ALLREDUCE = 0, OP_ALLGATHER = 1, OP_BROADCAST = 2,
              OP_ALLTOALL = 3, OP_REDUCESCATTER = 4 };

// Negotiated per-member row counts of a completed gather/alltoall result.
// Returns the number of splits read (0 if the engine recorded none).
static int ReadRecvSplits(int handle, std::vector<long long>* out) {
  out->assign(hvt_size() > 0 ? hvt_size() : 1, 0);
  int n = hvt_result_recv_splits(handle, out->data(),
                                 static_cast<int>(out->size()));
  return n < static_cast<int>(out->size()) ? n
                                           : static_cast<int>(out->size());
}

static int WireDType(DataType dt) {
  switch (dt) {
    case DT_UINT8: return 0;
    case DT_INT8: return 1;
    case DT_INT32: return 4;
    case DT_INT64: return 5;
    case DT_HALF: return 6;
    case DT_FLOAT: return 7;
    case DT_DOUBLE: return 8;
    case DT_BOOL: return 9;
    case DT_BFLOAT16: return 10;
    default: return -1;
  }
}

// One dedicated waiter thread serves all outstanding collectives:
// hvt_wait stores its result in C thread-locals, so wait + result reads
// must happen on one thread (same contract the ctypes bridge documents).
// The engine executes fused responses serially anyway, so a single waiter
// does not reduce parallelism.
class Waiter {
 public:
  static Waiter& Get() {
    // Intentionally leaked: exit() must not run ~Waiter while the detached
    // thread still waits on the condition variable (destroying a cv in use
    // deadlocks glibc — observed as workers hanging after main returns).
    static Waiter* w = new Waiter();
    return *w;
  }

  void Enqueue(std::function<void()> fn) {
    {
      std::lock_guard<std::mutex> l(mu_);
      queue_.push_back(std::move(fn));
    }
    cv_.notify_one();
  }

 private:
  Waiter() {
    thread_ = std::thread([this] { Loop(); });
    thread_.detach();  // process-lifetime singleton
  }

  void Loop() {
    for (;;) {
      std::function<void()> fn;
      {
        std::unique_lock<std::mutex> l(mu_);
        cv_.wait(l, [this] { return !queue_.empty(); });
        fn = std::move(queue_.front());
        queue_.pop_front();
      }
      fn();
    }
  }

  std::mutex mu_;
  std::condition_variable cv_;
  std::deque<std::function<void()>> queue_;
  std::thread thread_;
};

static std::string LastError() {
  char buf[1024];
  hvt_error_message(buf, sizeof(buf));
  return std::string(buf);
}

// Shared submit → wait → allocate-output plumbing for the collective
// kernels. `name` keys cross-rank matching (the engine's tensor table
// dedups and negotiates by name), so it must be identical across ranks —
// callers default it to the TF node name, which SPMD graphs replicate.
struct SubmitArgs {
  std::string name;
  int op = OP_ALLREDUCE;
  int reduce = 0;
  int root_rank = 0;
  double prescale = 1.0, postscale = 1.0;
  std::vector<long long> splits;
  int group_id = -1, group_size = 0;
  std::vector<long long> members;
};

class HvtAsyncOpBase : public AsyncOpKernel {
 public:
  explicit HvtAsyncOpBase(OpKernelConstruction* ctx) : AsyncOpKernel(ctx) {
    OP_REQUIRES_OK(ctx, ctx->GetAttr("tensor_name", &tensor_name_));
    if (ctx->HasAttr("process_set_ranks")) {
      std::vector<int64_t> ranks;
      OP_REQUIRES_OK(ctx, ctx->GetAttr("process_set_ranks", &ranks));
      members_.assign(ranks.begin(), ranks.end());
    }
  }

 protected:
  std::string Key(OpKernelContext* ctx) const {
    if (!tensor_name_.empty()) return tensor_name_;
    return std::string(ctx->op_kernel().name());
  }

  // Submits and schedules completion. `fill` runs on the waiter thread
  // after a successful wait; it must allocate + fill the outputs.
  void SubmitAndDefer(OpKernelContext* ctx, DoneCallback done,
                      const Tensor& input, const SubmitArgs& args,
                      std::function<Status(int handle)> fill) {
    if (!hvt_initialized()) {
      ctx->CtxFailure(errors::FailedPrecondition(
          "hvt engine not initialized — call horovod_tpu.init() under the "
          "hvtrun launcher (multi-process) before using native TF ops"));
      done();
      return;
    }
    int wire_dtype = WireDType(input.dtype());
    if (wire_dtype < 0) {
      ctx->CtxFailure(errors::InvalidArgument(
          "unsupported dtype for hvt collective: ",
          DataTypeString(input.dtype())));
      done();
      return;
    }
    std::vector<long long> dims;
    for (int i = 0; i < input.dims(); ++i) dims.push_back(input.dim_size(i));
    auto data = input.tensor_data();
    int handle = hvt_submit(
        args.name.c_str(), args.op, args.reduce, wire_dtype,
        static_cast<int>(dims.size()), dims.data(), data.data(),
        static_cast<long long>(data.size()), args.root_rank, args.prescale,
        args.postscale, static_cast<int>(args.splits.size()),
        args.splits.empty() ? nullptr : args.splits.data(), args.group_id,
        args.group_size, static_cast<int>(args.members.size()),
        args.members.empty() ? nullptr : args.members.data());
    if (handle < 0) {
      ctx->CtxFailure(errors::Internal("hvt_submit failed for ", args.name));
      done();
      return;
    }
    Waiter::Get().Enqueue([ctx, done, handle, fill, name = args.name] {
      int rc = hvt_wait(handle);
      if (rc != 0) {
        ctx->CtxFailure(errors::Internal(
            "hvt collective '", name, "' failed: ", LastError()));
      } else {
        Status s = fill(handle);
        if (!s.ok()) ctx->CtxFailure(s);
      }
      hvt_release(handle);
      done();
    });
  }

  std::string tensor_name_;
  std::vector<long long> members_;
};

class HvtAllreduceOp : public HvtAsyncOpBase {
 public:
  explicit HvtAllreduceOp(OpKernelConstruction* ctx) : HvtAsyncOpBase(ctx) {
    OP_REQUIRES_OK(ctx, ctx->GetAttr("reduce_op", &reduce_op_));
    OP_REQUIRES_OK(ctx, ctx->GetAttr("prescale_factor", &prescale_));
    OP_REQUIRES_OK(ctx, ctx->GetAttr("postscale_factor", &postscale_));
  }

  void ComputeAsync(OpKernelContext* ctx, DoneCallback done) override {
    const Tensor& input = ctx->input(0);
    SubmitArgs a;
    a.name = Key(ctx);
    a.op = OP_ALLREDUCE;
    a.reduce = reduce_op_;
    a.prescale = prescale_;
    a.postscale = postscale_;
    a.members = members_;
    TensorShape shape = input.shape();
    SubmitAndDefer(ctx, done, input, a, [ctx, shape](int handle) -> Status {
      Tensor* out = nullptr;
      TF_RETURN_IF_ERROR(ctx->allocate_output(0, shape, &out));
      auto dst = out->tensor_data();
      hvt_result_read(handle, const_cast<char*>(dst.data()),
                      static_cast<long long>(dst.size()));
      return Status();
    });
  }

 private:
  int reduce_op_ = 0;
  float prescale_ = 1.0f, postscale_ = 1.0f;
};

class HvtAllgatherOp : public HvtAsyncOpBase {
 public:
  explicit HvtAllgatherOp(OpKernelConstruction* ctx) : HvtAsyncOpBase(ctx) {}

  void ComputeAsync(OpKernelContext* ctx, DoneCallback done) override {
    const Tensor& input = ctx->input(0);
    OP_REQUIRES_ASYNC(ctx, input.dims() >= 1,
                      errors::InvalidArgument("allgather needs rank>=1"),
                      done);
    SubmitArgs a;
    a.name = Key(ctx);
    a.op = OP_ALLGATHER;
    a.members = members_;
    TensorShape shape = input.shape();
    DataType dt = input.dtype();
    SubmitAndDefer(ctx, done, input, a,
                   [ctx, shape, dt](int handle) -> Status {
      // Output dim 0 = sum of the NEGOTIATED per-member row counts, not
      // result_bytes / row_bytes: byte division collapses zero-width
      // rows (any trailing dim of 0) to zero rows, hiding the true
      // gathered count from downstream shape logic.
      std::vector<long long> rsp;
      int n = ReadRecvSplits(handle, &rsp);
      TensorShape out_shape = shape;
      int64_t total_rows = 0;
      if (n > 0) {
        for (int i = 0; i < n; ++i) total_rows += rsp[i];
      } else {
        // legacy fallback (engine predating recv_splits on allgather)
        int64_t row_elems = 1;
        for (int i = 1; i < shape.dims(); ++i)
          row_elems *= shape.dim_size(i);
        int64_t row_bytes = row_elems * DataTypeSize(dt);
        total_rows =
            row_bytes > 0 ? hvt_result_bytes(handle) / row_bytes : 0;
      }
      out_shape.set_dim(0, total_rows);
      Tensor* out = nullptr;
      TF_RETURN_IF_ERROR(ctx->allocate_output(0, out_shape, &out));
      auto dst = out->tensor_data();
      hvt_result_read(handle, const_cast<char*>(dst.data()),
                      static_cast<long long>(dst.size()));
      return Status();
    });
  }
};

class HvtBroadcastOp : public HvtAsyncOpBase {
 public:
  explicit HvtBroadcastOp(OpKernelConstruction* ctx) : HvtAsyncOpBase(ctx) {
    OP_REQUIRES_OK(ctx, ctx->GetAttr("root_rank", &root_rank_));
  }

  void ComputeAsync(OpKernelContext* ctx, DoneCallback done) override {
    const Tensor& input = ctx->input(0);
    SubmitArgs a;
    a.name = Key(ctx);
    a.op = OP_BROADCAST;
    a.root_rank = root_rank_;
    a.members = members_;
    TensorShape shape = input.shape();
    SubmitAndDefer(ctx, done, input, a, [ctx, shape](int handle) -> Status {
      Tensor* out = nullptr;
      TF_RETURN_IF_ERROR(ctx->allocate_output(0, shape, &out));
      auto dst = out->tensor_data();
      hvt_result_read(handle, const_cast<char*>(dst.data()),
                      static_cast<long long>(dst.size()));
      return Status();
    });
  }

 private:
  int root_rank_ = 0;
};

class HvtAlltoallOp : public HvtAsyncOpBase {
 public:
  explicit HvtAlltoallOp(OpKernelConstruction* ctx) : HvtAsyncOpBase(ctx) {}

  void ComputeAsync(OpKernelContext* ctx, DoneCallback done) override {
    const Tensor& input = ctx->input(0);
    const Tensor& splits = ctx->input(1);
    OP_REQUIRES_ASYNC(ctx, input.dims() >= 1,
                      errors::InvalidArgument("alltoall needs rank>=1"),
                      done);
    SubmitArgs a;
    a.name = Key(ctx);
    a.op = OP_ALLTOALL;
    a.members = members_;
    auto flat = splits.flat<int32>();
    for (int i = 0; i < flat.size(); ++i) a.splits.push_back(flat(i));
    TensorShape shape = input.shape();
    SubmitAndDefer(ctx, done, input, a,
                   [ctx, shape](int handle) -> Status {
      std::vector<long long> rsp;
      int n = ReadRecvSplits(handle, &rsp);
      TensorShape out_shape = shape;
      // dim 0 from the negotiated splits (byte division would collapse
      // zero-width rows to zero rows)
      int64_t total_rows = 0;
      for (int i = 0; i < n; ++i) total_rows += rsp[i];
      out_shape.set_dim(0, total_rows);
      Tensor* out = nullptr;
      TF_RETURN_IF_ERROR(ctx->allocate_output(0, out_shape, &out));
      auto dst = out->tensor_data();
      hvt_result_read(handle, const_cast<char*>(dst.data()),
                      static_cast<long long>(dst.size()));
      Tensor* rs = nullptr;
      TF_RETURN_IF_ERROR(
          ctx->allocate_output(1, TensorShape({n}), &rs));
      auto rflat = rs->flat<int32>();
      for (int i = 0; i < n; ++i) rflat(i) = static_cast<int32>(rsp[i]);
      return Status();
    });
  }
};

class HvtReducescatterOp : public HvtAsyncOpBase {
 public:
  explicit HvtReducescatterOp(OpKernelConstruction* ctx)
      : HvtAsyncOpBase(ctx) {
    OP_REQUIRES_OK(ctx, ctx->GetAttr("reduce_op", &reduce_op_));
  }

  void ComputeAsync(OpKernelContext* ctx, DoneCallback done) override {
    const Tensor& input = ctx->input(0);
    OP_REQUIRES_ASYNC(ctx, input.dims() >= 1,
                      errors::InvalidArgument("reducescatter needs rank>=1"),
                      done);
    SubmitArgs a;
    a.name = Key(ctx);
    a.op = OP_REDUCESCATTER;
    a.reduce = reduce_op_;
    a.members = members_;
    TensorShape shape = input.shape();
    // output row count is statically input rows / participant count
    // (the engine validates divisibility) — byte-based inference would
    // collapse zero-width inputs to zero rows
    int64_t m = members_.empty()
                    ? (hvt_initialized() ? hvt_size() : 1)
                    : static_cast<int64_t>(members_.size());
    if (m <= 0) m = 1;
    SubmitAndDefer(ctx, done, input, a, [ctx, shape, m](int handle)
                                            -> Status {
      TensorShape out_shape = shape;
      out_shape.set_dim(0, shape.dim_size(0) / m);
      Tensor* out = nullptr;
      TF_RETURN_IF_ERROR(ctx->allocate_output(0, out_shape, &out));
      auto dst = out->tensor_data();
      hvt_result_read(handle, const_cast<char*>(dst.data()),
                      static_cast<long long>(dst.size()));
      return Status();
    });
  }

 private:
  int reduce_op_ = 0;
};

// Scalar topology ops — graph-time *dynamic* values so elastic jobs pick
// up rescaled worlds without retracing (reference mpi_ops.cc:758-856).
// Stateful so constant folding cannot freeze them into the graph.
template <int (*Fn)()>
class HvtScalarOp : public OpKernel {
 public:
  explicit HvtScalarOp(OpKernelConstruction* ctx) : OpKernel(ctx) {}
  void Compute(OpKernelContext* ctx) override {
    Tensor* out = nullptr;
    OP_REQUIRES_OK(ctx, ctx->allocate_output(0, TensorShape({}), &out));
    out->scalar<int32>()() = Fn();
  }
};

static int SizeOrOne() { return hvt_initialized() ? hvt_size() : 1; }
static int RankOrZero() { return hvt_initialized() ? hvt_rank() : 0; }
static int LocalSizeOrOne() {
  return hvt_initialized() ? hvt_local_size() : 1;
}
static int LocalRankOrZero() {
  return hvt_initialized() ? hvt_local_rank() : 0;
}

#define HVT_DTYPES \
  "{uint8, int8, int32, int64, half, bfloat16, float, double, bool}"

REGISTER_OP("HvtAllreduce")
    .Attr("T: " HVT_DTYPES)
    .Attr("tensor_name: string = ''")
    .Attr("reduce_op: int = 1")  // wire ReduceKind; 1 = AVERAGE
    .Attr("prescale_factor: float = 1.0")
    .Attr("postscale_factor: float = 1.0")
    .Attr("process_set_ranks: list(int) = []")
    .Input("tensor: T")
    .Output("sum: T")
    .SetShapeFn([](shape_inference::InferenceContext* c) {
      c->set_output(0, c->input(0));
      return Status();
    });

REGISTER_OP("HvtAllgather")
    .Attr("T: " HVT_DTYPES)
    .Attr("tensor_name: string = ''")
    .Attr("process_set_ranks: list(int) = []")
    .Input("tensor: T")
    .Output("output: T")
    .SetShapeFn([](shape_inference::InferenceContext* c) {
      shape_inference::ShapeHandle out;
      TF_RETURN_IF_ERROR(c->ReplaceDim(c->input(0), 0, c->UnknownDim(),
                                       &out));
      c->set_output(0, out);
      return Status();
    });

REGISTER_OP("HvtBroadcast")
    .Attr("T: " HVT_DTYPES)
    .Attr("tensor_name: string = ''")
    .Attr("root_rank: int = 0")
    .Attr("process_set_ranks: list(int) = []")
    .Input("tensor: T")
    .Output("output: T")
    .SetShapeFn([](shape_inference::InferenceContext* c) {
      c->set_output(0, c->input(0));
      return Status();
    });

REGISTER_OP("HvtAlltoall")
    .Attr("T: " HVT_DTYPES)
    .Attr("tensor_name: string = ''")
    .Attr("process_set_ranks: list(int) = []")
    .Input("tensor: T")
    .Input("splits: int32")
    .Output("output: T")
    .Output("received_splits: int32")
    .SetShapeFn([](shape_inference::InferenceContext* c) {
      shape_inference::ShapeHandle out;
      TF_RETURN_IF_ERROR(c->ReplaceDim(c->input(0), 0, c->UnknownDim(),
                                       &out));
      c->set_output(0, out);
      c->set_output(1, c->Vector(c->UnknownDim()));
      return Status();
    });

REGISTER_OP("HvtReducescatter")
    .Attr("T: " HVT_DTYPES)
    .Attr("tensor_name: string = ''")
    .Attr("reduce_op: int = 0")  // wire ReduceKind; 0 = SUM
    .Attr("process_set_ranks: list(int) = []")
    .Input("tensor: T")
    .Output("output: T")
    .SetShapeFn([](shape_inference::InferenceContext* c) {
      shape_inference::ShapeHandle out;
      TF_RETURN_IF_ERROR(c->ReplaceDim(c->input(0), 0, c->UnknownDim(),
                                       &out));
      c->set_output(0, out);
      return Status();
    });

REGISTER_OP("HvtSize").Output("size: int32").SetIsStateful().SetShapeFn(
    shape_inference::ScalarShape);
REGISTER_OP("HvtRank").Output("rank: int32").SetIsStateful().SetShapeFn(
    shape_inference::ScalarShape);
REGISTER_OP("HvtLocalSize")
    .Output("local_size: int32")
    .SetIsStateful()
    .SetShapeFn(shape_inference::ScalarShape);
REGISTER_OP("HvtLocalRank")
    .Output("local_rank: int32")
    .SetIsStateful()
    .SetShapeFn(shape_inference::ScalarShape);

REGISTER_KERNEL_BUILDER(Name("HvtAllreduce").Device(DEVICE_CPU),
                        HvtAllreduceOp);
REGISTER_KERNEL_BUILDER(Name("HvtAllgather").Device(DEVICE_CPU),
                        HvtAllgatherOp);
REGISTER_KERNEL_BUILDER(Name("HvtBroadcast").Device(DEVICE_CPU),
                        HvtBroadcastOp);
REGISTER_KERNEL_BUILDER(
    Name("HvtAlltoall").Device(DEVICE_CPU).HostMemory("splits"),
    HvtAlltoallOp);
REGISTER_KERNEL_BUILDER(Name("HvtReducescatter").Device(DEVICE_CPU),
                        HvtReducescatterOp);
REGISTER_KERNEL_BUILDER(Name("HvtSize").Device(DEVICE_CPU),
                        HvtScalarOp<SizeOrOne>);
REGISTER_KERNEL_BUILDER(Name("HvtRank").Device(DEVICE_CPU),
                        HvtScalarOp<RankOrZero>);
REGISTER_KERNEL_BUILDER(Name("HvtLocalSize").Device(DEVICE_CPU),
                        HvtScalarOp<LocalSizeOrOne>);
REGISTER_KERNEL_BUILDER(Name("HvtLocalRank").Device(DEVICE_CPU),
                        HvtScalarOp<LocalRankOrZero>);

}  // namespace hvt_tf
