// Core types for the hvt engine — the TPU-native counterpart of the
// reference's framework-agnostic abstractions (horovod/common/common.h:
// Status:134, TensorShape:170, DataType in message.h:30).
//
// Design note: this engine serves the *eager, cross-process* path (metrics,
// parameter broadcast, the torch binding, CPU-only jobs). The TPU training
// hot path compiles collectives into the XLA program and never enters this
// code; that split is the core architectural decision of the port (see
// horovod_tpu/ops/collective_ops.py docstring).
#pragma once

#include <chrono>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <functional>
#include <memory>
#include <string>
#include <vector>

// wire-codec registry (WireCodec ids + the Codec interface) — the ids
// ride in Responses and the stats-slot ABI, so they live beside the
// other wire types this header aggregates.
#include "codecs.h"
// clang -Wthread-safety macros (no-ops under gcc) — included from the
// root header so every engine file can annotate its locking contracts.
#include "thread_annotations.h"

namespace hvt {

inline double NowSec() {
  return std::chrono::duration<double>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

inline int64_t EnvInt(const char* name, int64_t dflt) {
  const char* v = getenv(name);
  return v ? atoll(v) : dflt;
}

enum class StatusType : uint8_t {
  OK = 0,
  UNKNOWN_ERROR = 1,
  PRECONDITION_ERROR = 2,
  ABORTED = 3,
  INVALID_ARGUMENT = 4,
  IN_PROGRESS = 5,
};

struct Status {
  StatusType type = StatusType::OK;
  std::string reason;

  static Status OK() { return Status{}; }
  static Status Error(const std::string& msg) {
    return Status{StatusType::UNKNOWN_ERROR, msg};
  }
  static Status PreconditionError(const std::string& msg) {
    return Status{StatusType::PRECONDITION_ERROR, msg};
  }
  static Status InvalidArgument(const std::string& msg) {
    return Status{StatusType::INVALID_ARGUMENT, msg};
  }
  static Status Aborted(const std::string& msg) {
    return Status{StatusType::ABORTED, msg};
  }
  bool ok() const { return type == StatusType::OK; }
};

// Wire dtype ids — stable across the ctypes boundary (numpy interop in
// horovod_tpu/engine/native.py).
enum class DataType : uint8_t {
  UINT8 = 0,
  INT8 = 1,
  INT32 = 4,
  INT64 = 5,
  FLOAT16 = 6,
  FLOAT32 = 7,
  FLOAT64 = 8,
  BOOL = 9,
  BFLOAT16 = 10,
};

inline size_t DataTypeSize(DataType d) {
  switch (d) {
    case DataType::UINT8:
    case DataType::INT8:
    case DataType::BOOL:
      return 1;
    case DataType::FLOAT16:
    case DataType::BFLOAT16:
      return 2;
    case DataType::INT32:
    case DataType::FLOAT32:
      return 4;
    case DataType::INT64:
    case DataType::FLOAT64:
      return 8;
  }
  return 0;
}

enum class OpType : uint8_t {
  ALLREDUCE = 0,
  ALLGATHER = 1,
  BROADCAST = 2,
  ALLTOALL = 3,
  REDUCESCATTER = 4,
  JOIN = 5,
  BARRIER = 6,
};

enum class ReduceKind : uint8_t {
  SUM = 0,
  AVERAGE = 1,
  MIN = 2,
  MAX = 3,
  PRODUCT = 4,
  ADASUM = 5,
};

struct TensorShape {
  std::vector<int64_t> dims;
  int64_t num_elements() const {
    int64_t n = 1;
    for (auto d : dims) n *= d;
    return n;
  }
  bool operator==(const TensorShape& o) const { return dims == o.dims; }
  std::string DebugString() const {
    std::string s = "[";
    for (size_t i = 0; i < dims.size(); ++i) {
      if (i) s += ",";
      s += std::to_string(dims[i]);
    }
    return s + "]";
  }
};

inline const char* OpName(OpType op) {
  switch (op) {
    case OpType::ALLREDUCE: return "ALLREDUCE";
    case OpType::ALLGATHER: return "ALLGATHER";
    case OpType::BROADCAST: return "BROADCAST";
    case OpType::ALLTOALL: return "ALLTOALL";
    case OpType::REDUCESCATTER: return "REDUCESCATTER";
    case OpType::JOIN: return "JOIN";
    case OpType::BARRIER: return "BARRIER";
  }
  return "OP";
}

// A pending collective submitted by a client thread — the analog of
// TensorTableEntry (reference common.h:237). Owns copies of the payload so
// client buffers can be released immediately.
struct TensorTableEntry {
  std::string name;
  int32_t handle = -1;
  OpType op = OpType::ALLREDUCE;
  ReduceKind reduce = ReduceKind::SUM;
  DataType dtype = DataType::FLOAT32;
  TensorShape shape;
  int32_t root_rank = 0;
  double prescale = 1.0;
  double postscale = 1.0;
  std::vector<uint8_t> input;           // payload
  std::vector<int64_t> splits;          // alltoallv send splits (rows)
  std::vector<uint8_t> output;          // filled by the op
  std::vector<int64_t> recv_splits;     // alltoallv result splits
  // deterministic fusion group (reference group_table.h): members of a
  // group are negotiated atomically and fused into one collective.
  // group_id < 0 → ungrouped. group_size = total members of the group.
  int32_t group_id = -1;
  int32_t group_size = 0;
  // process set: global ranks participating in this collective
  // (ascending); empty → the global set. Mirrors the later-lineage
  // horovod ProcessSet on the eager path.
  std::vector<int64_t> members;
  // stamped at Submit(); pending ages in the diagnostics snapshot
  double submit_sec = 0;
};

using EntryPtr = std::shared_ptr<TensorTableEntry>;

}  // namespace hvt
