// Ordered collective-backend architecture — the TPU-native engine's
// counterpart of the reference's OperationManager priority list
// (horovod/common/operations.cc:142-249): the engine dispatches each
// response to the FIRST backend whose Enabled() accepts it, so alternate
// data planes (hierarchical, future shared-memory local paths) slot in
// ahead of the always-enabled flat ring fallback.
//
// HierarchicalBackend is the eager analog of the reference's
// NCCLHierarchicalAllreduce (horovod/common/ops/nccl_operations.cc:188-350):
// reduce-scatter within the host (LOCAL communicator) → allreduce across
// hosts among same-local-index peers (CROSS) → allgather within the host.
// On a real deployment the local phase rides loopback/shared memory while
// only the cross phase crosses the network, cutting cross-host traffic to
// ~2·bytes/local_size per rank.
#pragma once

#include <memory>
#include <string>
#include <vector>

#include "codecs.h"
#include "common.h"
#include "ring_ops.h"
#include "wire.h"

namespace hvt {

// Host topology derived at rendezvous — the GLOBAL/LOCAL/CROSS
// communicator split (reference common.h:115-119, SURVEY §5.8: TPU
// mapping LOCAL=chips on one host, CROSS=one peer per host).
struct Topology {
  std::vector<std::string> host_of_rank;  // by global rank
  std::vector<int> local_group;           // ranks on my host, ascending
  std::vector<int> cross_group;           // my local index on every host
  int my_local = 0;
  int n_hosts = 1;
  bool homogeneous = true;  // every host has the same local size

  static Topology Build(int rank, const std::vector<std::string>& hosts);
};

// EQuARX-style link classification: does a collective over `group`
// (ascending global ranks; empty = full world) cross a host boundary?
// Deterministic from the rendezvous topology, hence identical on every
// rank — the property that lets each backend resolve a {intra, inter}
// codec pair locally without another negotiation round.
inline bool GroupSpansHosts(const Topology& t,
                            const std::vector<int>& group) {
  if (group.empty()) return t.n_hosts > 1;
  if (t.host_of_rank.empty()) return false;
  const std::string& h0 = t.host_of_rank[static_cast<size_t>(group[0])];
  for (int r : group)
    if (t.host_of_rank[static_cast<size_t>(r)] != h0) return true;
  return false;
}

// The codec a ring over `group` moves: inter-host rings take the
// `inter` codec, single-host rings the `intra` codec. (A mixed ring —
// some hops local, some not — counts as inter: its wire stream is
// forwarded hop to hop, so one codec must cover the whole rotation.)
inline WireCodec ResolveLinkCodec(const Topology& t, const WirePair& w,
                                  const std::vector<int>& group) {
  return GroupSpansHosts(t, group) ? w.inter : w.intra;
}

class CollectiveBackend {
 public:
  virtual ~CollectiveBackend() = default;
  virtual const char* Name() const = 0;
  // total_elems: summed numels of the (possibly fused) response.
  // resp.members non-empty = process-subset response; a backend that
  // accepts one must implement the *Group methods below (the reference
  // serves every op from the selected backend — operation_manager.cc).
  virtual bool Enabled(const Response& resp, int64_t total_elems) const = 0;
  // postscale: applied to the whole buffer as part of the collective —
  // backends fold it into their last data pass (ring: each rank scales
  // just its owned segment before the allgather; shm: each rank scales
  // its chunk of the shared result) instead of a separate full sweep.
  // wire: negotiated per-link-class codec pair from the Response
  // ({intra, inter} WireCodec ids); each backend maps the pair onto its
  // phases (ring: by whether the ring spans hosts; hierarchical: intra
  // on the local phases, inter on the cross phase; shm: no wire at
  // all).
  virtual void Allreduce(void* buf, int64_t count, DataType dtype,
                         ReduceKind red, double postscale,
                         WirePair wire) = 0;
  virtual void Allgatherv(const void* in, int64_t my_rows,
                          const std::vector<int64_t>& rows,
                          int64_t row_bytes, void* out);
  virtual void Broadcast(void* buf, int64_t bytes, int root);
  virtual void Alltoallv(const void* in,
                         const std::vector<int64_t>& send_rows,
                         int64_t row_bytes, void* out,
                         const std::vector<int64_t>& recv_rows);
  // full sender-position-major m x m row matrix (my_pos = this rank's
  // position). Default derives the send/recv vectors and delegates to
  // Alltoallv; the shm backend overrides to address peer slots directly.
  virtual void AlltoallvMatrix(const void* in,
                               const std::vector<int64_t>& rows_flat,
                               int m, int64_t row_bytes, void* out,
                               int my_pos);

  // ---- process-subset variants (group: ascending global ranks,
  // containing this rank; rows/positions indexed by group position) ----
  virtual void AllreduceGroup(void* buf, int64_t count, DataType dtype,
                              ReduceKind red,
                              const std::vector<int>& group,
                              double postscale, WirePair wire);
  virtual void AllgathervGroup(const void* in, int64_t my_rows,
                               const std::vector<int64_t>& rows,
                               int64_t row_bytes, void* out,
                               const std::vector<int>& group);
  virtual void BroadcastGroup(void* buf, int64_t bytes, int root,
                              const std::vector<int>& group);
  virtual void AlltoallvMatrixGroup(const void* in,
                                    const std::vector<int64_t>& rows_flat,
                                    int m, int64_t row_bytes, void* out,
                                    int my_pos,
                                    const std::vector<int>& group);
  // Reduce-scatter: leave THIS rank's chunk
  // [count*my_pos/m, count*(my_pos+1)/m) of buf reduced across the
  // participants (other regions of buf may stay stale — the engine
  // slices only the chunk). Default lowers to a full allreduce; the shm
  // backend overrides with a native chunk reduce.
  virtual void ReduceScatter(void* buf, int64_t count, DataType dtype,
                             ReduceKind red, int my_pos, int m,
                             const std::vector<int>& group,
                             bool full_world);

  // Called by the engine before dispatching each TENSOR response, with a
  // GLOBAL response sequence number (identical stream on every rank).
  // Synchronization keyed to it stays sound even when non-member ranks
  // skip responses and run ahead.
  virtual void BeginResponse(uint64_t seq) { (void)seq; }

  // True when *Group collectives over rank-disjoint link sets may run
  // on different threads at once — the eligibility gate of the engine's
  // per-lane execution pool (HVT_LANE_WORKERS). Only the flat TCP ring
  // qualifies: it is stateless per call (the DataPlane keeps per-thread
  // scratch) and pairwise, so disjoint groups never share a socket. The
  // shm backend sequences per-response barrier words through mutable
  // members and the hierarchical backend composes multiple phases —
  // both stay on the engine thread.
  virtual bool ConcurrentGroupsSafe() const { return false; }
};

// Flat TCP ring over the full mesh — always enabled (the fallback).
class RingBackend : public CollectiveBackend {
 public:
  // topo: used only to classify link classes for the wire-codec pair
  // (single-host ring → intra codec, host-spanning ring → inter).
  RingBackend(DataPlane* dp, Topology topo)
      : dp_(dp), topo_(std::move(topo)) {}
  const char* Name() const override { return "ring"; }
  bool Enabled(const Response&, int64_t) const override { return true; }
  bool ConcurrentGroupsSafe() const override { return true; }
  void Allreduce(void* buf, int64_t count, DataType dtype, ReduceKind red,
                 double postscale, WirePair wire) override;
  void Allgatherv(const void* in, int64_t my_rows,
                  const std::vector<int64_t>& rows, int64_t row_bytes,
                  void* out) override;
  void Broadcast(void* buf, int64_t bytes, int root) override;
  void Alltoallv(const void* in, const std::vector<int64_t>& send_rows,
                 int64_t row_bytes, void* out,
                 const std::vector<int64_t>& recv_rows) override;
  void AllreduceGroup(void* buf, int64_t count, DataType dtype,
                      ReduceKind red, const std::vector<int>& group,
                      double postscale, WirePair wire) override;
  void AllgathervGroup(const void* in, int64_t my_rows,
                       const std::vector<int64_t>& rows, int64_t row_bytes,
                       void* out, const std::vector<int>& group) override;
  void BroadcastGroup(void* buf, int64_t bytes, int root,
                      const std::vector<int>& group) override;
  void AlltoallvMatrixGroup(const void* in,
                            const std::vector<int64_t>& rows_flat, int m,
                            int64_t row_bytes, void* out, int my_pos,
                            const std::vector<int>& group) override;

 private:
  DataPlane* dp_;
  Topology topo_;
};

// Same-host POSIX-shared-memory data plane for single-host jobs: every
// rank copies its contribution into a per-rank slot of one shm segment,
// a sense-reversing barrier synchronizes, each rank reduces a contiguous
// chunk across all slots (parallel reduce-scatter in memory), and all
// ranks copy the combined result out — no sockets at all on the hot
// path, where the flat ring pays 2(N-1)/N of the payload through
// loopback TCP. Enabled for non-Adasum allreduces, broadcasts
// (write-once-read-many), allgathers, alltoalls, and native
// reduce-scatters that fit the preallocated capacity when every rank
// shares one host — full world AND process subsets (subset ops use
// per-group barrier cells and read peer slots directly, so disjoint
// subsets run concurrently without touching the shared result area).
// HVT_SHM_ALLREDUCE=0 disables the whole shm plane. The segment name is
// derived from the control-star port and unlinked as soon as every rank
// has mapped it, so crashed jobs never leak segments.
class ShmLocalBackend : public CollectiveBackend {
 public:
  // dp: used once at construction to sequence create-before-open across
  // ranks (tiny ring broadcasts); not used on the hot path.
  ShmLocalBackend(DataPlane* dp, int rank, int size, int shm_key,
                  int64_t capacity, bool enabled);
  ~ShmLocalBackend() override;
  const char* Name() const override { return "shm"; }
  bool Enabled(const Response& resp, int64_t total_elems) const override;
  void Allreduce(void* buf, int64_t count, DataType dtype, ReduceKind red,
                 double postscale, WirePair wire) override;
  void Broadcast(void* buf, int64_t bytes, int root) override;
  void Allgatherv(const void* in, int64_t my_rows,
                  const std::vector<int64_t>& rows, int64_t row_bytes,
                  void* out) override;
  void AlltoallvMatrix(const void* in,
                       const std::vector<int64_t>& rows_flat, int m,
                       int64_t row_bytes, void* out, int my_pos) override;
  void AllreduceGroup(void* buf, int64_t count, DataType dtype,
                      ReduceKind red, const std::vector<int>& group,
                      double postscale, WirePair wire) override;
  void AllgathervGroup(const void* in, int64_t my_rows,
                       const std::vector<int64_t>& rows, int64_t row_bytes,
                       void* out, const std::vector<int>& group) override;
  void BroadcastGroup(void* buf, int64_t bytes, int root,
                      const std::vector<int>& group) override;
  void AlltoallvMatrixGroup(const void* in,
                            const std::vector<int64_t>& rows_flat, int m,
                            int64_t row_bytes, void* out, int my_pos,
                            const std::vector<int>& group) override;
  void ReduceScatter(void* buf, int64_t count, DataType dtype,
                     ReduceKind red, int my_pos, int m,
                     const std::vector<int>& group,
                     bool full_world) override;
  void BeginResponse(uint64_t seq) override;

 private:
  // Group barrier via per-rank PROGRESS WORDS: each member publishes
  // (response seq << 3 | phase) into its own word and waits until every
  // co-member's word reaches that value. No shared counters, so a rank
  // that skipped this response and ran ahead into a later collective can
  // never pollute another group's barrier (values are monotonic per
  // writer; a co-member's larger value proves it already passed here).
  void Barrier(const std::vector<int>& group);
  void LogSubsetOnce(const std::vector<int>& group);
  void A2aFromSlots(const void* in, const std::vector<int64_t>& rows_flat,
                    int m, int64_t row_bytes, void* out, int my_pos,
                    const std::vector<int>& group);
  uint8_t* slot(int r) const;
  uint8_t* result() const;

  int rank_ = 0, size_ = 1;
  int64_t capacity_ = 0;
  bool enabled_ = false;
  bool used_logged_ = false;
  bool bcast_logged_ = false;
  bool gather_logged_ = false;
  bool a2a_logged_ = false;
  bool subset_logged_ = false;
  bool rs_logged_ = false;
  uint8_t* base_ = nullptr;
  size_t map_bytes_ = 0;
  size_t hdr_bytes_ = 0;
  uint64_t seq_ = 0;      // current response sequence (BeginResponse)
  uint32_t phase_ = 0;    // barrier index within the current response
  std::vector<int> world_group_;
};

// Local reduce-scatter → cross-host allreduce → local allgather.
// Enabled for non-Adasum allreduces on a homogeneous multi-host topology
// with >1 rank per host; HVT_HIERARCHICAL_ALLREDUCE=0 disables.
// The {intra, inter} codec pair maps 1:1 onto its phases: the local
// (intra-host) reduce-scatter/allgather take wire.intra — full
// precision under the recommended `none,<codec>` pair — while the
// cross-host phase takes wire.inter, which is exactly where DCN bytes
// are paid (EQuARX's topology-aware quantization).
class HierarchicalBackend : public CollectiveBackend {
 public:
  HierarchicalBackend(DataPlane* dp, Topology topo, bool enabled)
      : dp_(dp), topo_(std::move(topo)), enabled_(enabled) {}
  const char* Name() const override { return "hierarchical"; }
  bool Enabled(const Response& resp, int64_t total_elems) const override;
  void Allreduce(void* buf, int64_t count, DataType dtype, ReduceKind red,
                 double postscale, WirePair wire) override;

 private:
  DataPlane* dp_;
  Topology topo_;
  bool enabled_;
};

}  // namespace hvt
