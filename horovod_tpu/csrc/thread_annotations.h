// Clang thread-safety annotations (-Wthread-safety) for the engine's
// five-mutex concurrency (queue/handles/broken/diag locks + the event
// ring's drain lock). The macros expand to real attributes under clang
// and to nothing under gcc, so the default build is unaffected while
// `make tidy` (clang++ -fsyntax-only -Wthread-safety -Werror) machine-
// checks every GUARDED_BY / REQUIRES / EXCLUDES contract and the
// declared lock order. Reference: the Horovod lineage relies on TSan at
// runtime for this (SURVEY §5.2); the annotations move the same class
// of bug to compile time.
//
// std::mutex is not a capability-annotated type, so the analysis cannot
// follow it; hvt::Mutex wraps it with the capability attributes and
// hvt::MutexLock / hvt::CvLock are the annotated scoped guards (the
// std::lock_guard / std::unique_lock equivalents). Condition variables
// stay std::condition_variable, waiting on CvLock::native() — the
// underlying std::unique_lock<std::mutex>. (Not condition_variable_any:
// its internal shared mutex trips known TSan false positives on
// libstdc++ — double-lock / lock-order reports inside wait/notify —
// which would poison the `ci.sh --sanitize` gangs.) The wait's
// unlock/relock is invisible to the analysis, which is sound: the
// capability is held at every point the waiting code touches guarded
// state (predicates run with the lock held).
#pragma once

#include <mutex>

#if defined(__clang__) && (!defined(SWIG))
#define HVT_THREAD_ANNOTATION__(x) __attribute__((x))
#else
#define HVT_THREAD_ANNOTATION__(x)  // no-op under gcc
#endif

#define CAPABILITY(x) HVT_THREAD_ANNOTATION__(capability(x))
#define SCOPED_CAPABILITY HVT_THREAD_ANNOTATION__(scoped_lockable)
#define GUARDED_BY(x) HVT_THREAD_ANNOTATION__(guarded_by(x))
#define PT_GUARDED_BY(x) HVT_THREAD_ANNOTATION__(pt_guarded_by(x))
#define ACQUIRED_BEFORE(...) \
  HVT_THREAD_ANNOTATION__(acquired_before(__VA_ARGS__))
#define ACQUIRED_AFTER(...) \
  HVT_THREAD_ANNOTATION__(acquired_after(__VA_ARGS__))
#define REQUIRES(...) \
  HVT_THREAD_ANNOTATION__(requires_capability(__VA_ARGS__))
#define ACQUIRE(...) \
  HVT_THREAD_ANNOTATION__(acquire_capability(__VA_ARGS__))
#define RELEASE(...) \
  HVT_THREAD_ANNOTATION__(release_capability(__VA_ARGS__))
#define TRY_ACQUIRE(...) \
  HVT_THREAD_ANNOTATION__(try_acquire_capability(__VA_ARGS__))
#define EXCLUDES(...) HVT_THREAD_ANNOTATION__(locks_excluded(__VA_ARGS__))
#define RETURN_CAPABILITY(x) HVT_THREAD_ANNOTATION__(lock_returned(x))
#define NO_THREAD_SAFETY_ANALYSIS \
  HVT_THREAD_ANNOTATION__(no_thread_safety_analysis)

namespace hvt {

// std::mutex with the capability attribute the analysis needs.
// native() exposes the wrapped mutex for std::condition_variable waits
// (via CvLock below) — the capability and the lockable object are the
// same mutex, so the annotation stays truthful.
class CAPABILITY("mutex") Mutex {
 public:
  Mutex() = default;
  Mutex(const Mutex&) = delete;
  Mutex& operator=(const Mutex&) = delete;

  void lock() ACQUIRE() { mu_.lock(); }
  void unlock() RELEASE() { mu_.unlock(); }
  bool try_lock() TRY_ACQUIRE(true) { return mu_.try_lock(); }
  std::mutex& native() { return mu_; }

 private:
  std::mutex mu_;
};

// Annotated std::lock_guard equivalent.
class SCOPED_CAPABILITY MutexLock {
 public:
  explicit MutexLock(Mutex& mu) ACQUIRE(mu) : mu_(mu) { mu_.lock(); }
  ~MutexLock() RELEASE() { mu_.unlock(); }
  MutexLock(const MutexLock&) = delete;
  MutexLock& operator=(const MutexLock&) = delete;

 private:
  Mutex& mu_;
};

// Annotated std::unique_lock equivalent for condition-variable waits:
// pass native() to std::condition_variable::wait / wait_for. The lock
// is held whenever control is outside the wait (including inside wait
// predicates), which is exactly what the scope annotation claims.
class SCOPED_CAPABILITY CvLock {
 public:
  explicit CvLock(Mutex& mu) ACQUIRE(mu) : lk_(mu.native()) {}
  ~CvLock() RELEASE() {}
  CvLock(const CvLock&) = delete;
  CvLock& operator=(const CvLock&) = delete;

  std::unique_lock<std::mutex>& native() { return lk_; }

 private:
  std::unique_lock<std::mutex> lk_;
};

}  // namespace hvt
