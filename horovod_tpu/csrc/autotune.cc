#include "autotune.h"

#include <algorithm>
#include <cmath>
#include <cstdio>

#include "common.h"  // NowSec, EnvInt

namespace hvt {

// ------------------------------------------------------------------ GP

double GaussianProcess::Kernel(const std::vector<double>& a,
                               const std::vector<double>& b) const {
  double d2 = 0;
  for (size_t i = 0; i < a.size(); ++i) {
    double d = a[i] - b[i];
    d2 += d * d;
  }
  return std::exp(-d2 / (2.0 * length_scale_ * length_scale_));
}

bool GaussianProcess::Fit(const std::vector<std::vector<double>>& X,
                          const std::vector<double>& y) {
  const int n = static_cast<int>(X.size());
  if (n == 0 || y.size() != X.size()) return false;
  X_ = X;
  y_mean_ = 0;
  for (double v : y) y_mean_ += v;
  y_mean_ /= n;
  double var = 0;
  for (double v : y) var += (v - y_mean_) * (v - y_mean_);
  y_std_ = n > 1 ? std::sqrt(var / (n - 1)) : 1.0;
  if (y_std_ < 1e-12) y_std_ = 1.0;

  // K + noise I, then Cholesky (n is small: <= max_samples)
  std::vector<std::vector<double>> K(n, std::vector<double>(n));
  for (int i = 0; i < n; ++i)
    for (int j = 0; j < n; ++j)
      K[i][j] = Kernel(X[i], X[j]) + (i == j ? noise_ : 0.0);

  L_.assign(n, std::vector<double>(n, 0.0));
  for (int i = 0; i < n; ++i) {
    for (int j = 0; j <= i; ++j) {
      double s = K[i][j];
      for (int k = 0; k < j; ++k) s -= L_[i][k] * L_[j][k];
      if (i == j) {
        if (s <= 0) return false;
        L_[i][j] = std::sqrt(s);
      } else {
        L_[i][j] = s / L_[j][j];
      }
    }
  }

  // alpha = L^-T (L^-1 z), z = standardized y
  std::vector<double> z(n);
  for (int i = 0; i < n; ++i) z[i] = (y[i] - y_mean_) / y_std_;
  // forward solve L v = z
  std::vector<double> v(n);
  for (int i = 0; i < n; ++i) {
    double s = z[i];
    for (int k = 0; k < i; ++k) s -= L_[i][k] * v[k];
    v[i] = s / L_[i][i];
  }
  // back solve L^T alpha = v
  alpha_.assign(n, 0.0);
  for (int i = n - 1; i >= 0; --i) {
    double s = v[i];
    for (int k = i + 1; k < n; ++k) s -= L_[k][i] * alpha_[k];
    alpha_[i] = s / L_[i][i];
  }
  fitted_ = true;
  return true;
}

void GaussianProcess::Predict(const std::vector<double>& x, double* mean,
                              double* var) const {
  const int n = static_cast<int>(X_.size());
  if (!fitted_ || n == 0) {
    if (mean) *mean = y_mean_;
    if (var) *var = 1.0;
    return;
  }
  std::vector<double> ks(n);
  for (int i = 0; i < n; ++i) ks[i] = Kernel(x, X_[i]);
  double mu = 0;
  for (int i = 0; i < n; ++i) mu += ks[i] * alpha_[i];
  if (mean) *mean = y_mean_ + y_std_ * mu;
  if (var) {
    // v = L^-1 ks ; var = k(x,x) - vᵀv
    std::vector<double> v(n);
    for (int i = 0; i < n; ++i) {
      double s = ks[i];
      for (int k = 0; k < i; ++k) s -= L_[i][k] * v[k];
      v[i] = s / L_[i][i];
    }
    double vv = 0;
    for (int i = 0; i < n; ++i) vv += v[i] * v[i];
    double raw = Kernel(x, x) - vv;
    *var = std::max(raw, 1e-12) * y_std_ * y_std_;
  }
}

// ------------------------------------------------------------------ BO

double BayesianOptimizer::NextUniform() {
  // xorshift64* — deterministic, no global state
  rng_ ^= rng_ >> 12;
  rng_ ^= rng_ << 25;
  rng_ ^= rng_ >> 27;
  return static_cast<double>((rng_ * 0x2545F4914F6CDD1DULL) >> 11) /
         9007199254740992.0;
}

void BayesianOptimizer::AddSample(const std::vector<double>& x, double y) {
  xs_.push_back(x);
  ys_.push_back(y);
  if (y > best_y_) {
    best_y_ = y;
    best_x_ = x;
  }
}

static double NormCdf(double z) { return 0.5 * std::erfc(-z / M_SQRT2); }
static double NormPdf(double z) {
  return std::exp(-0.5 * z * z) / std::sqrt(2.0 * M_PI);
}

double BayesianOptimizer::ExpectedImprovement(
    const GaussianProcess& gp, const std::vector<double>& x) const {
  double mu, var;
  gp.Predict(x, &mu, &var);
  double sigma = std::sqrt(var);
  if (sigma < 1e-12) return 0.0;
  const double xi = 0.01 * std::abs(best_y_);  // exploration margin
  double z = (mu - best_y_ - xi) / sigma;
  return (mu - best_y_ - xi) * NormCdf(z) + sigma * NormPdf(z);
}

std::vector<double> BayesianOptimizer::Suggest(int candidates, int min_fit) {
  if (num_samples() < min_fit) {
    // space-filling start: jittered grid diagonal per dimension
    std::vector<double> x(dims_);
    for (int d = 0; d < dims_; ++d) {
      double base = (num_samples() + 0.5) / min_fit;
      x[d] = std::min(1.0, std::max(0.0,
          (d % 2 == 0 ? base : 1.0 - base) +
              0.1 * (NextUniform() - 0.5)));
    }
    return x;
  }
  GaussianProcess gp;
  if (!gp.Fit(xs_, ys_)) {
    std::vector<double> x(dims_);
    for (int d = 0; d < dims_; ++d) x[d] = NextUniform();
    return x;
  }
  std::vector<double> best(dims_, 0.5);
  double best_ei = -1;
  for (int c = 0; c < candidates; ++c) {
    std::vector<double> x(dims_);
    for (int d = 0; d < dims_; ++d) x[d] = NextUniform();
    double ei = ExpectedImprovement(gp, x);
    if (ei > best_ei) {
      best_ei = ei;
      best = x;
    }
  }
  return best;
}

// ---------------------------------------------------- ParameterManager

// tunable box: x0 = log2(fusion_threshold) in [20, 28] (1 MB..256 MB),
// x1 = cycle_ms in [1, 25], x2 = cache enabled (>0.5), x3 = prefer the
// flat ring over the priority backends (>0.5)
static const double kLog2FusionMin = 20.0, kLog2FusionMax = 28.0;
static const double kCycleMin = 1.0, kCycleMax = 25.0;

ParameterManager::ParameterManager() = default;

void ParameterManager::Initialize(int64_t fusion_threshold, int cycle_ms) {
  // full reset: Initialize is re-entered on elastic shutdown/re-init and
  // must not inherit a finished or half-run tuning session
  done_ = false;
  samples_ = 0;
  cycle_count_ = 0;
  bytes_acc_ = 0;
  bo_ = BayesianOptimizer(4);
  fusion_threshold_ = fusion_threshold;
  cycle_ms_ = cycle_ms;
  cache_enabled_ = true;
  prefer_flat_ = false;
  active_ = EnvInt("HVT_AUTOTUNE", 0) != 0;
  warmup_remaining_ =
      static_cast<int>(EnvInt("HVT_AUTOTUNE_WARMUP_SAMPLES", 3));
  cycles_per_sample_ =
      static_cast<int>(EnvInt("HVT_AUTOTUNE_CYCLES_PER_SAMPLE", 50));
  max_samples_ = static_cast<int>(EnvInt("HVT_AUTOTUNE_MAX_SAMPLES", 20));
  const char* log = getenv("HVT_AUTOTUNE_LOG");
  log_path_ = log ? log : "";
  window_start_ = NowSec();
}

std::vector<double> ParameterManager::CurrentPoint() const {
  double x0 = (std::log2(static_cast<double>(fusion_threshold_)) -
               kLog2FusionMin) / (kLog2FusionMax - kLog2FusionMin);
  double x1 = (cycle_ms_ - kCycleMin) / (kCycleMax - kCycleMin);
  return {std::min(1.0, std::max(0.0, x0)),
          std::min(1.0, std::max(0.0, x1)),
          cache_enabled_ ? 1.0 : 0.0,
          prefer_flat_ ? 1.0 : 0.0};
}

void ParameterManager::ApplyPoint(const std::vector<double>& x) {
  double l2 = kLog2FusionMin + x[0] * (kLog2FusionMax - kLog2FusionMin);
  fusion_threshold_ = static_cast<int64_t>(std::pow(2.0, l2));
  cycle_ms_ = static_cast<int>(
      std::lround(kCycleMin + x[1] * (kCycleMax - kCycleMin)));
  if (cycle_ms_ < 1) cycle_ms_ = 1;
  cache_enabled_ = x.size() > 2 ? x[2] > 0.5 : true;
  prefer_flat_ = x.size() > 3 ? x[3] > 0.5 : false;
}

void ParameterManager::Log(double score) {
  if (log_path_.empty()) return;
  FILE* f = fopen(log_path_.c_str(), "a");
  if (!f) return;
  fprintf(f, "%d,%lld,%d,%d,%d,%.1f\n", samples_.load(),
          static_cast<long long>(fusion_threshold_), cycle_ms_,
          cache_enabled_ ? 1 : 0, prefer_flat_ ? 1 : 0, score);
  fclose(f);
}

bool ParameterManager::Record(int64_t bytes) {
  if (!active_ || done_) return false;
  if (bytes <= 0 && cycle_count_ == 0) {
    // idle engine (no tensor traffic yet): don't open a sample window —
    // otherwise the whole tuning budget elapses on startup noise and the
    // tuner freezes on an arbitrary point. The reference ties samples to
    // actual traffic the same way.
    window_start_ = NowSec();
    return false;
  }
  bytes_acc_ += bytes;
  if (++cycle_count_ < cycles_per_sample_) return false;
  double now = NowSec();
  double dur = now - window_start_;
  double score = dur > 0 ? static_cast<double>(bytes_acc_) / dur : 0.0;
  bool empty_window = bytes_acc_ == 0;
  cycle_count_ = 0;
  bytes_acc_ = 0;
  window_start_ = now;
  if (empty_window) return false;  // traffic stopped mid-window: discard

  if (warmup_remaining_ > 0) {
    // discard: engine still filling caches / JIT warm-up on the client
    --warmup_remaining_;
    return false;
  }
  ++samples_;
  bo_.AddSample(CurrentPoint(), score);
  Log(score);
  if (samples_ >= max_samples_) {
    // freeze at the best observed point
    ApplyPoint(bo_.best_x());
    done_ = true;
    return true;
  }
  ApplyPoint(bo_.Suggest());
  return true;
}

// ---------------------------------------------------------------- CodecTuner

namespace {
constexpr WireCodec kCodecCands[CodecTuner::kNumCand] = {
    WireCodec::RAW, WireCodec::BF16, WireCodec::INT8_BLOCK};
}  // namespace

void CodecTuner::Reset() {
  for (auto& link : cells_)
    for (auto& c : link) c = Cell{};
}

int CodecTuner::Bucket(int64_t bytes) {
  int b = 0;
  while ((int64_t{1} << (b + 11)) < bytes && b < kBuckets - 1) ++b;
  return b;  // bucket 0 ≤ 2 KB, each next doubles
}

int CodecTuner::CandIndex(WireCodec c) {
  for (int i = 0; i < kNumCand; ++i)
    if (kCodecCands[i] == c) return i;
  return -1;
}

WireCodec CodecTuner::Pick(int64_t bytes, int link) {
  Cell& cell = cells_[link & 1][Bucket(bytes)];
  if (cell.locked >= 0) return kCodecCands[cell.locked];
  // rotate: the first candidate still short of its trial budget. Several
  // responses may pick the same candidate before its observations land —
  // the budget then merely overfills, which is harmless and keeps Pick
  // deterministic without cross-call state.
  for (int i = 0; i < kNumCand; ++i)
    if (cell.n[i] < kTrials) return kCodecCands[i];
  // all sampled: lock the byte-throughput argmax
  int best = 0;
  double best_tp = -1.0;
  for (int i = 0; i < kNumCand; ++i) {
    double tp = cell.ns[i] > 0
                    ? static_cast<double>(cell.bytes[i]) / cell.ns[i]
                    : 0.0;
    if (tp > best_tp) {
      best_tp = tp;
      best = i;
    }
  }
  cell.locked = best;
  return kCodecCands[best];
}

void CodecTuner::Observe(int64_t bytes, int link, WireCodec codec,
                         int64_t ns) {
  int i = CandIndex(codec);
  if (i < 0 || ns <= 0) return;
  Cell& cell = cells_[link & 1][Bucket(bytes)];
  cell.ns[i] += ns;
  cell.bytes[i] += bytes;
  cell.n[i] += 1;
}

bool CodecTuner::Locked(int64_t bytes, int link) const {
  return cells_[link & 1][Bucket(bytes)].locked >= 0;
}

}  // namespace hvt
