// Leveled stream logger — counterpart of the reference's
// common/logging.{h,cc}: HVT_LOG(INFO) << "...", filtered by
// HVT_LOG_LEVEL (trace|debug|info|warning|error|fatal|none, default
// warning) with optional timestamps (HVT_LOG_HIDE_TIME=1 disables),
// mirroring the HOROVOD_LOG_LEVEL / timestamp knobs surfaced by the
// launcher (reference launch.py:455-463).
#pragma once

#include <cstdio>
#include <cstring>
#include <ctime>
#include <sstream>
#include <string>

#include "common.h"  // EnvInt

namespace hvt {

enum class LogLevel : int {
  TRACE = 0,
  DEBUG = 1,
  INFO = 2,
  WARNING = 3,
  ERROR = 4,
  FATAL = 5,
  NONE = 6,
};

inline LogLevel MinLogLevel() {
  static LogLevel cached = [] {
    const char* v = getenv("HVT_LOG_LEVEL");
    if (!v) return LogLevel::WARNING;
    std::string s(v);
    for (auto& c : s) c = tolower(c);
    if (s == "trace") return LogLevel::TRACE;
    if (s == "debug") return LogLevel::DEBUG;
    if (s == "info") return LogLevel::INFO;
    if (s == "warning" || s == "warn") return LogLevel::WARNING;
    if (s == "error") return LogLevel::ERROR;
    if (s == "fatal") return LogLevel::FATAL;
    if (s == "none" || s == "off") return LogLevel::NONE;
    return LogLevel::WARNING;
  }();
  return cached;
}

class LogMessage : public std::ostringstream {
 public:
  LogMessage(LogLevel level, int rank) : level_(level), rank_(rank) {}
  ~LogMessage() override {
    static const char* names[] = {"TRACE", "DEBUG", "INFO", "WARNING",
                                  "ERROR", "FATAL"};
    char ts[32] = "";
    if (EnvInt("HVT_LOG_HIDE_TIME", 0) == 0) {
      time_t t = time(nullptr);
      struct tm tmv;
      localtime_r(&t, &tmv);
      strftime(ts, sizeof(ts), "%H:%M:%S ", &tmv);
    }
    fprintf(stderr, "[%s%s hvt:%d] %s\n", ts,
            names[static_cast<int>(level_)], rank_, str().c_str());
    if (level_ == LogLevel::FATAL) abort();
  }

 private:
  LogLevel level_;
  int rank_;
};

// usage: HVT_LOG(INFO, rank) << "engine up, size " << size;
// The if/else pair keeps the macro dangling-else-safe inside an
// unbraced outer if/else.
#define HVT_LOG(level, rank)                             \
  if (::hvt::LogLevel::level < ::hvt::MinLogLevel()) {   \
  } else                                                 \
    ::hvt::LogMessage(::hvt::LogLevel::level, (rank))

}  // namespace hvt
