// The background engine — counterpart of the reference's
// BackgroundThreadLoop / RunLoopOnce / PerformOperation
// (horovod/common/operations.cc:356,587,253) plus the rank-0 coordinator
// protocol (horovod/common/controller.cc:69 ComputeResponseList).
//
// One engine per process. Client threads submit TensorTableEntry and get an
// integer handle; the engine thread runs a cycle loop:
//
//   1. drain the submission queue into the pending table
//   2. control-plane exchange with rank 0 (cache-hit positions, cache
//      invalidations, full requests for cache misses, shutdown/join flags)
//   3. rank 0: AND cache-hit sets, count per-tensor readiness, run
//      cross-rank consistency checks, fuse, order → ResponseList
//   4. every rank executes the identical ResponseList against the data
//      plane (ring collectives), fills outputs, completes handles
//
// Consistency checks turn cross-rank mismatches (dtype/shape/op/root) into
// per-tensor ERROR responses instead of deadlocks, matching
// controller.cc:481-706. The stall inspector (stall_inspector.h lineage)
// warns from rank 0 when some ranks submitted a tensor and others haven't.
//
// Concurrency map (machine-checked by `make tidy` via the clang
// -Wthread-safety annotations below; see thread_annotations.h):
//   queue_mu_   guards the submission queue (client threads push, the
//               engine thread drains);
//   handles_mu_ guards the handle table + the in-flight entry list
//               (client threads wait/poll/release, engine thread
//               completes);
//   broken_mu_  guards the sticky abort cause/reason strings;
//   diag_mu_    guards the diagnostics snapshot.
// Documented lock order: broken_mu_ and queue_mu_ may each be held when
// handles_mu_ is acquired (FailAll drains submitted_ under queue_mu_
// and completes each entry, which takes handles_mu_); never the
// reverse. Fields with no GUARDED_BY are either atomics, engine-thread-
// only state (pending_/counts_/groups_/...), or set once at Init before
// the engine thread starts.
#pragma once

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <deque>
#include <map>
#include <memory>
#include <mutex>
#include <set>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

#include "autotune.h"
#include "backends.h"
#include "cache.h"
#include "common.h"
#include "events.h"
#include "net.h"
#include "ring_ops.h"
#include "timeline.h"
#include "transport.h"
#include "wire.h"

namespace hvt {

// Atomic engine stats block, polled live over the C API
// (hvt_engine_stats → horovod_tpu/metrics registry). Writers are the
// engine thread (plus Submit on client threads); readers poll from any
// thread, so every field is a relaxed atomic — cheap enough to keep the
// counters unconditionally on.
constexpr int kStatsOps = 7;  // OpType 0..6 (common.h)
// the DataPlane writes codec_tx_bytes with a kWireOps stride while the
// array below is sized with kStatsOps — drift between the two would be
// out-of-bounds atomic writes, not just a misattributed slot
static_assert(kWireOps == kStatsOps,
              "ring_ops.h kWireOps must match engine.h kStatsOps: "
              "DataPlane::CountTx indexes EngineStats::codec_tx_bytes");

// --------------------------------------------------------------------------
// per-set engine lanes
// --------------------------------------------------------------------------
// A "lane" is the engine-side identity of a process set: negotiation
// state, the response cache, and the fusion buffer are all keyed by it,
// so disjoint sub-gangs (e.g. serving replicas) never contend on one
// shared buffer or renegotiate through one another's cache entries.
// Lane 0 is the global set; any other lane is the FNV-1a hash of the
// sorted member-rank list (the submit path sorts and dedups members, so
// equal sets always hash equal).
inline uint64_t LaneId(const std::vector<int64_t>& members) {
  if (members.empty()) return 0;
  uint64_t h = 1469598103934665603ull;  // FNV offset basis
  for (int64_t m : members) {
    for (int b = 0; b < 8; ++b) {
      h ^= static_cast<uint64_t>(m >> (b * 8)) & 0xff;
      h *= 1099511628211ull;  // FNV prime
    }
  }
  return h ? h : 1;  // 0 is reserved for the global lane
}

// Fixed telemetry buckets for per-lane stats (the stats-slot ABI cannot
// grow per live lane): bucket 0 is the global lane, set lanes hash onto
// buckets 1..kLaneSlots-1. Collisions merge telemetry, never semantics.
constexpr int kLaneSlots = 8;
inline int LaneSlot(uint64_t lane) {
  return lane == 0 ? 0 : 1 + static_cast<int>(lane % (kLaneSlots - 1));
}

// --------------------------------------------------------------------------
// control-plane topology
// --------------------------------------------------------------------------
// HVT_CTRL_TOPOLOGY selects how negotiation traffic reaches rank 0:
//   star (default): every rank exchanges frames with rank 0 directly —
//     the parity baseline, O(world) sockets on the coordinator.
//   tree: one LEADER per host aggregates its co-located MEMBERS'
//     announcements into a single batched cross-host frame and fans the
//     (identical) response back down, so the rank-0 hot loop serves
//     O(hosts) sockets. Rank 0 is the pure ROOT: even on its own host
//     the members attach to a separate leader (the lowest non-zero
//     rank), capping the root's fan-in at one peer per host that has a
//     leader (= the host count; one less when rank 0 sits on a host of
//     its own, which then needs no leader).
// Role wire ids are stamped into CTRL_BYTES events (EventView.op) and
// mirrored by hvt_analyze.CTRL_ROLES — a cross-language contract
// checked by tools/hvt_lint.py.
enum class CtrlRole : int32_t {
  ROOT = 0,    // rank 0: terminates every negotiation
  LEADER = 1,  // aggregates one host's members (tree mode only)
  MEMBER = 2,  // talks to its leader (tree) or to rank 0 (star)
};
inline const char* CtrlRoleName(CtrlRole r) {
  switch (r) {
    case CtrlRole::ROOT: return "root";
    case CtrlRole::LEADER: return "leader";
    case CtrlRole::MEMBER: return "member";
  }
  return "?";
}

// Abort causes for the coordinated-abort path — index into
// EngineStats::aborts and the {cause} label of
// hvt_engine_aborts_total. Wire ids (part of the stats-slot ABI).
enum AbortCause : int {
  kAbortTimeout = 0,      // an op hit its HVT_OP_TIMEOUT_MS deadline
  kAbortPeerLost = 1,     // a connection dropped (FIN/RST/EPIPE)
  kAbortRemote = 2,       // an ABORT control frame arrived from a peer
  kAbortHeartbeat = 3,    // idle-gang heartbeat missed (HVT_HEARTBEAT_MS)
  kAbortInternal = 4,     // any other engine-thread exception
};
constexpr int kAbortCauses = 5;
inline const char* AbortCauseName(int c) {
  switch (c) {
    case kAbortTimeout: return "timeout";
    case kAbortPeerLost: return "peer_lost";
    case kAbortRemote: return "remote_abort";
    case kAbortHeartbeat: return "heartbeat";
  }
  return "internal";
}

// Engine-thrown abort classifications layered over the net.h transport
// errors (PeerLostError / OpTimeoutError).
struct RemoteAbortError : std::runtime_error {
  explicit RemoteAbortError(const std::string& w)
      : std::runtime_error(w) {}
};
struct HeartbeatLostError : std::runtime_error {
  explicit HeartbeatLostError(const std::string& w)
      : std::runtime_error(w) {}
};

// Fixed log-scale latency histogram: bucket i holds observations
// ≤ 1 µs · 4^i (matches metrics.DEFAULT_LATENCY_BUCKETS so the Python
// bridge maps buckets 1:1), slot kLatBuckets is +Inf overflow. Writers
// are engine/client threads, readers poll — relaxed atomics throughout.
constexpr int kLatBuckets = 14;

struct LatencyHist {
  std::atomic<int64_t> buckets[kLatBuckets + 1]{};
  std::atomic<int64_t> sum_ns{0};
  std::atomic<int64_t> count{0};

  void Observe(int64_t ns) {
    int64_t bound = 1000;  // 1 µs
    int i = 0;
    while (i < kLatBuckets && ns > bound) {
      bound *= 4;
      ++i;
    }
    buckets[i].fetch_add(1, std::memory_order_relaxed);
    sum_ns.fetch_add(ns, std::memory_order_relaxed);
    count.fetch_add(1, std::memory_order_relaxed);
  }
  void Reset() {
    for (auto& b : buckets) b = 0;
    sum_ns = 0;
    count = 0;
  }
};

struct EngineStats {
  std::atomic<int64_t> cycles{0};               // RunCycle iterations
  std::atomic<int64_t> tensors_submitted{0};    // client Submit() calls
  std::atomic<int64_t> tensors_coordinated{0};  // names executed (TENSOR)
  std::atomic<int64_t> cache_hits{0};           // response-cache hits
  std::atomic<int64_t> cache_misses{0};         // cacheable lookups missed
  std::atomic<int64_t> fusion_bytes{0};         // bytes through the
                                                // fusion buffer
  std::atomic<int64_t> responses_fused{0};      // responses merged by
                                                // FuseResponses
  std::atomic<int64_t> stall_events{0};         // stall-inspector warnings
  std::atomic<int64_t> exec_ns[kStatsOps]{};    // per-OpType execution ns
  std::atomic<int64_t> exec_count[kStatsOps]{};
  // TCP data-plane wire telemetry. Owned HERE (not by the DataPlane,
  // which Shutdown destroys) so scrape threads polling hvt_engine_stats
  // can never race a teardown; the DataPlane writes through bound
  // pointers (DataPlane::BindTxCounters).
  std::atomic<int64_t> wire_tx_bytes[kStatsOps]{};
  std::atomic<int64_t> wire_tx_comp_bytes[kStatsOps]{};
  // coordinated aborts by cause (hvt_engine_aborts_total{cause}); at
  // most one increment per engine run — the broken state is sticky
  std::atomic<int64_t> aborts[kAbortCauses]{};
  // per-set lane telemetry (hvt_lane_*): distinct lanes seen since
  // init, pending-entry depth per lane bucket (a gauge, overwritten
  // each cycle), and data-plane execution time/count per lane bucket
  std::atomic<int64_t> lanes_active{0};
  std::atomic<int64_t> lane_depth[kLaneSlots]{};
  std::atomic<int64_t> lane_exec_ns[kLaneSlots]{};
  std::atomic<int64_t> lane_exec_count[kLaneSlots]{};
  // control-plane frame bytes through the rank-0 star (payload + the
  // 8-byte length prefixes), accumulated every cycle including idle
  // heartbeats — the negotiation-cost denominator of the critical-path
  // analysis (CTRL_BYTES flight-recorder events carry the per-cycle
  // deltas for cycles that did work)
  std::atomic<int64_t> ctrl_tx_bytes{0};
  std::atomic<int64_t> ctrl_rx_bytes{0};
  // direct control-plane peers this rank serves (gauge, set at Init):
  // the scaling story in one number — star rank 0 reports world-1,
  // tree rank 0 reports the host count
  std::atomic<int64_t> ctrl_peers{0};
  // cycles that rode the steady-state bypass (position-form response
  // rebuilt from the cache instead of full per-name payloads)
  std::atomic<int64_t> ctrl_bypass_cycles{0};
  // per-(codec, op) TCP data-plane bytes sent, codec-major flat array —
  // the source of hvt_wire_tx_bytes_total{op,codec}. Codec row 0
  // ("none") counts raw transfers, so summing rows reproduces the
  // per-op wire_tx_bytes totals. Owned here for the same
  // outlives-the-DataPlane reason as the counters above.
  std::atomic<int64_t> codec_tx_bytes[kWireCodecCount * kStatsOps]{};
  // error-feedback residual store: resident fp32 residual bytes (gauge)
  // and residual buffers dropped because HVT_EF_MAX_BYTES could not
  // admit them (counter)
  std::atomic<int64_t> ef_residual_bytes{0};
  std::atomic<int64_t> ef_residuals_dropped{0};
  // self-healing links (transport.h): transparent reconnects per plane
  // (hvt_link_reconnects_total{plane}), whole control frames re-sent
  // after a reconnect, and total replay-ring bytes re-sent. Owned here
  // (like the wire counters) so scrapes never race link teardown.
  std::atomic<int64_t> link_reconnects[kLinkPlanes]{};
  std::atomic<int64_t> frames_replayed{0};
  std::atomic<int64_t> replay_bytes{0};
  // per-lane execution pool (HVT_LANE_WORKERS): responses executed on
  // a pool worker instead of the engine thread (counter), and the
  // configured worker count (gauge, set at Init; 0 = pool off)
  std::atomic<int64_t> lane_pool_tasks{0};
  std::atomic<int64_t> lane_workers{0};
  // per-lane head-of-line wait (service-start delay): ns between a
  // submission landing in the client queue and the engine thread
  // picking it up to announce. Both ends are stamped on THIS rank, so
  // peers' submit skew and negotiation latency cannot leak in: a
  // single-thread engine executing a hot neighbor inline cannot drain
  // the queue, so that blocking lands here; with the lane pool the
  // engine thread stays free and the wait collapses to the
  // event-driven coalescing tick (≤ cycle_ms) + scheduler quanta.
  std::atomic<int64_t> lane_hol_ns[kLaneSlots]{};
  std::atomic<int64_t> lane_hol_count[kLaneSlots]{};
  // transport backend telemetry (stats slots 156-160): the resolved
  // HVT_LINK_BACKEND as an info gauge (0 = tcp, 1 = io_uring, set at
  // Init after Reset), the generic duplex pump's syscall tally
  // (poll+send/recv — the tcp side of syscalls-per-op), and the
  // io_uring ring counters (SQEs submitted, enter syscalls,
  // completions reaped) flushed per pump via the hub sinks
  std::atomic<int64_t> link_backend{0};
  std::atomic<int64_t> pump_syscalls{0};
  std::atomic<int64_t> uring_sqes{0};
  std::atomic<int64_t> uring_enters{0};
  std::atomic<int64_t> uring_cqes{0};
  LatencyHist cycle_hist;   // RunCycle wall time (includes the
                            // control-plane wait for peers)
  LatencyHist wakeup_hist;  // submit → engine-drain coalescing latency
                            // of the event-driven loop

  void Reset() {
    cycles = tensors_submitted = tensors_coordinated = 0;
    cache_hits = cache_misses = 0;
    fusion_bytes = responses_fused = stall_events = 0;
    for (int i = 0; i < kStatsOps; ++i) {
      exec_ns[i] = 0;
      exec_count[i] = 0;
      wire_tx_bytes[i] = 0;
      wire_tx_comp_bytes[i] = 0;
    }
    for (auto& a : aborts) a = 0;
    lanes_active = 0;
    for (int i = 0; i < kLaneSlots; ++i) {
      lane_depth[i] = 0;
      lane_exec_ns[i] = 0;
      lane_exec_count[i] = 0;
    }
    ctrl_tx_bytes = 0;
    ctrl_rx_bytes = 0;
    ctrl_peers = 0;
    ctrl_bypass_cycles = 0;
    for (auto& c : codec_tx_bytes) c = 0;
    ef_residual_bytes = 0;
    ef_residuals_dropped = 0;
    for (auto& l : link_reconnects) l = 0;
    frames_replayed = 0;
    replay_bytes = 0;
    lane_pool_tasks = 0;
    lane_workers = 0;
    for (auto& l : lane_hol_ns) l = 0;
    for (auto& l : lane_hol_count) l = 0;
    link_backend = 0;
    pump_syscalls = 0;
    uring_sqes = 0;
    uring_enters = 0;
    uring_cqes = 0;
    cycle_hist.Reset();
    wakeup_hist.Reset();
  }
};

struct HandleState {
  bool done = false;
  Status status;
  std::vector<uint8_t> output;
  std::vector<int64_t> recv_splits;
  int32_t join_result = -1;
};

// Diagnostics snapshot — refreshed by the engine thread once per cycle
// under diag_mu_, read by DiagnosticsJson() from any client thread
// (hvt_diagnostics → hvt.diagnostics() / GET /debugz). A snapshot
// rather than direct reads because pending_/counts_ are engine-thread-
// only state; the copy is a handful of small strings per cycle.
struct DiagNegotiation {
  std::string name;
  OpType op = OpType::ALLREDUCE;
  double waiting_sec = 0;
  std::vector<int> arrived;
  std::vector<int> missing;
};

struct DiagPending {
  std::string name;
  double age_sec = 0;
  int lane = 0;  // LaneSlot of the entry's process set (0 = global) —
                 // lets stall diagnosis on a serving gang name WHICH
                 // replica's lane is wedged
};

// Per-link health for hvt.diagnostics() / GET /debugz: a flapping link
// is visible (state, retry count, seconds-in-state) BEFORE it turns
// into an abort.
struct DiagLink {
  int peer = -1;
  int plane = 0;      // LinkPlane wire id (0 ctrl, 1 data)
  int state = 0;      // LinkState wire id
  int retries = 0;    // dial retries of the current/last episode
  int64_t epoch = 0;  // session epoch (one bump per successful heal)
  double in_state_sec = 0;
};

struct DiagState {
  bool valid = false;
  int64_t cycles = 0;
  int queue_depth = 0;           // undrained client submissions
  std::vector<DiagPending> pending;
  std::vector<DiagNegotiation> negotiations;  // rank 0 only
  std::vector<DiagLink> links;
  double stall_warn_sec = 60.0;
  double updated_sec = 0;
};

class Engine {
 public:
  static Engine& Get();

  // HVT_FAULT_INJECT (chaos harness) — parsed at Init for this rank.
  // KILL/DROP_CONN/DELAY_MS are the PR 4 hard faults (drop_conn marks
  // links DEAD — the permanent-loss baseline); FLAKY_CONN, PARTITION
  // and RESET_STORM are TRANSIENT: they cut sockets the self-healing
  // links are expected to reconnect through with zero aborts.
  enum class FaultKind {
    NONE, KILL, DROP_CONN, DELAY_MS, FLAKY_CONN, PARTITION, RESET_STORM
  };
  struct FaultSpec {
    FaultKind kind = FaultKind::NONE;
    int64_t after_ops = 0;
    int64_t arg = 0;        // delay_ms: MS; partition: ms=MS hold
    int64_t count = 0;      // flaky_conn: injections remaining
    int64_t every_ops = 0;  // reset_storm: period
    std::string hosts_a, hosts_b;  // partition: the two host groups
  };

  Status Init(int rank, int size, const std::string& master_addr,
              int master_port, int cycle_ms);
  void Shutdown();
  // per-lane execution pool introspection (tests)
  int lane_worker_count() const { return lane_workers_; }
  bool initialized() const { return initialized_.load(); }
  int rank() const { return rank_; }
  int size() const { return size_; }
  int local_rank() const { return topo_.my_local; }
  int local_size() const {
    return topo_.local_group.empty()
               ? 1
               : static_cast<int>(topo_.local_group.size());
  }
  const ParameterManager& autotune() const { return autotune_; }
  bool cache_enabled() const { return cache_enabled_.load(); }
  bool prefer_flat() const { return prefer_flat_.load(); }
  int64_t fusion_threshold() const { return fusion_threshold_; }
  int current_cycle_ms() const { return cycle_ms_; }
  // total data-plane collectives executed (one fused allreduce = one);
  // introspection for tests asserting fusion behavior
  int64_t data_ops() const { return data_ops_.load(); }
  const EngineStats& stats() const { return stats_; }
  // wire telemetry from the TCP data plane — reads the stats block, not
  // data_, so scrapes stay safe across Shutdown (0 for a bad op)
  int64_t wire_tx_bytes(int op) const {
    return (op >= 0 && op < kStatsOps)
               ? stats_.wire_tx_bytes[op].load(std::memory_order_relaxed)
               : 0;
  }
  int64_t wire_tx_comp_bytes(int op) const {
    return (op >= 0 && op < kStatsOps)
               ? stats_.wire_tx_comp_bytes[op].load(
                     std::memory_order_relaxed)
               : 0;
  }
  // current wire-codec pair packed as intra | inter << 8 (WireCodec
  // ids), bit 16 set while HVT_WIRE_COMPRESSION=auto is active. Rank
  // 0's values govern the gang — workers follow the per-response
  // stamps; under auto the packed ids are rank 0's latest picks.
  int wire_mode() const {
    return static_cast<int>(wire_cur_intra_.load(std::memory_order_relaxed)) |
           (static_cast<int>(wire_cur_inter_.load(std::memory_order_relaxed))
            << 8) |
           (wire_auto_ ? 1 << 16 : 0);
  }
  EventRing& events() { return events_; }
  // JSON stall/queue snapshot for hvt_diagnostics (thread-safe).
  std::string DiagnosticsJson() EXCLUDES(diag_mu_, broken_mu_);

  // getsockopt probe over the live link registry — pins socket-option
  // continuity across heals (every accept/dial path must re-apply
  // TCP_NODELAY + HVT_SOCK_BUF; tests/test_transport_backends.py).
  // Fills out3 = {TCP_NODELAY, SO_SNDBUF, SO_RCVBUF} for the
  // registered link on `plane` (LinkPlane id) to `peer`; returns 0,
  // or -1 when no registered link matches / its socket is down. The
  // registry itself is stable between Init and Shutdown (links
  // register in their ctors), so walking it from a client thread is
  // safe while the engine is up.
  int LinkSockoptProbe(int plane, int peer, long long out3[3]);

  // Sticky broken state (coordinated abort landed). Submits fail fast
  // and waits return errors until Shutdown() + a fresh Init().
  bool broken() const { return broken_.load(); }
  // "<cause>: <reason>" (empty when healthy); thread-safe.
  std::string BrokenInfo() EXCLUDES(broken_mu_);

  // Returns handle (>=0) or -1 when not initialized.
  int32_t Submit(EntryPtr entry) EXCLUDES(queue_mu_, handles_mu_);

  bool Poll(int32_t handle) EXCLUDES(handles_mu_);
  // Blocks; returns snapshot of the handle state.
  HandleState Wait(int32_t handle) EXCLUDES(handles_mu_);
  // Bounded wait: false when the handle is still pending after
  // timeout_ms (out untouched), true with the snapshot otherwise.
  bool WaitFor(int32_t handle, int64_t timeout_ms, HandleState& out)
      EXCLUDES(handles_mu_);
  void Release(int32_t handle) EXCLUDES(handles_mu_);

 private:
  Engine() = default;
  void ThreadLoop();
  // false → exit loop. Sets progressed when the cycle drained a
  // submission or executed a response, and outstanding when
  // negotiations remain open — the event-driven loop runs back-to-back
  // cycles while progressing (and, within a grace window, while
  // outstanding).
  bool RunCycle(bool& progressed, bool& outstanding);
  void ExecuteResponse(const Response& resp,
                       std::map<std::string, EntryPtr>& pending)
      EXCLUDES(handles_mu_);

  // ------------------------------------------------------------------
  // per-lane execution pool (HVT_LANE_WORKERS)
  // ------------------------------------------------------------------
  // In-rank blast-radius containment for multi-tenant serving: the
  // engine thread keeps sole ownership of negotiation, caches and the
  // pending table, but eligible TENSOR allreduces on process-SET lanes
  // are handed to a small worker pool so a hot or degraded lane's data
  // plane time no longer head-of-line-blocks its neighbors on the same
  // rank. Tasks hash to per-worker FIFO queues by LaneId (same lane →
  // same worker → program order); a task whose member set shares TWO
  // OR MORE ranks with any task queued/active on another worker (i.e.
  // shares a socket pair) waits at dispatch — response order is
  // identical gang-wide, so every rank serializes conflicting lanes
  // the same way. Everything else (global lane, shm/hierarchical
  // backends, Adasum, EF-compensated or tuner-observed responses)
  // takes LaneBarrier() and runs inline, preserving the single-thread
  // semantics exactly; HVT_LANE_WORKERS=0 keeps the engine
  // bit-identical to the pre-pool build.
  struct LaneTask {
    Response resp;
    std::vector<EntryPtr> entries;  // aligned with resp.names
    uint64_t seq = 0;               // resp_seq_ at dispatch
    std::vector<uint8_t> buf;       // task-local fusion scratch
  };
  void StartLanePool();
  void StopLanePool() EXCLUDES(pool_mu_);
  void LaneWorkerLoop(int wi) EXCLUDES(pool_mu_, handles_mu_);
  // Conflict-checked enqueue (engine thread): blocks until no other
  // worker holds a task sharing ≥2 member ranks with `t`.
  void DispatchLaneTask(std::shared_ptr<LaneTask> t)
      EXCLUDES(pool_mu_);
  // Wait until every queue is empty and every worker idle; then
  // surface any worker error (rethrown with its abort class).
  void LaneBarrier() EXCLUDES(pool_mu_);
  void RethrowLanePoolError() EXCLUDES(pool_mu_);
  // True when `resp` may run on a pool worker on this rank (member,
  // set-lane, ring-backend TENSOR allreduce outside the EF/auto-codec
  // paths).
  bool LanePoolEligible(const Response& resp,
                        const std::vector<int>& grp, bool mine);
  // Execute one dispatched task on a worker thread: flight-recorder
  // EXEC span, fused-allreduce body, per-op/per-lane stats.
  void RunLaneTask(LaneTask& t) EXCLUDES(handles_mu_);
  // The fused-allreduce execution body shared by the inline path and
  // the pool (pack → prescale → [EF, inline only] → backend → unpack →
  // complete). `scratch` is the fusion buffer to use when the response
  // cannot run in place.
  void ExecFusedAllreduce(const Response& resp,
                          std::vector<EntryPtr>& entries, uint64_t seq,
                          std::vector<uint8_t>& scratch, bool apply_ef)
      EXCLUDES(handles_mu_);
  void CompleteEntry(const EntryPtr& e, const Status& s)
      EXCLUDES(handles_mu_);
  void FailAll(const std::string& why)
      EXCLUDES(queue_mu_, handles_mu_);
  // Coordinated abort: sticky broken flag, ABORT fan-out to connected
  // peers, data-plane teardown, error-complete every pending and
  // in-flight entry. Engine-thread only; idempotent.
  void EnterBroken(int cause, const std::string& why)
      EXCLUDES(broken_mu_, queue_mu_, handles_mu_);
  // HVT_FAULT_INJECT hook, called once per data-plane response.
  void MaybeInjectFault();
  // Transiently cut every link whose peer is `r` (chaos helper: the
  // links stay HEALTHY and reconnect on their next use).
  void CutLinksToRank(int r);
  // Control-plane recv deadline: HVT_HEARTBEAT_MS when this side is
  // idle (frames are then pure keepalives), HVT_OP_TIMEOUT_MS when
  // work is outstanding.
  int64_t ControlTimeoutMs(bool idle) const;

  // coordinator (rank 0) state + logic
  struct TensorCount {
    std::vector<Request> requests;  // one per reporting rank
    std::vector<bool> seen;
    double first_seen_sec = 0;
    int count = 0;
  };
  std::vector<Response> Coordinate(const std::vector<Announce>& anns);
  Response BuildResponse(const std::vector<Request>& reqs);
  // Hierarchical control plane (HVT_CTRL_TOPOLOGY=tree): derive roles
  // from the rendezvous topology and build the leader/member links —
  // leaders listen, members dial, ports travel over the existing star.
  void SetupTreeControl(const std::vector<std::string>& endpoints,
                        const std::vector<std::string>& topo_hosts);
  // Decode a rank-0→worker response frame (full or positions form)
  // into responses + evictions + resp_flags, applying the synchronized
  // cycle/cache/backend parameters — shared by star workers, tree
  // members, and tree leaders.
  void DecodeResponseFrame(const std::vector<uint8_t>& frame,
                           std::vector<Response>& responses,
                           std::vector<int64_t>& evictions,
                           uint8_t& resp_flags);
  // Steady-state bypass: rebuild the coordinator's response list from
  // broadcast cache positions (caches are identical on every rank) and
  // re-apply fusion + the wire-codec stamps deterministically (the
  // frame carries rank 0's {intra, inter} pair — PR 8's synced-codec
  // slot, grown to two ids).
  std::vector<Response> ResponsesFromPositions(
      const std::vector<int64_t>& positions, uint8_t wire_intra,
      uint8_t wire_inter);
  // Stamp a uniform codec pair on every eligible response (workers
  // rebuilding a positions-form frame; rank 0 in fixed modes).
  static void StampWireCodec(std::vector<Response>& responses,
                             uint8_t wire_intra, uint8_t wire_inter);
  // Rank-0 stamping: fixed modes stamp the configured pair; auto mode
  // asks the CodecTuner per response. Records the stamped pair for the
  // bypass frame and whether every eligible response got ONE uniform
  // pair (the extra bypass eligibility condition under auto).
  void StampWireCodecs(std::vector<Response>& responses);
  // True when this response's payload is codec-eligible (fp32
  // non-Adasum TENSOR allreduce) — the single stamp/EF/tuner gate.
  static bool WireEligible(const Response& r);
  // The codec that will actually touch this response's payload given
  // the backend the engine picked — RAW for shm, the inter codec for
  // hierarchical (its lossy phase), the link-resolved codec for rings.
  // What the error-feedback pass must compensate.
  WireCodec EffectiveWire(const CollectiveBackend* be, const Response& resp,
                          const std::vector<int>& grp) const;
  // Error-feedback residual for (name, lane): zero-filled on first use,
  // LRU-bounded by HVT_EF_MAX_BYTES (nullptr when it cannot be
  // admitted; the drop is counted). Engine-thread only.
  float* EfResidual(const std::string& name, uint64_t lane, int64_t n);
  // lane-scoped negotiation key: tensor name + the process-set member
  // list (bare name for the global set) — the single spelling shared by
  // the request loop and the cache-hit fold so the two can never diverge
  static std::string NegotiationKey(const std::string& name,
                                    const std::vector<int64_t>& members);
  // cache bookkeeping for a cacheable response this rank does NOT
  // participate in: positions are assigned in response order on every
  // rank, so non-members must insert too or the position↔name maps
  // would diverge and the eviction broadcast would evict the wrong names
  void CacheResponseAllRanks(const Response& resp);
  bool CacheableResponse(const Response& resp) const;
  // refresh the per-lane pending-depth gauges (engine thread, per cycle)
  void UpdateLaneDepths();
  void FuseResponses(std::vector<Response>& responses);
  void CheckStalls();
  void UpdateDiag() EXCLUDES(diag_mu_, queue_mu_);
  void HitToArrival(int rank, int64_t pos, double now_sec);
  bool RegisterArrival(const std::string& key, int rank, Request q,
                       double now_sec);

  // first backend whose Enabled() accepts the response (never null —
  // the ring fallback accepts everything)
  CollectiveBackend* PickBackend(const Response& resp, int64_t total_elems);

  // control plane — self-healing links (transport.h). Dial roles match
  // the original rendezvous: workers/members dial, rank 0 / leaders
  // keep their listeners open for reconnect re-accepts.
  LinkPtr control_;              // workers: link to rank 0
  std::vector<LinkPtr> workers_; // rank 0: links from workers
  // hierarchical control plane (HVT_CTRL_TOPOLOGY=tree)
  bool tree_mode_ = false;
  bool ctrl_bypass_ = true;      // HVT_CTRL_BYPASS (0 → always full
                                 // frames; parity/debug baseline)
  CtrlRole ctrl_role_ = CtrlRole::ROOT;
  std::vector<int> ctrl_children_;         // root: leaders; leader: members
  std::map<int, LinkPtr> tree_child_socks_;  // leader: member links
  LinkPtr tree_parent_;                    // member: link to leader
  std::unique_ptr<DataPlane> data_;
  Listener data_listener_;
  Listener control_listener_;    // rank 0: stays open for ctrl re-accepts
  Listener tree_listener_;       // tree leaders: member re-accepts
  ReconnectHub hub_;             // shared reconnect state + link registry
  // ordered backend list (reference operations.cc:142-249); built at Init
  std::vector<std::unique_ptr<CollectiveBackend>> backends_;
  // global TENSOR-response counter (identical stream on every rank);
  // feeds CollectiveBackend::BeginResponse
  uint64_t resp_seq_ = 0;
  Topology topo_;

  int rank_ = 0, size_ = 1;
  // atomic: mutated by the engine thread, read by the introspection API
  // (hvt_autotune_state) from client threads
  std::atomic<int> cycle_ms_{2};
  // autotuned flags, applied at the response-frame boundary on EVERY rank
  // (cache lookups and backend picks must never diverge across ranks);
  // tuned_* hold rank 0's pending values until the next frame carries them
  std::atomic<bool> cache_enabled_{true};
  std::atomic<bool> prefer_flat_{false};
  bool tuned_cache_enabled_ = true;
  bool tuned_prefer_flat_ = false;
  std::atomic<bool> initialized_{false};
  std::atomic<bool> shutdown_requested_{false};
  std::atomic<bool> fatal_{false};
  // sticky containment state (EnterBroken): set with fatal_, but also
  // carries the cause/reason for hvt_engine_broken / diagnostics
  std::atomic<bool> broken_{false};
  Mutex broken_mu_ ACQUIRED_BEFORE(handles_mu_);
  std::string broken_reason_ GUARDED_BY(broken_mu_);
  int broken_cause_ GUARDED_BY(broken_mu_) = kAbortInternal;
  int64_t heartbeat_ms_ = 30000;  // HVT_HEARTBEAT_MS (0 → off)
  // HVT_FAULT_INJECT: parsed at Init when the rank matches; checked
  // once per data-plane response
  FaultSpec fault_;
  std::thread thread_;

  // FailAll completes drained entries while still holding queue_mu_
  // (CompleteEntry then takes handles_mu_) — hence the declared order.
  Mutex queue_mu_ ACQUIRED_BEFORE(handles_mu_);
  // Signaled by Submit (and Shutdown): the event-driven cycle loop
  // wakes immediately instead of finishing a cycle_ms sleep, so
  // cycle_ms is the MAX coalescing wait, not a latency floor.
  // Waits go through CvLock::native() — the std::unique_lock over the
  // annotated Mutex's underlying std::mutex.
  std::condition_variable queue_cv_;
  std::deque<EntryPtr> submitted_ GUARDED_BY(queue_mu_);
  bool event_driven_ = true;  // HVT_EVENT_DRIVEN (0 → legacy sleep loop)
  // HVT_WIRE_COMPRESSION parse (see docs/performance.md): a single
  // codec name applies to both link classes; "<intra>,<inter>" splits
  // them; "auto" (inter only) hands the choice to the CodecTuner.
  uint8_t wire_intra_ = 0;    // configured intra-host codec id
  uint8_t wire_inter_ = 0;    // configured inter-host codec id (fixed modes)
  bool wire_auto_ = false;    // inter codec chosen by codec_tuner_
  // current resolved pair for introspection (== configured unless auto,
  // where the engine thread refreshes it as the tuner explores/locks)
  std::atomic<uint8_t> wire_cur_intra_{0};
  std::atomic<uint8_t> wire_cur_inter_{0};
  // the uniform pair stamped this cycle + whether it WAS uniform — the
  // bypass frame broadcasts it (auto can stamp per-response pairs, and
  // a non-uniform cycle must fall back to full response frames)
  uint8_t stamped_intra_ = 0;
  uint8_t stamped_inter_ = 0;
  bool stamp_uniform_ = true;
  CodecTuner codec_tuner_;    // rank-0 auto-mode codec selection

  // error feedback (engine-thread only): per-(tensor, lane) fp32
  // residuals so repeated lossy quantization doesn't bias training.
  // Bounded by HVT_EF_MAX_BYTES with LRU eviction; cleared on
  // shutdown/re-init.
  struct EfBuf {
    std::vector<float> v;
    uint64_t tick = 0;
  };
  std::map<std::string, EfBuf> ef_bufs_;
  int64_t ef_bytes_ = 0;
  uint64_t ef_tick_ = 0;
  int64_t ef_max_bytes_ = 64 << 20;  // HVT_EF_MAX_BYTES
  bool ef_enabled_ = true;           // HVT_ERROR_FEEDBACK

  Mutex handles_mu_;
  std::condition_variable handles_cv_;
  std::unordered_map<int32_t, HandleState> handles_
      GUARDED_BY(handles_mu_);
  int32_t next_handle_ GUARDED_BY(handles_mu_) = 0;
  // Entries taken out of pending_ for the response being executed RIGHT
  // NOW. If execution throws mid-collective, FailAll error-completes
  // these too — without this, their handles would never complete and
  // Engine::Wait would hang forever on an aborted gang
  // (CompleteEntry removes; ExecuteResponse adds).
  std::vector<EntryPtr> inflight_ GUARDED_BY(handles_mu_);

  // engine-thread-only state
  std::map<std::string, EntryPtr> pending_;  // ordered for determinism
  std::set<std::string> announced_;  // names already sent to coordinator
  std::set<uint64_t> lanes_seen_;    // distinct lanes since init
  ResponseCache cache_{1024};
  bool join_pending_ = false;
  EntryPtr join_entry_;

  // rank-0-only state
  std::map<std::string, TensorCount> counts_;
  // Group table (reference group_table.h): members of a fusion group are
  // held after negotiation until EVERY member is globally ready, then
  // released adjacently (name-sorted) so FuseResponses merges them into
  // one collective. A member error poisons the whole group.
  struct GroupState {
    int expected = 0;
    int released = 0;
    bool poisoned = false;
    std::string error;
    std::map<std::string, Response> held;  // name-sorted → deterministic
  };
  std::map<int32_t, GroupState> groups_;
  bool disable_group_fusion_ = false;  // HVT_DISABLE_GROUP_FUSION
  std::vector<bool> rank_joined_;
  std::vector<bool> rank_shutdown_;
  std::vector<std::set<int64_t>> hit_pending_;  // per rank, cache positions
  std::vector<int64_t> pending_evictions_;
  // steady-state bypass bookkeeping (filled by Coordinate): the cache
  // positions emitted by the all-members-hit fast path this cycle, and
  // whether they were the ONLY responses — the eligibility condition
  // for broadcasting positions instead of full responses
  std::vector<int64_t> fastpath_positions_;
  bool coordinate_pure_fastpath_ = false;
  int last_join_rank_ = -1;
  std::atomic<int64_t> fusion_threshold_{64 << 20};  // see cycle_ms_ note
  double stall_warn_sec_ = 60.0;
  std::map<std::string, bool> stall_warned_;
  ParameterManager autotune_;     // rank 0 tunes; workers receive cycle_ms
  int64_t cycle_bytes_ = 0;       // payload bytes executed this cycle
  std::atomic<int64_t> data_ops_{0};
  EngineStats stats_;             // live telemetry (hvt_engine_stats)
  EngineTimeline timeline_;       // rank-0 chrome trace (HVT_TIMELINE)
  EventRing events_;              // flight recorder (hvt_events_drain)
  Mutex diag_mu_;
  DiagState diag_ GUARDED_BY(diag_mu_);  // see DiagState docs above

  // fusion scratch, one buffer per lane: a replica set's small serving
  // payloads never force a resize of the global lane's (large) training
  // buffer and vice versa — each lane's buffer converges to its own
  // working-set size
  std::map<uint64_t, std::vector<uint8_t>> fusion_buffers_;

  // per-lane execution pool (see the LaneTask block above). pool_mu_
  // is leaf-level: never held while taking queue_mu_/handles_mu_.
  int lane_workers_ = 0;  // HVT_LANE_WORKERS (0 = pool off)
  std::vector<std::thread> lane_threads_;
  Mutex pool_mu_;
  std::condition_variable pool_cv_;       // workers: task available
  std::condition_variable pool_done_cv_;  // dispatcher: drain/conflict
  std::vector<std::deque<std::shared_ptr<LaneTask>>> lane_queues_
      GUARDED_BY(pool_mu_);
  std::vector<std::shared_ptr<LaneTask>> lane_active_
      GUARDED_BY(pool_mu_);  // one slot per worker (null = idle)
  // sticky lane → worker assignment (least-busy on first sight; see
  // DispatchLaneTask) — a blind LaneId hash can deterministically
  // co-locate a hot lane with an idle neighbor on one worker FIFO
  std::map<uint64_t, int> lane_worker_of_ GUARDED_BY(pool_mu_);
  bool pool_stop_ GUARDED_BY(pool_mu_) = false;
  std::string pool_error_ GUARDED_BY(pool_mu_);
  int pool_error_cause_ GUARDED_BY(pool_mu_) = -1;
};

}  // namespace hvt
