// io_uring data-plane backend — see uring_link.h for the design notes.
//
// Raw-syscall io_uring (no liburing): ring setup/teardown, SQE prep,
// batched submit with a spin-then-block completion wait, a registered
// provided-buffer ring for multishot recv, and the PumpDuplex override
// that moves a full-duplex ring step through one ring instead of
// poll+send+recv per chunk. Constants newer than the toolchain's
// <linux/io_uring.h> are shimmed below under #ifndef so the same
// source builds against old headers and probes the running kernel for
// what it actually has.

#include "uring_link.h"

#include <linux/io_uring.h>
#include <linux/time_types.h>
#include <sys/mman.h>
#include <sys/syscall.h>
#include <unistd.h>

#include <chrono>
#include <cstring>
#include <memory>
#include <thread>

#include "common.h"

// ---- shims for pre-5.19 toolchain headers (kernel support is probed
// at runtime; these only name the ABI) --------------------------------------
#ifndef __NR_io_uring_setup
#define __NR_io_uring_setup 425
#endif
#ifndef __NR_io_uring_enter
#define __NR_io_uring_enter 426
#endif
#ifndef __NR_io_uring_register
#define __NR_io_uring_register 427
#endif
#ifndef IORING_FEAT_EXT_ARG
#define IORING_FEAT_EXT_ARG (1U << 8)
#endif
#ifndef IORING_ENTER_EXT_ARG
#define IORING_ENTER_EXT_ARG (1U << 3)
#endif
#ifndef IORING_RECV_MULTISHOT
#define IORING_RECV_MULTISHOT (1U << 1)  // sqe->ioprio flag
#endif
#ifndef IORING_CQE_F_BUFFER
#define IORING_CQE_F_BUFFER (1U << 0)
#endif
#ifndef IORING_CQE_F_MORE
#define IORING_CQE_F_MORE (1U << 1)
#endif
#ifndef IORING_CQE_BUFFER_SHIFT
#define IORING_CQE_BUFFER_SHIFT 16
#endif
#ifndef IORING_REGISTER_PBUF_RING
#define IORING_REGISTER_PBUF_RING 22
#endif
#ifndef IORING_UNREGISTER_PBUF_RING
#define IORING_UNREGISTER_PBUF_RING 23
#endif
// IORING_OP_SEND_ZC's opcode number doubles as the capability
// heuristic: a kernel whose probe knows it (6.0+) has multishot recv
// and provided-buffer rings (5.19+); the pbuf registration is still
// verified by doing it.
#ifndef IORING_OP_SEND_ZC
#define IORING_OP_SEND_ZC 47
#endif

namespace hvt {
namespace {

inline int64_t NowUs() {
  return std::chrono::duration_cast<std::chrono::microseconds>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

int UringSetupSys(unsigned entries, io_uring_params* p) {
  return static_cast<int>(syscall(__NR_io_uring_setup, entries, p));
}
int UringEnterSys(int fd, unsigned to_submit, unsigned min_complete,
                  unsigned flags, const void* arg, size_t argsz) {
  return static_cast<int>(syscall(__NR_io_uring_enter, fd, to_submit,
                                  min_complete, flags, arg, argsz));
}
int UringRegisterSys(int fd, unsigned opcode, void* arg, unsigned nr) {
  return static_cast<int>(syscall(__NR_io_uring_register, fd, opcode,
                                  arg, nr));
}

// Local mirrors of the 5.19 provided-buffer-ring ABI (absent from old
// headers; layout is fixed kernel ABI). The ring is an array of
// 16-byte entries whose entry 0 overlays the header — its last __u16
// is the producer tail.
struct HvtUringBuf {
  __u64 addr;
  __u32 len;
  __u16 bid;
  __u16 resv;
};
struct HvtUringBufReg {
  __u64 ring_addr;
  __u32 ring_entries;
  __u16 bgid;
  __u16 pad;
  __u64 resv[3];
};

constexpr unsigned kPbufCount = 32;        // power of two (ring ABI)
constexpr size_t kPbufBytes = 64 << 10;    // per-buffer; 2 MiB arena
constexpr unsigned kPbufGroup = 0;

// One ring per executing thread (engine thread + each lane worker),
// created lazily on the first PumpDuplex that thread runs and torn
// down when the thread exits. All state is thread-confined.
struct Ring {
  int fd = -1;
  unsigned sq_entries = 0, cq_entries = 0;
  unsigned sq_mask = 0, cq_mask = 0;
  unsigned* sq_head = nullptr;
  unsigned* sq_tail = nullptr;
  unsigned* sq_array = nullptr;
  io_uring_sqe* sqes = nullptr;
  unsigned* cq_head = nullptr;
  unsigned* cq_tail = nullptr;
  io_uring_cqe* cq_cqes = nullptr;
  void* sq_ring_ptr = nullptr;
  size_t sq_ring_sz = 0;
  void* cq_ring_ptr = nullptr;  // == sq_ring_ptr under FEAT_SINGLE_MMAP
  size_t cq_ring_sz = 0;
  void* sqe_ptr = nullptr;
  size_t sqe_sz = 0;
  unsigned to_submit = 0;   // queued SQEs not yet handed to the kernel
  uint64_t next_ud = 1;     // user_data tags (ring empty between pumps)
  bool mshot_ok = false;    // kernel has multishot recv + pbuf rings
  // provided-buffer pool (multishot recv lands here, copied out to the
  // caller; recycled immediately after each completion)
  HvtUringBuf* bufring = nullptr;
  size_t bufring_sz = 0;
  uint8_t* arena = nullptr;
  size_t arena_sz = 0;
  unsigned pbuf_tail = 0;  // local producer cursor (mirrored to shared)
  bool pbuf_ok = false;
  // telemetry accumulators, flushed into the hub sinks per pump
  int64_t sqes_n = 0, enters_n = 0, cqes_n = 0;
};

void RingDestroy(Ring& r) {
  if (r.fd >= 0 && r.pbuf_ok) {
    HvtUringBufReg reg{};
    reg.bgid = kPbufGroup;
    UringRegisterSys(r.fd, IORING_UNREGISTER_PBUF_RING, &reg, 1);
  }
  if (r.bufring) munmap(r.bufring, r.bufring_sz);
  if (r.arena) munmap(r.arena, r.arena_sz);
  if (r.sqe_ptr) munmap(r.sqe_ptr, r.sqe_sz);
  if (r.cq_ring_ptr && r.cq_ring_ptr != r.sq_ring_ptr)
    munmap(r.cq_ring_ptr, r.cq_ring_sz);
  if (r.sq_ring_ptr) munmap(r.sq_ring_ptr, r.sq_ring_sz);
  if (r.fd >= 0) ::close(r.fd);
  r = Ring{};
  r.fd = -1;
}

// Recycle/provide buffer `bid` to the kernel pool.
void PbufAdd(Ring& r, unsigned bid) {
  HvtUringBuf* e = &r.bufring[r.pbuf_tail & (kPbufCount - 1)];
  e->addr = reinterpret_cast<uint64_t>(r.arena + bid * kPbufBytes);
  e->len = kPbufBytes;
  e->bid = static_cast<uint16_t>(bid);
  ++r.pbuf_tail;
  // entry 0's resv overlays the shared tail word (ring ABI)
  __atomic_store_n(&r.bufring[0].resv,
                   static_cast<uint16_t>(r.pbuf_tail), __ATOMIC_RELEASE);
}

bool RingInitPbuf(Ring& r) {
  r.bufring_sz = kPbufCount * sizeof(HvtUringBuf);
  r.arena_sz = kPbufCount * kPbufBytes;
  void* ringp = mmap(nullptr, r.bufring_sz, PROT_READ | PROT_WRITE,
                     MAP_PRIVATE | MAP_ANONYMOUS, -1, 0);
  if (ringp == MAP_FAILED) return false;
  void* arenap = mmap(nullptr, r.arena_sz, PROT_READ | PROT_WRITE,
                      MAP_PRIVATE | MAP_ANONYMOUS, -1, 0);
  if (arenap == MAP_FAILED) {
    munmap(ringp, r.bufring_sz);
    return false;
  }
  r.bufring = static_cast<HvtUringBuf*>(ringp);
  r.arena = static_cast<uint8_t*>(arenap);
  memset(r.bufring, 0, r.bufring_sz);
  HvtUringBufReg reg{};
  reg.ring_addr = reinterpret_cast<uint64_t>(r.bufring);
  reg.ring_entries = kPbufCount;
  reg.bgid = kPbufGroup;
  if (UringRegisterSys(r.fd, IORING_REGISTER_PBUF_RING, &reg, 1) < 0) {
    munmap(r.bufring, r.bufring_sz);
    munmap(r.arena, r.arena_sz);
    r.bufring = nullptr;
    r.arena = nullptr;
    return false;
  }
  for (unsigned i = 0; i < kPbufCount; ++i) PbufAdd(r, i);
  return true;
}

bool RingInit(Ring& r, unsigned entries) {
  io_uring_params p;
  memset(&p, 0, sizeof(p));
  int fd = UringSetupSys(entries, &p);
  if (fd < 0) return false;
  r.fd = fd;
  // the pump depends on the timed EXT_ARG wait (no TIMEOUT SQE path)
  if (!(p.features & IORING_FEAT_EXT_ARG)) {
    RingDestroy(r);
    return false;
  }
  r.sq_entries = p.sq_entries;
  r.cq_entries = p.cq_entries;
  r.sq_ring_sz = p.sq_off.array + p.sq_entries * sizeof(unsigned);
  r.cq_ring_sz = p.cq_off.cqes + p.cq_entries * sizeof(io_uring_cqe);
  if (p.features & IORING_FEAT_SINGLE_MMAP) {
    r.sq_ring_sz = r.cq_ring_sz = std::max(r.sq_ring_sz, r.cq_ring_sz);
  }
  r.sq_ring_ptr = mmap(nullptr, r.sq_ring_sz, PROT_READ | PROT_WRITE,
                       MAP_SHARED | MAP_POPULATE, fd, IORING_OFF_SQ_RING);
  if (r.sq_ring_ptr == MAP_FAILED) {
    r.sq_ring_ptr = nullptr;
    RingDestroy(r);
    return false;
  }
  if (p.features & IORING_FEAT_SINGLE_MMAP) {
    r.cq_ring_ptr = r.sq_ring_ptr;
  } else {
    r.cq_ring_ptr =
        mmap(nullptr, r.cq_ring_sz, PROT_READ | PROT_WRITE,
             MAP_SHARED | MAP_POPULATE, fd, IORING_OFF_CQ_RING);
    if (r.cq_ring_ptr == MAP_FAILED) {
      r.cq_ring_ptr = nullptr;
      RingDestroy(r);
      return false;
    }
  }
  r.sqe_sz = p.sq_entries * sizeof(io_uring_sqe);
  r.sqe_ptr = mmap(nullptr, r.sqe_sz, PROT_READ | PROT_WRITE,
                   MAP_SHARED | MAP_POPULATE, fd, IORING_OFF_SQES);
  if (r.sqe_ptr == MAP_FAILED) {
    r.sqe_ptr = nullptr;
    RingDestroy(r);
    return false;
  }
  auto* sqb = static_cast<uint8_t*>(r.sq_ring_ptr);
  r.sq_head = reinterpret_cast<unsigned*>(sqb + p.sq_off.head);
  r.sq_tail = reinterpret_cast<unsigned*>(sqb + p.sq_off.tail);
  r.sq_mask = *reinterpret_cast<unsigned*>(sqb + p.sq_off.ring_mask);
  r.sq_array = reinterpret_cast<unsigned*>(sqb + p.sq_off.array);
  r.sqes = static_cast<io_uring_sqe*>(r.sqe_ptr);
  auto* cqb = static_cast<uint8_t*>(r.cq_ring_ptr);
  r.cq_head = reinterpret_cast<unsigned*>(cqb + p.cq_off.head);
  r.cq_tail = reinterpret_cast<unsigned*>(cqb + p.cq_off.tail);
  r.cq_mask = *reinterpret_cast<unsigned*>(cqb + p.cq_off.ring_mask);
  r.cq_cqes = reinterpret_cast<io_uring_cqe*>(cqb + p.cq_off.cqes);

  // opcode probe: everything the pump submits must be supported
  const unsigned nprobe = 64;
  std::vector<uint8_t> pb(sizeof(io_uring_probe) +
                              nprobe * sizeof(io_uring_probe_op),
                          0);
  auto* probe = reinterpret_cast<io_uring_probe*>(pb.data());
  if (UringRegisterSys(fd, IORING_REGISTER_PROBE, probe, nprobe) < 0) {
    RingDestroy(r);
    return false;
  }
  auto op_ok = [&](unsigned op) {
    return op <= probe->last_op &&
           (probe->ops[op].flags & IO_URING_OP_SUPPORTED);
  };
  if (!op_ok(IORING_OP_SEND) || !op_ok(IORING_OP_RECV) ||
      !op_ok(IORING_OP_ASYNC_CANCEL)) {
    RingDestroy(r);
    return false;
  }
  // multishot recv + pbuf rings landed in 5.19; a kernel that knows
  // IORING_OP_SEND_ZC (6.0) definitely has both — then prove the pbuf
  // registration by doing it (falls back to single-shot recv if not)
  r.mshot_ok = op_ok(IORING_OP_SEND_ZC);
  r.pbuf_ok = r.mshot_ok && RingInitPbuf(r);
  return true;
}

// SQE prep: fill the slot, then release the tail so the next enter
// picks it up. false = SQ full (caller submits first and retries).
io_uring_sqe* NextSqe(Ring& r) {
  unsigned tail = *r.sq_tail;
  unsigned head = __atomic_load_n(r.sq_head, __ATOMIC_ACQUIRE);
  if (tail - head >= r.sq_entries) return nullptr;
  io_uring_sqe* sqe = &r.sqes[tail & r.sq_mask];
  memset(sqe, 0, sizeof(*sqe));
  r.sq_array[tail & r.sq_mask] = tail & r.sq_mask;
  return sqe;
}
void CommitSqe(Ring& r) {
  __atomic_store_n(r.sq_tail, *r.sq_tail + 1, __ATOMIC_RELEASE);
  ++r.to_submit;
  ++r.sqes_n;
}

bool PrepSend(Ring& r, int fd, const void* buf, size_t len,
              uint64_t ud) {
  io_uring_sqe* sqe = NextSqe(r);
  if (!sqe) return false;
  sqe->opcode = IORING_OP_SEND;
  sqe->fd = fd;
  sqe->addr = reinterpret_cast<uint64_t>(buf);
  sqe->len = static_cast<uint32_t>(
      std::min<size_t>(len, 1u << 30));
  sqe->msg_flags = MSG_NOSIGNAL;
  sqe->user_data = ud;
  CommitSqe(r);
  return true;
}
bool PrepRecv(Ring& r, int fd, void* buf, size_t len, uint64_t ud) {
  io_uring_sqe* sqe = NextSqe(r);
  if (!sqe) return false;
  sqe->opcode = IORING_OP_RECV;
  sqe->fd = fd;
  sqe->addr = reinterpret_cast<uint64_t>(buf);
  sqe->len = static_cast<uint32_t>(
      std::min<size_t>(len, 1u << 30));
  sqe->user_data = ud;
  CommitSqe(r);
  return true;
}
bool PrepRecvMultishot(Ring& r, int fd, uint64_t ud) {
  io_uring_sqe* sqe = NextSqe(r);
  if (!sqe) return false;
  sqe->opcode = IORING_OP_RECV;
  sqe->fd = fd;
  sqe->ioprio = IORING_RECV_MULTISHOT;
  sqe->flags = IOSQE_BUFFER_SELECT;
  sqe->buf_group = kPbufGroup;
  sqe->user_data = ud;
  CommitSqe(r);
  return true;
}
bool PrepCancel(Ring& r, uint64_t target_ud, uint64_t ud) {
  io_uring_sqe* sqe = NextSqe(r);
  if (!sqe) return false;
  sqe->opcode = IORING_OP_ASYNC_CANCEL;
  sqe->fd = -1;
  sqe->addr = target_ud;
  sqe->user_data = ud;
  CommitSqe(r);
  return true;
}

bool PeekCqe(Ring& r, io_uring_cqe* out) {
  unsigned head = *r.cq_head;
  unsigned tail = __atomic_load_n(r.cq_tail, __ATOMIC_ACQUIRE);
  if (head == tail) return false;
  *out = r.cq_cqes[head & r.cq_mask];
  __atomic_store_n(r.cq_head, head + 1, __ATOMIC_RELEASE);
  ++r.cqes_n;
  return true;
}

// Submit queued SQEs and/or flush completions. min_complete > 0 blocks
// up to wait_ms for a completion (timed EXT_ARG wait). Returns false
// only on a non-retryable enter failure (ring unusable).
bool Enter(Ring& r, unsigned min_complete, int wait_ms) {
  while (true) {
    unsigned flags = IORING_ENTER_GETEVENTS;
    io_uring_getevents_arg arg;
    __kernel_timespec ts;
    const void* argp = nullptr;
    size_t argsz = 0;
    if (min_complete > 0 && wait_ms >= 0) {
      memset(&arg, 0, sizeof(arg));
      ts.tv_sec = wait_ms / 1000;
      ts.tv_nsec = static_cast<long long>(wait_ms % 1000) * 1000000;
      arg.ts = reinterpret_cast<uint64_t>(&ts);
      argp = &arg;
      argsz = sizeof(arg);
      flags |= IORING_ENTER_EXT_ARG;
    }
    int rc = UringEnterSys(r.fd, r.to_submit, min_complete, flags, argp,
                           argsz);
    ++r.enters_n;
    if (rc >= 0) {
      r.to_submit -= std::min<unsigned>(r.to_submit,
                                        static_cast<unsigned>(rc));
      return true;
    }
    if (errno == EINTR) continue;
    if (errno == ETIME) {
      // timed wait expired: not an error — and the submit phase ran
      // before the wait, so the batch is in the kernel's hands
      r.to_submit = 0;
      return true;
    }
    if (errno == EBUSY || errno == EAGAIN) {
      // CQ backed up: a GETEVENTS pass without submission drains it
      if (UringEnterSys(r.fd, 0, 0, IORING_ENTER_GETEVENTS, nullptr,
                        0) >= 0) {
        ++r.enters_n;
        continue;
      }
    }
    return false;
  }
}

Ring* ThreadRing() {
  struct Holder {
    Ring r;
    bool ok = false;
    bool tried = false;
    ~Holder() {
      if (ok) RingDestroy(r);
    }
  };
  thread_local Holder h;
  if (!h.tried) {
    h.tried = true;
    h.ok = UringSupported() &&
           RingInit(h.r, static_cast<unsigned>(UringDepth()));
  }
  return h.ok ? &h.r : nullptr;
}

}  // namespace

int64_t UringDepth() {
  static const int64_t d = [] {
    int64_t v = EnvInt("HVT_URING_DEPTH", 64);
    if (v < 8) v = 8;
    if (v > 4096) v = 4096;
    return v;
  }();
  return d;
}
int64_t UringSpinUs() {
  // Spinning only helps when the peer can make progress WHILE we spin
  // — on a single-CPU host it actively hurts (the spin burns the
  // timeslice the peer needs to produce our completion), so the
  // default is 0 there and the pump goes straight to the fused
  // submit+block enter.
  static const int64_t v = EnvInt(
      "HVT_URING_SPIN_US",
      std::thread::hardware_concurrency() > 1 ? 40 : 0);
  return v < 0 ? 0 : v;
}
int64_t UringMultishotMax() {
  static const int64_t v = EnvInt("HVT_URING_MULTISHOT_MAX", 256 << 10);
  return v < 0 ? 0 : v;
}

bool UringSupported() {
  static const bool ok = [] {
    Ring r;
    if (!RingInit(r, 8)) return false;
    RingDestroy(r);
    return true;
  }();
  return ok;
}

int ResolveLinkBackend() {
  static const int be = [] {
    const char* v = getenv("HVT_LINK_BACKEND");
    std::string s = v ? v : "auto";
    if (s == "tcp") return kLinkBackendTcp;
    if (s == "io_uring" || s == "auto")
      return UringSupported() ? kLinkBackendUring : kLinkBackendTcp;
    return kLinkBackendTcp;  // unknown value: the safe backend
  }();
  return be;
}

IoUringLink::~IoUringLink() = default;

size_t IoUringLink::TakeSpill(void* p, size_t n) {
  size_t have = spill_.size() - spill_off_;
  if (have == 0) return 0;
  size_t k = std::min(have, n);
  memcpy(p, spill_.data() + spill_off_, k);
  spill_off_ += k;
  if (spill_off_ == spill_.size()) {
    spill_.clear();
    spill_off_ = 0;
  }
  return k;
}

size_t IoUringLink::RecvSome(void* p, size_t n) {
  Claim claim(this);
  // spill bytes were rx_-counted when reaped off the ring — serve them
  // before touching the socket so the stream order is preserved
  size_t k = TakeSpill(p, n);
  if (k) return k;
  return TcpLink::RecvSome(p, n);
}

void IoUringLink::Recv(void* p, size_t n, int64_t timeout_ms) {
  Claim claim(this);
  auto* dst = static_cast<uint8_t*>(p);
  size_t got = TakeSpill(dst, n);
  if (got < n) TcpLink::Recv(dst + got, n - got, timeout_ms);
}

void IoUringLink::PumpDuplex(Transport& in_t, const uint8_t* send_buf,
                             size_t send_n, uint8_t* recv_buf,
                             size_t recv_n, size_t chunk_bytes,
                             size_t& sent, size_t& rcvd,
                             const std::function<void()>& on_progress) {
  (void)chunk_bytes;
  auto* in = dynamic_cast<IoUringLink*>(&in_t);
  if (!in) return;  // mixed backends: the generic loop handles it
  Ring* r = ThreadRing();
  if (!r) return;
  Claim claim_out(this);
  Claim claim_in(in);

  // Overrun bytes a previous pump's multishot recv banked belong to
  // the head of this transfer — consume them before the socket.
  if (rcvd < recv_n) {
    size_t k = in->TakeSpill(recv_buf + rcvd, recv_n - rcvd);
    if (k) {
      rcvd += k;
      if (on_progress) on_progress();
    }
  }

  // Session-layer conditions the pump does not handle: pending replay,
  // a link mid-heal, a closed socket. The generic loop's Some() path
  // owns all of them.
  auto pumpable = [&]() {
    return state() == LinkState::HEALTHY && sock_.valid() &&
           replay_from_ < 0 && in->state() == LinkState::HEALTHY &&
           in->sock_.valid() && in->replay_from_ < 0;
  };
  if (!pumpable()) return;

  const int out_fd = sock_.fd();
  const int in_fd = in->sock_.fd();
  const bool use_mshot =
      r->pbuf_ok && recv_n > 0 &&
      recv_n <= static_cast<size_t>(UringMultishotMax());
  uint64_t ud_send = 0, ud_recv = 0, ud_mshot = 0;
  std::vector<uint64_t> cancel_uds;
  bool failed = false;

  // flush the ring telemetry into the hub sinks on every exit path
  struct Flush {
    Ring* r;
    ReconnectHub* hub;
    ~Flush() {
      if (hub) {
        if (hub->uring_sqes)
          hub->uring_sqes->fetch_add(r->sqes_n,
                                     std::memory_order_relaxed);
        if (hub->uring_enters)
          hub->uring_enters->fetch_add(r->enters_n,
                                       std::memory_order_relaxed);
        if (hub->uring_cqes)
          hub->uring_cqes->fetch_add(r->cqes_n,
                                     std::memory_order_relaxed);
      }
      r->sqes_n = r->enters_n = r->cqes_n = 0;
    }
  } flush{r, hub_};

  // Reap every posted completion: account bytes exactly like the
  // SendSome/RecvSome syscall paths (replay ring, tx_/rx_, chaos
  // cuts), bank multishot overrun in the spill, recycle pbufs.
  auto reap = [&]() -> size_t {
    size_t moved = 0;
    io_uring_cqe cqe;
    while (PeekCqe(*r, &cqe)) {
      if (cqe.user_data == ud_send) {
        ud_send = 0;
        if (cqe.res > 0) {
          AccountTx(send_buf + sent, cqe.res);
          sent += static_cast<size_t>(cqe.res);
          moved += static_cast<size_t>(cqe.res);
          if (!sock_.valid()) failed = true;  // chaos cut tripped
        } else if (cqe.res != -ECANCELED) {
          failed = true;
        }
      } else if (cqe.user_data == ud_recv) {
        ud_recv = 0;
        if (cqe.res > 0) {
          in->AccountRx(cqe.res);
          rcvd += static_cast<size_t>(cqe.res);
          moved += static_cast<size_t>(cqe.res);
          if (!in->sock_.valid()) failed = true;
        } else if (cqe.res != -ECANCELED) {
          failed = true;  // 0 = EOF, <0 = socket error
        }
      } else if (cqe.user_data == ud_mshot) {
        if (cqe.res > 0 && (cqe.flags & IORING_CQE_F_BUFFER)) {
          unsigned bid = cqe.flags >> IORING_CQE_BUFFER_SHIFT;
          const uint8_t* src = r->arena + bid * kPbufBytes;
          size_t k = static_cast<size_t>(cqe.res);
          in->AccountRx(static_cast<int64_t>(k));
          size_t take = std::min(k, recv_n - rcvd);
          memcpy(recv_buf + rcvd, src, take);
          rcvd += take;
          moved += take;
          if (k > take) {
            // the peer ran ahead into the next ring step: bank the
            // overrun (already rx_-counted) for the next receive
            in->spill_.insert(in->spill_.end(), src + take,
                              src + take + (k - take));
          }
          PbufAdd(*r, bid);
          if (!in->sock_.valid()) failed = true;
        } else if (cqe.res <= 0 && cqe.res != -ECANCELED &&
                   cqe.res != -ENOBUFS) {
          failed = true;
        }
        if (!(cqe.flags & IORING_CQE_F_MORE))
          ud_mshot = 0;  // terminated (done, canceled, or ENOBUFS)
      } else {
        for (size_t i = 0; i < cancel_uds.size(); ++i)
          if (cancel_uds[i] == cqe.user_data) {
            cancel_uds.erase(cancel_uds.begin() +
                             static_cast<long>(i));
            break;
          }
      }
    }
    return moved;
  };

  // Cancel + reap until nothing is in flight: no SQE may reference the
  // caller's buffers (or deliver unaccounted bytes) after we return.
  auto drain = [&]() {
    const int64_t give_up = NowMs() + 5000;
    while (ud_send || ud_recv || ud_mshot || r->to_submit ||
           !cancel_uds.empty()) {
      // (re)issue cancels for whatever is still armed — idempotent:
      // a cancel for a completed ud reports -ENOENT on its own CQE
      if (cancel_uds.empty()) {
        for (uint64_t target : {ud_send, ud_recv, ud_mshot})
          if (target) {
            uint64_t ud = r->next_ud++;
            if (PrepCancel(*r, target, ud)) cancel_uds.push_back(ud);
          }
      }
      if (!Enter(*r, 1, 50)) break;  // ring unusable: nothing to wait on
      reap();
      if (NowMs() >= give_up) break;  // pathological; see header note
    }
  };

  const int64_t timeout_ms = OpTimeoutMs();
  int64_t deadline = timeout_ms > 0 ? NowMs() + timeout_ms : -1;
  const int64_t spin_us = UringSpinUs();

  try {
    while (sent < send_n || rcvd < recv_n) {
      if (failed || !pumpable()) {
        drain();
        return;  // partial progress: the generic loop finishes/heals
      }
      // top up the submission batch (both directions in one enter)
      if (sent < send_n && !ud_send) {
        uint64_t ud = r->next_ud++;
        if (PrepSend(*r, out_fd, send_buf + sent, send_n - sent, ud))
          ud_send = ud;
      }
      if (rcvd < recv_n) {
        if (use_mshot) {
          if (!ud_mshot) {
            uint64_t ud = r->next_ud++;
            if (PrepRecvMultishot(*r, in_fd, ud)) ud_mshot = ud;
          }
        } else if (!ud_recv) {
          uint64_t ud = r->next_ud++;
          if (PrepRecv(*r, in_fd, recv_buf + rcvd, recv_n - rcvd, ud))
            ud_recv = ud;
        }
      }
      // Completion strategy by host shape. Poll-armed socket CQEs are
      // posted by kernel task work, which (measured) runs only when
      // THIS task enters the kernel — a pure userspace CQ-tail poll
      // never observes them. With a spin window (multi-CPU default)
      // the whole batch is submitted nonblocking and the window
      // alternates a free CQ peek with a ~0.3 µs GETEVENTS enter that
      // runs the pending task work — catching a loopback turnaround
      // without the sleep/wake of a blocking wait. Without a window
      // (single-CPU default: spinning would burn the timeslice the
      // peer needs) submit and wait FUSE into one timed enter — one
      // syscall per full-duplex ring step, against the generic loop's
      // poll+send+recv per chunk.
      size_t moved = 0;
      if (spin_us > 0) {
        if (!Enter(*r, 0, -1)) {
          failed = true;
          continue;
        }
        moved = reap();
        const int64_t spin_end = NowUs() + spin_us;
        while (!moved && NowUs() < spin_end) {
          moved = reap();  // free peek: may already be posted
          if (moved) break;
          if (!Enter(*r, 0, -1)) {
            failed = true;
            break;
          }
          moved = reap();
        }
      }
      if (!moved && !failed) {
        int wait_ms = 200;
        if (deadline >= 0) {
          int64_t left = deadline - NowMs();
          if (left <= 0) {
            drain();
            throw OpTimeoutError(
                "hvt: data-plane transfer made no progress for " +
                std::to_string(timeout_ms) + " ms (HVT_OP_TIMEOUT_MS)");
          }
          if (left < wait_ms) wait_ms = static_cast<int>(left);
        }
        if (!Enter(*r, 1, wait_ms)) {
          failed = true;
          continue;
        }
        moved = reap();
        if (!moved) {
          // idle round: service the engine's other broken links, same
          // as the generic loop's poll timeout
          ServiceSiblingLinks(hub_, this);
        }
      }
      if (moved) {
        if (deadline >= 0) deadline = NowMs() + timeout_ms;
        if (on_progress) on_progress();
      }
    }
    // transfer complete — the standing multishot recv (if any) must
    // not outlive the pump: a later blocking Recv would otherwise park
    // in poll() while the kernel consumes the socket into our pbufs
    drain();
  } catch (...) {
    drain();
    throw;
  }
}

}  // namespace hvt
