"""TPU preemption → elastic interrupt hook (SURVEY §5.3).

Reference analog: the discovery-driven HostsUpdatedInterrupt path —
the driver polls discovery and notifies workers so their next
``state.commit()`` raises (``horovod/runner/elastic/driver.py:177-260``,
``horovod/common/elastic.py:73-93``). On TPU the *earliest* preemption
signal lands on the worker itself (SIGTERM with a grace window on
GCE/GKE preemptible and spot slices; maintenance events via the metadata
server), so the watcher lives worker-side and feeds the same machinery:

- :meth:`PreemptionWatcher.install` registers a SIGTERM handler (and a
  poll thread when a maintenance-event ``poll_fn`` is supplied).
- On a notice, every watched :class:`~horovod_tpu.elastic.state.State`
  gets ``on_hosts_updated()``, so the next ``commit()`` raises
  ``HostsUpdatedInterrupt`` at a safe point and ``@hvt.elastic.run``
  re-rendezvous through the existing reset path.
- The notice is also reported to the elastic driver (PUT
  ``/kv/preempt/<host>/<slot>``), which broadcasts a host-update to ALL
  workers — the whole job converges to commit points and re-rendezvous
  together instead of dying mid-collective when the chip vanishes.

Enabled automatically by ``@hvt.elastic.run`` under an elastic launch;
``HVT_PREEMPTION_WATCH=0`` opts out, ``=1`` forces it on outside a
launcher.
"""

from __future__ import annotations

import os
import signal
import socket
import threading
import time
from typing import Callable, Optional

_watcher: Optional["PreemptionWatcher"] = None
_lock = threading.Lock()


class PreemptionWatcher:
    """Worker-side preemption/maintenance watcher.

    Parameters
    ----------
    poll_fn:
        Optional zero-arg callable polled from a daemon thread; returning
        truthy means "this host has a pending maintenance/preemption
        event" (plug a cloud metadata-server probe in here).
    poll_interval:
        Seconds between ``poll_fn`` polls.
    signals:
        Signals treated as preemption notices (default: SIGTERM).
    """

    def __init__(self, poll_fn: Optional[Callable[[], bool]] = None,
                 poll_interval: float = 5.0,
                 signals=(signal.SIGTERM,)):
        self._poll_fn = poll_fn
        self._poll_interval = poll_interval
        self._signals = tuple(signals)
        self._states = []
        self._prev_handlers = {}
        self._installed = False
        self._triggered = threading.Event()
        self._poll_thread = None
        self._state_lock = threading.Lock()

    # ------------------------------------------------------------- states

    def watch(self, state):
        with self._state_lock:
            if state not in self._states:
                self._states.append(state)

    def unwatch(self, state):
        with self._state_lock:
            if state in self._states:
                self._states.remove(state)

    # ------------------------------------------------------------ install

    def install(self):
        """Register signal handlers (main thread only — elsewhere only the
        poll thread runs) and start the maintenance poll thread."""
        if self._installed:
            return self
        self._installed = True
        if threading.current_thread() is threading.main_thread():
            for sig in self._signals:
                self._prev_handlers[sig] = signal.signal(
                    sig, self._on_signal)
        if self._poll_fn is not None:
            self._poll_thread = threading.Thread(
                target=self._poll_loop, daemon=True,
                name="hvt-preemption-poll")
            self._poll_thread.start()
        return self

    def uninstall(self):
        if not self._installed:
            return
        self._installed = False
        for sig, prev in self._prev_handlers.items():
            try:
                signal.signal(sig, prev)
            except (ValueError, OSError):
                pass
        self._prev_handlers.clear()

    @property
    def installed(self) -> bool:
        return self._installed

    @property
    def triggered(self) -> bool:
        return self._triggered.is_set()

    # ------------------------------------------------------------ trigger

    def trigger(self, reason: str = "preemption"):
        """Deliver a preemption notice: flag every watched state (the
        next ``commit()`` replicates its shards — ReplicatedState
        exchanges BEFORE the host-update check raises, so peers hold
        the final version when the chips vanish — then raises
        HostsUpdatedInterrupt) and tell the elastic driver so all peers
        converge to their commit points. The driver hears it twice, on
        purpose: ``/kv/preempt/<host>/<slot>`` broadcasts the
        host-update to every worker, and the ``/kv/failure/<host>/
        preempt`` notice marks this host as GRACEFULLY draining — the
        driver drops it from the next assignment up front, so a
        preempted host never has to look like a crash (no abort storm,
        no failure-report attribution) before it leaves."""
        self._triggered.set()
        now = time.time()
        with self._state_lock:
            states = list(self._states)
        for state in states:
            try:
                state.on_hosts_updated(now, reason)
            except Exception:
                pass
        self._report_driver(reason)

    def _on_signal(self, signum, frame):
        self.trigger(reason=f"signal:{signum}")

    def _poll_loop(self):
        while self._installed and not self._triggered.is_set():
            try:
                if self._poll_fn():
                    self.trigger(reason="maintenance-event")
                    return
            except Exception:
                pass
            time.sleep(self._poll_interval)

    def _report_driver(self, reason: str):
        addr = os.environ.get("HVT_RENDEZVOUS_ADDR")
        if not addr:
            return
        host = os.environ.get("HVT_HOSTNAME") or socket.gethostname()
        slot = os.environ.get("HVT_LOCAL_PROCESS_ID", "0")
        try:
            from horovod_tpu.metrics.telemetry import relay_put

            relay_put(addr, "preempt", f"{host}/{slot}",
                      {"reason": reason, "timestamp": time.time()},
                      urgent=True, timeout=2)
            # graceful-drain notice: one per HOST (the preemption takes
            # the whole host's chips), keyed `<host>/preempt` so the
            # driver's failure hook can tell a drain from a crash and
            # drop the host from the next round without blaming anyone
            relay_put(addr, "failure", f"{host}/preempt",
                      {"reason": reason, "graceful": True,
                       "timestamp": time.time()},
                      urgent=True, timeout=2)
        except Exception:
            pass


# ---------------------------------------------------------------- module API

def watch_state(state, poll_fn: Optional[Callable[[], bool]] = None):
    """Attach ``state`` to the process-wide watcher, creating/installing it
    if preemption watching is enabled (elastic launch, or
    ``HVT_PREEMPTION_WATCH=1``). Called by ``@hvt.elastic.run``."""
    global _watcher
    knob = os.environ.get("HVT_PREEMPTION_WATCH", "")
    if knob == "0":
        return None
    if not knob and not os.environ.get("HVT_RENDEZVOUS_ADDR"):
        return None
    with _lock:
        if _watcher is None:
            _watcher = PreemptionWatcher(poll_fn=poll_fn)
            _watcher.install()
        _watcher.watch(state)
    return _watcher


def get_watcher() -> Optional[PreemptionWatcher]:
    return _watcher


def _reset_for_tests():
    global _watcher
    with _lock:
        if _watcher is not None:
            _watcher.uninstall()
        _watcher = None
