"""Elastic (fault-tolerant) training.

Parity: ``horovod/common/elastic.py`` (State machine, run_fn wrapper) +
framework states (``horovod/torch/elastic/state.py``,
``horovod/tensorflow/elastic.py``). The driver/discovery side lives in
``horovod_tpu/runner/elastic``.

TPU mapping of the recovery loop (reference ``common/elastic.py:147``):
a TPU pre-emption notice / lost host surfaces as
:class:`HorovodInternalError` (collective abort) or
:class:`HostsUpdatedInterrupt` (driver notification at a commit point);
the wrapper restores the last committed state, re-initializes the runtime
(new rendezvous → new mesh shape), and re-enters the train function.
"""

from horovod_tpu.elastic.state import (State, ObjectState, JaxState,
                                       ReplicatedState,
                                       ReplicatedJaxState,
                                       ReplicaUnavailableError,
                                       ShardCorruptError)
from horovod_tpu.elastic.run import run

__all__ = ["State", "ObjectState", "JaxState", "ReplicatedState",
           "ReplicatedJaxState", "ReplicaUnavailableError",
           "ShardCorruptError", "run"]
