"""The elastic run wrapper (reference ``horovod/common/elastic.py:147``
``run_fn`` + the worker side of the re-rendezvous protocol,
``runner/elastic/worker.py``)."""

from __future__ import annotations

import functools
import json
import os
import socket
import time
import urllib.error

from horovod_tpu.common.exceptions import (HorovodInternalError,
                                           HostsUpdatedInterrupt)

_LOCAL_NAMES = ("localhost", "127.0.0.1")


def run(func):
    """Decorator: ``@hvt.elastic.run`` around ``train(state, ...)``.

    Loop semantics match the reference run_fn (``common/elastic.py:147``):

    - HorovodInternalError (collective failed — host lost mid-step):
      restore() to the last commit, then re-initialize and retry.
    - HostsUpdatedInterrupt (driver notified a host change at commit()):
      keep current state, re-initialize and retry (sync unless skip_sync).

    Under an elastic launch (``HVT_RENDEZVOUS_ADDR`` set), each
    re-initialization reports READY to the driver and blocks on the
    rendezvous for the next round's slot assignment (new rank/size/master)
    before re-joining; a worker whose slot was dropped exits cleanly.
    """

    @functools.wraps(func)
    def wrapper(state, *args, **kwargs):
        from horovod_tpu.elastic import preemption
        from horovod_tpu.runner.elastic import notification

        notification.init_worker_notification(state)
        # TPU preemption notices (SIGTERM / maintenance events) surface as
        # HostsUpdatedInterrupt at the next commit (SURVEY §5.3)
        preemption.watch_state(state)
        round_ = _sync_slot_from_rendezvous(0)
        reset_required = False
        skip_sync = False
        recovery = None  # _Recovery while a failure/update is in flight
        while True:
            if reset_required:
                round_ = _reset(round_, recovery)
                state.on_reset()
            try:
                if not skip_sync:
                    t0 = time.monotonic()
                    state.sync()
                    if recovery is not None:
                        recovery.phase("rebuild",
                                       time.monotonic() - t0,
                                       outcome=_sync_outcome(state))
                if recovery is not None:
                    recovery.finish(round_)
                    recovery = None
                return func(state, *args, **kwargs)
            except HorovodInternalError as e:
                # a collective failed (peer lost / deadline / abort):
                # tell the driver which peer we believe died so it can
                # blacklist the host before the next round, then roll
                # back to the last commit and re-rendezvous
                recovery = _Recovery("failure")
                _report_failure(round_, e)
                t0 = time.monotonic()
                state.restore()
                recovery.phase("restore", time.monotonic() - t0)
                skip_sync = False
            except HostsUpdatedInterrupt as e:
                if recovery is None:
                    recovery = _Recovery("host_update")
                skip_sync = e.skip_sync
            reset_required = True

    return wrapper


class _Recovery:
    """One recovery episode's clock + reporting: phase durations land
    as RECOVERY flight-recorder events (stamped once the engine is back
    up), ``hvt_recovery_*`` metrics, and ``/kv/recovery/<host>/<slot>``
    reports the driver's ``/statusz`` renders as recovery rows."""

    def __init__(self, trigger: str):
        self.trigger = trigger
        self.t0 = time.monotonic()
        self.phases = []  # (phase, seconds, outcome)

    def phase(self, name: str, seconds: float, outcome: str = "ok"):
        self.phases.append((name, seconds, outcome))
        _report_recovery({"phase": name, "outcome": outcome,
                          "seconds": round(seconds, 4),
                          "trigger": self.trigger})

    def finish(self, round_: int):
        total = time.monotonic() - self.t0
        _report_recovery({"phase": "recovered", "outcome": "ok",
                          "seconds": round(total, 4), "round": round_,
                          "trigger": self.trigger,
                          "phases": {n: round(s, 4)
                                     for n, s, _ in self.phases}})
        try:
            from horovod_tpu.engine import native

            # the engine was down for most of the episode; stamp every
            # phase into the ring now so one timeline/hvt_analyze drain
            # shows the whole recovery next to the engine's own events.
            # Outcome wire codes (events.h): only fallback(1)/failed(2)
            # are non-ok — peer/rollback/bootstrap are SUCCESSFUL
            # rebuild flavors and must stamp 0
            for name, seconds, outcome in self.phases:
                native.record_event(
                    "RECOVERY", name,
                    arg=1 if outcome == "fallback" else
                    2 if outcome == "failed" else 0,
                    arg2=int(seconds * 1e6))
            native.record_event("RECOVERY", "recovered", arg=0,
                                arg2=int(total * 1e6))
        except Exception:
            pass
        try:
            from horovod_tpu import metrics

            metrics.counter(
                "hvt_recovery_rounds_total",
                "completed elastic recovery episodes by trigger",
                ("trigger",)).labels(trigger=self.trigger).inc()
            metrics.gauge(
                "hvt_recovery_end_to_end_seconds",
                "duration of the last recovery episode (failure/update "
                "detection to training resumed)").set(total)
        except Exception:
            pass


def _sync_outcome(state) -> str:
    last = getattr(state, "last_recovery", None)
    if isinstance(last, dict):
        return str(last.get("outcome", "ok"))
    return "ok"


def _reset(last_round: int, recovery=None) -> int:
    """Re-initialize the runtime after a world change: report READY, wait
    for the new round's slot assignment, then shutdown + init gives a
    fresh rendezvous and a fresh mesh (the analog of the reference's
    shutdown/init cycle inside reset, ``common/elastic.py:95-109``)."""
    from horovod_tpu.common import basics

    _report_state("READY", last_round)
    basics.shutdown()
    t0 = time.monotonic()
    new_round = _sync_slot_from_rendezvous(last_round)
    t1 = time.monotonic()
    basics.init()
    if recovery is not None:
        recovery.phase("rendezvous", t1 - t0)
        recovery.phase("reinit", time.monotonic() - t1)
    return new_round


def _elastic_addr():
    return os.environ.get("HVT_RENDEZVOUS_ADDR")


_identity = None


def _my_identity():
    """Spawn-time (host, local_rank) — cached, because it is this
    process's stable identity toward the driver even after
    ``_apply_slot_env`` rewrites the env for a new round."""
    global _identity
    if _identity is None:
        _identity = (os.environ.get("HVT_HOSTNAME") or socket.gethostname(),
                     os.environ.get("HVT_LOCAL_PROCESS_ID", "0"))
    return _identity


# abort causes where the broken reason's rank annotation names a peer
# THIS engine directly observed failing. A remote_abort reason instead
# starts with "abort from rank N" where N is the (healthy, surviving)
# ORIGINATOR of the abort frame — parsing it would get an innocent
# host blacklisted — so remote aborts report nothing and leave the
# attribution to the rank that detected the failure first-hand.
_DIRECT_DETECTION_CAUSES = ("peer_lost", "timeout", "heartbeat")


def _failed_ranks_from_engine() -> list:
    """Best-effort list of peer ranks this worker believes failed,
    parsed from the engine's broken reason (the containment layer
    annotates control-plane failures with the peer's rank, e.g.
    "peer_lost: control connection to rank 2 lost"; data-plane failures
    carry no rank and yield [])."""
    import re

    try:
        from horovod_tpu.engine import native

        broken, info = native.engine_broken()
    except Exception:
        return []
    if not broken:
        return []
    cause = info.split(":", 1)[0].strip()
    if cause not in _DIRECT_DETECTION_CAUSES:
        return []
    return sorted({int(m) for m in re.findall(r"\brank (\d+)\b", info)})


def _relay_report(scope: str, key: str, obj: dict, urgent: bool,
                  timeout: float = 5.0):
    """Leader-routed, direct-falling-back PUT of a worker report
    (``metrics/telemetry.py relay_put``): routed gangs fold the
    per-round report storm through one per-host ``/kvbulk`` request;
    everyone else PUTs exactly as before. Always best-effort with
    retries=0 underneath — these sit on the recovery path and the
    driver may itself be down."""
    addr = _elastic_addr()
    if not addr:
        return False
    try:
        from horovod_tpu.metrics.telemetry import relay_put

        return relay_put(addr, scope, key, obj, urgent=urgent,
                         timeout=timeout)
    except Exception:
        return False


def _report_failure(round_: int, err: Exception):
    """PUT a failure report to the driver (``/kv/failure/<host>/<slot>``)
    so it can blacklist the failed peer's host ahead of the worker-exit
    signal. Best-effort — recovery proceeds regardless."""
    host, slot = _my_identity()
    _relay_report("failure", f"{host}/{slot}",
                  {"round": round_, "error": str(err)[:2048],
                   "failed_ranks": _failed_ranks_from_engine()},
                  urgent=True)


def _report_state(state_name: str, round_: int):
    host, slot = _my_identity()
    body = {"state": state_name, "round": round_}
    if _relay_report("state", f"{host}/{slot}", body, urgent=True):
        return
    # the driver's round barrier counts READY reports — unlike the
    # observability scopes this one is worth a retried direct PUT when
    # the relay AND its direct fallback both failed (server restarting)
    addr = _elastic_addr()
    if not addr:
        return
    from horovod_tpu.runner.http_client import put_json

    try:
        put_json(addr, f"/kv/state/{host}/{slot}", body, timeout=5)
    except OSError:
        pass


def _report_recovery(body: dict):
    """One recovery-phase report (``/kv/recovery/<host>/<slot>``) — the
    /statusz recovery rows' source. Non-urgent: phase rows are
    observability, not control flow, so they may ride the next relay
    tick."""
    host, slot = _my_identity()
    _relay_report("recovery", f"{host}/{slot}",
                  dict(body, host=host, slot=slot, ts=time.time()),
                  urgent=False, timeout=3.0)


def _sync_slot_from_rendezvous(last_round: int,
                               timeout: float = 600.0) -> int:
    """Block until the rendezvous publishes a round newer than
    ``last_round`` containing our (host, local_rank) slot, then update the
    process env (rank/size/cross/master) for ``basics.init``.

    Returns the new round number. No-op (returns ``last_round``) outside
    an elastic launch. Exits the process cleanly when our slot was
    dropped from the new assignment.
    """
    addr = _elastic_addr()
    if not addr:
        return last_round
    import random

    from horovod_tpu.runner.http_client import get_json

    host, slot = _my_identity()
    deadline = time.time() + timeout
    # jittered exponential poll backoff (0.1 s → 2 s cap): a fixed
    # 0.25 s poll is ~8 requests/s PER RANK against the one rendezvous
    # server, and during a recovery round at 100+ ranks that steady
    # storm starves the very failure/READY reports the round is
    # waiting on (every PUT times out behind the pollers — found live
    # at 128 simulated ranks). Workers poll fast right after READY,
    # then back off; activation lands within one current interval.
    delay = 0.1
    last_ready = time.time()
    while time.time() < deadline:
        # self-healing READY: the report may have been queued on a
        # host leader that died before flushing (relay success means
        # queued, not landed). If no new round shows up for a while,
        # re-report — the driver's barrier dedupes repeats, and a
        # re-report after the leader's death takes the direct path.
        if time.time() - last_ready > 7.5:
            _report_state("READY", last_round)
            last_ready = time.time()
        info = world = None
        try:
            world = get_json(addr, "/world")
            info = get_json(addr, f"/rendezvous/{host}/{slot}")
        except urllib.error.HTTPError as e:
            if e.code != 404:
                raise
        except OSError:
            pass
        if world and world.get("round", 0) > last_round:
            if info is None or info.get("round", 0) != world["round"]:
                if info is None:
                    # new round exists and we are not in it → retire
                    raise SystemExit(0)
            else:
                _apply_slot_env(info, world)
                return world["round"]
        time.sleep(delay * (0.5 + 0.5 * random.random()))
        delay = min(delay * 1.5, 2.0)
    raise TimeoutError(
        f"elastic worker {host}/{slot} timed out waiting for round "
        f"> {last_round} from rendezvous {addr}")


def _apply_slot_env(info: dict, world: dict):
    env = os.environ
    env["HVT_PROCESS_ID"] = str(info["rank"])
    env["HVT_NUM_PROCESSES"] = str(info["size"])
    env["HVT_LOCAL_PROCESS_ID"] = str(info["local_rank"])
    env["HVT_LOCAL_SIZE"] = str(info["local_size"])
    env["HVT_CROSS_RANK"] = str(info["cross_rank"])
    env["HVT_CROSS_SIZE"] = str(info["cross_size"])
    master_host = world.get("master_host")
    if master_host and env.get("HVT_MASTER_ADDR"):
        if master_host in _LOCAL_NAMES or \
                master_host == socket.gethostname():
            env["HVT_MASTER_ADDR"] = "127.0.0.1"
        else:
            env["HVT_MASTER_ADDR"] = master_host
        # per-round engine control port: prefer the launcher-published
        # free-probed port (world info), falling back to a wide rotation
        # so a lingering listener from an old round can't collide
        base = int(env.get("HVT_MASTER_PORT_BASE",
                           env.get("HVT_MASTER_PORT", "29510")))
        env.setdefault("HVT_MASTER_PORT_BASE", str(base))
        env["HVT_MASTER_PORT"] = str(
            world.get("master_port") or base + world["round"] % 2048)
