"""The elastic run wrapper (reference ``horovod/common/elastic.py:147``)."""

from __future__ import annotations

import functools

from horovod_tpu.common.exceptions import (HorovodInternalError,
                                           HostsUpdatedInterrupt)


def run(func):
    """Decorator: ``@hvt.elastic.run`` around ``train(state, ...)``.

    Loop semantics match the reference run_fn (``common/elastic.py:147``):

    - HorovodInternalError (collective failed — host lost mid-step):
      restore() to the last commit, then re-initialize and retry.
    - HostsUpdatedInterrupt (driver notified a host change at commit()):
      keep current state, re-initialize and retry (sync unless skip_sync).
    """

    @functools.wraps(func)
    def wrapper(state, *args, **kwargs):
        from horovod_tpu.runner.elastic import notification

        notification.init_worker_notification(state)
        reset_required = False
        skip_sync = False
        while True:
            if reset_required:
                _reset()
                state.on_reset()
            try:
                if not skip_sync:
                    state.sync()
                return func(state, *args, **kwargs)
            except HorovodInternalError:
                state.restore()
                skip_sync = False
            except HostsUpdatedInterrupt as e:
                skip_sync = e.skip_sync
            reset_required = True

    return wrapper


def _reset():
    """Re-initialize the runtime after a world change: shutdown + init gives
    a fresh rendezvous and a fresh mesh (the analog of the reference's
    shutdown/init cycle inside reset, ``common/elastic.py:95-109``)."""
    from horovod_tpu.common import basics

    basics.shutdown()
    basics.init()
