"""Elastic state objects (reference ``horovod/common/elastic.py:26-144``,
``horovod/torch/elastic/state.py:27-140``)."""

from __future__ import annotations

import copy

import jax


class State:
    """Tracked training state with commit / restore / sync
    (reference ``common/elastic.py:26``).

    - ``commit()``: snapshot state in host memory and check for pending
      host updates (raising HostsUpdatedInterrupt at a safe point).
    - ``restore()``: roll back to the last commit (after a failure).
    - ``sync()``: broadcast state from the new coordinator after a
      re-initialization.
    """

    def __init__(self, **kwargs):
        self._host_messages = []
        self._reset_callbacks = []
        for k, v in kwargs.items():
            setattr(self, k, v)

    def register_reset_callbacks(self, callbacks):
        self._reset_callbacks.extend(callbacks)

    def on_reset(self):
        self.reset()
        for cb in self._reset_callbacks:
            cb()

    def on_hosts_updated(self, timestamp, update_res):
        self._host_messages.append((timestamp, update_res))

    def commit(self):
        self.save()
        self.check_host_updates()

    def check_host_updates(self):
        """Raise HostsUpdatedInterrupt if the driver reported a host-set
        change since the last check (reference ``common/elastic.py:73-93``)."""
        from horovod_tpu.common.exceptions import HostsUpdatedInterrupt

        if self._host_messages:
            # skip_sync when only additions occurred and our state is current
            self._host_messages.clear()
            raise HostsUpdatedInterrupt(skip_sync=False)

    def save(self):
        raise NotImplementedError

    def restore(self):
        raise NotImplementedError

    def sync(self):
        raise NotImplementedError

    def reset(self):
        pass


class ObjectState(State):
    """Snapshot of plain Python attributes (reference
    ``common/elastic.py:112``)."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self._saved_state = {}
        self.save()

    def _tracked(self):
        return {k: v for k, v in self.__dict__.items()
                if not k.startswith("_")}

    def save(self):
        self._saved_state = copy.deepcopy(self._tracked())

    def restore(self):
        for k, v in copy.deepcopy(self._saved_state).items():
            setattr(self, k, v)

    def sync(self):
        from horovod_tpu.ops.functions import broadcast_object

        synced = broadcast_object(self._tracked(), root_rank=0,
                                  name="elastic.ObjectState")
        for k, v in synced.items():
            setattr(self, k, v)
        self.save()


class JaxState(ObjectState):
    """Elastic state for a JAX training loop: params + optimizer state
    pytrees plus arbitrary scalars (epoch, batch).

    The analog of ``TorchState`` (``torch/elastic/state.py:27``): pytree
    leaves are snapshotted to host memory on commit (device HBM is lost on
    pre-emption) and broadcast from the new rank 0 on sync.
    """

    def __init__(self, params=None, opt_state=None, **kwargs):
        self.params = params
        self.opt_state = opt_state
        super().__init__(**kwargs)

    def save(self):
        state = self._tracked()
        # jax arrays → host numpy for a durable snapshot
        self._saved_state = jax.tree.map(
            lambda x: jax.device_get(x) if hasattr(x, "devices") else
            copy.deepcopy(x), state)

    def restore(self):
        for k, v in self._saved_state.items():
            setattr(self, k, jax.tree.map(lambda x: x, v))

    def sync(self):
        from horovod_tpu.ops.functions import broadcast_parameters

        self.params = broadcast_parameters(self.params, root_rank=0)
        if self.opt_state is not None:
            self.opt_state = broadcast_parameters(self.opt_state,
                                                  root_rank=0)
        from horovod_tpu.ops.functions import broadcast_object

        scalars = {k: v for k, v in self._tracked().items()
                   if k not in ("params", "opt_state")}
        synced = broadcast_object(scalars, root_rank=0,
                                  name="elastic.JaxState")
        for k, v in synced.items():
            setattr(self, k, v)
        self.save()
