"""Elastic state objects (reference ``horovod/common/elastic.py:26-144``,
``horovod/torch/elastic/state.py:27-140``) plus the checkpointless
recovery layer: :class:`ReplicatedState` keeps every rank's committed
training state alive on K peer ranks (versioned, CRC-stamped shards,
refreshed on ``commit()``), so a permanent host loss rebuilds the lost
ranks' state from surviving peers in seconds instead of restarting from
the application's checkpoint.

Import-light on purpose: jax is imported lazily inside
:class:`JaxState`, and the replication core is pure stdlib over an
injectable collectives backend — the simulated 128-rank harness
(``benchmarks/elastic_recovery.py``) drives the exact same shard /
plan / rebuild code over bare-ctypes MiniEngine workers with no
jax/numpy in the process.
"""

from __future__ import annotations

import copy
import os
import pickle
import struct
import zlib


class State:
    """Tracked training state with commit / restore / sync
    (reference ``common/elastic.py:26``).

    - ``commit()``: snapshot state in host memory and check for pending
      host updates (raising HostsUpdatedInterrupt at a safe point).
    - ``restore()``: roll back to the last commit (after a failure).
    - ``sync()``: broadcast state from the new coordinator after a
      re-initialization.
    """

    def __init__(self, **kwargs):
        self._host_messages = []
        self._reset_callbacks = []
        for k, v in kwargs.items():
            setattr(self, k, v)

    def register_reset_callbacks(self, callbacks):
        self._reset_callbacks.extend(callbacks)

    def on_reset(self):
        self.reset()
        for cb in self._reset_callbacks:
            cb()

    def on_hosts_updated(self, timestamp, update_res):
        self._host_messages.append((timestamp, update_res))

    def commit(self):
        self.save()
        self.check_host_updates()

    def check_host_updates(self):
        """Raise HostsUpdatedInterrupt if the driver reported a host-set
        change since the last check (reference ``common/elastic.py:73-93``)."""
        from horovod_tpu.common.exceptions import HostsUpdatedInterrupt

        if self._host_messages:
            # skip_sync when only additions occurred and our state is current
            self._host_messages.clear()
            raise HostsUpdatedInterrupt(skip_sync=False)

    def save(self):
        raise NotImplementedError

    def restore(self):
        raise NotImplementedError

    def sync(self):
        raise NotImplementedError

    def reset(self):
        pass


class ObjectState(State):
    """Snapshot of plain Python attributes (reference
    ``common/elastic.py:112``)."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self._saved_state = {}
        self.save()

    def _tracked(self):
        return {k: v for k, v in self.__dict__.items()
                if not k.startswith("_")}

    def save(self):
        self._saved_state = copy.deepcopy(self._tracked())

    def restore(self):
        for k, v in copy.deepcopy(self._saved_state).items():
            setattr(self, k, v)

    def sync(self):
        from horovod_tpu.ops.functions import broadcast_object

        synced = broadcast_object(self._tracked(), root_rank=0,
                                  name="elastic.ObjectState")
        for k, v in synced.items():
            setattr(self, k, v)
        self.save()


class JaxState(ObjectState):
    """Elastic state for a JAX training loop: params + optimizer state
    pytrees plus arbitrary scalars (epoch, batch).

    The analog of ``TorchState`` (``torch/elastic/state.py:27``): pytree
    leaves are snapshotted to host memory on commit (device HBM is lost on
    pre-emption) and broadcast from the new rank 0 on sync.
    """

    def __init__(self, params=None, opt_state=None, **kwargs):
        self.params = params
        self.opt_state = opt_state
        super().__init__(**kwargs)

    def save(self):
        import jax

        state = self._tracked()
        # jax arrays → host numpy for a durable snapshot
        self._saved_state = jax.tree.map(
            lambda x: jax.device_get(x) if hasattr(x, "devices") else
            copy.deepcopy(x), state)

    def restore(self):
        import jax

        for k, v in self._saved_state.items():
            setattr(self, k, jax.tree.map(lambda x: x, v))

    def sync(self):
        from horovod_tpu.ops.functions import broadcast_parameters

        self.params = broadcast_parameters(self.params, root_rank=0)
        if self.opt_state is not None:
            self.opt_state = broadcast_parameters(self.opt_state,
                                                  root_rank=0)
        from horovod_tpu.ops.functions import broadcast_object

        scalars = {k: v for k, v in self._tracked().items()
                   if k not in ("params", "opt_state")}
        synced = broadcast_object(scalars, root_rank=0,
                                  name="elastic.JaxState")
        for k, v in synced.items():
            setattr(self, k, v)
        self.save()


# ---------------------------------------------------------------------------
# checkpointless recovery: peer-replicated shards
# ---------------------------------------------------------------------------

class ShardCorruptError(RuntimeError):
    """A replica shard failed its magic/CRC/length check on decode."""


class ReplicaUnavailableError(RuntimeError):
    """No intact replica exists for this rank's state — the caller must
    fall back to the application's own restore (checkpoint)."""


# Shard wire format: a fixed header + pickled snapshot payload. The CRC
# covers the payload only (the header fields are validated structurally)
# so a bit-flip anywhere in the blob is caught before it becomes
# somebody's optimizer state.
_SHARD_MAGIC = b"HVTS"
_SHARD_HEADER = struct.Struct("<4sqiIq")  # magic, version, owner, crc, len


def encode_shard(owner: int, version: int, payload: bytes) -> bytes:
    """``payload`` (the pickled snapshot) framed as a versioned,
    CRC-stamped replica shard."""
    return _SHARD_HEADER.pack(_SHARD_MAGIC, int(version), int(owner),
                              zlib.crc32(payload) & 0xFFFFFFFF,
                              len(payload)) + payload


def decode_shard(blob: bytes):
    """``(owner, version, payload)`` — raises :class:`ShardCorruptError`
    on any framing or CRC mismatch."""
    if len(blob) < _SHARD_HEADER.size:
        raise ShardCorruptError(
            f"shard truncated: {len(blob)} < header "
            f"{_SHARD_HEADER.size}")
    magic, version, owner, crc, n = _SHARD_HEADER.unpack_from(blob)
    if magic != _SHARD_MAGIC:
        raise ShardCorruptError(f"bad shard magic {magic!r}")
    payload = blob[_SHARD_HEADER.size:]
    if len(payload) != n:
        raise ShardCorruptError(
            f"shard length mismatch: header says {n}, got "
            f"{len(payload)}")
    if (zlib.crc32(payload) & 0xFFFFFFFF) != crc:
        raise ShardCorruptError(
            f"shard CRC mismatch for owner {owner} v{version}")
    return int(owner), int(version), payload


def replica_group_size() -> int:
    """Replication factor K (``HVT_REPLICA_GROUP_SIZE``, default 2):
    each rank's committed state lives on itself plus K-1 peers."""
    try:
        return max(1, int(os.environ.get("HVT_REPLICA_GROUP_SIZE", "")
                          or 2))
    except ValueError:
        return 2


def replication_enabled() -> bool:
    """``HVT_STATE_REPLICATION`` gate (default on): ``0`` turns every
    ReplicatedState into its plain base class — commits stop exchanging
    shards and sync falls back to the broadcast path."""
    return os.environ.get("HVT_STATE_REPLICATION", "1") not in (
        "0", "off", "false")


def partial_fallback_enabled() -> bool:
    """``HVT_PARTIAL_FALLBACK`` gate (default on): when only SOME
    lineages lost every intact replica, ranks with recoverable lineages
    keep their peer-rebuilt state and ONLY the lost lineages restore
    from the application fallback (ROADMAP 5d). ``0`` restores the
    pre-r15 all-or-nothing semantics — every rank takes the fallback
    together — for applications whose state is gang-replicated rather
    than per-lineage (a data-parallel optimizer restored from an older
    checkpoint on one rank only would diverge from its peers)."""
    return os.environ.get("HVT_PARTIAL_FALLBACK", "1") not in (
        "0", "off", "false")


def _interleave_by_host(ranks, hosts_by_rank):
    """Round-robin ranks across their hosts (h0's first slot, h1's
    first slot, ..., h0's second slot, ...): chunking the result into
    groups of k puts every group on k distinct hosts whenever there
    are >= k hosts."""
    by_host = {}
    order = []
    for r in ranks:
        h = hosts_by_rank[r]
        if h not in by_host:
            by_host[h] = []
            order.append(h)
        by_host[h].append(r)
    out = []
    depth = max(len(v) for v in by_host.values()) if by_host else 0
    for i in range(depth):
        for h in order:
            if i < len(by_host[h]):
                out.append(by_host[h][i])
    return out


def rack_of(host) -> str:
    """The topology group of a host id: the prefix before ``/`` when
    ``HVT_TOPO_HOST`` carries a rack dimension (``rack0/h3``), else
    ``None`` (flat topology — every host stands alone)."""
    h = str(host)
    return h.split("/", 1)[0] if "/" in h else None


def build_replica_groups(hosts_by_rank, k):
    """Partition ranks 0..n-1 into replication groups of ~k members,
    each spanning distinct hosts wherever the topology allows, and
    preferring SAME-RACK/different-host peers when ``HVT_TOPO_HOST``
    carries a rack dimension (``rack/host`` — ROADMAP 5b's
    topology-weighted placement).

    Within each rack that has at least k distinct hosts, ranks are
    interleaved round-robin across that rack's hosts and chunked into
    rack-local groups — replication traffic stays inside the rack
    while a host SIGKILL still cannot take a lineage and all of its
    replicas (every emitted group spans distinct hosts — a chunk that
    per-host count skew folds onto one host is never kept as-is).
    Rack remainders, racks too small to satisfy the cross-host
    guarantee on their own, and rack-less hosts pool into the classic
    global interleave, so a balanced flat topology (no ``/`` anywhere)
    produces exactly the pre-rack plan; a skewed one scatters
    skew-folded chunks across cross-host groups instead of keeping
    them. A trailing remainder group of one is merged into its
    predecessor (a group of one replicates nothing). Deterministic in
    its inputs: every rank computes the identical plan from the same
    gathered rank→host table."""
    n = len(hosts_by_rank)
    k = max(1, min(int(k), n))
    # first-seen rack order keeps the plan a pure function of the table
    racks = {}
    rack_order = []
    for r in range(n):
        rk = rack_of(hosts_by_rank[r])
        if rk not in racks:
            racks[rk] = []
            rack_order.append(rk)
        racks[rk].append(r)
    groups = []
    pool = []
    for rk in rack_order:
        ranks = racks[rk]
        hosts = {hosts_by_rank[r] for r in ranks}
        if rk is None or len(hosts) < k or len(ranks) < k:
            # cannot guarantee cross-host placement rack-locally —
            # fall back to the global pool (the pre-rack behavior)
            pool.extend(ranks)
            continue
        inter = _interleave_by_host(ranks, hosts_by_rank)
        whole = (len(inter) // k) * k
        for i in range(0, whole, k):
            g = inter[i:i + k]
            # host-count skew can fold a round-robin chunk onto ONE
            # host (three ranks on h0 + one on h1 at k=2 interleaves
            # to [0,3,1,2] and chunk [1,2] is all-h0) — such a chunk
            # would let a host SIGKILL take a lineage and all of its
            # replicas, so it rides the global pool instead
            if len({hosts_by_rank[r] for r in g}) > 1:
                groups.append(g)
            else:
                pool.extend(g)
        pool.extend(inter[whole:])  # remainder rides the global pool
    if pool:
        inter = _interleave_by_host(pool, hosts_by_rank)
        same_host = []
        for i in range(0, len(inter), k):
            g = inter[i:i + k]
            if len(g) > 1 and len({hosts_by_rank[r] for r in g}) == 1:
                same_host.append(g)
            else:
                groups.append(g)
        # the same skew can fold a pool chunk too: scatter those ranks
        # one-per-group across existing cross-host groups (adding a
        # member keeps a group cross-host). Only a world without
        # cross-host groups to absorb them (single-host topologies)
        # keeps same-host groups — replication within the host is
        # still better than none, and matches the pre-rack plan there.
        spill = [r for g in same_host for r in g]
        targets = [g for g in groups
                   if len({hosts_by_rank[r] for r in g}) > 1]
        if targets:
            for j, r in enumerate(spill):
                targets[j % len(targets)].append(r)
        else:
            groups.extend(same_host)
    if len(groups) > 1 and len(groups[-1]) == 1:
        groups[-2].extend(groups.pop())
    return [sorted(g) for g in groups]


def _recovery_metrics():
    """``hvt_recovery_*`` (horovod_tpu.metrics) — the observability half
    of the checkpointless story. Lazy + best-effort: the MiniEngine
    harness runs without the metrics registry's consumers."""
    from horovod_tpu import metrics

    return (
        metrics.counter("hvt_recovery_rebuilds_total",
                        "elastic state recoveries by outcome (peer = "
                        "rebuilt from a replica shard, bootstrap = "
                        "copied from a current peer, fallback = "
                        "application restore, failed)", ("outcome",)),
        metrics.counter("hvt_recovery_stale_shards_total",
                        "replica shards rejected for carrying a version "
                        "older than the one already held"),
        metrics.gauge("hvt_recovery_shard_bytes",
                      "bytes of peer replica shards held in memory"),
        metrics.gauge("hvt_recovery_last_seconds",
                      "duration of the last state rebuild/sync phase"),
    )


def _note(outcome=None, stale=0, shard_bytes=None, seconds=None):
    try:
        rebuilds, stales, held, last = _recovery_metrics()
        if outcome:
            rebuilds.labels(outcome=outcome).inc()
        if stale:
            stales.inc(stale)
        if shard_bytes is not None:
            held.set(shard_bytes)
        if seconds is not None:
            last.set(seconds)
    except Exception:
        pass  # telemetry must never block a recovery


class HvtCollectives:
    """The default collectives backend for :class:`ReplicatedState`:
    the engine's object collectives over dynamically registered process
    sets (PR 6's lanes — each replication group negotiates and caches
    on its own lane). Anything with the same four methods can stand in
    (the MiniEngine harness does, jax-free)."""

    def rank(self) -> int:
        from horovod_tpu.common import basics

        return basics.rank()

    def size(self) -> int:
        from horovod_tpu.common import basics

        return basics.size()

    def host(self) -> str:
        # one spelling of host identity (HVT_TOPO_HOST > HVT_HOSTNAME >
        # kernel hostname): replica-group planning and telemetry leader
        # election must agree about which ranks share a host
        from horovod_tpu.metrics.telemetry import host_name

        return host_name()

    def allgather(self, obj, name: str, ranks=None) -> list:
        """One picklable object per member; returns the list ordered by
        member rank. ``ranks=None`` = the world."""
        from horovod_tpu.common.process_sets import (ProcessSet,
                                                     add_process_set)
        from horovod_tpu.ops import collective_ops as C
        from horovod_tpu.ops.functions import allgather_object

        ps = C.global_process_set if ranks is None else \
            add_process_set(ProcessSet(list(ranks)))
        return allgather_object(obj, name=name, process_set=ps)


class ReplicatedState(ObjectState):
    """Checkpointless elastic state: :class:`ObjectState` whose
    ``commit()`` also refreshes versioned, CRC-stamped replica shards
    on K-1 peer ranks, and whose ``sync()`` rebuilds any rank's lost
    state from those peers instead of broadcasting blindly from rank 0.

    Life cycle under ``@hvt.elastic.run``:

    - ``commit()``: snapshot locally (base class), then allgather the
      pickled snapshot within this rank's replication group — after the
      call, K ranks on (topology permitting) K distinct hosts hold this
      rank's state at the committed version.
    - on failure: ``restore()`` rolls back locally exactly as before.
    - ``sync()`` (after re-rendezvous): the gang allgathers shard
      metadata; ranks whose state is missing or stale (fresh respawns)
      pull the newest intact shard for their owner id from a surviving
      replica via one allgather round; owner ids left unclaimed by a
      shrunken world are adopted deterministically and surface in
      :attr:`adopted` for the application to fold. A CRC-mismatched or
      missing replica falls back to ``fallback(self)`` when provided
      (application/checkpoint restore) and raises
      :class:`ReplicaUnavailableError` otherwise.

    ``owner`` is the rank's sticky identity: the rank it held when its
    state was first committed. Rank ids can shift across elastic rounds
    (the world shrinks); the owner id is what names a state lineage.

    Replication is on by default under ``HVT_STATE_REPLICATION`` and
    sized by ``HVT_REPLICA_GROUP_SIZE`` (K, default 2); commits stay
    off the hot path — nothing is exchanged until ``commit()`` runs.
    """

    def __init__(self, replicas=None, collectives=None, fallback=None,
                 **kwargs):
        self._replicas = replicas
        self._collectives = collectives
        self._fallback = fallback
        self._version = 0
        self._owner = None
        # owner -> [(version, shard blob)] newest-first, capped at TWO
        # generations: a host dying mid-commit leaves replication
        # groups skewed by one version (its own group's exchange
        # aborted, the others' completed), and the recovery cut is the
        # highest version EVERY lineage can produce — ranks past the
        # cut roll back one generation, which only works if the
        # previous generation still exists somewhere
        self._peer_shards = {}
        self._own_history = []   # [(version, payload)] newest-first
        self._groups_for = None  # (rank, size) the cached plan matches
        self._group = None
        self._adopted = {}       # orphaned owner -> decoded snapshot
        self._last_recovery = {}
        super().__init__(**kwargs)

    # ------------------------------------------------------------- plumbing
    @property
    def owner(self):
        return self._owner

    @property
    def version(self) -> int:
        return self._version

    @property
    def adopted(self) -> dict:
        """Snapshots of owner lineages orphaned by a shrunken world,
        adopted by this rank during the last ``sync()`` (deterministic
        assignment). The application decides how to fold them."""
        return self._adopted

    @property
    def last_recovery(self) -> dict:
        """``{phase, outcome, seconds, donor?}`` of the last sync."""
        return dict(self._last_recovery)

    def replica_info(self) -> dict:
        """Introspection for tests/debugz: group, versions held."""
        return {
            "owner": self._owner,
            "version": self._version,
            "group": list(self._group or ()),
            "held": {o: [v for v, _ in gens]
                     for o, gens in sorted(self._peer_shards.items())},
            "shard_bytes": self._shard_bytes(),
        }

    def _shard_bytes(self) -> int:
        return sum(len(b) for gens in self._peer_shards.values()
                   for _, b in gens)

    def _coll(self):
        if self._collectives is None:
            self._collectives = HvtCollectives()
        return self._collectives

    def _k(self) -> int:
        return self._replicas if self._replicas else replica_group_size()

    def _snapshot_payload(self) -> bytes:
        return pickle.dumps(self._saved_state, protocol=4)

    def _load_snapshot(self, payload: bytes, version: int):
        """One spelling of 'this payload is now my committed state'."""
        self._saved_state = pickle.loads(payload)
        self.restore()
        self._version = int(version)

    def _plan_group(self):
        """This rank's replication group under the CURRENT world,
        computed from one gathered rank→host table and cached until the
        world identity changes (sync() resets the cache on re-init)."""
        c = self._coll()
        key = (c.rank(), c.size())
        if self._groups_for == key and self._group:
            return self._group
        table = c.allgather({"rank": c.rank(), "host": c.host()},
                            name="hvt.elastic.replica_plan")
        hosts_by_rank = [None] * c.size()
        for m in table:
            hosts_by_rank[int(m["rank"])] = m["host"]
        groups = build_replica_groups(hosts_by_rank, self._k())
        self._group = next(g for g in groups if c.rank() in g)
        self._groups_for = key
        return self._group

    def _ingest(self, blob):
        """Keep a peer shard iff it is intact and newer than what is
        already held for its owner (stale versions are rejected and
        counted); the previous generation is retained — see the
        two-generation note in ``__init__``."""
        if not blob:
            return
        try:
            owner, version, _payload = decode_shard(bytes(blob))
        except ShardCorruptError:
            return  # a corrupt incoming copy never evicts a good one
        gens = self._peer_shards.setdefault(owner, [])
        if gens and version <= gens[0][0]:
            if version < gens[0][0]:
                _note(stale=1)
            return
        gens.insert(0, (version, bytes(blob)))
        del gens[2:]

    def _held_blob(self, owner, version):
        for v, blob in self._peer_shards.get(owner, ()):
            if v == version:
                return blob
        return None

    # ------------------------------------------------------------ commit
    def commit(self):
        self.save()
        if replication_enabled():
            self._replicate()
        self.check_host_updates()

    def _replicate(self):
        """Refresh this rank's shard on its group peers (and ingest
        theirs) — one object allgather on the group's process-set
        lane."""
        c = self._coll()
        if self._owner is None:
            self._owner = c.rank()
        payload = self._snapshot_payload()
        self._version += 1
        self._own_history.insert(0, (self._version, payload))
        del self._own_history[2:]
        if c.size() <= 1:
            return
        group = self._plan_group()
        blob = encode_shard(self._owner, self._version, payload)
        gi = min(group)
        shards = c.allgather(blob, name=f"hvt.elastic.replicate.g{gi}",
                             ranks=group)
        for member, peer_blob in zip(group, shards):
            if member != c.rank():
                self._ingest(peer_blob)
        # our own committed copy rides in _own_history; hold the framed
        # shard too so a donor lookup is uniform across owners
        self._ingest(blob)
        _note(shard_bytes=self._shard_bytes())

    # -------------------------------------------------------------- sync
    def sync(self):
        """Gang-wide state recovery after a re-initialization. See the
        class docstring for the full decision flow; every collective
        here runs on the WORLD set (the membership just changed — group
        lanes are re-planned afterwards)."""
        import time as _time

        if not replication_enabled():
            self._bootstrap_sync()
            return
        t0 = _time.monotonic()
        c = self._coll()
        self._groups_for = None  # world changed: re-plan groups lazily
        self._adopted = {}
        me = c.rank()
        meta = {"rank": me, "owner": self._owner,
                "version": self._version, "host": c.host(),
                "held": {o: [v for v, _ in gens]
                         for o, gens in self._peer_shards.items()}}
        metas = c.allgather(meta, name="hvt.elastic.replica_meta")
        metas.sort(key=lambda m: int(m["rank"]))
        # the meta exchange already carries the rank→host table — plan
        # the new world's replication groups from it now, so the
        # post-rebuild re-replication skips its own plan allgather
        # (two fewer gang collectives on the recovery path)
        try:
            hosts_by_rank = [m.get("host") or "?" for m in metas]
            groups = build_replica_groups(hosts_by_rank, self._k())
            self._group = next(g for g in groups if me in g)
            self._groups_for = (me, c.size())
        except (StopIteration, ValueError):
            self._groups_for = None  # re-plan lazily on next commit

        # versions available per owner lineage: owner -> {version:
        # [holder ranks]}
        available = {}
        for m in metas:
            for o, versions in (m.get("held") or {}).items():
                for v in versions:
                    o, v = int(o), int(v)
                    if v > 0:
                        available.setdefault(o, {}).setdefault(
                            v, []).append(int(m["rank"]))
        # the recovery cut: the highest version EVERY lineage can still
        # produce. A host dying mid-commit leaves groups one version
        # apart; ranks past the cut roll back a generation (held for
        # exactly this), so the gang resumes from one consistent step.
        target = min((max(vs) for vs in available.values()), default=0)
        if target <= 0:
            # nothing committed anywhere yet (initial round): plain
            # broadcast-from-rank-0 semantics
            self._bootstrap_sync()
            self._last_recovery = {"phase": "bootstrap_sync",
                                   "outcome": "ok"}
            return

        claimed = {int(m["owner"]) for m in metas
                   if m.get("owner") is not None}
        orphans = sorted(o for o in available if o not in claimed)
        fresh = sorted(int(m["rank"]) for m in metas
                       if m.get("owner") is None)
        # fresh respawns adopt unclaimed lineages first (a replacement
        # worker takes over the dead rank's state), deterministically;
        # fresh ranks beyond the orphan supply start BRAND-NEW
        # lineages with ids past every known owner — defaulting to the
        # rank id would collide with a survivor whose sticky owner
        # happens to equal this rank after a shrink
        adoption = dict(zip(fresh, orphans))
        next_id = max(set(available) | claimed | {-1}) + 1
        for i, r in enumerate(fresh[len(orphans):]):
            adoption[r] = next_id + i
        my_owner = self._owner if self._owner is not None \
            else adoption.get(me, me)
        # lineages still orphaned after respawns are adopted by live
        # members round-robin so a shrunken world loses no state
        leftovers = orphans[len(fresh):]
        ranks_sorted = sorted(int(m["rank"]) for m in metas)
        my_adoptions = [o for i, o in enumerate(leftovers)
                        if ranks_sorted[i % len(ranks_sorted)] == me]

        # which lineages must move at all: a rank serves its own owner
        # locally when it holds (owner, target); anything else — fresh
        # adopters, rolled-past ranks whose predecessor generation only
        # survives on a peer, leftover orphans — rides ONE gang
        # allgather, each shard contributed by its designated donor
        # (lowest holder rank)
        boot = min(available)  # bootstrap source for brand-new lineages
        need = set(leftovers)
        for m in metas:
            o = m["owner"] if m.get("owner") is not None \
                else adoption.get(int(m["rank"]))
            if o is None or int(o) not in available:
                # grown world: a rank starting a brand-new lineage
                # copies the cut-version state of the lowest lineage
                # (classic new-worker bootstrap, replica-served)
                need.add(boot)
                continue
            held = m.get("held") or {}
            if target not in held.get(o, held.get(str(o), [])):
                need.add(int(o))
        serving = {}
        for o in sorted(need):
            holders = available.get(o, {}).get(target, [])
            if holders and min(holders) == me:
                blob = self._held_blob(o, target)
                if blob is not None:
                    serving[o] = blob
        gathered = c.allgather(serving, name="hvt.elastic.replica_fill")
        fills = {}
        for contribution in gathered:
            for o, blob in (contribution or {}).items():
                fills.setdefault(int(o), bytes(blob))

        outcome, settle_err = "ok", None
        if self._version != target:
            try:
                if my_owner in available:
                    outcome = self._settle_own(my_owner, target,
                                               fills.get(my_owner))
                else:
                    outcome = self._settle_own(my_owner, target,
                                               fills.get(boot),
                                               bootstrap=True)
            except ReplicaUnavailableError as e:
                outcome, settle_err = "failed", e
        # gang-wide consensus on outcomes: a rank whose lineage is
        # unrecoverable AND has no fallback fails the whole gang (any
        # recovery the survivors kept would sit at a cut that rank can
        # never reach). A rank that DID restore from its application
        # fallback no longer drags the rest of the gang with it: with
        # HVT_PARTIAL_FALLBACK (default on) the intact lineages keep
        # their peer-rebuilt state at the cut and only the lost
        # lineages pay the checkpoint — per-lineage blast radius
        # (ROADMAP 5d) instead of all-or-nothing. HVT_PARTIAL_FALLBACK=0
        # restores the old gang-wide semantics for gang-replicated
        # application state (see partial_fallback_enabled).
        outs = c.allgather(outcome,
                           name="hvt.elastic.replica_outcome")
        if any(o == "failed" for o in outs):
            self._last_recovery = {"phase": "rebuild",
                                   "outcome": "failed",
                                   "version": target}
            raise settle_err if settle_err is not None else \
                ReplicaUnavailableError(
                    f"peer rank(s) "
                    f"{[i for i, o in enumerate(outs) if o == 'failed']} "
                    f"hold unrecoverable lineages; gang-wide fallback "
                    f"to application restore")
        fellback = [i for i, o in enumerate(outs) if o == "fallback"]
        if fellback and outcome != "fallback" and \
                not partial_fallback_enabled():
            if self._fallback is None:
                self._last_recovery = {"phase": "rebuild",
                                       "outcome": "failed",
                                       "version": target}
                raise ReplicaUnavailableError(
                    "a peer restored from its application fallback; "
                    "this rank has none to match the gang's cut "
                    "(HVT_PARTIAL_FALLBACK=0)")
            self._fallback(self)
            self.save()
            self._version = target
            self._own_history = [(target, self._snapshot_payload())]
            outcome = "fallback"
            _note(outcome="fallback")
        self._owner = my_owner
        orphans_lost = []
        for o in my_adoptions:
            blob = fills.get(o) or self._held_blob(o, target)
            try:
                if blob is None:
                    raise ShardCorruptError("no intact shard gathered")
                _owner, _v, payload = decode_shard(blob)
                self._adopted[o] = pickle.loads(payload)
            except ShardCorruptError:
                # best-effort by design (the gang must not fall back
                # wholesale over a lineage nobody is training), but
                # NEVER silent: the lineage's shards are about to be
                # retired below, so this is the moment its state is
                # actually lost
                orphans_lost.append(int(o))
                _note(outcome="orphan_lost")
        # drop shard generations past the cut everywhere (aborted
        # futures — version numbers are about to be reused by the
        # resumed trajectory), and RETIRE the leftover-adopted orphan
        # lineages entirely: their live data now rides inside the
        # adopter's own snapshot, and a frozen shard lingering in the
        # store would drag a FUTURE sync's recovery cut down to its
        # ancient version, failing the whole gang over state nobody
        # needs
        for o, gens in list(self._peer_shards.items()):
            kept = [] if o in leftovers else \
                [(v, b) for v, b in gens if v <= target]
            if kept:
                self._peer_shards[o] = kept[:2]
            else:
                del self._peer_shards[o]
        self._own_history = [(v, p) for v, p in self._own_history
                             if v <= target]
        self.save()
        dt = _time.monotonic() - t0
        self._last_recovery = {"phase": "rebuild", "outcome": outcome,
                               "version": target,
                               "seconds": round(dt, 4)}
        if orphans_lost:
            self._last_recovery["orphans_lost"] = orphans_lost
        if fellback:
            # which ranks restored their lineage from the application
            # fallback this round — the per-lineage recovery record
            # (/statusz recovery rows and the partial-loss tests read it)
            self._last_recovery["fallback_ranks"] = sorted(fellback)
        _note(seconds=dt)
        # RECOVERY flight-recorder stamping is owned by the caller's
        # episode (`elastic/run.py _Recovery`) — a second stamp here
        # would render every recovery as two rebuild markers
        # close the vulnerability window: re-replicate at the recovered
        # version so the gang is back at full replication factor before
        # training resumes (also re-plans groups for the new world)
        if c.size() > 1:
            self._replicate()

    def _bootstrap_sync(self):
        """Pre-first-commit sync: everyone takes rank 0's attributes
        (classic elastic semantics). Uses the injected backend when one
        is present so harness workers never touch the numpy-backed
        broadcast path."""
        if isinstance(self._coll(), HvtCollectives):
            super().sync()
            return
        c = self._coll()
        gathered = c.allgather(
            self._tracked() if c.rank() == 0 else None,
            name="hvt.elastic.bootstrap")
        for k, v in (gathered[0] or {}).items():
            setattr(self, k, v)
        self.save()

    def _settle_own(self, owner, target, blob, bootstrap=False):
        """Bring this rank's own lineage to the recovery cut: roll back
        a generation when it ran past the cut, rebuild from the
        gathered peer shard when it is behind (fresh respawn / adopted
        lineage), bootstrap-copy a peer lineage when this one never
        committed (grown world), and on a missing or corrupt replica
        fall back to the application restore."""
        if self._version > target:
            for v, payload in self._own_history:
                if v == target:
                    self._load_snapshot(payload, target)
                    _note(outcome="rollback")
                    return "rollback"
        if blob is None and not bootstrap:
            blob = self._held_blob(owner, target)
        if blob is not None:
            try:
                _o, v, payload = decode_shard(blob)
                if v == target:
                    self._load_snapshot(payload, target)
                    self._own_history = [(target, payload)]
                    if not bootstrap:
                        self._ingest(blob)
                    outcome = "bootstrap" if bootstrap else "peer"
                    _note(outcome=outcome)
                    return outcome
            except ShardCorruptError:
                pass
        if self._fallback is not None:
            self._fallback(self)
            self.save()
            self._version = target
            self._own_history = [(target, self._snapshot_payload())]
            _note(outcome="fallback")
            return "fallback"
        _note(outcome="failed")
        raise ReplicaUnavailableError(
            f"no intact replica for owner {owner} at version "
            f"{target} and no application fallback was provided")


class ReplicatedJaxState(ReplicatedState):
    """:class:`JaxState`'s semantics with peer replication: pytree
    leaves snapshot to host numpy on save (device HBM is lost on
    pre-emption), so the shard payloads pickle and CRC exactly like
    plain objects, and the pre-first-commit bootstrap broadcasts params
    through the engine's parameter path."""

    def __init__(self, params=None, opt_state=None, replicas=None,
                 collectives=None, fallback=None, **kwargs):
        super().__init__(replicas=replicas, collectives=collectives,
                         fallback=fallback, params=params,
                         opt_state=opt_state, **kwargs)

    # one spelling of the jax snapshot logic — JaxState owns it
    save = JaxState.save
    restore = JaxState.restore

    def _bootstrap_sync(self):
        from horovod_tpu.ops.functions import (broadcast_object,
                                               broadcast_parameters)

        self.params = broadcast_parameters(self.params, root_rank=0)
        if self.opt_state is not None:
            self.opt_state = broadcast_parameters(self.opt_state,
                                                  root_rank=0)
        scalars = {k: v for k, v in self._tracked().items()
                   if k not in ("params", "opt_state")}
        synced = broadcast_object(scalars, root_rank=0,
                                  name="elastic.ReplicatedJaxState")
        for k, v in synced.items():
            setattr(self, k, v)
        self.save()
