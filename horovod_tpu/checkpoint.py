"""Checkpoint / resume (SURVEY.md §5.4).

The reference has no file-checkpoint subsystem of its own — its layers are
(a) broadcast of variables/optimizer state at start so rank-0 restores
propagate (``tensorflow/functions.py`` broadcast_variables,
``torch/functions.py`` broadcast_optimizer_state), (b) elastic
``State.commit()`` in-memory snapshots (``common/elastic.py:60-71``), and
(c) Spark estimator stores. This module adds the TPU-native file layer on
top: orbax async checkpointing (non-blocking save off the training
thread), with the reference's broadcast-on-restore semantics preserved —
restore happens once and is broadcast from ``root_rank`` so every worker
resumes identically.

Usage::

    mgr = hvt.checkpoint.CheckpointManager("/ckpts", max_to_keep=3)
    mgr.save(step, {"params": params, "opt_state": opt_state})
    state = mgr.restore_latest(
        template={"params": params, "opt_state": opt_state})
"""

from __future__ import annotations

import os
from typing import Any, Optional


def _orbax():
    try:
        import orbax.checkpoint as ocp

        return ocp
    except ImportError as e:
        raise ImportError(
            "checkpointing requires orbax-checkpoint "
            "(pip install orbax-checkpoint)") from e


class CheckpointManager:
    """Thin orbax CheckpointManager wrapper with broadcast-on-restore.

    - ``save`` is asynchronous by default (orbax writes in a background
      thread; the train loop is only blocked for the on-device →
      host copy).
    - ``restore_latest``/``restore`` return the state broadcast from
      ``root_rank`` when the eager engine is up with size > 1, so a
      restore from shared storage — or from rank 0's local disk — yields
      identical state everywhere (the reference's broadcast-on-restore
      layering).
    """

    def __init__(self, directory: str, max_to_keep: int = 5,
                 save_interval_steps: int = 1, async_save: bool = True):
        ocp = _orbax()
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        options = ocp.CheckpointManagerOptions(
            max_to_keep=max_to_keep,
            save_interval_steps=save_interval_steps,
            enable_async_checkpointing=async_save)
        self._mgr = ocp.CheckpointManager(self.directory, options=options)

    def save(self, step: int, state: Any, force: bool = False) -> bool:
        """Queue an async save of the state pytree at ``step``."""
        ocp = _orbax()
        return self._mgr.save(step, args=ocp.args.StandardSave(state),
                              force=force)

    def wait(self):
        """Block until queued async saves are durable."""
        self._mgr.wait_until_finished()

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def all_steps(self):
        return list(self._mgr.all_steps())

    def restore(self, step: int, template: Any = None,
                broadcast: bool = True, root_rank: int = 0) -> Any:
        ocp = _orbax()
        args = ocp.args.StandardRestore(template) if template is not None \
            else ocp.args.StandardRestore()
        state = self._mgr.restore(step, args=args)
        if broadcast:
            state = _broadcast_if_distributed(state, root_rank)
        return state

    def restore_latest(self, template: Any = None, broadcast: bool = True,
                       root_rank: int = 0) -> Optional[Any]:
        step = self.latest_step()
        if step is None:
            return None
        return self.restore(step, template=template, broadcast=broadcast,
                            root_rank=root_rank)

    def close(self):
        self._mgr.close()


def _broadcast_if_distributed(state: Any, root_rank: int) -> Any:
    import horovod_tpu as hvt

    # standalone restore (inference, pre-init tooling) is a no-op; the
    # broadcast only applies inside an initialized multi-process job
    if not hvt.is_initialized() or hvt.size() <= 1:
        return state
    from horovod_tpu.ops.functions import broadcast_parameters

    return broadcast_parameters(state, root_rank=root_rank)


def save(path: str, state: Any):
    """One-shot synchronous save (no manager bookkeeping)."""
    ocp = _orbax()
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(os.path.abspath(path), state, force=True)
    ckptr.wait_until_finished()
    ckptr.close()


def restore(path: str, template: Any = None, broadcast: bool = True,
            root_rank: int = 0) -> Any:
    """One-shot restore + broadcast."""
    ocp = _orbax()
    ckptr = ocp.StandardCheckpointer()
    state = ckptr.restore(os.path.abspath(path), template)
    ckptr.close()
    if broadcast:
        state = _broadcast_if_distributed(state, root_rank)
    return state
