"""``horovod_tpu.compression`` — the quantized wire-codec subsystem.

Python face of the engine's wire-codec registry (``csrc/codecs.{h,cc}``):
block-scaled int8/fp8 and bf16 codecs for the eager data plane's TCP
links, selected per link class (EQuARX-style — quantize the inter-host
hops, keep intra-host traffic full precision) and compensated by
per-tensor error-feedback residuals so repeated quantization does not
bias training. Configure with ``HVT_WIRE_COMPRESSION`` (a codec name,
an ``"<intra>,<inter>"`` pair, or ``auto``); see
``docs/performance.md`` § "Wire compression: the codec subsystem".

Distinct from the framework-level gradient compressors
(``hvt.Compression`` / ``horovod_tpu.{tensorflow,torch}.compression``),
which cast tensors *before* submission: wire codecs are transparent to
callers and exist only on the wire.

:data:`CODEC_IDS` is the codec name ↔ wire-id table, kept in lockstep
with the C++ registry (``codecs.h`` ``HVT_WIRE_CODECS``) and the
``docs/performance.md`` codec table by the ``codecs`` pass of
``tools/hvt_lint.py``.
"""

from __future__ import annotations

# codec name -> WireCodec wire id (csrc/codecs.h registry order)
CODEC_IDS = {"none": 0, "bf16": 1, "int8": 2, "fp8": 3}

# wire id -> name (index == id)
CODEC_NAMES = tuple(sorted(CODEC_IDS, key=CODEC_IDS.get))


def codec_id(name: str) -> int:
    """WireCodec wire id for a codec name (``"raw"``/``""`` alias
    ``"none"``). Raises ``ValueError`` for unknown names."""
    if name in ("", "raw"):
        return 0
    if name not in CODEC_IDS:
        raise ValueError(
            f"unknown wire codec {name!r} (known: {CODEC_NAMES})")
    return CODEC_IDS[name]


def codec_name(wire_id: int) -> str:
    """Codec name for a WireCodec wire id; unknown ids (a newer .so)
    render as ``"codec<id>"`` rather than raising."""
    if 0 <= wire_id < len(CODEC_NAMES):
        return CODEC_NAMES[wire_id]
    return f"codec{wire_id}"


def wire_pair() -> tuple:
    """The engine's current ``(intra, inter)`` codec-name pair — which
    codec intra-host links and cross-host links move, e.g.
    ``("none", "int8")`` under ``HVT_WIRE_COMPRESSION=none,int8``.
    Under ``auto`` the pair reflects rank 0's latest tuner picks.
    ``("none", "none")`` when the engine is absent."""
    from horovod_tpu.engine import native

    intra, inter, _auto = native.wire_compression()
    return (codec_name(intra), codec_name(inter))


def auto_active() -> bool:
    """True while ``HVT_WIRE_COMPRESSION=auto`` drives codec selection
    (rank 0 samples candidates per (size, link class) and locks the
    byte-throughput argmax)."""
    from horovod_tpu.engine import native

    return native.wire_compression()[2]


def tx_bytes(op: str = None) -> dict:
    """TCP data-plane bytes sent per codec (exact counters from the
    engine's stats block — the source of
    ``hvt_wire_tx_bytes_total{op,codec}``). With ``op`` (an engine op
    name, e.g. ``"allreduce"``): ``{codec: bytes}`` for that op;
    without: ``{codec: {op: bytes}}``. ``{}`` when the engine is
    absent."""
    from horovod_tpu.engine import native

    by_codec = (native.engine_stats() or {}).get("codec_tx_bytes", {})
    if op is None:
        return by_codec
    return {codec: ops.get(op, 0) for codec, ops in by_codec.items()}
