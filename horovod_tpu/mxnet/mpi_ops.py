"""MXNet collective surface (reference ``horovod/mxnet/mpi_ops.py``:
allreduce:56, allreduce_:101, grouped_allreduce:140, allgather:232,
broadcast:272, broadcast_:315, alltoall:348 — each takes a ``priority``
hint for MXNet's async engine).

Transport: the engine data plane through the framework-neutral numpy
bridge (``ops.collective_ops``), the same layering as the TF binding's
fallback path. MXNet NDArrays are duck-typed — anything exposing
``.asnumpy()`` (real ``mx.nd.NDArray`` or the fakes in the gated tests)
round-trips; plain numpy arrays pass straight through. ``priority`` is
accepted for API compatibility; the engine's cycle negotiation replaces
MXNet's priority-queued async engine, so it is advisory only.
"""

from __future__ import annotations

import numpy as np

try:
    import mxnet as _mx
    _MX_AVAILABLE = True
except ImportError:
    _mx = None
    _MX_AVAILABLE = False


def _to_numpy(tensor):
    if hasattr(tensor, "asnumpy"):
        return tensor.asnumpy()
    return np.asarray(tensor)


def _like(arr, like):
    """Rebuild the caller's tensor type around a numpy result."""
    if _MX_AVAILABLE and isinstance(like, _mx.nd.NDArray):
        return _mx.nd.array(arr, ctx=like.context, dtype=arr.dtype)
    if hasattr(like, "asnumpy") and hasattr(type(like), "from_numpy"):
        return type(like).from_numpy(arr)  # duck-typed fakes
    return arr


def _assign(dst, arr):
    """In-place variants: write the result back into the caller's tensor."""
    if hasattr(dst, "asnumpy") and hasattr(dst, "__setitem__"):
        dst[:] = _like(arr, dst) if _MX_AVAILABLE and isinstance(
            dst, _mx.nd.NDArray) else arr
        return dst
    np.copyto(dst, arr)
    return dst


def allreduce(tensor, average=True, name=None, priority=0,
              prescale_factor=1.0, postscale_factor=1.0, process_set=None):
    del priority
    from horovod_tpu.ops import collective_ops as C

    out = C.allreduce(_to_numpy(tensor),
                      op=C.Average if average else C.Sum,
                      name=name or "mx.allreduce",
                      prescale_factor=prescale_factor,
                      postscale_factor=postscale_factor,
                      process_set=process_set or C.global_process_set)
    return _like(np.asarray(out), tensor)


def allreduce_(tensor, average=True, name=None, priority=0,
               prescale_factor=1.0, postscale_factor=1.0,
               process_set=None):
    """In-place allreduce (reference ``mpi_ops.py:101``)."""
    out = allreduce(tensor, average=average, name=name, priority=priority,
                    prescale_factor=prescale_factor,
                    postscale_factor=postscale_factor,
                    process_set=process_set)
    return _assign(tensor, _to_numpy(out))


def grouped_allreduce(tensors, average=True, name=None, priority=0,
                      prescale_factor=1.0, postscale_factor=1.0,
                      process_set=None):
    del priority
    from horovod_tpu.ops import collective_ops as C

    outs = C.grouped_allreduce(
        [_to_numpy(t) for t in tensors],
        op=C.Average if average else C.Sum,
        name=name or "mx.grouped_allreduce",
        prescale_factor=prescale_factor,
        postscale_factor=postscale_factor,
        process_set=process_set or C.global_process_set)
    return [_like(np.asarray(o), t) for o, t in zip(outs, tensors)]


def grouped_allreduce_(tensors, average=True, name=None, priority=0,
                       prescale_factor=1.0, postscale_factor=1.0,
                       process_set=None):
    outs = grouped_allreduce(tensors, average=average, name=name,
                             priority=priority,
                             prescale_factor=prescale_factor,
                             postscale_factor=postscale_factor,
                             process_set=process_set)
    for t, o in zip(tensors, outs):
        _assign(t, _to_numpy(o))
    return tensors


def allgather(tensor, name=None, priority=0, process_set=None):
    del priority
    from horovod_tpu.ops import collective_ops as C

    out = C.allgather(_to_numpy(tensor), name=name or "mx.allgather",
                      process_set=process_set or C.global_process_set)
    return _like(np.asarray(out), tensor)


def broadcast(tensor, root_rank, name=None, priority=0, process_set=None):
    del priority
    from horovod_tpu.ops import collective_ops as C

    out = C.broadcast(_to_numpy(tensor), root_rank=root_rank,
                      name=name or "mx.broadcast",
                      process_set=process_set or C.global_process_set)
    return _like(np.asarray(out), tensor)


def broadcast_(tensor, root_rank, name=None, priority=0, process_set=None):
    out = broadcast(tensor, root_rank, name=name, priority=priority,
                    process_set=process_set)
    return _assign(tensor, _to_numpy(out))


def alltoall(tensor, splits=None, name=None, priority=0, process_set=None):
    """Returns (output, received_splits)."""
    del priority
    from horovod_tpu.ops import collective_ops as C

    out, recv = C.alltoall(
        _to_numpy(tensor),
        splits=None if splits is None else np.asarray(_to_numpy(splits)),
        name=name or "mx.alltoall",
        process_set=process_set or C.global_process_set)
    return _like(np.asarray(out), tensor), np.asarray(recv)
