"""MXNet binding (reference ``horovod/mxnet/__init__.py``:
DistributedOptimizer:40, Gluon DistributedTrainer:102,
broadcast_parameters:191, plus the ``mpi_ops`` collective surface).

MXNet is end-of-life (retired from Apache incubation) and not installed
in TPU images, so this binding is **gated** the same way as the Ray/Spark
integrations: the collective plumbing, optimizer wrapper, and parameter
broadcast are framework-agnostic (duck-typed NDArrays — anything with
``.asnumpy()``; plain numpy passes through) and fully tested with fakes,
while the Gluon ``DistributedTrainer`` subclass materializes only when
``import mxnet`` succeeds. First-class TPU training lives in
``horovod_tpu.jax``; ``horovod_tpu.torch`` is the eager analog.
"""

from __future__ import annotations

from horovod_tpu.ops.functions import (allgather_object,  # noqa: F401
                                       broadcast_object,
                                       broadcast_object_fn)
from horovod_tpu.common.basics import (cross_rank, cross_size,  # noqa: F401
                                       init, is_initialized, local_rank,
                                       local_size, rank, shutdown, size)
from horovod_tpu.mxnet.mpi_ops import (_MX_AVAILABLE, allgather,  # noqa: F401
                                       allreduce, allreduce_, alltoall,
                                       broadcast, broadcast_,
                                       grouped_allreduce,
                                       grouped_allreduce_)


from horovod_tpu.common.util import split_list as _split_list


class DistributedOptimizer:
    """Wrap an MXNet-style optimizer: every ``update`` first sums the
    gradient across workers in place (reference ``mxnet/__init__.py:40``).

    Averaging is folded into the optimizer's ``rescale_grad`` (scaled by
    ``gradient_predivide_factor / size()``) instead of an explicit
    postscale — the reference does the same for performance. ``num_groups``
    > 0 batches gradients into grouped (engine-fused) allreduces.

    Duck-typed: the inner optimizer needs ``rescale_grad`` and
    ``update(index, weight, grad, state)`` (+ optional
    ``update_multi_precision``); gradients need ``.asnumpy()`` or to be
    numpy arrays.
    """

    def __init__(self, optimizer, gradient_predivide_factor=1.0,
                 num_groups=0):
        self._optimizer = optimizer
        self._optimizer.rescale_grad *= gradient_predivide_factor / size()
        self._gradient_predivide_factor = gradient_predivide_factor
        self._num_groups = num_groups

    def __getattr__(self, item):
        return getattr(self._optimizer, item)

    def _do_allreduce(self, index, grad):
        # no size()==1 shortcut: the 1/predivide prescale must still apply
        # to compensate the predivide folded into rescale_grad (the
        # single-process eager path applies prescale locally)
        pre = 1.0 / self._gradient_predivide_factor
        if isinstance(index, (tuple, list)):
            if self._num_groups > 0:
                for i, (grads, indices) in enumerate(zip(
                        _split_list(grad, self._num_groups),
                        _split_list(index, self._num_groups))):
                    grouped_allreduce_(
                        tensors=grads, average=False,
                        name=f"mx.{indices[0]}:{indices[-1]}", priority=-i,
                        prescale_factor=pre)
            else:
                for i in range(len(index)):
                    allreduce_(grad[i], average=False,
                               name=f"mx.{index[i]}", priority=-i,
                               prescale_factor=pre)
        else:
            allreduce_(grad, average=False, name=f"mx.{index}",
                       prescale_factor=pre)

    def update(self, index, weight, grad, state):
        self._do_allreduce(index, grad)
        self._optimizer.update(index, weight, grad, state)

    def update_multi_precision(self, index, weight, grad, state):
        self._do_allreduce(index, grad)
        self._optimizer.update_multi_precision(index, weight, grad, state)

    def set_learning_rate(self, lr):
        self._optimizer.set_learning_rate(lr)


def _allreduce_trainer_grads(params, gradient_predivide_factor=1.0,
                             num_groups=0, prefix=""):
    """Core of ``DistributedTrainer._allreduce_grads`` (reference
    ``mxnet/__init__.py:147``): in-place SUM over every trainable
    parameter's gradient, named by position (MXNet 2.0 parameter names
    are not unique), grouped when ``num_groups`` > 0.

    ``params``: iterable of objects with ``grad_req`` and ``list_grad()``
    (Gluon Parameters or the fakes in the gated tests). Runs even at
    size()==1 so the 1/predivide prescale always compensates the
    predivide folded into the trainer's ``_scale``."""
    pre = 1.0 / gradient_predivide_factor
    entries = [(i, p.list_grad()[0]) for i, p in enumerate(params)
               if p.grad_req != "null"]
    if num_groups > 0:
        for gi, group in enumerate(_split_list(entries, num_groups)):
            idxs = [i for i, _ in group]
            grouped_allreduce_(
                tensors=[g for _, g in group], average=False,
                name=f"{prefix}{idxs[0]}:{idxs[-1]}", priority=-gi,
                prescale_factor=pre)
    else:
        for i, g in entries:
            allreduce_(g, average=False, name=f"{prefix}{i}", priority=-i,
                       prescale_factor=pre)


if _MX_AVAILABLE:
    import mxnet as _mx

    class DistributedTrainer(_mx.gluon.Trainer):
        """Gluon trainer whose gradient exchange is the engine allreduce
        instead of kvstore push/pull (reference ``mxnet/__init__.py:102``;
        summation here, averaging folded into ``_scale``)."""

        def __init__(self, params, optimizer, optimizer_params=None,
                     gradient_predivide_factor=1.0, prefix=None,
                     num_groups=0):
            if isinstance(optimizer, DistributedOptimizer):
                # unfold the averaging DistributedOptimizer.__init__ baked
                # into rescale_grad — the trainer folds its own factor
                # into _scale below; leaving both would divide by size²
                optimizer._optimizer.rescale_grad /= (
                    optimizer._gradient_predivide_factor / size())
                optimizer = optimizer._optimizer
            super().__init__(params, optimizer, optimizer_params,
                             kvstore=None)
            self._scale *= gradient_predivide_factor / size()
            self._gradient_predivide_factor = gradient_predivide_factor
            self._hvt_prefix = prefix or ""
            self._num_groups = num_groups

        def _allreduce_grads(self):
            _allreduce_trainer_grads(
                self._params,
                gradient_predivide_factor=self._gradient_predivide_factor,
                num_groups=self._num_groups, prefix=self._hvt_prefix)
else:
    class DistributedTrainer:  # pragma: no cover - gated surface
        """Unavailable without MXNet; raises with migration guidance."""

        def __init__(self, *a, **kw):
            raise ImportError(
                "mxnet is not installed; DistributedTrainer requires "
                "Gluon. Use horovod_tpu.jax.DistributedOptimizer "
                "(TPU-compiled) or horovod_tpu.torch.DistributedOptimizer "
                "(eager). The gradient-exchange core is available as "
                "horovod_tpu.mxnet._allreduce_trainer_grads.")


def broadcast_parameters(params, root_rank=0, prefix=None):
    """Broadcast a dict of parameters from ``root_rank`` (reference
    ``mxnet/__init__.py:191`` — typical input is
    ``Block.collect_params()``). Entries may be Gluon Parameters
    (``.data()`` / ``.set_data``), NDArray-likes, or numpy arrays;
    results are written back in place. ``prefix`` namespaces tensor
    names when called more than once."""
    if size() == 1:
        return
    prefix = prefix or ""
    for name in sorted(params):
        p = params[name]
        if hasattr(p, "data") and callable(p.data):
            tensor = p.data()
            out = broadcast(tensor, root_rank=root_rank,
                            name=f"{prefix}{name}")
            if hasattr(p, "set_data"):
                p.set_data(out)
            else:  # NDArray-style in-place
                tensor[:] = out
        else:
            broadcast_(p, root_rank=root_rank, name=f"{prefix}{name}")
