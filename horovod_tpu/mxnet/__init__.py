"""MXNet compatibility stub.

The reference binds MXNet (``horovod/mxnet``: DistributedOptimizer,
Gluon DistributedTrainer, broadcast_parameters). MXNet is end-of-life
(retired from Apache incubation) and is not part of the TPU-native
target; training paths are ``horovod_tpu.jax`` (compiled) and
``horovod_tpu.torch`` (eager/hooks). This module exists so
``import horovod_tpu.mxnet`` fails with guidance rather than
AttributeError deep in user code."""

from __future__ import annotations

_MSG = ("horovod_tpu does not bind MXNet; use horovod_tpu.jax "
        "(TPU-compiled) or horovod_tpu.torch (eager). The reference's "
        "MXNet API maps 1:1: DistributedOptimizer → "
        "hvt.jax.DistributedOptimizer / hvt.torch.DistributedOptimizer, "
        "broadcast_parameters → hvt.torch.broadcast_parameters.")


def __getattr__(name):
    raise NotImplementedError(_MSG)
