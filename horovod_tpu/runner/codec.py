"""base64 ⇄ pickled-object codec for passing callables/config through
environment variables and command lines (reference
``horovod/runner/common/util/codec.py``). Uses cloudpickle so closures
and lambdas survive the trip."""

from __future__ import annotations

import base64

import cloudpickle


def dumps_base64(obj) -> str:
    return base64.b64encode(cloudpickle.dumps(obj)).decode("ascii")


def loads_base64(encoded: str):
    return cloudpickle.loads(base64.b64decode(encoded.encode("ascii")))
