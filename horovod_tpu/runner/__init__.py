"""Launcher package. ``run()`` is the programmatic API (reference
``horovod/runner/__init__.py:91``); the CLI lives in ``launch.py``."""


def __getattr__(name):
    # lazy: keeps cloudpickle (used only by run()) out of the import
    # path of the CLI and of MPI-placed workers
    if name == "run":
        from horovod_tpu.runner.api import run

        return run
    raise AttributeError(name)
