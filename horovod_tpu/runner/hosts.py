"""Host/slot parsing and rank assignment (reference
``horovod/runner/common/util/hosts.py``: ``parse_hosts``,
``get_host_assignments:100`` packing ranks onto host slots)."""

from __future__ import annotations

import dataclasses
from typing import List


@dataclasses.dataclass
class HostInfo:
    hostname: str
    slots: int


@dataclasses.dataclass
class SlotInfo:
    hostname: str
    rank: int
    local_rank: int
    cross_rank: int
    size: int
    local_size: int
    cross_size: int


def parse_hosts(hosts_string: str) -> List[HostInfo]:
    """Parse ``host1:2,host2:4`` (reference hosts.py parse_hosts)."""
    out = []
    for part in hosts_string.split(","):
        part = part.strip()
        if not part:
            continue
        if ":" in part:
            name, slots = part.rsplit(":", 1)
            out.append(HostInfo(name, int(slots)))
        else:
            out.append(HostInfo(part, 1))
    return out


def parse_hostfile(path: str) -> List[HostInfo]:
    """Hostfile lines: ``hostname slots=N`` (mpirun style) or ``host:N``."""
    out = []
    with open(path) as f:
        for line in f:
            line = line.split("#")[0].strip()
            if not line:
                continue
            if "slots=" in line:
                name, _, rest = line.partition(" ")
                slots = int(rest.split("slots=")[1].split()[0])
                out.append(HostInfo(name.strip(), slots))
            else:
                out.extend(parse_hosts(line))
    return out


def slot_env_vars(slot: SlotInfo) -> dict:
    """The HVT_* identity env for one slot — single source of truth for
    every launch path (hvtrun ssh, Ray actors, Spark barrier tasks)."""
    return {
        "HVT_PROCESS_ID": str(slot.rank),
        "HVT_NUM_PROCESSES": str(slot.size),
        "HVT_LOCAL_PROCESS_ID": str(slot.local_rank),
        "HVT_LOCAL_SIZE": str(slot.local_size),
        "HVT_CROSS_RANK": str(slot.cross_rank),
        "HVT_CROSS_SIZE": str(slot.cross_size),
        "HVT_HOSTNAME": slot.hostname,
    }


def get_host_assignments(hosts: List[HostInfo], np: int) -> List[SlotInfo]:
    """Pack ``np`` ranks onto host slots in host order, producing
    rank/local_rank/cross_rank per slot (reference hosts.py:100).

    cross_rank: index of the host among hosts that have at least one rank
    at this local_rank — matching the reference's cross-communicator
    construction for hierarchical ops.
    """
    total = sum(h.slots for h in hosts)
    if np > total:
        raise ValueError(
            f"requested -np {np} exceeds available slots {total} "
            f"({','.join(f'{h.hostname}:{h.slots}' for h in hosts)})")
    slots: List[SlotInfo] = []
    rank = 0
    used_hosts = []
    for h in hosts:
        if rank >= np:
            break
        n_here = min(h.slots, np - rank)
        used_hosts.append((h.hostname, n_here))
        for lr in range(n_here):
            slots.append(SlotInfo(hostname=h.hostname, rank=rank,
                                  local_rank=lr, cross_rank=0, size=np,
                                  local_size=n_here,
                                  cross_size=0))
            rank += 1
    # fill cross ranks: for each local_rank, hosts having that slot
    for s in slots:
        peers = [h for h, n in used_hosts if n > s.local_rank]
        s.cross_rank = peers.index(s.hostname)
        s.cross_size = len(peers)
    return slots
