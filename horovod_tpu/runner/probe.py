"""Pre-launch driver/task probe (reference
``horovod/runner/driver/driver_service.py:162`` ``_driver_fn`` +
``runner/task_fn.py:23``): before the real job starts, a small task
service runs on every host; each registers its host hash and NIC
addresses with the driver, then probes the NEXT host's addresses in a
ring so one-way/NAT'ed interfaces are weeded out; the driver intersects
the per-link results into the common reachable address set used for the
rendezvous.

Messages are HMAC-signed with the per-job secret key (reference
service messages do the same)."""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional

from horovod_tpu.runner import network, secret


class _SignedHandler(BaseHTTPRequestHandler):
    key: bytes = b""

    def _read_signed(self) -> Optional[dict]:
        n = int(self.headers.get("Content-Length", 0))
        body = self.rfile.read(n)
        try:
            digest = bytes.fromhex(self.headers.get("X-HVT-Digest", ""))
        except ValueError:
            # malformed (non-hex / odd-length) digest header is a failed
            # authentication, not a server error
            digest = b""
        if not secret.check_digest(self.key, body, digest):
            self.send_response(403)
            self.end_headers()
            return None
        return json.loads(body)

    def _send_json(self, obj, code=200):
        body = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *a):
        pass


def _signed_request(addr: str, path: str, obj: dict, key: bytes,
                    timeout: float = 5.0) -> dict:
    import urllib.request

    body = json.dumps(obj).encode()
    req = urllib.request.Request(
        f"http://{addr}{path}", data=body, method="PUT",
        headers={"X-HVT-Digest": secret.compute_digest(key, body).hex()})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        data = resp.read()
        return json.loads(data) if data else {}


class TaskService:
    """Runs on each candidate host; answers probe requests."""

    def __init__(self, index: int, key: bytes, salt: str = ""):
        self._index = index
        self._key = key
        self._salt = salt
        self._server = None
        self.port = None

    def start(self) -> int:
        svc = self

        class Handler(_SignedHandler):
            key = svc._key

            def do_PUT(self):
                msg = self._read_signed()
                if msg is None:
                    return
                if msg.get("cmd") == "info":
                    from horovod_tpu.runner.host_hash import host_hash

                    self._send_json({
                        "index": svc._index,
                        "host_hash": host_hash(svc._salt),
                        "addresses": network.local_addresses(),
                        "interfaces": network.get_local_interfaces(),
                    })
                elif msg.get("cmd") == "probe":
                    ok = network.probe_reachable(
                        msg["addresses"], int(msg["port"]),
                        timeout=float(msg.get("timeout", 2.0)))
                    self._send_json({"reachable": ok})
                else:
                    self._send_json({"error": "unknown cmd"}, 400)

        self._server = ThreadingHTTPServer(("0.0.0.0", 0), Handler)
        self.port = self._server.server_address[1]
        threading.Thread(target=self._server.serve_forever,
                         daemon=True).start()
        return self.port

    def stop(self):
        if self._server:
            self._server.shutdown()
            self._server = None


class DriverProbe:
    """Launcher-side: given the task services' addresses, collect host
    info and run the ring probe."""

    def __init__(self, key: bytes):
        self._key = key

    def collect_info(self, task_addrs: List[str]) -> List[dict]:
        return [_signed_request(a, "/", {"cmd": "info"}, self._key)
                for a in task_addrs]

    def ring_probe(self, task_addrs: List[str],
                   infos: List[dict]) -> Dict[str, List[str]]:
        """Task i probes task (i+1)'s addresses on (i+1)'s service port.
        Returns per-link reachable addresses keyed by the probed task
        index."""
        n = len(task_addrs)
        out: Dict[str, List[str]] = {}
        for i in range(n):
            nxt = (i + 1) % n
            port = int(task_addrs[nxt].rsplit(":", 1)[1])
            resp = _signed_request(
                task_addrs[i], "/",
                {"cmd": "probe", "addresses": infos[nxt]["addresses"],
                 "port": port}, self._key)
            out[str(nxt)] = resp.get("reachable", [])
        return out

    def common_interfaces(self, task_addrs: List[str]) -> List[str]:
        """Interface NAMES usable on every host: a NIC counts for a host
        when at least one of its addresses was reachable from the
        previous host in the ring. Hosts have different IPs, so the
        intersection is over names, matching the reference's
        get_common_interfaces (driver_service.py:218)."""
        infos = self.collect_info(task_addrs)
        links = self.ring_probe(task_addrs, infos)
        per_host_nics = {}
        for idx_str, reachable in links.items():
            info = infos[int(idx_str)]
            nics = {name for name, ips in info["interfaces"].items()
                    if any(ip in reachable for ip in ips)}
            per_host_nics[idx_str] = nics
        sets = list(per_host_nics.values())
        return sorted(set.intersection(*sets)) if sets else []

    def reachable_addresses(self, task_addrs: List[str]
                            ) -> Dict[str, List[str]]:
        """Per-host reachable addresses (keyed by task index) — what the
        rendezvous should advertise for each host."""
        infos = self.collect_info(task_addrs)
        return self.ring_probe(task_addrs, infos)


def wait_for_service(addr: str, timeout: float = 30.0) -> bool:
    host, port = addr.rsplit(":", 1)
    deadline = time.time() + timeout
    while time.time() < deadline:
        if network.can_connect(host, int(port), timeout=1.0):
            return True
        time.sleep(0.2)
    return False
