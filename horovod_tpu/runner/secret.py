"""HMAC signing of launcher control messages (reference
``horovod/runner/common/util/secret.py``). The launcher generates one key
per job; driver/task services reject unsigned or tampered payloads."""

from __future__ import annotations

import hashlib
import hmac
import os

DIGEST = hashlib.sha256


def make_secret_key() -> bytes:
    return os.urandom(32)


def compute_digest(secret_key: bytes, payload: bytes) -> bytes:
    return hmac.new(secret_key, payload, DIGEST).digest()


def check_digest(secret_key: bytes, payload: bytes, digest: bytes) -> bool:
    return hmac.compare_digest(compute_digest(secret_key, payload), digest)
