"""YAML config-file support for hvtrun (reference
``horovod/common/util/config_parser.py`` + ``launch.py:293``
--config-file): every CLI knob can come from a YAML file; explicit CLI
flags win over file values."""

from __future__ import annotations

import argparse
from typing import Optional

# YAML key → argparse dest, mirroring the reference's key set where a
# TPU-native equivalent exists
_KEYS = {
    "verbose": "verbose",
    "master-port": "master_port",
    "ssh-port": "ssh_port",
    "cycle-time-ms": "cycle_time_ms",
    "fusion-threshold-mb": "fusion_threshold_mb",
    "timeline": "timeline",
    "stall-warning-sec": "stall_warning_sec",
    "autotune": "autotune",
    "autotune-log-file": "autotune_log_file",
    "min-np": "min_np",
    "max-np": "max_np",
    "host-discovery-script": "host_discovery_script",
    "reset-limit": "reset_limit",
    "elastic-timeout": "elastic_timeout",
    "slots": "slots",
    "backend": "backend",
}


def load_config(path: str) -> dict:
    import yaml

    with open(path) as f:
        data = yaml.safe_load(f) or {}
    if not isinstance(data, dict):
        raise ValueError(f"config file {path} must be a YAML mapping")
    unknown = [k for k in data if k not in _KEYS]
    if unknown:
        raise ValueError(
            f"unknown config keys {unknown}; valid: {sorted(_KEYS)}")
    return {_KEYS[k]: v for k, v in data.items()}


def apply_config(args: argparse.Namespace, path: Optional[str],
                 parser: argparse.ArgumentParser) -> argparse.Namespace:
    """Fill args from the YAML file, but only where the CLI left the
    parser default (explicit flags always win — reference override-action
    semantics, launch.py:158)."""
    if not path:
        return args
    for dest, value in load_config(path).items():
        if getattr(args, dest, None) == parser.get_default(dest):
            setattr(args, dest, value)
    return args
