"""Robust child process management (reference
``horovod/runner/common/util/safe_shell_exec.py``: fork + process-group
kill, event-driven termination, stdout/err forwarding, parent-death
safety so a SIGKILLed launcher never leaks workers)."""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import threading
import time

_PR_SET_PDEATHSIG = 1  # linux/prctl.h

# Resolve libc at import time: preexec_fn runs between fork() and exec()
# where taking the import/allocator locks can deadlock a child forked
# from a multithreaded launcher (subprocess docs' preexec warning).
try:
    import ctypes as _ctypes

    _libc = _ctypes.CDLL(None, use_errno=True)
    _libc.prctl  # resolve the symbol now, not post-fork
except Exception:  # pragma: no cover - non-linux
    _libc = None


def _child_preexec():
    """Runs in the forked child before exec: new session (own process
    group, so terminate() can killpg) + PDEATHSIG so the kernel delivers
    SIGTERM to the child if the launcher dies — including SIGKILL, which
    the launcher cannot catch to clean up itself (reference
    safe_shell_exec.py:60-140 achieves this with a middleman process;
    prctl covers the same contract without one). PR_SET_PDEATHSIG
    survives execve, so arbitrary worker commands are covered.

    Note: the kernel ties PDEATHSIG to the spawning THREAD — callers must
    spawn from a thread that outlives the child (both launcher paths do:
    run_all spawns from the main thread; the elastic per-slot threads
    block on child.wait())."""
    os.setsid()
    if _libc is not None:
        _libc.prctl(_PR_SET_PDEATHSIG, signal.SIGTERM, 0, 0, 0)


class Child:
    def __init__(self, cmd, env, tag=None, stdout=None):
        self.tag = tag
        self.proc = subprocess.Popen(
            cmd, env=env, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, preexec_fn=_child_preexec)
        self._pump = threading.Thread(target=self._forward,
                                      args=(stdout or sys.stdout,),
                                      daemon=True)
        self._pump.start()

    def _forward(self, out):
        prefix = f"[{self.tag}] " if self.tag is not None else ""
        for line in iter(self.proc.stdout.readline, b""):
            try:
                out.write(prefix + line.decode(errors="replace"))
                out.flush()
            except ValueError:
                break

    def poll(self):
        return self.proc.poll()

    def terminate(self, grace_sec=5.0):
        """SIGTERM the whole process group, then SIGKILL stragglers —
        the reference's event-driven termination semantics."""
        if self.proc.poll() is not None:
            return
        try:
            os.killpg(os.getpgid(self.proc.pid), signal.SIGTERM)
        except ProcessLookupError:
            return
        deadline = time.time() + grace_sec
        while time.time() < deadline:
            if self.proc.poll() is not None:
                return
            time.sleep(0.05)
        try:
            os.killpg(os.getpgid(self.proc.pid), signal.SIGKILL)
        except ProcessLookupError:
            pass

    def wait(self):
        rc = self.proc.wait()
        self._pump.join(timeout=2)
        return rc


def run_all(commands_envs_tags, on_first_failure_kill_rest=True):
    """Launch all children; wait; on first non-zero exit, terminate the
    rest (reference gloo_run.py:261-271 raises on first failure)."""
    children = [Child(cmd, env, tag) for cmd, env, tag in commands_envs_tags]
    exit_codes = [None] * len(children)
    try:
        pending = set(range(len(children)))
        while pending:
            for i in list(pending):
                rc = children[i].poll()
                if rc is not None:
                    exit_codes[i] = rc
                    pending.discard(i)
                    if rc != 0 and on_first_failure_kill_rest:
                        for j in pending:
                            children[j].terminate()
            time.sleep(0.05)
    except KeyboardInterrupt:
        for c in children:
            c.terminate()
        raise
    for i, c in enumerate(children):
        exit_codes[i] = c.wait() if exit_codes[i] is None else exit_codes[i]
    return exit_codes
