"""HTTP KV store + rendezvous server (reference
``horovod/runner/http/http_server.py``: KVStoreHandler PUT/GET with scoped
keys ``global`` / ``local_<host>`` / ``cross_<rank>``, RendezvousServer with
re-``init()`` for elastic re-rendezvous).

Used by the elastic driver: workers PUT their endpoints/state under scoped
keys and GET peers'; each elastic restart calls ``init`` with the new host
allocation, resetting the store. Static engine jobs rendezvous over the TCP
control star instead (csrc/engine.cc), so this server is the *driver-side*
coordination surface.
"""

from __future__ import annotations

import json
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

# KV scopes that survive round resets AND are worker-telemetry streams:
# these are the only scopes the TTL sweep prunes — a blacklisted/shed
# rank's final snapshot must eventually leave the rollup (reported as
# "stale" first, dropped after HVT_KV_TTL_SEC), while `workers`
# (notification registrations) and `timeline` (shards merged at job
# end) are never aged out.
SWEEP_SCOPES = ("serving", "debugz", "telemetry", "recovery")

# scopes kept across elastic round resets (init / DELETE /rendezvous).
# `recovery` (worker recovery-phase reports) is written *between*
# rounds — clearing it at init would erase exactly the reports the
# /statusz recovery rows exist to show; the TTL sweep ages them out.
KEEP_SCOPES = ("workers", "timeline", "debugz", "serving", "telemetry",
               "recovery")


class _Store:
    def __init__(self):
        self.lock = threading.Lock()
        self.scopes = {}
        # last-write monotonic timestamps per (scope, key): the /statusz
        # liveness source — SERVER-side stamps, so worker clock skew
        # can never fake freshness
        self.meta = {}
        # cumulative ingest accounting per scope (bytes, puts): the
        # telemetry-scaling benchmark's primary metric, and the
        # /statusz "ingest" self-accounting block. put_requests counts
        # HTTP requests (a /kvbulk batch is ONE request however many
        # entries it carries) — the elastic-recovery benchmark's
        # O(hosts)-not-O(ranks) fan-in metric.
        self.put_bytes = {}
        self.put_count = {}
        self.put_requests = {}

    def put(self, scope, key, value: bytes, now=None):
        now = time.monotonic() if now is None else now
        with self.lock:
            self.scopes.setdefault(scope, {})[key] = value
            self.meta.setdefault(scope, {})[key] = now
            self.put_bytes[scope] = (self.put_bytes.get(scope, 0)
                                     + len(value))
            self.put_count[scope] = self.put_count.get(scope, 0) + 1

    def note_request(self, scope, n=1):
        with self.lock:
            self.put_requests[scope] = (self.put_requests.get(scope, 0)
                                        + n)

    def get(self, scope, key):
        with self.lock:
            return self.scopes.get(scope, {}).get(key)

    def keys(self, scope):
        with self.lock:
            return list(self.scopes.get(scope, {}).keys())

    def age(self, scope, key, now=None):
        """Seconds since the key was last written (None = never)."""
        now = time.monotonic() if now is None else now
        with self.lock:
            t = self.meta.get(scope, {}).get(key)
        return None if t is None else max(0.0, now - t)

    def ages(self, scope, now=None):
        now = time.monotonic() if now is None else now
        with self.lock:
            return {k: max(0.0, now - t)
                    for k, t in self.meta.get(scope, {}).items()}

    def ingest_stats(self):
        with self.lock:
            return {"put_bytes": dict(self.put_bytes),
                    "put_count": dict(self.put_count),
                    "put_requests": dict(self.put_requests)}

    def sweep(self, ttl_sec, scopes=SWEEP_SCOPES, now=None):
        """Drop entries not rewritten for ``ttl_sec`` from the
        telemetry-stream scopes; returns the removed (scope, key)
        pairs. The staleness-hygiene half of /statusz: without it the
        kept-across-rounds scopes replay a dead rank's final snapshot
        forever (the footgun the autoscaler's change-detection had to
        work around)."""
        if not ttl_sec or ttl_sec <= 0:
            return []
        now = time.monotonic() if now is None else now
        removed = []
        with self.lock:
            for scope in scopes:
                meta = self.meta.get(scope)
                if not meta:
                    continue
                for key, t in list(meta.items()):
                    if now - t > ttl_sec:
                        meta.pop(key, None)
                        self.scopes.get(scope, {}).pop(key, None)
                        removed.append((scope, key))
        return removed

    def clear(self, keep_scopes=()):
        with self.lock:
            self.scopes = {s: v for s, v in self.scopes.items()
                           if s in keep_scopes}
            self.meta = {s: v for s, v in self.meta.items()
                         if s in keep_scopes}


class RendezvousServer:
    """KV + slot-info rendezvous.

    Paths:
      PUT/GET /kv/<scope>/<key>         — raw bytes KV
      GET     /keys/<scope>             — JSON list of keys
      GET     /rendezvous/<host>/<local_rank> — JSON SlotInfo
      GET     /world                    — JSON {size, hosts}
      GET     /metrics                  — Prometheus text exposition
      GET     /metrics.json             — JSON metrics snapshot
      GET     /clock                    — server wall clock (epoch µs);
                                          the timeline clock-offset
                                          handshake samples this
      GET     /debugz                   — stall-diagnostics snapshot:
                                          world info + every worker's
                                          last hvt.diagnostics() report
                                          (pushed to /kv/debugz/<rank>)
      GET     /statusz                  — gang health rollup: per-rank
                                          liveness/lanes/links, host
                                          frames, straggler ranking,
                                          byte rates, health alerts
                                          (metrics/telemetry.py; the
                                          hvt_top data source)
      DELETE  /rendezvous               — finalize round (elastic)

    Worker-telemetry scopes (``serving``/``debugz``/``telemetry``) are
    server-timestamped on every PUT and TTL-swept after
    ``HVT_KV_TTL_SEC`` (default 120 s, 0 = off): a dead rank's final
    snapshot reads as "stale" in /statusz, then leaves the store.
    """

    def __init__(self, verbose=False, on_put=None):
        self._store = _Store()
        self._slots = {}
        self._world = {}
        self._server = None
        self._verbose = verbose
        self._round = 0
        self._on_put = on_put
        self._statusz = None  # lazy StatuszBuilder (metrics/telemetry)
        self._statusz_lock = threading.Lock()
        # optional fn(slots, round) -> int: the engine control-star port
        # for this round, published in world info so every worker (fresh
        # spawn or survivor re-syncing) agrees on it
        self.master_port_fn = None

    def set_put_hook(self, fn):
        """``fn(scope, key, value_bytes)`` called on every /kv PUT — the
        elastic driver uses this to receive worker state reports."""
        self._on_put = fn

    def init(self, slots):
        """(Re)initialize with a host allocation plan — one call per
        elastic rendezvous round (reference http_server.py:195). Worker
        notification registrations survive the reset — workers register
        once, at first state init. Each init bumps ``round`` so workers
        re-rendezvousing can tell fresh slot info from the previous
        round's."""
        # timeline/debugz survive re-rendezvous: shards from workers
        # torn down in round N must still be mergeable at job end
        # serving/telemetry join debugz as kept scopes: worker-pushed
        # stats streams must survive round resets or the autoscaler and
        # /statusz would go blind at exactly the rendezvous they caused
        # (the TTL sweep, not the round reset, is what ages them out)
        self._store.clear(keep_scopes=KEEP_SCOPES)
        self._round += 1
        self._slots = {
            f"{s.hostname}/{s.local_rank}": {
                "hostname": s.hostname, "rank": s.rank,
                "local_rank": s.local_rank, "cross_rank": s.cross_rank,
                "size": s.size, "local_size": s.local_size,
                "cross_size": s.cross_size, "round": self._round,
            } for s in slots
        }
        world = {"size": len(slots),
                 "hosts": sorted({s.hostname for s in slots}),
                 "master_host": slots[0].hostname if slots else None,
                 "round": self._round}
        if self.master_port_fn is not None and slots:
            world["master_port"] = int(
                self.master_port_fn(slots, self._round))
        # publish atomically, master_port included: a worker polling
        # /world between "round visible" and "port visible" would fall
        # back to the port-rotation guess and rendezvous into a
        # different engine port than its peers (split-gang init
        # failure, caught live by the recovery drive)
        self._world = world

    @property
    def round(self):
        return self._round

    def kv_ttl_sec(self) -> float:
        """TTL for the worker-telemetry scopes (HVT_KV_TTL_SEC; 0
        disables the sweep)."""
        try:
            return float(os.environ.get("HVT_KV_TTL_SEC", "") or 120.0)
        except ValueError:
            return 120.0

    def statusz_snapshot(self, now=None) -> dict:
        """The gang health rollup served at ``GET /statusz`` — also the
        autoscaler's alert feed. Sweeps expired telemetry entries
        first, so a dead rank reads as stale/absent rather than
        replaying its final snapshot."""
        from horovod_tpu.metrics import telemetry as _telemetry

        self._store.sweep(self.kv_ttl_sec(), now=now)
        with self._statusz_lock:
            if self._statusz is None:
                self._statusz = _telemetry.StatuszBuilder()
            return self._statusz.build(
                self._store, self._world, self._round, now=now,
                server_stats=self._store.ingest_stats())

    @property
    def world(self):
        return dict(self._world)

    def start(self, port=0) -> int:
        store, slots_ref, world_ref = self._store, self, self
        server_ref = self

        class Handler(BaseHTTPRequestHandler):
            def _send(self, code, body=b"", ctype="application/octet-stream"):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                if body:
                    self.wfile.write(body)

            def do_PUT(self):
                parts = self.path.strip("/").split("/")
                n = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(n)
                if len(parts) >= 3 and parts[0] == "kv":
                    store.note_request(parts[1])
                    store.put(parts[1], "/".join(parts[2:]), body)
                    hook = server_ref._on_put
                    if hook is not None:
                        try:
                            hook(parts[1], "/".join(parts[2:]), body)
                        except Exception:
                            pass
                    self._send(200)
                elif parts == ["kvbulk"]:
                    # leader-routed batch (metrics/telemetry.py relay):
                    # one request carrying many (scope, key, value_b64)
                    # entries — the door that keeps driver fan-in
                    # O(hosts) per elastic round. Entries land in the
                    # store and fire the put hook exactly as individual
                    # PUTs would.
                    import base64

                    try:
                        envs = json.loads(body)
                        assert isinstance(envs, list)
                    except (ValueError, AssertionError,
                            UnicodeDecodeError):
                        self._send(400)
                        return
                    scopes_seen = set()
                    accepted = 0
                    hook = server_ref._on_put
                    for env in envs:
                        try:
                            scope = str(env["scope"])
                            key = str(env["key"])
                            value = base64.b64decode(
                                env.get("value_b64") or "")
                        except (TypeError, KeyError, ValueError):
                            continue
                        if scope not in scopes_seen:
                            scopes_seen.add(scope)
                            store.note_request(scope)
                        store.put(scope, key, value)
                        accepted += 1
                        if hook is not None:
                            try:
                                hook(scope, key, value)
                            except Exception:
                                pass
                    self._send(200, json.dumps(
                        {"accepted": accepted}).encode(),
                        "application/json")
                else:
                    self._send(404)

            def do_HEAD(self):
                # existence probe for /kv paths: status + Content-Length
                # only, no body (HTTPStore.exists uses this so checking
                # a checkpoint's existence doesn't download it)
                parts = self.path.strip("/").split("/")
                if len(parts) >= 3 and parts[0] == "kv":
                    v = store.get(parts[1], "/".join(parts[2:]))
                    code, n = (404, 0) if v is None else (200, len(v))
                    self.send_response(code)
                    self.send_header("Content-Length", str(n))
                    self.end_headers()
                else:
                    self.send_response(404)
                    self.send_header("Content-Length", "0")
                    self.end_headers()

            def do_GET(self):
                parts = self.path.strip("/").split("/")
                if len(parts) >= 3 and parts[0] == "kv":
                    v = store.get(parts[1], "/".join(parts[2:]))
                    if v is None:
                        self._send(404)
                    else:
                        self._send(200, v)
                elif len(parts) == 2 and parts[0] == "keys":
                    self._send(200, json.dumps(
                        store.keys(parts[1])).encode(), "application/json")
                elif len(parts) == 3 and parts[0] == "rendezvous":
                    info = slots_ref._slots.get(f"{parts[1]}/{parts[2]}")
                    if info is None:
                        self._send(404)
                    else:
                        self._send(200, json.dumps(info).encode(),
                                   "application/json")
                elif parts == ["world"]:
                    self._send(200, json.dumps(world_ref._world).encode(),
                               "application/json")
                elif parts == ["clock"]:
                    import time

                    self._send(200, json.dumps(
                        {"epoch_us": time.time_ns() // 1000}).encode(),
                        "application/json")
                elif parts == ["debugz"]:
                    # stall-diagnostics endpoint: aggregate the per-rank
                    # hvt.diagnostics() snapshots workers push to
                    # /kv/debugz/<rank> (see common/basics.py _DebugzPusher)
                    server_ref._store.sweep(server_ref.kv_ttl_sec())
                    ranks = {}
                    for key in store.keys("debugz"):
                        v = store.get("debugz", key)
                        try:
                            ranks[key] = json.loads(v)
                        except Exception:
                            ranks[key] = {"error": "unparseable report"}
                    body = {"world": world_ref._world,
                            "round": server_ref._round,
                            "timeline_shards":
                                sorted(store.keys("timeline")),
                            # leader-aggregated gangs push host frames
                            # instead of per-rank debugz; point the
                            # reader at them (full rollup: /statusz)
                            "telemetry_hosts": sorted(
                                k[5:] for k in store.keys("telemetry")
                                if k.startswith("host/")),
                            "ranks": ranks}
                    self._send(200, json.dumps(body).encode(),
                               "application/json")
                elif parts == ["statusz"]:
                    # gang health rollup (metrics/telemetry.py): the
                    # one-view answer to "is the gang healthy, and if
                    # not, which rank/link/lane?" — hvt_top's feed
                    try:
                        body = server_ref.statusz_snapshot()
                    except Exception as e:
                        self._send(500, json.dumps(
                            {"error": repr(e)}).encode(),
                            "application/json")
                        return
                    self._send(200, json.dumps(body).encode(),
                               "application/json")
                elif parts in (["metrics"], ["metrics.json"]):
                    # Prometheus scrape surface on the driver-side server
                    # (horovod_tpu.metrics): the elastic driver's gauges
                    # plus whatever the launcher process itself recorded.
                    # Worker-side registries are served per worker via
                    # hvtrun --metrics-port (metrics.serve).
                    from horovod_tpu import metrics as _metrics

                    if parts == ["metrics"]:
                        self._send(200,
                                   _metrics.prometheus_text().encode(),
                                   _metrics.PROMETHEUS_CONTENT_TYPE)
                    else:
                        self._send(
                            200,
                            json.dumps(_metrics.json_snapshot()).encode(),
                            "application/json")
                else:
                    self._send(404)

            def do_DELETE(self):
                if self.path.strip("/") == "rendezvous":
                    store.clear(keep_scopes=KEEP_SCOPES)
                    self._send(200)
                else:
                    self._send(404)

            def log_message(self, *a):
                pass

        self._server = ThreadingHTTPServer(("0.0.0.0", port), Handler)
        threading.Thread(target=self._server.serve_forever,
                         daemon=True).start()
        return self._server.server_address[1]

    @property
    def port(self):
        return self._server.server_address[1] if self._server else None

    @property
    def store(self):
        return self._store

    def stop(self):
        if self._server:
            self._server.shutdown()
            self._server = None
