"""Worker entry for the programmatic ``run()`` API (reference
``horovod/runner/run_task.py``): loads the pickled function, initializes
the runtime, runs it, writes the per-rank result.

Fault injection (chaos harness): the Python-level half of
``HVT_FAULT_INJECT``. The C++ engine owns the op-count triggers
(``after_ops``, see csrc/engine.cc ParseFaultInject); this runner owns
the wall-clock trigger — ``kill:rank=R:after_sec=S`` arms a timer that
SIGKILLs the worker S seconds after init, simulating a host lost at an
arbitrary point (between collectives included). Used by the chaos gang
tests and ``ci.sh --chaos``.
"""

from __future__ import annotations

import os
import sys

import cloudpickle


def maybe_arm_fault_timer(rank: int, spec: str = None):
    """Arm the ``kill:rank=R:after_sec=S`` trigger of HVT_FAULT_INJECT
    for this process, if the spec names it. Returns the armed timer (a
    daemon Timer) or None. Specs with ``after_ops`` belong to the C++
    engine and are ignored here."""
    spec = spec if spec is not None else os.environ.get("HVT_FAULT_INJECT")
    if not spec or not spec.startswith("kill:"):
        return None
    fields = dict(
        f.split("=", 1) for f in spec.split(":")[1:] if "=" in f)
    if "after_sec" not in fields:
        return None  # op-count trigger: the engine owns it
    try:
        if int(fields.get("rank", -1)) != rank:
            return None
        delay = float(fields["after_sec"])
    except ValueError:
        return None
    import signal
    import threading

    def _die():
        print(f"[hvt rank {rank}] HVT_FAULT_INJECT: raising SIGKILL "
              f"after {delay} s", flush=True)
        os.kill(os.getpid(), signal.SIGKILL)

    t = threading.Timer(delay, _die)
    t.daemon = True
    t.start()
    return t


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    fn_path, out_dir = argv[0], argv[1]
    if os.environ.get("HVT_RUN_FORCE_CPU") == "1":
        import jax

        jax.config.update("jax_platforms", "cpu")
    from horovod_tpu.runner.codec import loads_base64

    with open(fn_path) as f:
        fn, args, kwargs = loads_base64(f.read())

    import horovod_tpu as hvt

    hvt.init()
    maybe_arm_fault_timer(hvt.rank())
    result = fn(*args, **kwargs)
    with open(os.path.join(out_dir, f"result_{hvt.rank()}.pkl"),
              "wb") as f:
        cloudpickle.dump(result, f)
    hvt.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
