"""Worker entry for the programmatic ``run()`` API (reference
``horovod/runner/run_task.py``): loads the pickled function, initializes
the runtime, runs it, writes the per-rank result."""

from __future__ import annotations

import os
import sys

import cloudpickle


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    fn_path, out_dir = argv[0], argv[1]
    if os.environ.get("HVT_RUN_FORCE_CPU") == "1":
        import jax

        jax.config.update("jax_platforms", "cpu")
    from horovod_tpu.runner.codec import loads_base64

    with open(fn_path) as f:
        fn, args, kwargs = loads_base64(f.read())

    import horovod_tpu as hvt

    hvt.init()
    result = fn(*args, **kwargs)
    with open(os.path.join(out_dir, f"result_{hvt.rank()}.pkl"),
              "wb") as f:
        cloudpickle.dump(result, f)
    hvt.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
