"""IBM LSF ``jsrun`` launch path (reference ``horovod/runner/js_run.py``
+ ``runner/util/lsf.py``): on LSF clusters the host list comes from
``LSB_MCPU_HOSTS``/``LSB_HOSTS`` and placement is delegated to jsrun
resource sets."""

from __future__ import annotations

import os
import shutil
import subprocess
import sys
from typing import Dict, List, Optional


def in_lsf_env(env: Optional[dict] = None) -> bool:
    env = os.environ if env is None else env
    return "LSB_JOBID" in env


def lsf_hosts(env: Optional[dict] = None) -> Dict[str, int]:
    """Parse LSF's host allocation. ``LSB_MCPU_HOSTS`` is
    ``host1 n1 host2 n2 ...``; fall back to counting ``LSB_HOSTS``
    entries. Batch/launch nodes are excluded like the reference."""
    env = os.environ if env is None else env
    hosts: Dict[str, int] = {}
    mcpu = env.get("LSB_MCPU_HOSTS", "")
    first_host = None
    if mcpu:
        toks = mcpu.split()
        for i in range(0, len(toks) - 1, 2):
            if first_host is None:
                first_host = toks[i]
            hosts[toks[i]] = int(toks[i + 1])
    else:
        for h in env.get("LSB_HOSTS", "").split():
            if first_host is None:
                first_host = h
            hosts[h] = hosts.get(h, 0) + 1
    # LSF lists the batch (launcher) host first; drop it by POSITION, not
    # by name — compute nodes may legitimately be named batch*
    if first_host is not None and len(hosts) > 1:
        hosts.pop(first_host, None)
    return hosts


def build_jsrun_command(np: int, command: List[str],
                        smpiargs: str = "-disable_gpu_hooks"
                        ) -> List[str]:
    """One resource set per rank (reference js_run.py builds
    ``jsrun -n<np> -a1 -cALL_CPUS -g<gpus>``; TPU hosts expose no GPUs so
    the resource set is CPU-only)."""
    cmd = ["jsrun", f"-n{np}", "-a1", "-cALL_CPUS"]
    if smpiargs:
        cmd += ["--smpiargs", smpiargs]
    cmd += command
    return cmd


def js_run(args, slots, master_addr: str) -> int:
    del slots  # placement is jsrun's job; identity comes from MPI env
    if shutil.which("jsrun") is None:
        print("[hvtrun] jsrun not found on PATH", file=sys.stderr)
        return 1
    env = dict(os.environ)
    env.update({
        "HVT_CYCLE_TIME_MS": str(args.cycle_time_ms),
        "HVT_FUSION_THRESHOLD": str(args.fusion_threshold_mb << 20),
        "HVT_FROM_MPI": "1",   # jsrun provides MPI-style rank env
    })
    if getattr(args, "backend", "engine") == "jax":
        env["HVT_COORDINATOR_ADDR"] = f"{master_addr}:{args.master_port}"
    else:
        env["HVT_MASTER_ADDR"] = master_addr
        env["HVT_MASTER_PORT"] = str(args.master_port)
    cmd = build_jsrun_command(args.num_proc, list(args.command))
    return subprocess.run(cmd, env=env).returncode
