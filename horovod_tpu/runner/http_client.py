"""Tiny HTTP KV client (reference ``horovod/runner/http/http_client.py``)."""

from __future__ import annotations

import json
import urllib.request


def put_json(addr, path, obj, timeout=5):
    data = json.dumps(obj).encode()
    req = urllib.request.Request(f"http://{addr}{path}", data=data,
                                 method="PUT",
                                 headers={"Content-Type":
                                          "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.status


def get_json(addr, path, timeout=5):
    req = urllib.request.Request(f"http://{addr}{path}")
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        body = resp.read()
        return json.loads(body) if body else None


def put_bytes(addr, path, data: bytes, timeout=15):
    """Raw-bytes PUT (timeline shard upload: the shards are pre-encoded
    JSON files, re-encoding them via put_json would double the memory)."""
    req = urllib.request.Request(f"http://{addr}{path}", data=data,
                                 method="PUT")
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.status
