"""Tiny HTTP KV client (reference ``horovod/runner/http/http_client.py``).

All three verbs are idempotent against the rendezvous KV (PUTs replace a
key, GETs read one), so transient transport failures — a connection
refused while the server is still binding, a reset mid-rendezvous, a
socket timeout — are retried with bounded exponential backoff + jitter
instead of killing the worker. HTTP errors below 500 (e.g. the 404 that
elastic workers poll through) are the server speaking and are never
retried; 5xx and OS-level errors are.
"""

from __future__ import annotations

import json
import random
import time
import urllib.error
import urllib.request

# bounded backoff: first retry after ~0.1 s, doubling to a 2 s cap, with
# full jitter so a gang of workers hammering a restarting rendezvous
# server decorrelates instead of thundering
DEFAULT_RETRIES = 4
_BACKOFF_BASE = 0.1
_BACKOFF_CAP = 2.0


def _urlopen_retrying(req, timeout, retries):
    delay = _BACKOFF_BASE
    for attempt in range(retries + 1):
        try:
            return urllib.request.urlopen(req, timeout=timeout)
        except urllib.error.HTTPError as e:
            # the server answered: 4xx is a real answer (elastic workers
            # poll through 404s), 5xx is transient server trouble
            if e.code < 500 or attempt >= retries:
                raise
        except OSError:
            # URLError (connection refused/reset) and socket.timeout
            # both subclass OSError
            if attempt >= retries:
                raise
        # 50-100% jitter: decorrelates a worker gang without collapsing
        # the backoff to near-zero (the retry budget stays predictable)
        time.sleep(delay * (0.5 + 0.5 * random.random()))
        delay = min(delay * 2, _BACKOFF_CAP)


def put_json(addr, path, obj, timeout=5, retries=DEFAULT_RETRIES):
    data = json.dumps(obj).encode()
    req = urllib.request.Request(f"http://{addr}{path}", data=data,
                                 method="PUT",
                                 headers={"Content-Type":
                                          "application/json"})
    with _urlopen_retrying(req, timeout, retries) as resp:
        return resp.status


def get_json(addr, path, timeout=5, retries=DEFAULT_RETRIES):
    req = urllib.request.Request(f"http://{addr}{path}")
    with _urlopen_retrying(req, timeout, retries) as resp:
        body = resp.read()
        return json.loads(body) if body else None


def put_bytes(addr, path, data: bytes, timeout=15,
              retries=DEFAULT_RETRIES):
    """Raw-bytes PUT (timeline shard upload: the shards are pre-encoded
    JSON files, re-encoding them via put_json would double the memory)."""
    req = urllib.request.Request(f"http://{addr}{path}", data=data,
                                 method="PUT")
    with _urlopen_retrying(req, timeout, retries) as resp:
        return resp.status
