"""Host identity hashing (reference
``horovod/runner/common/util/host_hash.py``): two launcher entries that
resolve to the same machine (e.g. ``localhost`` and the FQDN) must land in
the same local-rank group, so hosts are deduplicated by a hash of the
machine identity rather than by spelling."""

from __future__ import annotations

import hashlib
import socket


def host_hash(salt: str = "") -> str:
    """Hash identifying *this* machine. Mirrors the reference: hostname
    (minus any trailing domain) + salt, md5-hexed. The salt lets tests and
    containerized slots force distinct identities on one machine."""
    hostname = socket.gethostname()
    host = hostname.split(".")[0]
    return hashlib.md5(f"{host}-{salt}".encode()).hexdigest()


def hosts_equivalent(a: str, b: str) -> bool:
    """True when two host strings resolve to the same address set."""
    if a == b:
        return True
    try:
        ia = {r[4][0] for r in socket.getaddrinfo(a, None)}
        ib = {r[4][0] for r in socket.getaddrinfo(b, None)}
    except socket.gaierror:
        return False
    return bool(ia & ib)
