"""Programmatic launch API (reference ``horovod/runner/__init__.py:91``
``run()`` — the "interactive run" used by notebooks and the Spark/Ray
layers): run ``fn`` in ``np`` coordinated processes and return the
per-rank results ordered by rank."""

from __future__ import annotations

import os
import sys
import tempfile
from typing import Any, List, Optional


def run(fn, args=(), kwargs=None, np: int = 1,
        hosts: Optional[str] = None, env: Optional[dict] = None,
        master_port: int = 29540, force_cpu: bool = True,
        run_dir: Optional[str] = None,
        verbose: bool = False) -> List[Any]:
    """Launch ``fn(*args, **kwargs)`` across ``np`` processes through the
    hvtrun machinery; inside ``fn`` the full horovod_tpu API (rank/size,
    collectives, DistributedOptimizer) is live.

    ``force_cpu`` pins workers to the CPU JAX platform — required for
    multi-process runs on a single machine where the accelerator is
    single-process.

    Remote ``hosts`` require a filesystem shared between launcher and
    workers: pass ``run_dir`` pointing into it (the pickled function and
    per-rank results travel through that directory).
    """
    import cloudpickle

    from horovod_tpu.runner import launch as launch_mod
    from horovod_tpu.runner.codec import dumps_base64
    from horovod_tpu.runner.hosts import parse_hosts
    from horovod_tpu.runner.launch import _is_local

    if hosts and run_dir is None:
        remote = [h.hostname for h in parse_hosts(hosts)
                  if not _is_local(h.hostname)]
        if remote:
            raise ValueError(
                f"run(hosts=...) with remote hosts {remote} needs "
                f"run_dir= on a filesystem shared with those hosts — "
                f"the function and results are exchanged through it")

    kwargs = kwargs or {}
    with tempfile.TemporaryDirectory(prefix="hvt_run_",
                                     dir=run_dir) as tmp:
        fn_path = os.path.join(tmp, "fn.b64")
        with open(fn_path, "w") as f:
            f.write(dumps_base64((fn, args, kwargs)))
        argv = ["-np", str(np), "--master-port", str(master_port)]
        if hosts:
            argv += ["-H", hosts]
        if verbose:
            argv += ["--verbose"]
        argv += [sys.executable, "-m", "horovod_tpu.runner.task_runner",
                 fn_path, tmp]
        extra = dict(env or {})
        if force_cpu:
            extra["HVT_RUN_FORCE_CPU"] = "1"
        old = {k: os.environ.get(k) for k in extra}
        os.environ.update(extra)
        try:
            rc = launch_mod.main(argv)
        finally:
            for k, v in old.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
        if rc != 0:
            raise RuntimeError(f"hvt.runner.run failed with exit code {rc}")
        results = []
        for rank in range(np):
            path = os.path.join(tmp, f"result_{rank}.pkl")
            if not os.path.exists(path):
                raise RuntimeError(f"rank {rank} produced no result")
            with open(path, "rb") as f:
                results.append(cloudpickle.load(f))
        return results
