"""Metrics-driven elastic autoscaler — the policy loop that closes the
telemetry → elasticity feedback circle (ROADMAP item 4).

Signals, all read from the rendezvous KV the driver already hosts:

- ``serving`` scope (``/kv/serving/<rank>``, pushed by
  :class:`horovod_tpu.serving.ReplicaGang`): per-rank in-flight backlog,
  shed counts, p99 latency;
- ``debugz`` scope (``/kv/debugz/<rank>``, pushed every 5 s by
  ``common/basics.py``): the engine's client queue depth;
- ``failure`` scope (``/kv/failure/<host>/<slot>``, PUT by the elastic
  ``@run`` wrapper when a collective dies): failed-rank attributions;
- ``telemetry`` scope (``/kv/telemetry/host/<host>``, one merged frame
  per host leader under ``HVT_CTRL_TOPOLOGY=tree``): the same per-rank
  queue depths, arriving O(hosts) instead of O(ranks);
- the ``/statusz`` health engine's ``alerts`` list
  (``metrics/telemetry.py``): a ``serving_backlog`` alert counts as a
  sustained backlog, so the scale-out decision and the operator's
  dashboard fire from one definition of "sustained".

Decisions:

- **scale out** — when the backlog signal (max of serving in-flight and
  engine queue depth across workers) stays at/above
  ``HVT_AUTOSCALE_BACKLOG`` for ``HVT_AUTOSCALE_SUSTAIN_SEC`` AND
  discovery shows spare slots, notify the workers; they re-rendezvous
  into a bigger world through the existing elastic driver (the same
  zero-downtime host-update path a discovery change takes — state is
  kept, no process restarts).
- **shed** — when a failure report names broken ranks, blacklist their
  hosts (the driver's own KV hook does this too; the autoscaler repeats
  it idempotently so policy tests can drive either path) and count the
  decision. The subsequent re-rendezvous excludes them.

A cooldown (``HVT_AUTOSCALE_COOLDOWN_SEC``) separates decisions so one
backlog spike cannot thrash rendezvous rounds. Enable under ``hvtrun
--elastic`` with ``HVT_AUTOSCALE=1``; the loop polls every
``HVT_AUTOSCALE_INTERVAL_SEC``.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Optional


def _as_float(raw, default: float) -> float:
    try:
        return float(raw if raw not in (None, "") else default)
    except ValueError:
        return default


class AutoscalePolicy:
    """Thresholds for the decision loop (env-seeded, test-overridable).

    Env reads stay literal (no name indirection) so the env↔docs lint
    pass sees every knob."""

    def __init__(self, backlog_threshold: float = None,
                 sustain_sec: float = None, cooldown_sec: float = None,
                 interval_sec: float = None):
        self.backlog_threshold = (
            backlog_threshold if backlog_threshold is not None
            else _as_float(os.environ.get("HVT_AUTOSCALE_BACKLOG"), 8))
        self.sustain_sec = (
            sustain_sec if sustain_sec is not None
            else _as_float(os.environ.get("HVT_AUTOSCALE_SUSTAIN_SEC"),
                           10))
        self.cooldown_sec = (
            cooldown_sec if cooldown_sec is not None
            else _as_float(os.environ.get("HVT_AUTOSCALE_COOLDOWN_SEC"),
                           30))
        self.interval_sec = (
            interval_sec if interval_sec is not None
            else _as_float(os.environ.get("HVT_AUTOSCALE_INTERVAL_SEC"),
                           2))


def _metrics():
    from horovod_tpu import metrics

    return (
        metrics.counter("hvt_autoscaler_decisions_total",
                        "autoscaler decisions by action",
                        ("action",)),
        metrics.gauge("hvt_autoscaler_backlog",
                      "current gang-wide backlog signal (max of serving "
                      "in-flight and engine queue depth over workers)"),
        metrics.gauge("hvt_autoscaler_spare_slots",
                      "discovered slots beyond the current world size"),
    )


class Autoscaler:
    """Policy loop over an :class:`ElasticDriver` and its rendezvous.

    ``step(now)`` is the whole brain and is synchronous — tests drive it
    directly with fake stores/drivers; ``start()`` wraps it in a daemon
    thread for the launcher.
    """

    def __init__(self, driver, rendezvous,
                 policy: Optional[AutoscalePolicy] = None,
                 verbose: bool = False):
        self._driver = driver
        self._rendezvous = rendezvous
        self.policy = policy or AutoscalePolicy()
        self._verbose = verbose
        self._backlog_since: Optional[float] = None
        self._last_action_t = 0.0
        self._last_err_t = -1e9
        # (scope, key) → (last raw payload, first-seen monotonic sec)
        self._payload_seen = {}
        self._seen_failures = set()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.decisions = []  # (t, action, detail) — introspection/tests

    # ------------------------------------------------------------- signals

    def _store(self):
        return getattr(self._rendezvous, "store", None)

    # A snapshot whose payload has not CHANGED for this long (of the
    # driver's own monotonic clock) is treated as dead and ignored: the
    # "serving"/"debugz" KV scopes survive round resets (by design —
    # the autoscaler must not go blind at the rendezvous it caused), so
    # a shed rank's final push would otherwise pin the backlog signal
    # high forever. Change-detection rather than snapshot timestamps on
    # purpose: worker wall clocks skew across hosts (the timeline runs
    # a /clock offset handshake for exactly that reason), while a LIVE
    # worker re-pushes every few seconds with a changing payload (ts /
    # cycle counters), which this observes without trusting any remote
    # clock.
    STALE_SNAPSHOT_SEC = 15.0

    def _fresh(self, scope: str, key: str, raw, mono_now: float) -> bool:
        prev = self._payload_seen.get((scope, key))
        if prev is None or prev[0] != raw:
            self._payload_seen[(scope, key)] = (raw, mono_now)
            return True
        return mono_now - prev[1] <= self.STALE_SNAPSHOT_SEC

    def read_backlog(self, mono_now: Optional[float] = None) -> float:
        """Gang-wide backlog: max over workers of the serving in-flight
        depth and the engine client queue depth. Snapshots that stopped
        changing (dead rank) or whose rank id is outside the current
        world are discarded."""
        store = self._store()
        if store is None:
            return 0.0
        mono_now = time.monotonic() if mono_now is None else mono_now
        try:
            world = self._driver.world_size()
        except Exception:
            world = None
        worst = 0.0
        for scope, depth_of in (
                ("serving", lambda b: b.get("inflight", 0)),
                ("debugz",
                 lambda b: (b.get("engine") or {}).get("queue_depth", 0))):
            for key in store.keys(scope):
                try:
                    if world is not None and int(key) >= world:
                        continue  # rank id not in the current round
                    raw = store.get(scope, key)
                    if not self._fresh(scope, key, raw, mono_now):
                        continue  # a dead/shed rank's final push
                    worst = max(worst, float(depth_of(json.loads(raw))))
                except (ValueError, TypeError, AttributeError):
                    # AttributeError: valid JSON that is not an object
                    # (a buggy/old pusher) — skip it, never abort step()
                    continue
        # leader-aggregated gangs (HVT_CTRL_TOPOLOGY=tree): per-rank
        # queue depths arrive inside ONE host frame per host instead of
        # per-rank debugz keys — the autoscaler reads both shapes so a
        # topology switch never blinds the backlog signal
        for key in store.keys("telemetry"):
            if not key.startswith("host/"):
                continue
            try:
                raw = store.get("telemetry", key)
                if not self._fresh("telemetry", key, raw, mono_now):
                    continue
                for r_str, rec in (json.loads(raw).get("ranks")
                                   or {}).items():
                    if world is not None and int(r_str) >= world:
                        continue
                    worst = max(worst,
                                float(rec.get("queue_depth", 0)))
            except (ValueError, TypeError, AttributeError):
                continue
        return worst

    def read_failed_ranks(self) -> dict:
        """Unseen failure reports: ``{(host_slot_key): [ranks]}``."""
        store = self._store()
        if store is None:
            return {}
        out = {}
        for key in store.keys("failure"):
            raw = store.get("failure", key)
            # dedup by (key, payload): the failure scope is cleared at
            # round resets, so a later round's genuinely-new report can
            # legitimately reuse the same <host>/<slot> key. Marked
            # seen BEFORE parsing: a malformed report is skipped once,
            # not re-tripped on every poll.
            sig = (key, raw)
            if sig in self._seen_failures:
                continue
            self._seen_failures.add(sig)
            try:
                body = json.loads(raw)
                ranks = [int(r) for r in body.get("failed_ranks") or []]
            except (ValueError, TypeError, AttributeError):
                continue
            out[key] = ranks
        return out

    def _shed_report(self, key: str):
        """Route a failure report through the driver's own handler —
        ONE home for the blacklist policy (reporter-host guard, rank →
        host mapping). Idempotent with the driver's live KV put-hook,
        which already ran for reports that arrived over HTTP; this path
        covers store-injected reports (tests, replayed KV)."""
        handler = getattr(self._driver, "_on_failure_report", None)
        if handler is None:
            return
        store = self._store()
        raw = store.get("failure", key) if store is not None else None
        if raw is None:
            return
        try:
            handler(key, raw)
        except Exception as e:
            self._log_error(f"failure-report handoff failed: {e!r}")

    def read_health_alerts(self) -> list:
        """Active health alerts from the rendezvous server's /statusz
        health engine (``metrics/telemetry.py``), or [] when the
        rendezvous has no statusz surface (tests with bare fakes).
        Building the snapshot also advances the health windows — the
        engine self-gates ingestion to the push interval, so the 2 s
        policy loop cannot fast-forward persistence rules."""
        snap_fn = getattr(self._rendezvous, "statusz_snapshot", None)
        if snap_fn is None:
            return []
        try:
            return list((snap_fn() or {}).get("alerts") or [])
        except Exception as e:
            self._log_error(f"statusz read failed: {e!r}")
            return []

    def spare_slots(self) -> int:
        hm = getattr(self._driver, "host_manager", None)
        if hm is None:
            return 0
        try:
            avail = hm.current_hosts.count_available_slots()
        except Exception:
            return 0
        # the driver caps every round at settings.max_np — slots beyond
        # it are not scalable capacity, and counting them would force a
        # disruptive re-rendezvous that changes nothing, every cooldown
        max_np = getattr(getattr(self._driver, "_settings", None),
                         "max_np", None)
        if max_np:
            avail = min(avail, max_np)
        return max(0, avail - self._driver.world_size())

    # ------------------------------------------------------------ decisions

    def _record(self, now: float, action: str, detail: str):
        self.decisions.append((now, action, detail))
        self._last_action_t = now
        try:
            decisions, _, _ = _metrics()
            decisions.labels(action=action).inc()
        except Exception:
            pass
        if self._verbose:
            print(f"[autoscaler] {action}: {detail}")

    def step(self, now: Optional[float] = None):
        now = time.monotonic() if now is None else now

        # shed-and-blacklist first: a broken rank is a correctness event,
        # not a load event — it never waits out a cooldown
        failures = self.read_failed_ranks()
        if failures:
            named = sorted({r for rs in failures.values() for r in rs})
            for key in failures:
                self._shed_report(key)
            self._record(now, "shed",
                         f"failure reports {sorted(failures)} named "
                         f"ranks {named}; hosts blacklisted, next round "
                         f"excludes them")

        # one clock governs the whole decision: the staleness filter
        # must tick with the same `now` the sustain/cooldown logic uses
        # (tests drive step() with a synthetic clock)
        backlog = self.read_backlog(mono_now=now)
        spare = self.spare_slots()
        try:
            _, backlog_g, spare_g = _metrics()
            backlog_g.set(backlog)
            spare_g.set(spare)
        except Exception:
            pass

        if backlog < self.policy.backlog_threshold:
            self._backlog_since = None
            return
        if self._backlog_since is None:
            self._backlog_since = now
        sustained = now - self._backlog_since
        # a serving_backlog health alert already encodes persistence
        # (strict growth over HVT_HEALTH_BACKLOG_WINDOWS push windows),
        # so it satisfies the sustain requirement directly — the
        # statusz health engine and this loop agree on "sustained"
        # instead of each waiting out the other. Checked ONLY when the
        # time-based test alone would block: building the statusz
        # snapshot parses every pushed blob, which is not a
        # per-2s-tick cost to pay when the answer cannot change the
        # decision.
        if sustained < self.policy.sustain_sec:
            if not any(a.get("rule") == "serving_backlog"
                       for a in self.read_health_alerts()):
                return
        if now - self._last_action_t < self.policy.cooldown_sec:
            return
        if spare <= 0:
            # nothing to scale onto; keep the sustain window armed so
            # a host arriving later triggers immediately
            return
        self._scale_out(now, backlog, spare)

    def _scale_out(self, now: float, backlog: float, spare: int):
        # the zero-downtime path: notify workers exactly like a
        # discovery change — they reach their next commit, report READY,
        # and the driver's barrier activates a round over ALL available
        # slots (state intact, nobody restarted)
        notify = getattr(self._driver, "_notify_workers_host_changes",
                         None)
        if notify is None:
            return
        # the driver's notify returns None unconditionally and swallows
        # per-worker send errors, so "nobody is registered to hear this"
        # must be checked up front — otherwise a no-op notification
        # would burn the sustain window + cooldown having told no one
        addrs_fn = getattr(self._driver, "_worker_notify_addrs", None)
        if addrs_fn is not None:
            try:
                if not addrs_fn():
                    self._log_error(
                        "scale-out pending: no worker notification "
                        "endpoints registered yet")
                    return
            except Exception:
                pass  # cannot tell — proceed and let notify try
        try:
            notify()
        except Exception as e:
            # leave the sustain window armed: the missed notification
            # retries on the very next step instead of re-earning
            # sustain_sec + cooldown_sec
            self._log_error(f"scale-out notify failed: {e!r}")
            return
        self._record(now, "scale_out",
                     f"backlog {backlog:.0f} ≥ "
                     f"{self.policy.backlog_threshold:.0f} sustained "
                     f"{self.policy.sustain_sec:.0f}s with {spare} spare "
                     f"slot(s); re-rendezvous requested")
        self._backlog_since = None

    # -------------------------------------------------------------- thread

    def start(self):
        if self._thread is not None:
            return
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="hvt-autoscaler")
        self._thread.start()

    def stop(self):
        self._stop.set()

    def _log_error(self, msg: str):
        """Rate-limited (60 s) stderr note: a persistently-failing
        policy loop must be visible, never silently inert."""
        import sys

        now = time.monotonic()
        if now - self._last_err_t < 60:
            return
        self._last_err_t = now
        print(f"[autoscaler] {msg}", file=sys.stderr)

    def _loop(self):
        while not self._stop.wait(self.policy.interval_sec):
            if getattr(self._driver, "finished", lambda: False)():
                return
            try:
                self.step()
            except Exception as e:
                # policy failures must never take the launcher down —
                # but they must not be invisible either
                self._log_error(f"step failed: {e!r}")


def maybe_start_autoscaler(driver, rendezvous, verbose=False):
    """Launcher hook: start the loop iff ``HVT_AUTOSCALE=1``. Returns
    the Autoscaler (started) or None."""
    if os.environ.get("HVT_AUTOSCALE", "0") != "1":
        return None
    scaler = Autoscaler(driver, rendezvous, verbose=verbose)
    scaler.start()
    return scaler
