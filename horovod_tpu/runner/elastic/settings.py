"""Elastic job settings (reference ``horovod/runner/elastic/settings.py``
and the elastic arg group of ``runner/launch.py:392``)."""

from __future__ import annotations

import dataclasses
from typing import Optional


@dataclasses.dataclass
class ElasticSettings:
    """Knobs for an elastic run.

    - ``min_np`` / ``max_np``: world-size bounds; the job starts as soon as
      ``min_np`` slots are discovered and never grows past ``max_np``.
    - ``elastic_timeout``: seconds to wait for ``min_np`` slots before
      giving up (reference constant ELASTIC_TIMEOUT_SECS, default 600).
    - ``reset_limit``: max number of re-rendezvous rounds before the job is
      failed (reference ``launch.py:392`` --reset-limit).
    - ``cooldown_range``: (min, max) seconds a blacklisted host stays
    blacklisted before it may be retried; ``None`` = permanent blacklist.
    - ``discovery_interval``: seconds between discovery polls (reference
      polls every 1 s, ``runner/elastic/driver.py:177``).
    """

    min_np: int = 1
    max_np: Optional[int] = None
    elastic_timeout: float = 600.0
    reset_limit: Optional[int] = None
    cooldown_range: Optional[tuple] = None
    discovery_interval: float = 1.0
    verbose: bool = False

    def __post_init__(self):
        if self.max_np is not None and self.max_np < self.min_np:
            raise ValueError(
                f"max_np ({self.max_np}) < min_np ({self.min_np})")
