"""Worker-side host-update notifications (reference
``horovod/runner/elastic/worker.py:37`` WorkerNotificationManager).

Each elastic worker runs a small HTTP server; the driver POSTs host-set
changes to it, and the manager forwards them to every registered State via
``on_hosts_updated`` so the next ``state.commit()`` raises
HostsUpdatedInterrupt. Outside an elastic launch (no
``HVT_ELASTIC_NOTIFY_ADDR`` env), this is inert and states simply never see
host updates — matching the reference, where the manager only initializes
under horovodrun-elastic."""

from __future__ import annotations

import json
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

_manager = None
_lock = threading.Lock()


class WorkerNotificationManager:
    def __init__(self):
        self._states = []
        self._server = None
        self._port = None

    def register_state(self, state):
        self._states.append(state)

    def remove_state(self, state):
        if state in self._states:
            self._states.remove(state)

    @property
    def port(self):
        return self._port

    def handle_hosts_updated(self, timestamp, update_res):
        for s in list(self._states):
            s.on_hosts_updated(timestamp, update_res)

    def start_server(self):
        mgr = self

        class Handler(BaseHTTPRequestHandler):
            def do_PUT(self):
                length = int(self.headers.get("Content-Length", 0))
                body = json.loads(self.rfile.read(length) or b"{}")
                mgr.handle_hosts_updated(body.get("timestamp", time.time()),
                                         body.get("res", 0))
                self.send_response(200)
                self.end_headers()

            def log_message(self, *a):
                pass

        self._server = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self._port = self._server.server_address[1]
        threading.Thread(target=self._server.serve_forever,
                         daemon=True).start()

    def init(self, rendezvous_addr=None):
        """Register with the elastic driver's rendezvous so it can notify us
        (reference worker.py:44-66 PUTs its address to the driver)."""
        self.start_server()
        addr = rendezvous_addr or os.environ.get("HVT_ELASTIC_NOTIFY_ADDR")
        if addr:
            from horovod_tpu.runner.http_client import put_json

            # key by stable spawn identity (host, local_rank), not rank —
            # ranks reshuffle across rounds and a rank-keyed registration
            # would let a new worker overwrite a live survivor's entry
            import socket as _socket

            host = os.environ.get("HVT_HOSTNAME") or _socket.gethostname()
            slot = os.environ.get("HVT_LOCAL_PROCESS_ID", "0")
            try:
                put_json(addr, f"/kv/workers/{host}/{slot}",
                         {"host": "127.0.0.1", "port": self._port})
            except OSError:
                pass


def init_worker_notification(state):
    """Called by @hvt.elastic.run: lazily start the manager and register the
    state. Inert outside an elastic launch."""
    global _manager
    with _lock:
        if _manager is None:
            _manager = WorkerNotificationManager()
            if os.environ.get("HVT_ELASTIC_NOTIFY_ADDR"):
                _manager.init()
        _manager.register_state(state)
    return _manager
