"""Worker state registry (reference
``horovod/runner/elastic/registration.py:28`` WorkerStateRegistry —
READY/SUCCESS/FAILURE barrier that triggers ``driver.resume()``).

Each worker process reports a terminal state for the current rendezvous
round. When every worker of the round has reported:

- all SUCCESS            → the job is done; the driver stops.
- any FAILURE / READY    → a new rendezvous round is needed; the driver
                           resumes (re-assigns ranks, restarts workers)
                           unless ``reset_limit`` is exhausted.

READY means "I hit HostsUpdatedInterrupt and am waiting for the new
round" — it counts toward the barrier but is not a failure.
"""

from __future__ import annotations

import threading
from typing import Optional

READY = "READY"
SUCCESS = "SUCCESS"
FAILURE = "FAILURE"


class WorkerStateRegistry:
    def __init__(self, driver, host_manager, reset_limit: Optional[int] = None,
                 verbose: bool = False):
        self._driver = driver
        self._host_manager = host_manager
        self._reset_limit = reset_limit
        self._verbose = verbose
        self._lock = threading.Lock()
        self._barrier_done = threading.Event()
        self._states = {}          # (host, slot) → state, current round
        self._round = 0
        self._reset_count = 0
        self._size = 0

    @property
    def reset_count(self) -> int:
        return self._reset_count

    @property
    def round(self) -> int:
        return self._round

    def reset(self, size: int):
        """Start a new round expecting ``size`` workers."""
        with self._lock:
            self._states = {}
            self._size = size
            self._round += 1
            self._barrier_done.clear()

    def record_ready(self, host: str, slot: int):
        return self._record(host, slot, READY)

    def record_success(self, host: str, slot: int):
        return self._record(host, slot, SUCCESS)

    def record_failure(self, host: str, slot: int):
        return self._record(host, slot, FAILURE)

    def count(self, state: str) -> int:
        with self._lock:
            return sum(1 for s in self._states.values() if s == state)

    def last_round_complete(self) -> bool:
        return self._barrier_done.is_set()

    def _record(self, host: str, slot: int, state: str) -> int:
        with self._lock:
            key = (host, slot)
            # first terminal state wins: a worker that failed and was then
            # torn down should not flip to SUCCESS
            if key not in self._states or self._states[key] == READY:
                self._states[key] = state
            complete = (self._size > 0
                        and len(self._states) >= self._size)
            rnd = self._round
        if complete:
            self._on_barrier(rnd)
        return rnd

    def _on_barrier(self, rnd: int):
        with self._lock:
            if self._barrier_done.is_set() or rnd != self._round:
                return
            self._barrier_done.set()
            states = dict(self._states)
        failures = sum(1 for s in states.values() if s == FAILURE)
        successes = sum(1 for s in states.values() if s == SUCCESS)
        if failures == 0 and successes == len(states) and successes > 0:
            self._driver.stop(error=False)
            return
        # blacklist hosts where every slot failed (reference blacklists the
        # failing host so ranks are not reassigned onto it)
        by_host = {}
        for (host, _slot), s in states.items():
            by_host.setdefault(host, []).append(s)
        for host, slot_states in by_host.items():
            if slot_states and all(s == FAILURE for s in slot_states):
                self._host_manager.blacklist(host)
        self._reset_count += 1
        if self._reset_limit is not None \
                and self._reset_count > self._reset_limit:
            self._driver.stop(
                error=True,
                reason=f"reset count {self._reset_count} exceeded limit "
                       f"{self._reset_limit}")
            return
        self._driver.resume()
