"""Host discovery for elastic jobs (reference
``horovod/runner/elastic/discovery.py``: ``HostManager:79``,
``HostDiscoveryScript`` — a user script prints ``host:slots`` lines;
blacklisting with optional cooldown).

The discovery source is pluggable: a user script (re-run every poll), a
fixed host list (for static-within-elastic tests), or any object with a
``find_available_hosts_and_slots() -> {host: slots}`` method (the Ray
integration supplies one backed by the Ray cluster state).
"""

from __future__ import annotations

import random
import subprocess
import threading
import time
from typing import Dict, List, Optional


class HostDiscovery:
    """Interface: return the currently available hosts and their slots."""

    def find_available_hosts_and_slots(self) -> Dict[str, int]:
        raise NotImplementedError


class HostDiscoveryScript(HostDiscovery):
    """Runs a user-provided executable that prints one host per line,
    either ``hostname:slots`` or bare ``hostname`` (then ``default_slots``
    applies). A failing or timed-out script RAISES — callers that poll
    (the driver's discovery thread) catch and keep the previous view, so
    a transient discovery blip never reads as "all hosts gone" (reference
    ``driver.py`` ``_discover_hosts`` retains state on a failed poll).
    """

    def __init__(self, script: str, default_slots: int = 1,
                 timeout: float = 10.0):
        self._script = script
        self._default_slots = default_slots
        self._timeout = timeout

    def find_available_hosts_and_slots(self) -> Dict[str, int]:
        out = subprocess.run(
            self._script, shell=True, capture_output=True,
            timeout=self._timeout, check=True).stdout.decode()
        hosts: Dict[str, int] = {}
        for line in out.splitlines():
            line = line.strip()
            if not line:
                continue
            if ":" in line:
                name, _, slots = line.rpartition(":")
                try:
                    hosts[name] = int(slots)
                except ValueError:
                    continue
            else:
                hosts[line] = self._default_slots
        return hosts


class FixedHostDiscovery(HostDiscovery):
    """A constant host set (``host1:2,host2:2`` string or dict)."""

    def __init__(self, hosts):
        if isinstance(hosts, str):
            from horovod_tpu.runner.hosts import parse_hosts

            hosts = {h.hostname: h.slots for h in parse_hosts(hosts)}
        self._hosts = dict(hosts)

    def find_available_hosts_and_slots(self) -> Dict[str, int]:
        return dict(self._hosts)


class DiscoveredHosts:
    """Immutable snapshot of one discovery poll, with blacklist applied.

    ``host_assignment_order`` is stable: hosts already present keep their
    relative order; new hosts append — so surviving ranks stay on the same
    hosts across updates (reference ``driver.py:228`` stable ranks).
    """

    def __init__(self, host_slots: Dict[str, int],
                 host_assignment_order: List[str]):
        self.host_slots = dict(host_slots)
        self.host_assignment_order = list(host_assignment_order)

    def count_available_slots(self) -> int:
        return sum(self.host_slots.get(h, 0)
                   for h in self.host_assignment_order)

    def update(self, host_slots: Dict[str, int]) -> "DiscoveredHosts":
        order = [h for h in self.host_assignment_order if h in host_slots]
        order += sorted(h for h in host_slots
                        if h not in self.host_assignment_order)
        return DiscoveredHosts(host_slots, order)

    def __eq__(self, other):
        return (isinstance(other, DiscoveredHosts)
                and self.host_slots == other.host_slots
                and self.host_assignment_order
                == other.host_assignment_order)

    def __repr__(self):
        return f"DiscoveredHosts({self.host_slots})"


class HostManager:
    """Tracks the live host set across discovery polls and owns the
    blacklist (reference ``discovery.py:79``)."""

    def __init__(self, discovery: HostDiscovery,
                 cooldown_range: Optional[tuple] = None):
        self._discovery = discovery
        self._lock = threading.Lock()
        self._current_hosts = DiscoveredHosts({}, [])
        self._blacklist: Dict[str, float] = {}   # host → retry-after ts
        self._cooldown_range = cooldown_range

    @property
    def current_hosts(self) -> DiscoveredHosts:
        with self._lock:
            return self._current_hosts

    def update_available_hosts(self) -> bool:
        """Poll discovery once; returns True when the usable host set
        changed (the driver then notifies workers)."""
        found = self._discovery.find_available_hosts_and_slots()
        now = time.time()
        with self._lock:
            usable = {h: s for h, s in found.items()
                      if not self._is_blacklisted_locked(h, now)}
            new = self._current_hosts.update(usable)
            changed = new != self._current_hosts
            self._current_hosts = new
            return changed

    def blacklist(self, host: str):
        """Mark a host bad; with a cooldown range it may return after a
        randomized backoff, otherwise it is out for the job's lifetime."""
        with self._lock:
            if self._cooldown_range is not None:
                lo, hi = self._cooldown_range
                self._blacklist[host] = time.time() + random.uniform(lo, hi)
            else:
                self._blacklist[host] = float("inf")
            hs = dict(self._current_hosts.host_slots)
            hs.pop(host, None)
            self._current_hosts = self._current_hosts.update(hs)

    def is_blacklisted(self, host: str) -> bool:
        with self._lock:
            return self._is_blacklisted_locked(host, time.time())

    def blacklisted_count(self) -> int:
        """Hosts currently serving a blacklist sentence (expired cooldowns
        are purged on the way) — feeds the hvt_elastic_blacklisted_hosts
        telemetry gauge."""
        with self._lock:
            now = time.time()
            return sum(1 for h in list(self._blacklist)
                       if self._is_blacklisted_locked(h, now))

    def _is_blacklisted_locked(self, host: str, now: float) -> bool:
        until = self._blacklist.get(host)
        if until is None:
            return False
        if now >= until:
            del self._blacklist[host]
            return False
        return True
