"""Elastic driver (reference ``horovod/runner/elastic/driver.py``:
``ElasticDriver:68`` — discovery thread ``_discover_hosts:177`` (1 s
poll), ``_update_host_assignments:228`` (stable ranks, requires ≥1
surviving host), ``_start_worker_process:277``,
``_handle_worker_exit:292``).

Orchestrates a fault-tolerant job:

- polls a HostDiscovery source; on a host-set change notifies workers so
  their next ``state.commit()`` raises HostsUpdatedInterrupt;
- assigns ranks to (host, slot) pairs, keeping surviving workers' ranks
  stable across rounds;
- spawns one worker per slot via a pluggable ``create_worker_fn`` (the
  launcher passes an ssh/subprocess spawner; tests pass fakes);
- feeds worker exits into the WorkerStateRegistry, whose barrier calls
  back into ``resume()`` (new round) or ``stop()``.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Callable, Dict, Optional, Tuple

from horovod_tpu.runner.elastic.discovery import HostManager
from horovod_tpu.runner.elastic.registration import WorkerStateRegistry
from horovod_tpu.runner.hosts import HostInfo, SlotInfo, \
    get_host_assignments
from horovod_tpu.runner.elastic.settings import ElasticSettings

_NOTIFY_SCOPE = "workers"


def _elastic_metrics():
    """Driver-side telemetry (horovod_tpu.metrics): rendezvous rounds,
    world size, alive/blacklisted hosts — the live form of what the
    reference only logs (reference driver.py verbose prints)."""
    from horovod_tpu import metrics

    return (
        metrics.counter("hvt_elastic_rounds_total",
                        "elastic rendezvous rounds activated"),
        metrics.counter("hvt_elastic_resets_total",
                        "elastic restarts after the initial round"),
        metrics.gauge("hvt_elastic_world_size",
                      "slots assigned in the current round"),
        metrics.gauge("hvt_elastic_alive_hosts",
                      "distinct hosts in the current assignment"),
        metrics.gauge("hvt_elastic_blacklisted_hosts",
                      "hosts currently blacklisted by the host manager"),
        metrics.counter("hvt_elastic_preemptions_total",
                        "hosts drained gracefully on a preemption "
                        "notice (/kv/failure/<host>/preempt)"),
        metrics.counter("hvt_elastic_folded_rounds_total",
                        "host changes folded into an in-flight "
                        "re-rendezvous instead of costing their own "
                        "restart round"),
    )


class ElasticDriver:
    def __init__(self, rendezvous, discovery, settings: ElasticSettings,
                 create_worker_fn: Optional[Callable] = None,
                 on_stop: Optional[Callable] = None):
        self._on_stop = on_stop
        self._rendezvous = rendezvous
        self._settings = settings
        self._host_manager = HostManager(
            discovery, cooldown_range=settings.cooldown_range)
        self._registry = WorkerStateRegistry(
            self, self._host_manager, reset_limit=settings.reset_limit,
            verbose=settings.verbose)
        self._create_worker_fn = create_worker_fn
        self._lock = threading.Lock()
        # re-rendezvous coalescing (see resume()): a host blacklisted
        # while a round activation is already in flight folds into that
        # activation's loop instead of buying its own restart round
        self._resume_lock = threading.Lock()
        self._resuming = False
        self._resume_pending = False
        self._last_round_view = None
        # hosts gracefully draining on a preemption notice: host ->
        # monotonic expiry. SOFT exclusion — a draining host leaves the
        # next assignment only while the remaining capacity still
        # covers min_np (the platform may give the notice and then not
        # follow through; hard-blacklisting would kill thin jobs), and
        # the mark expires so an un-preempted host can rejoin.
        self._draining: Dict[str, float] = {}
        self._assignments: Dict[Tuple[str, int], SlotInfo] = {}
        self._workers: Dict[Tuple[str, int], threading.Thread] = {}
        self._results: Dict[int, int] = {}     # rank → exit code
        self._shutdown = threading.Event()
        self._finished = threading.Event()
        self._error: Optional[str] = None
        self._discovery_thread = threading.Thread(
            target=self._discover_hosts, daemon=True)
        if hasattr(rendezvous, "set_put_hook"):
            rendezvous.set_put_hook(self._on_kv_put)

    # ------------------------------------------------------------------ API

    @property
    def registry(self) -> WorkerStateRegistry:
        return self._registry

    @property
    def host_manager(self) -> HostManager:
        return self._host_manager

    def start(self, np: int, create_worker_fn: Optional[Callable] = None):
        """Wait for min_np slots, assign ranks, spawn workers, start the
        discovery poll. ``np`` is the preferred initial world size."""
        if create_worker_fn is not None:
            self._create_worker_fn = create_worker_fn
        try:
            self._host_manager.update_available_hosts()
        except Exception:
            # transient discovery failure at startup: wait_for_available
            # _slots below keeps polling until the deadline
            pass
        self.wait_for_available_slots(self._settings.min_np)
        self._activate_round(np)
        self._discovery_thread.start()

    def resume(self):
        """Start a new rendezvous round after a failure or host update.

        Coalescing: only one activation loop runs at a time. A second
        ``resume()`` — or a host blacklisted via
        :meth:`_note_host_change` — while an activation is in flight
        sets the pending flag and returns; the in-flight loop picks the
        change up and re-activates with the updated host view before
        any worker has invested in the superseded assignment. Two
        near-simultaneous failure reports therefore cost the workers
        ONE restart, not two back-to-back rounds."""
        if self._shutdown.is_set():
            return
        with self._resume_lock:
            self._resume_pending = True
            if self._resuming:
                return  # folded into the in-flight activation loop
            self._resuming = True
        folded = -1  # first pass is the round itself, not a fold
        released = False  # did the normal exit already clear _resuming?
        try:
            while True:
                with self._resume_lock:
                    if not self._resume_pending:
                        # clearing _resuming must be atomic with the
                        # final pending check: a concurrent resume()
                        # between "no pending -> return" and a
                        # later-cleared flag would see _resuming still
                        # True, queue its change on the exiting loop,
                        # and lose the wakeup
                        self._resuming = False
                        released = True
                        return
                    self._resume_pending = False
                if self._shutdown.is_set():
                    return
                # fresh discovery snapshot so the new assignment
                # reflects hosts that died/joined since the last poll
                try:
                    self._host_manager.update_available_hosts()
                except Exception:
                    pass
                # a FOLD pass re-activates only when the usable host
                # view actually moved: redundant notifications (a host
                # blacklisted twice, late duplicate failure reports)
                # must not bump the round out from under workers that
                # are already rendezvousing on the one just published
                if folded >= 0 and self._host_view() == \
                        self._last_round_view:
                    continue
                folded += 1
                try:
                    # _update_host_assignments records the view the
                    # assignment actually consumed as _last_round_view
                    self._activate_round(self._preferred_np())
                except RuntimeError:
                    # stop(error=True) was already called with the reason
                    return
        finally:
            if not released:
                # exception paths only: a normal exit already released
                # ownership under the lock, and a NEW activation loop
                # may have legitimately taken it since — clobbering
                # the flag here would let two loops run concurrently
                with self._resume_lock:
                    self._resuming = False
            if folded > 0:
                try:
                    _elastic_metrics()[6].inc(folded)
                except Exception:
                    pass

    def _note_host_change(self):
        """A host left/joined outside the barrier path (failure report,
        preemption drain, late worker exit). If a round activation is
        in flight, fold the change into it — the assignment it was
        about to publish is already stale."""
        with self._resume_lock:
            if self._resuming:
                self._resume_pending = True

    def stop(self, error: bool = False, reason: Optional[str] = None):
        if error:
            self._error = reason or "elastic job failed"
        self._shutdown.set()
        self._finished.set()
        if self._on_stop is not None:
            try:
                self._on_stop()
            except Exception:
                pass

    def finished(self) -> bool:
        return self._finished.is_set()

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._finished.wait(timeout)

    @property
    def error(self) -> Optional[str]:
        return self._error

    def get_results(self) -> Dict[int, int]:
        with self._lock:
            return dict(self._results)

    def world_size(self) -> int:
        with self._lock:
            return len(self._assignments)

    def get_slot_info(self, host: str, slot: int) -> Optional[SlotInfo]:
        with self._lock:
            return self._assignments.get((host, slot))

    def has_rank_assignment(self, host: str, slot: int) -> bool:
        return self.get_slot_info(host, slot) is not None

    def wait_for_available_slots(self, min_np: int,
                                 timeout: Optional[float] = None):
        """Block until discovery shows ≥ min_np usable slots (reference
        ``driver.py`` wait_for_available_slots with elastic_timeout)."""
        deadline = time.time() + (timeout if timeout is not None
                                  else self._settings.elastic_timeout)
        while True:
            hosts = self._host_manager.current_hosts
            if hosts.count_available_slots() >= min_np:
                return hosts
            if time.time() >= deadline:
                raise TimeoutError(
                    f"timed out waiting for {min_np} slots; discovered "
                    f"{hosts.count_available_slots()} "
                    f"({hosts.host_slots})")
            try:
                self._host_manager.update_available_hosts()
            except Exception:
                pass  # keep previous view; retry next interval
            time.sleep(self._settings.discovery_interval)

    # -------------------------------------------------- worker-facing hooks

    def record_ready(self, host: str, slot: int):
        self._registry.record_ready(host, slot)

    def _on_kv_put(self, scope: str, key: str, value: bytes):
        """Rendezvous PUT hook: live workers report READY when they hit a
        reset without exiting (reference workers PUT state to the
        rendezvous the same way, ``registration.py:28``). Reports carry
        the worker's round; stale-round reports are dropped so a slow
        READY can't leak into the next round's barrier."""
        if scope == "preempt":
            # a worker received a preemption notice (SIGTERM/maintenance
            # event); broadcast a host-update so every worker reaches its
            # commit point and re-rendezvous before the chips vanish
            self._notify_workers_host_changes()
            return
        if scope == "failure":
            # a surviving worker caught HorovodInternalError and named
            # the ranks it believes died (parsed from the engine's abort
            # reason); blacklist their hosts now rather than waiting for
            # the dead workers' exit codes to trickle in
            self._on_failure_report(key, value)
            return
        if scope != "state":
            return
        try:
            host, slot = key.rsplit("/", 1)
            body = json.loads(value)
            state = str(body.get("state", "")).upper()
            rnd = int(body.get("round", -1))
        except (ValueError, KeyError, TypeError, UnicodeDecodeError):
            return
        if rnd >= 0 and rnd != self._rendezvous_round():
            return
        if state == "READY":
            self._registry.record_ready(host, int(slot))

    def _on_failure_report(self, key: str, value: bytes):
        """A survivor's /kv/failure report (key = ``<host>/<slot>`` of
        the REPORTER): blacklist the hosts of the ranks it named as
        failed. A rank maps to a host through the CURRENT round's
        assignment. The reporter's own host is never blacklisted from
        its report — a process crash sharing the survivor's host is not
        a lost host (the worker-exit path applies the per-host policy
        there); this also keeps single-host jobs recoverable. Reports
        that name no rank (data-plane failures carry no attribution)
        blacklist nothing — the dead worker's exit handles that.

        A ``<host>/preempt`` key is a GRACEFUL drain notice from the
        preemption watcher, not a crash: the named host leaves the next
        assignment up front and workers get the host-update broadcast,
        so the whole job converges to commit points and re-forms
        without that host ever aborting a collective."""
        try:
            reporter_host, tail = key.rsplit("/", 1)
            body = json.loads(value)
        except (ValueError, KeyError, TypeError, UnicodeDecodeError):
            return
        if tail == "preempt" or (isinstance(body, dict)
                                 and body.get("graceful")):
            if self._settings.verbose:
                print(f"[elastic driver] host {reporter_host} draining "
                      f"on a preemption notice")
            self._mark_draining(reporter_host)
            self._note_host_change()
            try:
                _elastic_metrics()[5].inc()
            except Exception:
                pass
            self._notify_workers_host_changes()
            return
        try:
            ranks = [int(r) for r in body.get("failed_ranks") or []]
        except (ValueError, TypeError, AttributeError):
            return
        if not ranks:
            return
        with self._lock:
            by_rank = {s.rank: s.hostname
                       for s in self._assignments.values()}
        for r in ranks:
            host = by_rank.get(r)
            if host is not None and host != reporter_host:
                if self._settings.verbose:
                    print(f"[elastic driver] failure report names rank "
                          f"{r} ({host}); blacklisting")
                self._host_manager.blacklist(host)
                self._note_host_change()

    def _host_view(self):
        """The inputs an assignment depends on — the fold loop's
        change detector."""
        hosts = self._host_manager.current_hosts
        return (tuple(sorted(hosts.host_slots.items())),
                tuple(sorted(self._active_draining())))

    def _mark_draining(self, host: str):
        import os

        try:
            ttl = float(os.environ.get("HVT_PREEMPT_DRAIN_SEC", "")
                        or 300.0)
        except ValueError:
            ttl = 300.0
        with self._lock:
            self._draining[host] = time.monotonic() + ttl

    def _active_draining(self) -> set:
        now = time.monotonic()
        with self._lock:
            self._draining = {h: t for h, t in self._draining.items()
                              if t > now}
            return set(self._draining)

    def _rendezvous_round(self) -> int:
        return getattr(self._rendezvous, "round", -1)

    def _handle_worker_exit(self, host: str, slot: int, exit_code: int):
        """A worker process exited. Count it toward the current round's
        barrier iff its (host, slot) is still assigned — workers are
        long-lived across rounds, so exits are always 'current' unless the
        host was dropped from the assignment."""
        slot_info = self.get_slot_info(host, slot)
        with self._lock:
            self._workers.pop((host, slot), None)
            if slot_info is not None:
                self._results[slot_info.rank] = exit_code
        if slot_info is None:
            if exit_code != 0 and not self._shutdown.is_set():
                self._host_manager.blacklist(host)
                self._note_host_change()
            return
        if exit_code == 0:
            self._registry.record_success(host, slot)
        else:
            self._registry.record_failure(host, slot)

    # ------------------------------------------------------------ internals

    def _preferred_np(self) -> int:
        avail = self._host_manager.current_hosts.count_available_slots()
        if self._settings.max_np is not None:
            avail = min(avail, self._settings.max_np)
        return max(avail, self._settings.min_np)

    def _activate_round(self, np: int):
        slots = self._update_host_assignments(np)
        self._rendezvous.init(slots)
        self._registry.reset(len(slots))
        with self._lock:
            # results are per-round: a rank that failed in a superseded
            # round must not make a successfully recovered job exit 1
            self._results = {}
        try:
            rounds, resets, world, alive, blacklisted = \
                _elastic_metrics()[:5]
            rounds.inc()
            if rounds.value > 1:
                resets.inc()
            world.set(len(slots))
            alive.set(len({s.hostname for s in slots}))
            blacklisted.set(self._host_manager.blacklisted_count())
        except Exception:
            pass  # telemetry must never block a rendezvous round
        if self._create_worker_fn is not None:
            self._start_missing_workers()

    def _update_host_assignments(self, np: int):
        """Recompute rank assignments over the current hosts, keeping
        surviving (host, slot) pairs on their previous ranks where
        possible. Raises if no host survived — elastic recovery needs at
        least one live copy of the state (reference ``driver.py:228``)."""
        hosts_snapshot = self._host_manager.current_hosts
        host_list = [HostInfo(h, hosts_snapshot.host_slots[h])
                     for h in hosts_snapshot.host_assignment_order]
        draining = self._active_draining()
        # the change-detector baseline for resume()'s fold loop: the
        # exact inputs THIS assignment consumed — a blacklist landing
        # after this line must trigger a re-activation
        self._last_round_view = (
            tuple(sorted(hosts_snapshot.host_slots.items())),
            tuple(sorted(draining)))
        if draining:
            kept = [h for h in host_list if h.hostname not in draining]
            # soft drain: preempted hosts leave the assignment only
            # while the survivors still cover min_np — a thin job keeps
            # its draining host (and simply re-rendezvouses) rather
            # than dying on a notice the platform may not honor
            if sum(h.slots for h in kept) >= self._settings.min_np:
                host_list = kept
        avail = sum(h.slots for h in host_list)
        np = min(np, avail)
        if self._settings.max_np is not None:
            np = min(np, self._settings.max_np)
        if np < self._settings.min_np:
            self.stop(error=True,
                      reason=f"available slots ({avail}) fell below "
                             f"min_np ({self._settings.min_np})")
            raise RuntimeError(self._error)
        with self._lock:
            had_assignments = bool(self._assignments)
            surviving = [k for k in self._assignments
                         if k[0] in hosts_snapshot.host_slots
                         and k[1] < hosts_snapshot.host_slots[k[0]]]
            if had_assignments and not surviving:
                self.stop(error=True,
                          reason="no hosts from the previous round "
                                 "survived; training state is lost")
                raise RuntimeError(self._error)
            slots = get_host_assignments(host_list, np)
            self._assignments = {(s.hostname, s.local_rank): s
                                 for s in slots}
        return slots

    def _start_missing_workers(self):
        started = []
        with self._lock:
            to_start = [key for key in self._assignments
                        if key not in self._workers]
            for key in to_start:
                slot_info = self._assignments[key]
                t = threading.Thread(
                    target=self._run_worker,
                    args=(key[0], key[1], slot_info), daemon=True)
                self._workers[key] = t
                started.append(t)
        for t in started:
            t.start()

    def _run_worker(self, host: str, slot: int, slot_info: SlotInfo):
        try:
            exit_code = self._create_worker_fn(slot_info)
        except Exception:
            exit_code = 1
        self._handle_worker_exit(host, slot, exit_code)

    def _discover_hosts(self):
        while not self._shutdown.is_set():
            try:
                changed = self._host_manager.update_available_hosts()
            except Exception:
                changed = False
            try:
                _elastic_metrics()[4].set(
                    self._host_manager.blacklisted_count())
            except Exception:
                pass
            if changed:
                self._notify_workers_host_changes()
                self._start_missing_workers_if_growing()
            self._shutdown.wait(self._settings.discovery_interval)

    def _start_missing_workers_if_growing(self):
        # New hosts don't get workers until the next round — workers join
        # at rendezvous boundaries, exactly like the reference (spawn
        # happens in _activate_round via resume()).
        pass

    def _notify_workers_host_changes(self):
        """PUT a host-update to every registered worker notification
        server (reference ``driver.py:198-226`` notifies the coordinator;
        we notify all registered workers — same observable effect: the
        next commit raises HostsUpdatedInterrupt)."""
        addrs = self._worker_notify_addrs()
        if not addrs:
            return
        from horovod_tpu.runner.http_client import put_json

        payload = {"timestamp": time.time(), "res": 1}
        for addr in addrs:
            try:
                # retries=0: this fans out to every registered worker,
                # dead ones included — backoff here would stall the
                # notification of the live ones
                put_json(addr, "/notify", payload, timeout=2, retries=0)
            except OSError:
                continue

    def _worker_notify_addrs(self):
        store = getattr(self._rendezvous, "store", None)
        if store is None:
            return []
        addrs = []
        for key in store.keys(_NOTIFY_SCOPE):
            raw = store.get(_NOTIFY_SCOPE, key)
            try:
                info = json.loads(raw)
                addrs.append(f"{info['host']}:{info['port']}")
            except (ValueError, KeyError, TypeError):
                continue
        return addrs
