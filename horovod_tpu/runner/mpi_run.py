"""MPI launch path (reference ``horovod/runner/mpi_run.py``: impl
detection ``_get_mpi_implementation:73``, flag sets ``:32-44``, mpirun
command template ``:177-196`` incl. ``-x`` env forwarding).

``hvtrun --use-mpi`` builds ONE ``mpirun`` command that places all ranks;
each rank then reads ``OMPI_COMM_WORLD_RANK``-style env to derive its
HVT_* slot env (see ``env_from_mpi``)."""

from __future__ import annotations

import os
import shlex
import subprocess
import sys
from typing import List, Optional

OPENMPI = "OpenMPI"
SPECTRUM = "Spectrum MPI"
MPICH = "MPICH"
INTEL = "IMPI"
UNKNOWN = "Unknown"

# flags matching the reference's per-implementation sets (mpi_run.py:32-44)
_BASIC_ARGS = {
    OPENMPI: ["--allow-run-as-root", "--tag-output"],
    SPECTRUM: ["--tag-output"],
    MPICH: [],
    INTEL: [],
    UNKNOWN: [],
}
# large-cluster tuning (reference adds these past 64 hosts)
_LARGE_CLUSTER_ARGS = {
    OPENMPI: ["-mca", "plm_rsh_no_tree_spawn", "true"],
    SPECTRUM: [],
    MPICH: [],
    INTEL: [],
    UNKNOWN: [],
}
_LARGE_CLUSTER_THRESHOLD = 64


def get_mpi_implementation(mpirun: str = "mpirun") -> Optional[str]:
    """Probe ``mpirun --version`` (reference
    _get_mpi_implementation:73). None when mpirun is absent."""
    try:
        out = subprocess.run([mpirun, "--version"], capture_output=True,
                             text=True, timeout=10)
    except (FileNotFoundError, subprocess.TimeoutExpired):
        return None
    text = out.stdout + out.stderr
    if "Open MPI" in text or "OpenRTE" in text:
        return OPENMPI
    if "IBM Spectrum MPI" in text:
        return SPECTRUM
    if "MPICH" in text or "HYDRA" in text:
        return MPICH
    if "Intel(R) MPI" in text:
        return INTEL
    return UNKNOWN


def env_forward_args(impl: str, env_keys: List[str]) -> List[str]:
    """Per-implementation env forwarding (-x for OpenMPI family,
    -genvlist for MPICH/Intel)."""
    if impl in (OPENMPI, SPECTRUM, UNKNOWN):
        out = []
        for k in env_keys:
            out += ["-x", k]
        return out
    return ["-genvlist", ",".join(env_keys)] if env_keys else []


def build_mpirun_command(np: int, hosts: str, command: List[str],
                         env: dict, impl: str = OPENMPI,
                         ssh_port: Optional[int] = None,
                         extra_args: Optional[List[str]] = None
                         ) -> List[str]:
    """Assemble the single mpirun invocation (reference
    mpi_run.py:177-196)."""
    host_list = [h for h in hosts.split(",") if h]
    cmd = ["mpirun", "-np", str(np)]
    cmd += _BASIC_ARGS.get(impl, [])
    if len(host_list) > _LARGE_CLUSTER_THRESHOLD:
        cmd += _LARGE_CLUSTER_ARGS.get(impl, [])
    if impl in (OPENMPI, SPECTRUM, UNKNOWN):
        cmd += ["-H", hosts]
        if ssh_port:
            cmd += ["-mca", "plm_rsh_args", f"-p {ssh_port}"]
    else:
        cmd += ["-hosts", ",".join(h.split(":")[0] for h in host_list)]
    forward = sorted(k for k in env
                     if k.startswith("HVT_") or k in ("PATH", "PYTHONPATH"))
    cmd += env_forward_args(impl, forward)
    cmd += extra_args or []
    cmd += command
    return cmd


def env_from_mpi(base_env: Optional[dict] = None) -> dict:
    """Derive HVT_* slot env from the MPI launcher's environment, so a
    process started by mpirun (not hvtrun) self-configures — the analog
    of the reference reading OMPI env in MPI mode."""
    env = dict(os.environ if base_env is None else base_env)
    pairs = [
        ("HVT_PROCESS_ID", ["OMPI_COMM_WORLD_RANK", "PMI_RANK"]),
        ("HVT_NUM_PROCESSES", ["OMPI_COMM_WORLD_SIZE", "PMI_SIZE"]),
        ("HVT_LOCAL_PROCESS_ID", ["OMPI_COMM_WORLD_LOCAL_RANK",
                                  "MPI_LOCALRANKID"]),
        ("HVT_LOCAL_SIZE", ["OMPI_COMM_WORLD_LOCAL_SIZE",
                            "MPI_LOCALNRANKS"]),
    ]
    out = {}
    for hvt_key, mpi_keys in pairs:
        if env.get(hvt_key):
            continue
        for mk in mpi_keys:
            if env.get(mk):
                out[hvt_key] = env[mk]
                break
    return out


def mpi_run(args, slots, master_addr: str) -> int:
    """Execute the job through mpirun (called from hvtrun with
    --use-mpi). All ranks share one command; slot identity comes from the
    MPI env at worker startup."""
    impl = get_mpi_implementation()
    if impl is None:
        print("[hvtrun] mpirun not found on PATH", file=sys.stderr)
        return 1
    env = dict(os.environ)
    env.update({
        "HVT_CYCLE_TIME_MS": str(args.cycle_time_ms),
        "HVT_FUSION_THRESHOLD": str(args.fusion_threshold_mb << 20),
        "HVT_FROM_MPI": "1",
    })
    # mirror slot_env's backend split (launch.py): engine → C++ control
    # star; jax → jax.distributed coordinator
    if getattr(args, "backend", "engine") == "jax":
        env["HVT_COORDINATOR_ADDR"] = f"{master_addr}:{args.master_port}"
    else:
        env["HVT_MASTER_ADDR"] = master_addr
        env["HVT_MASTER_PORT"] = str(args.master_port)
    hosts = ",".join(sorted({f"{s.hostname}:{s.local_size}"
                             for s in slots}))
    cmd = build_mpirun_command(args.num_proc, hosts, list(args.command),
                               env, impl=impl, ssh_port=args.ssh_port)
    if args.verbose:
        print("[hvtrun] " + " ".join(shlex.quote(c) for c in cmd),
              file=sys.stderr)
    return subprocess.run(cmd, env=env).returncode
