"""Network interface enumeration and reachability probing (reference
``horovod/runner/common/util/network.py`` + the NIC ring check of
``runner/task_fn.py:23``)."""

from __future__ import annotations

import socket
from typing import Dict, List, Optional


def get_local_interfaces(ipv4_only: bool = True) -> Dict[str, List[str]]:
    """Map interface name → addresses on this machine."""
    import psutil

    out: Dict[str, List[str]] = {}
    for name, addrs in psutil.net_if_addrs().items():
        ips = [a.address for a in addrs
               if a.family == socket.AF_INET
               or (not ipv4_only and a.family == socket.AF_INET6)]
        if ips:
            out[name] = ips
    return out


def can_connect(host: str, port: int, timeout: float = 2.0) -> bool:
    try:
        with socket.create_connection((host, port), timeout=timeout):
            return True
    except OSError:
        return False


def probe_reachable(addresses: List[str], port: int,
                    timeout: float = 2.0) -> List[str]:
    """Which of ``addresses`` accept a TCP connection on ``port`` — the
    ring-probe primitive: each task probes the *next* host's candidate
    addresses to weed out NAT'ed/one-way NICs."""
    return [a for a in addresses if can_connect(a, port, timeout)]


def local_addresses() -> List[str]:
    return sorted({ip for ips in get_local_interfaces().values()
                   for ip in ips})


def filter_common_interfaces(per_host_reachable: Dict[str, List[str]]
                             ) -> List[str]:
    """Intersect reachable-NIC names/addresses across hosts (reference
    driver_service.py:218 get_common_interfaces)."""
    sets = [set(v) for v in per_host_reachable.values()]
    if not sets:
        return []
    common = set.intersection(*sets)
    return sorted(common)


def get_free_port(bind: str = "127.0.0.1") -> int:
    with socket.socket() as s:
        s.bind((bind, 0))
        return s.getsockname()[1]
