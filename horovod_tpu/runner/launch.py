"""``hvtrun`` — the launcher CLI (reference ``horovod/runner/launch.py``:
parse_args:242, _run_static:527, run_controller:675).

Usage:
    python -m horovod_tpu.runner.launch -np 4 python train.py
    hvtrun -np 8 -H host1:4,host2:4 python train.py

Local slots run as direct subprocesses; remote hosts are reached over ssh
with the slot env inlined (reference gloo_run.py:65-145 builds the same
per-slot env + ssh command). The engine rendezvous is a TCP control star on
``--master-port`` of the first host, replacing the reference's HTTP-store
rendezvous for static jobs; elastic jobs use the HTTP rendezvous server
(``runner/http_server.py``).
"""

from __future__ import annotations

import argparse
import os
import shlex
import socket
import sys

from horovod_tpu.runner import safe_exec
from horovod_tpu.runner.hosts import (get_host_assignments, parse_hostfile,
                                      parse_hosts)

_LOCAL_NAMES = ("localhost", "127.0.0.1")


def parse_args(argv=None):
    p = argparse.ArgumentParser(
        prog="hvtrun",
        description="Launch a horovod_tpu job (CPU engine processes or one "
                    "process per TPU host).")
    p.add_argument("-np", "--num-proc", type=int, required=True,
                   help="total number of processes")
    p.add_argument("-H", "--hosts", default=None,
                   help="host1:slots,host2:slots (default: localhost:np)")
    p.add_argument("--hostfile", default=None,
                   help="hostfile with 'host slots=N' lines")
    p.add_argument("--master-port", type=int, default=29510,
                   help="engine control-plane port on the first host")
    p.add_argument("--ssh-port", type=int, default=22)
    p.add_argument("--cycle-time-ms", type=int, default=2,
                   help="engine cycle time (reference HOROVOD_CYCLE_TIME)")
    p.add_argument("--fusion-threshold-mb", type=int, default=64,
                   help="tensor fusion buffer threshold "
                        "(reference HOROVOD_FUSION_THRESHOLD)")
    p.add_argument("--timeline", default=None,
                   help="chrome-trace timeline output path "
                        "(reference HOROVOD_TIMELINE)")
    p.add_argument("--stall-warning-sec", type=int, default=60,
                   help="stall inspector warning threshold")
    p.add_argument("--backend", choices=["engine", "jax"], default="engine",
                   help="engine: C++ TCP collectives (CPU/eager); jax: "
                        "jax.distributed bring-up (one process per TPU "
                        "host)")
    p.add_argument("--verbose", action="store_true")
    p.add_argument("command", nargs=argparse.REMAINDER,
                   help="training command")
    args = p.parse_args(argv)
    if not args.command:
        p.error("no training command given")
    if args.command[0] == "--":
        args.command = args.command[1:]
    return args


def _is_local(hostname: str) -> bool:
    return hostname in _LOCAL_NAMES or hostname == socket.gethostname()


def slot_env(base_env, slot, args, master_addr):
    """Per-slot environment (reference gloo_run.py:65-99
    create_slot_env_vars: HOROVOD_RANK/SIZE/LOCAL_RANK/..._ADDR)."""
    env = dict(base_env)
    env.update({
        "HVT_PROCESS_ID": str(slot.rank),
        "HVT_NUM_PROCESSES": str(slot.size),
        "HVT_LOCAL_PROCESS_ID": str(slot.local_rank),
        "HVT_LOCAL_SIZE": str(slot.local_size),
        "HVT_CROSS_RANK": str(slot.cross_rank),
        "HVT_CROSS_SIZE": str(slot.cross_size),
        "HVT_HOSTNAME": slot.hostname,
        "HVT_CYCLE_TIME_MS": str(args.cycle_time_ms),
        "HVT_FUSION_THRESHOLD": str(args.fusion_threshold_mb << 20),
        "HVT_STALL_WARN_SEC": str(args.stall_warning_sec),
    })
    if args.backend == "engine":
        env["HVT_MASTER_ADDR"] = master_addr
        env["HVT_MASTER_PORT"] = str(args.master_port)
    else:
        env["HVT_COORDINATOR_ADDR"] = f"{master_addr}:{args.master_port}"
    if args.timeline:
        env["HVT_TIMELINE"] = args.timeline
    return env


def build_commands(args, slots, master_addr, base_env=None):
    base_env = dict(os.environ if base_env is None else base_env)
    cmds = []
    for slot in slots:
        env = slot_env(base_env, slot, args, master_addr)
        if _is_local(slot.hostname):
            cmds.append((list(args.command), env, slot.rank))
        else:
            # ssh with inline env (reference gloo_run.py:114-145)
            inline = " ".join(
                f"{k}={shlex.quote(v)}" for k, v in env.items()
                if k.startswith("HVT_") or k in ("PATH", "PYTHONPATH"))
            remote = f"cd {shlex.quote(os.getcwd())} && env {inline} " + \
                " ".join(shlex.quote(c) for c in args.command)
            cmds.append((["ssh", "-o", "StrictHostKeyChecking=no", "-p",
                          str(args.ssh_port), slot.hostname, remote],
                         dict(os.environ), slot.rank))
    return cmds


def main(argv=None) -> int:
    args = parse_args(argv)
    if args.hostfile:
        hosts = parse_hostfile(args.hostfile)
    elif args.hosts:
        hosts = parse_hosts(args.hosts)
    else:
        hosts = parse_hosts(f"localhost:{args.num_proc}")
    slots = get_host_assignments(hosts, args.num_proc)
    master_addr = ("127.0.0.1" if _is_local(slots[0].hostname)
                   else slots[0].hostname)
    if args.verbose:
        for s in slots:
            print(f"[hvtrun] rank {s.rank} → {s.hostname} "
                  f"(local {s.local_rank}/{s.local_size}, "
                  f"cross {s.cross_rank}/{s.cross_size})", file=sys.stderr)
    cmds = build_commands(args, slots, master_addr)
    exit_codes = safe_exec.run_all(cmds)
    bad = [(i, rc) for i, rc in enumerate(exit_codes) if rc != 0]
    if bad:
        print(f"[hvtrun] ranks failed: {bad}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
