"""``hvtrun`` — the launcher CLI (reference ``horovod/runner/launch.py``:
parse_args:242, _run_static:527, run_controller:675).

Usage:
    python -m horovod_tpu.runner.launch -np 4 python train.py
    hvtrun -np 8 -H host1:4,host2:4 python train.py

Local slots run as direct subprocesses; remote hosts are reached over ssh
with the slot env inlined (reference gloo_run.py:65-145 builds the same
per-slot env + ssh command). The engine rendezvous is a TCP control star on
``--master-port`` of the first host, replacing the reference's HTTP-store
rendezvous for static jobs; elastic jobs use the HTTP rendezvous server
(``runner/http_server.py``).
"""

from __future__ import annotations

import argparse
import os
import shlex
import socket
import sys

from horovod_tpu.runner import safe_exec
from horovod_tpu.runner.hosts import (get_host_assignments, parse_hostfile,
                                      parse_hosts)

_LOCAL_NAMES = ("localhost", "127.0.0.1")


def parse_args(argv=None):
    p = argparse.ArgumentParser(
        prog="hvtrun",
        description="Launch a horovod_tpu job (CPU engine processes or one "
                    "process per TPU host).")
    # not required at the argparse level so `hvtrun --check-build`
    # answers alone; main() enforces it for actual launches
    p.add_argument("-np", "--num-proc", type=int, default=None,
                   help="total number of processes (required unless "
                        "--check-build)")
    p.add_argument("-H", "--hosts", default=None,
                   help="host1:slots,host2:slots (default: localhost:np)")
    p.add_argument("--hostfile", default=None,
                   help="hostfile with 'host slots=N' lines")
    p.add_argument("--master-port", type=int, default=29510,
                   help="engine control-plane port on the first host")
    p.add_argument("--ssh-port", type=int, default=22)
    p.add_argument("--cycle-time-ms", type=int, default=2,
                   help="engine cycle time (reference HOROVOD_CYCLE_TIME)")
    p.add_argument("--fusion-threshold-mb", type=int, default=64,
                   help="tensor fusion buffer threshold "
                        "(reference HOROVOD_FUSION_THRESHOLD)")
    p.add_argument("--timeline", default=None,
                   help="chrome-trace timeline output path "
                        "(reference HOROVOD_TIMELINE)")
    p.add_argument("--metrics-port", type=int, default=None,
                   help="serve Prometheus GET /metrics from every worker "
                        "at this base port (worker rank r binds "
                        "port+r); 0 binds ephemeral ports")
    p.add_argument("--stall-warning-sec", type=int, default=60,
                   help="stall inspector warning threshold")
    p.add_argument("--ctrl-topology", choices=["star", "tree"],
                   default=None,
                   help="control-plane shape (HVT_CTRL_TOPOLOGY): tree "
                        "elects one leader per host to aggregate "
                        "negotiation frames, capping rank 0's fan-in at "
                        "the host count (docs/performance.md "
                        "§control-plane); star is the default. The "
                        "launcher sets it for every worker — the value "
                        "must agree gang-wide")
    p.add_argument("--autotune", action="store_true",
                   help="enable Bayesian autotuning of fusion threshold "
                        "and cycle time (reference --autotune)")
    p.add_argument("--autotune-log-file", default=None,
                   help="CSV log of autotune samples "
                        "(reference --autotune-log-file)")
    p.add_argument("--backend", choices=["engine", "jax"], default="engine",
                   help="engine: C++ TCP collectives (CPU/eager); jax: "
                        "jax.distributed bring-up (one process per TPU "
                        "host)")
    elastic = p.add_argument_group(
        "elastic", "fault-tolerant launch (reference launch.py:392 "
        "--min-np/--max-np/--host-discovery-script)")
    elastic.add_argument("--min-np", type=int, default=None,
                         help="minimum world size; enables elastic mode")
    elastic.add_argument("--max-np", type=int, default=None,
                         help="maximum world size (default: -np)")
    elastic.add_argument("--host-discovery-script", default=None,
                         help="executable printing one 'host:slots' per "
                              "line; polled every second")
    elastic.add_argument("--reset-limit", type=int, default=None,
                         help="max re-rendezvous rounds before failing")
    elastic.add_argument("--elastic-timeout", type=float, default=600.0,
                         help="seconds to wait for min-np slots")
    elastic.add_argument("--slots", type=int, default=1,
                         help="default slots per discovered host")
    p.add_argument("--use-mpi", action="store_true",
                   help="launch through a single mpirun command "
                        "(reference run_controller mpi path)")
    p.add_argument("--use-jsrun", action="store_true",
                   help="launch through IBM LSF jsrun")
    p.add_argument("--config-file", default=None,
                   help="YAML file supplying any of these flags; "
                        "explicit CLI flags win (reference --config-file)")
    p.add_argument("--verbose", action="store_true")
    p.add_argument("-cb", "--check-build", action="store_true",
                   help="print available frameworks/controllers/tensor "
                        "operations and exit (-np and a training "
                        "command are not required)")
    p.add_argument("command", nargs=argparse.REMAINDER,
                   help="training command")
    args = p.parse_args(argv)
    if not args.command and not args.check_build:
        p.error("no training command given")
    if args.command and args.command[0] == "--":
        args.command = args.command[1:]
    if args.config_file:
        from horovod_tpu.runner.config_parser import apply_config

        args = apply_config(args, args.config_file, p)
    return args


def _is_local(hostname: str) -> bool:
    return hostname in _LOCAL_NAMES or hostname == socket.gethostname()


def _ssh_command(env, hostname, ssh_port, command):
    """Build the per-slot ssh command with inline env (reference
    gloo_run.py:114-145). Shared by the static and elastic paths."""
    inline = " ".join(
        f"{k}={shlex.quote(v)}" for k, v in env.items()
        if k.startswith("HVT_") or k in ("PATH", "PYTHONPATH"))
    remote = f"cd {shlex.quote(os.getcwd())} && env {inline} " + \
        " ".join(shlex.quote(c) for c in command)
    return ["ssh", "-o", "StrictHostKeyChecking=no", "-p",
            str(ssh_port), hostname, remote]


def slot_env(base_env, slot, args, master_addr):
    """Per-slot environment (reference gloo_run.py:65-99
    create_slot_env_vars: HOROVOD_RANK/SIZE/LOCAL_RANK/..._ADDR)."""
    from horovod_tpu.runner.hosts import slot_env_vars

    env = dict(base_env)
    env.update(slot_env_vars(slot))
    env.update({
        "HVT_CYCLE_TIME_MS": str(args.cycle_time_ms),
        "HVT_FUSION_THRESHOLD": str(args.fusion_threshold_mb << 20),
        "HVT_STALL_WARN_SEC": str(args.stall_warning_sec),
    })
    if args.backend == "engine":
        env["HVT_MASTER_ADDR"] = master_addr
        env["HVT_MASTER_PORT"] = str(args.master_port)
    else:
        env["HVT_COORDINATOR_ADDR"] = f"{master_addr}:{args.master_port}"
    if args.timeline:
        # HVT_TIMELINE: the legacy engine-side rank-0 trace (kept as a
        # fallback surface); HVT_TIMELINE_SHARD: the per-rank flight-
        # recorder shard (<path>.rank<r>) every worker records, uploads
        # to the rendezvous KV, and the launcher merges into <path>
        env["HVT_TIMELINE"] = args.timeline
        env["HVT_TIMELINE_SHARD"] = args.timeline
    if getattr(args, "metrics_port", None) is not None:
        env["HVT_METRICS_PORT"] = str(args.metrics_port)
    if getattr(args, "ctrl_topology", None):
        # must agree across the gang (leaders/members derive from it)
        env["HVT_CTRL_TOPOLOGY"] = args.ctrl_topology
    if getattr(args, "autotune", False):
        env["HVT_AUTOTUNE"] = "1"
        if args.autotune_log_file:
            env["HVT_AUTOTUNE_LOG"] = args.autotune_log_file
    return env


def build_commands(args, slots, master_addr, base_env=None,
                   rendezvous_port=None):
    base_env = dict(os.environ if base_env is None else base_env)
    cmds = []
    for slot in slots:
        env = slot_env(base_env, slot, args, master_addr)
        if rendezvous_port is not None:
            # launcher-side KV server (timeline shard upload, /clock
            # handshake, /debugz); a remote worker must dial the
            # LAUNCHER host, not itself. Deliberately NOT
            # HVT_RENDEZVOUS_ADDR: that var is the "elastic launch"
            # marker (elastic/run.py, preemption.py key off it), and a
            # static --timeline run must not trip those paths.
            host = ("127.0.0.1" if _is_local(slot.hostname)
                    else socket.gethostname())
            env["HVT_DIAG_ADDR"] = f"{host}:{rendezvous_port}"
        if _is_local(slot.hostname):
            cmds.append((list(args.command), env, slot.rank))
        else:
            cmds.append((_ssh_command(env, slot.hostname, args.ssh_port,
                                      args.command),
                         dict(os.environ), slot.rank))
    return cmds


def merge_timeline_shards(timeline_path, store, expected_ranks=()):
    """Merge per-rank timeline shards into ``timeline_path``.

    Shards come from the rendezvous KV (``PUT /kv/timeline/<rank>`` at
    worker teardown); any expected rank missing from the KV falls back
    to its local shard file ``<timeline_path>.rank<r>`` — a SIGKILLed
    worker never uploads, but its flushed shard is still loadable
    (``utils/timeline.py`` crash-safety notes)."""
    from horovod_tpu.utils import timeline as tl

    shards, found = [], set()
    if store is not None:
        for key in store.keys("timeline"):
            v = store.get("timeline", key)
            if v is None:
                continue
            shards.append(tl.parse_trace(v.decode(errors="replace")))
            found.add(str(key))
    missing = []
    for r in expected_ranks:
        if str(r) in found:
            continue
        local = f"{timeline_path}.rank{r}"
        if os.path.exists(local):
            shards.append(tl.load_trace(local))
        else:
            missing.append(r)
    if not shards:
        print(f"[hvtrun] timeline: no shards recorded; {timeline_path} "
              f"not written", file=sys.stderr)
        return 0
    merged = tl.merge_traces(shards)
    import json

    with open(timeline_path, "w") as f:
        json.dump(merged, f)
    note = f" (no shard from ranks {missing})" if missing else ""
    print(f"[hvtrun] timeline: merged {len(shards)} shard(s), "
          f"{len(merged)} events -> {timeline_path}{note}",
          file=sys.stderr)
    return len(shards)


def _run_elastic(args) -> int:
    """Elastic launch: start the ElasticDriver + rendezvous server, spawn
    one training subprocess per assigned slot, restart rounds on host
    changes / failures (reference ``launch.py:619`` _run_elastic)."""
    from horovod_tpu.runner.elastic.discovery import (FixedHostDiscovery,
                                                      HostDiscoveryScript)
    from horovod_tpu.runner.elastic.driver import ElasticDriver
    from horovod_tpu.runner.elastic.settings import ElasticSettings
    from horovod_tpu.runner.http_server import RendezvousServer

    if args.host_discovery_script:
        discovery = HostDiscoveryScript(args.host_discovery_script,
                                        default_slots=args.slots)
    elif args.hosts:
        discovery = FixedHostDiscovery(args.hosts)
    else:
        discovery = FixedHostDiscovery(f"localhost:{args.num_proc}")
    settings = ElasticSettings(
        min_np=args.min_np or args.num_proc,
        max_np=args.max_np or args.num_proc,
        elastic_timeout=args.elastic_timeout,
        reset_limit=args.reset_limit, verbose=args.verbose)
    rendezvous = RendezvousServer(verbose=args.verbose)

    def choose_master_port(slots, round_):
        # Engine control-star port for this round, published via world
        # info. When the master slot is on this host, probe a genuinely
        # free port (a fixed rotation window wraps after enough rounds
        # and can collide with a lingering listener from an old round —
        # ADVICE r1); for a remote master fall back to a wide rotation
        # off the configured base.
        if _is_local(slots[0].hostname):
            with socket.socket() as s:
                s.bind(("", 0))
                return s.getsockname()[1]
        return args.master_port + round_ % 2048

    rendezvous.master_port_fn = choose_master_port
    rendezvous_port = rendezvous.start()

    def driver_addr_for(slot_hostname):
        # a remote worker must reach the rendezvous on the *launcher*
        # host, not on itself
        return ("127.0.0.1" if _is_local(slot_hostname)
                else socket.gethostname())

    children = set()
    children_lock = __import__("threading").Lock()

    def create_worker(slot):
        drv_addr = driver_addr_for(slot.hostname)
        mh = (rendezvous.world or {}).get("master_host") or slot.hostname
        master = "127.0.0.1" if _is_local(slot.hostname) and \
            _is_local(mh) else mh
        env = slot_env(dict(os.environ), slot, args, master)
        env["HVT_ELASTIC"] = "1"
        env["HVT_ELASTIC_NOTIFY_ADDR"] = f"{drv_addr}:{rendezvous_port}"
        env["HVT_RENDEZVOUS_ADDR"] = f"{drv_addr}:{rendezvous_port}"
        # per-round engine port, so a worker spawned into round N joins the
        # same control star as survivors re-initializing into round N (see
        # elastic/run.py _apply_slot_env)
        env["HVT_MASTER_PORT_BASE"] = str(args.master_port)
        env["HVT_MASTER_PORT"] = str(
            (rendezvous.world or {}).get("master_port")
            or args.master_port + rendezvous.round % 2048)
        if _is_local(slot.hostname):
            cmd = list(args.command)
        else:
            cmd = _ssh_command(env, slot.hostname, args.ssh_port,
                               args.command)
            env = dict(os.environ)
        child = safe_exec.Child(cmd, env, tag=slot.rank)
        with children_lock:
            children.add(child)
        try:
            return child.wait()
        finally:
            with children_lock:
                children.discard(child)

    def terminate_children():
        with children_lock:
            live = list(children)
        for c in live:
            c.terminate()

    driver = ElasticDriver(rendezvous, discovery, settings,
                           create_worker_fn=create_worker,
                           on_stop=terminate_children)
    # HVT_AUTOSCALE=1: metrics-driven policy loop — scale out on
    # sustained worker backlog, shed/blacklist on failure reports
    # (runner/elastic/autoscaler.py)
    from horovod_tpu.runner.elastic.autoscaler import \
        maybe_start_autoscaler
    autoscaler = maybe_start_autoscaler(driver, rendezvous,
                                        verbose=bool(args.verbose))
    try:
        driver.start(args.num_proc)
        driver.wait()
    finally:
        if autoscaler is not None:
            autoscaler.stop()
        terminate_children()
        if args.timeline:
            # elastic world size varies per round; merge whatever shards
            # workers uploaded (the KV keeps the timeline scope across
            # re-rendezvous resets), with the local-file fallback over
            # the final round's world — elastic is exactly the mode
            # where workers get killed before they can upload
            try:
                final_world = (rendezvous.world or {}).get("size") \
                    or args.num_proc
                merge_timeline_shards(args.timeline, rendezvous.store,
                                      expected_ranks=range(final_world))
            except Exception as e:
                print(f"[hvtrun] timeline merge failed: {e}",
                      file=sys.stderr)
        rendezvous.stop()
    if driver.error:
        print(f"[hvtrun] elastic job failed: {driver.error}",
              file=sys.stderr)
        return 1
    results = driver.get_results()
    bad = {r: rc for r, rc in results.items() if rc != 0}
    if bad:
        print(f"[hvtrun] ranks failed: {sorted(bad.items())}",
              file=sys.stderr)
        return 1
    return 0


def check_build(verbose: bool = False) -> int:
    """Print what this installation can do (reference
    ``runner/launch.py:110`` ``horovodrun --check-build``), recast for
    the TPU stack: framework bindings by importability, the C++ engine
    and TF custom-op library by presence of their built artifacts, and
    the data planes they unlock."""
    import importlib.util
    import os

    def mark(ok):
        return "X" if ok else " "

    def importable(name):
        try:
            return importlib.util.find_spec(name) is not None
        except Exception:
            return False

    from horovod_tpu import __version__
    from horovod_tpu.engine.native import _lib_path

    engine_lib = _lib_path()
    engine = os.path.exists(engine_lib)
    tf_ops = os.path.exists(os.path.join(os.path.dirname(engine_lib),
                                         "libhvt_tf_ops.so"))
    # the Keras wrapper gates on `import tensorflow.keras`
    # (horovod_tpu/keras/__init__.py:_KERAS_AVAILABLE); probing the bare
    # 'tensorflow' spec showed an X for TF builds whose keras shim is
    # broken/absent, so probe the same module the wrapper imports
    keras_ok = importable("tensorflow.keras")
    engine_stats = False
    if engine:
        try:
            from horovod_tpu.engine import native as _native

            engine_stats = bool(_native.engine_stats())
        except Exception:
            engine_stats = False
    out = f"""\
horovod_tpu v{__version__}:

Available Frameworks:
    [X] JAX (core)
    [{mark(importable('tensorflow'))}] TensorFlow
    [{mark(importable('torch'))}] PyTorch
    [{mark(importable('mxnet'))}] MXNet (numpy bridge)
    [{mark(keras_ok)}] Keras

Available Controllers:
    [{mark(engine)}] TCP control star (C++ engine)
    [X] Elastic HTTP rendezvous

Available Tensor Operations:
    [X] XLA/ICI compiled collectives (psum / all_gather / ...)
    [{mark(engine)}] shared-memory local plane
    [{mark(engine)}] TCP ring
    [{mark(engine)}] hierarchical (local RS -> cross AR -> local AG)
    [{mark(tf_ops)}] TF native custom ops

Telemetry:
    [X] Prometheus /metrics registry (hvtrun --metrics-port)
    [{mark(engine_stats)}] engine stats bridge (hvt_engine_stats)"""
    print(out)
    if verbose:
        state = ("present" if engine
                 else "NOT BUILT — run make -C horovod_tpu/csrc")
        print(f"\nengine library: {engine_lib} ({state})")
    return 0


def main(argv=None) -> int:
    args = parse_args(argv)
    # bound by argparse BEFORE the REMAINDER command, so a
    # --check-build belonging to the training script is not hijacked
    if args.check_build:
        return check_build(verbose=args.verbose)
    if args.num_proc is None:
        print("hvtrun: error: -np/--num-proc is required", file=sys.stderr)
        return 2
    if args.min_np is not None or args.host_discovery_script:
        return _run_elastic(args)
    if args.hostfile:
        hosts = parse_hostfile(args.hostfile)
    elif args.hosts:
        hosts = parse_hosts(args.hosts)
    else:
        hosts = parse_hosts(f"localhost:{args.num_proc}")
    slots = get_host_assignments(hosts, args.num_proc)
    master_addr = ("127.0.0.1" if _is_local(slots[0].hostname)
                   else slots[0].hostname)
    if args.use_mpi:
        from horovod_tpu.runner.mpi_run import mpi_run

        return mpi_run(args, slots, master_addr)
    if args.use_jsrun:
        from horovod_tpu.runner.js_run import js_run

        return js_run(args, slots, master_addr)
    if args.verbose:
        for s in slots:
            print(f"[hvtrun] rank {s.rank} → {s.hostname} "
                  f"(local {s.local_rank}/{s.local_size}, "
                  f"cross {s.cross_rank}/{s.cross_size})", file=sys.stderr)
    rendezvous = None
    rendezvous_port = None
    if args.timeline:
        # static jobs rendezvous over the TCP control star; the timeline
        # still needs an HTTP surface for the clock-offset handshake,
        # shard upload, and GET /debugz — start a KV server for the run
        from horovod_tpu.runner.http_server import RendezvousServer

        rendezvous = RendezvousServer(verbose=args.verbose)
        rendezvous.init(slots)
        rendezvous_port = rendezvous.start()
    try:
        cmds = build_commands(args, slots, master_addr,
                              rendezvous_port=rendezvous_port)
        exit_codes = safe_exec.run_all(cmds)
        if args.timeline:
            try:
                merge_timeline_shards(
                    args.timeline,
                    rendezvous.store if rendezvous else None,
                    expected_ranks=range(args.num_proc))
            except Exception as e:
                # training already finished: a merge failure must not
                # eat the per-rank exit-code report below
                print(f"[hvtrun] timeline merge failed: {e}",
                      file=sys.stderr)
    finally:
        if rendezvous is not None:
            rendezvous.stop()
    bad = [(i, rc) for i, rc in enumerate(exit_codes) if rc != 0]
    if bad:
        print(f"[hvtrun] ranks failed: {bad}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
