"""JAX framework binding — the TPU-native analog of ``horovod.torch`` /
``horovod.tensorflow``'s optimizer layer.

``DistributedOptimizer`` wraps any optax ``GradientTransformation`` so that
gradients are combined across workers before being applied — the exact role
of ``hvd.DistributedOptimizer`` (reference ``tensorflow/__init__.py:568``,
``torch/optimizer.py:441``), with the same knobs: op (Average/Sum/Adasum),
compression, pre/postscale, ``gradient_predivide_factor``,
``backward_passes_per_step`` local aggregation
(``tensorflow/gradient_aggregation.py:16``, ``torch/optimizer.py:170-198``).

Where the reductions happen, TPU-natively:

- **shard_map / pmap training loops** (explicit per-chip gradients): pass
  ``axis_name=...`` and the wrapper emits ICI collectives into the step.
- **pjit global-array data parallelism**: XLA's autodiff of a
  batch-sharded loss already inserts the gradient ``psum`` (the compiler
  plays the role of Horovod's background engine). The wrapper then runs
  with ``axis_name=None`` (no second reduction) and still provides
  compression/aggregation/Adasum semantics.
"""

from __future__ import annotations

from typing import Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp
import optax

from horovod_tpu.ops import collective_ops as C
from horovod_tpu.ops.compression import Compression


def allreduce_gradients(grads, *, op=C.Average, axis_name=None,
                        compression=Compression.none,
                        prescale_factor=1.0, postscale_factor=1.0,
                        process_set=C.global_process_set):
    """Reduce a gradient pytree across workers (the body of
    ``_make_allreduce_grads_fn``, reference ``tensorflow/__init__.py:333``).

    ``axis_name=None`` means "already reduced by XLA sharding" and applies
    only the local transforms (compression round-trip, scaling).

    Varying-manual-axes subtlety: under ``shard_map(..., check_vma=True)``
    (the default), JAX's autodiff transpose *already* psums gradients of
    axis-invariant (replicated) parameters — the compiler inserted the
    allreduce for us. Such leaves arrive invariant over ``axis_name`` and
    hold the global **sum**; emitting another collective would be wrong, so
    for Average we only divide by the axis size. Per-shard (varying) leaves
    — including everything under ``check_vma=False`` — get the explicit
    collective.
    """

    def _already_reduced(leaf) -> bool:
        try:
            from jax._src import config as _jcfg

            if not _jcfg._check_vma.value:
                return False
            return axis_name not in jax.typeof(leaf).vma
        except Exception:
            return False

    def _one(g):
        c, ctx = compression.compress(g)
        if axis_name is not None:
            if isinstance(c, jax.core.Tracer) and _already_reduced(c):
                if op is C.Average:
                    c = c / jax.lax.axis_size(axis_name)
                if prescale_factor != 1.0:
                    c = c * jnp.asarray(prescale_factor, c.dtype)
                if postscale_factor != 1.0:
                    c = c * jnp.asarray(postscale_factor, c.dtype)
            else:
                c = C.allreduce(c, op=op, axis_name=axis_name,
                                prescale_factor=prescale_factor,
                                postscale_factor=postscale_factor,
                                process_set=process_set)
        else:
            if prescale_factor != 1.0:
                c = c * jnp.asarray(prescale_factor, c.dtype)
            if postscale_factor != 1.0:
                c = c * jnp.asarray(postscale_factor, c.dtype)
        return compression.decompress(c, ctx)

    return jax.tree.map(_one, grads)


class _AggregationState(NamedTuple):
    """State for backward_passes_per_step local aggregation."""

    step: jnp.ndarray           # int32 counter
    acc: optax.Updates          # gradient accumulator
    inner_state: optax.OptState


def DistributedGradientTransformation(
        optimizer: optax.GradientTransformation,
        *,
        op=C.Average,
        axis_name: Optional[str] = None,
        compression=Compression.none,
        prescale_factor: float = 1.0,
        postscale_factor: float = 1.0,
        gradient_predivide_factor: float = 1.0,
        backward_passes_per_step: int = 1,
        average_aggregated_gradients: bool = False,
        num_groups: int = 0,
        process_set=C.global_process_set,
        reduce_filter: Optional[Callable[[tuple], bool]] = None,
) -> optax.GradientTransformation:
    """optax transformation: [accumulate N steps] → allreduce → inner update.

    Mirrors the reference semantics:

    - ``gradient_predivide_factor`` splits Average's 1/size between a
      prescale (f/size) and postscale (1/f), reference
      ``tensorflow/__init__.py:578-590``.
    - ``backward_passes_per_step > 1`` accumulates locally and performs the
      collective + inner update every Nth call; in-between calls return
      zero updates and leave the inner optimizer state untouched
      (``gradient_aggregation.py:16``; implemented with ``lax.cond`` so it
      stays a single compiled program).
    - ``average_aggregated_gradients`` divides the accumulator by N before
      reducing (``gradient_aggregation.py`` allreduce_grads path).
    - ``num_groups`` is accepted for parity; on the traced path XLA's
      collective combiner performs fusion, so the hint is a no-op.
    - under an explicit ``shard_map`` training loop,
      ``backward_passes_per_step > 1`` requires ``check_vma=False`` on the
      shard_map (the held/emit ``lax.cond`` mixes axis-varying and
      axis-invariant values, which the varying-manual-axes type checker
      can't yet express); ``jit``/pjit loops (``axis_name=None``) have no
      such restriction.
    - ``reduce_filter`` (TPU extension): predicate on the leaf path; leaves
      where it returns False skip the collective (stay process-local).
    """
    del num_groups
    if gradient_predivide_factor != 1.0:
        if op is not C.Average:
            raise ValueError(
                "gradient_predivide_factor requires op=Average "
                "(reference tensorflow/__init__.py:585)")
        # Average = Sum with pre/post scales (reference splits it this way).
        op = C.Sum
        prescale_factor = prescale_factor * gradient_predivide_factor
        postscale_factor = postscale_factor / gradient_predivide_factor
        _predivide_by_size = True
    else:
        _predivide_by_size = False

    def _reduce(grads):
        pre, post = prescale_factor, postscale_factor
        if _predivide_by_size:
            if axis_name is not None:
                n = jax.lax.axis_size(axis_name)
            else:
                n = 1
            pre = pre / n
        if reduce_filter is None:
            return allreduce_gradients(
                grads, op=op, axis_name=axis_name, compression=compression,
                prescale_factor=pre, postscale_factor=post,
                process_set=process_set)
        flat = jax.tree_util.tree_flatten_with_path(grads)
        paths_leaves, treedef = flat
        out = []
        for path, leaf in paths_leaves:
            if reduce_filter(path):
                out.append(allreduce_gradients(
                    leaf, op=op, axis_name=axis_name,
                    compression=compression, prescale_factor=pre,
                    postscale_factor=post, process_set=process_set))
            else:
                out.append(leaf)
        return jax.tree.unflatten(treedef, out)

    if backward_passes_per_step == 1:
        def init(params):
            return optimizer.init(params)

        def update(grads, state, params=None, **extra):
            reduced = _reduce(grads)
            return optimizer.update(reduced, state, params, **extra)

        return optax.GradientTransformation(init, update)

    n_steps = backward_passes_per_step

    def init(params):
        return _AggregationState(
            step=jnp.zeros((), jnp.int32),
            acc=jax.tree.map(jnp.zeros_like, params),
            inner_state=optimizer.init(params),
        )

    def update(grads, state, params=None, **extra):
        acc = jax.tree.map(jnp.add, state.acc, grads)
        emit = (state.step + 1) % n_steps == 0

        def do_emit(operand):
            acc_, inner_ = operand
            g = acc_
            if average_aggregated_gradients:
                g = jax.tree.map(lambda x: x / n_steps, g)
            g = _reduce(g)
            updates, new_inner = optimizer.update(g, inner_, params, **extra)
            return updates, new_inner, jax.tree.map(jnp.zeros_like, acc_)

        def hold(operand):
            acc_, inner_ = operand
            zeros = jax.tree.map(jnp.zeros_like, acc_)
            return zeros, inner_, acc_

        updates, new_inner, new_acc = jax.lax.cond(
            emit, do_emit, hold, (acc, state.inner_state))
        return updates, _AggregationState(step=state.step + 1, acc=new_acc,
                                          inner_state=new_inner)

    return optax.GradientTransformation(init, update)


# The user-facing name, matching hvd.DistributedOptimizer.
DistributedOptimizer = DistributedGradientTransformation


from horovod_tpu.jax.callbacks import (  # noqa: E402,F401
    BroadcastGlobalVariablesCallback, Callback, CallbackList,
    LearningRateScheduleCallback, LearningRateWarmupCallback,
    MetricAverageCallback, MetricsCallback, exponential_schedule,
    warmup_schedule)


def __getattr__(name):
    # lazy: sync_batch_norm imports flax, which must stay an optional
    # dependency of `import horovod_tpu`
    if name == "SyncBatchNorm":
        from horovod_tpu.jax.sync_batch_norm import SyncBatchNorm

        return SyncBatchNorm
    raise AttributeError(name)


def PartialDistributedGradientTransformation(
        optimizer: optax.GradientTransformation,
        local_layers=(),
        **kwargs) -> optax.GradientTransformation:
    """Like DistributedOptimizer but leaves parameters whose path mentions a
    name in ``local_layers`` un-reduced (process-local parameters, e.g.
    per-host embeddings). Parity with the reference lineage's
    PartialDistributedOptimizer concept."""
    names = tuple(local_layers)

    def _filter(path) -> bool:
        keys = "/".join(str(getattr(p, "key", getattr(p, "idx", p)))
                        for p in path)
        return not any(n in keys for n in names)

    return DistributedGradientTransformation(
        optimizer, reduce_filter=_filter, **kwargs)
