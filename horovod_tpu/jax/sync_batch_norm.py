"""Cross-replica batch normalization — the JAX/flax counterpart of the
reference's ``SyncBatchNormalization`` (``tensorflow/sync_batch_norm.py:22``
and ``torch/sync_batch_norm.py``): batch statistics (mean/var) are
averaged across all workers of the axis before normalizing, so small
per-chip batches behave like one large global batch.

TPU-natively this is flax's BatchNorm with ``axis_name`` set — XLA lowers
the moment reduction to an ICI ``psum`` fused into the surrounding
program (no out-of-graph engine involvement). This wrapper pins the
default to the framework's world axis and degrades to local statistics
when no mesh axis is bound (size-1 and plain-jit cases), matching the
reference's size==1 behavior."""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax

from horovod_tpu.parallel.mesh import WORLD_AXIS


def _axis_bound(name: Optional[str]) -> bool:
    if name is None:
        return False
    try:
        jax.lax.axis_size(name)
        return True
    except Exception:
        return False


class SyncBatchNorm(nn.Module):
    """Drop-in BatchNorm whose statistics are synchronized over the mesh
    axis (default: the global world axis) when one is bound.

    Under pure pjit data parallelism (global arrays), plain BatchNorm over
    the global batch is already globally correct; this module matters for
    explicit shard_map/pmap loops where the local batch is a shard.
    """

    use_running_average: Optional[bool] = None
    momentum: float = 0.99
    epsilon: float = 1e-5
    dtype: Optional[Any] = None
    use_bias: bool = True
    use_scale: bool = True
    axis_name: Optional[str] = WORLD_AXIS

    @nn.compact
    def __call__(self, x, use_running_average: Optional[bool] = None):
        axis = self.axis_name if _axis_bound(self.axis_name) else None
        bn = nn.BatchNorm(
            use_running_average=self.use_running_average,
            momentum=self.momentum, epsilon=self.epsilon,
            dtype=self.dtype, use_bias=self.use_bias,
            use_scale=self.use_scale, axis_name=axis, name="bn")
        return bn(x, use_running_average=use_running_average)
