"""Training-loop callbacks — the JAX-native counterpart of the
reference's Keras callback set (``horovod/_keras/callbacks.py``:
``BroadcastGlobalVariablesCallbackImpl:22``,
``MetricAverageCallbackImpl:48``, ``LearningRateScheduleCallbackImpl:89``,
``LearningRateWarmupCallbackImpl:172``).

JAX has no Model.fit; a training loop drives a ``CallbackList`` at the
standard hook points::

    cbs = hvt.jax.CallbackList([
        hvt.jax.BroadcastGlobalVariablesCallback(0),
        hvt.jax.MetricAverageCallback(),
        hvt.jax.LearningRateWarmupCallback(initial_lr=0.1 * hvt.size(),
                                           warmup_epochs=5,
                                           steps_per_epoch=100),
    ])
    state = cbs.on_train_begin(state)
    for epoch ...:
        cbs.on_epoch_begin(epoch)
        for batch ...:
            lr = cbs.learning_rate(step)     # or use the optax schedule
            ...
        metrics = cbs.on_epoch_end(epoch, metrics)

For purely functional loops the same warmup/schedule math is available as
optax schedules via ``warmup_schedule`` / ``exponential_schedule``.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional

import numpy as np


class Callback:
    def on_train_begin(self, state):
        return state

    def on_epoch_begin(self, epoch: int):
        pass

    def on_epoch_end(self, epoch: int, metrics: Optional[Dict] = None):
        return metrics

    def learning_rate(self, step: int) -> Optional[float]:
        return None


class CallbackList(Callback):
    def __init__(self, callbacks: List[Callback]):
        self.callbacks = list(callbacks)

    def on_train_begin(self, state):
        for cb in self.callbacks:
            state = cb.on_train_begin(state)
        return state

    def on_epoch_begin(self, epoch):
        for cb in self.callbacks:
            cb.on_epoch_begin(epoch)

    def on_epoch_end(self, epoch, metrics=None):
        for cb in self.callbacks:
            metrics = cb.on_epoch_end(epoch, metrics)
        return metrics

    def learning_rate(self, step):
        lr = None
        for cb in self.callbacks:
            v = cb.learning_rate(step)
            lr = v if v is not None else lr
        return lr


class BroadcastGlobalVariablesCallback(Callback):
    """Broadcast the initial state pytree from ``root_rank`` at train
    start so all workers begin identical (reference
    ``BroadcastGlobalVariablesCallbackImpl:22``)."""

    def __init__(self, root_rank: int = 0):
        self.root_rank = root_rank

    def on_train_begin(self, state):
        from horovod_tpu.ops.functions import broadcast_parameters

        return broadcast_parameters(state, root_rank=self.root_rank)


class MetricAverageCallback(Callback):
    """Average epoch metrics across workers (reference
    ``MetricAverageCallbackImpl:48``). Metrics dict values may be floats
    or 0-d arrays."""

    def on_epoch_end(self, epoch, metrics=None):
        if not metrics:
            return metrics
        import horovod_tpu as hvt

        keys = sorted(metrics)
        vals = np.asarray([float(metrics[k]) for k in keys], np.float64)
        avg = np.asarray(hvt.allreduce(vals, name=f"metric_avg_e{epoch}",
                                       average=True))
        out = dict(metrics)
        out.update({k: float(v) for k, v in zip(keys, avg)})
        return out


class MetricsCallback(Callback):
    """Fold training-loop metrics into the ``horovod_tpu.metrics``
    registry so they ride the same scrape/snapshot plane as the engine
    counters.

    Every value in the epoch-end metrics dict becomes a sample of the
    ``hvt_train_metric`` gauge (labeled by metric name); epochs are
    counted in ``hvt_train_epochs_total``. Pair with
    :class:`MetricAverageCallback` (ordered before this one) to publish
    the cross-worker average instead of the local value. A Keras adapter
    is exported as ``horovod_tpu.keras.MetricsCallback``."""

    def __init__(self, registry=None, prefix: str = "hvt_train"):
        from horovod_tpu import metrics as _metrics

        reg = registry if registry is not None else _metrics.registry()
        self._gauge = reg.gauge(
            f"{prefix}_metric", "training metrics by name (last epoch)",
            ("metric",))
        self._epochs = reg.counter(f"{prefix}_epochs_total",
                                   "training epochs completed")

    def on_epoch_end(self, epoch, metrics=None):
        self._epochs.inc()
        for k, v in (metrics or {}).items():
            try:
                self._gauge.labels(metric=str(k)).set(float(v))
            except (TypeError, ValueError):
                continue  # non-numeric entries (e.g. strings) are skipped
        return metrics


class LearningRateScheduleCallback(Callback):
    """Piecewise/exponential LR schedule (reference
    ``LearningRateScheduleCallbackImpl:89``): from ``start_epoch`` until
    ``end_epoch``, lr = initial_lr * multiplier(epoch); ``staircase``
    holds the multiplier constant within an epoch, otherwise the epoch is
    fractional per step."""

    def __init__(self, initial_lr: float, multiplier, start_epoch: int = 0,
                 end_epoch: Optional[int] = None, staircase: bool = True,
                 steps_per_epoch: Optional[int] = None):
        self.initial_lr = initial_lr
        self.start_epoch = start_epoch
        self.end_epoch = end_epoch
        self.staircase = staircase
        self.steps_per_epoch = steps_per_epoch
        self.current_epoch = 0
        if callable(multiplier):
            self.multiplier = multiplier
        else:
            self.multiplier = lambda epoch: multiplier

    def on_epoch_begin(self, epoch):
        self.current_epoch = epoch

    def _in_range(self, epoch) -> bool:
        if epoch < self.start_epoch:
            return False
        return self.end_epoch is None or epoch < self.end_epoch

    def learning_rate(self, step):
        if self.staircase or not self.steps_per_epoch:
            epoch = self.current_epoch
        else:
            epoch = step / self.steps_per_epoch
        if not self._in_range(epoch):
            return None
        return self.initial_lr * self.multiplier(epoch)


class LearningRateWarmupCallback(LearningRateScheduleCallback):
    """Gradual warmup from lr/size to the scaled lr over the first
    epochs — "Accurate Large Minibatch SGD" style, reference
    ``LearningRateWarmupCallbackImpl:172``: multiplier =
    1/size * (epoch * (size - 1) / warmup_epochs + 1)."""

    def __init__(self, initial_lr: float, warmup_epochs: int = 5,
                 steps_per_epoch: Optional[int] = None, verbose: bool = False,
                 size: Optional[int] = None):
        import horovod_tpu as hvt

        self.size = size if size is not None else hvt.size()
        self.warmup_epochs = warmup_epochs
        self.verbose = verbose

        def multiplier(epoch):
            if self.size <= 1 or self.warmup_epochs == 0:
                return 1.0
            return 1.0 / self.size * (
                epoch * (self.size - 1) / self.warmup_epochs + 1)

        super().__init__(initial_lr=initial_lr, multiplier=multiplier,
                         start_epoch=0, end_epoch=warmup_epochs,
                         staircase=False, steps_per_epoch=steps_per_epoch)

    def learning_rate(self, step):
        lr = super().learning_rate(step)
        # after the warmup window, hold the target lr (the reference
        # leaves the optimizer at the scaled lr) instead of returning
        # None and leaving the loop without a value
        return lr if lr is not None else self.initial_lr

    def on_epoch_end(self, epoch, metrics=None):
        if self.verbose and epoch == self.end_epoch - 1:
            print(f"LearningRateWarmup: reached target lr "
                  f"{self.initial_lr:.6g} after {self.warmup_epochs} "
                  f"epochs")
        return metrics


def warmup_schedule(initial_lr: float, warmup_steps: int,
                    size: Optional[int] = None):
    """optax-compatible schedule: linear warmup from initial_lr/size to
    initial_lr over warmup_steps, then constant."""
    import horovod_tpu as hvt

    n = size if size is not None else hvt.size()

    def schedule(step):
        import jax.numpy as jnp

        frac = jnp.minimum(step / max(warmup_steps, 1), 1.0)
        start = initial_lr / n
        return start + (initial_lr - start) * frac

    return schedule


def exponential_schedule(initial_lr: float, decay: float,
                         steps_per_epoch: int, staircase: bool = True):
    """optax-compatible schedule matching LearningRateScheduleCallback
    with multiplier = decay**epoch."""

    def schedule(step):
        import jax.numpy as jnp

        epoch = step / steps_per_epoch
        if staircase:
            epoch = jnp.floor(epoch)
        return initial_lr * jnp.power(decay, epoch)

    return schedule
