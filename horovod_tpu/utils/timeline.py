"""Chrome-trace timeline + distributed flight recorder
(reference ``horovod/common/timeline.{h,cc}``).

Records the lifecycle of every collective as chrome://tracing events:
NEGOTIATE → (QUEUE, MEMCPY_IN_FUSION_BUFFER, <BACKEND>_ALLREDUCE, ...) →
done, one "thread" lane per tensor, exactly the reference's event scheme
(activity names at ``common/common.h:31-62``). Every event is tagged
``pid=<process rank>``, so shards from different ranks merge into one
clock-aligned multi-process view (``merge_files`` /
``python -m horovod_tpu.utils.timeline merge``).

Three producers feed one per-rank trace shard:

- the **Python producer API** below (``negotiate_start`` /
  ``activity_start`` / ...), called around eager dispatches;
- the **engine drainer thread**, which pulls the C++ engine's flight
  recorder ring (``csrc/events.h`` via ``hvt_events_drain``) and
  converts ENQUEUED / NEGOTIATE / FUSED / EXEC / DONE / STALL records
  into chrome events on per-tensor ``(engine)`` lanes;
- ``mark_cycle`` instants on a dedicated metadata-named CYCLE lane.

Architecture mirrors the reference's lock-free writer split
(``timeline.h:84-86``): producers append to an unbounded deque and
signal a ``threading.Condition``; a dedicated writer thread drains to
disk, so the hot path never blocks on file I/O and an idle timeline
costs ~zero CPU (no polling). The writer flushes after every batch, so
a SIGKILLed worker still leaves a loadable shard: Chrome and Perfetto
both tolerate a trace whose closing ``]`` is missing, and
``load_trace`` below repairs it explicitly when merging.

Timestamps are wall-clock microseconds (``time.time_ns``) plus a
cross-rank clock offset measured against the rendezvous server's
``GET /clock`` at init (``measure_clock_offset_us``) — the same epoch
the C++ ring stamps with (``EventRing::NowEpochUs``), so engine-thread
and dispatch-thread events interleave correctly across ranks.

For the traced/TPU path, per-op device timings come from XLA profiler
sessions (``jax.profiler``); ``start()`` optionally arms one so both
views share a trace directory.
"""

from __future__ import annotations

import collections
import json
import threading
import time

_state = None
_state_lock = threading.Lock()

# Microseconds to ADD to local wall-clock timestamps so every rank's
# events land on the rendezvous server's clock (0 when no handshake ran;
# same-host ranks share a clock anyway).
_clock_offset_us = 0.0

# kind wire ids — must match csrc/events.h EventKind / native.EVENT_KINDS
_ENQUEUED, _NEG_B, _NEG_E, _RANK_READY, _FUSED, _EXEC_B, _EXEC_E, \
    _DONE, _CYCLE, _STALL, _WAKEUP, _ABORT, _CTRL_BYTES, _WIRE_B, \
    _WIRE_E, _RECONNECT, _REPLAY, _RECOVERY = range(18)

# control-plane role names by wire id — must match csrc/engine.h
# CtrlRole (the CTRL_BYTES event stamps the recording rank's role into
# its op field; hvt_analyze attributes ctrl bytes per role through
# this table). Cross-checked by tools/hvt_lint.py.
CTRL_ROLES = ("root", "leader", "member")

_ENGINE_DRAIN_SEC = 0.05


def set_clock_offset_us(offset_us: float):
    global _clock_offset_us
    _clock_offset_us = float(offset_us)


def clock_offset_us() -> float:
    return _clock_offset_us


def _now_us() -> float:
    return time.time_ns() / 1e3 + _clock_offset_us


def measure_clock_offset_us(addr: str, samples: int = 5,
                            timeout: float = 2.0) -> float:
    """Clock-offset handshake against the rendezvous server's
    ``GET /clock``: offset = server_epoch_us + rtt/2 − local_now, taking
    the minimum-RTT sample (NTP's classic estimator). Workers call this
    once at init so cross-rank (cross-host) shard timestamps align."""
    from horovod_tpu.runner.http_client import get_json

    best_rtt, best_off = None, 0.0
    for _ in range(max(1, samples)):
        t0 = time.time_ns() / 1e3
        obj = get_json(addr, "/clock", timeout=timeout)
        t1 = time.time_ns() / 1e3
        rtt = t1 - t0
        off = float(obj["epoch_us"]) + rtt / 2.0 - t1
        if best_rtt is None or rtt < best_rtt:
            best_rtt, best_off = rtt, off
    return best_off


class _TimelineState:
    def __init__(self, path, mark_cycles, pid=0, upload_addr=None):
        self.path = path
        self.mark_cycles = mark_cycles
        self.pid = int(pid)
        self.upload_addr = upload_addr
        self.queue = collections.deque()
        self.cond = threading.Condition()
        self.stopping = False
        self.tensor_lanes = {}
        self.next_lane = 0
        self.cycle_lane = None
        self.file = open(path, "w")
        self.file.write("[\n")
        self.first = True
        self._emit({"name": "process_name", "ph": "M", "pid": self.pid,
                    "args": {"name": f"rank {self.pid}"}})
        self._emit({"name": "process_sort_index", "ph": "M",
                    "pid": self.pid, "args": {"sort_index": self.pid}})
        self.writer = threading.Thread(target=self._drain, daemon=True)
        self.writer.start()
        self.drainer = None
        self._maybe_start_engine_drainer()

    # ----------------------------------------------------------- lanes
    def _lane(self, key, display_name):
        if key not in self.tensor_lanes:
            self.tensor_lanes[key] = self.next_lane
            self.next_lane += 1
            self._emit({"name": "thread_name", "ph": "M", "pid": self.pid,
                        "tid": self.tensor_lanes[key],
                        "args": {"name": display_name}})
        return self.tensor_lanes[key]

    def _cycle_lane(self):
        # dedicated metadata-named lane: cycle instants must never land
        # in tensor lane 0 (they used to hardcode tid=0)
        if self.cycle_lane is None:
            self.cycle_lane = self._lane(("__cycle__",), "CYCLE")
        return self.cycle_lane

    # ------------------------------------------------------- producers
    def _emit(self, ev):
        with self.cond:
            self.queue.append(ev)
            self.cond.notify()

    def record(self, tensor_name, phase, name=None):
        tid = self._lane(tensor_name, tensor_name)
        ev = {"ph": phase, "pid": self.pid, "tid": tid, "ts": _now_us()}
        if name is not None:
            ev["name"] = name
        self._emit(ev)

    def cycle_mark(self, name="CYCLE_START", ts=None, args=None):
        ev = {"ph": "i", "pid": self.pid, "tid": self._cycle_lane(),
              "name": name, "ts": _now_us() if ts is None else ts,
              "s": "p"}
        if args:
            ev["args"] = args
        self._emit(ev)

    # ---------------------------------------------------------- writer
    def _drain(self):
        while True:
            with self.cond:
                while not self.queue and not self.stopping:
                    self.cond.wait()
                batch = list(self.queue)
                self.queue.clear()
                stopping = self.stopping
            for ev in batch:
                if not self.first:
                    self.file.write(",\n")
                self.first = False
                self.file.write(json.dumps(ev))
            if batch:
                # crash-safety: everything up to here survives a SIGKILL
                # (the trailing "]" is optional to Chrome/Perfetto and
                # repaired by load_trace)
                self.file.flush()
            if stopping and not self.queue:
                break
        self.file.write("\n]\n")
        self.file.close()

    # -------------------------------------------- engine flight recorder
    def _maybe_start_engine_drainer(self):
        try:
            from horovod_tpu.engine import native

            if not native.events_supported():
                return
        except Exception:
            return
        self.drainer_stop = threading.Event()
        self.drainer = threading.Thread(target=self._drain_engine,
                                        daemon=True)
        self.drainer.start()

    def _drain_engine(self):
        from horovod_tpu.engine import native

        while not self.drainer_stop.wait(_ENGINE_DRAIN_SEC):
            self._convert_engine_events(native.drain_events())
        # final sweep: Shutdown's DONE/abort events land after the last
        # periodic tick
        self._convert_engine_events(native.drain_events())

    def _convert_engine_events(self, events):
        for ev in events:
            kind = ev["kind"]
            ts = ev["ts_us"] + _clock_offset_us
            name = ev["name"]
            op = ev["op_name"]
            if kind == _CYCLE:
                if self.mark_cycles:
                    # arg counts the responses the cycle executed — not
                    # a cycle index, so label it unambiguously
                    self.cycle_mark(
                        name=f"ENGINE_CYCLE({ev['arg']} responses)",
                        ts=ts)
                continue
            if kind == _WAKEUP:
                # cycle-lane instant (no tensor name): arg = submissions
                # drained, arg2 = submit→drain coalescing latency (µs)
                if self.mark_cycles:
                    self.cycle_mark(
                        name=f"WAKEUP({ev['arg']} subs, "
                             f"{ev['arg2']} µs)", ts=ts)
                continue
            if kind == _CTRL_BYTES:
                # cycle-lane instant: control-plane frame bytes this
                # cycle (arg = sent, arg2 = received) — hvt_analyze
                # reads these for the per-cycle negotiation cost. The
                # event's op field carries the rank's control role
                # (engine.h CtrlRole / hvt_analyze CTRL_ROLES), so tree
                # mode's leader hop is attributable separately.
                if self.mark_cycles:
                    role = (CTRL_ROLES[ev["op"]]
                            if 0 <= ev["op"] < len(CTRL_ROLES)
                            else "member")
                    self.cycle_mark(
                        name=f"CTRL({ev['arg']} B tx, "
                             f"{ev['arg2']} B rx)",
                        ts=ts, args={"role": role})
                continue
            if kind == _RECONNECT or kind == _REPLAY:
                # always recorded, like ABORT: link heals are rare
                # headline events. The event's op field carries the
                # LinkPlane (0 ctrl, 1 data); the name is the peer
                # ("rank N"). RECONNECT: arg = dial retries, arg2 =
                # time spent RECONNECTING (µs) — the stall the heal
                # cost, which hvt_analyze's recovery section sums.
                # REPLAY: arg = whole control frames re-sent, arg2 =
                # bytes re-sent from the replay ring.
                plane = "ctrl" if ev["op"] == 0 else "data"
                if kind == _RECONNECT:
                    args = {"plane": plane, "peer": name,
                            "retries": ev["arg"],
                            "duration_us": ev["arg2"]}
                    label = f"RECONNECT({name}, {plane})"
                else:
                    args = {"plane": plane, "peer": name,
                            "frames": ev["arg"], "bytes": ev["arg2"]}
                    label = f"REPLAY({name}, {plane})"
                self._emit({"ph": "i", "pid": self.pid,
                            "tid": self._cycle_lane(), "name": label,
                            "ts": ts, "s": "g", "args": args})
                continue
            if kind == _RECOVERY:
                # always recorded, like ABORT/RECONNECT: an elastic
                # recovery is a rare headline event. name = the phase
                # ("restore"/"rendezvous"/"rebuild"/...), arg = outcome
                # (0 ok, 1 fallback-to-application-restore, 2 failed),
                # arg2 = the phase's measured duration in µs — stamped
                # from Python after re-init (hvt_record_event), since
                # the engine is down for most of a recovery.
                outcome = {0: "ok", 1: "fallback", 2: "failed"}.get(
                    ev["arg"], "?")
                self._emit({"ph": "i", "pid": self.pid,
                            "tid": self._cycle_lane(),
                            "name": f"RECOVERY({name}, {outcome})",
                            "ts": ts, "s": "g",
                            "args": {"phase": name, "outcome": outcome,
                                     "duration_us": ev["arg2"]}})
                continue
            if kind == _ABORT:
                # always recorded (mark_cycles or not): an abort is the
                # headline event of any trace that contains one. The
                # event name field carries the truncated reason; arg is
                # the cause id (native.ABORT_CAUSES).
                from horovod_tpu.engine.native import ABORT_CAUSES

                cause = (ABORT_CAUSES[ev["arg"]]
                         if 0 <= ev["arg"] < len(ABORT_CAUSES)
                         else "internal")
                self._emit({"ph": "i", "pid": self.pid,
                            "tid": self._cycle_lane(),
                            "name": f"ENGINE_ABORT({cause})", "ts": ts,
                            "s": "g",
                            "args": {"cause": cause, "reason": name}})
                continue
            key = ("eng", name)
            tid = self._lane(key, f"{name} (engine)")
            out = {"pid": self.pid, "tid": tid, "ts": ts}
            if kind == _NEG_B:
                out.update(ph="B", name=f"NEGOTIATE_{op}")
            elif kind == _NEG_E or kind == _EXEC_E or kind == _WIRE_E:
                out.update(ph="E")
            elif kind == _EXEC_B:
                # lane rides along so hvt_analyze can attribute exec
                # time per process-set lane (0 = global)
                out.update(ph="B", name=op,
                           args={"lane": ev.get("lane", 0)})
            elif kind == _WIRE_B:
                # nested span inside the exec span: the TCP duplex
                # pump's wire phase (arg2 = bytes this pump moves)
                out.update(ph="B", name=f"WIRE_{op}",
                           args={"lane": ev.get("lane", 0),
                                 "bytes": ev["arg2"]})
            elif kind == _RANK_READY:
                out.update(ph="i", name=f"RANK_READY_{ev['arg']}", s="t")
            elif kind == _ENQUEUED:
                out.update(ph="i", name="ENQUEUED", s="t",
                           args={"lane": ev.get("lane", 0)})
            elif kind == _FUSED:
                out.update(ph="i", name=f"FUSED_x{ev['arg2']}", s="t")
            elif kind == _DONE:
                ok = ev["arg"] == 0
                out.update(ph="i", name="DONE" if ok else "ERROR", s="t")
            elif kind == _STALL:
                missing = [r for r in range(64)
                           if ev["arg2"] & (1 << r)]
                out.update(ph="i", name="STALL", s="g",
                           args={"missing_ranks": missing,
                                 "waiting_sec": ev["arg"]})
            else:
                continue
            self._emit(out)

    # ----------------------------------------------------------- close
    def close(self):
        if self.drainer is not None:
            self.drainer_stop.set()
            self.drainer.join(timeout=5)
        with self.cond:
            self.stopping = True
            self.cond.notify()
        self.writer.join(timeout=5)
        self._upload()

    def _shard_landed(self, deadline_sec: float = 8.0) -> bool:
        """Poll a HEAD on the shard's KV key until the leader's batch
        flush lands it (or the deadline passes)."""
        import time as _time
        import urllib.request

        url = (f"http://{self.upload_addr}/kv/timeline/{self.pid}")
        deadline = _time.monotonic() + deadline_sec
        while _time.monotonic() < deadline:
            try:
                req = urllib.request.Request(url, method="HEAD")
                with urllib.request.urlopen(req, timeout=3) as resp:
                    if resp.status == 200:
                        return True
            except Exception:
                pass
            _time.sleep(0.3)
        return False

    def _upload(self):
        """PUT the finished shard to the rendezvous KV store
        (``/kv/timeline/<rank>``) so the launcher can merge every rank's
        shard without a shared filesystem. Best-effort: a dead server
        must not fail teardown (the local file is the fallback)."""
        if not self.upload_addr:
            return
        try:
            # leader-routed when the KV relay is active: at teardown
            # every rank uploads at once, and folding the shard storm
            # through per-host /kvbulk batches keeps the driver's
            # request fan-in O(hosts) (metrics/telemetry.py). Relay
            # success only means QUEUED on the leader, and the leader
            # may itself be tearing down — so verify the shard landed
            # (HEAD against the driver) and fall back to the direct
            # PUT when it did not. A shard is merged exactly once
            # (same key), so the fallback can never duplicate it.
            from horovod_tpu.metrics.telemetry import relay_put

            with open(self.path, "rb") as f:
                data = f.read()
            delivered = relay_put(self.upload_addr, "timeline",
                                  str(self.pid), data=data,
                                  urgent=True, timeout=15) and \
                self._shard_landed()
            if not delivered:
                from horovod_tpu.runner.http_client import put_bytes

                put_bytes(self.upload_addr,
                          f"/kv/timeline/{self.pid}", data)
        except Exception as e:
            import sys

            print(f"horovod_tpu: timeline shard upload to "
                  f"{self.upload_addr} failed ({type(e).__name__}: {e}); "
                  f"shard remains at {self.path}", file=sys.stderr)


def _default_pid() -> int:
    import os

    try:
        from horovod_tpu.engine import native

        if native.engine_running():
            return native.engine_rank()
    except Exception:
        pass
    return int(os.environ.get("HVT_PROCESS_ID", "0"))


def start(path, mark_cycles=False, xla_profiler=True, pid=None,
          upload_addr=None):
    """Begin recording (reference ``operations.cc:738`` horovod_start_timeline).

    With ``xla_profiler=True`` (default) an XLA/PJRT profiler session is
    armed alongside the engine timeline (SURVEY §5.1: "same per-tensor
    lifecycle trace, plus hooks into XLA/PJRT profiler sessions"): device
    activity of every compiled step lands as an xplane trace under
    ``<path>.xplane/`` (TensorBoard / xprof readable), so one
    ``hvt.start_timeline()`` captures the control plane AND the compiled
    data plane. Armed best-effort: a profiler that cannot start (another
    session already active, backend without profiling) never blocks the
    engine timeline.

    Session ownership: JAX allows ONE active profiler session, and while
    the timeline holds it a user's own ``jax.profiler.start_trace``
    fails. Pass ``xla_profiler=False`` (or set ``HVT_TIMELINE_XLA=0``)
    when your code manages its own profiler sessions; if a session is
    already active when the timeline starts, the timeline leaves it
    untouched and records without device traces (ADVICE r4).

    ``pid`` tags every event (defaults to the engine/process rank);
    ``upload_addr`` makes ``stop()`` PUT the finished shard to
    ``http://<upload_addr>/kv/timeline/<pid>`` (the hvtrun --timeline
    collection path).
    """
    import os as _os

    global _state
    with _state_lock:
        if _state is not None:
            return
        _state = _TimelineState(
            path, mark_cycles,
            pid=_default_pid() if pid is None else pid,
            upload_addr=upload_addr)
        _state.xla_profiling = False
        if _os.environ.get("HVT_TIMELINE_XLA", "1") == "0":
            xla_profiler = False
        if xla_profiler:
            try:
                import jax

                jax.profiler.start_trace(path + ".xplane")
                _state.xla_profiling = True
            except Exception:
                # includes "already active": that session belongs to the
                # user — never stolen, and stop() below won't touch it
                # because xla_profiling stays False
                pass


def stop():
    global _state
    with _state_lock:
        if _state is None:
            return
        if getattr(_state, "xla_profiling", False):
            try:
                import jax

                jax.profiler.stop_trace()
            except Exception as e:
                # a failed trace DUMP is data loss the user asked for —
                # never silent (unlike best-effort start)
                import sys

                print(f"horovod_tpu: XLA profiler trace dump failed "
                      f"({type(e).__name__}: {e}); the .xplane trace "
                      f"may be empty or partial", file=sys.stderr)
        _state.close()
        _state = None


def active() -> bool:
    return _state is not None


# --- producer API (used by the engine + collective ops) --------------------

def negotiate_start(tensor_name, op_name):
    s = _state
    if s:
        s.record(tensor_name, "B", name=f"NEGOTIATE_{op_name}")


def negotiate_end(tensor_name):
    s = _state
    if s:
        s.record(tensor_name, "E")


def activity_start(tensor_name, activity):
    s = _state
    if s:
        s.record(tensor_name, "B", name=activity)


def activity_end(tensor_name):
    s = _state
    if s:
        s.record(tensor_name, "E")


def mark_cycle():
    s = _state
    if s and s.mark_cycles:
        s.cycle_mark()


# --- shard loading / merging ------------------------------------------------

def load_trace(path):
    """Load one trace shard file (see :func:`parse_trace`)."""
    with open(path) as f:
        return parse_trace(f.read())


def parse_trace(text):
    """Parse one trace shard, tolerating truncation: a crashed writer
    leaves no closing ``]`` (and possibly a half-written last event).
    Chrome/Perfetto already accept such files; merging must too."""
    try:
        return json.loads(text)
    except json.JSONDecodeError:
        pass
    repaired = text.rstrip().rstrip(",")
    if repaired.startswith("["):
        try:
            return json.loads(repaired + "\n]")
        except json.JSONDecodeError:
            pass
    # last resort: the writer emits one event per line — keep every line
    # that parses, drop the torn tail
    events = []
    for line in text.splitlines():
        line = line.strip().rstrip(",")
        if not line or line in "[]":
            continue
        try:
            ev = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(ev, dict):
            events.append(ev)
    return events


def merge_traces(shards):
    """Merge per-rank event lists into one chrome-trace event list.

    Metadata (``ph == "M"``) events sort first so lane/process names
    apply before their events; everything else orders by timestamp. A
    ``process_name`` metadata event is synthesized for any pid that
    lacks one (older shards)."""
    merged, named_pids, seen_pids = [], set(), set()
    for events in shards:
        for ev in events:
            if not isinstance(ev, dict):
                continue
            merged.append(ev)
            pid = ev.get("pid")
            if pid is not None:
                seen_pids.add(pid)
                if ev.get("ph") == "M" and ev.get("name") == "process_name":
                    named_pids.add(pid)
    for pid in sorted(seen_pids - named_pids):
        merged.append({"name": "process_name", "ph": "M", "pid": pid,
                       "args": {"name": f"rank {pid}"}})
    merged.sort(key=lambda e: (0 if e.get("ph") == "M" else 1,
                               e.get("ts", 0)))
    return merged


def merge_files(shard_paths, out_path) -> int:
    """Merge shard files into one chrome://tracing-loadable JSON file;
    returns the merged event count."""
    merged = merge_traces([load_trace(p) for p in shard_paths])
    with open(out_path, "w") as f:
        json.dump(merged, f)
    return len(merged)


def _main(argv=None):
    import argparse

    p = argparse.ArgumentParser(
        prog="python -m horovod_tpu.utils.timeline",
        description="offline timeline shard tools")
    sub = p.add_subparsers(dest="cmd", required=True)
    m = sub.add_parser(
        "merge",
        help="merge per-rank shards into one chrome://tracing file")
    m.add_argument("shards", nargs="+", help="per-rank shard files")
    m.add_argument("-o", "--output", default="timeline.merged.json")
    args = p.parse_args(argv)
    n = merge_files(args.shards, args.output)
    print(f"merged {len(args.shards)} shard(s), {n} events "
          f"-> {args.output}")
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(_main())
