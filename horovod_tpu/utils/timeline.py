"""Chrome-trace timeline (reference ``horovod/common/timeline.{h,cc}``).

Records the lifecycle of every collective as chrome://tracing events:
NEGOTIATE → (QUEUE, MEMCPY_IN_FUSION_BUFFER, <BACKEND>_ALLREDUCE, ...) →
done, one "thread" lane per tensor, exactly the reference's event scheme
(activity names at ``common/common.h:31-62``).

Architecture mirrors the reference's lock-free writer split
(``timeline.h:84-86``): producers append to an unbounded deque (append is
atomic under the GIL — the Python analog of the SPSC queue) and a dedicated
writer thread drains to disk, so the hot path never blocks on file I/O.
For the traced/TPU path, per-op device timings come from XLA profiler
sessions (``jax.profiler``); ``start()`` optionally arms one so both views
share a trace directory.
"""

from __future__ import annotations

import collections
import json
import threading
import time

_state = None
_state_lock = threading.Lock()


class _TimelineState:
    def __init__(self, path, mark_cycles):
        self.path = path
        self.mark_cycles = mark_cycles
        self.queue = collections.deque()
        self.stop_event = threading.Event()
        self.tensor_lanes = {}
        self.next_lane = 0
        self.file = open(path, "w")
        self.file.write("[\n")
        self.first = True
        self.writer = threading.Thread(target=self._drain, daemon=True)
        self.writer.start()

    def _lane(self, tensor_name):
        if tensor_name not in self.tensor_lanes:
            self.tensor_lanes[tensor_name] = self.next_lane
            self.next_lane += 1
            self._emit({"name": "thread_name", "ph": "M", "pid": 0,
                        "tid": self.tensor_lanes[tensor_name],
                        "args": {"name": tensor_name}})
        return self.tensor_lanes[tensor_name]

    def _emit(self, ev):
        self.queue.append(ev)

    def record(self, tensor_name, phase, name=None):
        tid = self._lane(tensor_name)
        ev = {"ph": phase, "pid": 0, "tid": tid,
              "ts": time.perf_counter_ns() / 1e3}
        if name is not None:
            ev["name"] = name
        self._emit(ev)

    def _drain(self):
        while not self.stop_event.is_set() or self.queue:
            try:
                ev = self.queue.popleft()
            except IndexError:
                time.sleep(0.001)
                continue
            if not self.first:
                self.file.write(",\n")
            self.first = False
            self.file.write(json.dumps(ev))
        self.file.write("\n]\n")
        self.file.close()

    def close(self):
        self.stop_event.set()
        self.writer.join(timeout=5)


def start(path, mark_cycles=False, xla_profiler=True):
    """Begin recording (reference ``operations.cc:738`` horovod_start_timeline).

    With ``xla_profiler=True`` (default) an XLA/PJRT profiler session is
    armed alongside the engine timeline (SURVEY §5.1: "same per-tensor
    lifecycle trace, plus hooks into XLA/PJRT profiler sessions"): device
    activity of every compiled step lands as an xplane trace under
    ``<path>.xplane/`` (TensorBoard / xprof readable), so one
    ``hvt.start_timeline()`` captures the control plane AND the compiled
    data plane. Armed best-effort: a profiler that cannot start (another
    session already active, backend without profiling) never blocks the
    engine timeline.

    Session ownership: JAX allows ONE active profiler session, and while
    the timeline holds it a user's own ``jax.profiler.start_trace``
    fails. Pass ``xla_profiler=False`` (or set ``HVT_TIMELINE_XLA=0``)
    when your code manages its own profiler sessions; if a session is
    already active when the timeline starts, the timeline leaves it
    untouched and records without device traces (ADVICE r4).
    """
    import os as _os

    global _state
    with _state_lock:
        if _state is not None:
            return
        _state = _TimelineState(path, mark_cycles)
        _state.xla_profiling = False
        if _os.environ.get("HVT_TIMELINE_XLA", "1") == "0":
            xla_profiler = False
        if xla_profiler:
            try:
                import jax

                jax.profiler.start_trace(path + ".xplane")
                _state.xla_profiling = True
            except Exception:
                # includes "already active": that session belongs to the
                # user — never stolen, and stop() below won't touch it
                # because xla_profiling stays False
                pass


def stop():
    global _state
    with _state_lock:
        if _state is None:
            return
        if getattr(_state, "xla_profiling", False):
            try:
                import jax

                jax.profiler.stop_trace()
            except Exception as e:
                # a failed trace DUMP is data loss the user asked for —
                # never silent (unlike best-effort start)
                import sys

                print(f"horovod_tpu: XLA profiler trace dump failed "
                      f"({type(e).__name__}: {e}); the .xplane trace "
                      f"may be empty or partial", file=sys.stderr)
        _state.close()
        _state = None


def active() -> bool:
    return _state is not None


# --- producer API (used by the engine + collective ops) --------------------

def negotiate_start(tensor_name, op_name):
    s = _state
    if s:
        s.record(tensor_name, "B", name=f"NEGOTIATE_{op_name}")


def negotiate_end(tensor_name):
    s = _state
    if s:
        s.record(tensor_name, "E")


def activity_start(tensor_name, activity):
    s = _state
    if s:
        s.record(tensor_name, "B", name=activity)


def activity_end(tensor_name):
    s = _state
    if s:
        s.record(tensor_name, "E")


def mark_cycle():
    s = _state
    if s and s.mark_cycles:
        s._emit({"ph": "i", "pid": 0, "tid": 0, "name": "CYCLE_START",
                 "ts": time.perf_counter_ns() / 1e3, "s": "g"})
