"""Sequence / context parallelism — ring attention and Ulysses.

The reference has no sequence parallelism (SURVEY.md §5.7: the only relevant
primitive is ``alltoall``, reference ``operations.cc:1099``). On TPU long
context is first-class, so this module provides the two standard strategies,
built on XLA collectives over ICI:

- **Ring attention** (`ring_attention`): each device owns a sequence shard of
  Q and streams K/V shards around the ring with ``lax.ppermute`` while
  accumulating flash-attention-style online softmax. Peak memory per device is
  O(seq/N); comm is overlap-friendly neighbor exchange on the ICI torus.
  (Pattern: Liu et al., "Ring Attention with Blockwise Transformers", 2023.)

- **Ulysses attention** (`ulysses_attention`): ``lax.all_to_all`` reshards
  from sequence-sharded to head-sharded, runs dense local attention over the
  full sequence, and reshards back. Comm volume is O(seq·d) per device pair
  but only 2 all-to-alls per layer; best when heads ≥ devices.
  (Pattern: DeepSpeed-Ulysses, Jacobs et al., 2023.)

Both are written as **per-shard functions** to be used under
``jax.shard_map`` (or inside a larger shard_mapped training step), plus
convenience wrappers that apply shard_map for you.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

try:  # jax>=0.4.35 exposes shard_map at top level
    from jax import shard_map as _shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map

_NEG_INF = -1e30


def _local_attention(q, k, v, q_pos, k_pos, *, causal, scale):
    """One blockwise attention step, returning unnormalized (o, m, l).

    q: [B, Sq, H, D]; k/v: [B, Sk, H, D]; q_pos/k_pos: global token indices
    used for causal masking across sequence shards.
    Returns o [B, Sq, H, D] (fp32), m, l [B, H, Sq] (fp32 running max / sum).
    """
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        mask = (k_pos[None, :] <= q_pos[:, None])[None, None]
        scores = jnp.where(mask, scores, _NEG_INF)
    m = jnp.max(scores, axis=-1)                      # [B, H, Sq]
    p = jnp.exp(scores - m[..., None])
    l = jnp.sum(p, axis=-1)                           # [B, H, Sq]
    o = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v,
                   preferred_element_type=jnp.float32)
    return o, m, l


def ring_attention_shard(q, k, v, *, axis_name, causal=True, scale=None,
                         use_flash=False):
    """Ring attention on per-device shards; call under ``shard_map``.

    Args:
      q, k, v: [batch, seq_shard, heads, head_dim] — this device's sequence
        shard (sequence axis sharded over ``axis_name``).
      axis_name: mesh axis carrying the sequence shards.
      causal: apply a causal mask using *global* token positions.
      scale: softmax scale; default ``head_dim ** -0.5``.
      use_flash: run each K/V block through the pallas fused kernel
        (``ops/flash_attention.py``) instead of the einsum-softmax block
        step — O(shard) VMEM-resident scores instead of a materialized
        [Sq × Sk] tile. Blocks combine via the kernel's differentiable
        logsumexp output. ``"auto"`` resolves by THIS function's local
        shard length (``q.shape[1]``) against the measured crossover —
        resolved here, under shard_map, where the per-device shape is
        unambiguous regardless of who owns the shard_map (ADVICE r4).

    Returns [batch, seq_shard, heads, head_dim] in q.dtype.
    """
    from horovod_tpu.ops.flash_attention import resolve_flash

    if resolve_flash(use_flash, q.shape[1]):
        return _ring_flash_shard(q, k, v, axis_name=axis_name,
                                 causal=causal, scale=scale)
    n = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    b, s, h, d = q.shape
    # Grouped-query attention: K/V may carry FEWER heads than Q. The
    # ring circulates the small K/V buffers (ICI payload shrinks by the
    # group factor — the point of GQA at long context) and each step
    # broadcasts them to the query head count LOCALLY, where XLA fuses
    # the repeat into the attention einsum instead of materializing it.
    h_kv = k.shape[2]
    if h % h_kv:
        raise ValueError(f"query heads ({h}) must be a multiple of K/V "
                         f"heads ({h_kv})")
    group = h // h_kv
    if scale is None:
        scale = d ** -0.5

    q_pos = idx * s + jnp.arange(s)
    perm = [(j, (j + 1) % n) for j in range(n)]

    o0 = jnp.zeros((b, s, h, d), jnp.float32)
    m0 = jnp.full((b, h, s), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, s), jnp.float32)

    def body(step, carry):
        o, m, l, k_blk, v_blk = carry
        # After `step` rotations this device holds the shard that started on
        # ring neighbor (idx - step) mod n.
        k_idx = (idx - step) % n
        k_pos = k_idx * s + jnp.arange(s)
        if group > 1:   # local GQA broadcast; the RING carries h_kv heads
            kb = jnp.repeat(k_blk, group, axis=2)
            vb = jnp.repeat(v_blk, group, axis=2)
        else:
            kb, vb = k_blk, v_blk
        o_blk, m_blk, l_blk = _local_attention(
            q, kb, vb, q_pos, k_pos, causal=causal, scale=scale)
        m_new = jnp.maximum(m, m_blk)
        c_old = jnp.exp(m - m_new)        # rescale previous accumulator
        c_blk = jnp.exp(m_blk - m_new)
        l_new = l * c_old + l_blk * c_blk
        o_new = (o * c_old.transpose(0, 2, 1)[..., None]
                 + o_blk * c_blk.transpose(0, 2, 1)[..., None])
        k_nxt = lax.ppermute(k_blk, axis_name, perm)
        v_nxt = lax.ppermute(v_blk, axis_name, perm)
        return o_new, m_new, l_new, k_nxt, v_nxt

    o, m, l, _, _ = lax.fori_loop(0, n, body, (o0, m0, l0, k, v))
    # Fully-masked rows (can't happen with causal self-attention over the
    # full ring, but guard against l == 0 from user masks).
    l = jnp.maximum(l, 1e-30)
    out = o / l.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def _ring_flash_shard(q, k, v, *, axis_name, causal, scale):
    """Ring attention where each block step IS the flash kernel.

    With sequence shards, the causal structure is block-triangular: the
    K/V shard that started on this device attends causally (the kernel's
    own mask — positions align), shards from EARLIER ring positions are
    fully visible (no mask), and later shards are fully hidden (skipped
    via an lse of −∞, so their combine weight underflows to exactly 0).
    Blocks merge by the flash kernel's differentiable logsumexp:
    ``o = Σ_i exp(lse_i − logaddexp_i lse_i) · o_i``.
    """
    from horovod_tpu.ops.flash_attention import flash_attention_with_lse

    n = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    b, s, h, d = q.shape
    if scale is None:
        scale = d ** -0.5
    perm = [(j, (j + 1) % n) for j in range(n)]

    def flash_blk(blk_causal):
        def run(k_blk, v_blk):
            # out_dtype fp32: the kernel's accumulator reaches the
            # logsumexp combine unrounded (parity with the einsum ring
            # path, which carries fp32 end-to-end)
            o, lse = flash_attention_with_lse(q, k_blk, v_blk,
                                              causal=blk_causal,
                                              scale=scale,
                                              out_dtype=jnp.float32)
            return o, lse
        return run

    def masked_blk(k_blk, v_blk):
        return (jnp.zeros((b, s, h, d), jnp.float32),
                jnp.full((b, s, h), _NEG_INF, jnp.float32))

    def body(step, carry):
        o, lse, k_blk, v_blk = carry
        k_idx = (idx - step) % n
        if causal:
            case = jnp.where(k_idx == idx, 0,
                             jnp.where(k_idx < idx, 1, 2))
            o_blk, lse_blk = lax.switch(
                case, [flash_blk(True), flash_blk(False), masked_blk],
                k_blk, v_blk)
        else:
            o_blk, lse_blk = flash_blk(False)(k_blk, v_blk)
        lse_new = jnp.logaddexp(lse, lse_blk)
        w_old = jnp.exp(lse - lse_new)[..., None]
        w_blk = jnp.exp(lse_blk - lse_new)[..., None]
        o_new = o * w_old + o_blk * w_blk
        k_nxt = lax.ppermute(k_blk, axis_name, perm)
        v_nxt = lax.ppermute(v_blk, axis_name, perm)
        return o_new, lse_new, k_nxt, v_nxt

    o0 = jnp.zeros((b, s, h, d), jnp.float32)
    # finite −∞ stand-in: fully-masked rows produce 0, never inf−inf NaN
    lse0 = jnp.full((b, s, h), _NEG_INF, jnp.float32)
    o, _, _, _ = lax.fori_loop(0, n, body, (o0, lse0, k, v))
    return o.astype(q.dtype)


def ulysses_attention_shard(q, k, v, *, axis_name, causal=True, scale=None,
                            attn_fn=None, use_flash=False):
    """Ulysses (all-to-all) attention on per-device shards; under shard_map.

    Reshard [B, S/N, H, D] → all_to_all → [B, S, H/N, D], run dense local
    attention over the full sequence with a head subset, reshard back.
    ``heads`` must be divisible by the axis size.

    After the head exchange the local problem IS full-sequence causal
    attention, so ``use_flash=True`` runs it through the pallas fused
    kernel (``ops/flash_attention.py``) — O(seq) memory where the dense
    path materializes the [S × S] score matrix. ``attn_fn`` overrides
    both.
    """
    n = lax.axis_size(axis_name)
    b, s, h, d = q.shape
    if h % n != 0:
        raise ValueError(f"Ulysses needs heads ({h}) divisible by the "
                         f"sequence-parallel axis size ({n})")
    # GQA: K/V may carry fewer heads; the head-exchange all_to_all then
    # needs the K/V head count divisible by the axis too (each device
    # ends up with h/n query heads and h_kv/n K/V heads — the group
    # structure is preserved because consecutive query heads share a
    # K/V head)
    h_kv = k.shape[2]
    if h % h_kv:
        raise ValueError(f"query heads ({h}) must be a multiple of K/V "
                         f"heads ({h_kv})")
    if h_kv != h and h_kv % n != 0:
        raise ValueError(
            f"Ulysses with grouped-query K/V needs K/V heads ({h_kv}) "
            f"divisible by the axis size ({n}); repeat K/V to the "
            f"query head count first for smaller head counts")
    group = h // h_kv
    if scale is None:
        scale = d ** -0.5

    def a2a(x, fwd):
        # tiled all_to_all: split heads across devices, gather sequence
        # (fwd) or the reverse.
        split, concat = (2, 1) if fwd else (1, 2)
        return lax.all_to_all(x, axis_name, split_axis=split,
                              concat_axis=concat, tiled=True)

    qg, kg, vg = a2a(q, True), a2a(k, True), a2a(v, True)  # [B, S, H/N, D]
    # after the head exchange the local problem is FULL-sequence
    # attention, so "auto" resolves against the gathered length
    from horovod_tpu.ops.flash_attention import resolve_flash

    if attn_fn is None and resolve_flash(use_flash, qg.shape[1]):
        from horovod_tpu.ops.flash_attention import flash_attention

        # the kernel serves GQA zero-copy (head-index aliasing)
        attn_fn = functools.partial(flash_attention, causal=causal,
                                    scale=scale)
    if attn_fn is None:
        if group > 1:   # local broadcast for the dense einsum path
            kg = jnp.repeat(kg, group, axis=2)
            vg = jnp.repeat(vg, group, axis=2)
        pos = jnp.arange(s * n)
        og, _, l = _local_attention(qg, kg, vg, pos, pos,
                                    causal=causal, scale=scale)
        og = (og / jnp.maximum(l, 1e-30).transpose(0, 2, 1)[..., None]
              ).astype(q.dtype)
    else:
        og = attn_fn(qg, kg, vg)
    return a2a(og, False)


def _wrap(shard_fn, q, k, v, *, mesh, axis_name, seq_specs, **kw):
    fn = functools.partial(shard_fn, axis_name=axis_name, **kw)
    return _shard_map(fn, mesh=mesh, in_specs=(seq_specs,) * 3,
                      out_specs=seq_specs, check_vma=False)(q, k, v)


def ring_attention(q, k, v, *, mesh, axis_name="sp", seq_specs=None,
                   causal=True, scale=None, use_flash=False):
    """Global-array convenience wrapper: shard_map + `ring_attention_shard`.

    ``seq_specs`` is the PartitionSpec of q/k/v (default: batch over 'dp' if
    present, sequence over ``axis_name``, heads over 'tp' if present).
    """
    if seq_specs is None:
        seq_specs = _default_specs(mesh, axis_name)
    return _wrap(ring_attention_shard, q, k, v, mesh=mesh,
                 axis_name=axis_name, seq_specs=seq_specs,
                 causal=causal, scale=scale, use_flash=use_flash)


def ulysses_attention(q, k, v, *, mesh, axis_name="sp", seq_specs=None,
                      causal=True, scale=None, use_flash=False):
    """Global-array convenience wrapper for `ulysses_attention_shard`."""
    if seq_specs is None:
        seq_specs = _default_specs(mesh, axis_name)
    return _wrap(ulysses_attention_shard, q, k, v, mesh=mesh,
                 axis_name=axis_name, seq_specs=seq_specs,
                 causal=causal, scale=scale, use_flash=use_flash)


def _default_specs(mesh, axis_name):
    names = mesh.axis_names
    dp = "dp" if "dp" in names else None
    tp = "tp" if "tp" in names else None
    return P(dp, axis_name, tp, None)
