"""Pipeline parallelism (PP) — GPipe-style microbatch pipelining over a
``pp`` mesh axis.

The reference has no pipeline parallelism (SURVEY.md §2.6); on TPU it
falls out of the SPMD building blocks: every stage runs the same compiled
program each tick, activations hop to the next stage with
``lax.ppermute`` over ICI, and the schedule is a ``lax.scan`` —
compiler-friendly control flow with static shapes, no host round-trips.

Schedule: B microbatches over S stages take B + S - 1 ticks. At tick t,
stage s computes microbatch ``t - s`` (a bubble when that index is out of
range — inherent to GPipe; keep B ≫ S to amortize). Stage boundaries are
neighbor exchanges on the ICI torus, so communication per tick is one
activation tensor per link.

Constraints of this formulation: every stage maps activations of one
uniform shape to the same shape (standard for transformer blocks).
Autodiff works through the whole schedule (``scan`` + ``ppermute`` are
differentiable), so ``jax.grad`` of a pipelined loss gives the 1F1B-less
GPipe backward for free.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

try:
    from jax import shard_map as _shard_map
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map as _shard_map

PIPELINE_AXIS = "pp"


def split_microbatches(x, n_micro: int):
    """[batch, ...] → [n_micro, batch/n_micro, ...]."""
    b = x.shape[0]
    if b % n_micro:
        raise ValueError(f"batch {b} not divisible by {n_micro} microbatches")
    return x.reshape((n_micro, b // n_micro) + x.shape[1:])


def merge_microbatches(x):
    """Inverse of :func:`split_microbatches`."""
    return x.reshape((-1,) + x.shape[2:])


def pipeline_apply(stage_fn, stage_params, microbatches,
                   axis_name: str = PIPELINE_AXIS):
    """Run the pipeline; call INSIDE ``shard_map`` over ``axis_name``.

    - ``stage_fn(params, x) -> y`` with ``y.shape == x.shape``.
    - ``stage_params``: this stage's parameter pytree (leaves already
      sliced to the local stage, leading stage dim squeezed).
    - ``microbatches``: ``[n_micro, mb, ...]`` — the full input,
      replicated over the axis (only stage 0 reads it).

    Returns ``[n_micro, mb, ...]`` outputs, valid on the LAST stage
    (other stages hold zeros); wrap with :func:`pipeline` to get the
    result gathered to every shard.
    """
    S = lax.axis_size(axis_name)
    s = lax.axis_index(axis_name)
    n_micro = microbatches.shape[0]
    ticks = n_micro + S - 1
    mb_shape = microbatches.shape[1:]

    fwd_perm = [(i, i + 1) for i in range(S - 1)]

    def tick(carry, t):
        recv, out_buf = carry
        mb_idx = jnp.clip(t, 0, n_micro - 1)
        first_stage_in = lax.dynamic_index_in_dim(
            microbatches, mb_idx, keepdims=False)
        inp = jnp.where(s == 0, first_stage_in, recv)
        act = stage_fn(stage_params, inp)
        sent = lax.ppermute(act, axis_name, fwd_perm)
        # last stage: act computed at tick t belongs to microbatch t-(S-1)
        out_idx = t - (S - 1)
        write = (s == S - 1) & (out_idx >= 0)
        out_buf = jnp.where(
            write,
            lax.dynamic_update_index_in_dim(
                out_buf, act, jnp.clip(out_idx, 0, n_micro - 1), 0),
            out_buf)
        return (sent, out_buf), None

    init = (jnp.zeros(mb_shape, microbatches.dtype),
            jnp.zeros((n_micro,) + mb_shape, microbatches.dtype))
    (_, out_buf), _ = lax.scan(tick, init, jnp.arange(ticks))
    return out_buf


def pipeline(stage_fn, stacked_params, x, n_micro: int, mesh,
             axis_name: str = PIPELINE_AXIS):
    """Convenience wrapper: shard stacked stage parameters over the pipe
    axis, run the schedule, return ``[batch, ...]`` outputs on every
    shard.

    ``stacked_params``: pytree with a leading stage dimension of size S
    on every leaf (the scan-over-layers layout).
    """

    def per_shard(params, xs):
        local = jax.tree.map(lambda p: jnp.squeeze(p, 0), params)
        mb = split_microbatches(xs, n_micro)
        out = pipeline_apply(stage_fn, local, mb, axis_name=axis_name)
        # result lives on the last stage; a psum broadcasts it (all other
        # shards contribute zeros)
        out = lax.psum(out, axis_name)
        return merge_microbatches(out)

    return _shard_map(
        per_shard, mesh=mesh,
        in_specs=(jax.tree.map(lambda _: P(axis_name), stacked_params),
                  P()),
        out_specs=P(),
        check_vma=False)(stacked_params, x)


def stage_partition_spec(stacked_params, axis_name: str = PIPELINE_AXIS):
    """PartitionSpecs placing each leaf's leading stage dim on the pipe
    axis (for device_put before entering :func:`pipeline`)."""
    return jax.tree.map(
        lambda leaf: P(*((axis_name,) + (None,) * (leaf.ndim - 1))),
        stacked_params)
