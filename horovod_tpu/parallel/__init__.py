from horovod_tpu.parallel.mesh import (
    build_global_mesh,
    global_mesh,
    hierarchical_mesh,
    make_parallel_mesh,
    WORLD_AXIS,
    LOCAL_AXIS,
    CROSS_AXIS,
)

__all__ = [
    "build_global_mesh",
    "global_mesh",
    "hierarchical_mesh",
    "make_parallel_mesh",
    "WORLD_AXIS",
    "LOCAL_AXIS",
    "CROSS_AXIS",
]
