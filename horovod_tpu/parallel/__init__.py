from horovod_tpu.parallel.mesh import (
    build_global_mesh,
    global_mesh,
    hierarchical_mesh,
    make_parallel_mesh,
    WORLD_AXIS,
    LOCAL_AXIS,
    CROSS_AXIS,
)
from horovod_tpu.parallel.fsdp import (
    FSDP_AXIS,
    fsdp_partition_spec,
    init_sharded_state,
    shard_pytree,
)
from horovod_tpu.parallel.pipeline import (
    PIPELINE_AXIS,
    merge_microbatches,
    pipeline,
    pipeline_apply,
    split_microbatches,
    stage_partition_spec,
)

__all__ = [
    "build_global_mesh",
    "global_mesh",
    "hierarchical_mesh",
    "make_parallel_mesh",
    "WORLD_AXIS",
    "LOCAL_AXIS",
    "CROSS_AXIS",
    "FSDP_AXIS",
    "fsdp_partition_spec",
    "init_sharded_state",
    "shard_pytree",
    "PIPELINE_AXIS",
    "merge_microbatches",
    "pipeline",
    "pipeline_apply",
    "split_microbatches",
    "stage_partition_spec",
]
