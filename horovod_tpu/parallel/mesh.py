"""Device meshes — the TPU-native communicator layer.

The reference maintains three communicators — GLOBAL, LOCAL (one node),
CROSS (one rank per node) — built at init (``horovod/common/common.h:115-119``,
``mpi_controller.cc:25-82``) and used by hierarchical collectives
(``ops/nccl_operations.cc:188-350``). On TPU the analog is a
:class:`jax.sharding.Mesh`:

- the **global mesh** is 1-D over every chip (axis ``hvt_world``) — GLOBAL;
- the **hierarchical mesh** is 2-D ``(hvt_cross, hvt_local)`` =
  (hosts, chips-per-host), so a ``psum`` over ``hvt_local`` rides ICI within
  a host and a ``psum`` over ``hvt_cross`` crosses DCN — exactly the
  reference's intra-node reduce-scatter / inter-node allreduce / intra-node
  allgather decomposition, except XLA emits and schedules the collectives.

``make_parallel_mesh`` builds general N-D meshes for dp/fsdp/pp/tp/sp/ep —
the parallelism strategies §2.6 of SURVEY.md marks absent in the reference
but which the TPU design gets from sharding annotations.
"""

from __future__ import annotations

import numpy as np

WORLD_AXIS = "hvt_world"
LOCAL_AXIS = "hvt_local"
CROSS_AXIS = "hvt_cross"

# Canonical parallelism axis names, outermost (most DCN-friendly) first.
# dp/fsdp change gradients (allreduce-heavy, tolerate DCN); tp/sp are
# latency-critical (keep on ICI, innermost).
PARALLEL_AXES = ("dp", "fsdp", "pp", "ep", "sp", "tp")

_global_mesh = None
_hier_mesh = None


def _jax():
    import jax

    return jax


def build_global_mesh():
    """(Re)build the global 1-D mesh over all chips. Called from hvt.init()."""
    global _global_mesh, _hier_mesh
    jax = _jax()
    devices = np.asarray(jax.devices())
    _global_mesh = jax.sharding.Mesh(devices, axis_names=(WORLD_AXIS,))
    _hier_mesh = None
    return _global_mesh


def _reset():
    global _global_mesh, _hier_mesh
    _global_mesh = None
    _hier_mesh = None


def global_mesh():
    """The GLOBAL communicator: 1-D mesh, axis ``hvt_world``."""
    if _global_mesh is None:
        raise ValueError("horovod_tpu not initialized; call hvt.init() first")
    return _global_mesh


def hierarchical_mesh():
    """(hosts × chips-per-host) mesh — the LOCAL/CROSS communicator pair.

    Requires a homogeneous job (same chip count per host), like the
    reference's hierarchical ops (``operations.cc:472-480`` forces the
    hierarchical knobs off for inhomogeneous clusters).
    """
    global _hier_mesh
    if _hier_mesh is not None:
        return _hier_mesh
    jax = _jax()
    devices = jax.devices()
    by_proc = {}
    for d in devices:
        by_proc.setdefault(d.process_index, []).append(d)
    counts = {len(v) for v in by_proc.values()}
    if len(counts) != 1:
        raise ValueError(
            "hierarchical_mesh requires a homogeneous job "
            f"(chips per host: { {k: len(v) for k, v in by_proc.items()} })")
    rows = [sorted(v, key=lambda d: d.id)
            for _, v in sorted(by_proc.items())]
    arr = np.asarray(rows)  # [hosts, chips_per_host]
    _hier_mesh = jax.sharding.Mesh(arr, axis_names=(CROSS_AXIS, LOCAL_AXIS))
    return _hier_mesh


def make_parallel_mesh(devices=None, **axis_sizes):
    """Build an N-D mesh for arbitrary parallelism strategies.

    ``axis_sizes`` maps axis name → size; one axis may be ``-1`` to absorb
    the remaining devices. Axes are laid out in :data:`PARALLEL_AXES` order
    (unknown names keep their kwarg order, appended innermost) so that tp/sp
    land on the fastest (innermost, ICI-adjacent) mesh dimensions.

    Example::

        mesh = make_parallel_mesh(dp=-1, tp=4)          # e.g. (64, 4) on 256
        mesh = make_parallel_mesh(dp=2, sp=2, tp=2)     # 8 devices
    """
    jax = _jax()
    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    n = len(devices)

    names = [a for a in PARALLEL_AXES if a in axis_sizes]
    names += [a for a in axis_sizes if a not in names]
    sizes = [axis_sizes[a] for a in names]

    if sizes.count(-1) > 1:
        raise ValueError("at most one axis may be -1")
    fixed = int(np.prod([s for s in sizes if s != -1])) if sizes else 1
    if -1 in sizes:
        if n % fixed != 0:
            raise ValueError(
                f"{n} devices not divisible by fixed axes product {fixed}")
        sizes[sizes.index(-1)] = n // fixed
        fixed = n
    if fixed != n:
        raise ValueError(
            f"mesh axes {dict(zip(names, sizes))} use {fixed} devices, "
            f"but {n} are available")
    arr = np.asarray(devices).reshape(sizes)
    return jax.sharding.Mesh(arr, axis_names=tuple(names))
