"""Fully-sharded data parallelism (FSDP / ZeRO-3) via sharding
annotations.

The reference has no parameter sharding (SURVEY.md §2.6 — data parallel
only, every rank holds a full replica). On TPU, FSDP is not a new
runtime: annotate each parameter (and its optimizer state) as sharded
over the ``fsdp`` mesh axis and XLA inserts the all-gather before each
use and the reduce-scatter after each gradient — the ZeRO-3 schedule,
derived by the compiler from the shardings (the scaling-book recipe).

This module provides the annotation helpers:

- :func:`fsdp_partition_spec` — shard the largest divisible dim of every
  big leaf over the axis; small leaves stay replicated.
- :func:`shard_pytree` — device_put a pytree according to specs.
- optimizer state sharding falls out for free: ``tx.init(params)`` on
  sharded params produces sharded moments (optax states mirror the
  param tree), which is ZeRO-1/2 included.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

FSDP_AXIS = "fsdp"


def fsdp_partition_spec(params, mesh, axis_name: str = FSDP_AXIS,
                        min_shard_elements: int = 1024):
    """PartitionSpecs sharding each leaf's largest ``axis_size``-divisible
    dimension over ``axis_name``.

    Leaves smaller than ``min_shard_elements`` or with no divisible dim
    stay replicated (sharding tiny tensors costs more in collective
    latency than it saves in HBM — same reasoning as the reference's
    fusion threshold, inverted).
    """
    axis_size = dict(zip(mesh.axis_names, mesh.devices.shape))[axis_name]

    def spec(leaf):
        shape = np.shape(leaf)
        if int(np.prod(shape, dtype=np.int64)) < min_shard_elements:
            return P()
        divisible = [i for i, d in enumerate(shape)
                     if d % axis_size == 0 and d >= axis_size]
        if not divisible:
            return P()
        dim = max(divisible, key=lambda i: shape[i])
        parts = [None] * len(shape)
        parts[dim] = axis_name
        return P(*parts)

    return jax.tree.map(spec, params)


def shard_pytree(tree, specs, mesh):
    """device_put every leaf with its NamedSharding."""
    return jax.tree.map(
        lambda v, s: jax.device_put(v, NamedSharding(mesh, s)),
        tree, specs, is_leaf=lambda v: isinstance(v, P))


def init_sharded_state(tx, params, mesh):
    """Initialize an optax state with ZeRO-1/2 sharding: optax moments
    mirror the parameter TREE, so any state subtree structurally
    identical to ``params`` (same treedef, same leaf shapes) inherits the
    parameters' shardings positionally; everything else (counters,
    scalars) replicates. Positional matching — not shape lookup — keeps
    same-shaped params with different shardings (e.g. FSDP+TP mixes)
    correct.

    A plain ``jax.jit(tx.init)(params)`` is NOT enough — ``zeros_like``
    has no layout dependence on its input, so XLA is free to replicate
    the moments; explicit ``out_shardings`` pin them.
    """
    replicated = NamedSharding(mesh, P())
    params_td = jax.tree.structure(params)
    param_leaves = jax.tree.leaves(params)
    param_shapes = [tuple(np.shape(l)) for l in param_leaves]
    param_shards = [getattr(l, "sharding", replicated)
                    for l in param_leaves]
    shards_tree = jax.tree.unflatten(params_td, param_shards)

    def is_params_mirror(sub):
        try:
            if jax.tree.structure(sub) != params_td:
                return False
            return [tuple(np.shape(l)) for l in jax.tree.leaves(sub)] \
                == param_shapes
        except Exception:
            return False

    shapes = jax.eval_shape(tx.init, params)
    out_shardings = jax.tree.map(
        lambda sub: shards_tree if is_params_mirror(sub) else
        jax.tree.map(lambda _: replicated, sub),
        shapes, is_leaf=is_params_mirror)
    return jax.jit(tx.init, out_shardings=out_shardings)(params)
