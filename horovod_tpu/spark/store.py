"""Storage abstraction for Spark estimators (reference
``horovod/spark/common/store.py``: ``Store`` / ``FilesystemStore`` /
``LocalStore`` / ``HDFSStore`` / ``DBFSLocalStore``).

A Store owns the layout under a prefix path:

    <prefix>/intermediate_train_data[.<idx>]   training data
    <prefix>/intermediate_val_data[.<idx>]     validation data
    <prefix>/runs/<run_id>/checkpoint.<ext>    per-run checkpoints
    <prefix>/runs/<run_id>/logs                per-run logs

plus the executor-side contract the estimators use: a local scratch dir
per run (``get_local_output_dir_fn``) and a ``sync_fn`` that publishes it
into the store — on a shared/local filesystem that is a copy; remote
flavors override ``exists/read/write/sync_fn``.

The reference materializes DataFrames into Petastorm parquet under the
data paths; the TPU estimators keep datasets in memory (see
``estimator.py``), so the data-path API exists for layout parity and
user code, while checkpoints/logs are fully used."""

from __future__ import annotations

import contextlib
import os
import shutil
import tempfile


class Store:
    """Interface (reference ``store.py:32``)."""

    @staticmethod
    def create(prefix_path: str, *args, **kwargs) -> "Store":
        """Factory by path scheme (reference ``store.py:144``):
        ``hdfs://`` → HDFSStore, ``dbfs:/`` → DBFSLocalStore,
        ``gs://`` → GCSStore, ``http(s)://`` → HTTPStore,
        anything else (incl. ``file://``) → FilesystemStore."""
        if prefix_path.startswith("hdfs://"):
            return HDFSStore(prefix_path, *args, **kwargs)
        if prefix_path.startswith("dbfs:/"):
            return DBFSLocalStore(prefix_path, *args, **kwargs)
        if prefix_path.startswith("gs://"):
            return GCSStore(prefix_path, *args, **kwargs)
        if prefix_path.startswith(("http://", "https://")):
            return HTTPStore(prefix_path, *args, **kwargs)
        return FilesystemStore(prefix_path, *args, **kwargs)

    # -- layout ------------------------------------------------------------

    def get_train_data_path(self, idx=None) -> str:
        raise NotImplementedError

    def get_val_data_path(self, idx=None) -> str:
        raise NotImplementedError

    def get_test_data_path(self, idx=None) -> str:
        raise NotImplementedError

    def get_runs_path(self) -> str:
        raise NotImplementedError

    def get_run_path(self, run_id: str) -> str:
        raise NotImplementedError

    def get_checkpoint_path(self, run_id: str) -> str:
        raise NotImplementedError

    def get_logs_path(self, run_id: str) -> str:
        raise NotImplementedError

    def get_checkpoint_filename(self) -> str:
        return "checkpoint.bin"

    # -- io ----------------------------------------------------------------

    def exists(self, path: str) -> bool:
        raise NotImplementedError

    def read(self, path: str) -> bytes:
        raise NotImplementedError

    def write(self, path: str, data: bytes):
        raise NotImplementedError

    # -- executor-side contract -------------------------------------------

    def get_local_output_dir_fn(self, run_id: str):
        """Context manager yielding a scratch dir on the executor; used
        with ``sync_fn`` (reference ``store.py:109``)."""

        @contextlib.contextmanager
        def local_dir():
            d = tempfile.mkdtemp(prefix=f"hvt_run_{run_id}_")
            try:
                yield d
            finally:
                shutil.rmtree(d, ignore_errors=True)

        return local_dir

    def sync_fn(self, run_id: str):
        """Returns ``sync(local_dir)`` publishing the scratch dir into the
        run path (reference ``store.py:112``)."""
        raise NotImplementedError


class FilesystemStore(Store):
    """Store over a locally-mounted filesystem path — local disk, NFS, or
    any fuse mount (reference ``FilesystemStore:153`` / ``LocalStore``)."""

    def __init__(self, prefix_path: str, train_path=None, val_path=None,
                 test_path=None, runs_path=None):
        self.prefix_path = self._localize(prefix_path)
        self._train = train_path or os.path.join(self.prefix_path,
                                                 "intermediate_train_data")
        self._val = val_path or os.path.join(self.prefix_path,
                                             "intermediate_val_data")
        self._test = test_path or os.path.join(self.prefix_path,
                                               "intermediate_test_data")
        self._runs = runs_path or os.path.join(self.prefix_path, "runs")

    @staticmethod
    def _localize(path: str) -> str:
        if path.startswith("file://"):
            return path[len("file://"):]
        return path

    @staticmethod
    def _with_idx(path: str, idx) -> str:
        return path if idx is None else f"{path}.{idx}"

    def get_train_data_path(self, idx=None) -> str:
        return self._with_idx(self._train, idx)

    def get_val_data_path(self, idx=None) -> str:
        return self._with_idx(self._val, idx)

    def get_test_data_path(self, idx=None) -> str:
        return self._with_idx(self._test, idx)

    def get_runs_path(self) -> str:
        return self._runs

    def get_run_path(self, run_id: str) -> str:
        return os.path.join(self._runs, run_id)

    def get_checkpoint_path(self, run_id: str) -> str:
        return os.path.join(self.get_run_path(run_id),
                            self.get_checkpoint_filename())

    def get_logs_path(self, run_id: str) -> str:
        return os.path.join(self.get_run_path(run_id), "logs")

    def exists(self, path: str) -> bool:
        return os.path.exists(self._localize(path))

    def read(self, path: str) -> bytes:
        with open(self._localize(path), "rb") as f:
            return f.read()

    def write(self, path: str, data: bytes):
        path = self._localize(path)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, path)  # atomic publish

    def sync_fn(self, run_id: str):
        run_path = self.get_run_path(run_id)

        def sync(local_dir: str):
            os.makedirs(run_path, exist_ok=True)
            for root, _dirs, files in os.walk(local_dir):
                rel = os.path.relpath(root, local_dir)
                dst_dir = (run_path if rel == "."
                           else os.path.join(run_path, rel))
                os.makedirs(dst_dir, exist_ok=True)
                for fn in files:
                    shutil.copy2(os.path.join(root, fn),
                                 os.path.join(dst_dir, fn))

        return sync


class DBFSLocalStore(FilesystemStore):
    """Databricks DBFS through its local fuse mount (reference
    ``DBFSLocalStore``): ``dbfs:/path`` ↔ ``/dbfs/path``."""

    @staticmethod
    def _localize(path: str) -> str:
        if path.startswith("dbfs:/"):
            return "/dbfs/" + path[len("dbfs:/"):].lstrip("/")
        return FilesystemStore._localize(path)


class HDFSStore(Store):
    """HDFS-backed store via pyarrow (reference ``HDFSStore``). Gated:
    raises a clear ImportError when pyarrow's HDFS support is absent."""

    def __init__(self, prefix_path: str, **hdfs_kwargs):
        try:
            from pyarrow import fs as pafs
        except ImportError as e:  # pragma: no cover - env without pyarrow
            raise ImportError(
                "HDFSStore requires pyarrow; use FilesystemStore over an "
                "NFS/fuse mount instead") from e
        # hdfs://[host[:port]]/path — the URL authority names the
        # namenode (reference HDFSStore parses it the same way);
        # hdfs:///path falls back to the ambient Hadoop config
        rest = prefix_path[len("hdfs://"):]
        authority, _, path = rest.partition("/")
        host = hdfs_kwargs.pop("host", None)
        port = hdfs_kwargs.pop("port", None)
        if authority:
            if ":" in authority:
                ahost, aport = authority.rsplit(":", 1)
                host = host or ahost
                port = port if port is not None else int(aport)
            else:
                host = host or authority
        kw = dict(hdfs_kwargs)
        if port is not None:
            kw["port"] = port
        self._fs = pafs.HadoopFileSystem(host or "default", **kw)
        self.prefix_path = "/" + path
        self._runs = self.prefix_path.rstrip("/") + "/runs"

    def get_train_data_path(self, idx=None) -> str:
        p = self.prefix_path.rstrip("/") + "/intermediate_train_data"
        return p if idx is None else f"{p}.{idx}"

    def get_val_data_path(self, idx=None) -> str:
        p = self.prefix_path.rstrip("/") + "/intermediate_val_data"
        return p if idx is None else f"{p}.{idx}"

    def get_test_data_path(self, idx=None) -> str:
        p = self.prefix_path.rstrip("/") + "/intermediate_test_data"
        return p if idx is None else f"{p}.{idx}"

    def get_runs_path(self) -> str:
        return self._runs

    def get_run_path(self, run_id: str) -> str:
        return f"{self._runs}/{run_id}"

    def get_checkpoint_path(self, run_id: str) -> str:
        return f"{self.get_run_path(run_id)}/{self.get_checkpoint_filename()}"

    def get_logs_path(self, run_id: str) -> str:
        return f"{self.get_run_path(run_id)}/logs"

    def exists(self, path: str) -> bool:
        from pyarrow import fs as pafs

        info = self._fs.get_file_info([path])[0]
        return info.type != pafs.FileType.NotFound

    def read(self, path: str) -> bytes:
        with self._fs.open_input_stream(path) as f:
            return f.read()

    def write(self, path: str, data: bytes):
        self._fs.create_dir(os.path.dirname(path), recursive=True)
        with self._fs.open_output_stream(path) as f:
            f.write(data)

    def sync_fn(self, run_id: str):
        run_path = self.get_run_path(run_id)

        def sync(local_dir: str):
            for root, _dirs, files in os.walk(local_dir):
                rel = os.path.relpath(root, local_dir)
                dst_dir = (run_path if rel == "."
                           else f"{run_path}/{rel}")
                for fn in files:
                    with open(os.path.join(root, fn), "rb") as f:
                        self.write(f"{dst_dir}/{fn}", f.read())

        return sync


class RemoteStore(Store):
    """Base for stores whose backing filesystem is NOT locally mounted
    (reference ``store.py`` splits the same way: path-layout logic shared,
    ``exists/read/write/sync_fn`` remote). Subclasses implement the four
    IO primitives against their service; the POSIX-style layout methods
    live here."""

    def __init__(self, prefix_path: str):
        self.prefix_path = prefix_path.rstrip("/")
        self._runs = self.prefix_path + "/runs"

    def _data(self, name, idx):
        p = f"{self.prefix_path}/{name}"
        return p if idx is None else f"{p}.{idx}"

    def get_train_data_path(self, idx=None) -> str:
        return self._data("intermediate_train_data", idx)

    def get_val_data_path(self, idx=None) -> str:
        return self._data("intermediate_val_data", idx)

    def get_test_data_path(self, idx=None) -> str:
        return self._data("intermediate_test_data", idx)

    def get_runs_path(self) -> str:
        return self._runs

    def get_run_path(self, run_id: str) -> str:
        return f"{self._runs}/{run_id}"

    def get_checkpoint_path(self, run_id: str) -> str:
        return f"{self.get_run_path(run_id)}/" \
               f"{self.get_checkpoint_filename()}"

    def get_logs_path(self, run_id: str) -> str:
        return f"{self.get_run_path(run_id)}/logs"

    def sync_fn(self, run_id: str):
        run_path = self.get_run_path(run_id)

        def sync(local_dir: str):
            for root, _dirs, files in os.walk(local_dir):
                rel = os.path.relpath(root, local_dir)
                dst = (run_path if rel == "."
                       else f"{run_path}/{rel.replace(os.sep, '/')}")
                for fn in files:
                    with open(os.path.join(root, fn), "rb") as f:
                        self.write(f"{dst}/{fn}", f.read())

        return sync


class HTTPStore(RemoteStore):
    """Remote store over the framework's own rendezvous HTTP KV server
    (``runner/http_server.py`` — PUT/GET ``/kv/<scope>/<key>``). The
    in-repo stand-in for an object store: every byte of the estimator
    round-trip (checkpoints, logs, synced run dirs) travels over the
    wire, so remote-store code paths are exercised for real even though
    this image cannot reach cloud object storage.

    ``prefix_path``: ``http://host:port[/subpath]`` — objects land under
    KV scope ``store`` with key ``<subpath>/...``.
    """

    SCOPE = "store"

    def __init__(self, prefix_path: str, timeout: float = 30.0):
        super().__init__(prefix_path)
        from urllib.parse import urlparse

        u = urlparse(self.prefix_path)
        self._base = f"{u.scheme}://{u.netloc}"
        self._timeout = timeout

    def _key(self, path: str) -> str:
        # strip the server authority; keys keep the subpath so multiple
        # stores can share one server
        if path.startswith(self._base):
            path = path[len(self._base):]
        return path.lstrip("/")

    def _url(self, path: str) -> str:
        from urllib.parse import quote

        return (f"{self._base}/kv/{self.SCOPE}/"
                f"{quote(self._key(path))}")

    def exists(self, path: str) -> bool:
        import urllib.error
        import urllib.request

        # HEAD: headers only — a GET would ship the whole object (a
        # multi-MB checkpoint) just to learn it exists
        req = urllib.request.Request(self._url(path), method="HEAD")
        try:
            with urllib.request.urlopen(req, timeout=self._timeout):
                return True
        except urllib.error.HTTPError as e:
            if e.code == 404:
                return False
            raise

    def read(self, path: str) -> bytes:
        import urllib.request

        with urllib.request.urlopen(self._url(path),
                                    timeout=self._timeout) as r:
            return r.read()

    def write(self, path: str, data: bytes):
        import urllib.request

        req = urllib.request.Request(self._url(path), data=data,
                                     method="PUT")
        with urllib.request.urlopen(req, timeout=self._timeout):
            pass


class GCSStore(RemoteStore):
    """Google Cloud Storage store (``gs://bucket/path``) — the
    TPU-idiomatic object store for checkpoints/logs. Gated on the
    ``google-cloud-storage`` client, which this image cannot install
    (zero egress): constructing without it raises a clear ImportError,
    like :class:`HDFSStore` without pyarrow. The IO surface mirrors
    HTTPStore's, which the tests exercise end-to-end."""

    def __init__(self, prefix_path: str, client=None):
        super().__init__(prefix_path)
        rest = prefix_path[len("gs://"):]
        self._bucket_name = rest.partition("/")[0]
        if client is None:
            try:
                from google.cloud import storage  # type: ignore
            except ImportError as e:  # pragma: no cover - env w/o gcs
                raise ImportError(
                    "GCSStore requires the google-cloud-storage client; "
                    "use HTTPStore or FilesystemStore instead") from e
            client = storage.Client()
        self._bucket = client.bucket(self._bucket_name)

    def _key(self, path: str) -> str:
        if path.startswith("gs://"):
            path = path[len("gs://"):].partition("/")[2]
        return path.lstrip("/")

    def exists(self, path: str) -> bool:
        return self._bucket.blob(self._key(path)).exists()

    def read(self, path: str) -> bytes:
        return self._bucket.blob(self._key(path)).download_as_bytes()

    def write(self, path: str, data: bytes):
        self._bucket.blob(self._key(path)).upload_from_string(data)


# reference exposes LocalStore as an alias of the filesystem flavor
LocalStore = FilesystemStore
