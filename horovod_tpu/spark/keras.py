"""Reference import-path alias: ``horovod.spark.keras`` →
``horovod_tpu.spark.keras`` (reference ``spark/keras/estimator.py:106``).
The implementation lives in :mod:`horovod_tpu.spark.estimator`."""

from horovod_tpu.spark.estimator import (KerasEstimator,  # noqa: F401
                                         KerasModel)
