"""Reference import-path alias: ``horovod.spark.torch`` →
``horovod_tpu.spark.torch`` (reference ``spark/torch/estimator.py:91``).
The implementation lives in :mod:`horovod_tpu.spark.estimator`."""

from horovod_tpu.spark.estimator import (TorchEstimator,  # noqa: F401
                                         TorchModel)
