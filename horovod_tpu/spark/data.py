"""Out-of-core data path for the Spark estimators — the TPU-native
analog of the reference's Petastorm materialization
(``horovod/spark/common/store.py:1`` disk-backed stores +
``spark/keras/remote.py`` reading row groups from the train data path).

``write_dataframe_shards`` writes each DataFrame partition ON THE
EXECUTOR to one compressed ``.npz`` shard under the store's train-data
path — the driver never holds the dataset. ``ShardedDataset`` assigns
shard FILES to ranks (strided, like Petastorm row-group sharding) and
streams batches one file at a time: peak memory is O(largest shard +
batch), not O(dataset).
"""

from __future__ import annotations

import io
import json
from typing import List, Optional


def write_dataframe_shards(df, store, feature_cols: List[str],
                           label_col: str, idx=None):
    """Materialize ``df`` into per-partition shard files + a manifest.

    Runs one ``mapPartitionsWithIndex`` pass; each partition writes
    ``part-<pid>.npz`` (float32 X/y) into ``store.get_train_data_path(idx)``
    from the executor. Returns the parsed manifest dict. The ``store``
    object must be picklable (FilesystemStore and friends are).
    """
    data_path = store.get_train_data_path(idx)
    cols = list(feature_cols)
    label = label_col

    def write_part(pid, rows_iter):
        import numpy as np

        rows = list(rows_iter)
        if not rows:
            return iter([])
        X = np.asarray([[rw[c] for c in cols] for rw in rows], np.float32)
        y = np.asarray([rw[label] for rw in rows], np.float32)
        buf = io.BytesIO()
        np.savez_compressed(buf, X=X, y=y)
        name = f"part-{pid:05d}.npz"
        store.write(f"{data_path}/{name}", buf.getvalue())
        return iter([(name, len(rows))])

    parts = (df.select(*cols, label).rdd
             .mapPartitionsWithIndex(write_part).collect())
    if not parts:
        # fail on the DRIVER, loudly — an empty manifest would leave
        # every training worker with zero batches to stream
        raise ValueError("cannot materialize an empty DataFrame "
                         "(no rows in any partition)")
    manifest = {"files": [{"name": n, "rows": int(r)}
                          for n, r in sorted(parts)],
                "feature_cols": cols, "label_col": label}
    store.write(f"{data_path}/manifest.json",
                json.dumps(manifest).encode())
    return manifest


class ShardedDataset:
    """Streaming reader over materialized shards.

    File-granular strided rank assignment; every rank derives the SAME
    lockstep step count from the manifest, so per-step gradient
    collectives stay synchronized even with uneven shards (ranks with
    fewer rows wrap around their files).
    """

    def __init__(self, store, idx=None, data_path: Optional[str] = None):
        self._store = store
        self._path = data_path or store.get_train_data_path(idx)
        self.manifest = json.loads(
            store.read(f"{self._path}/manifest.json"))
        self.files = self.manifest["files"]
        if not self.files:
            # a zero-file manifest would make iter_batches spin forever
            # chasing a step count no file can feed
            raise ValueError(f"empty shard manifest at {self._path}")
        self.feature_cols = self.manifest["feature_cols"]
        self.label_col = self.manifest["label_col"]

    @property
    def global_rows(self) -> int:
        return sum(f["rows"] for f in self.files)

    def rank_files(self, rank: int, size: int):
        """This rank's shard files. When there are fewer files than
        ranks, tail ranks wrap (every rank MUST have data to keep the
        lockstep loop alive — same contract as estimator._shard_rows)."""
        mine = self.files[rank::size]
        if not mine and self.files:
            mine = [self.files[rank % len(self.files)]]
        return mine

    def rank_rows(self, rank: int, size: int) -> int:
        return sum(f["rows"] for f in self.rank_files(rank, size))

    def lockstep_steps(self, size: int, batch_size: int) -> int:
        """ceil(largest rank's rows / batch) — identical on every rank."""
        mx = max((self.rank_rows(r, size) for r in range(size)),
                 default=0)
        return max(1, (mx + batch_size - 1) // batch_size)

    def _load(self, name: str):
        import numpy as np

        with io.BytesIO(self._store.read(f"{self._path}/{name}")) as b:
            z = np.load(b)
            return z["X"], z["y"]

    def iter_batches(self, rank: int, size: int, batch_size: int,
                     steps: int, seed: int = 0):
        """Yield exactly ``steps`` (X, y) batches of ``batch_size``,
        loading one shard file at a time. Shuffles file order and
        within-file rows by ``seed``; wraps around when this rank's rows
        run out before ``steps`` (lockstep padding)."""
        import numpy as np

        files = self.rank_files(rank, size)
        rng = np.random.RandomState(seed + 7919 * rank)
        produced = 0
        buf_x, buf_y = [], []
        buffered = 0
        while produced < steps:
            for fi in rng.permutation(len(files)):
                X, y = self._load(files[fi]["name"])
                perm = rng.permutation(len(X))
                buf_x.append(X[perm])
                buf_y.append(y[perm])
                buffered += len(X)
                while buffered >= batch_size and produced < steps:
                    bx = np.concatenate(buf_x) if len(buf_x) > 1 \
                        else buf_x[0]
                    by = np.concatenate(buf_y) if len(buf_y) > 1 \
                        else buf_y[0]
                    yield bx[:batch_size], by[:batch_size]
                    buf_x = [bx[batch_size:]]
                    buf_y = [by[batch_size:]]
                    buffered -= batch_size
                    produced += 1
                if produced >= steps:
                    return
