"""Horovod-on-Spark equivalent (reference ``horovod/spark/runner.py:195``
``run(fn, args…)`` — run fn in ``num_proc`` Spark tasks, return per-rank
results).

The reference predates Spark barrier execution and hand-rolls driver/task
services plus an mpirun-into-executors shim (``spark/driver/``,
``spark/task/``, ``mpirun_rsh.py``). The idiomatic modern equivalent —
and what this module uses — is a **barrier-mode RDD**: all ``num_proc``
tasks are scheduled simultaneously, ``BarrierTaskContext.getTaskInfos()``
gives every task the full address list (replacing the driver service's
host discovery), and task 0's host becomes the engine control-star
master. Rank = partition id.

``slot_envs_from_task_infos`` is pure logic, unit-testable without
pyspark; ``run`` is import-gated."""

from __future__ import annotations

import os
from typing import Any, Callable, Dict, List, Optional


def slot_envs_from_task_infos(addresses: List[str], master_port: int,
                              ) -> List[Dict[str, str]]:
    """Per-rank HVT_* env from the barrier task address list
    (``host:port`` strings, rank-ordered). Local ranks count occurrences
    of the same host before/at each rank; cross ranks index hosts having
    that local slot — identical semantics to hosts.get_host_assignments."""
    from horovod_tpu.runner.hosts import SlotInfo, slot_env_vars

    hosts = [a.rsplit(":", 1)[0] for a in addresses]
    size = len(hosts)
    envs = []
    for rank, host in enumerate(hosts):
        # rank MUST equal the Spark partition id, so hosts may interleave
        # — local/cross ranks are computed positionally, not regrouped
        local_rank = hosts[:rank].count(host)
        hosts_with_slot = [h for h in dict.fromkeys(hosts)
                           if hosts.count(h) > local_rank]
        slot = SlotInfo(hostname=host, rank=rank, local_rank=local_rank,
                        cross_rank=hosts_with_slot.index(host), size=size,
                        local_size=hosts.count(host),
                        cross_size=len(hosts_with_slot))
        env = slot_env_vars(slot)
        env.update({"HVT_MASTER_ADDR": hosts[0],
                    "HVT_MASTER_PORT": str(master_port)})
        envs.append(env)
    return envs


def _require_pyspark():
    try:
        import pyspark  # noqa: F401
    except ImportError as e:
        raise ImportError(
            "horovod_tpu.spark requires pyspark; machine-local "
            "equivalents are hvtrun and horovod_tpu.runner.run") from e


def run(fn: Callable, args=(), kwargs=None, num_proc: Optional[int] = None,
        master_port: int = 29570, force_cpu_jax: bool = True,
        extra_env: Optional[dict] = None, verbose: bool = False
        ) -> List[Any]:
    """Run ``fn(*args, **kwargs)`` in ``num_proc`` Spark barrier tasks
    with the horovod_tpu runtime initialized in each; returns the
    per-rank results ordered by rank (reference ``spark/runner.py:195``).
    """
    _require_pyspark()
    from pyspark import BarrierTaskContext
    from pyspark.sql import SparkSession

    spark = SparkSession.builder.getOrCreate()
    sc = spark.sparkContext
    if num_proc is None:
        num_proc = int(sc.defaultParallelism)
    kwargs = kwargs or {}
    captured_env = dict(extra_env or {})

    def task(_it):
        ctx = BarrierTaskContext.get()
        infos = ctx.getTaskInfos()
        addresses = [t.address for t in infos]
        rank = ctx.partitionId()
        env = slot_envs_from_task_infos(addresses, master_port)[rank]
        env.update(captured_env)
        os.environ.update(env)
        if force_cpu_jax:
            import jax

            jax.config.update("jax_platforms", "cpu")
        ctx.barrier()      # everyone has env before anyone inits
        import horovod_tpu as hvt

        hvt.init()
        try:
            result = fn(*args, **kwargs)
        finally:
            hvt.shutdown()
        yield rank, result

    pairs = (sc.parallelize(range(num_proc), num_proc)
             .barrier()
             .mapPartitions(task)
             .collect())
    return [r for _, r in sorted(pairs)]


def _elastic_attempt_loop(attempt, available_slots, num_proc=None,
                          min_np=None, max_np=None, reset_limit=3,
                          elastic_timeout=600.0, _sleep=None,
                          _monotonic=None):
    """Driver-side elastic retry loop, pure and unit-testable.

    ``attempt(world_size, attempt_idx)`` runs one gang; on failure the
    world is RE-SIZED from ``available_slots()`` (scale up and down
    between attempts, clamped to [min_np, max_np]) and retried, up to
    ``reset_limit`` resets (reference spark/runner.py:303 semantics).
    ``max_np`` defaults to ``num_proc`` when given — a reset must not
    silently outgrow the requested world (same convention as hvtrun's
    launcher). A slot pool momentarily below ``min_np`` is waited out up
    to ``elastic_timeout`` seconds (the hvtrun --elastic-timeout analog)
    before the job is declared dead.
    """
    import time as _time

    _sleep = _sleep or _time.sleep
    _monotonic = _monotonic or _time.monotonic
    if num_proc is not None and max_np is None:
        max_np = num_proc
    if (min_np is not None and max_np is not None and min_np > max_np):
        raise ValueError(f"min_np ({min_np}) > max_np ({max_np})")
    if (min_np is not None and num_proc is not None
            and num_proc < min_np):
        raise ValueError(f"num_proc ({num_proc}) < min_np ({min_np})")
    last_err = None
    for i in range(reset_limit + 1):
        world = available_slots()
        if min_np is not None and world < min_np:
            # a transient dip (executor replacement in flight) is the
            # exact event elasticity exists to survive — wait it out
            deadline = _monotonic() + elastic_timeout
            while world < min_np and _monotonic() < deadline:
                _sleep(min(5.0, max(elastic_timeout / 10.0, 0.1)))
                world = available_slots()
            if world < min_np:
                raise RuntimeError(
                    f"elastic job needs min_np={min_np} slots but only "
                    f"{world} were available after waiting "
                    f"{elastic_timeout:.0f}s") from last_err
        if i == 0 and num_proc is not None:
            world = num_proc
        if max_np is not None:
            world = min(world, max_np)
        if world < 1:
            raise RuntimeError("no slots available") from last_err
        try:
            return attempt(world, i)
        except Exception as e:  # gang failed — reset and re-size
            last_err = e
    raise RuntimeError(
        f"elastic job failed after {reset_limit + 1} attempts "
        f"(reset_limit={reset_limit})") from last_err


def run_elastic(fn: Callable, args=(), kwargs=None,
                num_proc: Optional[int] = None,
                min_np: Optional[int] = None,
                max_np: Optional[int] = None, reset_limit: int = 3,
                elastic_timeout: float = 600.0,
                master_port: int = 29571, force_cpu_jax: bool = True,
                extra_env: Optional[dict] = None) -> List[Any]:
    """Elastic Horovod-on-Spark (reference ``spark/runner.py:303``
    ``run_elastic``).

    Spark's barrier mode gang-schedules every task of a stage, so
    elasticity maps to STAGE boundaries rather than the per-worker
    respawn ``hvtrun --min-np`` does: a task failure tears the whole
    attempt down, the world is re-sized to the slots available at retry
    (scale down after executor loss, up after new executors join,
    clamped to ``[min_np, max_np]``), and ``fn`` re-runs with
    ``HVT_ELASTIC_ATTEMPT`` advanced in its environment. ``fn`` should
    restore from its last commit/checkpoint on a non-zero attempt —
    exactly what an ``@hvt.elastic.run`` function does after a reset.
    ``reset_limit`` bounds the number of resets.
    """
    _require_pyspark()
    from pyspark.sql import SparkSession

    spark = SparkSession.builder.getOrCreate()
    sc = spark.sparkContext

    def available_slots() -> int:
        return int(sc.defaultParallelism)

    def attempt(world: int, attempt_idx: int):
        env = dict(extra_env or {})
        env["HVT_ELASTIC_ATTEMPT"] = str(attempt_idx)
        # fresh port per attempt: a dying gang can leave the previous
        # control-star port in TIME_WAIT on the master host
        return run(fn, args=args, kwargs=kwargs, num_proc=world,
                   master_port=master_port + attempt_idx,
                   force_cpu_jax=force_cpu_jax, extra_env=env)

    return _elastic_attempt_loop(attempt, available_slots,
                                 num_proc=num_proc, min_np=min_np,
                                 max_np=max_np, reset_limit=reset_limit,
                                 elastic_timeout=elastic_timeout)
