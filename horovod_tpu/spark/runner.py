"""Horovod-on-Spark equivalent (reference ``horovod/spark/runner.py:195``
``run(fn, args…)`` — run fn in ``num_proc`` Spark tasks, return per-rank
results).

The reference predates Spark barrier execution and hand-rolls driver/task
services plus an mpirun-into-executors shim (``spark/driver/``,
``spark/task/``, ``mpirun_rsh.py``). The idiomatic modern equivalent —
and what this module uses — is a **barrier-mode RDD**: all ``num_proc``
tasks are scheduled simultaneously, ``BarrierTaskContext.getTaskInfos()``
gives every task the full address list (replacing the driver service's
host discovery), and task 0's host becomes the engine control-star
master. Rank = partition id.

``slot_envs_from_task_infos`` is pure logic, unit-testable without
pyspark; ``run`` is import-gated."""

from __future__ import annotations

import os
from typing import Any, Callable, Dict, List, Optional


def slot_envs_from_task_infos(addresses: List[str], master_port: int,
                              ) -> List[Dict[str, str]]:
    """Per-rank HVT_* env from the barrier task address list
    (``host:port`` strings, rank-ordered). Local ranks count occurrences
    of the same host before/at each rank; cross ranks index hosts having
    that local slot — identical semantics to hosts.get_host_assignments."""
    from horovod_tpu.runner.hosts import SlotInfo, slot_env_vars

    hosts = [a.rsplit(":", 1)[0] for a in addresses]
    size = len(hosts)
    envs = []
    for rank, host in enumerate(hosts):
        # rank MUST equal the Spark partition id, so hosts may interleave
        # — local/cross ranks are computed positionally, not regrouped
        local_rank = hosts[:rank].count(host)
        hosts_with_slot = [h for h in dict.fromkeys(hosts)
                           if hosts.count(h) > local_rank]
        slot = SlotInfo(hostname=host, rank=rank, local_rank=local_rank,
                        cross_rank=hosts_with_slot.index(host), size=size,
                        local_size=hosts.count(host),
                        cross_size=len(hosts_with_slot))
        env = slot_env_vars(slot)
        env.update({"HVT_MASTER_ADDR": hosts[0],
                    "HVT_MASTER_PORT": str(master_port)})
        envs.append(env)
    return envs


def _require_pyspark():
    try:
        import pyspark  # noqa: F401
    except ImportError as e:
        raise ImportError(
            "horovod_tpu.spark requires pyspark; machine-local "
            "equivalents are hvtrun and horovod_tpu.runner.run") from e


def run(fn: Callable, args=(), kwargs=None, num_proc: Optional[int] = None,
        master_port: int = 29570, force_cpu_jax: bool = True,
        extra_env: Optional[dict] = None, verbose: bool = False
        ) -> List[Any]:
    """Run ``fn(*args, **kwargs)`` in ``num_proc`` Spark barrier tasks
    with the horovod_tpu runtime initialized in each; returns the
    per-rank results ordered by rank (reference ``spark/runner.py:195``).
    """
    _require_pyspark()
    from pyspark import BarrierTaskContext
    from pyspark.sql import SparkSession

    spark = SparkSession.builder.getOrCreate()
    sc = spark.sparkContext
    if num_proc is None:
        num_proc = int(sc.defaultParallelism)
    kwargs = kwargs or {}
    captured_env = dict(extra_env or {})

    def task(_it):
        ctx = BarrierTaskContext.get()
        infos = ctx.getTaskInfos()
        addresses = [t.address for t in infos]
        rank = ctx.partitionId()
        env = slot_envs_from_task_infos(addresses, master_port)[rank]
        env.update(captured_env)
        os.environ.update(env)
        if force_cpu_jax:
            import jax

            jax.config.update("jax_platforms", "cpu")
        ctx.barrier()      # everyone has env before anyone inits
        import horovod_tpu as hvt

        hvt.init()
        try:
            result = fn(*args, **kwargs)
        finally:
            hvt.shutdown()
        yield rank, result

    pairs = (sc.parallelize(range(num_proc), num_proc)
             .barrier()
             .mapPartitions(task)
             .collect())
    return [r for _, r in sorted(pairs)]
