"""Spark integration (reference ``horovod/spark/__init__.py`` +
``spark/runner.py:195`` ``run()`` — Spark tasks become job slots)."""

from horovod_tpu.spark.runner import (run, slot_envs_from_task_infos)  # noqa: F401,E501
