"""Spark integration (reference ``horovod/spark/__init__.py`` +
``spark/runner.py:195`` ``run()`` — Spark tasks become job slots;
estimator/store ecosystem per ``spark/common/store.py`` +
``spark/keras/estimator.py`` / ``spark/torch/estimator.py``)."""

from horovod_tpu.spark.estimator import (JaxEstimator, JaxModel,  # noqa: F401,E501
                                         KerasEstimator, KerasModel,
                                         TorchEstimator, TorchModel)
from horovod_tpu.spark.runner import (run, run_elastic,  # noqa: F401
                                      slot_envs_from_task_infos)  # noqa: F401,E501
from horovod_tpu.spark.store import (DBFSLocalStore, FilesystemStore,  # noqa: F401,E501
                                     GCSStore, HDFSStore, HTTPStore,
                                     LocalStore, RemoteStore, Store)
