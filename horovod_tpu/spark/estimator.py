"""Spark ML Estimator API (reference ``spark/keras/estimator.py:106``
KerasEstimator / ``spark/torch/estimator.py:91`` TorchEstimator:
DataFrame → distributed fit → Spark Transformer, with ``Store``-backed
checkpointing and callbacks plumbed into the executor training loop —
reference ``spark/keras/remote.py`` / ``spark/torch/remote.py``).

Three flavors:

- :class:`JaxEstimator` — wraps a user ``train_fn`` (the JAX-native
  flavor); the loop is the user's.
- :class:`KerasEstimator` — owns an epoch-structured Keras loop (model
  shipped as ``.keras`` bytes, gradients through
  ``DistributedGradientTape``) — the reference's
  ``spark/keras/estimator.py:106``.
- :class:`TorchEstimator` — owns an epoch-structured torch training loop
  (module + optimizer factory + loss), gradients combined through
  ``horovod_tpu.torch.DistributedOptimizer`` — the reference's
  ``spark/torch/estimator.py:91``.

Both owned loops publish per-epoch checkpoints to the store via the
local-scratch-dir + sync contract and invoke ``callbacks``
(``on_epoch_end(epoch, logs)``) on rank 0.

The reference materializes DataFrames through Petastorm stores
(``spark/common/store.py``); TPU-natively the in-memory default converts
the (feature, label) columns to per-partition numpy shards — each
barrier task trains on its shard with gradients combined across tasks.
For beyond-memory datasets, the Torch and Keras flavors accept
``out_of_core=True``: per-partition ``.npz`` shard files are
materialized into the store on the executors and STREAMED
file-at-a-time in the training loop (``spark/data.py`` — the
Petastorm-store analog); the Jax flavor still collects to memory.

Both estimators split fit into a Spark-facing ``fit(df)`` and a pure
``_fit_arrays(X, y, run_fn=...)`` so the gated test rig exercises the
full fit → checkpoint → load → transform round trip without pyspark
(the Ray/Spark fake-test pattern)."""

from __future__ import annotations

import io
import json
import pickle
import uuid
from typing import Any, Callable, List, Optional


def _pickle_dumps(obj) -> bytes:
    """cloudpickle when available (ships with pyspark; required for
    closures/lambdas), stdlib pickle otherwise."""
    try:
        import cloudpickle

        return cloudpickle.dumps(obj)
    except ImportError:
        return pickle.dumps(obj)


def _local_run(worker, num_proc=None, **_kw):
    """In-process run_fn used by the fake test rig (world size 1)."""
    return [worker()]


def _steps_per_epoch(global_rows: int, n_procs: int, batch_size: int
                     ) -> int:
    """Identical step count on every rank (largest shard, rounded up) —
    per-step gradient collectives must stay in lockstep even when shard
    sizes differ by one."""
    shard_max = (global_rows + n_procs - 1) // max(n_procs, 1)
    return max(1, (shard_max + batch_size - 1) // batch_size)


def _train_val_split(total: int, validation):
    """Deterministic global train/validation split — identical on every
    rank (seeded permutation; no coordination needed). ``validation`` is
    None or a fraction in (0, 1). Each rank evaluates the FULL hold-out
    (these estimators are in-memory; the reference shards validation
    through Petastorm instead), which keeps ranks trivially in lockstep.
    """
    import numpy as np

    if not validation:
        return np.arange(total), np.asarray([], np.int64)
    if not 0.0 < float(validation) < 1.0:
        raise ValueError(
            f"validation={validation} must be a fraction in (0, 1)")
    n_val = max(1, int(total * float(validation)))
    if n_val >= total:
        raise ValueError(
            f"validation={validation} leaves no training rows")
    perm = np.random.RandomState(9172).permutation(total)
    return np.sort(perm[n_val:]), np.sort(perm[:n_val])


def _shard_rows(global_rows: int, r: int, n: int):
    """Row indices of rank ``r``'s shard (strided, like the reference's
    Petastorm row-group sharding). Every rank must come back non-empty —
    a rank with no rows could not run the lockstep per-step collectives —
    so when there are fewer rows than ranks the tail ranks wrap around
    (sampling with replacement on tiny datasets)."""
    import numpy as np

    rows = np.arange(global_rows)[r::n]
    if rows.size == 0 and global_rows > 0:
        rows = np.asarray([r % global_rows])
    return rows


def _spark_transform(df, predict, feature_cols, output_col):
    """Shared Transformer body: mapPartitions batched inference appending
    ``output_col`` (used by Jax/Keras/Torch models alike)."""
    from horovod_tpu.spark.runner import _require_pyspark

    _require_pyspark()
    import numpy as np
    from pyspark.sql import Row
    from pyspark.sql.types import DoubleType, StructField, StructType

    def infer(rows_iter):
        rows = list(rows_iter)
        if not rows:
            return
        Xp = np.asarray([[rw[c] for c in feature_cols] for rw in rows],
                        dtype=np.float32)
        for rw, pv in zip(rows, np.asarray(predict(Xp)).reshape(-1)
                          .tolist()):
            d = rw.asDict()
            d[output_col] = float(pv)
            yield Row(**d)

    # explicit schema: inference from an empty RDD fails, and the
    # empty-input case must still yield the prediction column
    schema = StructType(df.schema.fields
                        + [StructField(output_col, DoubleType())])
    return df.sparkSession.createDataFrame(
        df.rdd.mapPartitions(infer), schema)


def _collect_xy(df, feature_cols, label_col):
    import numpy as np

    rows = df.select(*feature_cols, label_col).collect()
    X = np.asarray([[r[c] for c in feature_cols] for r in rows],
                   dtype=np.float32)
    y = np.asarray([r[label_col] for r in rows], dtype=np.float32)
    return X, y


class _EstimatorBase:
    """Shared Spark-facing plumbing (collect-or-materialize →
    _fit_arrays → model)."""

    def _set_out_of_core(self, out_of_core, validation):
        """Streaming-mode flag + its validation mutual exclusion (the
        hold-out split needs the in-memory dataset)."""
        self.out_of_core = bool(out_of_core)
        if self.out_of_core and validation:
            raise ValueError("out_of_core=True does not support "
                             "validation= (stream the hold-out from a "
                             "separate materialized DataFrame instead)")

    def fit(self, df):
        from horovod_tpu.spark.runner import _require_pyspark, run

        _require_pyspark()

        def run_fn(worker, num_proc=None, master_port=29575):
            return run(worker, num_proc=num_proc, master_port=master_port)

        return self._fit_dataframe(df, run_fn=run_fn)

    def _fit_dataframe(self, df, run_fn=None):
        """The DataFrame half of ``fit`` (everything between the Spark
        session and ``_fit_arrays``), factored so the gated test rig can
        execute it with a fake DataFrame/barrier context — the coverage
        ``_fit_arrays`` alone skips."""
        if getattr(self, "out_of_core", False):
            # reference-parity out-of-core path: materialize per-partition
            # shard files into the store on the executors; workers stream
            # them (spark/data.py — the Petastorm-store analog)
            if self.store is None:
                raise ValueError("out_of_core=True requires store=")
            from horovod_tpu.spark.data import write_dataframe_shards

            write_dataframe_shards(df, self.store, self.feature_cols,
                                   self.label_col, idx=self.run_id)
            return self._fit_arrays(None, None, run_fn=run_fn,
                                    sharded=True)
        X, y = _collect_xy(df, self.feature_cols, self.label_col)
        # ship the dataset once per executor (broadcast), not once per
        # task via the function closure
        sc = df.sparkSession.sparkContext
        bc = sc.broadcast((X, y))
        # X/y must NOT also ride the worker closure (cloudpickle would
        # serialize the captured cells per task, defeating the broadcast)
        return self._fit_arrays(None, None, run_fn=run_fn, broadcast=bc)


class JaxEstimator(_EstimatorBase):
    """Spark estimator over a user-provided train step.

    Parameters
    - ``train_fn(shard_X, shard_y, epochs) -> (params, predict_fn)``:
      trains on the rank's shard (gradients allreduced via the live
      horovod_tpu runtime) and returns the final params plus a pure
      ``predict_fn(params, X) -> scalar-per-row predictions``; must be
      picklable (cloudpickle under pyspark).
    - ``feature_cols`` / ``label_col``: DataFrame columns.
    - ``num_proc``: world size (default: spark default parallelism).
    - ``epochs``: passes over each shard.
    - ``store`` / ``run_id``: when given, the fitted model is published
      to ``store.get_checkpoint_path(run_id)`` and can be restored with
      :meth:`JaxModel.load`.
    """

    def __init__(self, train_fn: Callable, feature_cols: List[str],
                 label_col: str, num_proc: Optional[int] = None,
                 epochs: int = 1, master_port: int = 29575,
                 store=None, run_id: Optional[str] = None):
        self.train_fn = train_fn
        self.feature_cols = list(feature_cols)
        self.label_col = label_col
        self.num_proc = num_proc
        self.epochs = epochs
        self.master_port = master_port
        self.store = store
        self.run_id = run_id

    def _fit_arrays(self, X, y, run_fn=None, broadcast=None) -> "JaxModel":
        train_fn, epochs = self.train_fn, self.epochs
        run_fn = run_fn or _local_run
        bc = broadcast

        def worker():
            import horovod_tpu as hvt

            bx, by = bc.value if bc is not None else (X, y)
            # shard by PROCESS: the estimator loop is per-worker-process
            # (a process may drive several chips; hvt.size() counts chips)
            n, r = hvt.process_size(), hvt.process_rank()
            rows = _shard_rows(len(bx), r, n)
            return train_fn(bx[rows], by[rows], epochs)

        results = run_fn(worker, num_proc=self.num_proc,
                         master_port=self.master_port)
        # all ranks end with identical params (allreduced training);
        # rank 0's result is the model
        params, predict_fn = results[0]
        model = JaxModel(params, predict_fn, self.feature_cols)
        if self.store is not None:
            run_id = self.run_id or f"jax-{uuid.uuid4().hex[:8]}"
            self.run_id = run_id
            self.store.write(
                self.store.get_checkpoint_path(run_id),
                _pickle_dumps({"params": params, "predict_fn": predict_fn,
                               "feature_cols": self.feature_cols}))
        return model


class JaxModel:
    """Spark Transformer produced by ``JaxEstimator.fit`` (the analog of
    the reference's KerasModel/TorchModel transformers)."""

    def __init__(self, params: Any, predict_fn: Callable,
                 feature_cols: List[str],
                 output_col: str = "prediction"):
        self.params = params
        self.predict_fn = predict_fn
        self.feature_cols = list(feature_cols)
        self.output_col = output_col

    @classmethod
    def load(cls, store, run_id: str, output_col: str = "prediction"
             ) -> "JaxModel":
        """Restore a fitted model from the store (reference estimators
        read back through Store the same way)."""
        blob = pickle.loads(store.read(store.get_checkpoint_path(run_id)))
        return cls(blob["params"], blob["predict_fn"],
                   blob["feature_cols"], output_col=output_col)

    def _predict_arrays(self, X):
        import numpy as np

        return np.asarray(self.predict_fn(self.params, X))

    def transform(self, df):
        return _spark_transform(df, self._predict_arrays,
                                self.feature_cols,
                                self.output_col)


class TorchEstimator(_EstimatorBase):
    """Torch-flavor estimator owning the training loop (reference
    ``spark/torch/estimator.py:91`` + the executor loop in
    ``spark/torch/remote.py``).

    Parameters
    - ``model``: a ``torch.nn.Module`` (its initial weights are the
      starting point on every rank — broadcast from rank 0).
    - ``optimizer_fn(params) -> torch.optim.Optimizer``.
    - ``loss_fn(pred, target) -> scalar tensor`` (default MSE).
    - ``epochs`` / ``batch_size``: loop shape.
    - ``store`` / ``run_id``: per-epoch checkpoints are written to a
      local scratch dir and published via ``store.sync_fn`` (the
      reference's remote-training contract); final weights land at
      ``store.get_checkpoint_path(run_id)``.
    - ``callbacks``: objects with ``on_epoch_end(epoch, logs)`` —
      invoked on rank 0 with ``logs={"loss": float}``.
    """

    def __init__(self, model, optimizer_fn: Callable,
                 feature_cols: List[str], label_col: str,
                 loss_fn: Optional[Callable] = None,
                 num_proc: Optional[int] = None, epochs: int = 1,
                 batch_size: int = 32, master_port: int = 29576,
                 store=None, run_id: Optional[str] = None,
                 callbacks: Optional[list] = None,
                 validation: Optional[float] = None,
                 out_of_core: bool = False):
        self.model = model
        self.optimizer_fn = optimizer_fn
        self.loss_fn = loss_fn
        self.feature_cols = list(feature_cols)
        self.label_col = label_col
        self.num_proc = num_proc
        self.epochs = epochs
        self.batch_size = batch_size
        self.master_port = master_port
        self.store = store
        self.run_id = run_id or f"torch-{uuid.uuid4().hex[:8]}"
        self.callbacks = list(callbacks or [])
        # fraction in (0,1): deterministic hold-out, per-epoch val_loss
        # in history/callbacks (reference estimator `validation` param)
        self.validation = validation
        # out-of-core: fit(df) materializes per-partition shard files
        # into the store (spark/data.py) and workers STREAM them instead
        # of holding the dataset in memory — the reference's
        # Petastorm-store path.
        self._set_out_of_core(out_of_core, validation)

    def _fit_arrays(self, X, y, run_fn=None, broadcast=None,
                    sharded=False) -> "TorchModel":
        import torch

        run_fn = run_fn or _local_run
        model_blob = _pickle_dumps(self.model)
        optimizer_fn, loss_fn = self.optimizer_fn, self.loss_fn
        epochs, batch_size = self.epochs, self.batch_size
        store, run_id = self.store, self.run_id
        callbacks = self.callbacks
        validation = self.validation
        bc = broadcast

        def worker():
            import numpy as np
            import torch

            import horovod_tpu as hvt
            import horovod_tpu.torch as hvt_torch

            # shard by PROCESS: the estimator loop is per-worker-process
            # (a process may drive several chips; hvt.size() counts chips)
            n, r = hvt.process_size(), hvt.process_rank()
            if sharded:
                # streaming path: batches come one shard FILE at a time
                # from the store (spark/data.py); nothing in memory
                # beyond the current file
                from horovod_tpu.spark.data import ShardedDataset

                ds = ShardedDataset(store, idx=run_id)
                sx = sy = vx = vy = None
                steps = ds.lockstep_steps(n, batch_size)

                def epoch_batches(epoch):
                    for bx_, by_ in ds.iter_batches(
                            r, n, batch_size, steps, seed=1000 + epoch):
                        yield (torch.from_numpy(
                                   np.ascontiguousarray(bx_)),
                               torch.from_numpy(
                                   np.ascontiguousarray(by_)))
            else:
                bx, by = bc.value if bc is not None else (X, y)
                train_ids, val_ids = _train_val_split(len(bx), validation)
                rows = train_ids[_shard_rows(len(train_ids), r, n)]
                sx = torch.from_numpy(np.ascontiguousarray(bx[rows]))
                sy = torch.from_numpy(np.ascontiguousarray(by[rows]))
                vx = (torch.from_numpy(np.ascontiguousarray(bx[val_ids]))
                      if len(val_ids) else None)
                vy = (torch.from_numpy(np.ascontiguousarray(by[val_ids]))
                      if len(val_ids) else None)
                # equal step count on every rank (see _steps_per_epoch):
                # per-step gradient collectives must stay in lockstep
                steps = _steps_per_epoch(len(train_ids), n, batch_size)

                def epoch_batches(epoch):
                    perm = torch.from_numpy(np.resize(
                        torch.randperm(
                            len(sx),
                            generator=torch.Generator().manual_seed(
                                1000 + epoch)).numpy(),
                        steps * batch_size))
                    for s in range(steps):
                        idx = perm[s * batch_size:(s + 1) * batch_size]
                        yield sx[idx], sy[idx]

            model = pickle.loads(model_blob)
            opt = hvt_torch.DistributedOptimizer(
                optimizer_fn(model.parameters()),
                named_parameters=model.named_parameters())
            hvt_torch.broadcast_parameters(model.state_dict(), root_rank=0)
            lf = loss_fn or torch.nn.functional.mse_loss

            def val_loss():
                total, seen = 0.0, 0
                model.eval()  # dropout off; BN must not absorb hold-out
                try:
                    with torch.no_grad():
                        for i in range(0, len(vx), batch_size):
                            xb = vx[i:i + batch_size]
                            yb = vy[i:i + batch_size]
                            lv = lf(model(xb).reshape(-1), yb.reshape(-1))
                            total += float(lv) * len(xb)
                            seen += len(xb)
                finally:
                    model.train()
                return total / max(seen, 1)

            def train_epochs(ckpt_dir=None, on_epoch=None):
                history = []
                for epoch in range(epochs):
                    total, batches = 0.0, 0
                    for xb, yb in epoch_batches(epoch):
                        opt.zero_grad()
                        pred = model(xb)
                        loss = lf(pred.reshape(-1), yb.reshape(-1))
                        loss.backward()
                        opt.step()
                        total += float(loss.detach())
                        batches += 1
                    logs = {"loss": total / max(batches, 1)}
                    if vx is not None and r == 0:
                        # rank-0 only: no collectives inside, and only
                        # rank 0's history/callbacks are consumed
                        logs["val_loss"] = val_loss()
                    history.append(logs)
                    if r == 0:
                        for cb in callbacks:
                            cb.on_epoch_end(epoch, dict(logs))
                        if ckpt_dir is not None:
                            torch.save(model.state_dict(),
                                       f"{ckpt_dir}/checkpoint-{epoch}.pt")
                            if on_epoch is not None:
                                # publish NOW: a failure at epoch k must
                                # not lose checkpoints 0..k-1 (reference
                                # remote.py publishes each epoch)
                                on_epoch()
                return history

            if store is not None and r == 0:
                sync = store.sync_fn(run_id)
                with store.get_local_output_dir_fn(run_id)() as d:
                    history = train_epochs(ckpt_dir=d, on_epoch=lambda:
                                           sync(d))
            else:
                history = train_epochs()
            return model.state_dict(), history

        results = run_fn(worker, num_proc=self.num_proc,
                         master_port=self.master_port)
        state_dict, history = results[0]
        model = pickle.loads(model_blob)
        model.load_state_dict(state_dict)
        if store is not None:
            buf = io.BytesIO()
            torch.save(model.state_dict(), buf)
            store.write(store.get_checkpoint_path(run_id), buf.getvalue())
            store.write(
                store.get_run_path(run_id) + "/meta.json",
                json.dumps({"feature_cols": self.feature_cols,
                            "label_col": self.label_col}).encode())
            store.write(
                store.get_logs_path(run_id) + "/history.json",
                json.dumps(history).encode())
        return TorchModel(model, self.feature_cols)


class KerasEstimator(_EstimatorBase):
    """Keras-flavor estimator (reference ``spark/keras/estimator.py:106``
    KerasEstimator + the executor loop in ``spark/keras/remote.py``).

    The model ships to workers as serialized ``.keras`` bytes; each
    worker rebuilds it, broadcasts rank 0's initial weights, and runs an
    epoch-structured loop with gradients exchanged through
    ``DistributedGradientTape``. Checkpoints/callbacks follow the same
    Store contract as :class:`TorchEstimator`.
    """

    def __init__(self, model, feature_cols: List[str], label_col: str,
                 optimizer="sgd", loss="mse",
                 num_proc: Optional[int] = None, epochs: int = 1,
                 batch_size: int = 32, master_port: int = 29577,
                 store=None, run_id: Optional[str] = None,
                 callbacks: Optional[list] = None,
                 validation: Optional[float] = None,
                 out_of_core: bool = False):
        self.model = model
        self.optimizer = optimizer
        self.loss = loss
        self.feature_cols = list(feature_cols)
        self.label_col = label_col
        self.num_proc = num_proc
        self.epochs = epochs
        self.batch_size = batch_size
        self.master_port = master_port
        self.store = store
        self.run_id = run_id or f"keras-{uuid.uuid4().hex[:8]}"
        self.callbacks = list(callbacks or [])
        self.validation = validation
        # same streaming contract as TorchEstimator (spark/data.py)
        self._set_out_of_core(out_of_core, validation)

    @staticmethod
    def _model_to_bytes(model) -> bytes:
        import os
        import tempfile

        fd, path = tempfile.mkstemp(suffix=".keras")
        os.close(fd)
        try:
            model.save(path)
            with open(path, "rb") as f:
                return f.read()
        finally:
            os.unlink(path)

    @staticmethod
    def _model_from_bytes(blob: bytes):
        import os
        import tempfile

        import tensorflow as tf

        fd, path = tempfile.mkstemp(suffix=".keras")
        os.close(fd)
        try:
            with open(path, "wb") as f:
                f.write(blob)
            return tf.keras.models.load_model(path)
        finally:
            os.unlink(path)

    def _fit_arrays(self, X, y, run_fn=None, broadcast=None,
                    sharded=False) -> "KerasModel":
        import tensorflow as tf

        run_fn = run_fn or _local_run
        model_blob = self._model_to_bytes(self.model)
        # ship the optimizer as CONFIG: Keras 3 optimizers bind to the
        # variables they are first built against, so sharing an instance
        # across fits/workers breaks
        opt_cfg = tf.keras.optimizers.serialize(
            tf.keras.optimizers.get(self.optimizer))
        loss = self.loss
        epochs, batch_size = self.epochs, self.batch_size
        store, run_id = self.store, self.run_id
        callbacks = self.callbacks
        validation = self.validation
        bc = broadcast

        def worker():
            import numpy as np
            import tensorflow as tf

            import horovod_tpu as hvt
            import horovod_tpu.tensorflow as hvt_tf

            # shard by PROCESS: the estimator loop is per-worker-process
            # (a process may drive several chips; hvt.size() counts chips)
            n, r = hvt.process_size(), hvt.process_rank()
            if sharded:
                from horovod_tpu.spark.data import ShardedDataset

                ds = ShardedDataset(store, idx=run_id)
                vx = vy = None
                steps = ds.lockstep_steps(n, batch_size)
                # build-only input: shape/dtype from the manifest — no
                # reason to fetch+decompress a whole shard for one row
                first_x = np.zeros((1, len(ds.feature_cols)), np.float32)

                def epoch_batches(epoch):
                    yield from ds.iter_batches(r, n, batch_size, steps,
                                               seed=1000 + epoch)
            else:
                bx, by = bc.value if bc is not None else (X, y)
                train_ids, val_ids = _train_val_split(len(bx), validation)
                rows = train_ids[_shard_rows(len(train_ids), r, n)]
                sx = np.ascontiguousarray(bx[rows])
                sy = np.ascontiguousarray(by[rows])
                vx = (np.ascontiguousarray(bx[val_ids]) if len(val_ids)
                      else None)
                vy = (np.ascontiguousarray(by[val_ids]) if len(val_ids)
                      else None)
                first_x = sx[:1]
                # every rank must run the SAME number of steps per epoch
                # — uneven shards would desynchronize the per-step
                # gradient collectives (wrap-around padding; global row
                # count is known to all ranks)
                steps = _steps_per_epoch(len(train_ids), n, batch_size)

                def epoch_batches(epoch):
                    perm = np.resize(
                        np.random.RandomState(1000 + epoch).permutation(
                            len(sx)), steps * batch_size)
                    for s in range(steps):
                        idx = perm[s * batch_size:(s + 1) * batch_size]
                        yield sx[idx], sy[idx]

            model = KerasEstimator._model_from_bytes(model_blob)
            opt = tf.keras.optimizers.deserialize(opt_cfg)
            loss_fn = tf.keras.losses.get(loss)
            model(tf.constant(first_x))  # build weights
            hvt_tf.broadcast_variables(model.weights, root_rank=0)

            def val_loss():
                total, seen = 0.0, 0
                for i in range(0, len(vx), batch_size):
                    xb = tf.constant(vx[i:i + batch_size])
                    yb = tf.constant(vy[i:i + batch_size])
                    lv = tf.reduce_mean(loss_fn(
                        tf.reshape(yb, [-1]),
                        tf.reshape(model(xb, training=False), [-1])))
                    total += float(lv) * int(xb.shape[0])
                    seen += int(xb.shape[0])
                return total / max(seen, 1)

            def train_epochs(ckpt_dir=None, on_epoch=None):
                history = []
                for epoch in range(epochs):
                    total, batches = 0.0, 0
                    for xb_, yb_ in epoch_batches(epoch):
                        xb = tf.constant(xb_)
                        yb = tf.constant(yb_)
                        with hvt_tf.DistributedGradientTape(
                                tf.GradientTape()) as tape:
                            pred = model(xb, training=True)
                            lv = tf.reduce_mean(loss_fn(
                                tf.reshape(yb, [-1]),
                                tf.reshape(pred, [-1])))
                        grads = tape.gradient(
                            lv, model.trainable_variables)
                        opt.apply_gradients(
                            zip(grads, model.trainable_variables))
                        total += float(lv)
                        batches += 1
                    logs = {"loss": total / max(batches, 1)}
                    if vx is not None and r == 0:
                        logs["val_loss"] = val_loss()
                    history.append(logs)
                    if r == 0:
                        for cb in callbacks:
                            cb.on_epoch_end(epoch, dict(logs))
                        if ckpt_dir is not None:
                            model.save_weights(
                                f"{ckpt_dir}/checkpoint-{epoch}"
                                f".weights.h5")
                            if on_epoch is not None:
                                on_epoch()
                return history

            if store is not None and r == 0:
                sync = store.sync_fn(run_id)
                with store.get_local_output_dir_fn(run_id)() as d:
                    history = train_epochs(ckpt_dir=d,
                                           on_epoch=lambda: sync(d))
            else:
                history = train_epochs()
            return KerasEstimator._model_to_bytes(model), history

        results = run_fn(worker, num_proc=self.num_proc,
                         master_port=self.master_port)
        final_blob, history = results[0]
        model = self._model_from_bytes(final_blob)
        if store is not None:
            store.write(store.get_checkpoint_path(run_id), final_blob)
            store.write(
                store.get_run_path(run_id) + "/meta.json",
                json.dumps({"feature_cols": self.feature_cols,
                            "label_col": self.label_col}).encode())
            store.write(
                store.get_logs_path(run_id) + "/history.json",
                json.dumps(history).encode())
        return KerasModel(model, self.feature_cols)


class KerasModel:
    """Transformer produced by ``KerasEstimator.fit`` (reference
    ``spark/keras`` KerasModel)."""

    def __init__(self, model, feature_cols: List[str],
                 output_col: str = "prediction"):
        self.model = model
        self.feature_cols = list(feature_cols)
        self.output_col = output_col

    @classmethod
    def load(cls, store, run_id: str, feature_cols=None,
             output_col: str = "prediction") -> "KerasModel":
        blob = store.read(store.get_checkpoint_path(run_id))
        model = KerasEstimator._model_from_bytes(blob)
        if feature_cols is None:
            meta = json.loads(store.read(
                store.get_run_path(run_id) + "/meta.json"))
            feature_cols = meta["feature_cols"]
        return cls(model, feature_cols=list(feature_cols),
                   output_col=output_col)

    def _predict_arrays(self, X):
        import numpy as np

        out = self.model.predict(
            np.ascontiguousarray(np.asarray(X, np.float32)), verbose=0)
        return np.asarray(out).reshape(len(X), -1).squeeze(-1)

    def transform(self, df):
        return _spark_transform(df, self._predict_arrays,
                                self.feature_cols,
                                self.output_col)


class TorchModel:
    """Transformer produced by ``TorchEstimator.fit``."""

    def __init__(self, model, feature_cols: List[str],
                 output_col: str = "prediction"):
        self.model = model
        self.feature_cols = list(feature_cols)
        self.output_col = output_col

    @classmethod
    def load(cls, store, run_id: str, model, feature_cols=None,
             output_col: str = "prediction") -> "TorchModel":
        """Restore weights from the store into ``model`` (an instance of
        the architecture that was fitted); feature_cols default to the
        ones persisted at fit time."""
        import torch

        blob = store.read(store.get_checkpoint_path(run_id))
        model.load_state_dict(torch.load(io.BytesIO(blob)))
        if feature_cols is None:
            meta = json.loads(store.read(
                store.get_run_path(run_id) + "/meta.json"))
            feature_cols = meta["feature_cols"]
        return cls(model, feature_cols=list(feature_cols),
                   output_col=output_col)

    def _predict_arrays(self, X):
        import numpy as np
        import torch

        self.model.eval()
        with torch.no_grad():
            out = self.model(torch.from_numpy(
                np.ascontiguousarray(np.asarray(X, np.float32))))
        return out.reshape(len(X), -1).squeeze(-1).numpy()

    def transform(self, df):
        return _spark_transform(df, self._predict_arrays,
                                self.feature_cols,
                                self.output_col)
