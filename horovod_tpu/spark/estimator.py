"""Spark ML Estimator API (reference ``spark/keras/estimator.py:106``
KerasEstimator / ``spark/torch/estimator.py:91`` TorchEstimator:
DataFrame → distributed fit → Spark Transformer).

The reference materializes DataFrames through Petastorm stores
(``spark/common/store.py``); TPU-natively the estimator converts the
(feature, label) columns to per-partition numpy shards — each barrier
task trains on its shard with gradients combined across tasks — and
returns a ``JaxModel`` whose ``transform`` runs batched inference inside
``mapPartitions``. Petastorm-scale out-of-core storage is out of scope;
for datasets beyond executor memory, feed TFRecord/array files directly
from the training fn instead."""

from __future__ import annotations

from typing import Any, Callable, List, Optional


class JaxEstimator:
    """Minimal Spark estimator over a user-provided train step.

    Parameters
    - ``train_fn(shard_X, shard_y, epochs) -> (params, predict_fn)``:
      trains on the rank's shard (gradients allreduced via the live
      horovod_tpu runtime) and returns the final params plus a pure
      ``predict_fn(params, X) -> scalar-per-row predictions``; must be
      cloudpickle-able.
    - ``feature_cols`` / ``label_col``: DataFrame columns.
    - ``num_proc``: world size (default: spark default parallelism).
    - ``epochs``: passes over each shard.
    """

    def __init__(self, train_fn: Callable, feature_cols: List[str],
                 label_col: str, num_proc: Optional[int] = None,
                 epochs: int = 1, master_port: int = 29575):
        self.train_fn = train_fn
        self.feature_cols = list(feature_cols)
        self.label_col = label_col
        self.num_proc = num_proc
        self.epochs = epochs
        self.master_port = master_port

    def fit(self, df) -> "JaxModel":
        from horovod_tpu.spark.runner import _require_pyspark, run

        _require_pyspark()
        import numpy as np

        feature_cols, label_col = self.feature_cols, self.label_col
        rows = df.select(*feature_cols, label_col).collect()
        X = np.asarray([[r[c] for c in feature_cols] for r in rows],
                       dtype=np.float32)
        y = np.asarray([r[label_col] for r in rows], dtype=np.float32)
        train_fn, epochs = self.train_fn, self.epochs
        # ship the dataset once per executor (broadcast), not once per
        # task via the function closure
        sc = df.sparkSession.sparkContext
        bc = sc.broadcast((X, y))

        def worker():
            import horovod_tpu as hvt

            bx, by = bc.value
            n = hvt.size()
            r = hvt.rank()
            return train_fn(bx[r::n], by[r::n], epochs)

        results = run(worker, num_proc=self.num_proc,
                      master_port=self.master_port)
        # all ranks end with identical params (allreduced training);
        # rank 0's result is the model
        params, predict_fn = results[0]
        return JaxModel(params, predict_fn, self.feature_cols)


class JaxModel:
    """Spark Transformer produced by ``JaxEstimator.fit`` (the analog of
    the reference's KerasModel/TorchModel transformers)."""

    def __init__(self, params: Any, predict_fn: Callable,
                 feature_cols: List[str],
                 output_col: str = "prediction"):
        self.params = params
        self.predict_fn = predict_fn
        self.feature_cols = list(feature_cols)
        self.output_col = output_col

    def transform(self, df):
        from horovod_tpu.spark.runner import _require_pyspark

        _require_pyspark()
        import numpy as np
        from pyspark.sql import Row
        from pyspark.sql.types import DoubleType, StructField, StructType

        params, predict_fn = self.params, self.predict_fn
        feature_cols, output_col = self.feature_cols, self.output_col

        def infer(rows_iter):
            rows = list(rows_iter)
            if not rows:
                return
            X = np.asarray([[r[c] for c in feature_cols] for r in rows],
                           dtype=np.float32)
            preds = np.asarray(predict_fn(params, X)).tolist()
            for r, p in zip(rows, preds):
                d = r.asDict()
                d[output_col] = float(p)
                yield Row(**d)

        # explicit schema: inference from an empty RDD fails, and the
        # empty-input case must still yield a DataFrame with the
        # prediction column
        schema = StructType(df.schema.fields
                            + [StructField(output_col, DoubleType())])
        return df.sparkSession.createDataFrame(
            df.rdd.mapPartitions(infer), schema)
