"""Mixture-of-Experts layer with expert parallelism (EP).

The reference is data-parallel only; its alltoall primitive
(``operations.cc:1099``) is "the usual EP building block" (SURVEY.md
§2.6). TPU-natively, EP needs no hand-written alltoall: experts are
sharded over the ``ep`` mesh axis and tokens over ``dp``; the
dispatch/combine einsums below contract across those axes, so XLA inserts
the all-to-alls on ICI and fuses them with the expert matmuls — the
Mesh-TensorFlow / GShard dense-dispatch formulation, which is the
MXU-friendly way to write MoE (einsums, static shapes, no gather loops).

Components:
- ``Router``: top-1 softmax gating with capacity and an auxiliary
  load-balancing loss (GShard eq. (4): E * Σ_e mean(gates_e)·mean(mask_e)).
- ``MoEMlp``: expert-parallel FFN; expert weights [n_experts, ...] carry
  ``P("ep", ...)`` in ``param_partition_spec``.
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


class Router(nn.Module):
    """Top-1 router with capacity (tokens per expert per batch row).
    Routing math is always float32 — the standard numerically-safe
    choice regardless of the expert compute dtype."""

    n_experts: int
    capacity_factor: float = 1.25

    @nn.compact
    def __call__(self, x):
        # x: [batch, seq, d_model] → gates [batch, seq, n_experts]
        logits = nn.Dense(self.n_experts, use_bias=False,
                          dtype=jnp.float32, name="router")(
                              x.astype(jnp.float32))
        gates = jax.nn.softmax(logits, axis=-1)
        expert_idx = jnp.argmax(gates, axis=-1)            # [b, s]
        mask = jax.nn.one_hot(expert_idx, self.n_experts,
                              dtype=jnp.float32)           # [b, s, e]

        # auxiliary load-balance loss (GShard): encourages uniform routing
        density = mask.mean(axis=1)                        # [b, e]
        density_proxy = gates.mean(axis=1)                 # [b, e]
        aux_loss = (density * density_proxy).sum(-1).mean() \
            * self.n_experts

        seq = x.shape[1]
        capacity = int(self.capacity_factor * seq / self.n_experts) or 1

        # position of each token within its expert's queue
        pos_in_expert = (jnp.cumsum(mask, axis=1) - 1.0) * mask  # [b,s,e]
        keep = (pos_in_expert < capacity).astype(jnp.float32) * mask
        pos = jnp.einsum("bse,bse->bs", pos_in_expert, keep)
        pos_oh = jax.nn.one_hot(pos.astype(jnp.int32), capacity,
                                dtype=jnp.float32)         # [b, s, c]
        # dispatch [b, s, e, c]: token (b,s) → slot (e,c)
        dispatch = jnp.einsum("bse,bsc->bsec", keep, pos_oh)
        gate_val = jnp.einsum("bse,bse->bs", gates.astype(jnp.float32),
                              keep)
        combine = dispatch * gate_val[..., None, None]
        return dispatch, combine, aux_loss


class MoEMlp(nn.Module):
    """Expert-parallel FFN block: route → all-to-all → expert matmuls
    (MXU, batched over the local experts) → all-to-all back → combine."""

    n_experts: int
    d_ff: int
    capacity_factor: float = 1.25
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        b, s, d = x.shape
        dispatch, combine, aux_loss = Router(
            self.n_experts, self.capacity_factor, name="router_block")(x)

        # [e, b, c, d]: with x sharded over dp and wi/wo over ep, XLA
        # lowers this contraction to an all-to-all over ICI
        expert_in = jnp.einsum("bsec,bsd->ebcd",
                               dispatch.astype(self.dtype),
                               x.astype(self.dtype))
        wi = self.param("wi", nn.initializers.lecun_normal(),
                        (self.n_experts, d, self.d_ff))
        wo = self.param("wo", nn.initializers.lecun_normal(),
                        (self.n_experts, self.d_ff, d))
        h = jnp.einsum("ebcd,edf->ebcf", expert_in,
                       wi.astype(self.dtype))
        h = nn.gelu(h)
        expert_out = jnp.einsum("ebcf,efd->ebcd", h,
                                wo.astype(self.dtype))
        out = jnp.einsum("bsec,ebcd->bsd",
                         combine.astype(self.dtype), expert_out)
        self.sow("intermediates", "aux_loss", aux_loss)
        return out.astype(x.dtype), aux_loss


def moe_param_partition_spec(params, ep_axis: str = "ep",
                             tp_axis: Optional[str] = None):
    """PartitionSpecs for an MoE param tree: expert-stacked weights
    ([n_experts, ...]) shard over ``ep_axis`` (dim 0); everything else
    replicated (compose with the dense model's tp spec separately)."""

    def spec(path, leaf):
        last = str(getattr(path[-1], "key", path[-1])) if path else ""
        if last == "wi" and leaf.ndim == 3:
            return P(ep_axis, None, tp_axis)
        if last == "wo" and leaf.ndim == 3:
            return P(ep_axis, tp_axis, None)
        return P()

    return jax.tree_util.tree_map_with_path(spec, params)
