"""VGG-16 and Inception V3 for the Horovod-parity benchmarks.

The reference's published scaling headline is Inception V3 and VGG-16
(``/root/reference/README.rst:96``, ``docs/benchmarks.rst:13-14``: 90%
scaling efficiency for Inception V3 / ResNet-101, 68% for VGG-16 at 512
GPUs) plus ResNet throughput. ``horovod_tpu/models/resnet.py`` covers
the ResNet family; this module completes the benchmark trio so
``bench.py --model vgg16|inception_v3`` can reproduce the same model mix
TPU-natively.

TPU-first choices (same policy as resnet.py):
- NHWC layout throughout — XLA:TPU's native conv layout.
- bfloat16 activations/weights, fp32 master params.
- VGG uses the original architecture but with BatchNorm (the common
  modern variant — plain VGG's huge fp32 FC head would dominate HBM for
  no benchmark value; the classifier keeps the 4096-wide FCs).
- Inception V3 follows the canonical tower layout (torchvision
  inception.py structure: 5b/5c/5d mixed, 6a reduction, 6b-6e 7x7
  factorized towers, 7a reduction, 7b/7c expanded) with BN after every
  conv, aux head omitted (benchmarks train the main head only).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Sequence

import flax.linen as nn
import jax.numpy as jnp

from .normalization import TpuBatchNorm

ModuleDef = Any


class _ConvBN(nn.Module):
    """conv → BN → ReLU, the building block of both models."""
    features: int
    kernel: Sequence[int] = (3, 3)
    strides: Sequence[int] = (1, 1)
    padding: Any = "SAME"
    dtype: Any = jnp.bfloat16
    norm_impl: str = "tpu"
    axis_name: Any = None

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = nn.Conv(self.features, tuple(self.kernel),
                    strides=tuple(self.strides), padding=self.padding,
                    use_bias=False, dtype=self.dtype,
                    param_dtype=jnp.float32)(x)
        norm_cls = TpuBatchNorm if self.norm_impl == "tpu" else nn.BatchNorm
        x = norm_cls(use_running_average=not train, momentum=0.9,
                     epsilon=1e-3, dtype=self.dtype,
                     param_dtype=jnp.float32,
                     axis_name=self.axis_name)(x)
        return nn.relu(x)


class VGG16(nn.Module):
    """VGG-16 (configuration D) with BatchNorm.

    Reference benchmark subject (``docs/benchmarks.rst:14``: 68% scaling
    efficiency at 512 GPUs — VGG's fat dense head is the classic
    gradient-fusion stress test, which is exactly why Horovod benchmarks
    it: one 102M-parameter FC gradient dominates the allreduce)."""
    num_classes: int = 1000
    dtype: Any = jnp.bfloat16
    norm_impl: str = "tpu"
    axis_name: Any = None

    @nn.compact
    def __call__(self, x, train: bool = True):
        cbn = partial(_ConvBN, dtype=self.dtype, norm_impl=self.norm_impl,
                      axis_name=self.axis_name)
        x = x.astype(self.dtype)
        for block, (features, convs) in enumerate(
                [(64, 2), (128, 2), (256, 3), (512, 3), (512, 3)]):
            for i in range(convs):
                x = cbn(features, name=f"conv{block}_{i}")(x, train)
            x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(4096, dtype=self.dtype,
                             param_dtype=jnp.float32, name="fc1")(x))
        x = nn.relu(nn.Dense(4096, dtype=self.dtype,
                             param_dtype=jnp.float32, name="fc2")(x))
        # fp32 logits for a stable softmax (same policy as resnet head)
        return nn.Dense(self.num_classes, dtype=jnp.float32,
                        param_dtype=jnp.float32, name="head")(x)


class _InceptionTower(nn.Module):
    """One mixed block: parallel conv towers concatenated on channels."""
    towers: Sequence[Sequence[dict]]
    pool_features: int
    dtype: Any = jnp.bfloat16
    norm_impl: str = "tpu"
    axis_name: Any = None

    @nn.compact
    def __call__(self, x, train: bool = True):
        cbn = partial(_ConvBN, dtype=self.dtype, norm_impl=self.norm_impl,
                      axis_name=self.axis_name)
        outs = []
        for t, tower in enumerate(self.towers):
            h = x
            for c, spec in enumerate(tower):
                h = cbn(spec["features"], kernel=spec.get("kernel", (1, 1)),
                        strides=spec.get("strides", (1, 1)),
                        padding=spec.get("padding", "SAME"),
                        name=f"t{t}_c{c}")(h, train)
            outs.append(h)
        if self.pool_features:
            p = nn.avg_pool(x, (3, 3), strides=(1, 1), padding="SAME")
            outs.append(_ConvBN(self.pool_features, kernel=(1, 1),
                                dtype=self.dtype, norm_impl=self.norm_impl,
                                axis_name=self.axis_name,
                                name="pool_proj")(p, train))
        return jnp.concatenate(outs, axis=-1)


def _c(features, kernel=(1, 1), strides=(1, 1), padding="SAME"):
    return {"features": features, "kernel": kernel, "strides": strides,
            "padding": padding}


class InceptionV3(nn.Module):
    """Inception V3 (299×299 input), canonical tower layout, aux head
    omitted. Reference benchmark subject (``docs/benchmarks.rst:13``:
    90% scaling efficiency at 512 GPUs)."""
    num_classes: int = 1000
    dtype: Any = jnp.bfloat16
    norm_impl: str = "tpu"
    axis_name: Any = None

    @nn.compact
    def __call__(self, x, train: bool = True):
        kw = dict(dtype=self.dtype, norm_impl=self.norm_impl,
                  axis_name=self.axis_name)
        cbn = partial(_ConvBN, **kw)
        mix = partial(_InceptionTower, **kw)
        x = x.astype(self.dtype)
        # stem: 299 → 35x35x192
        x = cbn(32, strides=(2, 2), padding="VALID", name="stem1")(x, train)
        x = cbn(32, padding="VALID", name="stem2")(x, train)
        x = cbn(64, name="stem3")(x, train)
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        x = cbn(80, kernel=(1, 1), padding="VALID", name="stem4")(x, train)
        x = cbn(192, padding="VALID", name="stem5")(x, train)
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        # 5b/5c/5d: 35x35 mixed, pool proj 32/64/64
        for i, pf in enumerate([32, 64, 64]):
            x = mix(towers=[
                [_c(64)],
                [_c(48), _c(64, kernel=(5, 5))],
                [_c(64), _c(96, kernel=(3, 3)), _c(96, kernel=(3, 3))],
            ], pool_features=pf, name=f"mixed5{'bcd'[i]}")(x, train)
        # 6a: reduction to 17x17
        x = jnp.concatenate([
            cbn(384, kernel=(3, 3), strides=(2, 2), padding="VALID",
                name="red6a_a")(x, train),
            cbn(96, kernel=(3, 3), strides=(2, 2), padding="VALID",
                name="red6a_b3")(
                cbn(96, kernel=(3, 3), name="red6a_b2")(
                    cbn(64, kernel=(1, 1), name="red6a_b1")(x, train), train), train),
            nn.max_pool(x, (3, 3), strides=(2, 2)),
        ], axis=-1)
        # 6b-6e: 17x17 factorized 7x1/1x7 towers
        for i, f7 in enumerate([128, 160, 160, 192]):
            x = mix(towers=[
                [_c(192)],
                [_c(f7), _c(f7, kernel=(1, 7)), _c(192, kernel=(7, 1))],
                [_c(f7), _c(f7, kernel=(7, 1)), _c(f7, kernel=(1, 7)),
                 _c(f7, kernel=(7, 1)), _c(192, kernel=(1, 7))],
            ], pool_features=192, name=f"mixed6{'bcde'[i]}")(x, train)
        # 7a: reduction to 8x8
        x = jnp.concatenate([
            cbn(320, kernel=(3, 3), strides=(2, 2), padding="VALID",
                name="red7a_a2")(
                cbn(192, kernel=(1, 1), name="red7a_a1")(x, train), train),
            cbn(192, kernel=(3, 3), strides=(2, 2), padding="VALID",
                name="red7a_b4")(
                cbn(192, kernel=(1, 7), name="red7a_b3")(
                    cbn(192, kernel=(7, 1), name="red7a_b2")(
                        cbn(192, kernel=(1, 1), name="red7a_b1")(x, train), train),
                    train), train),
            nn.max_pool(x, (3, 3), strides=(2, 2)),
        ], axis=-1)
        # 7b/7c: 8x8 expanded towers (3x3 split into 1x3 + 3x1 branches)
        for i in range(2):
            y1 = cbn(384, kernel=(1, 1), name=f"m7{'bc'[i]}_b1")(x, train)
            y1 = jnp.concatenate([
                cbn(384, kernel=(1, 3), name=f"m7{'bc'[i]}_b1a")(y1, train),
                cbn(384, kernel=(3, 1), name=f"m7{'bc'[i]}_b1b")(y1, train),
            ], axis=-1)
            y2 = cbn(448, kernel=(1, 1), name=f"m7{'bc'[i]}_b2")(x, train)
            y2 = cbn(384, kernel=(3, 3), name=f"m7{'bc'[i]}_b2a")(y2, train)
            y2 = jnp.concatenate([
                cbn(384, kernel=(1, 3), name=f"m7{'bc'[i]}_b2b")(y2, train),
                cbn(384, kernel=(3, 1), name=f"m7{'bc'[i]}_b2c")(y2, train),
            ], axis=-1)
            p = nn.avg_pool(x, (3, 3), strides=(1, 1), padding="SAME")
            p = cbn(192, kernel=(1, 1), name=f"m7{'bc'[i]}_pool")(p, train)
            x = jnp.concatenate(
                [cbn(320, kernel=(1, 1), name=f"m7{'bc'[i]}_b0")(x, train), y1, y2, p],
                axis=-1)
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(self.num_classes, dtype=jnp.float32,
                        param_dtype=jnp.float32, name="head")(x)
